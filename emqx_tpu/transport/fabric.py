"""Worker-fabric wire protocol: connection workers <-> router process.

The reference scales its connection layer with one BEAM process per
connection inside a single node (emqx_connection.erl:173-176 — the
scheduler spreads them over cores). A Python host gets the same effect
with OS processes: N connection WORKERS own the client sockets (accepting
on a shared SO_REUSEPORT port, one asyncio loop + full Channel/Session
stack each), while the ROUTER process owns the single DeviceRouter and
the subscription tables. This module is the seam between them: a
length-prefixed binary protocol over a unix-domain socket, batched in
both directions so the device batch window keeps its shape.

Frames (all little-endian, u32 length prefix EXCLUDES the 5-byte header):

  [u32 len][u8 type][body]

  HELLO (w->r): u16 worker_id
  SUB   (w->r): json {h, sid, cid, f, qos, nl, rap, rh}
  UNSUB (w->r): json {sid, f}
  PUBB  (w->r): u32 seq, u32 n, n * pub_record
  DLV   (r->w): u32 n, n * dlv_record
  PUBB_ACK (r->w): u32 seq, u32 n, n * i32 delivery_count

A PUBB is acked AFTER the router dispatched (or banked) every message
in it, with per-message delivery counts — the worker-side channel
holds each QoS1/2 client ack on that confirmation, so the at-least-once
boundary sits at the router, not at the worker's socket buffer.

  pub_record: u16 tlen, topic, u32 plen, payload,
              u8 flags (qos | retain<<2 | dup<<3 | has_props<<4),
              u16 clen, from_client,
              [u32 pblen, props_block]           (iff has_props)
  dlv_record: u16 tlen, topic, u32 plen, payload,
              u8 flags (pub qos | retain<<2 | retained<<3 |
                        has_props<<4),
              u16 clen, from_client,
              [u32 pblen, props_block],          (iff has_props)
              u16 ntargets, ntargets * u32 handle

props_block is the MQTT5 encoded property block (frame.encode_properties
output) — v5 publish properties survive the worker fabric end to end.

A delivery record carries the message ONCE per worker; per-subscription
QoS downgrade happens worker-side in the Session (same code path as the
in-process broker), so the router serializes each matched message once
per worker, not once per subscriber.
"""

from __future__ import annotations

import json
import struct
from typing import Iterable, List, Tuple

T_HELLO = 0
T_SUB = 1
T_UNSUB = 2
T_PUBB = 3
T_DLV = 4
T_PUBB_ACK = 5
# SUB confirm (router -> worker, body = json {h}): sent after the
# router registered the subscription + enqueued retained replay. The
# worker holds the client's SUBACK on it, so SUBACK keeps the
# reference's meaning — the subscription is ROUTABLE, broker-wide
# (emqx_broker.erl:127-160 is synchronous for the same reason).
T_SUB_ACK = 6
# RAW delivery (r->w): pre-serialized MQTT PUBLISH frames for the QoS0
# fast lane — the router serializes once per (message, version, retain)
# and the worker writes the bytes straight to subscriber sockets,
# bypassing the per-delivery Channel/Session work (eligibility is
# negotiated per subscription via the SUB json's "fl" field: qos 0, no
# mountpoint, empty delivered/completed hook chains worker-side).
#   body: u32 n, n * (u32 blen, frame_bytes, u16 nh, nh * u32 handle)
T_RAW = 8
# Session ops (json, both directions): the router brokers emqx_cm
# semantics ACROSS workers — open (w->r: resolve takeover/resume at
# CONNECT), take/discard (r->w: hand over / kill a live channel),
# state (w->r: serialized session after take), open_ack (r->w),
# park (w->r: disconnect with expiry>0 -> router-side detached store,
# WAL-backed when persistence is on), resume_done (w->r: new channel
# installed; router flushes handoff-banked messages), closed (w->r).
T_SESS = 7

_HDR = struct.Struct("<IB")
_U16 = struct.Struct("<H")
_U32 = struct.Struct("<I")

MAX_FRAME = 64 * 1024 * 1024
# soft per-frame body cap for senders: batches above this split into
# multiple frames so a large tick (pipelined max-size publishes, a huge
# fan-out delivery flush) can never hit the receiver's MAX_FRAME reject,
# which would tear down the whole fabric link
MAX_BODY = 8 * 1024 * 1024


def pack_frame(ftype: int, body: bytes) -> bytes:
    return _HDR.pack(len(body), ftype) + body


def pub_record_size(m) -> int:
    """Serialized size of one pub_record (sender-side chunking)."""
    return (
        9
        + len(m.topic.encode())
        + len(m.payload or b"")
        + len((m.from_client or "").encode())
    )


def pack_json(ftype: int, obj) -> bytes:
    return pack_frame(ftype, json.dumps(obj).encode())


def _encode_props(props) -> bytes:
    from emqx_tpu.mqtt.frame import encode_properties

    return encode_properties(props)


def _decode_props(blob: bytes):
    from emqx_tpu.mqtt.frame import decode_properties

    props, _off = decode_properties(blob, 0)
    return props


def pack_pub_batch(msgs, seq: int = 0) -> bytes:
    """msgs: iterable of Message."""
    parts = [b""]
    n = 0
    for m in msgs:
        t = m.topic.encode()
        p = m.payload or b""
        c = (m.from_client or "").encode()
        props = getattr(m, "properties", None)
        flags = (m.qos & 3) | (4 if m.retain else 0) | (
            8 if getattr(m, "dup", False) else 0
        ) | (0x10 if props else 0)
        rec = (
            _U16.pack(len(t)) + t + _U32.pack(len(p)) + p
            + bytes([flags]) + _U16.pack(len(c)) + c
        )
        if props:
            pb = _encode_props(props)
            rec += _U32.pack(len(pb)) + pb
        parts.append(rec)
        n += 1
    parts[0] = _U32.pack(seq) + _U32.pack(n)
    return pack_frame(T_PUBB, b"".join(parts))


def unpack_pub_batch(body: bytes):
    """-> (seq, [(topic, payload, qos, retain, dup, from_client,
    props | None)])"""
    (seq,) = _U32.unpack_from(body, 0)
    (n,) = _U32.unpack_from(body, 4)
    off = 8
    out = []
    for _ in range(n):
        (tl,) = _U16.unpack_from(body, off)
        off += 2
        topic = body[off : off + tl].decode()
        off += tl
        (pl,) = _U32.unpack_from(body, off)
        off += 4
        payload = body[off : off + pl]
        off += pl
        flags = body[off]
        off += 1
        (cl,) = _U16.unpack_from(body, off)
        off += 2
        client = body[off : off + cl].decode()
        off += cl
        props = None
        if flags & 0x10:
            (pbl,) = _U32.unpack_from(body, off)
            off += 4
            props = _decode_props(body[off : off + pbl])
            off += pbl
        out.append(
            (topic, payload, flags & 3, bool(flags & 4), bool(flags & 8),
             client, props)
        )
    return seq, out


def pack_pub_ack(seq: int, counts) -> bytes:
    return pack_frame(
        T_PUBB_ACK,
        _U32.pack(seq) + _U32.pack(len(counts))
        + struct.pack(f"<{len(counts)}i", *counts),
    )


def unpack_pub_ack(body: bytes):
    (seq,) = _U32.unpack_from(body, 0)
    (n,) = _U32.unpack_from(body, 4)
    return seq, list(struct.unpack_from(f"<{n}i", body, 8))


def pack_dlv_batches(records, max_body: float = MAX_BODY):
    """records: [(msg, [handle, ...])] -> yields one or more DLV frames,
    each body bounded by ~max_body (always at least one record per
    frame), so a huge delivery tick can't exceed the receiver's
    MAX_FRAME and tear the fabric link."""
    out = bytearray(9)  # frame header (5) + count (4), patched below
    n = 0
    for m, handles in records:
        t = m.topic.encode()
        p = m.payload or b""
        c = (m.from_client or "").encode()
        props = getattr(m, "properties", None)
        flags = (m.qos & 3) | (4 if m.retain else 0) | (
            8 if m.headers.get("retained") else 0
        ) | (0x10 if props else 0)
        head = (
            _U16.pack(len(t)) + t + _U32.pack(len(p)) + p
            + bytes([flags]) + _U16.pack(len(c)) + c
        )
        if props:
            pb = _encode_props(props)
            head += _U32.pack(len(pb)) + pb
        # ntargets is u16: split monster fan-outs across records rather
        # than raise mid-flush (a 10M-sub broker CAN put >65535 matching
        # subscriptions on one worker)
        for lo in range(0, len(handles), 0xFFFF):
            chunk = handles[lo : lo + 0xFFFF]
            rec_len = len(head) + 2 + 4 * len(chunk)
            if n and len(out) + rec_len > max_body:
                out[0:5] = _HDR.pack(len(out) - 5, T_DLV)
                out[5:9] = _U32.pack(n)
                yield bytes(out)
                out = bytearray(9)
                n = 0
            out += head
            out += _U16.pack(len(chunk))
            out += struct.pack(f"<{len(chunk)}I", *chunk)
            n += 1
    if n:
        out[0:5] = _HDR.pack(len(out) - 5, T_DLV)
        out[5:9] = _U32.pack(n)
        yield bytes(out)


def pack_dlv_batch(records) -> bytes:
    """Single-frame variant (tests / small ticks)."""
    frames = list(pack_dlv_batches(records, max_body=float("inf")))
    return frames[0] if frames else pack_frame(T_DLV, _U32.pack(0))


def unpack_dlv_batch(body: bytes):
    """-> [(topic, payload, qos, retain, retained, from_client,
    props | None, [handles])]"""
    (n,) = _U32.unpack_from(body, 0)
    off = 4
    out = []
    for _ in range(n):
        (tl,) = _U16.unpack_from(body, off)
        off += 2
        topic = body[off : off + tl].decode()
        off += tl
        (pl,) = _U32.unpack_from(body, off)
        off += 4
        payload = body[off : off + pl]
        off += pl
        flags = body[off]
        off += 1
        (cl,) = _U16.unpack_from(body, off)
        off += 2
        client = body[off : off + cl].decode()
        off += cl
        props = None
        if flags & 0x10:
            (pbl,) = _U32.unpack_from(body, off)
            off += 4
            props = _decode_props(body[off : off + pbl])
            off += pbl
        (nh,) = _U16.unpack_from(body, off)
        off += 2
        handles = list(struct.unpack_from(f"<{nh}I", body, off))
        off += 4 * nh
        out.append(
            (topic, payload, flags & 3, bool(flags & 4), bool(flags & 8),
             client, props, handles)
        )
    return out


# -- native acceleration ------------------------------------------------
# The C codec (mqtt/_codec.c) implements the same wire format; the pure-
# Python functions above stay the semantic reference and differentially
# test it (tests/test_codec_native.py). Packing DLV batches in Python
# was the largest router-process cost in the serving profile.
from emqx_tpu.mqtt import codec_native as _nc  # noqa: E402

_py_pack_dlv_batches = pack_dlv_batches
_py_pack_pub_batch = pack_pub_batch
_py_unpack_pub_batch = unpack_pub_batch
_py_unpack_dlv_batch = unpack_dlv_batch

if _nc.pack_dlv_frames is not None:

    def pack_dlv_batches(records, max_body: float = MAX_BODY):  # noqa: F811
        if max_body == float("inf"):
            max_body = 1 << 62
        if not isinstance(records, list):
            records = list(records)
        if any(getattr(m, "properties", None) for m, _h in records):
            # props-carrying batches take the (rarer) Python packer;
            # the C packer handles the propless hot path
            return _py_pack_dlv_batches(records, max_body)
        return _nc.pack_dlv_frames(records, int(max_body))

    def pack_pub_batch(msgs, seq: int = 0) -> bytes:  # noqa: F811
        if not isinstance(msgs, list):
            msgs = list(msgs)
        if any(getattr(m, "properties", None) for m in msgs):
            return _py_pack_pub_batch(msgs, seq)
        return _nc.pack_pub_batch(msgs, seq)

    def unpack_pub_batch(body: bytes):  # noqa: F811
        seq, recs = _nc.unpack_pub_batch(body)
        # the C layer returns the raw props block (or None); decode here
        return seq, [
            r if r[6] is None else r[:6] + (_decode_props(r[6]),)
            for r in recs
        ]

    def unpack_dlv_batch(body: bytes):  # noqa: F811
        return [
            r if r[6] is None else r[:6] + (_decode_props(r[6]), r[7])
            for r in _nc.unpack_dlv_batch(body)
        ]


def pack_raw_batches(records, max_body: float = MAX_BODY):
    """records: [(frame_bytes, [handle, ...])] -> one or more T_RAW
    frames, each body bounded by ~max_body."""
    out = bytearray(9)
    n = 0
    for buf, handles in records:
        # nh is u16: split monster fan-outs across records (same rule
        # as pack_dlv_batches — a 10M-sub broker CAN put >65535
        # matching subscriptions on one worker)
        for lo in range(0, len(handles), 0xFFFF):
            chunk = handles[lo : lo + 0xFFFF]
            rec_len = 4 + len(buf) + 2 + 4 * len(chunk)
            if n and len(out) + rec_len > max_body:
                out[0:5] = _HDR.pack(len(out) - 5, T_RAW)
                out[5:9] = _U32.pack(n)
                yield bytes(out)
                out = bytearray(9)
                n = 0
            out += _U32.pack(len(buf))
            out += buf
            out += _U16.pack(len(chunk))
            out += struct.pack(f"<{len(chunk)}I", *chunk)
            n += 1
    if n:
        out[0:5] = _HDR.pack(len(out) - 5, T_RAW)
        out[5:9] = _U32.pack(n)
        yield bytes(out)


def unpack_raw_batch(body: bytes):
    """-> [(frame_bytes, [handles])]"""
    (n,) = _U32.unpack_from(body, 0)
    off = 4
    out = []
    for _ in range(n):
        (bl,) = _U32.unpack_from(body, off)
        off += 4
        buf = body[off : off + bl]
        off += bl
        (nh,) = _U16.unpack_from(body, off)
        off += 2
        handles = list(struct.unpack_from(f"<{nh}I", body, off))
        off += 4 * nh
        out.append((buf, handles))
    return out


async def read_frame(reader) -> Tuple[int, bytes]:
    hdr = await reader.readexactly(5)
    length, ftype = _HDR.unpack(hdr)
    if length > MAX_FRAME:
        raise ValueError(f"fabric frame too large: {length}")
    body = await reader.readexactly(length) if length else b""
    return ftype, body
