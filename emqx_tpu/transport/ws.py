"""MQTT-over-WebSocket transport (reference: apps/emqx/src/emqx_ws_connection.erl,
listener wiring at apps/emqx/src/emqx_listeners.erl:239-248).

The reference runs a cowboy websocket handler that feeds the same
emqx_channel state machine as the TCP path. Here a `websockets` server
adapts each WS connection to the stream interface `Connection` expects, so
the parser/channel/keepalive logic is shared verbatim with TCP/TLS.

MQTT-over-WS rules (MQTT 5.0 spec §6, mirrored from emqx_ws_connection):
- subprotocol must be "mqtt" (the reference also accepts the legacy
  "mqttv3.1" names via `fail_if_no_subprotocol=false`; we accept absent
  subprotocol for lenient clients, matching that default-off check)
- payload is binary frames; text frames are a protocol error
- a single WS message may carry multiple or partial MQTT packets (the
  incremental Parser already handles both).
"""

from __future__ import annotations

import asyncio
import ssl as ssl_mod
from typing import Optional

# `websockets` is imported lazily (same pattern as dtls.py's
# `cryptography`): the module must stay importable on images without the
# package — a ws/wss listener fails at START time with an actionable
# error, not at import, and runtime ws tests skip cleanly.
try:
    from websockets.asyncio.server import serve
    from websockets.exceptions import ConnectionClosed
except ImportError:  # pragma: no cover - exercised on slim images
    serve = None

    class ConnectionClosed(Exception):  # placeholder: keeps the
        """Never raised when `websockets` is absent."""  # except clauses
        # below importable; real connections cannot exist without serve()

HAVE_WEBSOCKETS = serve is not None


def require_ws_support() -> None:
    """Raise a clear error when the websockets backend is unavailable;
    called when a ws/wss listener actually starts."""
    if serve is None:
        raise RuntimeError(
            "WebSocket listeners require the 'websockets' package; "
            "install it or remove the ws/wss listener from the config"
        )


from emqx_tpu.transport.connection import Connection  # noqa: E402
from emqx_tpu.transport.listener import build_ssl_context  # noqa: E402


class _WsStream:
    """Adapts a websockets ServerConnection to the asyncio stream reader and
    writer duck-types used by `Connection` (read / write / drain / close)."""

    def __init__(self, ws):
        self._ws = ws
        self._buf = bytearray()
        self._closed = False
        self._flush_task: Optional[asyncio.Task] = None

    # -- reader side -------------------------------------------------------
    async def read(self, _n: int) -> bytes:
        try:
            msg = await self._ws.recv()
        except ConnectionClosed:
            return b""
        if isinstance(msg, str):
            # MQTT requires binary WS frames; treat text as EOF-with-error
            await self._ws.close(code=1003)  # unsupported data
            return b""
        return msg

    # -- writer side -------------------------------------------------------
    def write(self, data: bytes) -> None:
        # asyncio StreamWriter.write transmits eagerly; mirror that by
        # scheduling a flush as soon as bytes are buffered, so callers that
        # never await drain() (fire-and-forget sends) still make progress
        self._buf += data
        if not self._closed and (self._flush_task is None or self._flush_task.done()):
            try:
                self._flush_task = asyncio.get_running_loop().create_task(
                    self._flush()
                )
            except RuntimeError:
                pass

    # Upper bound on a single outgoing WS message: a delivery burst must not
    # coalesce into one message bigger than the peer's max_size (the MQTT
    # parser reassembles packets across WS messages either way)
    CHUNK = 32 * 1024

    async def _flush(self) -> None:
        while self._buf and not self._closed:
            out = bytes(self._buf[: self.CHUNK])
            del self._buf[: self.CHUNK]
            try:
                await self._ws.send(out)
            except ConnectionClosed:
                self._closed = True
                return

    async def drain(self) -> None:
        # Exactly one _flush coroutine may run at a time (write() and this
        # loop both create a task only when the previous one is done, with no
        # await between check and create), so MQTT byte order is preserved.
        while not self._closed and self._buf:
            task = self._flush_task
            if task is None or task.done():
                task = asyncio.get_running_loop().create_task(self._flush())
                self._flush_task = task
            await task
        if self._closed:
            raise ConnectionResetError("ws closed")

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        # flush anything the channel wrote right before closing (e.g. the
        # final DISCONNECT/CONNACK) then close the WS connection
        buf = bytes(self._buf)
        self._buf.clear()

        async def _shutdown():
            try:
                if buf:
                    await self._ws.send(buf)
            except ConnectionClosed:
                pass
            try:
                await self._ws.close()
            except Exception:
                pass

        try:
            asyncio.get_running_loop().create_task(_shutdown())
        except RuntimeError:
            pass

    async def wait_closed(self) -> None:
        try:
            await self._ws.wait_closed()
        except Exception:
            pass

    def get_extra_info(self, key: str):
        if key == "peername":
            return self._ws.remote_address
        return None


class WsListener:
    """A ws/wss listener feeding the shared Connection pump."""

    def __init__(self, broker, cm, config, channel_config, ctx=None):
        self.broker = broker
        self.cm = cm
        self.config = config
        self.channel_config = channel_config
        self.ctx = ctx
        from emqx_tpu.transport.listener import AdmissionControl

        self._admission = AdmissionControl(ctx, broker.metrics)
        self._server = None
        self._conns: set = set()

    @property
    def port(self) -> int:
        if self._server is not None:
            socks = list(self._server.sockets or [])
            if socks:
                return socks[0].getsockname()[1]
        return self.config.port

    async def start(self) -> None:
        require_ws_support()
        ctx: Optional[ssl_mod.SSLContext] = None
        if self.config.type == "wss":
            ctx = build_ssl_context(self.config)
            if self.ctx is not None and getattr(self.ctx, "psk", None) is not None:
                self.ctx.psk.wire_into(ctx)
        # One WS message may legally coalesce several MQTT packets; allow a
        # generous multiple of max_packet_size before the anti-OOM cap bites
        max_size = max(8 * self.channel_config.caps.max_packet_size, 1 << 20)
        self._server = await serve(
            self._on_ws,
            self.config.bind,
            self.config.port,
            ssl=ctx,
            subprotocols=["mqtt"],
            select_subprotocol=self._select_subprotocol,
            max_size=max_size,
        )

    @staticmethod
    def _select_subprotocol(connection, offered):
        # fail_if_no_subprotocol=false semantics: prefer "mqtt" (or the
        # legacy mqttv3.1* names), but let header-less clients through
        for sp in offered:
            if sp == "mqtt" or str(sp).startswith("mqttv3.1"):
                return sp
        return None

    def connection_count(self) -> int:
        return len(self._conns)

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
        for t in list(self._conns):
            t.cancel()
        if self._server is not None:
            await self._server.wait_closed()
            self._server = None

    async def _on_ws(self, ws) -> None:
        if not self._admission.admit(
            len(self._conns), self.config.max_connections
        ):
            await ws.close(code=1013)  # try again later
            return
        stream = _WsStream(ws)
        conn = Connection(
            self.broker, self.cm, stream, stream, self.channel_config,
            ctx=self.ctx,
        )
        task = asyncio.current_task()
        self._conns.add(task)
        try:
            await conn.run()
        finally:
            self._conns.discard(task)
