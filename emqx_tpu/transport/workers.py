"""Multi-process connection workers: the host data plane at scale.

One Python event loop tops out near a thousand MQTT messages/s once it
also pays codec + per-subscriber serialization. The reference never has
this wall — every connection is a BEAM process spread over cores
(emqx_connection.erl:173-176). The equivalent here:

- N WORKER processes accept clients on a shared SO_REUSEPORT port (the
  kernel load-balances accepts). Each runs the full Connection/Channel/
  Session stack — codec, keepalive, QoS state, acks — against a
  `WorkerBroker` proxy instead of the real Broker.
- The ROUTER process keeps the single DeviceRouter, subscription tables,
  retainer, rules, and cluster links. Workers speak the batched fabric
  protocol (transport/fabric.py) to it over a unix socket: SUB/UNSUB
  register proxy subscribers; publishes arrive in batches that ride the
  ingest window onto the TPU kernel; deliveries return batched, one
  record per (message, worker), fanned to sockets worker-side.

Scope: worker listeners are the high-throughput serving path. Sessions
live in their worker (no cross-worker takeover; persistent-session WAL
stays with in-process listeners). Authn/authz/banned guards are rebuilt
per worker from the same config, so admission semantics match.
"""

from __future__ import annotations

import asyncio
import os
import tempfile
from typing import Dict, List, Optional, Tuple

from emqx_tpu.broker.message import Message
from emqx_tpu.mqtt import packet as pkt
from emqx_tpu.ops import topics as T
from emqx_tpu.transport import fabric as F

# ---------------------------------------------------------------------------
# router side
# ---------------------------------------------------------------------------


class WorkerFabric:
    """Router-process endpoint: UDS server the workers dial into.

    For every worker SUB it registers a proxy subscriber with the real
    Broker whose deliver() enqueues (msg, handle) into that worker's
    outbox; outboxes flush once per loop tick with one DLV record per
    message (per-subscriber QoS handling stays worker-side)."""

    def __init__(self, app, uds_path: str):
        self.app = app
        self.broker = app.broker
        self.uds_path = uds_path
        self._server: Optional[asyncio.AbstractServer] = None
        self._writers: Dict[int, asyncio.StreamWriter] = {}
        # wid -> {(full_sid, filter)}: explicit registry of the broker
        # subscriptions each worker proxies (worker-death cleanup walks
        # this, never a sid-prefix match that could catch an in-process
        # client whose id happens to start with "w{wid}|")
        self._fabric_subs: Dict[int, set] = {}
        # wid -> [(msg, [handles])]; one record per message per tick
        self._outbox: Dict[int, List] = {}
        self._outbox_last: Dict[int, Tuple[int, List[int]]] = {}
        self._flush_scheduled = False
        self._tasks: set = set()

    async def start(self) -> None:
        try:
            os.unlink(self.uds_path)
        except FileNotFoundError:
            pass
        self._server = await asyncio.start_unix_server(
            self._on_worker, path=self.uds_path
        )

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
        for t in list(self._tasks):
            t.cancel()
        if self._server is not None:
            await self._server.wait_closed()
            self._server = None
        try:
            os.unlink(self.uds_path)
        except FileNotFoundError:
            pass

    async def _on_worker(self, reader, writer) -> None:
        task = asyncio.current_task()
        self._tasks.add(task)
        wid = -1
        try:
            ftype, body = await F.read_frame(reader)
            if ftype != F.T_HELLO:
                return
            wid = int.from_bytes(body[:2], "little")
            self._writers[wid] = writer
            while True:
                ftype, body = await F.read_frame(reader)
                if ftype == F.T_SUB:
                    h = self._on_sub(wid, body)
                    # confirm AFTER registration + retained enqueue:
                    # the worker releases the client's SUBACK on this
                    if not writer.is_closing():
                        writer.write(F.pack_json(F.T_SUB_ACK, {"h": h}))
                elif ftype == F.T_UNSUB:
                    self._on_unsub(wid, body)
                elif ftype == F.T_PUBB:
                    await self._on_pub_batch(writer, body)
        except (
            asyncio.IncompleteReadError,
            ConnectionResetError,
            asyncio.CancelledError,
        ):
            pass
        finally:
            self._tasks.discard(task)
            if wid >= 0:
                self._writers.pop(wid, None)
                self._outbox.pop(wid, None)
                self._drop_worker_subs(wid)
            writer.close()

    # -- subscribe side ---------------------------------------------------
    def _sid(self, wid: int, sid: str) -> str:
        return f"w{wid}|{sid}"

    def _on_sub(self, wid: int, body: bytes) -> int:
        """Register a worker subscription; returns its handle (the read
        loop confirms it back as SUB_ACK after this returns)."""
        import json

        d = json.loads(body)
        handle = int(d["h"])
        opts = pkt.SubOpts(
            qos=int(d.get("qos", 0)),
            no_local=bool(d.get("nl", False)),
            retain_as_published=bool(d.get("rap", False)),
            retain_handling=int(d.get("rh", 0)),
        )
        filter_ = d["f"]
        _group, real = T.parse_share(filter_)
        # rh=1 semantics key on THIS CLIENT's prior subscription, which
        # only the worker-side session knows (channel.py sets
        # opts._existing); broker-wide existence would suppress replay
        # for every later client
        existing = bool(d.get("ex", False))

        def deliver(msg, _opts, _wid=wid, _h=handle):
            self.enqueue(_wid, _h, msg)

        full_sid = self._sid(wid, d["sid"])
        self.broker.subscribe(full_sid, d.get("cid", ""), filter_, opts,
                              deliver)
        self._fabric_subs.setdefault(wid, set()).add((full_sid, filter_))
        # retained replay (the worker-side channel hooks have no retainer;
        # semantics per emqx_retainer: never for $share, rh=2 never,
        # rh=1 only for fresh subscriptions)
        ret = getattr(self.app, "retainer", None)
        if (
            ret is not None
            and ret.enabled
            and _group is None
            and opts.retain_handling != 2
            and not (opts.retain_handling == 1 and existing)
        ):
            for m in ret.match(real):
                import copy

                mm = copy.copy(m)
                mm.headers = dict(m.headers, retained=True)
                self.enqueue(wid, handle, mm)
        return handle

    def _on_unsub(self, wid: int, body: bytes) -> None:
        import json

        d = json.loads(body)
        full_sid = self._sid(wid, d["sid"])
        self.broker.unsubscribe(full_sid, d["f"])
        subs = self._fabric_subs.get(wid)
        if subs is not None:
            subs.discard((full_sid, d["f"]))

    def _drop_worker_subs(self, wid: int) -> None:
        """Worker died: every subscription it proxied is gone."""
        for sid, f in self._fabric_subs.pop(wid, set()):
            self.broker.unsubscribe(sid, f)

    # -- publish side -----------------------------------------------------
    async def _on_pub_batch(self, writer, body: bytes) -> None:
        # `writer` is the CONNECTION's stream, not a wid lookup: a stale
        # ack task must die with its (closed) connection, never resolve a
        # respawned worker's identically-numbered batch
        seq, records = F.unpack_pub_batch(body)
        results = []
        # enqueue INLINE (per-publisher ordering is an MQTT contract);
        # only the confirm-wait runs as a task so the next frame parses
        # while this batch's ingest window flushes
        for topic, payload, qos, retain, dup, client in records:
            msg = Message(
                topic=topic,
                payload=payload,
                qos=qos,
                retain=retain,
                dup=dup,
                from_client=client,
            )
            results.append(await self.broker.apublish_enqueue(msg))
        if not any(r[2] > 0 for r in records):
            return  # pure-QoS0 batch: the worker holds no PUBACKs on it
        t = asyncio.get_running_loop().create_task(
            self._ack_pub_batch(writer, seq, results)
        )
        self._tasks.add(t)
        t.add_done_callback(self._tasks.discard)

    async def _ack_pub_batch(self, writer, seq: int, results) -> None:
        """Confirm AFTER every message dispatched/banked (ingest futures
        resolve at the batch-window flush) with per-message delivery
        counts — the worker holds client PUBACKs on this."""
        counts = []
        for r in results:
            if isinstance(r, int):
                counts.append(r)
            else:
                try:
                    counts.append(int(await r))
                except Exception:
                    counts.append(0)
        if not writer.is_closing():
            try:
                writer.write(F.pack_pub_ack(seq, counts))
            except Exception:
                self.broker.metrics.inc("fabric.flush.errors")

    # -- delivery side ----------------------------------------------------
    def enqueue(self, wid: int, handle: int, msg) -> None:
        if wid not in self._writers:
            return
        box = self._outbox.setdefault(wid, [])
        last = self._outbox_last.get(wid)
        if last is not None and last[0] == id(msg) and box:
            last[1].append(handle)
        else:
            handles = [handle]
            box.append((msg, handles))
            self._outbox_last[wid] = (id(msg), handles)
        if not self._flush_scheduled:
            self._flush_scheduled = True
            asyncio.get_running_loop().call_soon(self._flush)

    # a worker that stops reading its UDS must not grow this process's
    # write buffer without bound: past the high-water mark its deliveries
    # drop (the mqueue-overflow analog at the fabric seam)
    WRITE_HIGH_WATER = 32 * 1024 * 1024

    def _flush(self) -> None:
        self._flush_scheduled = False
        self._outbox_last.clear()
        boxes, self._outbox = self._outbox, {}
        for wid, records in boxes.items():
            w = self._writers.get(wid)
            if w is None or w.is_closing():
                continue
            try:
                if (
                    w.transport.get_write_buffer_size()
                    > self.WRITE_HIGH_WATER
                ):
                    self.broker.metrics.inc(
                        "fabric.flush.dropped", len(records)
                    )
                    continue
                for frame in F.pack_dlv_batches(records):
                    w.write(frame)
            except Exception:
                # one worker's dead pipe (or a malformed record) must not
                # lose the OTHER workers' deliveries in this tick
                self.broker.metrics.inc("fabric.flush.errors")


# ---------------------------------------------------------------------------
# worker side
# ---------------------------------------------------------------------------


class WorkerBroker:
    """Broker facade inside a worker: same surface Channel/CM consume
    (subscribe/unsubscribe/apublish/metrics/hooks), forwarding over the
    fabric link. Deliveries come back by subscription handle."""

    def __init__(self, hooks, metrics):
        self.hooks = hooks
        self.metrics = metrics
        self._link_w: Optional[asyncio.StreamWriter] = None
        self._subs: Dict[int, Tuple] = {}  # handle -> (deliver, opts)
        self._byname: Dict[Tuple[str, str], int] = {}
        self._next_handle = 1
        # publish buffer entries: (msg, future) — the future resolves
        # with the message's delivery count when the router acks the
        # batch (PUBB_ACK), which is when the channel releases the
        # client's PUBACK
        self._pub_buf: List[Tuple[Message, Optional["asyncio.Future"]]] = []
        self._pub_scheduled = False
        self._next_seq = 1
        # seq -> (futures, safety TimerHandle cancelled on ack)
        self._inflight: Dict[int, Tuple[list, object]] = {}
        # handle -> (future resolved by the router's SUB_ACK, safety
        # timer cancelled on ack); the channel holds the client's SUBACK
        # on the future: SUBACK == routable
        self._sub_acks: Dict[int, Tuple["asyncio.Future", object]] = {}
        self.ACK_TIMEOUT_S = 60.0

    # fabric glue
    def attach_link(self, writer) -> None:
        self._link_w = writer

    def _send(self, data: bytes) -> None:
        if self._link_w is not None and not self._link_w.is_closing():
            self._link_w.write(data)

    # Broker surface ------------------------------------------------------
    def subscribe(self, sid, client_id, filter_, opts, deliver):
        """Returns a future resolved when the router CONFIRMS the
        subscription (SUB_ACK) — the channel awaits it before SUBACK, so
        a publish racing the SUBACK still delivers (the in-process
        broker's subscribe is synchronous for the same contract)."""
        key = (sid, filter_)
        h = self._byname.get(key)
        if h is None:
            h = self._next_handle
            self._next_handle += 1
            self._byname[key] = h
        self._subs[h] = (deliver, opts)
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        if self._link_w is None or self._link_w.is_closing():
            # fail fast: no link, no registration — the channel turns
            # False into a SUBACK failure code instead of stalling 30s
            fut.set_result(False)
            return fut
        ent = self._sub_acks.get(h)
        if ent is not None and not ent[0].done():
            fut = ent[0]  # re-subscribe racing its own confirm
        else:
            timer = loop.call_later(
                30.0,
                lambda: fut.done() or fut.set_result(False),
            )
            self._sub_acks[h] = (fut, timer)
        self._send(
            F.pack_json(
                F.T_SUB,
                {
                    "h": h,
                    "sid": sid,
                    "cid": client_id,
                    "f": filter_,
                    "qos": opts.qos,
                    "nl": opts.no_local,
                    "rap": opts.retain_as_published,
                    "rh": opts.retain_handling,
                    # per-client resubscribe flag set by the worker-side
                    # channel (rh=1 retained-replay suppression)
                    "ex": bool(getattr(opts, "_existing", False)),
                },
            )
        )
        return fut

    def on_sub_ack(self, h: int) -> None:
        ent = self._sub_acks.pop(h, None)
        if ent is None:
            return
        fut, timer = ent
        timer.cancel()
        if not fut.done():
            fut.set_result(True)

    def unsubscribe(self, sid, filter_) -> bool:
        h = self._byname.pop((sid, filter_), None)
        if h is None:
            return False
        self._subs.pop(h, None)
        ent = self._sub_acks.pop(h, None)
        if ent is not None:
            # unsubscribing a confirm-pending handle (e.g. the channel's
            # failed-subscribe rollback): cancel the timer and resolve
            # so nothing leaks or waits on an ack that can't arrive
            fut, timer = ent
            timer.cancel()
            if not fut.done():
                fut.set_result(False)
        self._send(F.pack_json(F.T_UNSUB, {"sid": sid, "f": filter_}))
        return True

    def drop_session_subs(self, sid, filters) -> None:
        for f in list(filters):
            self.unsubscribe(sid, f)

    def _enqueue_pub(self, msg: Message):
        """QoS>0 returns a Future resolved by the router's PUBB_ACK (the
        client's PUBACK waits on it); QoS0 is fire-and-forget — coupling
        it to the ack round-trip measured ~4x e2e throughput loss for a
        guarantee QoS0 never promises."""
        self.metrics.inc("messages.received")
        fut = None
        if msg.qos > 0:
            fut = asyncio.get_running_loop().create_future()
        self._pub_buf.append((msg, fut))
        if not self._pub_scheduled:
            self._pub_scheduled = True
            asyncio.get_running_loop().call_soon(self._flush_pubs)
        return fut if fut is not None else 0

    def _flush_pubs(self) -> None:
        self._pub_scheduled = False
        buf, self._pub_buf = self._pub_buf, []
        if not buf:
            return
        # chunk below the fabric frame cap: ~64 pipelined max-size
        # publishes in one tick would otherwise exceed the receiver's
        # MAX_FRAME and tear down the link
        start = 0
        while start < len(buf):
            size = 8
            end = start
            while end < len(buf):
                r = F.pub_record_size(buf[end][0])
                if end > start and size + r > F.MAX_BODY:
                    break
                size += r
                end += 1
            chunk = buf[start:end]
            start = end
            seq = self._next_seq
            self._next_seq += 1
            futs = [f for _, f in chunk]
            if any(f is not None for f in futs):
                # safety: a lost ack (router bug / torn link mid-restart)
                # must not wedge every publisher's PUBACK forever
                timer = asyncio.get_running_loop().call_later(
                    self.ACK_TIMEOUT_S, self._expire_batch, seq
                )
                self._inflight[seq] = (futs, timer)
            self._send(F.pack_pub_batch([m for m, _ in chunk], seq))

    def _expire_batch(self, seq: int) -> None:
        ent = self._inflight.pop(seq, None)
        if ent:
            self.metrics.inc("fabric.puback.timeouts")
            for f in ent[0]:
                if f is not None and not f.done():
                    # -1 = the 'never no-subscribers' sentinel (see
                    # channel._send_pub_ack): a late-but-delivered batch
                    # must not tell v5 clients NO_MATCHING_SUBSCRIBERS
                    f.set_result(-1)

    def on_pub_ack(self, seq: int, counts) -> None:
        ent = self._inflight.pop(seq, None)
        if not ent:
            return
        futs, timer = ent
        timer.cancel()
        for f, n in zip(futs, counts):
            if f is not None and not f.done():
                f.set_result(n)

    async def apublish_enqueue(self, msg: Message):
        """-> int (dropped) or a Future resolving with the delivery count
        once the router CONFIRMS the batch — same contract as the real
        Broker's ingest path, so the channel's ack queue holds each
        QoS1/2 PUBACK until the message is actually routed."""
        msg = await self.hooks.arun_fold("message.publish", (), msg)
        if msg is None or msg.headers.get("allow_publish") is False:
            self.metrics.inc("messages.dropped")
            return 0
        return self._enqueue_pub(msg)

    async def apublish(self, msg: Message) -> int:
        r = await self.apublish_enqueue(msg)
        return r if isinstance(r, int) else await r

    def publish(self, msg: Message) -> int:
        msg = self.hooks.run_fold("message.publish", (), msg)
        if msg is None or msg.headers.get("allow_publish") is False:
            return 0
        self._enqueue_pub(msg)  # fire-and-forget (sync callers: will, sys)
        return 0

    # delivery ------------------------------------------------------------
    def on_delivery(self, topic, payload, qos, retain, retained, client,
                    handles) -> None:
        msg = Message(
            topic=topic,
            payload=payload,
            qos=qos,
            retain=retain,
            from_client=client,
        )
        if retained:
            msg.headers["retained"] = True
        for h in handles:
            ent = self._subs.get(h)
            if ent is None:
                continue
            deliver, opts = ent
            try:
                deliver(msg, opts)
            except Exception:
                self.metrics.inc("delivery.errors")


def worker_main(
    wid: int,
    bind: str,
    port: int,
    uds_path: str,
    config,
) -> None:
    """Entry point of a spawned connection worker (own interpreter; the
    TPU is never touched here — jax stays uninitialized)."""
    asyncio.run(_worker_async(wid, bind, port, uds_path, config))


async def _worker_async(wid, bind, port, uds_path, config) -> None:
    from emqx_tpu.app import build_guard_hooks
    from emqx_tpu.broker.cm import ChannelManager
    from emqx_tpu.broker.hooks import Hooks
    from emqx_tpu.broker.metrics import Metrics
    from emqx_tpu.transport.connection import Connection

    hooks = Hooks()
    metrics = Metrics()
    broker = WorkerBroker(hooks, metrics)
    channel_config = build_guard_hooks(config, hooks)
    cm = ChannelManager(broker)

    # fabric link to the router process (retry: the router may still be
    # binding the UDS when workers spawn)
    for attempt in range(100):
        try:
            reader, writer = await asyncio.open_unix_connection(uds_path)
            break
        except (FileNotFoundError, ConnectionRefusedError):
            await asyncio.sleep(0.05 * (attempt + 1))
    else:
        raise RuntimeError(f"worker {wid}: router fabric not reachable")
    writer.write(F.pack_frame(F.T_HELLO, wid.to_bytes(2, "little")))
    broker.attach_link(writer)

    async def pump_link():
        try:
            while True:
                ftype, body = await F.read_frame(reader)
                if ftype == F.T_DLV:
                    for rec in F.unpack_dlv_batch(body):
                        broker.on_delivery(*rec)
                elif ftype == F.T_PUBB_ACK:
                    broker.on_pub_ack(*F.unpack_pub_ack(body))
                elif ftype == F.T_SUB_ACK:
                    import json as _json

                    broker.on_sub_ack(int(_json.loads(body)["h"]))
        except (asyncio.IncompleteReadError, ConnectionResetError):
            os._exit(0)  # router gone: worker has nothing to serve

    link_task = asyncio.create_task(pump_link())

    conns: set = set()

    async def on_client(r, w):
        conn = Connection(broker, cm, r, w, channel_config)
        task = asyncio.current_task()
        conns.add(task)
        try:
            await conn.run()
        finally:
            conns.discard(task)

    server = await asyncio.start_server(
        on_client, bind, port, reuse_port=True
    )
    try:
        await asyncio.gather(server.serve_forever(), link_task)
    except asyncio.CancelledError:
        pass


# ---------------------------------------------------------------------------
# pool management (router side)
# ---------------------------------------------------------------------------


class WorkerPool:
    """Spawns and supervises the worker processes for one listener.

    Workers launch as `python -m emqx_tpu.transport.workers ...` with the
    app config re-serialized to JSON — plain subprocesses, no
    multiprocessing __main__ re-import (which breaks under embedding
    hosts) and no pickle coupling."""

    def __init__(self, app, bind: str, port: int, n_workers: int, config):
        self.app = app
        self.bind = bind
        self.port = port
        self.n = n_workers
        self.config = config
        base = f"emqx-tpu-fabric-{os.getpid()}-{port}"
        self.uds_path = os.path.join(tempfile.gettempdir(), base + ".sock")
        self._cfg_path = os.path.join(tempfile.gettempdir(), base + ".json")
        self.fabric = WorkerFabric(app, self.uds_path)
        self._procs: List = []

    # supervision: a crashed worker respawns (one-for-one, like the
    # reference's esockd supervisor over connection processes); a worker
    # that dies repeatedly within the window stays down to avoid a
    # crash-loop eating the host
    RESPAWN_WINDOW_S = 60.0
    MAX_RESPAWNS_PER_WINDOW = 5

    def _spawn(self, wid: int):
        import subprocess
        import sys

        return subprocess.Popen(
            [
                sys.executable,
                "-m",
                "emqx_tpu.transport.workers",
                "--wid", str(wid),
                "--bind", self.bind,
                "--port", str(self.port),
                "--uds", self.uds_path,
                "--config", self._cfg_path,
            ],
        )

    async def start(self) -> None:
        import dataclasses
        import json

        await self.fabric.start()
        with open(self._cfg_path, "w") as f:
            json.dump(dataclasses.asdict(self.config), f, default=str)
        for wid in range(self.n):
            self._procs.append(self._spawn(wid))
        self._respawns: List[float] = []
        self._supervisor = asyncio.get_running_loop().create_task(
            self._supervise()
        )

    async def _supervise(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            await asyncio.sleep(2.0)
            for wid, p in enumerate(self._procs):
                if p.poll() is None:
                    continue
                now = loop.time()
                self._respawns = [
                    t for t in self._respawns
                    if now - t < self.RESPAWN_WINDOW_S
                ]
                if len(self._respawns) >= self.MAX_RESPAWNS_PER_WINDOW:
                    self.app.broker.metrics.inc("fabric.worker.crash_loop")
                    continue
                self._respawns.append(now)
                self.app.broker.metrics.inc("fabric.worker.respawns")
                self._procs[wid] = self._spawn(wid)

    def describe(self) -> dict:
        """Listener-style status row (mgmt REST surface)."""
        alive = sum(1 for p in self._procs if p.poll() is None)
        return {
            "id": f"tcp:workers:{self.port}",
            "type": "tcp",
            "name": f"workers:{self.port}",
            "bind": f"{self.bind}:{self.port}",
            "running": alive > 0,
            "workers": self.n,
            "workers_alive": alive,
            "workers_connected": len(self.fabric._writers),
            "max_connections": 0,
            "current_connections": 0,
            "port": self.port,
        }

    async def wait_ready(self, timeout: float = 30.0) -> None:
        """Block until every worker has dialed the fabric."""
        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout
        while len(self.fabric._writers) < self.n:
            if loop.time() > deadline:
                raise TimeoutError(
                    f"{len(self.fabric._writers)}/{self.n} workers ready"
                )
            await asyncio.sleep(0.05)

    async def stop(self) -> None:
        sup = getattr(self, "_supervisor", None)
        if sup is not None:
            sup.cancel()
            try:
                await sup
            except asyncio.CancelledError:
                pass
            self._supervisor = None
        for p in self._procs:
            p.terminate()
        for p in self._procs:
            try:
                p.wait(timeout=5)
            except Exception:
                p.kill()
        self._procs.clear()
        await self.fabric.stop()
        try:
            os.unlink(self._cfg_path)
        except FileNotFoundError:
            pass


def _cli() -> None:
    import argparse
    import json

    from emqx_tpu.config.schema import load_config

    ap = argparse.ArgumentParser(prog="emqx_tpu.transport.workers")
    ap.add_argument("--wid", type=int, required=True)
    ap.add_argument("--bind", required=True)
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--uds", required=True)
    ap.add_argument("--config", required=True)
    a = ap.parse_args()
    with open(a.config) as f:
        c = load_config(json.load(f))
    worker_main(a.wid, a.bind, a.port, a.uds, c)


if __name__ == "__main__":
    _cli()
