"""Multi-process connection workers: the host data plane at scale.

One Python event loop tops out near a thousand MQTT messages/s once it
also pays codec + per-subscriber serialization. The reference never has
this wall — every connection is a BEAM process spread over cores
(emqx_connection.erl:173-176). The equivalent here:

- N WORKER processes accept clients on a shared SO_REUSEPORT port (the
  kernel load-balances accepts). Each runs the full Connection/Channel/
  Session stack — codec, keepalive, QoS state, acks — against a
  `WorkerBroker` proxy instead of the real Broker.
- The ROUTER process keeps the single DeviceRouter, subscription tables,
  retainer, rules, and cluster links. Workers speak the batched fabric
  protocol (transport/fabric.py) to it over a unix socket: SUB/UNSUB
  register proxy subscribers; publishes arrive in batches that ride the
  ingest window onto the TPU kernel; deliveries return batched, one
  record per (message, worker), fanned to sockets worker-side.

Scope: worker listeners are the high-throughput serving path. Authn/
authz/banned guards are rebuilt per worker from the same config, so
admission semantics match. Workers survive a router-process restart:
connections hold, the fabric link re-dials, subscriptions and unacked
publish batches replay (emqx_machine_boot's restart-without-dropping-
esockd layering). Delivery overflow parks per subscriber with a bounded
drop-oldest queue (emqx_mqueue parity at the seam).
"""

from __future__ import annotations

import asyncio
import os
import tempfile
from typing import Dict, List, Optional, Tuple

from emqx_tpu.broker.message import Message
from emqx_tpu.mqtt import packet as pkt
from emqx_tpu.ops import topics as T
from emqx_tpu.transport import fabric as F

# ---------------------------------------------------------------------------
# router side
# ---------------------------------------------------------------------------


class WorkerFabric:
    """Router-process endpoint: UDS server the workers dial into.

    For every worker SUB it registers a proxy subscriber with the real
    Broker whose deliver() enqueues (msg, handle) into that worker's
    outbox; outboxes flush once per loop tick with one DLV record per
    message (per-subscriber QoS handling stays worker-side)."""

    def __init__(self, app, uds_path: str, expected_workers: int = 0):
        self.app = app
        self.broker = app.broker
        self.uds_path = uds_path
        self._server: Optional[asyncio.AbstractServer] = None
        self._writers: Dict[int, asyncio.StreamWriter] = {}
        # boot gate: a RESTARTED router must not dispatch one worker's
        # re-sent publish batches before ANOTHER worker's subscription
        # replay has registered (cross-link ordering — each link's own
        # FIFO already orders its SUBs before its PUBBs). PUBBs buffer
        # until every expected worker reports replay_done, or the
        # force-open timer fires (a worker lost for good must not wedge
        # publishers).
        self.expected_workers = expected_workers
        self._pub_gate_open = expected_workers == 0
        self._boot_ready: set = set()
        self._held_pubs: List = []
        self._gate_timer = None
        # wid -> {(full_sid, filter)}: explicit registry of the broker
        # subscriptions each worker proxies (worker-death cleanup walks
        # this, never a sid-prefix match that could catch an in-process
        # client whose id happens to start with "w{wid}|")
        self._fabric_subs: Dict[int, set] = {}
        # wid -> [(msg, [handles])]; one record per message per tick
        self._outbox: Dict[int, List] = {}
        self._outbox_last: Dict[int, Tuple[int, List[int]]] = {}
        self._flush_scheduled = False
        # congestion parking: wid -> {handle -> deque[msg|raw bytes]}
        # + drain tasks
        self._parked: Dict[int, Dict[int, object]] = {}
        self._drainers: Dict[int, asyncio.Task] = {}
        # QoS0 fast lane: wid -> [(frame_bytes, [handles])]
        self._raw_outbox: Dict[int, List] = {}
        self._raw_last: Dict[int, Tuple] = {}
        # emqx_cm across workers: cid -> owning wid (live channels);
        # takes pending the owner's state reply, keyed by a ROUTER-
        # generated token (worker request ids are only unique per
        # worker): token -> (owner_wid, cid, reply_fn); sessions
        # mid-resume (snapshot shipped, handoff bankers still live)
        self._owner: Dict[str, int] = {}
        # negotiated session expiry per live worker client (sent by the
        # worker after CONNACK): worker-crash parking keys on it
        self._owner_expiry: Dict[str, float] = {}
        self._take_pending: Dict[int, Tuple[int, str, object]] = {}
        self._next_take = 1
        self._resuming: Dict[str, dict] = {}
        self._tasks: set = set()

    async def start(self) -> None:
        try:
            os.unlink(self.uds_path)
        except FileNotFoundError:
            pass
        self._server = await asyncio.start_unix_server(
            self._on_worker, path=self.uds_path
        )
        if not self._pub_gate_open:
            self._gate_timer = asyncio.get_running_loop().call_later(
                10.0, self._open_pub_gate
            )
        # the router's own CM consults us at open_session so a client
        # live on a WORKER reconnecting via an in-process listener
        # (ws/ssl) still takes its session over (node-wide emqx_cm)
        cm = getattr(self.app, "cm", None)
        if cm is not None and hasattr(cm, "fabrics") and \
                self not in cm.fabrics:
            cm.fabrics.append(self)

    async def stop(self) -> None:
        cm = getattr(self.app, "cm", None)
        if cm is not None and hasattr(cm, "fabrics") and \
                self in cm.fabrics:
            cm.fabrics.remove(self)
        if self._server is not None:
            self._server.close()
        for t in list(self._tasks):
            t.cancel()
        for d in list(self._drainers.values()):
            d.cancel()
        self._drainers.clear()
        self._parked.clear()
        if self._server is not None:
            await self._server.wait_closed()
            self._server = None
        try:
            os.unlink(self.uds_path)
        except FileNotFoundError:
            pass

    async def _on_worker(self, reader, writer) -> None:
        task = asyncio.current_task()
        self._tasks.add(task)
        wid = -1
        try:
            ftype, body = await F.read_frame(reader)
            if ftype != F.T_HELLO:
                return
            wid = int.from_bytes(body[:2], "little")
            self._writers[wid] = writer
            while True:
                ftype, body = await F.read_frame(reader)
                if ftype == F.T_SUB:
                    h = self._on_sub(wid, body)
                    # confirm AFTER registration + retained enqueue:
                    # the worker releases the client's SUBACK on this
                    if not writer.is_closing():
                        writer.write(F.pack_json(F.T_SUB_ACK, {"h": h}))
                elif ftype == F.T_UNSUB:
                    self._on_unsub(wid, body)
                elif ftype in (F.T_PUBB, F.T_PUBB_S):
                    if self._pub_gate_open:
                        if ftype == F.T_PUBB_S:
                            await self._on_pub_slab(writer, body)
                        else:
                            await self._on_pub_batch(writer, body)
                    else:
                        self._held_pubs.append((writer, ftype, body))
                elif ftype == F.T_SESS:
                    import json

                    self._on_sess(wid, writer, json.loads(body))
        except (
            asyncio.IncompleteReadError,
            ConnectionResetError,
            asyncio.CancelledError,
        ):
            pass
        finally:
            self._tasks.discard(task)
            if wid >= 0:
                self._writers.pop(wid, None)
                self._outbox.pop(wid, None)
                self._raw_outbox.pop(wid, None)
                self._parked.pop(wid, None)
                d = self._drainers.pop(wid, None)
                if d is not None:
                    d.cancel()
                self._drop_worker_subs(wid)
                for cid in [
                    c for c, w in self._owner.items() if w == wid
                ]:
                    self._owner.pop(cid, None)
                    self._owner_expiry.pop(cid, None)
                # takes waiting on this (now dead) owner fail fast
                # instead of leaking / stalling requesters 30s
                for tk in [
                    t for t, (ow, _c, _r) in self._take_pending.items()
                    if ow == wid
                ]:
                    _ow, _cid, reply = self._take_pending.pop(tk)
                    reply(None, False)
            writer.close()

    # -- subscribe side ---------------------------------------------------
    def _sid(self, wid: int, sid: str) -> str:
        return f"w{wid}|{sid}"

    def _on_sub(self, wid: int, body: bytes) -> int:
        """Register a worker subscription; returns its handle (the read
        loop confirms it back as SUB_ACK after this returns)."""
        import json

        d = json.loads(body)
        handle = int(d["h"])
        opts = pkt.SubOpts(
            qos=int(d.get("qos", 0)),
            no_local=bool(d.get("nl", False)),
            retain_as_published=bool(d.get("rap", False)),
            retain_handling=int(d.get("rh", 0)),
        )
        filter_ = d["f"]
        _group, real = T.parse_share(filter_)
        # rh=1 semantics key on THIS CLIENT's prior subscription, which
        # only the worker-side session knows (channel.py sets
        # opts._existing); broker-wide existence would suppress replay
        # for every later client
        existing = bool(d.get("ex", False))
        # QoS0 fast lane ("fl": protocol version): the router ships a
        # pre-serialized PUBLISH the worker writes straight to the
        # subscriber socket. Retained replays stay on the message path
        # (their Message objects are store-owned; see channel._fb note).
        fl = d.get("fl")
        if fl:
            rap = bool(d.get("rap", False))

            def deliver(msg, _opts, _wid=wid, _h=handle, _v=int(fl),
                        _rap=rap):
                if msg.headers.get("retained"):
                    self.enqueue(_wid, _h, msg)
                else:
                    self.enqueue_raw(_wid, _h, _v, _rap, msg)
        else:

            def deliver(msg, _opts, _wid=wid, _h=handle):
                self.enqueue(_wid, _h, msg)

        full_sid = self._sid(wid, d["sid"])
        self.broker.subscribe(full_sid, d.get("cid", ""), filter_, opts,
                              deliver)
        self._fabric_subs.setdefault(wid, set()).add((full_sid, filter_))
        # retained replay (the worker-side channel hooks have no retainer;
        # semantics per emqx_retainer: never for $share, rh=2 never,
        # rh=1 only for fresh subscriptions)
        ret = getattr(self.app, "retainer", None)
        if (
            ret is not None
            and ret.enabled
            and not d.get("nr")  # link-reconnect replay: never retained
            and _group is None
            and opts.retain_handling != 2
            and not (opts.retain_handling == 1 and existing)
        ):
            for m in ret.match(real):
                import copy

                mm = copy.copy(m)
                mm.headers = dict(m.headers, retained=True)
                self.enqueue(wid, handle, mm)
        return handle

    def _on_unsub(self, wid: int, body: bytes) -> None:
        import json

        d = json.loads(body)
        full_sid = self._sid(wid, d["sid"])
        self.broker.unsubscribe(full_sid, d["f"])
        subs = self._fabric_subs.get(wid)
        if subs is not None:
            subs.discard((full_sid, d["f"]))

    def _drop_worker_subs(self, wid: int) -> None:
        """Worker died: every subscription it proxied is gone — but
        sessions with a positive expiry are RECONSTRUCTED and parked
        first (subscriptions + future offline banking survive the
        crash; in-flight/queued state died with the worker process).
        The reference's node keeps sessions across connection-process
        crashes the same way (the channel process dies, emqx_cm keeps
        the session)."""
        dropped = self._fabric_subs.pop(wid, set())
        # (cid, filter) -> opts, harvested before the registry drops
        crash_park: Dict[str, Dict] = {}
        for sid, f in dropped:
            cid = sid.split("|", 1)[1] if "|" in sid else sid
            expiry = self._owner_expiry.get(cid, 0)
            if expiry > 0:
                _g, real = T.parse_share(f)
                sub = self.broker._subs.get(real, {}).get(sid)
                if sub is not None:
                    crash_park.setdefault(cid, {})[f] = sub.opts
            self.broker.unsubscribe(sid, f)
        cm = getattr(self.app, "cm", None)
        if cm is None:
            return
        from emqx_tpu.broker.persistent_session import (
            make_detached_deliverer,
        )
        from emqx_tpu.broker.session import Session, SessionConfig

        import time as _t

        for cid, subs in crash_park.items():
            if cid in cm._detached or cm.get_channel(cid) is not None:
                continue
            if self._owner.get(cid) not in (None, wid):
                # already reconnected onto ANOTHER worker before this
                # cleanup ran: the live session wins, nothing to park
                continue
            scfg = getattr(
                getattr(self.app, "config", None), "session", None
            )
            sess = Session(cid, scfg or SessionConfig())
            expiry = self._owner_expiry.get(cid, 0)
            sess.config.expiry_interval = expiry
            sess.subscriptions = dict(subs)
            deliver = make_detached_deliverer(sess, None, cid)
            for f, opts in subs.items():
                self.broker.subscribe(cid, cid, f, opts, deliver)
            # monotonic like cm.on_channel_closed: detach deadlines must
            # survive wall-clock steps
            cm._detached[cid] = (sess, _t.monotonic() + expiry)
            self.broker.hooks.run("session.detached", cid)
            self.broker.metrics.inc("fabric.sess.crash_parked")

    # -- session ops (emqx_cm parity across workers) ----------------------
    # The router process is the node-level session registry: a client
    # reconnecting onto ANY worker (or an in-process listener) finds its
    # session — takeover of live channels, resume of parked ones, and
    # persistent parking into the app CM's detached store (WAL-backed
    # when session persistence is enabled). Reference:
    # emqx_cm.erl:245-273 open_session, :346-366 takeover_session.

    def _open_pub_gate(self) -> None:
        if self._gate_timer is not None:
            self._gate_timer.cancel()
            self._gate_timer = None
        if self._pub_gate_open:
            return
        if self._held_pubs:
            t = asyncio.get_running_loop().create_task(self._drain_held())
            self._tasks.add(t)
            t.add_done_callback(self._tasks.discard)
        else:
            self._pub_gate_open = True

    async def _drain_held(self) -> None:
        # the gate stays CLOSED while draining: new PUBBs keep appending
        # behind the held ones so per-link order is preserved
        try:
            while self._held_pubs:
                writer, ftype, body = self._held_pubs.pop(0)
                if not writer.is_closing():
                    if ftype == F.T_PUBB_S:
                        await self._on_pub_slab(writer, body)
                    else:
                        await self._on_pub_batch(writer, body)
        finally:
            self._pub_gate_open = True

    def _sess_reply(self, writer, r: int, sess_json, present: bool) -> None:
        if writer is not None and not writer.is_closing():
            writer.write(F.pack_json(F.T_SESS, {
                "op": "open_ack", "r": r, "sess": sess_json,
                "present": bool(present),
            }))

    def _on_sess(self, wid: int, writer, d: dict) -> None:
        op = d.get("op")
        if op == "open":
            self._sess_open(wid, writer, d)
        elif op == "state":
            self._sess_state(d)
        elif op == "park":
            self._sess_park(wid, d)
        elif op == "resume_done":
            self._sess_resume_done(wid, d["cid"])
        elif op == "replay_done":
            # this worker's boot/reconnect flight (SUB replays etc.) is
            # fully on the wire; once every expected worker reports in,
            # held publish batches flow (cross-link ordering gate)
            self._boot_ready.add(wid)
            if (
                not self._pub_gate_open
                and len(self._boot_ready) >= self.expected_workers
            ):
                self._open_pub_gate()
        elif op == "opened":
            # post-CONNACK: the session's negotiated expiry is final
            self._owner[d["cid"]] = wid
            self._owner_expiry[d["cid"]] = float(d.get("expiry", 0))
        elif op == "claim":
            # link-reconnect replay: the worker re-announces its live
            # channels (the drop-path cleared their owner entries)
            self._owner[d["cid"]] = wid
        elif op == "closed":
            if self._owner.get(d["cid"]) == wid:
                self._owner.pop(d["cid"], None)
                self._owner_expiry.pop(d["cid"], None)

    def _sess_open(self, wid: int, writer, d: dict) -> None:
        from emqx_tpu.storage.codec import session_to_json

        cid, clean, r = d["cid"], bool(d.get("clean")), int(d["r"])
        self._gc_resuming()
        cm = getattr(self.app, "cm", None)
        # live on a worker (possibly this one — the take round trip is
        # uniform): hand over or kill the old channel there
        own = self._owner.get(cid)
        if own is not None and own in self._writers:
            ow = self._writers[own]
            if clean:
                ow.write(F.pack_json(F.T_SESS, {"op": "discard",
                                                "cid": cid}))
                self._drop_parked(cid)
                self._owner[cid] = wid
                self._sess_reply(writer, r, None, False)
            else:
                def reply(sj, present, _w=writer, _r=r):
                    self._sess_reply(_w, _r, sj, present)

                self._begin_take(own, cid, reply)
                self._owner[cid] = wid
            return
        # live on an in-process listener of the router
        old = cm.get_channel(cid) if cm is not None else None
        if old is not None:
            cm._channels.pop(cid, None)
            sess = old.kick("discarded" if clean else "takenover")
            self.broker.hooks.run(
                "session.discarded" if clean else "session.takenover", cid
            )
            sj = None
            if sess is not None:
                if not clean:
                    sj = session_to_json(sess)
                self.broker.drop_session_subs(
                    cid, list(sess.subscriptions)
                )
            if clean:
                self._drop_parked(cid)
            self._owner[cid] = wid
            self._sess_reply(writer, r, sj, sj is not None)
            return
        if clean:
            self._drop_parked(cid)
            self._owner[cid] = wid
            self._sess_reply(writer, r, None, False)
            return
        # parked in the router CM's detached store (covers sessions
        # parked by ANY worker, in-process listeners, and
        # persistence-restored ones)
        ent = cm._detached.pop(cid, None) if cm is not None else None
        if ent is not None:
            sess, _deadline = ent
            sj = session_to_json(sess)
            # bankers stay live until resume_done: messages arriving
            # during the handoff keep banking into this Session object
            self._resuming[cid] = {
                "sess": sess,
                "n0": len(sess.mqueue),
                "wid": wid,
                "ts": asyncio.get_running_loop().time(),
            }
            self.broker.hooks.run("session.resumed", cid)
            self.broker.metrics.inc("fabric.sess.resumes")
            self._owner[cid] = wid
            self._sess_reply(writer, r, sj, True)
            return
        self._owner[cid] = wid
        self._sess_reply(writer, r, None, False)

    def _begin_take(self, owner_wid: int, cid: str, reply) -> None:
        """Send 'take' to the live owner; `reply(sess_json, present)`
        fires on its state reply (or on owner death)."""
        tk = self._next_take
        self._next_take += 1
        self._take_pending[tk] = (owner_wid, cid, reply)
        self._writers[owner_wid].write(
            F.pack_json(F.T_SESS, {"op": "take", "cid": cid, "r": tk})
        )

    def _sess_state(self, d: dict) -> None:
        """A worker handed over a live session after 'take'."""
        ent = self._take_pending.pop(int(d["r"]), None)
        if ent is None:
            return
        _owner_wid, _cid, reply = ent
        self.broker.metrics.inc("fabric.sess.takeovers")
        reply(d.get("sess"), d.get("sess") is not None)

    def _sess_park(self, wid: int, d: dict) -> None:
        """Worker client disconnected with expiry > 0: the session parks
        in the ROUTER's detached store — same store as in-process
        listeners, so persistence (WAL + snapshot + restore) and expiry
        sweep apply unchanged, and any future connect finds it."""
        from emqx_tpu.broker.persistent_session import (
            make_detached_deliverer,
        )
        from emqx_tpu.storage.codec import session_from_json

        cid = d["cid"]
        if self._owner.get(cid) == wid:
            self._owner.pop(cid, None)
        self._owner_expiry.pop(cid, None)
        cm = getattr(self.app, "cm", None)
        if cm is None:
            return
        scfg = getattr(
            getattr(self.app, "config", None), "session", None
        )
        from emqx_tpu.broker.session import SessionConfig

        import time as _t

        sess = session_from_json(d["sess"], scfg or SessionConfig())
        deadline = _t.monotonic() + float(d.get("expiry", 0))
        # plain banker now; the persistence hook (if attached) replaces
        # it under the same (sid, filter) key with the WAL-backed one
        deliver = make_detached_deliverer(sess, None, cid)
        for f, opts in sess.subscriptions.items():
            self.broker.subscribe(cid, cid, f, opts, deliver)
        cm._detached[cid] = (sess, deadline)
        self.broker.hooks.run("session.detached", cid)

    def _sess_resume_done(self, wid: int, cid: str) -> None:
        """The new worker installed the session and its SUB frames are
        registered (they precede resume_done on the FIFO link): drop the
        handoff bankers and forward anything banked after the snapshot."""
        ent = self._resuming.pop(cid, None)
        if ent is None:
            return
        sess = ent["sess"]
        self.broker.drop_session_subs(cid, list(sess.subscriptions))
        extras = list(sess.mqueue.peek_all())[ent["n0"]:]
        if not extras:
            return
        full_sid = self._sid(wid, cid)
        for sub_sid, f in list(self._fabric_subs.get(wid, ())):
            if sub_sid != full_sid:
                continue
            _group, real = T.parse_share(f)
            entry = self.broker._subs.get(real, {})
            sub = entry.get(full_sid)
            if sub is None:
                continue
            for m in extras:
                if T.match(m.topic, real):
                    try:
                        sub.deliver(m, sub.opts)
                    except Exception:
                        self.broker.metrics.inc("delivery.errors")

    # -- in-process takeover bridge (ChannelManager.fabrics) --------------
    def owns(self, cid: str) -> bool:
        """True when a live WORKER channel holds this client id."""
        return self._owner.get(cid) in self._writers

    def take_session(self, cid: str, clean: bool) -> "asyncio.Future":
        """Take (or discard) a live worker session on behalf of an
        in-process listener's CONNECT. Resolves with the serialized
        session json (None for clean/absent/dead-owner)."""
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        own = self._owner.get(cid)
        w = self._writers.get(own)
        if w is None or w.is_closing():
            fut.set_result(None)
            return fut
        if clean:
            w.write(F.pack_json(F.T_SESS, {"op": "discard", "cid": cid}))
            self._owner.pop(cid, None)
            fut.set_result(None)
            return fut

        def reply(sj, _present):
            if not fut.done():
                fut.set_result(sj)

        self._begin_take(own, cid, reply)
        self._owner.pop(cid, None)
        # safety: a wedged worker must not stall the CONNECT forever
        loop.call_later(
            10.0, lambda: fut.done() or fut.set_result(None)
        )
        return fut

    def _drop_parked(self, cid: str) -> None:
        cm = getattr(self.app, "cm", None)
        if cm is not None and cid in cm._detached:
            cm._drop_detached(cid)

    RESUME_GC_S = 120.0

    def _gc_resuming(self) -> None:
        """A resume the worker never completed (client vanished between
        CONNECT and install): re-park so the session isn't leaked."""
        now = asyncio.get_running_loop().time()
        cm = getattr(self.app, "cm", None)
        for cid in [
            c for c, e in self._resuming.items()
            if now - e["ts"] > self.RESUME_GC_S
        ]:
            ent = self._resuming.pop(cid)
            if cm is not None:
                import time as _t

                sess = ent["sess"]
                cm._detached[cid] = (
                    sess, _t.monotonic() + sess.config.expiry_interval
                )

    # -- publish side -----------------------------------------------------
    async def _on_pub_batch(self, writer, body: bytes) -> None:
        # `writer` is the CONNECTION's stream, not a wid lookup: a stale
        # ack task must die with its (closed) connection, never resolve a
        # respawned worker's identically-numbered batch
        seq, records = F.unpack_pub_batch(body)
        results = []
        # enqueue INLINE (per-publisher ordering is an MQTT contract);
        # only the confirm-wait runs as a task so the next frame parses
        # while this batch's ingest window flushes
        for topic, payload, qos, retain, dup, client, props in records:
            msg = Message(
                topic=topic,
                payload=payload,
                qos=qos,
                retain=retain,
                dup=dup,
                from_client=client,
                properties=props or {},
            )
            results.append(await self.broker.apublish_enqueue(msg))
        if not any(r[2] > 0 for r in records):
            return  # pure-QoS0 batch: the worker holds no PUBACKs on it
        t = asyncio.get_running_loop().create_task(
            self._ack_pub_batch(writer, seq, results)
        )
        self._tasks.add(t)
        t.add_done_callback(self._tasks.discard)

    async def _on_pub_slab(self, writer, body: bytes) -> None:
        """Slab PUBB (T_PUBB_S): ONE vectorized header scan recovers
        every record; messages enter the ingest window as SlabMessages —
        topic bytes feed the tokenizer straight from this frame body
        (ops/tokenizer TopicRef gather) and payload copies defer until a
        subscriber needs them (zero-copy ingest, docs/protocol_plane.md)."""
        from emqx_tpu.broker.message import SlabMessage

        slab = F.unpack_pub_slab(body)
        met = self.broker.metrics
        met.inc("fabric.slab.pub.frames")
        if slab.n:
            met.inc("fabric.slab.pub.records", slab.n)
            met.inc("ingest.zerocopy.records", slab.n)
            met.inc(
                "ingest.zerocopy.deferred.bytes",
                int(slab.t_len.sum() + slab.p_len.sum()),
            )
        flags = slab.flags
        qos_l = (flags & 3).tolist()
        retain_l = (flags & 4).astype(bool).tolist()
        dup_l = (flags & 8).astype(bool).tolist()
        props_l = (flags & 0x10).astype(bool).tolist()
        results = []
        # enqueue INLINE (per-publisher ordering), confirm-wait as a task
        # — same contract as the per-record path
        for i in range(slab.n):
            msg = SlabMessage(
                slab, i, qos=qos_l[i], retain=retain_l[i], dup=dup_l[i],
                from_client=slab.client(i),
                properties=slab.props(i) if props_l[i] else None,
            )
            results.append(await self.broker.apublish_enqueue(msg))
        if not any(qos_l):
            return  # pure-QoS0 batch: the worker holds no PUBACKs on it
        t = asyncio.get_running_loop().create_task(
            self._ack_pub_batch(writer, slab.seq, results)
        )
        self._tasks.add(t)
        t.add_done_callback(self._tasks.discard)

    async def _ack_pub_batch(self, writer, seq: int, results) -> None:
        """Confirm AFTER every message dispatched/banked (ingest futures
        resolve at the batch-window flush) with per-message delivery
        counts — the worker holds client PUBACKs on this."""
        counts = []
        for r in results:
            if isinstance(r, int):
                counts.append(r)
            else:
                try:
                    counts.append(int(await r))
                except Exception:
                    counts.append(0)
        if not writer.is_closing():
            try:
                writer.write(F.pack_pub_ack(seq, counts))
            except Exception:
                self.broker.metrics.inc("fabric.flush.errors")

    # -- delivery side ----------------------------------------------------
    def enqueue(self, wid: int, handle: int, msg) -> None:
        if wid not in self._writers:
            return
        box = self._outbox.setdefault(wid, [])
        last = self._outbox_last.get(wid)
        if last is not None and last[0] == id(msg) and box:
            last[1].append(handle)
        else:
            handles = [handle]
            box.append((msg, handles))
            self._outbox_last[wid] = (id(msg), handles)
        if not self._flush_scheduled:
            self._flush_scheduled = True
            asyncio.get_running_loop().call_soon(self._flush)

    def enqueue_raw(self, wid: int, handle: int, version: int, rap: bool,
                    msg) -> None:
        """QoS0 fast lane: serialize the PUBLISH once per (version,
        retain, topic) — the cache rides the Message — and queue the
        bytes for direct socket writes worker-side. Congested workers
        fall back to the message path (parked per subscriber there)."""
        if wid not in self._writers:
            return
        if wid in self._parked:
            return self.enqueue(wid, handle, msg)
        retain = bool(msg.retain and rap)
        fb = getattr(msg, "_fb", None)
        if fb is None:
            fb = {}
            msg._fb = fb
        # the (version, retain, topic) key is SHARED with the in-process
        # channel's QoS0 frame cache — safe because both producers emit
        # identical bytes: v5 frames here carry the full encoded
        # properties, exactly like channel.handle_deliver's serialize
        key = (version, retain, msg.topic)
        buf = fb.get(key)
        if buf is None:
            from emqx_tpu.mqtt import codec_native as _nc

            v5 = version == pkt.MQTT_V5
            if _nc.serialize_publish is not None:
                from emqx_tpu.mqtt.frame import encode_properties

                props = encode_properties(msg.properties) if v5 else b""
                buf = _nc.serialize_publish(
                    msg.topic.encode(), msg.payload or b"", 0,
                    1 if retain else 0, 0, 0, props, 1 if v5 else 0,
                )
            else:
                from emqx_tpu.mqtt.frame import serialize

                buf = serialize(
                    pkt.Publish(topic=msg.topic,
                                payload=msg.payload or b"",
                                qos=0, retain=retain, packet_id=None,
                                properties=dict(msg.properties)),
                    version,
                )
            fb[key] = buf
        box = self._raw_outbox.setdefault(wid, [])
        last = self._raw_last.get(wid)
        if last is not None and last[0] is buf and box:
            last[1].append(handle)
        else:
            handles = [handle]
            box.append((buf, handles))
            self._raw_last[wid] = (buf, handles)
        if not self._flush_scheduled:
            self._flush_scheduled = True
            asyncio.get_running_loop().call_soon(self._flush)

    # a worker that stops reading its UDS must not grow this process's
    # write buffer without bound. Past the high-water mark, deliveries
    # PARK in per-subscriber bounded queues (mqueue-overflow parity at
    # the fabric seam, emqx_mqueue.erl: per-session bound + drop-oldest)
    # and a drain task replays them in order once the pipe recovers —
    # one slow worker degrades only its over-quota subscribers, never
    # whole delivery batches
    WRITE_HIGH_WATER = 32 * 1024 * 1024
    PARK_CAP = 1000  # per subscriber handle (SessionConfig.max_mqueue)

    def _flush(self) -> None:
        self._flush_scheduled = False
        self._outbox_last.clear()
        self._raw_last.clear()
        boxes, self._outbox = self._outbox, {}
        raws, self._raw_outbox = self._raw_outbox, {}
        for wid in boxes.keys() | raws.keys():
            records = boxes.get(wid, ())
            raw_records = raws.get(wid, ())
            w = self._writers.get(wid)
            if w is None or w.is_closing():
                continue
            try:
                if (
                    wid in self._parked
                    or w.transport.get_write_buffer_size()
                    > self.WRITE_HIGH_WATER
                ):
                    # congested (or actively draining a prior backlog —
                    # direct writes would reorder per-subscriber flows):
                    # park per handle, bounded, dropping the OLDEST.
                    # Raw-lane bufs park as bufs (replayed verbatim).
                    if records:
                        self._park(wid, records)
                    if raw_records:
                        self._park(wid, raw_records)
                    continue
                if records:
                    if F.SLAB_WIRE:
                        nf = 0
                        for frame in F.pack_dlv_slabs(records):
                            w.write(frame)
                            nf += 1
                        self.broker.metrics.inc(
                            "fabric.slab.dlv.frames", nf
                        )
                        self.broker.metrics.inc(
                            "fabric.slab.dlv.records", len(records)
                        )
                    else:
                        for frame in F.pack_dlv_batches(records):
                            w.write(frame)
                if raw_records:
                    for frame in F.pack_raw_batches(raw_records):
                        w.write(frame)
                    self.broker.metrics.inc(
                        "fabric.raw.records", len(raw_records)
                    )
            except Exception:
                # one worker's dead pipe (or a malformed record) must not
                # lose the OTHER workers' deliveries in this tick
                self.broker.metrics.inc("fabric.flush.errors")

    def _park(self, wid: int, records) -> None:
        import collections

        queues = self._parked.setdefault(wid, {})
        for msg, handles in records:
            # slab-escape site: parked deliveries outlive their fabric
            # read buffer (raw-lane bufs park as plain bytes)
            ob = getattr(msg, "own_buffers", None)
            if ob is not None:
                ob()
            for h in handles:
                q = queues.get(h)
                if q is None:
                    q = queues[h] = collections.deque()
                if len(q) >= self.PARK_CAP:
                    q.popleft()  # drop-oldest (emqx_mqueue default)
                    self.broker.metrics.inc("fabric.parked.dropped")
                q.append(msg)
        if wid not in self._drainers:
            t = asyncio.get_running_loop().create_task(
                self._drain_parked(wid)
            )
            self._drainers[wid] = t
            t.add_done_callback(
                lambda _t, _w=wid: self._drainers.pop(_w, None)
            )

    DRAIN_CHUNK = 256  # records per drain write burst

    async def _drain_parked(self, wid: int) -> None:
        """Replay a congested worker's parked deliveries in per-subscriber
        order once its pipe drains below the transport's write high-water
        mark."""
        while True:
            w = self._writers.get(wid)
            queues = self._parked.get(wid)
            if queues is None or not queues:
                self._parked.pop(wid, None)
                return
            if w is None or w.is_closing():
                # worker died: its subscriptions are being dropped; the
                # parked backlog dies with them
                self._parked.pop(wid, None)
                return
            try:
                await w.drain()
            except (ConnectionResetError, BrokenPipeError):
                self._parked.pop(wid, None)
                return
            if w.transport.get_write_buffer_size() > self.WRITE_HIGH_WATER:
                # still over OUR high-water (transport limits are lower):
                # yield and re-check rather than spin
                await asyncio.sleep(0.01)
                continue
            n = 0
            try:
                for h in list(queues):
                    q = queues.get(h)
                    run: list = []
                    while q and n < self.DRAIN_CHUNK:
                        run.append(q.popleft())
                        n += 1
                    if q is not None and not q:
                        del queues[h]
                    # a subscriber's queue may interleave Message
                    # records (DLV path) and raw-lane bufs: emit
                    # same-type runs in pop order so per-subscriber
                    # ordering holds
                    i = 0
                    while i < len(run):
                        j = i
                        is_raw = isinstance(run[i], (bytes, bytearray))
                        while j < len(run) and isinstance(
                            run[j], (bytes, bytearray)
                        ) == is_raw:
                            j += 1
                        seg = [(x, [h]) for x in run[i:j]]
                        packer = (
                            F.pack_raw_batches if is_raw
                            else (F.pack_dlv_slabs if F.SLAB_WIRE
                                  else F.pack_dlv_batches)
                        )
                        for frame in packer(seg):
                            w.write(frame)
                        i = j
                    if n >= self.DRAIN_CHUNK:
                        break
                if n:
                    self.broker.metrics.inc("fabric.parked.replayed", n)
            except Exception:
                self.broker.metrics.inc("fabric.flush.errors")
                self._parked.pop(wid, None)
                return


# ---------------------------------------------------------------------------
# worker side
# ---------------------------------------------------------------------------


class WorkerBroker:
    """Broker facade inside a worker: same surface Channel/CM consume
    (subscribe/unsubscribe/apublish/metrics/hooks), forwarding over the
    fabric link. Deliveries come back by subscription handle."""

    def __init__(self, hooks, metrics):
        self.hooks = hooks
        self.metrics = metrics
        self.cm = None  # WorkerChannelManager, set after construction
        self._link_w: Optional[asyncio.StreamWriter] = None
        self._subs: Dict[int, Tuple] = {}  # handle -> (deliver, opts)
        # QoS0 fast lane: handle -> sink with send_bytes (raw writes)
        self._raw_sinks: Dict[int, object] = {}
        self._byname: Dict[Tuple[str, str], int] = {}
        self._next_handle = 1
        # session RPC: reqid -> (future, safety timer)
        self._sess_reqs: Dict[int, Tuple["asyncio.Future", object]] = {}
        self._next_sess_req = 1
        # publish buffer entries: (msg, future) — the future resolves
        # with the message's delivery count when the router acks the
        # batch (PUBB_ACK), which is when the channel releases the
        # client's PUBACK
        self._pub_buf: List[Tuple[Message, Optional["asyncio.Future"]]] = []
        self._pub_scheduled = False
        self._next_seq = 1
        # seq -> (futures, safety TimerHandle cancelled on ack, msgs —
        # kept for re-send across a router-restart link blip)
        self._inflight: Dict[int, Tuple[list, object, list]] = {}
        # handle -> (future resolved by the router's SUB_ACK, safety
        # timer cancelled on ack); the channel holds the client's SUBACK
        # on the future: SUBACK == routable
        self._sub_acks: Dict[int, Tuple["asyncio.Future", object]] = {}
        self.ACK_TIMEOUT_S = 60.0

    # fabric glue
    def attach_link(self, writer) -> None:
        self._link_w = writer

    def detach_link(self) -> None:
        """Link lost (router blip): hold all local state; _send becomes a
        no-op until reattach_link replays it."""
        self._link_w = None

    def reattach_link(self, writer) -> None:
        """Re-dialed after a router restart: replay every live
        subscription (the new router process has empty tables) and
        re-send unacked QoS>0 publish batches (at-least-once across the
        blip; the 60s ack timer keeps bounding each batch)."""
        self._link_w = writer
        for (sid, filter_), h in list(self._byname.items()):
            ent = self._subs.get(h)
            if ent is None:
                continue
            _deliver, opts = ent
            ent_raw = self._raw_sinks.get(h)
            fl = ent_raw[1] if ent_raw else 0
            self._send(
                F.pack_json(
                    F.T_SUB,
                    {
                        "h": h,
                        "sid": sid,
                        "cid": sid,
                        "f": filter_,
                        "qos": opts.qos,
                        "nl": opts.no_local,
                        "rap": opts.retain_as_published,
                        "rh": opts.retain_handling,
                        "ex": True,
                        # replay of an ESTABLISHED subscription: never
                        # re-deliver retained messages the client already
                        # got at its real SUBSCRIBE
                        "nr": True,
                        **({"fl": fl} if fl else {}),
                    },
                )
            )
        for seq in sorted(self._inflight):
            futs, _timer, msgs = self._inflight[seq]
            if any(f is not None and not f.done() for f in futs):
                self._send(self._pack_pub(msgs, seq))
        # re-announce live channels: the router's drop-path cleared
        # their session-owner entries when the link fell
        if self.cm is not None:
            for cid in list(self.cm._channels):
                self._send(
                    F.pack_json(F.T_SESS, {"op": "claim", "cid": cid})
                )

    def _send(self, data: bytes) -> None:
        if self._link_w is not None and not self._link_w.is_closing():
            self._link_w.write(data)

    @staticmethod
    def _pack_pub(msgs, seq: int) -> bytes:
        """Publish batches ride the slab wire (one header table + joined
        regions; T_PUBB_S) unless the env kill-switch forces legacy."""
        if F.SLAB_WIRE:
            return F.pack_pub_slab(msgs, seq)
        return F.pack_pub_batch(msgs, seq)

    # session RPC ---------------------------------------------------------
    SESS_TIMEOUT_S = 30.0

    def sess_open(self, cid: str, clean: bool) -> "asyncio.Future":
        """Ask the router to resolve this client's session (takeover /
        resume / fresh) — emqx_cm.open_session, brokered node-wide.
        Resolves to (sess_json | None, present)."""
        loop = asyncio.get_running_loop()
        r = self._next_sess_req
        self._next_sess_req += 1
        fut = loop.create_future()
        timer = loop.call_later(
            self.SESS_TIMEOUT_S,
            lambda: fut.done() or fut.set_result((None, False)),
        )
        self._sess_reqs[r] = (fut, timer)
        self._send(F.pack_json(F.T_SESS, {
            "op": "open", "r": r, "cid": cid, "clean": bool(clean),
        }))
        return fut

    def sess_opened(self, cid: str, expiry: float) -> None:
        """Post-CONNACK: tell the router this session's negotiated
        expiry (worker-crash parking keys on it)."""
        self._send(F.pack_json(F.T_SESS, {
            "op": "opened", "cid": cid, "expiry": float(expiry),
        }))

    def sess_park(self, cid: str, sess_json, expiry: float) -> None:
        self._send(F.pack_json(F.T_SESS, {
            "op": "park", "cid": cid, "sess": sess_json,
            "expiry": float(expiry),
        }))

    def sess_resume_done(self, cid: str) -> None:
        self._send(F.pack_json(F.T_SESS, {"op": "resume_done",
                                          "cid": cid}))

    def sess_closed(self, cid: str) -> None:
        self._send(F.pack_json(F.T_SESS, {"op": "closed", "cid": cid}))

    def on_sess(self, d: dict) -> None:
        """Inbound session op from the router (pump_link)."""
        from emqx_tpu.storage.codec import session_to_json

        op = d.get("op")
        if op == "open_ack":
            ent = self._sess_reqs.pop(int(d["r"]), None)
            if ent is None:
                return
            fut, timer = ent
            timer.cancel()
            if not fut.done():
                fut.set_result((d.get("sess"), bool(d.get("present"))))
        elif op in ("take", "discard") and self.cm is not None:
            cid = d["cid"]
            ch = self.cm._channels.pop(cid, None)
            det = self.cm._detached.pop(cid, None)
            sj = None
            if ch is not None:
                sess = ch.kick(
                    "takenover" if op == "take" else "discarded"
                )
                self.hooks.run(
                    "session.takenover" if op == "take"
                    else "session.discarded",
                    cid,
                )
                if sess is not None:
                    if op == "take":
                        sj = session_to_json(sess)
                    self.drop_session_subs(
                        cid, list(sess.subscriptions)
                    )
            elif det is not None:
                sess, _dl = det
                if op == "take":
                    sj = session_to_json(sess)
                self.drop_session_subs(cid, list(sess.subscriptions))
            if op == "take":
                self._send(F.pack_json(F.T_SESS, {
                    "op": "state", "r": int(d["r"]), "cid": cid,
                    "sess": sj,
                }))

    # Broker surface ------------------------------------------------------
    # channels probe this before offering a raw-lane sink (the
    # in-process Broker has no fabric seam to shortcut)
    supports_raw_lane = True

    def subscribe(self, sid, client_id, filter_, opts, deliver,
                  replay_retained: bool = True, raw_sink=None,
                  raw_version: int = 0):
        """Returns a future resolved when the router CONFIRMS the
        subscription (SUB_ACK) — the channel awaits it before SUBACK, so
        a publish racing the SUBACK still delivers (the in-process
        broker's subscribe is synchronous for the same contract).
        `replay_retained=False` marks session-resume re-registrations,
        which must never re-deliver retained messages. `raw_sink` opts
        this subscription into the QoS0 fast lane: the router ships
        pre-serialized PUBLISH frames and on_raw writes them straight
        to the sink, bypassing the channel."""
        key = (sid, filter_)
        h = self._byname.get(key)
        if h is None:
            h = self._next_handle
            self._next_handle += 1
            self._byname[key] = h
        self._subs[h] = (deliver, opts)
        if raw_sink is not None:
            self._raw_sinks[h] = (raw_sink, int(raw_version))
        else:
            # re-subscribe that no longer qualifies (e.g. QoS upgrade)
            # must leave the fast lane
            self._raw_sinks.pop(h, None)
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        # NOTE: a down link (router restarting) does NOT fail fast — the
        # registration is recorded locally, reattach_link replays it, and
        # the 30s confirm timer bounds the client's SUBACK wait
        ent = self._sub_acks.get(h)
        if ent is not None and not ent[0].done():
            fut = ent[0]  # re-subscribe racing its own confirm
        else:
            timer = loop.call_later(
                30.0,
                lambda: fut.done() or fut.set_result(False),
            )
            self._sub_acks[h] = (fut, timer)
        self._send(
            F.pack_json(
                F.T_SUB,
                {
                    "h": h,
                    "sid": sid,
                    "cid": client_id,
                    "f": filter_,
                    "qos": opts.qos,
                    "nl": opts.no_local,
                    "rap": opts.retain_as_published,
                    "rh": opts.retain_handling,
                    # per-client resubscribe flag set by the worker-side
                    # channel (rh=1 retained-replay suppression)
                    "ex": bool(getattr(opts, "_existing", False)),
                    **({} if replay_retained else {"nr": True}),
                    **({"fl": raw_version} if raw_sink is not None
                       else {}),
                },
            )
        )
        return fut

    def on_sub_ack(self, h: int) -> None:
        ent = self._sub_acks.pop(h, None)
        if ent is None:
            return
        fut, timer = ent
        timer.cancel()
        if not fut.done():
            fut.set_result(True)

    def unsubscribe(self, sid, filter_) -> bool:
        h = self._byname.pop((sid, filter_), None)
        if h is None:
            return False
        self._subs.pop(h, None)
        self._raw_sinks.pop(h, None)
        ent = self._sub_acks.pop(h, None)
        if ent is not None:
            # unsubscribing a confirm-pending handle (e.g. the channel's
            # failed-subscribe rollback): cancel the timer and resolve
            # so nothing leaks or waits on an ack that can't arrive
            fut, timer = ent
            timer.cancel()
            if not fut.done():
                fut.set_result(False)
        self._send(F.pack_json(F.T_UNSUB, {"sid": sid, "f": filter_}))
        return True

    def drop_session_subs(self, sid, filters) -> None:
        for f in list(filters):
            self.unsubscribe(sid, f)

    def _enqueue_pub(self, msg: Message):
        """QoS>0 returns a Future resolved by the router's PUBB_ACK (the
        client's PUBACK waits on it); QoS0 is fire-and-forget — coupling
        it to the ack round-trip measured ~4x e2e throughput loss for a
        guarantee QoS0 never promises."""
        self.metrics.inc("messages.received")
        fut = None
        if msg.qos > 0:
            fut = asyncio.get_running_loop().create_future()
        self._pub_buf.append((msg, fut))
        if not self._pub_scheduled:
            self._pub_scheduled = True
            asyncio.get_running_loop().call_soon(self._flush_pubs)
        return fut if fut is not None else 0

    def _flush_pubs(self) -> None:
        self._pub_scheduled = False
        buf, self._pub_buf = self._pub_buf, []
        if not buf:
            return
        # chunk below the fabric frame cap: ~64 pipelined max-size
        # publishes in one tick would otherwise exceed the receiver's
        # MAX_FRAME and tear down the link
        start = 0
        while start < len(buf):
            size = 8
            end = start
            while end < len(buf):
                r = F.pub_record_size(buf[end][0])
                if end > start and size + r > F.MAX_BODY:
                    break
                size += r
                end += 1
            chunk = buf[start:end]
            start = end
            seq = self._next_seq
            self._next_seq += 1
            futs = [f for _, f in chunk]
            msgs = [m for m, _ in chunk]
            if any(f is not None for f in futs):
                # safety: a lost ack (router bug / torn link mid-restart)
                # must not wedge every publisher's PUBACK forever
                timer = asyncio.get_running_loop().call_later(
                    self.ACK_TIMEOUT_S, self._expire_batch, seq
                )
                self._inflight[seq] = (futs, timer, msgs)
            self._send(self._pack_pub(msgs, seq))

    def _expire_batch(self, seq: int) -> None:
        ent = self._inflight.pop(seq, None)
        if ent:
            self.metrics.inc("fabric.puback.timeouts")
            for f in ent[0]:
                if f is not None and not f.done():
                    # -1 = the 'never no-subscribers' sentinel (see
                    # channel._send_pub_ack): a late-but-delivered batch
                    # must not tell v5 clients NO_MATCHING_SUBSCRIBERS
                    f.set_result(-1)

    def on_pub_ack(self, seq: int, counts) -> None:
        ent = self._inflight.pop(seq, None)
        if not ent:
            return
        futs, timer, _msgs = ent
        timer.cancel()
        for f, n in zip(futs, counts):
            if f is not None and not f.done():
                f.set_result(n)

    async def apublish_enqueue(self, msg: Message):
        """-> int (dropped) or a Future resolving with the delivery count
        once the router CONFIRMS the batch — same contract as the real
        Broker's ingest path, so the channel's ack queue holds each
        QoS1/2 PUBACK until the message is actually routed."""
        msg = await self.hooks.arun_fold("message.publish", (), msg)
        if msg is None or msg.headers.get("allow_publish") is False:
            self.metrics.inc("messages.dropped")
            return 0
        return self._enqueue_pub(msg)

    async def apublish(self, msg: Message) -> int:
        r = await self.apublish_enqueue(msg)
        return r if isinstance(r, int) else await r

    def publish(self, msg: Message) -> int:
        msg = self.hooks.run_fold("message.publish", (), msg)
        if msg is None or msg.headers.get("allow_publish") is False:
            return 0
        self._enqueue_pub(msg)  # fire-and-forget (sync callers: will, sys)
        return 0

    # delivery ------------------------------------------------------------
    def on_raw(self, records) -> None:
        """QoS0 fast lane: pre-serialized PUBLISH frames from the
        router, written straight to subscriber sockets (the negotiated
        eligibility guarantees no channel-side work is being skipped:
        qos 0, no mountpoint, empty delivered/completed chains)."""
        sinks = self._raw_sinks
        sent = errs = 0
        for buf, handles in records:
            for h in handles:
                ent = sinks.get(h)
                if ent is None:
                    continue
                try:
                    ent[0].send_bytes(buf)
                    sent += 1
                except Exception:
                    errs += 1
        if sent:
            self.metrics.inc("packets.sent", sent)
        if errs:
            self.metrics.inc("delivery.errors", errs)

    def on_dlv_slab(self, slab) -> None:
        """Slab DLV (T_DLV_S): handles resolve FIRST, so a record whose
        targets all unsubscribed mid-flight skips decode entirely; one
        lazy SlabMessage per record is shared across its targets (str
        decode / payload copy happen at most once, on first need)."""
        from emqx_tpu.broker.message import SlabMessage

        subs = self._subs
        flags = slab.flags
        for i in range(slab.n):
            ents = [
                ent
                for h in slab.handles(i).tolist()
                if (ent := subs.get(h)) is not None
            ]
            if not ents:
                continue
            f = int(flags[i])
            msg = SlabMessage(
                slab, i, qos=f & 3, retain=bool(f & 4),
                from_client=slab.client(i), properties=slab.props(i),
            )
            if f & 8:
                msg.headers["retained"] = True
            for deliver, opts in ents:
                try:
                    deliver(msg, opts)
                except Exception:
                    self.metrics.inc("delivery.errors")

    def on_delivery(self, topic, payload, qos, retain, retained, client,
                    props, handles) -> None:
        msg = Message(
            topic=topic,
            payload=payload,
            qos=qos,
            retain=retain,
            from_client=client,
            properties=props or {},
        )
        if retained:
            msg.headers["retained"] = True
        for h in handles:
            ent = self._subs.get(h)
            if ent is None:
                continue
            deliver, opts = ent
            try:
                deliver(msg, opts)
            except Exception:
                self.metrics.inc("delivery.errors")


class WorkerChannelManager:
    """emqx_cm semantics ACROSS workers: session open/takeover/resume and
    persistent parking are brokered by the router process, so a client
    reconnecting onto a DIFFERENT worker (or an in-process listener of
    the router) still finds its session. Reference:
    emqx_cm.erl:245-273 open_session, :346-366 takeover_session —
    there the registry is node-level; here the router process is the
    node."""

    def __init__(self, broker: "WorkerBroker"):
        self.broker = broker
        broker.cm = self
        self._channels: Dict[str, object] = {}
        # after CONNACK the negotiated expiry is final (v5 property /
        # v4 clean_start zeroing applied): announce it for crash parking
        broker.hooks.add(
            "client.connected",
            lambda ci, ch: broker.sess_opened(
                ch.client_id, ch.session.config.expiry_interval
            ) if getattr(ch, "session", None) is not None else None,
            tag="worker_cm.opened",
        )
        # transient only (mid-takeover stash); authoritative parking
        # lives in the ROUTER's detached store
        self._detached: Dict[str, Tuple] = {}

    def get_channel(self, client_id: str):
        return self._channels.get(client_id)

    def channel_count(self) -> int:
        return len(self._channels)

    def client_ids(self):
        return list(self._channels)

    def open_session(self, channel):
        """Awaitable (the channel awaits it): one router round trip
        resolves discard/takeover/resume node-wide."""
        return self._open_async(channel)

    async def _open_async(self, channel):
        from emqx_tpu.broker.session import Session
        from emqx_tpu.storage.codec import session_from_json

        cid = channel.client_id
        sj, present = await self.broker.sess_open(
            cid, channel.clean_start
        )
        session = None
        if sj is not None:
            try:
                session = session_from_json(sj, channel.config.session)
            except Exception:
                self.broker.metrics.inc("fabric.sess.decode_errors")
        if session is not None:
            self.broker.hooks.run("session.resumed", cid)
            for f, opts in session.subscriptions.items():
                # re-registration of a live session: confirm futures are
                # intentionally not awaited (CONNACK carries `present`;
                # deliveries begin as each SUB registers) and retained
                # must not replay
                self.broker.subscribe(
                    cid, cid, f, opts, channel._make_deliverer(opts),
                    replay_retained=False,
                )
            # SUB frames precede resume_done on the FIFO link: the
            # router flushes handoff-banked messages to the handles
            # registered above
            self.broker.sess_resume_done(cid)
        else:
            session = Session(cid, channel.config.session)
            self.broker.hooks.run("session.created", cid)
            present = False
        # same-worker concurrent CONNECT race: both were awaiting the
        # router; the loser installed first and must be kicked
        old = self._channels.pop(cid, None)
        if old is not None and old is not channel:
            old.kick("takenover")
        self._channels[cid] = channel
        self.broker.metrics.gauge_set(
            "connections.count", len(self._channels)
        )
        return session, bool(present)

    def on_channel_closed(self, channel, reason: str) -> None:
        from emqx_tpu.storage.codec import session_to_json

        cid = channel.client_id
        if self._channels.get(cid) is not channel:
            return  # already replaced by takeover/discard
        del self._channels[cid]
        self.broker.metrics.gauge_set(
            "connections.count", len(self._channels)
        )
        sess = channel.session
        if sess is None:
            return
        expiry = sess.config.expiry_interval
        if expiry > 0:
            # park at the ROUTER: survives this worker, resumable from
            # any worker/listener, WAL-backed when persistence is on
            self.broker.sess_park(cid, session_to_json(sess), expiry)
            self.broker.drop_session_subs(
                cid, list(sess.subscriptions)
            )
            self.broker.hooks.run("session.detached", cid)
        else:
            self.broker.drop_session_subs(
                cid, list(sess.subscriptions)
            )
            self.broker.hooks.run("session.terminated", cid, reason)
            self.broker.sess_closed(cid)

    def kick_client(self, client_id: str) -> bool:
        ch = self._channels.pop(client_id, None)
        if ch is None:
            return False
        sess = ch.kick("kicked")
        if sess is not None:
            self.broker.drop_session_subs(
                client_id, list(sess.subscriptions)
            )
        self.broker.sess_closed(client_id)
        return True

    def sweep_expired(self, now=None) -> int:
        return 0  # expiry lives with the router's detached store


def worker_main(
    wid: int,
    bind: str,
    port: int,
    uds_path: str,
    config,
) -> None:
    """Entry point of a spawned connection worker (own interpreter; the
    TPU is never touched here — jax stays uninitialized)."""
    asyncio.run(_worker_async(wid, bind, port, uds_path, config))


async def _worker_async(wid, bind, port, uds_path, config) -> None:
    from emqx_tpu.app import build_guard_hooks
    from emqx_tpu.broker.hooks import Hooks
    from emqx_tpu.broker.metrics import Metrics
    from emqx_tpu.transport.connection import Connection

    hooks = Hooks()
    metrics = Metrics()
    broker = WorkerBroker(hooks, metrics)
    channel_config = build_guard_hooks(config, hooks)
    cm = WorkerChannelManager(broker)

    # fabric link to the router process (retry: the router may still be
    # binding the UDS when workers spawn)
    for attempt in range(100):
        try:
            reader, writer = await asyncio.open_unix_connection(uds_path)
            break
        except (FileNotFoundError, ConnectionRefusedError):
            await asyncio.sleep(0.05 * (attempt + 1))
    else:
        raise RuntimeError(f"worker {wid}: router fabric not reachable")
    writer.write(F.pack_frame(F.T_HELLO, wid.to_bytes(2, "little")))
    broker.attach_link(writer)
    # boot flight complete (nothing to replay on first dial): the router
    # holds cross-worker publish dispatch until every worker reports in
    writer.write(F.pack_json(F.T_SESS, {"op": "replay_done"}))

    # a router-process blip must not drop every client on this worker
    # (the reference's layered supervision restarts subsystems without
    # dropping esockd connections, emqx_machine_boot restart ordering):
    # hold connections, re-dial the (pid-stable) UDS path, replay SUBs
    # and unacked publish batches. Only a router gone past the window
    # ends the worker.
    RECONNECT_WINDOW_S = 60.0

    async def pump_link():
        nonlocal reader, writer
        loop = asyncio.get_running_loop()
        while True:
            try:
                while True:
                    ftype, body = await F.read_frame(reader)
                    if ftype == F.T_DLV:
                        for rec in F.unpack_dlv_batch(body):
                            broker.on_delivery(*rec)
                    elif ftype == F.T_DLV_S:
                        broker.on_dlv_slab(F.unpack_dlv_slab(body))
                    elif ftype == F.T_RAW:
                        broker.on_raw(F.unpack_raw_batch(body))
                    elif ftype == F.T_PUBB_ACK:
                        broker.on_pub_ack(*F.unpack_pub_ack(body))
                    elif ftype == F.T_SUB_ACK:
                        import json as _json

                        broker.on_sub_ack(int(_json.loads(body)["h"]))
                    elif ftype == F.T_SESS:
                        import json as _json

                        broker.on_sess(_json.loads(body))
            except (
                asyncio.IncompleteReadError,
                # OSError covers ConnectionResetError AND BrokenPipeError
                # — a write racing the router's shutdown surfaces on the
                # read waiter as EPIPE, and must trigger the re-dial, not
                # kill the worker (and its clients) with it
                OSError,
                ValueError,
            ):
                pass
            broker.detach_link()
            broker.metrics.inc("fabric.link.lost")
            deadline = loop.time() + RECONNECT_WINDOW_S
            nc = None
            while loop.time() < deadline:
                try:
                    nc = await asyncio.open_unix_connection(uds_path)
                    break
                except (FileNotFoundError, ConnectionRefusedError, OSError):
                    await asyncio.sleep(0.25)
            if nc is None:
                os._exit(0)  # router gone for good: nothing to serve
            reader, writer = nc
            writer.write(F.pack_frame(F.T_HELLO, wid.to_bytes(2, "little")))
            broker.reattach_link(writer)
            writer.write(F.pack_json(F.T_SESS, {"op": "replay_done"}))
            broker.metrics.inc("fabric.link.reconnected")

    link_task = asyncio.create_task(pump_link())

    conns: set = set()

    async def on_client(r, w):
        conn = Connection(broker, cm, r, w, channel_config)
        task = asyncio.current_task()
        conns.add(task)
        try:
            await conn.run()
        finally:
            conns.discard(task)

    server = await asyncio.start_server(
        on_client, bind, port, reuse_port=True
    )
    try:
        await asyncio.gather(server.serve_forever(), link_task)
    except asyncio.CancelledError:
        pass


# ---------------------------------------------------------------------------
# pool management (router side)
# ---------------------------------------------------------------------------


class WorkerPool:
    """Spawns and supervises the worker processes for one listener.

    Workers launch as `python -m emqx_tpu.transport.workers ...` with the
    app config re-serialized to JSON — plain subprocesses, no
    multiprocessing __main__ re-import (which breaks under embedding
    hosts) and no pickle coupling."""

    def __init__(self, app, bind: str, port: int, n_workers: int, config):
        self.app = app
        self.bind = bind
        self.port = port
        self.n = n_workers
        self.config = config
        # pid-free path: a RESTARTED router process rebinds the same
        # socket, so surviving workers can re-dial it. bind+port key the
        # broker instance on this host (pid in the name would break
        # restart re-dial; bind alone distinguishes two brokers sharing
        # a port number on different addresses)
        safe_bind = bind.replace(":", "_").replace("/", "_")
        base = f"emqx-tpu-fabric-{safe_bind}-{port}"
        self.uds_path = os.path.join(tempfile.gettempdir(), base + ".sock")
        self._cfg_path = os.path.join(tempfile.gettempdir(), base + ".json")
        self.fabric = WorkerFabric(app, self.uds_path,
                                   expected_workers=n_workers)
        self._procs: List = []

    # supervision: a crashed worker respawns (one-for-one, like the
    # reference's esockd supervisor over connection processes); a worker
    # that dies repeatedly within the window stays down to avoid a
    # crash-loop eating the host
    RESPAWN_WINDOW_S = 60.0
    MAX_RESPAWNS_PER_WINDOW = 5

    def _spawn(self, wid: int):
        import subprocess
        import sys

        return subprocess.Popen(
            [
                sys.executable,
                "-m",
                "emqx_tpu.transport.workers",
                "--wid", str(wid),
                "--bind", self.bind,
                "--port", str(self.port),
                "--uds", self.uds_path,
                "--config", self._cfg_path,
            ],
        )

    def _write_worker_config(self) -> None:
        import dataclasses
        import json

        with open(self._cfg_path, "w") as f:
            json.dump(dataclasses.asdict(self.config), f, default=str)

    async def start(self) -> None:
        await self.fabric.start()
        # config snapshot for the worker processes: written off-loop (the
        # dump can hit a slow tmpdir while listeners are already serving)
        await asyncio.get_running_loop().run_in_executor(
            None, self._write_worker_config
        )
        for wid in range(self.n):
            self._procs.append(self._spawn(wid))
        self._respawns: List[float] = []
        self._supervisor = asyncio.get_running_loop().create_task(
            self._supervise()
        )

    async def _supervise(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            await asyncio.sleep(2.0)
            for wid, p in enumerate(self._procs):
                if p.poll() is None:
                    continue
                now = loop.time()
                self._respawns = [
                    t for t in self._respawns
                    if now - t < self.RESPAWN_WINDOW_S
                ]
                if len(self._respawns) >= self.MAX_RESPAWNS_PER_WINDOW:
                    self.app.broker.metrics.inc("fabric.worker.crash_loop")
                    continue
                self._respawns.append(now)
                self.app.broker.metrics.inc("fabric.worker.respawns")
                self._procs[wid] = self._spawn(wid)

    def describe(self) -> dict:
        """Listener-style status row (mgmt REST surface)."""
        alive = sum(1 for p in self._procs if p.poll() is None)
        return {
            "id": f"tcp:workers:{self.port}",
            "type": "tcp",
            "name": f"workers:{self.port}",
            "bind": f"{self.bind}:{self.port}",
            "running": alive > 0,
            "workers": self.n,
            "workers_alive": alive,
            "workers_connected": len(self.fabric._writers),
            "max_connections": 0,
            "current_connections": 0,
            "port": self.port,
        }

    async def wait_ready(self, timeout: float = 30.0) -> None:
        """Block until every worker has dialed the fabric."""
        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout
        while len(self.fabric._writers) < self.n:
            if loop.time() > deadline:
                raise TimeoutError(
                    f"{len(self.fabric._writers)}/{self.n} workers ready"
                )
            await asyncio.sleep(0.05)

    async def stop(self) -> None:
        sup = getattr(self, "_supervisor", None)
        if sup is not None:
            sup.cancel()
            try:
                await sup
            except asyncio.CancelledError:
                pass
            self._supervisor = None
        for p in self._procs:
            p.terminate()
        for p in self._procs:
            try:
                p.wait(timeout=5)
            except Exception:
                p.kill()
        self._procs.clear()
        await self.fabric.stop()
        try:
            os.unlink(self._cfg_path)
        except FileNotFoundError:
            pass


def _cli() -> None:
    import argparse
    import json

    from emqx_tpu.config.schema import load_config

    ap = argparse.ArgumentParser(prog="emqx_tpu.transport.workers")
    ap.add_argument("--wid", type=int, required=True)
    ap.add_argument("--bind", required=True)
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--uds", required=True)
    ap.add_argument("--config", required=True)
    a = ap.parse_args()
    with open(a.config) as f:
        c = load_config(json.load(f))
    prof_dir = os.environ.get("EMQX_TPU_WORKER_PROFILE")
    if prof_dir:
        # perf tooling: profile this worker's whole life, dump on exit
        # (SIGTERM mapped to sys.exit so the pool's terminate() still
        # flushes the profile)
        import cProfile
        import signal as _sig

        pr = cProfile.Profile()

        def _dump(*_):
            pr.disable()
            pr.dump_stats(
                os.path.join(prof_dir, f"worker-{a.wid}.prof")
            )
            os._exit(0)

        _sig.signal(_sig.SIGTERM, _dump)
        pr.enable()
    worker_main(a.wid, a.bind, a.port, a.uds, c)


if __name__ == "__main__":
    _cli()
