"""Per-connection write-buffer congestion alarms + forced GC.

Reference: apps/emqx/src/emqx_congestion.erl (TCP send-queue congestion
alarms with a min-alarm-interval) and emqx_gc.erl (force a collection after
N delivered messages / bytes per connection). SURVEY.md §2.1.

Congestion here watches the asyncio transport's write buffer: a connection
whose peer stops reading accumulates bytes in `transport.get_write_buffer_size()`;
above `high_watermark` an alarm `conn_congestion/<clientid>` raises, and it
clears once the buffer drains below `low_watermark`.

ForcedGC is the CPython translation of emqx_gc: gen-0 collections are cheap
and bound per-connection garbage growth on busy brokers where the automatic
threshold would otherwise let cycles pile up.
"""

from __future__ import annotations

import gc
import time
from typing import Optional


class Congestion:
    def __init__(
        self,
        alarms=None,
        high_watermark: int = 1024 * 1024,
        low_watermark: int = 64 * 1024,
        min_alarm_interval: float = 60.0,
    ):
        self.alarms = alarms
        self.high = high_watermark
        self.low = low_watermark
        self.min_alarm_interval = min_alarm_interval
        self._alarmed = False
        self._last_alarm = 0.0

    def check(self, transport, client_id: str) -> None:
        if self.alarms is None or transport is None:
            return
        try:
            size = transport.get_write_buffer_size()
        except Exception:
            return
        now = time.monotonic()
        name = f"conn_congestion/{client_id}"
        if not self._alarmed and size > self.high:
            if now - self._last_alarm >= self.min_alarm_interval:
                self.alarms.activate(
                    name,
                    {"buffer_bytes": size, "high_watermark": self.high},
                    "connection send buffer congested",
                )
                self._alarmed = True
                self._last_alarm = now
        elif self._alarmed and size < self.low:
            self.alarms.deactivate(name)
            self._alarmed = False

    def on_close(self, client_id: str) -> None:
        if self._alarmed and self.alarms is not None:
            self.alarms.deactivate(f"conn_congestion/{client_id}")
            self._alarmed = False


class ForcedGC:
    """Count-triggered gen-0 collection (emqx_gc.erl state machine)."""

    def __init__(self, count: int = 16000, bytes_: int = 16 * 1024 * 1024):
        self.count_limit = count
        self.bytes_limit = bytes_
        self._count = 0
        self._bytes = 0
        self.collections = 0

    def inc(self, msgs: int, nbytes: int) -> bool:
        """Returns True when a collection was forced."""
        if self.count_limit <= 0 and self.bytes_limit <= 0:
            return False
        self._count += msgs
        self._bytes += nbytes
        if (self.count_limit > 0 and self._count >= self.count_limit) or (
            self.bytes_limit > 0 and self._bytes >= self.bytes_limit
        ):
            self._count = 0
            self._bytes = 0
            gc.collect(0)
            self.collections += 1
            return True
        return False
