"""Listener lifecycle: named TCP/TLS endpoints feeding connections.

Parity with emqx_listeners (apps/emqx/src/emqx_listeners.erl:230-266):
start/stop/restart per {type, name}; TLS via ssl.SSLContext; WebSocket and
QUIC are follow-on transports behind the same Connection pump.
"""

from __future__ import annotations

import asyncio
import ssl as ssl_mod
from dataclasses import dataclass, field
from typing import Dict, Optional

from emqx_tpu.broker.channel import ChannelConfig
from emqx_tpu.transport.connection import Connection


@dataclass
class TransportContext:
    """Cross-cutting services every connection shares: rate limiting,
    overload gate, alarms, forced-GC factory (reference: esockd limiter
    adapter + emqx_olp + emqx_congestion wiring in emqx_connection.erl)."""

    limiters: object = None  # LimiterServer
    olp: object = None  # Olp
    alarms: object = None  # AlarmManager
    make_forced_gc: object = None  # Optional[Callable[[], ForcedGC]]
    psk: object = None  # PskStore (wired into ssl/wss contexts when set)


class AdmissionControl:
    """Shared accept-time gate: max-connections + OLP + connection-rate
    limiter; refuse-don't-queue (used by both TCP and WS listeners)."""

    def __init__(self, ctx: Optional[TransportContext], metrics):
        self.ctx = ctx
        self.metrics = metrics
        self._conn_limiter = (
            ctx.limiters.connect("connection")
            if ctx is not None and ctx.limiters is not None
            else None
        )

    def admit(self, current: int, maximum: int) -> bool:
        if current >= maximum:
            return False
        if self.ctx is not None and self.ctx.olp is not None \
                and self.ctx.olp.is_overloaded():
            self.metrics.inc("olp.refused")
            return False
        if (
            self._conn_limiter is not None
            and not self._conn_limiter.try_acquire(1)
        ):
            self.metrics.inc("limiter.refused.connection")
            return False
        return True


@dataclass
class ListenerConfig:
    name: str = "default"
    type: str = "tcp"  # tcp | ssl | ws | wss
    bind: str = "127.0.0.1"
    port: int = 1883
    max_connections: int = 1_024_000
    ssl_certfile: Optional[str] = None
    ssl_keyfile: Optional[str] = None
    ssl_cacertfile: Optional[str] = None
    ssl_verify: bool = False


def build_ssl_context(config: "ListenerConfig") -> ssl_mod.SSLContext:
    """Server-side TLS context shared by the ssl and wss listener types."""
    ctx = ssl_mod.SSLContext(ssl_mod.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(config.ssl_certfile, config.ssl_keyfile)
    if config.ssl_cacertfile:
        ctx.load_verify_locations(config.ssl_cacertfile)
    if config.ssl_verify:
        ctx.verify_mode = ssl_mod.CERT_REQUIRED
    return ctx


class Listener:
    def __init__(
        self,
        broker,
        cm,
        config: ListenerConfig,
        channel_config=None,
        ctx: Optional[TransportContext] = None,
    ):
        self.broker = broker
        self.cm = cm
        self.config = config
        self.channel_config = channel_config or ChannelConfig()
        self.ctx = ctx
        self._admission = AdmissionControl(ctx, broker.metrics)
        self._server: Optional[asyncio.AbstractServer] = None
        self._conns: set = set()

    @property
    def port(self) -> int:
        """Actual bound port (useful when configured with port=0)."""
        if self._server and self._server.sockets:
            return self._server.sockets[0].getsockname()[1]
        return self.config.port

    def connection_count(self) -> int:
        return len(self._conns)

    async def start(self) -> None:
        ctx = None
        if self.config.type == "ssl":
            ctx = build_ssl_context(self.config)
            if self.ctx is not None and self.ctx.psk is not None:
                self.ctx.psk.wire_into(ctx)
        self._server = await asyncio.start_server(
            self._on_client, self.config.bind, self.config.port, ssl=ctx
        )

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
        # cancel live connection handlers BEFORE wait_closed: since 3.12
        # Server.wait_closed blocks until every handler returns
        for t in list(self._conns):
            t.cancel()
        if self._server is not None:
            await self._server.wait_closed()
            self._server = None

    async def _on_client(self, reader, writer) -> None:
        if not self._admission.admit(
            len(self._conns), self.config.max_connections
        ):
            writer.close()
            return
        conn = Connection(
            self.broker, self.cm, reader, writer, self.channel_config,
            ctx=self.ctx,
        )
        task = asyncio.current_task()
        self._conns.add(task)
        try:
            await conn.run()
        finally:
            self._conns.discard(task)


class Listeners:
    """Registry of named listeners (emqx_listeners API parity)."""

    def __init__(self, broker, cm, ctx: Optional[TransportContext] = None):
        self.broker = broker
        self.cm = cm
        self.ctx = ctx
        self._listeners: Dict[str, Listener] = {}
        # specs survive a stop so the REST surface can start/restart by id
        # (emqx_mgmt_api_listeners start/stop/restart semantics)
        self._specs: Dict[str, tuple] = {}  # key -> (config, channel_config)

    async def start_listener(
        self, config: ListenerConfig, channel_config=None
    ) -> "Listener":
        key = f"{config.type}:{config.name}"
        if key in self._listeners:
            raise ValueError(f"listener {key} already running")
        if config.type in ("ws", "wss"):
            from emqx_tpu.transport.ws import WsListener

            l = WsListener(
                self.broker, self.cm, config,
                channel_config or ChannelConfig(), ctx=self.ctx,
            )
        else:
            l = Listener(
                self.broker, self.cm, config, channel_config, ctx=self.ctx
            )
        await l.start()
        # spec recorded only on success: a failed create must not leave
        # a phantom stopped-listener entry on the REST surface
        self._specs[key] = (config, channel_config)
        self._listeners[key] = l
        return l

    async def stop_listener(self, type_: str, name: str) -> bool:
        key = f"{type_}:{name}"
        l = self._listeners.pop(key, None)
        if l is None:
            return False
        await l.stop()
        return True

    async def start_stopped(self, type_: str, name: str) -> "Listener":
        """Start a previously-stopped listener from its saved spec."""
        key = f"{type_}:{name}"
        if key in self._listeners:
            raise ValueError(f"listener {key} already running")
        spec = self._specs.get(key)
        if spec is None:
            raise KeyError(f"unknown listener {key}")
        return await self.start_listener(spec[0], spec[1])

    async def restart_listener(self, type_: str, name: str) -> "Listener":
        key = f"{type_}:{name}"
        if key not in self._specs:
            raise KeyError(f"unknown listener {key}")
        await self.stop_listener(type_, name)
        return await self.start_stopped(type_, name)

    async def delete_listener(self, type_: str, name: str) -> bool:
        """Stop (if running) and forget the saved spec entirely."""
        await self.stop_listener(type_, name)
        return self._specs.pop(f"{type_}:{name}", None) is not None

    async def stop_all(self) -> None:
        for key in list(self._listeners):
            t, n = key.split(":", 1)
            await self.stop_listener(t, n)

    def list(self):
        return dict(self._listeners)

    def describe(self):
        """Listener status rows for the REST surface: running and
        stopped-but-known listeners alike."""
        rows = []
        for key, (config, _cc) in self._specs.items():
            l = self._listeners.get(key)
            rows.append(
                {
                    "id": key,
                    "type": config.type,
                    "name": config.name,
                    "bind": f"{config.bind}:{config.port}",
                    "running": l is not None,
                    "current_connections": (
                        l.connection_count() if l is not None
                        and hasattr(l, "connection_count") else 0
                    ),
                    "max_connections": config.max_connections,
                    "port": l.port if l is not None else config.port,
                }
            )
        return rows
