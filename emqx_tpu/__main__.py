"""Broker entrypoint: `python -m emqx_tpu [--port 1883]`.

The `bin/emqx foreground` analog (reference: bin/emqx:75-110). Boots the
broker kernel, channel manager, and TCP listener on one asyncio loop and
runs until SIGINT/SIGTERM.
"""

from __future__ import annotations

import argparse
import asyncio
import signal
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="emqx_tpu", description=__doc__)
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=1883)
    ap.add_argument(
        "--no-tpu", action="store_true",
        help="route on the CPU trie only (skip JAX/TPU engine)",
    )
    ap.add_argument(
        "--min-tpu-batch", type=int, default=64,
        help="publish batch size at which routing moves to the TPU kernel",
    )
    args = ap.parse_args(argv)
    return asyncio.run(serve(args))


async def serve(args) -> int:
    from emqx_tpu.broker.broker import Broker
    from emqx_tpu.broker.cm import ChannelManager
    from emqx_tpu.broker.router import Router
    from emqx_tpu.transport.listener import ListenerConfig, Listeners

    router = Router(
        enable_tpu=not args.no_tpu, min_tpu_batch=args.min_tpu_batch
    )
    broker = Broker(router=router)
    cm = ChannelManager(broker)
    listeners = Listeners(broker, cm)
    l = await listeners.start_listener(
        ListenerConfig(bind=args.host, port=args.port)
    )
    print(f"emqx_tpu broker listening on {args.host}:{l.port}", flush=True)

    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    print("shutting down", flush=True)
    await listeners.stop_all()
    return 0


if __name__ == "__main__":
    sys.exit(main())
