"""Broker entrypoint: `python -m emqx_tpu [-c config.json] [--port 1883]`.

The `bin/emqx foreground` analog (reference: bin/emqx:75-110). Boots the
full application (broker kernel, extensions, listeners, management API,
housekeeping) from a config file plus EMQX_TPU__* env overrides and runs
until SIGINT/SIGTERM.
"""

from __future__ import annotations

import argparse
import asyncio
import signal
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="emqx_tpu", description=__doc__)
    ap.add_argument("-c", "--config", default=None, help="JSON config file")
    ap.add_argument("--host", default=None, help="override listener bind")
    ap.add_argument("--port", type=int, default=None, help="override listener port")
    ap.add_argument(
        "--no-tpu", action="store_true",
        help="route on the CPU trie only (skip JAX/TPU engine)",
    )
    ap.add_argument(
        "--no-dashboard", action="store_true", help="disable the REST API"
    )
    args = ap.parse_args(argv)
    return asyncio.run(serve(args))


async def serve(args) -> int:
    from emqx_tpu.app import BrokerApp
    from emqx_tpu.config.schema import load_file

    config = load_file(args.config)
    if args.host is not None:
        config.listeners[0].bind = args.host
    if args.port is not None:
        config.listeners[0].port = args.port
    if args.no_tpu:
        config.router.enable_tpu = False
    if args.no_dashboard:
        config.dashboard.enable = False

    app = BrokerApp(config)
    await app.start()
    for l in app.listeners.list().values():
        print(
            f"emqx_tpu listener {l.config.type}:{l.config.name} on "
            f"{l.config.bind}:{l.port}",
            flush=True,
        )
    for pool in app.worker_pools:
        row = pool.describe()
        print(
            f"emqx_tpu listener {row['id']} on {row['bind']} "
            f"({row['workers']} workers)",
            flush=True,
        )
    if app.mgmt_server is not None:
        print(
            f"emqx_tpu mgmt api on {config.dashboard.bind}:{app.mgmt_server.port}",
            flush=True,
        )
    if app.cluster_bus is not None:
        print(
            f"emqx_tpu cluster bus on "
            f"{app.cluster_bus.host}:{app.cluster_bus.port}",
            flush=True,
        )

    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    print("shutting down", flush=True)
    await app.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
