"""Node identity (reference analog: the Erlang node() name used in $SYS topics)."""

from __future__ import annotations

import os
import socket

_node_name: str | None = None


def node_name() -> str:
    global _node_name
    if _node_name is None:
        _node_name = os.environ.get(
            "EMQX_TPU_NODE", f"emqx_tpu@{socket.gethostname()}"
        )
    return _node_name


def set_node_name(name: str) -> None:
    global _node_name
    _node_name = name
