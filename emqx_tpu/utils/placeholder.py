"""Shared ``${var}`` placeholder templating.

The emqx_plugin_libs `emqx_placeholder` analog: one implementation used by
the rule engine, data bridges, authz patterns, and auto-subscribe instead
of per-module reimplementations. Supports dotted paths into nested dicts
(JSON-decoding string/bytes nodes on the way down), with the reference's
rendering conventions (bools as true/false, integral floats as ints,
missing vars as empty string).
"""

from __future__ import annotations

import json
import re
from typing import Dict

_PLACEHOLDER = re.compile(r"\$\{([A-Za-z0-9_.$]+)\}")


def render(template: str, env: Dict) -> str:
    """Substitute every ``${a.b}`` in `template` from `env`."""

    def repl(m):
        cur = env
        for seg in m.group(1).split("."):
            if isinstance(cur, (bytes, str)):
                try:
                    cur = json.loads(cur)
                except (ValueError, TypeError):
                    cur = None
            if not isinstance(cur, dict) or seg not in cur:
                return ""
            cur = cur[seg]
        if isinstance(cur, bytes):
            return cur.decode("utf-8", "replace")
        if isinstance(cur, (dict, list)):
            return json.dumps(cur)
        if isinstance(cur, bool):
            return "true" if cur else "false"
        if isinstance(cur, float) and cur.is_integer():
            return str(int(cur))
        return "" if cur is None else str(cur)

    return _PLACEHOLDER.sub(repl, template)
