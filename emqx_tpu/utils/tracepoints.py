"""Structured trace points + scheduling nemesis for concurrency testing.

The snabbkaffe analog (the reference compiles ?tp trace points into
modules and has CT suites assert causal properties over collected traces
while a nemesis perturbs scheduling; SURVEY.md §4/§5.2). Here:

- `tp(kind, **fields)` emits a structured event into the active collector
  — a single module-level flag check when tracing is off, so production
  paths pay one branch;
- `await atp(kind, **fields)` is the async variant where the NEMESIS can
  inject an await (sleep / custom coroutine) to widen race windows at
  exactly the instrumented point, the way snabbkaffe's scheduling
  injections force interleavings;
- `TraceCollector` gathers events and provides causal assertions:
  `causally_ordered(a, b, key)` (every `b` is preceded by a matching
  `a`), `pairs(a, b, key)` (one-to-one), `projection(kind)`.

Only tests activate collection; there is no global registry of trace
kinds — kinds are free-form strings named at the emission site.
"""

from __future__ import annotations

import asyncio
import time
from typing import Awaitable, Callable, Dict, List, Optional

_active: Optional["TraceCollector"] = None


def tp(kind: str, **fields) -> None:
    if _active is not None:
        _active._emit(kind, fields)


async def atp(kind: str, **fields) -> None:
    """Trace point that is also a nemesis injection site."""
    c = _active
    if c is None:
        return
    c._emit(kind, fields)
    inj = c._nemesis.get(kind)
    if inj is not None:
        r = inj(fields)
        if asyncio.iscoroutine(r) or isinstance(r, Awaitable):
            await r


class TraceCollector:
    def __init__(self):
        self.events: List[Dict] = []
        self._nemesis: Dict[str, Callable] = {}

    # -- lifecycle ---------------------------------------------------------
    def __enter__(self):
        global _active
        if _active is not None:
            raise RuntimeError("a TraceCollector is already active")
        _active = self
        return self

    def __exit__(self, *exc):
        global _active
        _active = None

    def _emit(self, kind: str, fields: Dict) -> None:
        self.events.append(
            {"kind": kind, "at": time.monotonic(), **fields}
        )

    # -- nemesis -----------------------------------------------------------
    def inject_delay(self, kind: str, delay: float) -> None:
        """Sleep `delay` whenever `atp(kind)` fires — widens the race
        window at that point (snabbkaffe scheduling nemesis)."""
        self._nemesis[kind] = lambda _f: asyncio.sleep(delay)

    def inject(self, kind: str, fn: Callable[[Dict], Optional[Awaitable]]):
        """Custom injection: fn(fields) may return an awaitable."""
        self._nemesis[kind] = fn

    # -- assertions --------------------------------------------------------
    def projection(self, kind: str) -> List[Dict]:
        return [e for e in self.events if e["kind"] == kind]

    def causally_ordered(self, a: str, b: str, key: str) -> bool:
        """Every `b` event must be preceded by an `a` event whose `key`
        field matches (the ?causality assertion)."""
        seen = set()
        for e in self.events:
            if e["kind"] == a:
                seen.add(e.get(key))
            elif e["kind"] == b and e.get(key) not in seen:
                return False
        return True

    def pairs(self, a: str, b: str, key: str) -> bool:
        """One-to-one: every `a` is eventually followed by exactly one
        matching `b`, and no unmatched `b` exists."""
        opened: Dict = {}
        for e in self.events:
            if e["kind"] == a:
                opened[e.get(key)] = opened.get(e.get(key), 0) + 1
            elif e["kind"] == b:
                k = e.get(key)
                if opened.get(k, 0) <= 0:
                    return False
                opened[k] -= 1
        return all(v == 0 for v in opened.values())
