"""Monotonic snowflake-style message ids (reference: emqx_guid.erl)."""

from __future__ import annotations

import itertools
import os
import time

_node_bits = (os.getpid() & 0x3FF) << 22
_counter = itertools.count()


def next_guid() -> int:
    """53-ish bit id: ms timestamp | pid slice | sequence."""
    return (
        (int(time.time() * 1000) & 0x1FFFFFFFFFF) << 32
        | _node_bits
        | (next(_counter) & 0x3FFFFF)
    )
