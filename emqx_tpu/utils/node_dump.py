"""Node state dump for support/debugging.

Parity: apps/emqx/src/emqx_node_dump.erl + bin/node_dump — a one-call
snapshot of everything an operator attaches to a support ticket: config
(secrets redacted), broker/session/route gauges, component statuses,
alarms, metrics, and versions. Exposed at ``GET /api/v5/node_dump`` and
``emqx_tpu_ctl node_dump``.
"""

from __future__ import annotations

import sys
import time
from typing import Dict

# exact-ish credential field names — NOT bare "key", which would also hide
# TLS key-file PATHS the dump exists to show
REDACT_KEYS = (
    "password", "passwd", "secret", "jwt_secret", "token", "api_key", "cookie"
)
# subtrees whose dict VALUES are secrets keyed by arbitrary names
REDACT_VALUE_MAPS = (("dashboard", "admins"), ("psk", "identities"))


def _redact(obj, path=()):
    if isinstance(obj, dict):
        if path in REDACT_VALUE_MAPS:
            return {k: "*****" for k in obj}
        return {
            k: (
                "*****"
                if k.lower() in REDACT_KEYS and v
                else _redact(v, path + (k,))
            )
            for k, v in obj.items()
        }
    if isinstance(obj, list):
        return [_redact(v, path) for v in obj]
    return obj


def collect(app) -> Dict:
    from emqx_tpu import __version__
    from emqx_tpu.config.schema import to_dict

    broker = app.broker
    dump: Dict = {
        "at": time.time(),
        "versions": {
            "emqx_tpu": __version__,
            "python": sys.version.split()[0],
        },
        "config": _redact(to_dict(app.config)),
        "broker": {
            "connections": app.cm.channel_count(),
            "detached_sessions": app.cm.detached_count(),
            "subscriptions": broker.subscription_count(),
            "routes": len(broker.router),
            "shared_groups": broker.shared.count(),
            "retained": len(app.retainer),
            "route_index": {
                "filters": len(broker.router.index),
                "residual": broker.router.index.residual_count,
                "shapes": broker.router.index.shapes.num_active_shapes(),
            },
        },
        "metrics": broker.metrics.snapshot(),
        "alarms": app.alarms.list(None),
        "components": {
            "gateways": app.gateways.list() if app.gateways else [],
            "bridges": app.bridges.list() if app.bridges else [],
            "plugins": app.plugins.list() if app.plugins else [],
            "exhook": app.exhook.info() if app.exhook else [],
            "license": app.license.license.info(),
        },
        "rules": [
            {"id": r.id, "enabled": r.enabled, "metrics": r.metrics.as_dict()}
            for r in app.rule_engine.rules()
        ],
    }
    # only report devices when JAX is ALREADY initialized — first-touch
    # backend init can take seconds and this runs on the serving loop
    if "jax" in sys.modules:
        try:
            dump["devices"] = [str(d) for d in sys.modules["jax"].devices()]
        except Exception as e:
            dump["devices"] = [f"unavailable: {e}"]
    else:
        dump["devices"] = ["jax not initialized"]
    return dump
