"""Durable broker state (reference: mnesia disc tables — retained msgs,
delayed msgs, banned, persistent sessions; SURVEY.md §5.4(iii))."""
