"""Namespace -> JSON file store with atomic replace.

The durability substrate standing in for the reference's mnesia disc copies
(disc_copies tables hold retained/delayed/banned/persistent-session state;
SURVEY.md §5.4). Writes go to a temp file then rename() — crash-atomic on
POSIX — so a partially written snapshot can never shadow the previous good
one. JSON keeps snapshots debuggable (`emqx_node_dump` spirit); payload
bytes are base64 in the codec layer.
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Any, Dict, Optional


class FileKv:
    def __init__(self, data_dir: str, fsync: bool = False):
        self.data_dir = data_dir
        self.fsync = fsync
        os.makedirs(data_dir, exist_ok=True)

    def _path(self, namespace: str) -> str:
        safe = "".join(
            c if c.isalnum() or c in "-_." else "_" for c in namespace
        )
        return os.path.join(self.data_dir, f"{safe}.json")

    def read(self, namespace: str) -> Optional[Dict[str, Any]]:
        try:
            with open(self._path(namespace), encoding="utf-8") as f:
                return json.load(f)
        except FileNotFoundError:
            return None
        except (OSError, json.JSONDecodeError):
            # corrupt/unreadable snapshot: behave like a cold start rather
            # than refusing to boot (mnesia would recover from the log; we
            # degrade to empty)
            return None

    def write(self, namespace: str, obj: Dict[str, Any]) -> None:
        path = self._path(namespace)
        fd, tmp = tempfile.mkstemp(
            dir=self.data_dir, prefix=".tmp_", suffix=".json"
        )
        try:
            with os.fdopen(fd, "w", encoding="utf-8") as f:
                json.dump(obj, f, separators=(",", ":"))
                if self.fsync:
                    f.flush()
                    os.fsync(f.fileno())
            os.replace(tmp, path)
            if self.fsync:
                # the rename is only crash-durable once the directory
                # entry itself is synced
                dfd = os.open(self.data_dir, os.O_RDONLY)
                try:
                    os.fsync(dfd)
                finally:
                    os.close(dfd)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def delete(self, namespace: str) -> bool:
        try:
            os.unlink(self._path(namespace))
            return True
        except OSError:
            return False
