"""JSON codecs for durable records (Message, SubOpts, Session).

The persistence key scheme mirrors the reference's persistent-session
records (apps/emqx/src/emqx_persistent_session.erl:63-77: session,
subscriptions, undelivered messages) collapsed into one snapshot per
session.
"""

from __future__ import annotations

import base64
from typing import Dict

from emqx_tpu.broker.message import Message
from emqx_tpu.mqtt import packet as pkt


def _enc(v):
    """Lossless JSON encoding for property/header values, including MQTT5
    list-valued properties (User-Property pair lists,
    Subscription-Identifier lists). Tuples come back as lists, which the
    frame serializer unpacks identically."""
    if isinstance(v, bytes):
        return {"__b64__": base64.b64encode(v).decode()}
    if isinstance(v, (list, tuple)):
        return {"__list__": [_enc(x) for x in v]}
    if isinstance(v, dict):
        return {"__map__": {str(k): _enc(x) for k, x in v.items()}}
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return str(v)


def _dec(v):
    if isinstance(v, dict):
        if "__b64__" in v:
            return base64.b64decode(v["__b64__"])
        if "__list__" in v:
            return [_dec(x) for x in v["__list__"]]
        if "__map__" in v:
            return {k: _dec(x) for k, x in v["__map__"].items()}
    return v


def _jsonable(d: Dict) -> Dict:
    return {str(k): _enc(v) for k, v in d.items()}


def _unjsonable(d: Dict) -> Dict:
    return {k: _dec(v) for k, v in d.items()}


def msg_to_json(m: Message) -> Dict:
    return {
        "topic": m.topic,
        "payload": base64.b64encode(m.payload).decode(),
        "qos": m.qos,
        "retain": m.retain,
        "dup": m.dup,
        "from_client": m.from_client,
        "from_username": m.from_username,
        "mid": m.mid,
        "headers": _jsonable(m.headers),
        "properties": _jsonable(m.properties),
        "timestamp": m.timestamp,
    }


def msg_from_json(d: Dict) -> Message:
    return Message(
        topic=d["topic"],
        payload=base64.b64decode(d["payload"]),
        qos=d.get("qos", 0),
        retain=d.get("retain", False),
        dup=d.get("dup", False),
        from_client=d.get("from_client", ""),
        from_username=d.get("from_username"),
        mid=d.get("mid", 0),
        headers=_unjsonable(d.get("headers", {})),
        properties=_unjsonable(d.get("properties", {})),
        timestamp=d.get("timestamp", 0.0),
    )


def subopts_to_json(o: pkt.SubOpts) -> Dict:
    return {
        "qos": o.qos,
        "no_local": o.no_local,
        "retain_as_published": o.retain_as_published,
        "retain_handling": o.retain_handling,
    }


def subopts_from_json(d: Dict) -> pkt.SubOpts:
    return pkt.SubOpts(
        qos=d.get("qos", 0),
        no_local=d.get("no_local", False),
        retain_as_published=d.get("retain_as_published", False),
        retain_handling=d.get("retain_handling", 0),
    )


def session_to_json(sess) -> Dict:
    """Snapshot: metadata + subscriptions + pending (mqueue/inflight)."""
    import time as _time

    _mono = _time.monotonic()
    inflight = []
    for pid, e in sess.inflight.items():
        inflight.append(
            {
                "pid": pid,
                "phase": e.phase,
                # inflight stamps are monotonic-clock readings, which are
                # meaningless in another process: persist the AGE and
                # rebase at restore (broker/inflight.py clock discipline)
                "age": round(max(0.0, _mono - e.ts), 3),
                "msg": msg_to_json(e.msg) if e.msg is not None else None,
            }
        )
    return {
        "client_id": sess.client_id,
        "created_at": sess.created_at,
        "expiry_interval": sess.config.expiry_interval,
        "next_pid": sess._next_pid,
        "subscriptions": {
            f: subopts_to_json(o) for f, o in sess.subscriptions.items()
        },
        "mqueue": [msg_to_json(m) for m in sess.mqueue.peek_all()],
        "inflight": inflight,
        "awaiting_rel": list(sess.awaiting_rel),
    }


def session_from_json(d: Dict, config, store=None) -> "object":
    import time as _time

    from emqx_tpu.broker.session import Session

    sess = Session(d["client_id"], config, store=store)
    sess.created_at = d.get("created_at", sess.created_at)
    sess.config.expiry_interval = d.get(
        "expiry_interval", sess.config.expiry_interval
    )
    sess._next_pid = d.get("next_pid", 1)
    sess.subscriptions = {
        f: subopts_from_json(o)
        for f, o in d.get("subscriptions", {}).items()
    }
    for m in d.get("mqueue", []):
        sess.mqueue.in_(msg_from_json(m))
    _mono = _time.monotonic()
    for e in d.get("inflight", []):
        msg = msg_from_json(e["msg"]) if e.get("msg") else None
        sess.inflight.insert(e["pid"], msg, phase=e.get("phase", "publish"))
        # rebase the persisted AGE onto this process's monotonic clock;
        # legacy snapshots carried raw stamps ("ts") from another clock —
        # treat those as age 0 (fresh) rather than mass-expiring them
        sess.inflight.get(e["pid"]).ts = _mono - e.get("age", 0.0)
    # fresh timestamp: the receiver-side QoS2 dedup window restarts at
    # resume instead of being instantly expired by the first tick
    for pid in d.get("awaiting_rel", []):
        sess.awaiting_rel[int(pid)] = _mono
    return sess
