"""Append-only message WAL: the between-snapshot durability delta.

Parity: the reference persists every message for persistent sessions at publish
time and tracks per-session delivered/undelivered markers
(emqx_persistent_session.erl:63-77, persist at emqx_broker.erl:213). This
stack keeps session *state* in periodic snapshots (persistent_session.py)
and closes the crash window between snapshots with this WAL:

- every message banked for a detached persistent session appends one
  JSONL record (optionally fsynced);
- a snapshot flush truncates the log (the snapshot now owns the state);
- restore = snapshot + replay of the post-snapshot WAL suffix.

Crash between a resumed client consuming a message and the next snapshot
re-delivers it (at-least-once, QoS1 semantics — same guarantee the
reference provides). Records are self-describing JSON lines; a torn tail
line (crash mid-append) is dropped on replay.
"""

from __future__ import annotations

import json
import os
from typing import Callable, Iterator, Optional, Tuple


class MessageWal:
    def __init__(self, path: str, fsync: bool = False):
        self.path = path
        self.fsync = fsync
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, "a", encoding="utf-8")

    def append(self, client_id: str, msg_json: dict) -> None:
        rec = json.dumps(
            {"cid": client_id, "msg": msg_json}, separators=(",", ":")
        )
        self._f.write(rec + "\n")
        self._f.flush()
        if self.fsync:
            os.fsync(self._f.fileno())

    def truncate(self) -> None:
        """Snapshot taken: the log's contents are now owned by it."""
        self._f.close()
        self._f = open(self.path, "w", encoding="utf-8")
        self._f.flush()
        if self.fsync:
            os.fsync(self._f.fileno())

    def replay(self) -> Iterator[Tuple[str, dict]]:
        """Yield (client_id, msg_json) records; tolerates a torn tail."""
        try:
            with open(self.path, encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                        yield rec["cid"], rec["msg"]
                    except (ValueError, KeyError):
                        return  # torn/corrupt tail: stop replay here
        except FileNotFoundError:
            return

    def close(self) -> None:
        self._f.close()
