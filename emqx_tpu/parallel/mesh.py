"""Mesh construction + the sharded distributed route step.

See package docstring for the axis semantics (dp = topic batch, tp =
subscriber bitmap lanes). The distributed step is `jax.shard_map` over the
mesh with XLA psum collectives for the global stats — the TPU-native
replacement for the reference's gen_rpc forwards + counter aggregation
(emqx_broker.erl:278-293, emqx_metrics.erl).
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from emqx_tpu.models.router_model import (
    compact_fanout_slots,
    route_step_impl,
    shape_route_step_impl,
)
from emqx_tpu.ops.contract import device_contract

# -- shard_map compat -------------------------------------------------------
# jax moved shard_map from jax.experimental to the top level around 0.4.35;
# this image's 0.4.37 only ships the experimental spelling. Resolve once at
# import; HAS_SHARD_MAP lets callers (and mesh tests) skip fast on images
# with neither instead of stalling or dying on AttributeError mid-dispatch.
_shard_map = getattr(jax, "shard_map", None)
if _shard_map is None:
    try:
        from jax.experimental.shard_map import shard_map as _shard_map
    except Exception:  # pragma: no cover - images without any shard_map
        _shard_map = None

HAS_SHARD_MAP = _shard_map is not None

# built mesh step programs, registered for the device-watch compile
# probe (observe/device_watch.py): lru_cache hides its values, so the
# builders append their jitted fns here (bounded by the caches' maxsize)
_BUILT_PROGRAMS: list = []


def _register_built(fn):
    _BUILT_PROGRAMS.append(fn)
    return fn


def jit_cache_size() -> int:
    """Summed jit-cache entries across every built mesh step program —
    the mesh-path contribution to `device.compile.cache_size`."""
    n = 0
    for fn in _BUILT_PROGRAMS:
        cs = getattr(fn, "_cache_size", None)
        if cs is None:
            continue
        try:
            n += int(cs())
        except Exception:
            continue
    return n


def shard_map(*args, **kwargs):
    """`jax.shard_map` under either spelling; RuntimeError when absent."""
    if _shard_map is None:
        raise RuntimeError(
            "this jax installation provides neither jax.shard_map nor "
            "jax.experimental.shard_map.shard_map; mesh serving is "
            "unavailable (check emqx_tpu.parallel.mesh.HAS_SHARD_MAP)"
        )
    return _shard_map(*args, **kwargs)


def make_mesh(
    n_devices: Optional[int] = None,
    tp: Optional[int] = None,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Factor the first n devices into a ('dp', 'tp') mesh.

    tp defaults to 2 when n is even and > 1, else 1 — subscriber-lane
    sharding wants
    fewer, larger slices so each chip keeps big contiguous bitmap rows
    (HBM-bandwidth friendly), while dp soaks up the rest of the chips for
    batch throughput.
    """
    devs = list(devices if devices is not None else jax.devices())
    n = len(devs) if n_devices is None else n_devices
    if n > len(devs):
        raise ValueError(
            f"requested {n} devices but only {len(devs)} are available"
        )
    devs = devs[:n]
    if tp is None:
        tp = 2 if n % 2 == 0 and n > 1 else 1
    assert n % tp == 0, (n, tp)
    dp = n // tp
    arr = np.array(devs).reshape(dp, tp)
    return Mesh(arr, axis_names=("dp", "tp"))


# canonical output shardings + stats reduction, shared by both engines
def _out_specs(with_groups: bool = False, with_slots: bool = False,
               dense_bitmaps: bool = True):
    specs = {
        "matched": P("dp", None),
        "mcount": P("dp"),
        "flags": P("dp"),
        # the CSR engine emits NO bitmap matrix: None here mirrors the
        # output dict's None leaf (empty pytree node on both sides)
        "bitmaps": P("dp", "tp") if dense_bitmaps else None,
        "stats": {"routed": P(), "matches": P(), "fanout_bits": P()},
    }
    if with_groups:
        specs["pick_gid"] = P("dp", None)
        specs["pick_idx"] = P("dp", None)
    if with_slots:
        # per-tp-shard compactions concatenate on the minor axis: the
        # global array is [B, kslot * tp] with -1 holes between shard
        # segments (the host filters >= 0, it never slices by count)
        specs["slots"] = P("dp", "tp")
        specs["slot_count"] = P("dp")
        specs["overflow"] = P("dp")
    return specs


def _sem_rules_local(out, sem_tables, qv, rfeats, rvalid, sem_topk,
                     rule_progs):
    """Per-shard semantic union + compiled-rule masks, shared by both
    serving builders. Runs INSIDE shard_map: `sem_tables` is this tp
    shard's slice of the entry axis (slot-owner sharding — winner slots
    are global ids, so the union lands before the 'tp' concat with no
    rebase); the qualifying counts psum over 'tp'. Rule feature rows
    ride the 'dp' batch shards and are tp-replicated, like `matched`."""
    if sem_tables is not None:
        from emqx_tpu.ops.semantic_table import (
            semantic_match_step,
            union_semantic_slots,
        )

        sem_slots, sem_count = semantic_match_step(
            sem_tables, qv, out["matched"], sem_topk
        )
        out["slots"] = union_semantic_slots(out["slots"], sem_slots)
        out["sem_count"] = jax.lax.psum(sem_count, "tp")
    if rule_progs:
        from emqx_tpu.rules.compile import eval_rule_masks

        out["rule_masks"] = eval_rule_masks(rule_progs, rfeats, rvalid)


def _reduce_stats(out, with_groups: bool = False):
    """routed/matches are identical across tp replicas: reduce over dp
    only. fanout_bits is partial per lane slice: reduce over both axes."""
    stats = out["stats"]
    out["stats"] = {
        "routed": jax.lax.psum(stats["routed"], "dp"),
        "matches": jax.lax.psum(stats["matches"], "dp"),
        "fanout_bits": jax.lax.psum(stats["fanout_bits"], ("dp", "tp")),
    }
    if not with_groups:
        out.pop("pick_gid", None)
        out.pop("pick_idx", None)
    return out


@device_contract(
    "dist_step",
    kind="builder",
    # the ONLY cross-chip traffic the NFA serving step may compile to:
    # the stats psums over ('dp','tp'). A new collective here is a new
    # ICI dependency and must be a deliberate contract change.
    collectives=("psum",),
)
@lru_cache(maxsize=32)
def _dist_step_fn(
    mesh: Mesh,
    table_keys: tuple,
    salt: int,
    max_levels: int,
    frontier: int,
    max_matches: int,
    probes: int,
):
    """Build (once per mesh/config) the jitted sharded route step.

    Cached so repeated dist_route_step calls reuse the compiled program
    instead of re-tracing a fresh shard_map closure per batch.
    """

    def local_step(tables, sub_bitmaps, bytes_mat, lengths):
        out = route_step_impl(
            tables,
            sub_bitmaps,
            bytes_mat,
            lengths,
            salt=salt,
            max_levels=max_levels,
            frontier=frontier,
            max_matches=max_matches,
            probes=probes,
        )
        return _reduce_stats(out)

    table_specs = {k: P() for k in table_keys}
    fn = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(table_specs, P(None, "tp"), P("dp", None), P("dp")),
        out_specs=_out_specs(),
    )
    return _register_built(jax.jit(fn))


def dist_route_step(
    mesh: Mesh,
    tables: Dict,
    sub_bitmaps,
    bytes_mat,
    lengths,
    *,
    salt: int,
    max_levels: int = 16,
    frontier: int = 32,
    max_matches: int = 64,
    probes: int = 8,
):
    """Run the full route step SPMD over the mesh.

    Sharding layout:
      - NFA tables: replicated (read-mostly; updates are host-pushed deltas)
      - sub_bitmaps [Fcap, W]: sharded on W over 'tp' (each chip owns a
        subscriber-lane slice — the topic-shard fan-out analog)
      - bytes_mat/lengths [B, ...]: sharded on B over 'dp'
      - outputs: matched/mcount/flags sharded over 'dp'; bitmaps sharded
        over ('dp','tp'); stats psum'd to replicated scalars
    """
    fn = _dist_step_fn(
        mesh,
        tuple(sorted(tables)),
        salt,
        max_levels,
        frontier,
        max_matches,
        probes,
    )
    import time

    from emqx_tpu.broker.metrics import default_metrics
    from emqx_tpu.observe.profiler import record_kernel_launch

    t0 = time.perf_counter()
    out = fn(tables, sub_bitmaps, bytes_mat, lengths)
    record_kernel_launch(
        default_metrics, ("dist_step",), time.perf_counter() - t0
    )
    return out


@device_contract(
    "dist_shape_step",
    kind="builder",
    # stats psum over ('dp','tp') + the kslot>0 per-shard compaction's
    # lane-offset rebase (axis_index) and count/overflow psum over 'tp'
    collectives=("psum", "axis_index"),
    out_bounds={
        # per-shard compaction concatenates over tp: [B, kslot * tp]
        "slots": lambda cfg: (
            cfg["B"] * cfg["kslot"] * cfg.get("tp", 1) * 4
        ),
        "slot_count": lambda cfg: cfg["B"] * 4,
    },
)
@lru_cache(maxsize=32)
def _dist_shape_step_fn(
    mesh: Mesh,
    shape_keys: tuple,
    nfa_keys: Optional[tuple],
    group_keys: Optional[tuple],
    share_strategy: int,
    m_active: int,
    salt: int,
    max_levels: int,
    frontier: int,
    max_matches: int,
    probes: int,
    kslot: int = 0,
    donate: bool = False,
    sub_keys: Optional[tuple] = None,
    kg: int = 0,
    sem_keys: Optional[tuple] = None,
    sem_topk: int = 0,
    rule_progs: tuple = (),
):
    """The SERVING engine (shape index + residual NFA + fan-out + $share
    pick) sharded over the mesh — same layout as `_dist_step_fn`, all
    table sets replicated; per-topic pick entropy (client/topic hashes,
    rand) rides the 'dp' shards with the batch, and round_robin's
    occurrence index is made globally exact via an all_gather histogram
    over 'dp' (share_pick_device dp_axis).

    ``kslot > 0`` adds the sparse fan-out compaction PER tp SHARD: each
    shard compacts its own bitmap lanes (local slot ids rebased by the
    shard's lane offset, so they are the same global slot ids the host
    uses), the per-shard slot lists concatenate over 'tp' in the output
    (-1 holes between segments), and count/overflow psum/OR over 'tp'.
    A row overflows when ANY shard's local fan-out exceeds kslot —
    conservative, and the host's dense fallback keeps it correct.

    ``sub_keys`` set = the CSR subscriber table (ops/csr_table.py):
    its arrays shard their leading slot-owner axis over 'tp'
    (`csr_placement`), each shard's `sparse_fanout_slots` emits GLOBAL
    slot ids directly (no lane rebase), and only the count psum /
    overflow OR run here. Same output contract either way.

    ``sem_keys`` set = the semantic table (ops/semantic_table.py):
    entries shard their leading slot-owner axis over 'tp'
    (`semantic_placement`, the CSR regime), each shard's
    `semantic_match_step` matmul answers its slice of the embedding
    filters against the dp-sharded query batch, and the winner slots
    (GLOBAL ids) union into the shard's compact rows before the 'tp'
    concat; the qualifying counts psum over 'tp'. ``rule_progs``
    evaluates the compiled WHERE masks over the dp-sharded feature
    batch (tp-replicated, like `matched`)."""
    with_nfa = nfa_keys is not None
    with_groups = group_keys is not None
    sparse = sub_keys is not None
    with_sem = sem_keys is not None

    def local_step(
        shape_tables, nfa_tables, group_tables, ch, th, rand,
        sub_bitmaps, bytes_mat, lengths, sem_tables, qv, rfeats, rvalid,
    ):
        out = shape_route_step_impl(
            shape_tables,
            nfa_tables,
            sub_bitmaps,
            bytes_mat,
            lengths,
            group_tables,
            ch,
            th,
            rand,
            m_active=m_active,
            with_nfa=with_nfa,
            salt=salt,
            max_levels=max_levels,
            frontier=frontier,
            max_matches=max_matches,
            probes=probes,
            with_groups=with_groups,
            share_strategy=share_strategy,
            dp_axis="dp" if with_groups else None,
            kslot=kslot if sparse else 0,
            kg=kg,
        )
        if kslot:
            if sparse:
                # per-shard CSR compaction already ran inside the impl;
                # reduce the per-shard counts/overflow over 'tp'
                out["slot_count"] = jax.lax.psum(out["slot_count"], "tp")
                out["overflow"] = (
                    jax.lax.psum(
                        out["overflow"].astype(jnp.int32), "tp"
                    )
                    > 0
                )
            else:
                slots, count, over = compact_fanout_slots(
                    out["bitmaps"], kslot
                )
                w_local = out["bitmaps"].shape[1]
                off = jax.lax.axis_index("tp").astype(jnp.int32) * (
                    w_local * 32
                )
                out["slots"] = jnp.where(slots >= 0, slots + off, -1)
                out["slot_count"] = jax.lax.psum(count, "tp")
                out["overflow"] = (
                    jax.lax.psum(over.astype(jnp.int32), "tp") > 0
                )
        _sem_rules_local(
            out, sem_tables, qv, rfeats, rvalid, sem_topk, rule_progs
        )
        return _reduce_stats(out, with_groups)

    shape_specs = {k: P() for k in shape_keys}
    nfa_specs = {k: P() for k in nfa_keys} if with_nfa else None
    group_specs = {k: P() for k in group_keys} if with_groups else None
    per_topic = P("dp") if with_groups else P()
    sub_spec = (
        {k: P("tp", None) for k in sub_keys}
        if sparse
        else P(None, "tp")
    )
    sem_specs = {k: P("tp") for k in sem_keys} if with_sem else None
    out_specs = _out_specs(
        with_groups, with_slots=kslot > 0,
        dense_bitmaps=not sparse,
    )
    if with_sem:
        out_specs["sem_count"] = P("dp")
    if rule_progs:
        out_specs["rule_masks"] = P(None, "dp")
    fn = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(
            shape_specs, nfa_specs, group_specs,
            per_topic, per_topic, per_topic,
            sub_spec, P("dp", None), P("dp"),
            sem_specs, P("dp", None), P("dp", None), P("dp", None),
        ),
        out_specs=out_specs,
    )
    # ``donate``: recycle the per-batch lengths buffer (aliases the
    # [B]-shaped int32 outputs under the same 'dp' sharding) — the mesh
    # twin of shape_route_step_donated; tables/bitmaps never donate.
    jit_kw = {"donate_argnums": (8,)} if donate else {}
    return _register_built(jax.jit(fn, **jit_kw))


@device_contract(
    "dist_fused_step",
    kind="builder",
    # the fused serving builder inherits dist_shape_step's ICI budget:
    # stats psums + the per-shard compaction's lane-offset rebase. The
    # retained half is shard-local by construction (chunk rows ride
    # 'dp'; its tables are replicated) — a collective appearing there
    # is a contract violation, not a tuning knob.
    collectives=("psum", "axis_index"),
    out_bounds={
        "slots": lambda cfg: (
            cfg["B"] * cfg["kslot"] * cfg.get("tp", 1) * 4
        ),
        "slot_count": lambda cfg: cfg["B"] * 4,
    },
)
@lru_cache(maxsize=32)
def _dist_fused_step_fn(
    mesh: Mesh,
    shape_keys: tuple,
    nfa_keys: Optional[tuple],
    group_keys: Optional[tuple],
    ret_shape_keys: tuple,
    ret_nfa_keys: Optional[tuple],
    share_strategy: int,
    m_active: int,
    salt: int,
    max_levels: int,
    frontier: int,
    max_matches: int,
    probes: int,
    kslot: int,
    ret_m_active: int,
    ret_with_nfa: bool,
    ret_salt: int,
    ret_max_levels: int,
    ret_narrow: bool,
    donate: bool = False,
    sub_keys: Optional[tuple] = None,
    kg: int = 0,
    sem_keys: Optional[tuple] = None,
    sem_topk: int = 0,
    rule_progs: tuple = (),
):
    """`_dist_shape_step_fn` + the retained-replay half fused into the
    SAME sharded program (the mesh analog of
    `fused_route_retained_step`): a wildcard-subscribe storm's filter
    tables ride replicated like the match tables, and the retained-topic
    chunk shards its ROWS over 'dp' — each dp slice matches its share of
    the stored topics, so the replay scan scales with the mesh instead
    of serializing on one chip. The [chunk, lanes] match matrix
    concatenates over 'dp' in the output and rides the same coalesced
    readback as the route outputs.

    ``donate``: donate the per-batch `lengths` buffer (aliases the
    [B]-shaped int32 outputs, same 'dp' sharding) — the mesh-path twin
    of `shape_route_step_donated`."""
    from emqx_tpu.models.router_model import shape_route_step_impl

    with_nfa = nfa_keys is not None
    with_groups = group_keys is not None
    sparse = sub_keys is not None
    with_sem = sem_keys is not None

    def local_step(
        shape_tables, nfa_tables, group_tables, ch, th, rand,
        sub_bitmaps, bytes_mat, lengths,
        ret_shape_tables, ret_nfa_tables, ret_bytes,
        sem_tables, qv, rfeats, rvalid,
    ):
        out = shape_route_step_impl(
            shape_tables,
            nfa_tables,
            sub_bitmaps,
            bytes_mat,
            lengths,
            group_tables,
            ch,
            th,
            rand,
            m_active=m_active,
            with_nfa=with_nfa,
            salt=salt,
            max_levels=max_levels,
            frontier=frontier,
            max_matches=max_matches,
            probes=probes,
            with_groups=with_groups,
            share_strategy=share_strategy,
            dp_axis="dp" if with_groups else None,
            kslot=kslot if sparse else 0,
            kg=kg,
        )
        if kslot:
            if sparse:
                out["slot_count"] = jax.lax.psum(out["slot_count"], "tp")
                out["overflow"] = (
                    jax.lax.psum(
                        out["overflow"].astype(jnp.int32), "tp"
                    )
                    > 0
                )
            else:
                slots, count, over = compact_fanout_slots(
                    out["bitmaps"], kslot
                )
                w_local = out["bitmaps"].shape[1]
                off = jax.lax.axis_index("tp").astype(jnp.int32) * (
                    w_local * 32
                )
                out["slots"] = jnp.where(slots >= 0, slots + off, -1)
                out["slot_count"] = jax.lax.psum(count, "tp")
                out["overflow"] = (
                    jax.lax.psum(over.astype(jnp.int32), "tp") > 0
                )
        _sem_rules_local(
            out, sem_tables, qv, rfeats, rvalid, sem_topk, rule_progs
        )
        # retained half: bit-identical to fused_route_retained_step's,
        # on this shard's slice of the chunk rows (lengths derive
        # on-device — retained topics cannot contain NUL)
        rl = jnp.sum((ret_bytes != 0).astype(jnp.int32), axis=1)
        rout = shape_route_step_impl(
            ret_shape_tables,
            ret_nfa_tables,
            None,
            ret_bytes,
            rl,
            m_active=ret_m_active,
            with_nfa=ret_with_nfa,
            salt=ret_salt,
            max_levels=ret_max_levels,
        )
        rm = rout["matched"]
        out["retained"] = rm.astype(jnp.int16) if ret_narrow else rm
        return _reduce_stats(out, with_groups)

    shape_specs = {k: P() for k in shape_keys}
    nfa_specs = {k: P() for k in nfa_keys} if with_nfa else None
    group_specs = {k: P() for k in group_keys} if with_groups else None
    ret_shape_specs = {k: P() for k in ret_shape_keys}
    ret_nfa_specs = (
        {k: P() for k in ret_nfa_keys} if ret_nfa_keys is not None else None
    )
    per_topic = P("dp") if with_groups else P()
    out_specs = _out_specs(
        with_groups, with_slots=kslot > 0, dense_bitmaps=not sparse
    )
    out_specs["retained"] = P("dp", None)
    if with_sem:
        out_specs["sem_count"] = P("dp")
    if rule_progs:
        out_specs["rule_masks"] = P(None, "dp")
    sub_spec = (
        {k: P("tp", None) for k in sub_keys}
        if sparse
        else P(None, "tp")
    )
    sem_specs = {k: P("tp") for k in sem_keys} if with_sem else None
    fn = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(
            shape_specs, nfa_specs, group_specs,
            per_topic, per_topic, per_topic,
            sub_spec, P("dp", None), P("dp"),
            ret_shape_specs, ret_nfa_specs, P("dp", None),
            sem_specs, P("dp", None), P("dp", None), P("dp", None),
        ),
        out_specs=out_specs,
    )
    jit_kw = {"donate_argnums": (8,)} if donate else {}
    return _register_built(jax.jit(fn, **jit_kw))


# Second registry entry for the serving builder traced with the CSR
# subscriber table: the sparse mesh program replaces the dense per-shard
# compaction (which needs the axis_index lane rebase) with the in-impl
# CSR gather — its ICI budget is the stats/count psums ONLY. A lane
# rebase appearing in the sparse trace is a contract violation.
# Registry entry for the serving builder traced WITH a semantic table:
# the semantic union adds the per-shard similarity matmul + top-k and
# one more count psum to the program; the dense per-shard compaction's
# lane rebase (axis_index) stays. Its ICI budget is pinned here.
device_contract(
    "sem_dist_shape_step",
    kind="builder",
    collectives=("psum", "axis_index"),
    out_bounds={
        "slots": lambda cfg: (
            cfg["B"] * cfg["kslot"] * 2 * cfg.get("tp", 1) * 4
        ),
        "slot_count": lambda cfg: cfg["B"] * 4,
        "sem_count": lambda cfg: cfg["B"] * 4,
    },
)(_dist_shape_step_fn)

device_contract(
    "sparse_dist_shape_step",
    kind="builder",
    collectives=("psum",),
    out_bounds={
        "slots": lambda cfg: (
            cfg["B"] * cfg["kslot"] * cfg.get("tp", 1) * 4
        ),
        "slot_count": lambda cfg: cfg["B"] * 4,
    },
)(_dist_shape_step_fn)


def dist_fused_route_step(
    mesh: Mesh,
    shape_tables: Dict,
    nfa_tables: Optional[Dict],
    sub_bitmaps,
    bytes_mat,
    lengths,
    ret_shape_tables: Dict,
    ret_nfa_tables: Optional[Dict],
    ret_bytes,
    group_tables: Optional[Dict] = None,
    client_hash=None,
    topic_hash=None,
    rand=None,
    sem_tables: Optional[Dict] = None,
    q_vecs=None,
    rule_feats=None,
    rule_valid=None,
    *,
    m_active: int,
    salt: int,
    ret_m_active: int,
    ret_with_nfa: bool,
    ret_salt: int,
    ret_max_levels: int,
    ret_narrow: bool,
    max_levels: int = 16,
    frontier: int = 32,
    max_matches: int = 64,
    probes: int = 8,
    share_strategy: int = 0,
    kslot: int = 0,
    donate: bool = False,
    kg: int = 0,
    sem_topk: int = 0,
    rule_progs: tuple = (),
):
    """Distributed serving step WITH a fused retained-replay storm —
    the mesh engine `MeshServingRouter.route_prepared` launches when a
    prepared `StormJob` rides the batch. Sharding as in
    `dist_shape_route_step`, plus: storm filter tables replicated,
    retained chunk rows on 'dp', the match matrix back on ('dp', None)."""
    fn = _dist_fused_step_fn(
        mesh,
        tuple(sorted(shape_tables)),
        tuple(sorted(nfa_tables)) if nfa_tables is not None else None,
        tuple(sorted(group_tables)) if group_tables is not None else None,
        tuple(sorted(ret_shape_tables)),
        tuple(sorted(ret_nfa_tables))
        if ret_nfa_tables is not None
        else None,
        share_strategy,
        m_active,
        salt,
        max_levels,
        frontier,
        max_matches,
        probes,
        kslot,
        ret_m_active,
        ret_with_nfa,
        ret_salt,
        ret_max_levels,
        ret_narrow,
        donate,
        tuple(sorted(sub_bitmaps))
        if isinstance(sub_bitmaps, dict)
        else None,
        kg,
        tuple(sorted(sem_tables)) if sem_tables is not None else None,
        sem_topk,
        rule_progs,
    )
    return fn(
        shape_tables, nfa_tables, group_tables, client_hash, topic_hash,
        rand, sub_bitmaps, bytes_mat, lengths,
        ret_shape_tables, ret_nfa_tables, ret_bytes,
        sem_tables, q_vecs, rule_feats, rule_valid,
    )


def dist_shape_route_step(
    mesh: Mesh,
    shape_tables: Dict,
    nfa_tables: Optional[Dict],
    sub_bitmaps,
    bytes_mat,
    lengths,
    group_tables: Optional[Dict] = None,
    client_hash=None,
    topic_hash=None,
    rand=None,
    sem_tables: Optional[Dict] = None,
    q_vecs=None,
    rule_feats=None,
    rule_valid=None,
    *,
    m_active: int,
    salt: int,
    max_levels: int = 16,
    frontier: int = 32,
    max_matches: int = 64,
    probes: int = 8,
    share_strategy: int = 0,
    kslot: int = 0,
    donate: bool = False,
    kg: int = 0,
    sem_topk: int = 0,
    rule_progs: tuple = (),
):
    """Distributed serving step (shape engine). Sharding as in
    `dist_route_step`: tables replicated, subscriber lanes on 'tp',
    topic batch on 'dp', stats psum'd over ICI. With `group_tables`,
    $share picks resolve on-device per dp shard (r3 verdict item 4 —
    the host pick wall stays down on the multi-chip path too).
    ``kslot`` engages per-shard sparse fan-out compaction (see
    `_dist_shape_step_fn`). A dict `sub_bitmaps` = the CSR subscriber
    table, arrays sharded over 'tp' by their leading slot-owner axis."""
    fn = _dist_shape_step_fn(
        mesh,
        tuple(sorted(shape_tables)),
        tuple(sorted(nfa_tables)) if nfa_tables is not None else None,
        tuple(sorted(group_tables)) if group_tables is not None else None,
        share_strategy,
        m_active,
        salt,
        max_levels,
        frontier,
        max_matches,
        probes,
        kslot,
        donate,
        tuple(sorted(sub_bitmaps))
        if isinstance(sub_bitmaps, dict)
        else None,
        kg,
        tuple(sorted(sem_tables)) if sem_tables is not None else None,
        sem_topk,
        rule_progs,
    )
    return fn(
        shape_tables, nfa_tables, group_tables, client_hash, topic_hash,
        rand, sub_bitmaps, bytes_mat, lengths,
        sem_tables, q_vecs, rule_feats, rule_valid,
    )


def shard_inputs(mesh: Mesh, tables: Dict, sub_bitmaps, bytes_mat, lengths):
    """device_put inputs with the canonical shardings (for repeated calls)."""
    t = {
        k: jax.device_put(v, NamedSharding(mesh, P()))
        for k, v in tables.items()
    }
    sb = jax.device_put(sub_bitmaps, NamedSharding(mesh, P(None, "tp")))
    bm = jax.device_put(bytes_mat, NamedSharding(mesh, P("dp", None)))
    ln = jax.device_put(lengths, NamedSharding(mesh, P("dp")))
    return t, sb, bm, ln


def table_placement(mesh: Mesh):
    """Canonical placement for match tables: replicated over the mesh.
    Returned as a (name, np_array) -> device array fn so DeviceDeltaSync
    can upload straight into the sharded layout."""
    sh = NamedSharding(mesh, P())
    return lambda _name, arr: jax.device_put(arr, sh)


def bitmap_placement(mesh: Mesh):
    """Canonical placement for subscriber bitmaps: lanes sharded on 'tp'."""
    sh = NamedSharding(mesh, P(None, "tp"))
    return lambda _name, arr: jax.device_put(arr, sh)


def csr_placement(mesh: Mesh):
    """Canonical placement for the SPARSE subscriber table
    (ops/csr_table.py): every array's leading axis is the shard-owner
    axis (subscription owned by ``slot % shards``), sharded over 'tp' —
    the CSR twin of the dense lane sharding, O(subscriptions / tp)
    per device. Slot ids are stored globally, so per-shard compact
    lists concatenate over 'tp' with no lane rebase."""
    sh = NamedSharding(mesh, P("tp", None))
    return lambda _name, arr: jax.device_put(arr, sh)


def semantic_placement(mesh: Mesh):
    """Canonical placement for the semantic table
    (ops/semantic_table.py): every array's leading axis is the
    shard-owner axis (entry owned by ``slot % shards``), sharded over
    'tp' — the CSR slot-ownership regime, so per-shard semantic winners
    are GLOBAL slot ids and the compact rows concatenate over 'tp'
    with no lane rebase. O(filters / tp) embedding rows per device."""
    sh = NamedSharding(mesh, P("tp"))
    return lambda _name, arr: jax.device_put(arr, sh)


def retained_placement(mesh: Mesh):
    """Canonical placement for retained-topic chunks: ROWS sharded on
    'dp' (each dp slice scans its share of the stored topics; CHUNK is a
    pow2, so any pow2 dp divides it). Storm filter tables ride
    `table_placement` (replicated) like every other match table."""
    sh = NamedSharding(mesh, P("dp", None))
    return lambda _name, arr: jax.device_put(arr, sh)


def session_placement(mesh: Mesh):
    """Canonical placement for the session table (ops/session_table.py):
    1-D row/slot lanes sharded over 'dp' (pow2 capacities, so any pow2
    dp divides them) — each dp slice owns its share of the inflight
    rows, consistent with PR 10's shard-ownership regime. Delta scatters
    and compaction-offered buffers land pre-sharded through this hook;
    nothing is re-placed per batch."""
    sh = NamedSharding(mesh, P("dp"))
    return lambda _name, arr: jax.device_put(arr, sh)


def place_batch(mesh: Mesh, bytes_mat, lengths):
    """Canonical placement for a topic batch: rows sharded on 'dp'."""
    bm = jax.device_put(bytes_mat, NamedSharding(mesh, P("dp", None)))
    ln = jax.device_put(lengths, NamedSharding(mesh, P("dp")))
    return bm, ln


def shard_shape_inputs(
    mesh: Mesh,
    shape_tables: Dict,
    nfa_tables: Optional[Dict],
    sub_bitmaps,
    bytes_mat,
    lengths,
):
    """`shard_inputs` for the serving (shape) engine — built from the
    canonical placement helpers above (the ONE place the layout is
    declared for every caller: dryrun, tests, DeviceRouter mesh mode)."""
    tp = table_placement(mesh)
    st = {k: tp(k, v) for k, v in shape_tables.items()}
    nt = (
        {k: tp(k, v) for k, v in nfa_tables.items()}
        if nfa_tables is not None
        else None
    )
    sb = bitmap_placement(mesh)("sub_bitmaps", sub_bitmaps)
    bm, ln = place_batch(mesh, bytes_mat, lengths)
    return st, nt, sb, bm, ln
