"""Multi-chip scaling: device mesh, sharded route step, collectives.

The reference scales by running one broker per node with replicated route
tables and per-topic gen_rpc forwards (SURVEY.md §2.4, §5.8). The TPU-native
design instead runs ONE logical router SPMD over a `jax.sharding.Mesh`:

- axis ``dp`` — data parallelism over the topic batch (the analog of the
  reference's hash-sharded router_pool workers, emqx_router.erl:188-189);
- axis ``tp`` — tensor parallelism over subscriber bitmap lanes (the analog
  of topic-shard fan-out, emqx_broker_helper.erl:82-91): each chip owns a
  slice of the subscriber universe and fans out only to its slice;
- stats ride XLA collectives (psum) over ICI instead of counter RPCs.

NFA tables are replicated (they are read-mostly and small relative to HBM);
subscriber bitmaps are sharded on the lane axis. Multi-host DCN distribution
reuses the same program via jax distributed initialization.
"""
