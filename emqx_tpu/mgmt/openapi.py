"""OpenAPI 3 spec generated from the typed config schema + route table.

Parity: apps/emqx_dashboard/src/emqx_dashboard_swagger.erl — the reference
derives its OpenAPI document from the same HOCON schemas that validate
config; here the single source of truth is the AppConfig dataclass tree
(config/schema.py): every dataclass becomes a component schema via
reflection, so REST docs can never drift from what `load_config` accepts.
"""

from __future__ import annotations

import dataclasses
import typing
from typing import Dict, get_args, get_origin


def _type_schema(tp, components: Dict) -> Dict:
    origin = get_origin(tp)
    if dataclasses.is_dataclass(tp):
        name = tp.__name__
        if name not in components:
            components[name] = None  # cycle guard
            components[name] = dataclass_schema(tp, components)
        return {"$ref": f"#/components/schemas/{name}"}
    if origin is list:
        (item,) = get_args(tp)
        return {"type": "array", "items": _type_schema(item, components)}
    if origin is dict:
        return {"type": "object", "additionalProperties": True}
    if origin is typing.Union:
        args = [a for a in get_args(tp) if a is not type(None)]
        if len(args) == 1:
            inner = _type_schema(args[0], components)
            return {**inner, "nullable": True}
        return {"anyOf": [_type_schema(a, components) for a in args]}
    if tp is bool:
        return {"type": "boolean"}
    if tp is int:
        return {"type": "integer"}
    if tp is float:
        return {"type": "number"}
    if tp is str:
        return {"type": "string"}
    return {}


def dataclass_schema(cls, components: Dict) -> Dict:
    hints = typing.get_type_hints(cls)
    props = {}
    for f in dataclasses.fields(cls):
        sch = _type_schema(hints[f.name], components)
        if f.default is not dataclasses.MISSING:
            sch = {**sch, "default": f.default}
        props[f.name] = sch
    out = {"type": "object", "properties": props}
    if cls.__doc__:
        out["description"] = " ".join(cls.__doc__.split())
    return out


def build_spec(route_specs, version: str) -> Dict:
    """route_specs: [(method, path, summary, tag)]"""
    from emqx_tpu.config.schema import AppConfig

    components: Dict[str, Dict] = {}
    _type_schema(AppConfig, components)

    paths: Dict[str, Dict] = {}
    for method, path, summary, tag in route_specs:
        # aiohttp {param} and {param:regex} -> openapi {param}
        norm = []
        for seg in path.split("/"):
            if seg.startswith("{") and ":" in seg:
                seg = seg.split(":", 1)[0] + "}"
            norm.append(seg)
        path = "/".join(norm)
        op = {
            "summary": summary,
            "tags": [tag],
            "responses": {"200": {"description": "success"}},
        }
        params = [
            seg[1:-1]
            for seg in path.split("/")
            if seg.startswith("{") and seg.endswith("}")
        ]
        if params:
            op["parameters"] = [
                {
                    "name": p,
                    "in": "path",
                    "required": True,
                    "schema": {"type": "string"},
                }
                for p in params
            ]
        if method in ("post", "put"):
            op["requestBody"] = {
                "content": {"application/json": {"schema": {"type": "object"}}}
            }
        paths.setdefault(path, {})[method] = op

    return {
        "openapi": "3.0.3",
        "info": {
            "title": "emqx_tpu management API",
            "version": version,
            "description": (
                "REST management surface; config component schemas are "
                "generated from the same typed schema that validates "
                "broker configuration."
            ),
        },
        "paths": paths,
        "components": {"schemas": components},
    }
