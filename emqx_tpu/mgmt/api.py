"""REST management API (reference: apps/emqx_management/src/emqx_mgmt_api_*,
served at /api/v5 like the reference's minirest dashboard listener).

Endpoints:
  GET    /api/v5/status                       node + broker liveness
  GET    /api/v5/metrics                      counters
  GET    /api/v5/stats                        gauges
  GET    /api/v5/clients[?like=]              connected clients
  GET    /api/v5/clients/{clientid}
  DELETE /api/v5/clients/{clientid}           kick
  GET    /api/v5/subscriptions[?clientid=]
  GET    /api/v5/routes                       route table topics
  POST   /api/v5/publish                      {topic, payload, qos, retain}
  GET    /api/v5/banned  POST /api/v5/banned  DELETE /api/v5/banned/{kind}/{v}
  GET    /api/v5/retainer/messages
  DELETE /api/v5/retainer/message/{topic}
  GET    /api/v5/configs                      full running config

Auth: `Authorization: Bearer <api_key>` when dashboard.api_key is set
(emqx_mgmt_auth analog); open in dev mode otherwise.
"""

from __future__ import annotations

import asyncio
import base64
import dataclasses
import json
from typing import Optional

from aiohttp import web

from emqx_tpu.broker.banned import BanEntry
from emqx_tpu.broker.message import Message
from emqx_tpu.config.schema import to_dict
from emqx_tpu.utils.node import node_name


# the single route table: registration AND the OpenAPI document derive
# from it (emqx_dashboard_swagger generates both from one schema source)
ROUTES = [
    ("get", "/api/v5/status", "status", "Node and broker liveness", "node"),
    ("get", "/api/v5/cluster", "cluster_info", "Cluster membership", "node"),
    ("post", "/api/v5/nodes/drain", "node_drain",
     "Drain this node: stop accepting, park/hand off sessions "
     "(rolling-upgrade orchestration)", "node"),
    ("get", "/api/v5/metrics", "metrics", "Counter metrics", "metrics"),
    ("get", "/api/v5/metrics/hotpath", "metrics_hotpath",
     "Hot-path flight recorder: ingest/matcher/dispatch p50/p99, "
     "fallback rate, batch occupancy", "metrics"),
    ("get", "/api/v5/stats", "stats", "Gauge statistics", "metrics"),
    ("get", "/api/v5/clients", "clients", "List connected clients", "clients"),
    ("get", "/api/v5/clients/{clientid}", "client_one", "One client", "clients"),
    ("delete", "/api/v5/clients/{clientid}", "client_kick", "Kick a client", "clients"),
    ("get", "/api/v5/subscriptions", "subscriptions", "List subscriptions", "subscriptions"),
    ("get", "/api/v5/routes", "routes", "Route table topics", "routes"),
    ("post", "/api/v5/publish", "publish", "Publish a message", "publish"),
    ("get", "/api/v5/banned", "banned_list", "List bans", "banned"),
    ("post", "/api/v5/banned", "banned_add", "Add a ban", "banned"),
    ("delete", "/api/v5/banned/{kind}/{value}", "banned_del", "Remove a ban", "banned"),
    ("get", "/api/v5/retainer/messages", "retained_list", "List retained messages", "retainer"),
    ("delete", "/api/v5/retainer/message/{topic:.+}", "retained_del", "Delete retained message", "retainer"),
    ("get", "/api/v5/configs", "configs", "Full running config", "configs"),
    ("put", "/api/v5/configs/{path:.+}", "configs_update", "Update a config subtree at runtime", "configs"),
    ("get", "/api/v5/rules", "rules_list", "List rules", "rules"),
    ("post", "/api/v5/rules", "rules_create", "Create a rule", "rules"),
    ("get", "/api/v5/rules/{id}", "rules_one", "One rule", "rules"),
    ("delete", "/api/v5/rules/{id}", "rules_delete", "Delete a rule", "rules"),
    ("post", "/api/v5/rule_test", "rule_test", "Test a rule SQL", "rules"),
    ("get", "/api/v5/alarms", "alarms_list", "List alarms", "alarms"),
    ("delete", "/api/v5/alarms", "alarms_clear", "Clear deactivated alarms", "alarms"),
    ("get", "/api/v5/slow_subscriptions", "slow_subs_list", "Slow consumers top-k", "slow_subs"),
    ("delete", "/api/v5/slow_subscriptions", "slow_subs_clear", "Clear slow-subs records", "slow_subs"),
    ("get", "/api/v5/mqtt/topic_metrics", "topic_metrics_list", "Per-topic metrics", "topic_metrics"),
    ("post", "/api/v5/mqtt/topic_metrics", "topic_metrics_add", "Track a topic", "topic_metrics"),
    ("delete", "/api/v5/mqtt/topic_metrics/{topic:.+}", "topic_metrics_del", "Untrack a topic", "topic_metrics"),
    ("get", "/api/v5/prometheus/stats", "prometheus_stats", "Prometheus exposition", "metrics"),
    ("get", "/api/v5/semantic/filters", "semantic_list",
     "List embedding-filter subscriptions (docs/semantic_routing.md)",
     "semantic"),
    ("post", "/api/v5/semantic/filters", "semantic_attach",
     "Attach an embedding filter to an existing subscription",
     "semantic"),
    ("delete", "/api/v5/semantic/filters", "semantic_detach",
     "Detach embedding filters (?clientid=&topic_filter=)", "semantic"),
    ("get", "/api/v5/faults", "faults_list",
     "Armed fault-injection rules + degradation breaker states "
     "(docs/robustness.md)", "faults"),
    ("post", "/api/v5/faults", "faults_arm",
     "Arm a fault rule at a registered site (soak testing)", "faults"),
    ("delete", "/api/v5/faults", "faults_disarm",
     "Disarm fault rules (?site= for one, all otherwise)", "faults"),
    ("get", "/api/v5/profile", "profile_get",
     "Profiler snapshot: stage waterfall, per-kernel attribution, "
     "hardware fingerprint, cached roofline (docs/observability.md)",
     "profile"),
    ("post", "/api/v5/profile", "profile_arm",
     "Arm a bounded jax.profiler trace capture {duration_s?, "
     "max_bytes?}, or {action: 'cost_harvest'} to (re)build the static "
     "cost matrix", "profile"),
    ("delete", "/api/v5/profile", "profile_disarm",
     "Stop the armed capture early (finalizes the trace directory)",
     "profile"),
    ("get", "/api/v5/trace/spans", "trace_spans",
     "Recent causal trace spans (publish -> batch -> device -> deliver "
     "ring buffer, OTLP-shaped)", "trace"),
    ("get", "/api/v5/trace", "trace_list", "List packet traces", "trace"),
    ("post", "/api/v5/trace", "trace_create", "Create a packet trace", "trace"),
    ("delete", "/api/v5/trace/{name}", "trace_delete", "Delete a trace", "trace"),
    ("put", "/api/v5/trace/{name}/stop", "trace_stop", "Stop a trace", "trace"),
    ("get", "/api/v5/trace/{name}/download", "trace_download", "Download trace log", "trace"),
    ("get", "/api/v5/exhooks", "exhooks_list", "List exhook servers", "exhook"),
    ("get", "/api/v5/gateways", "gateways_list", "List gateways", "gateways"),
    ("get", "/api/v5/gateways/{name}", "gateways_one", "One gateway", "gateways"),
    ("post", "/api/v5/gateways", "gateways_load", "Load a gateway", "gateways"),
    ("delete", "/api/v5/gateways/{name}", "gateways_unload", "Unload a gateway", "gateways"),
    ("get", "/api/v5/bridges", "bridges_list", "List bridges", "bridges"),
    ("post", "/api/v5/bridges", "bridges_create", "Create a bridge", "bridges"),
    ("delete", "/api/v5/bridges/{id}", "bridges_delete", "Delete a bridge", "bridges"),
    ("post", "/api/v5/bridges/{id}/restart", "bridges_restart", "Restart a bridge", "bridges"),
    ("get", "/api/v5/plugins", "plugins_list", "List plugins", "plugins"),
    ("post", "/api/v5/plugins/install", "plugins_install", "Install a plugin package", "plugins"),
    ("put", "/api/v5/plugins/{ref}/start", "plugins_start", "Start a plugin", "plugins"),
    ("put", "/api/v5/plugins/{ref}/stop", "plugins_stop", "Stop a plugin", "plugins"),
    ("delete", "/api/v5/plugins/{ref}", "plugins_delete", "Uninstall a plugin", "plugins"),
    ("get", "/api/v5/listeners", "listeners_list", "List listeners", "listeners"),
    ("post", "/api/v5/listeners", "listeners_create", "Create a listener", "listeners"),
    ("delete", "/api/v5/listeners/{id}", "listeners_delete", "Delete a listener", "listeners"),
    ("post", "/api/v5/listeners/{id}/stop", "listeners_stop", "Stop a listener", "listeners"),
    ("post", "/api/v5/listeners/{id}/start", "listeners_start", "Start a stopped listener", "listeners"),
    ("post", "/api/v5/listeners/{id}/restart", "listeners_restart", "Restart a listener", "listeners"),
    ("get", "/api/v5/authentication", "authn_list", "List authentication providers", "authentication"),
    ("post", "/api/v5/authentication", "authn_create", "Create an authentication provider", "authentication"),
    ("delete", "/api/v5/authentication/{id}", "authn_delete", "Remove an authentication provider", "authentication"),
    ("get", "/api/v5/authentication/{id}/users", "authn_users_list", "List builtin users", "authentication"),
    ("post", "/api/v5/authentication/{id}/users", "authn_users_add", "Add a builtin user", "authentication"),
    ("delete", "/api/v5/authentication/{id}/users/{user}", "authn_users_del", "Delete a builtin user", "authentication"),
    ("get", "/api/v5/authorization/sources", "authz_sources_list", "List authorization sources", "authorization"),
    ("post", "/api/v5/authorization/sources", "authz_sources_create", "Add an authorization source", "authorization"),
    ("delete", "/api/v5/authorization/sources/{type}", "authz_sources_delete", "Remove an authorization source", "authorization"),
    ("post", "/api/v5/authorization/sources/{type}/move", "authz_sources_move", "Reorder an authorization source", "authorization"),
    ("get", "/api/v5/api_key", "api_keys_list", "List API keys", "api_keys"),
    ("post", "/api/v5/api_key", "api_keys_create", "Create an API key (secret shown once)", "api_keys"),
    ("get", "/api/v5/api_key/{name}", "api_keys_get", "One API key", "api_keys"),
    ("put", "/api/v5/api_key/{name}", "api_keys_update", "Update an API key", "api_keys"),
    ("delete", "/api/v5/api_key/{name}", "api_keys_delete", "Delete an API key", "api_keys"),
    ("get", "/api/v5/telemetry/data", "telemetry_data", "Inspect the telemetry report", "telemetry"),
    ("get", "/api/v5/node_dump", "node_dump", "Full node state dump", "node"),
    ("get", "/api-docs", "api_docs", "This OpenAPI document", "meta"),
    ("post", "/api/v5/login", "login", "Obtain an admin JWT", "dashboard"),
    ("get", "/api/v5/monitor_current", "monitor_current", "Latest monitor sample", "dashboard"),
    ("get", "/api/v5/monitor_history", "monitor_history", "Monitor sample history", "dashboard"),
    ("get", "/api/v5/monitor", "monitor_ws", "Live monitor stream (WebSocket)", "dashboard"),
    ("get", "/", "index_page", "Status page", "dashboard"),
]

# reachable without credentials (login mints them; the page fetches the
# sample endpoint, which stays protected)
_PUBLIC_PATHS = {"/api/v5/login", "/"}


class MgmtApi:
    def __init__(self, app):
        self.app = app
        self.broker = app.broker
        self.cm = app.cm
        self._runner: Optional[web.AppRunner] = None
        self.port: Optional[int] = None

        from emqx_tpu.mgmt.api_keys import ApiKeyStore
        from emqx_tpu.mgmt.dashboard import DashboardAdmin, Monitor

        d = app.config.dashboard
        self.admin = DashboardAdmin(d.admins, ttl=d.jwt_ttl)
        self.api_keys = ApiKeyStore()
        # authn providers created over REST: id -> (provider, connector)
        self._authn_by_id = {}
        self.monitor = Monitor(
            app, interval=d.monitor_interval, history=d.monitor_history
        )

        w = web.Application(middlewares=[self._auth_middleware])
        w.add_routes(
            [
                getattr(web, method)(path, getattr(self, handler))
                for method, path, handler, _summary, _tag in ROUTES
            ]
        )
        self._webapp = w

    @web.middleware
    async def _auth_middleware(self, request, handler):
        key = self.app.config.dashboard.api_key
        needs_auth = bool(
            key or self.admin.has_admins() or self.api_keys.has_keys()
        )
        if needs_auth and request.path not in _PUBLIC_PATHS:
            auth = request.headers.get("Authorization", "")
            ok = bool(key) and auth == f"Bearer {key}"
            if not ok and auth.startswith("Bearer "):
                # admin JWT (emqx_dashboard_admin tokens)
                ok = self.admin.verify(auth[7:]) is not None
            if not ok and auth.startswith("Basic "):
                try:
                    decoded = base64.b64decode(auth[6:]).decode()
                    user, _, secret = decoded.partition(":")
                    # machine API keys (emqx_mgmt_auth), the static key as
                    # a password, or the legacy bare-key form (no colon)
                    ok = self.api_keys.verify(user, secret) or (
                        bool(key)
                        and (secret == key or (not secret and user == key))
                    )
                except Exception:
                    ok = False
            if not ok:
                return web.json_response(
                    {"code": "UNAUTHORIZED"}, status=401
                )
        return await handler(request)

    async def start(self, bind: str, port: int) -> None:
        self._runner = web.AppRunner(self._webapp)
        await self._runner.setup()
        site = web.TCPSite(self._runner, bind, port)
        await site.start()
        self.port = self._runner.addresses[0][1] if self._runner.addresses else port
        self.monitor.start()

    async def stop(self) -> None:
        await self.monitor.stop()
        if self._runner is not None:
            await self._runner.cleanup()

    # -- dashboard (emqx_dashboard admin/monitor analogs) ------------------
    async def login(self, request):
        try:
            body = await request.json()
            token = self.admin.login(body["username"], body["password"])
        except (ValueError, KeyError, TypeError):
            token = None
        if token is None:
            return web.json_response({"code": "BAD_USERNAME_OR_PWD"}, status=401)
        return web.json_response(
            {"token": token, "version": __import__("emqx_tpu").__version__}
        )

    async def monitor_current(self, request):
        return web.json_response(self.monitor.sample())

    async def monitor_history(self, request):
        return web.json_response({"data": self.monitor.samples})

    async def monitor_ws(self, request):
        ws = web.WebSocketResponse(heartbeat=30)
        await ws.prepare(request)
        q = self.monitor.subscribe()

        async def pump():
            try:
                await ws.send_json(self.monitor.sample())
                while True:
                    await ws.send_json(await q.get())
            except (ConnectionError, asyncio.CancelledError):
                pass

        task = asyncio.get_running_loop().create_task(pump())
        try:
            # drain client frames so the CLOSE handshake completes (a
            # handler parked only on q.get() would never see it)
            async for _ in ws:
                pass
        finally:
            task.cancel()
            self.monitor.unsubscribe(q)
        return ws

    async def index_page(self, request):
        from emqx_tpu.mgmt.dashboard import STATUS_PAGE

        return web.Response(text=STATUS_PAGE, content_type="text/html")

    # -- handlers ----------------------------------------------------------
    async def status(self, request):
        return web.json_response(
            {
                "node": node_name(),
                "status": "running",
                "version": __import__("emqx_tpu").__version__,
                "uptime_seconds": self.broker.metrics.snapshot()[
                    "uptime_seconds"
                ],
                "connections": self.cm.channel_count(),
                "subscriptions": self.broker.subscription_count(),
                "routes": len(self.broker.router),
                "retained": len(self.app.retainer),
            }
        )

    async def cluster_info(self, request):
        """Membership + route-table view (emqx_mgmt_api_nodes analog)."""
        node = getattr(self.app, "cluster_node", None)
        if node is None:
            return web.json_response(
                {"enabled": False, "nodes": [node_name()]}
            )
        return web.json_response(
            {
                "enabled": True,
                "name": node.name,
                "running_nodes": node.membership.running_nodes(),
                "stats": node.stats(),
            }
        )

    async def node_drain(self, request):
        """Rolling-upgrade drain (see BrokerApp.drain): body may name the
        handoff peer ({"peer": "n2@host"}); defaults to the first live
        peer. The caller stops/replaces the process afterwards."""
        try:
            body = await request.json()
        except Exception:
            body = {}
        if not isinstance(body, dict):
            body = {}
        out = await self.app.drain(peer=body.get("peer"))
        return web.json_response(out)

    async def metrics(self, request):
        return web.json_response(self.broker.metrics.snapshot())

    async def metrics_hotpath(self, request):
        """Flight-recorder summary of the ingest -> matcher -> dispatch
        pipeline: histogram percentiles, fallback rates, batch occupancy
        (docs/observability.md). The before/after read for perf PRs."""
        from emqx_tpu.observe import provenance as _provenance
        from emqx_tpu.observe.profiler import (
            kernel_summary as _kernel_summary,
            roofline_summary as _roofline_summary,
            waterfall as _waterfall,
        )

        m = self.broker.metrics
        _prof = getattr(self.app, "profiler", None)

        def hist(name, scale=1.0):
            h = m.histogram(name)
            if h is None or h.count == 0:
                return None
            return {
                "count": h.count,
                "mean": (h.sum / h.count) * scale,
                "p50": h.p50 * scale,
                "p95": h.p95 * scale,
                "p99": h.p99 * scale,
            }

        routed_dev = m.get("messages.routed.device")
        routed_fb = m.get("messages.routed.device_fallback")
        routed_total = routed_dev + routed_fb
        occ = m.histogram("ingest.batch.occupancy")
        ing = getattr(self.broker, "ingest", None)
        slo = getattr(ing, "slo", None) if ing is not None else None
        out = {
            "ingest": {
                "batch_size": hist("ingest.batch.size"),
                "batch_occupancy_mean": (
                    occ.sum / occ.count if occ and occ.count else None
                ),
                "window_wait_ms": hist("ingest.window.wait.seconds", 1e3),
                "settle_ms": hist("ingest.settle.seconds", 1e3),
                "pipeline_depth": m.gauge("ingest.pipeline.depth"),
                "launch_errors": m.get("ingest.launch.errors"),
                "dispatch_errors": m.get("ingest.dispatch.errors"),
            },
            "slo": (
                {
                    # live controller state (broker/slo.py): the window
                    # it chose, the tail it observed, the ladder rung
                    # it stands on, and the lane depths behind it
                    **slo.to_json(),
                    "eval_windows": m.get("slo.eval.windows"),
                    "violations": m.get("slo.violations"),
                    "adjustments": m.get("slo.adjustments"),
                    "deferrals": m.get("slo.deferrals"),
                    "sheds": m.get("slo.shed"),
                    "olp_pressure": (
                        ing.olp.pressure()
                        if ing is not None and ing.olp is not None
                        else None
                    ),
                    "lane_depth": {
                        "control": m.gauge("ingest.lane.depth.control"),
                        "normal": m.gauge("ingest.lane.depth.normal"),
                        "low": m.gauge("ingest.lane.depth.low"),
                    },
                    "lane_settle_ms": {
                        "control": hist(
                            "ingest.lane.settle.seconds.control", 1e3
                        ),
                        "normal": hist(
                            "ingest.lane.settle.seconds.normal", 1e3
                        ),
                        "low": hist(
                            "ingest.lane.settle.seconds.low", 1e3
                        ),
                    },
                    "starvation_breaks": m.get(
                        "ingest.lane.starvation.breaks"
                    ),
                    "storm_deferred": m.get("retained.storm.deferred"),
                }
                if slo is not None
                else None
            ),
            "matcher": {
                "device_ms": hist("matcher.device.seconds", 1e3),
                "sync_ms": hist("matcher.sync.seconds", 1e3),
                "batch_size": hist("matcher.batch.size"),
                "rows": m.get("matcher.rows"),
                "fallback_rows": m.get("matcher.fallback.rows"),
                "fallback_by_cause": {
                    cause: m.get(f"matcher.fallback.rows.{cause}")
                    for cause in (
                        "too_deep",
                        "frontier_overflow",
                        "match_overflow",
                        "too_long",
                    )
                },
            },
            "router": {
                "device_ms": hist("router.device.seconds", 1e3),
                "sync_ms": hist("router.sync.seconds", 1e3),
                "batch_size": hist("router.batch.size"),
                "prepare_dirty": m.get("router.prepare.dirty"),
                "sync_skipped": m.get("router.sync.skipped"),
            },
            "sub_table": {
                # subscriber-table representation (docs/serving_pipeline
                # "subscriber-table memory budget"): mode + live device
                # footprint straight from the table, overflow/flip
                # counters from the flight recorder
                **self.broker.subtab.status(),
                "overflow_rows": m.get("router.sparse.overflow.rows"),
                "rep_flips": m.get("router.sparse.flips"),
            },
            "segment": {
                "hot_fill": m.gauge("router.segment.hot.fill"),
                "hot_capacity": m.gauge("router.segment.hot.capacity"),
                "tombstones": m.gauge("router.segment.tombstones"),
                "compact_runs": m.get("router.compact.runs"),
                "compact_aborted": m.get("router.compact.aborted"),
                "compact_merged": m.get("router.compact.merged"),
                "compact_ms": hist("router.compact.seconds", 1e3),
                "compact_lag_s": m.gauge("router.compact.lag.seconds"),
            },
            "session": (
                {
                    **self.broker.session_store.status(),
                    "ack_rides": m.get("session.ack.rides"),
                    "ack_rows": m.get("session.ack.rows"),
                    "ack_scatters": m.get("session.ack.scatters"),
                    "sweeps_device": m.get("session.sweep.device"),
                    "sweeps_host": m.get("session.sweep.host"),
                    "redeliveries": m.get("session.redeliveries"),
                    "resumed": m.get("session.resume.replayed"),
                }
                if self.broker.session_store is not None
                else None
            ),
            "mesh": {
                "shape": (
                    f"{self.broker.mesh.shape['dp']}x"
                    f"{self.broker.mesh.shape['tp']}"
                    if self.broker.mesh is not None
                    else None
                ),
                "shard_label": self.broker.shard_label,
                "shard_count": m.gauge("mesh.shard.count"),
                "shard_fill_max": m.gauge("mesh.shard.fill"),
                "scatter_launches": m.get("mesh.shard.scatter.launches"),
                "compact_runs": m.get("mesh.shard.compact.runs"),
                "rebalance_events": m.get("mesh.shard.rebalance"),
                "reroutes": m.get("mesh.shard.reroutes"),
            },
            "semantic": (
                {
                    **self.broker.semantic.status(),
                    "hits": m.get("semantic.hits"),
                    "topk_truncated": m.get("semantic.topk.truncated"),
                    "host_batches": m.get("semantic.host.batches"),
                    "host_matches": m.get("semantic.host.matches"),
                    "embed_rejected": m.get("semantic.embed.rejected"),
                }
                if self.broker.semantic is not None
                else None
            ),
            "rules": {
                "matched": m.get("rules.matched"),
                "passed": m.get("rules.passed"),
                "failed": m.get("rules.failed"),
                "dropped": m.get("rules.dropped"),
                "device_batches": m.get("rules.device.batches"),
                "host_batches": m.get("rules.host.batches"),
            },
            "fabric": {
                "slab_pub_frames": m.get("fabric.slab.pub.frames"),
                "slab_pub_records": m.get("fabric.slab.pub.records"),
                "slab_dlv_frames": m.get("fabric.slab.dlv.frames"),
                "slab_dlv_records": m.get("fabric.slab.dlv.records"),
                "zerocopy_records": m.get("ingest.zerocopy.records"),
                "zerocopy_deferred_bytes": m.get(
                    "ingest.zerocopy.deferred.bytes"
                ),
                "serialize_batches": m.get("dispatch.serialize.batches"),
                "serialize_frames": m.get("dispatch.serialize.frames"),
                "serialize_bytes": m.get("dispatch.serialize.bytes"),
                "raw_records": m.get("fabric.raw.records"),
                "parked_dropped": m.get("fabric.parked.dropped"),
                "flush_errors": m.get("fabric.flush.errors"),
            },
            "dispatch": {
                "fanout": hist("dispatch.fanout"),
                "routed_device": routed_dev,
                "routed_device_fallback": routed_fb,
                "fallback_rate": (
                    routed_fb / routed_total if routed_total else None
                ),
            },
            "device": {
                "compile_count": m.get("device.compile.count"),
                "compile_ms": hist("device.compile.seconds", 1e3),
                "compile_cache_size": m.gauge("device.compile.cache_size"),
                "hbm_bytes": m.gauge("device.hbm.bytes"),
                "transfer_bytes": m.get("device.transfer.bytes"),
            },
            "trace": {
                "spans_sampled": m.get("trace.spans.sampled"),
                "spans_dropped": m.get("trace.spans.dropped"),
            },
            "profile": {
                "waterfall": _waterfall(m),
                "kernels": _kernel_summary(m),
                "capture_armed": _prof.armed if _prof else False,
                "captures": m.get("profile.captures"),
                "fingerprint": _provenance.fingerprint_key(),
                "proxy": _provenance.is_proxy(),
                "roofline": _roofline_summary(
                    _prof.cost_cached() if _prof else None
                ),
            },
            "alarms": {
                "tpu_fallback_rate_active": self.app.alarms.is_active(
                    "tpu_fallback_rate"
                ),
                "tpu_retrace_storm_active": self.app.alarms.is_active(
                    "tpu_retrace_storm"
                ),
                "slo_p99_violation_active": self.app.alarms.is_active(
                    "slo_p99_violation"
                ),
            },
        }
        return web.json_response(out)

    async def stats(self, request):
        return web.json_response(
            {
                "connections.count": self.cm.channel_count(),
                "subscriptions.count": self.broker.subscription_count(),
                "topics.count": len(self.broker.router),
                "retained.count": len(self.app.retainer),
                "delayed.count": len(self.app.delayed),
            }
        )

    def _client_json(self, ch):
        return {
            "clientid": ch.client_id,
            "username": ch.username,
            "proto_ver": ch.version,
            "clean_start": ch.clean_start,
            "keepalive": ch.keepalive,
            "connected_at": ch.connected_at,
            "peerhost": ch.conninfo.get("peerhost"),
            "subscriptions_cnt": len(ch.session.subscriptions)
            if ch.session
            else 0,
        }

    async def clients(self, request):
        like = request.query.get("like", "")
        out = [
            self._client_json(self.cm.get_channel(cid))
            for cid in self.cm.client_ids()
            if like in cid
        ]
        return web.json_response({"data": out, "meta": {"count": len(out)}})

    async def client_one(self, request):
        ch = self.cm.get_channel(request.match_info["clientid"])
        if ch is None:
            return web.json_response({"code": "NOT_FOUND"}, status=404)
        return web.json_response(self._client_json(ch))

    async def client_kick(self, request):
        ok = self.cm.kick_client(request.match_info["clientid"])
        if not ok:
            return web.json_response({"code": "NOT_FOUND"}, status=404)
        return web.json_response({}, status=204)

    async def subscriptions(self, request):
        cid = request.query.get("clientid")
        out = [
            {
                "clientid": c,
                "topic": f,
                "qos": o.qos,
                "no_local": o.no_local,
            }
            for (c, f, o) in self.broker.subscriptions()
            if cid is None or c == cid
        ]
        return web.json_response({"data": out, "meta": {"count": len(out)}})

    async def routes(self, request):
        topics = self.broker.router.topics()
        return web.json_response(
            {"data": topics, "meta": {"count": len(topics)}}
        )

    async def publish(self, request):
        from emqx_tpu.ops import topics as T

        try:
            body = await request.json()
            topic = body["topic"]
            payload = body.get("payload", "")
            if not isinstance(topic, str) or not isinstance(payload, str):
                raise KeyError("topic/payload must be strings")
            T.validate(topic, kind="name")
            if body.get("payload_encoding") == "base64":
                payload = base64.b64decode(payload, validate=True)
            else:
                payload = payload.encode()
            qos = body.get("qos", 0)
            if isinstance(qos, bool) or not isinstance(qos, int) or qos not in (0, 1, 2):
                raise ValueError(f"invalid qos {qos!r}")
            retain = body.get("retain", False)
            if not isinstance(retain, bool):
                raise ValueError(f"invalid retain {retain!r}")
        except (json.JSONDecodeError, KeyError, ValueError, TypeError) as e:
            return web.json_response(
                {"code": "BAD_REQUEST", "message": str(e)}, status=400
            )
        # apublish: API publishes traverse the full async extension chain
        # (exhook message.publish) exactly like client traffic
        n = await self.broker.apublish(
            Message(
                topic=topic,
                payload=payload,
                qos=qos,
                retain=retain,
                from_client="mgmt_api",
            )
        )
        return web.json_response({"delivered": n})

    # -- rules (emqx_mgmt_api rules + emqx_rule_engine_api parity) ---------
    def _rule_json(self, rule):
        return {
            "id": rule.id,
            "sql": rule.sql,
            "enable": rule.enabled,
            "description": rule.description,
            "outputs": [o.name for o in rule.outputs],
            "metrics": rule.metrics.as_dict(),
        }

    async def rules_list(self, request):
        eng = self.app.rule_engine
        return web.json_response(
            {"data": [self._rule_json(r) for r in eng.rules()]}
        )

    async def rules_create(self, request):
        from emqx_tpu.rules import SqlParseError
        from emqx_tpu.rules.engine import Console, Republish

        eng = self.app.rule_engine
        try:
            body = await request.json()
            rule_id = str(body["id"])
            sql = str(body["sql"])
            outputs = []
            for spec in body.get("outputs", [{"function": "console"}]):
                fn = spec.get("function", "console")
                if fn == "republish":
                    args = spec.get("args", {})
                    outputs.append(
                        Republish(
                            topic=str(args["topic"]),
                            payload=str(args.get("payload", "${payload}")),
                            qos=int(args.get("qos", 0)),
                            retain=bool(args.get("retain", False)),
                        )
                    )
                elif fn == "console":
                    outputs.append(Console())
                else:
                    raise ValueError(f"unknown output function {fn!r}")
            rule = eng.create_rule(
                rule_id, sql, outputs, str(body.get("description", ""))
            )
            rule.enabled = bool(body.get("enable", True))
            eng.refresh_device()
        except (json.JSONDecodeError, KeyError, ValueError, TypeError, SqlParseError) as e:
            # ValueError also covers duplicate rule ids (create_rule)
            return web.json_response(
                {"code": "BAD_REQUEST", "message": str(e)}, status=400
            )
        return web.json_response(self._rule_json(rule), status=201)

    async def rules_one(self, request):
        rule = self.app.rule_engine.get_rule(request.match_info["id"])
        if rule is None:
            return web.json_response({"code": "NOT_FOUND"}, status=404)
        return web.json_response(self._rule_json(rule))

    async def rules_delete(self, request):
        if not self.app.rule_engine.delete_rule(request.match_info["id"]):
            return web.json_response({"code": "NOT_FOUND"}, status=404)
        return web.json_response({}, status=204)

    async def rule_test(self, request):
        from emqx_tpu.rules import SqlParseError, test_sql
        from emqx_tpu.rules.runtime import RuleEvalError

        try:
            body = await request.json()
            rows = test_sql(str(body["sql"]), dict(body.get("context", {})))
        except (
            json.JSONDecodeError,
            KeyError,
            ValueError,
            TypeError,
            SqlParseError,
            RuleEvalError,
        ) as e:
            return web.json_response(
                {"code": "BAD_REQUEST", "message": str(e)}, status=400
            )
        return web.json_response({"match": rows is not None, "rows": rows})

    async def banned_list(self, request):
        return web.json_response(
            {
                "data": [
                    dataclasses.asdict(e) for e in self.app.banned.entries()
                ]
            }
        )

    async def banned_add(self, request):
        try:
            body = await request.json()
            kind = body["as"]
            if kind not in ("clientid", "username", "peerhost"):
                raise ValueError(f"invalid kind {kind!r}")
            self.app.banned.add(
                BanEntry(
                    kind=kind,
                    value=str(body["who"]),
                    by=str(body.get("by", "mgmt_api")),
                    reason=str(body.get("reason", "")),
                    until=float(body.get("until", float("inf"))),
                )
            )
        except (json.JSONDecodeError, KeyError, TypeError, ValueError) as e:
            return web.json_response(
                {"code": "BAD_REQUEST", "message": str(e)}, status=400
            )
        return web.json_response({}, status=201)

    async def banned_del(self, request):
        ok = self.app.banned.delete(
            request.match_info["kind"], request.match_info["value"]
        )
        return web.json_response(
            {} if ok else {"code": "NOT_FOUND"}, status=204 if ok else 404
        )

    async def retained_list(self, request):
        # cursor-paged (a multi-million-message store must not dump in
        # one response; emqx_retainer_mnesia paged-read parity): pass
        # ?limit= and the meta.cursor of the previous page
        try:
            limit = min(int(request.query.get("limit", 10000)), 100000)
        except ValueError:
            limit = 10000
        cursor = request.query.get("cursor") or None
        msgs, nxt = self.app.retainer.messages_page(cursor, limit)
        return web.json_response(
            {
                "data": [m.topic for m in msgs],
                "meta": {
                    "count": len(self.app.retainer),
                    "limit": limit,
                    "cursor": nxt,
                    "hasnext": nxt is not None,
                },
            }
        )

    async def retained_del(self, request):
        ok = self.app.retainer.delete(request.match_info["topic"])
        return web.json_response(
            {} if ok else {"code": "NOT_FOUND"}, status=204 if ok else 404
        )

    async def configs(self, request):
        return web.json_response(to_dict(self.app.config))

    # -- observability (emqx_mgmt_api_alarms/trace, emqx_slow_subs REST,
    #    emqx_topic_metrics REST, emqx_prometheus scrape) ------------------
    async def alarms_list(self, request):
        q = request.query.get("activated")
        activated = None if q is None else q in ("true", "1")
        return web.json_response({"data": self.app.alarms.list(activated)})

    async def alarms_clear(self, request):
        n = self.app.alarms.delete_all_deactivated()
        return web.json_response({"cleared": n}, status=200)

    # -- semantic routing plane (broker/semantic.py,
    #    docs/semantic_routing.md) -----------------------------------------
    async def semantic_list(self, request):
        sem = self.broker.semantic
        if sem is None:
            return web.json_response(
                {"code": "NOT_ENABLED",
                 "message": "semantic.enable is off"}, status=404,
            )
        return web.json_response(
            {"status": sem.status(), "data": sem.entries()}
        )

    async def semantic_attach(self, request):
        """Attach an embedding filter to an EXISTING subscription:
        {clientid, topic_filter, embedding (JSON list | base64 f32le),
        threshold?}. The subscription then delivers on topic match AND
        similarity; re-POST replaces the embedding in place."""
        sem = self.broker.semantic
        if sem is None:
            return web.json_response(
                {"code": "NOT_ENABLED",
                 "message": "semantic.enable is off"}, status=404,
            )
        try:
            body = await request.json()
            cid = str(body["clientid"])
            tf = str(body["topic_filter"])
            from emqx_tpu.broker.semantic import decode_embedding

            vec = decode_embedding(body["embedding"], sem.table.dim)
            th = float(body.get("threshold", sem.default_threshold))
        except (json.JSONDecodeError, KeyError, ValueError,
                TypeError) as e:
            return web.json_response(
                {"code": "BAD_REQUEST", "message": str(e)}, status=400
            )
        b = self.broker
        entry = b._subs.get(tf) or {}
        sub = entry.get(cid)
        if sub is None or sub.slot < 0:
            return web.json_response(
                {"code": "NOT_FOUND",
                 "message": f"no subscription {tf!r} for {cid!r}"},
                status=404,
            )
        fid = b.router.filter_id(tf)
        if not sub.semantic and fid is not None:
            # the slot migrates from the fan-out table to the semantic
            # table — same transition the SUBSCRIBE path performs
            b.subtab.remove(fid, sub.slot)
        sub.semantic = True
        sem.attach(
            cid, sub.slot, vec, th,
            fid=-1 if fid is None else fid, scope=tf,
        )
        return web.json_response(
            {"slot": sub.slot, "threshold": th}, status=201
        )

    async def semantic_detach(self, request):
        """Detach filters; ?clientid= narrows to one client,
        &topic_filter= to one subscription (which reverts to plain
        fan-out delivery)."""
        sem = self.broker.semantic
        if sem is None:
            return web.json_response(
                {"code": "NOT_ENABLED"}, status=404
            )
        cid = request.query.get("clientid")
        tf = request.query.get("topic_filter")
        b = self.broker
        n = 0
        for item in list(sem.entries()):
            if cid is not None and item["clientid"] != cid:
                continue
            if tf is not None and item["topic_filter"] != tf:
                continue
            slot = item["slot"]
            sem.detach(slot)
            sub = (
                b._slot_subs[slot]
                if 0 <= slot < len(b._slot_subs)
                else None
            )
            if sub is not None and sub.semantic:
                sub.semantic = False
                fid = b.router.filter_id(sub.filter)
                if fid is not None:
                    b.subtab.add(fid, slot)
            n += 1
        return web.json_response({"detached": n})

    # -- fault injection + degradation (observe/faults.py,
    #    broker/degrade.py; docs/robustness.md) ----------------------------
    async def faults_list(self, request):
        out = self.app.faults.snapshot()
        deg = getattr(self.app, "degrade", None)
        out["degrade"] = deg.to_json() if deg is not None else None
        return web.json_response(out)

    async def faults_arm(self, request):
        """Arm one rule: {site, mode?, probability?, nth?, max_fires?,
        delay_ms?}. The injector validates site/mode/probability against
        the same registry the config loader enforces."""
        try:
            body = await request.json()
        except Exception:
            return web.json_response({"code": "BAD_REQUEST"}, status=400)
        if not isinstance(body, dict):
            return web.json_response({"code": "BAD_REQUEST"}, status=400)
        try:
            rule = self.app.faults.arm(
                str(body.get("site", "")),
                mode=str(body.get("mode", "raise")),
                probability=float(body.get("probability", 1.0)),
                nth=int(body.get("nth", 0)),
                max_fires=int(body.get("max_fires", 0)),
                delay_ms=float(body.get("delay_ms", 0.0)),
            )
        except (ValueError, TypeError) as e:
            return web.json_response(
                {"code": "BAD_REQUEST", "message": str(e)}, status=400
            )
        return web.json_response(rule.to_json(), status=201)

    async def faults_disarm(self, request):
        site = request.query.get("site")
        self.app.faults.disarm(site)
        return web.Response(status=204)

    # -- performance provenance & device profiling (observe/profiler.py,
    #    observe/provenance.py; docs/observability.md) --------------------
    async def profile_get(self, request):
        from emqx_tpu.observe import provenance
        from emqx_tpu.observe.profiler import kernel_summary, waterfall

        prof = self.app.profiler
        m = self.broker.metrics
        out = prof.snapshot()
        out["waterfall"] = waterfall(m)
        out["kernels"] = kernel_summary(m)
        out["fingerprint"] = provenance.fingerprint()
        cost = prof.cost_cached()
        if cost is not None:
            out["cost"] = cost
        return web.json_response(out)

    async def profile_arm(self, request):
        """Arm a bounded trace capture: {duration_s?, max_bytes?} (both
        clamped against the profiler's configured ceilings), or run the
        static cost harvest with {action: 'cost_harvest',
        max_configs_per_kernel?, refresh?} — the harvest compiles every
        contract kernel, so it runs on the executor, not the loop."""
        try:
            body = await request.json()
        except Exception:
            body = {}
        if not isinstance(body, dict):
            return web.json_response({"code": "BAD_REQUEST"}, status=400)
        prof = self.app.profiler
        if body.get("action") == "cost_harvest":
            import asyncio

            cap = body.get("max_configs_per_kernel")
            result = await asyncio.get_running_loop().run_in_executor(
                None,
                lambda: prof.cost_harvest(
                    int(cap) if cap else None,
                    refresh=bool(body.get("refresh", False)),
                ),
            )
            return web.json_response(
                {
                    "kernels": sorted({r["kernel"] for r in result["rows"]}),
                    "rows": len(result["rows"]),
                    "skipped": result["skipped"],
                    "proxy": result["proxy"],
                },
                status=201,
            )
        try:
            info = prof.arm(
                duration_s=body.get("duration_s"),
                max_bytes=body.get("max_bytes"),
            )
        except (RuntimeError, ValueError, TypeError) as e:
            return web.json_response(
                {"code": "BAD_REQUEST", "message": str(e)}, status=400
            )
        return web.json_response(info, status=201)

    async def profile_disarm(self, request):
        entry = self.app.profiler.disarm(reason="rest")
        if entry is None:
            return web.Response(status=204)
        return web.json_response(entry)

    async def slow_subs_list(self, request):
        return web.json_response({"data": self.app.slow_subs.topk()})

    async def slow_subs_clear(self, request):
        self.app.slow_subs.clear()
        return web.Response(status=204)

    async def topic_metrics_list(self, request):
        return web.json_response(self.app.topic_metrics.metrics())

    async def topic_metrics_add(self, request):
        try:
            body = await request.json()
        except Exception:
            return web.json_response({"code": "BAD_REQUEST"}, status=400)
        topic = body.get("topic", "")
        try:
            created = self.app.topic_metrics.register(topic)
        except OverflowError:
            return web.json_response({"code": "QUOTA_EXCEEDED"}, status=409)
        except Exception:
            return web.json_response({"code": "BAD_TOPIC"}, status=400)
        if not created:
            return web.json_response({"code": "ALREADY_EXISTED"}, status=409)
        return web.json_response({"topic": topic}, status=201)

    async def topic_metrics_del(self, request):
        ok = self.app.topic_metrics.deregister(request.match_info["topic"])
        return web.json_response(
            {} if ok else {"code": "NOT_FOUND"}, status=204 if ok else 404
        )

    async def prometheus_stats(self, request):
        from emqx_tpu.observe.exporters import prometheus_exposition

        extra = {
            "connections.count": self.cm.channel_count(),
            "subscriptions.count": self.broker.subscription_count(),
            "topics.count": len(self.broker.router),
            "retained.count": len(self.app.retainer),
        }
        if self.app.os_mon is not None:
            extra["cpu.usage"] = self.app.os_mon.cpu_usage
            extra["mem.usage"] = self.app.os_mon.mem_usage
        if self.app.vm_mon is not None:
            extra["tasks.count"] = self.app.vm_mon.task_count
        body = prometheus_exposition(
            self.broker.metrics.snapshot(),
            extra,
            histograms=self.broker.metrics.histograms(),
        )
        return web.Response(text=body, content_type="text/plain")

    async def trace_spans(self, request):
        """Recent causal spans (observe/spans.py ring buffer), newest
        first, OTLP/JSON-shaped. Query: `limit` (default 100),
        `trace_id` (filter to one trace — follow a single publish
        through batch/device/deliver and across cluster forwards)."""
        rec = getattr(self.app, "spans", None)
        if rec is None:
            return web.json_response(
                {"data": [], "enabled": False}
            )
        try:
            limit = int(request.query.get("limit", 100))
        except ValueError:
            return web.json_response({"code": "BAD_REQUEST"}, status=400)
        return web.json_response(
            {
                "data": rec.recent(
                    limit=limit, trace_id=request.query.get("trace_id")
                ),
                "enabled": True,
                "sampled": self.broker.metrics.get("trace.spans.sampled"),
                "dropped": self.broker.metrics.get("trace.spans.dropped"),
            }
        )

    async def trace_list(self, request):
        return web.json_response({"data": self.app.trace.list()})

    async def trace_create(self, request):
        try:
            body = await request.json()
        except Exception:
            return web.json_response({"code": "BAD_REQUEST"}, status=400)
        try:
            spec = self.app.trace.create(
                name=body["name"],
                type=body["type"],
                value=body.get(body.get("type"), body.get("value", "")),
                start_at=body.get("start_at"),
                end_at=body.get("end_at"),
            )
        except KeyError:
            return web.json_response({"code": "BAD_REQUEST"}, status=400)
        except ValueError as e:
            code = (
                "ALREADY_EXISTED" if "existed" in str(e) else "BAD_REQUEST"
            )
            return web.json_response(
                {"code": code}, status=409 if code == "ALREADY_EXISTED" else 400
            )
        except OverflowError:
            return web.json_response({"code": "QUOTA_EXCEEDED"}, status=409)
        return web.json_response(
            {"name": spec.name, "type": spec.type, "status": spec.status()},
            status=201,
        )

    async def trace_delete(self, request):
        ok = self.app.trace.delete(request.match_info["name"])
        return web.json_response(
            {} if ok else {"code": "NOT_FOUND"}, status=204 if ok else 404
        )

    async def trace_stop(self, request):
        ok = self.app.trace.stop(request.match_info["name"])
        return web.json_response(
            {"status": "stopped"} if ok else {"code": "NOT_FOUND"},
            status=200 if ok else 404,
        )

    async def exhooks_list(self, request):
        ex = getattr(self.app, "exhook", None)
        return web.json_response({"data": ex.info() if ex else []})

    async def configs_update(self, request):
        """PUT /configs/{path}: runtime config update through the
        validated handler pipeline (emqx_config_handler + PUT /configs)."""
        from emqx_tpu.config.schema import ConfigError

        path = request.match_info["path"].replace("/", ".")
        try:
            value = await request.json()
        except ValueError:
            return web.json_response(
                {"code": "BAD_REQUEST", "message": "invalid JSON"}, status=400
            )
        try:
            new_subtree = self.app.config_handler.update(path, value)
        except ConfigError as e:
            return web.json_response(
                {"code": "BAD_REQUEST", "message": str(e)}, status=400
            )
        except Exception as e:
            return web.json_response(
                {"code": "UPDATE_FAILED", "message": str(e)}, status=500
            )
        return web.json_response(new_subtree)

    # -- plugins / telemetry (emqx_plugins + emqx_telemetry analogs) -------
    async def plugins_list(self, request):
        pm = self.app._plugin_manager()
        return web.json_response({"data": pm.list()})

    async def plugins_install(self, request):
        from emqx_tpu.plugins import PluginError

        body = await request.json()
        try:
            p = self.app._plugin_manager().install(body["path"])
        except (KeyError, PluginError, OSError) as e:
            return web.json_response(
                {"code": "BAD_REQUEST", "message": str(e)}, status=400
            )
        return web.json_response(
            {"name": p.name, "version": p.version}, status=201
        )

    async def plugins_start(self, request):
        from emqx_tpu.plugins import PluginError

        try:
            self.app._plugin_manager().start(request.match_info["ref"])
        except PluginError as e:
            return web.json_response(
                {"code": "NOT_FOUND", "message": str(e)}, status=404
            )
        return web.json_response({"status": "running"})

    async def plugins_stop(self, request):
        from emqx_tpu.plugins import PluginError

        try:
            self.app._plugin_manager().stop(request.match_info["ref"])
        except PluginError as e:
            return web.json_response(
                {"code": "NOT_FOUND", "message": str(e)}, status=404
            )
        return web.json_response({"status": "stopped"})

    async def plugins_delete(self, request):
        from emqx_tpu.plugins import PluginError

        try:
            self.app._plugin_manager().uninstall(request.match_info["ref"])
        except PluginError as e:
            return web.json_response(
                {"code": "NOT_FOUND", "message": str(e)}, status=404
            )
        return web.json_response({}, status=204)

    async def telemetry_data(self, request):
        t = self.app.telemetry
        if t is None:
            from emqx_tpu.observe.telemetry import Telemetry

            t = self.app.telemetry = Telemetry(self.app)
        return web.json_response(t.get_telemetry_data())

    async def node_dump(self, request):
        from emqx_tpu.utils.node_dump import collect

        return web.json_response(collect(self.app), dumps=lambda o: json.dumps(o, default=str))

    async def api_docs(self, request):
        from emqx_tpu import __version__
        from emqx_tpu.mgmt.openapi import build_spec

        spec = build_spec(
            [(m, p, s, t) for m, p, _h, s, t in ROUTES], __version__
        )
        return web.json_response(spec)

    # -- listeners (emqx_mgmt_api_listeners analog) ------------------------
    async def listeners_list(self, request):
        rows = self.app.listeners.describe()
        # worker-pool listeners (multi-process data plane) are owned by
        # the worker processes, not the in-process registry — surface
        # them so the operator sees every serving port
        rows += [
            pool.describe() for pool in getattr(self.app, "worker_pools", [])
        ]
        return web.json_response({"data": rows})

    @staticmethod
    def _listener_id(request):
        lid = request.match_info["id"]
        if ":" not in lid:
            raise ValueError("listener id is type:name")
        return lid.split(":", 1)

    async def listeners_create(self, request):
        from emqx_tpu.transport.listener import ListenerConfig

        try:
            body = await request.json()
            config = ListenerConfig(
                name=body.get("name", "default"),
                type=body.get("type", "tcp"),
                bind=body.get("bind", "127.0.0.1"),
                port=int(body.get("port", 1883)),
                max_connections=int(body.get("max_connections", 1_024_000)),
                ssl_certfile=body.get("ssl_certfile"),
                ssl_keyfile=body.get("ssl_keyfile"),
                ssl_cacertfile=body.get("ssl_cacertfile"),
                ssl_verify=bool(body.get("ssl_verify", False)),
            )
            l = await self.app.listeners.start_listener(
                config, self.app.channel_config
            )
        except (ValueError, TypeError, OSError) as e:
            return web.json_response(
                {"code": "BAD_REQUEST", "message": str(e)}, status=400
            )
        return web.json_response(
            {"id": f"{config.type}:{config.name}", "port": l.port},
            status=201,
        )

    async def listeners_delete(self, request):
        try:
            type_, name = self._listener_id(request)
        except ValueError as e:
            return web.json_response(
                {"code": "BAD_REQUEST", "message": str(e)}, status=400
            )
        if not await self.app.listeners.delete_listener(type_, name):
            return web.json_response({"code": "NOT_FOUND"}, status=404)
        return web.json_response({}, status=204)

    async def _listener_action(self, request, action):
        try:
            type_, name = self._listener_id(request)
            if action == "stop":
                ok = await self.app.listeners.stop_listener(type_, name)
                if not ok:
                    return web.json_response(
                        {"code": "NOT_FOUND"}, status=404
                    )
            elif action == "start":
                await self.app.listeners.start_stopped(type_, name)
            else:
                await self.app.listeners.restart_listener(type_, name)
        except KeyError:
            return web.json_response({"code": "NOT_FOUND"}, status=404)
        except (ValueError, OSError) as e:
            return web.json_response(
                {"code": "BAD_REQUEST", "message": str(e)}, status=400
            )
        return web.json_response({})

    async def listeners_stop(self, request):
        return await self._listener_action(request, "stop")

    async def listeners_start(self, request):
        return await self._listener_action(request, "start")

    async def listeners_restart(self, request):
        return await self._listener_action(request, "restart")

    # -- authentication chain (emqx_authn_api analog) ----------------------
    def _authn_chain(self):
        """The live AuthChain, created+attached on first REST use."""
        if self.app.authn is None:
            from emqx_tpu.broker.auth import AuthChain

            self.app.authn = AuthChain(
                [],
                allow_anonymous=self.app.config.authn.allow_anonymous,
            )
            self.app.authn.attach(self.app.hooks)
        return self.app.authn

    async def authn_list(self, request):
        chain = self.app.authn
        rows = []
        for p in chain.providers if chain else []:
            pid = getattr(p, "_api_id", None) or type(p).__name__
            rows.append(
                {"id": pid, "provider": type(p).__name__, "enable": True}
            )
        return web.json_response({"data": rows})

    async def _make_authn_provider(self, pid: str, body: dict):
        """-> (provider, connector|None); raises ValueError."""
        backend = pid.split(":", 1)[1] if ":" in pid else pid
        if backend == "built_in_database":
            from emqx_tpu.broker.auth import BuiltinDatabase

            db = BuiltinDatabase(
                user_id_type=body.get("user_id_type", "username"),
                algo=body.get("password_hash_algorithm", "pbkdf2"),
            )
            return db, None
        if backend == "jwt":
            from emqx_tpu.broker.auth import JwtAuth

            secret = body.get("secret")
            if not secret:
                raise ValueError("jwt provider needs 'secret'")
            return (
                JwtAuth(secret.encode(), body.get("verify_claims", {})),
                None,
            )
        if backend == "http":
            from emqx_tpu.auth.http import HttpAuthProvider

            if not body.get("url"):
                raise ValueError("http provider needs 'url'")
            return (
                HttpAuthProvider(
                    body["url"],
                    method=body.get("method", "POST"),
                    timeout=float(body.get("timeout", 5.0)),
                ),
                None,
            )
        if backend == "redis":
            from emqx_tpu.integration.redis import (
                RedisAuthProvider,
                RedisConnector,
            )

            server = body.get("server", "127.0.0.1:6379")
            host, _, port = server.partition(":")
            conn = RedisConnector(
                host=host or "127.0.0.1",
                port=int(port or 6379),
                db=int(body.get("database", 0)),
                password=body.get("password"),
            )
            await conn.start()
            return (
                RedisAuthProvider(
                    conn,
                    key_template=body.get("cmd_key", "mqtt_user:${username}"),
                    algo=body.get("password_hash_algorithm", "sha256"),
                ),
                conn,
            )
        if backend == "ldap":
            from emqx_tpu.integration.ldap import (
                LdapAuthProvider,
                LdapConnector,
            )

            server = body.get("server", "127.0.0.1:389")
            host, _, port = server.partition(":")
            conn = LdapConnector(
                host=host or "127.0.0.1",
                port=int(port or 389),
                bind_dn=body.get("bind_dn", ""),
                bind_password=body.get("bind_password", ""),
                base_dn=body.get("base_dn", ""),
            )
            await conn.start()
            return (
                LdapAuthProvider(
                    conn,
                    mode=body.get("method", "bind"),
                    dn_template=body.get(
                        "dn_template", "cn=${username},${base_dn}"
                    ),
                    filter_attr=body.get("filter_attr", "uid"),
                    hash_attr=body.get("hash_attr", "userPassword"),
                    algo=body.get("password_hash_algorithm", "plain"),
                ),
                conn,
            )
        if backend == "mongodb":
            from emqx_tpu.integration.mongodb import (
                MongoAuthProvider,
                MongoConnector,
            )

            server = body.get("server", "127.0.0.1:27017")
            host, _, port = server.partition(":")
            conn = MongoConnector(
                host=host or "127.0.0.1",
                port=int(port or 27017),
                username=body.get("username", ""),
                password=body.get("password", ""),
                database=body.get("database", "mqtt"),
                auth_source=body.get("auth_source", "admin"),
            )
            await conn.start()
            return (
                MongoAuthProvider(
                    conn,
                    collection=body.get("collection", "mqtt_user"),
                    filter_template=body.get("filter"),
                    algo=body.get("password_hash_algorithm", "sha256"),
                ),
                conn,
            )
        if backend in ("mysql", "postgresql", "pgsql"):
            from emqx_tpu.integration.sql_common import DEFAULT_AUTHN_QUERY

            if backend == "mysql":
                from emqx_tpu.integration.mysql import (
                    MysqlAuthProvider as Prov,
                    MysqlConnector as Conn,
                )
                default_port = 3306
            else:
                from emqx_tpu.integration.pgsql import (
                    PgsqlAuthProvider as Prov,
                    PgsqlConnector as Conn,
                )
                default_port = 5432
            server = body.get("server", "127.0.0.1")
            host, _, port = server.partition(":")
            conn = Conn(
                host=host or "127.0.0.1",
                port=int(port or default_port),
                user=body.get("username", ""),
                password=body.get("password", ""),
                database=body.get("database", ""),
            )
            await conn.start()
            return (
                Prov(
                    conn,
                    query=body.get("query", DEFAULT_AUTHN_QUERY),
                    algo=body.get("password_hash_algorithm", "sha256"),
                ),
                conn,
            )
        raise ValueError(f"unknown authn backend: {backend}")

    async def authn_create(self, request):
        try:
            body = await request.json()
            mechanism = body.get("mechanism", "password_based")
            backend = body.get("backend", "built_in_database")
            pid = f"{mechanism}:{backend}"
            if pid in self._authn_by_id:
                return web.json_response(
                    {"code": "ALREADY_EXISTS"}, status=409
                )
            provider, conn = await self._make_authn_provider(pid, body)
        except (ValueError, KeyError, TypeError, OSError) as e:
            return web.json_response(
                {"code": "BAD_REQUEST", "message": str(e)}, status=400
            )
        provider._api_id = pid
        self._authn_by_id[pid] = (provider, conn)
        self._authn_chain().providers.append(provider)
        return web.json_response({"id": pid}, status=201)

    async def authn_delete(self, request):
        pid = request.match_info["id"]
        entry = self._authn_by_id.pop(pid, None)
        chain = self.app.authn
        if entry is None or chain is None:
            return web.json_response({"code": "NOT_FOUND"}, status=404)
        provider, conn = entry
        if provider in chain.providers:
            chain.providers.remove(provider)
        if conn is not None:
            try:
                await conn.stop()
            except Exception:
                pass
        return web.json_response({}, status=204)

    def _builtin_db(self, pid):
        from emqx_tpu.broker.auth import BuiltinDatabase

        entry = self._authn_by_id.get(pid)
        provider = entry[0] if entry else None
        if (
            provider is None
            and pid == "password_based:built_in_database"
            and self.app.authn is not None
        ):
            # the config-file-created builtin database has no REST id;
            # only the canonical id may address it
            for p in self.app.authn.providers:
                if isinstance(p, BuiltinDatabase):
                    return p
        return provider if isinstance(provider, BuiltinDatabase) else None

    async def authn_users_list(self, request):
        db = self._builtin_db(request.match_info["id"])
        if db is None:
            return web.json_response({"code": "NOT_FOUND"}, status=404)
        return web.json_response({"data": db.users()})

    async def authn_users_add(self, request):
        db = self._builtin_db(request.match_info["id"])
        if db is None:
            return web.json_response({"code": "NOT_FOUND"}, status=404)
        try:
            body = await request.json()
            db.add_user(
                body["user_id"],
                body["password"],
                bool(body.get("is_superuser", False)),
            )
        except (ValueError, KeyError, TypeError) as e:
            return web.json_response(
                {"code": "BAD_REQUEST", "message": str(e)}, status=400
            )
        return web.json_response({"user_id": body["user_id"]}, status=201)

    async def authn_users_del(self, request):
        db = self._builtin_db(request.match_info["id"])
        if db is None:
            return web.json_response({"code": "NOT_FOUND"}, status=404)
        if not db.delete_user(request.match_info["user"]):
            return web.json_response({"code": "NOT_FOUND"}, status=404)
        return web.json_response({}, status=204)

    # -- authorization sources (emqx_authz_api_sources analog) -------------
    async def authz_sources_list(self, request):
        rows = []
        for s in self.app.authz.sources:
            rows.append(
                {
                    "type": getattr(s, "_api_type", type(s).__name__),
                    "enable": True,
                }
            )
        return web.json_response({"data": rows})

    async def authz_sources_create(self, request):
        try:
            body = await request.json()
            stype = body["type"]
            if any(
                getattr(s, "_api_type", None) == stype
                for s in self.app.authz.sources
            ):
                return web.json_response(
                    {"code": "ALREADY_EXISTS"}, status=409
                )
            source, conn = await self._make_authz_source(stype, body)
        except (ValueError, KeyError, TypeError, OSError) as e:
            return web.json_response(
                {"code": "BAD_REQUEST", "message": str(e)}, status=400
            )
        source._api_type = stype
        source._api_conn = conn
        self.app.authz.add_source(source)
        return web.json_response({"type": stype}, status=201)

    async def _make_authz_source(self, stype: str, body: dict):
        if stype == "http":
            from emqx_tpu.auth.http import HttpAuthzSource

            if not body.get("url"):
                raise ValueError("http source needs 'url'")
            return (
                HttpAuthzSource(
                    body["url"],
                    method=body.get("method", "POST"),
                    timeout=float(body.get("timeout", 5.0)),
                ),
                None,
            )
        if stype == "redis":
            from emqx_tpu.integration.redis import (
                RedisAuthzSource,
                RedisConnector,
            )

            server = body.get("server", "127.0.0.1:6379")
            host, _, port = server.partition(":")
            conn = RedisConnector(
                host=host or "127.0.0.1",
                port=int(port or 6379),
                db=int(body.get("database", 0)),
                password=body.get("password"),
            )
            await conn.start()
            return (
                RedisAuthzSource(
                    conn,
                    key_template=body.get("cmd_key", "mqtt_acl:${username}"),
                ),
                conn,
            )
        if stype == "mongodb":
            from emqx_tpu.integration.mongodb import (
                MongoAuthzSource,
                MongoConnector,
            )

            server = body.get("server", "127.0.0.1:27017")
            host, _, port = server.partition(":")
            conn = MongoConnector(
                host=host or "127.0.0.1",
                port=int(port or 27017),
                username=body.get("username", ""),
                password=body.get("password", ""),
                database=body.get("database", "mqtt"),
                auth_source=body.get("auth_source", "admin"),
            )
            await conn.start()
            return (
                MongoAuthzSource(
                    conn,
                    collection=body.get("collection", "mqtt_acl"),
                    filter_template=body.get("filter"),
                ),
                conn,
            )
        if stype in ("mysql", "postgresql", "pgsql"):
            from emqx_tpu.integration.sql_common import DEFAULT_AUTHZ_QUERY

            if stype == "mysql":
                from emqx_tpu.integration.mysql import (
                    MysqlAuthzSource as Src,
                    MysqlConnector as Conn,
                )
                default_port = 3306
            else:
                from emqx_tpu.integration.pgsql import (
                    PgsqlAuthzSource as Src,
                    PgsqlConnector as Conn,
                )
                default_port = 5432
            server = body.get("server", "127.0.0.1")
            host, _, port = server.partition(":")
            conn = Conn(
                host=host or "127.0.0.1",
                port=int(port or default_port),
                user=body.get("username", ""),
                password=body.get("password", ""),
                database=body.get("database", ""),
            )
            await conn.start()
            return Src(conn, query=body.get("query", DEFAULT_AUTHZ_QUERY)), conn
        raise ValueError(f"unknown authz source type: {stype}")

    def _find_authz_source(self, stype: str):
        for s in self.app.authz.sources:
            if getattr(s, "_api_type", None) == stype:
                return s
        return None

    async def authz_sources_delete(self, request):
        s = self._find_authz_source(request.match_info["type"])
        if s is None:
            return web.json_response({"code": "NOT_FOUND"}, status=404)
        self.app.authz.sources.remove(s)
        conn = getattr(s, "_api_conn", None)
        if conn is not None:
            try:
                await conn.stop()
            except Exception:
                pass
        return web.json_response({}, status=204)

    async def authz_sources_move(self, request):
        s = self._find_authz_source(request.match_info["type"])
        if s is None:
            return web.json_response({"code": "NOT_FOUND"}, status=404)
        try:
            body = await request.json()
            position = body["position"]  # front | rear | before:T | after:T | index
        except (ValueError, KeyError, TypeError):
            return web.json_response({"code": "BAD_REQUEST"}, status=400)
        src = self.app.authz.sources
        # resolve the target index BEFORE mutating, so a bad position
        # leaves the evaluation order untouched
        if position == "front":
            idx = 0
        elif position == "rear":
            idx = len(src)  # after removal this is the end
        elif isinstance(position, str) and position.partition(":")[0] in (
            "before",
            "after",
        ):
            rel, _, other_type = position.partition(":")
            other = self._find_authz_source(other_type)
            if other is None or other is s:
                return web.json_response({"code": "BAD_REQUEST"}, status=400)
            idx = src.index(other) + (1 if rel == "after" else 0)
        else:
            try:
                idx = int(position)
            except (ValueError, TypeError):
                return web.json_response({"code": "BAD_REQUEST"}, status=400)
        cur = src.index(s)
        src.remove(s)
        if cur < idx:
            idx -= 1  # removal shifted everything after s left by one
        src.insert(min(max(idx, 0), len(src)), s)
        return web.json_response({})

    # -- API keys (emqx_mgmt_auth analog) -----------------------------------
    async def api_keys_list(self, request):
        return web.json_response({"data": self.api_keys.list()})

    async def api_keys_create(self, request):
        from emqx_tpu.mgmt.api_keys import DuplicateKey

        try:
            body = await request.json()
            rec = self.api_keys.create(
                body["name"],
                description=body.get("description", ""),
                enable=bool(body.get("enable", True)),
                expired_at=body.get("expired_at"),
            )
        except DuplicateKey as e:
            return web.json_response(
                {"code": "ALREADY_EXISTS", "message": str(e)}, status=409
            )
        except (ValueError, KeyError, TypeError) as e:
            return web.json_response(
                {"code": "BAD_REQUEST", "message": str(e)}, status=400
            )
        return web.json_response(rec, status=201)

    async def api_keys_get(self, request):
        rec = self.api_keys.get(request.match_info["name"])
        if rec is None:
            return web.json_response({"code": "NOT_FOUND"}, status=404)
        return web.json_response(rec)

    async def api_keys_update(self, request):
        try:
            body = await request.json()
            rec = self.api_keys.update(
                request.match_info["name"],
                description=body.get("description"),
                enable=body.get("enable"),
                expired_at=body.get("expired_at", "unset"),
            )
        except (ValueError, TypeError) as e:
            return web.json_response(
                {"code": "BAD_REQUEST", "message": str(e)}, status=400
            )
        if rec is None:
            return web.json_response({"code": "NOT_FOUND"}, status=404)
        return web.json_response(rec)

    async def api_keys_delete(self, request):
        if not self.api_keys.delete(request.match_info["name"]):
            return web.json_response({"code": "NOT_FOUND"}, status=404)
        return web.json_response({}, status=204)

    # -- gateways (emqx_mgmt_api_gateway analog) ---------------------------
    def _gw_registry(self):
        if self.app.gateways is None:
            from emqx_tpu.app import _register_builtin_gateways
            from emqx_tpu.gateway.registry import GatewayRegistry

            self.app.gateways = GatewayRegistry(
                self.app.broker,
                self.app.hooks,
                retainer=getattr(self.app, "retainer", None),
            )
            _register_builtin_gateways(self.app.gateways)
        return self.app.gateways

    async def gateways_list(self, request):
        return web.json_response({"data": self._gw_registry().list()})

    async def gateways_one(self, request):
        gw = self._gw_registry().get(request.match_info["name"])
        if gw is None:
            return web.json_response({"code": "NOT_FOUND"}, status=404)
        return web.json_response(gw.status())

    async def gateways_load(self, request):
        body = await request.json()
        try:
            gw = await self._gw_registry().load(
                body["type"], dict(body.get("opts", {})), name=body.get("name")
            )
        except (KeyError, ValueError) as e:
            return web.json_response(
                {"code": "BAD_REQUEST", "message": str(e)}, status=400
            )
        return web.json_response(gw.status(), status=201)

    async def gateways_unload(self, request):
        ok = await self._gw_registry().unload(request.match_info["name"])
        return web.json_response(
            {} if ok else {"code": "NOT_FOUND"},
            status=204 if ok else 404,
        )

    # -- bridges (emqx_mgmt_api_bridge analog) -----------------------------
    async def bridges_list(self, request):
        b = self.app.bridges
        return web.json_response({"data": b.list() if b else []})

    async def bridges_create(self, request):
        body = await request.json()
        try:
            inst = await self.app._bridge_manager().create(
                body["id"], dict(body.get("opts", {}))
            )
        except (KeyError, ValueError) as e:
            return web.json_response(
                {"code": "BAD_REQUEST", "message": str(e)}, status=400
            )
        return web.json_response(
            {"id": inst.id, "status": inst.status}, status=201
        )

    async def bridges_delete(self, request):
        b = self.app.bridges
        ok = b is not None and await b.remove(request.match_info["id"])
        return web.json_response(
            {} if ok else {"code": "NOT_FOUND"},
            status=204 if ok else 404,
        )

    async def bridges_restart(self, request):
        b = self.app.bridges
        ok = b is not None and await b.resources.restart(
            request.match_info["id"]
        )
        return web.json_response(
            {"status": b.resources.status(request.match_info["id"])}
            if ok
            else {"code": "NOT_FOUND"},
            status=200 if ok else 404,
        )

    async def trace_download(self, request):
        content = self.app.trace.read(request.match_info["name"])
        if content is None:
            return web.json_response({"code": "NOT_FOUND"}, status=404)
        return web.Response(
            text=content,
            content_type="text/plain",
            headers={
                "Content-Disposition": (
                    f'attachment; filename="{request.match_info["name"]}.log"'
                )
            },
        )
