"""Dashboard backend: admin JWT auth + live monitor sampling/stream.

Parity: apps/emqx_dashboard/src — emqx_dashboard_admin (username/password
admins, JWT bearer tokens for the REST surface), emqx_dashboard_monitor
(periodic sampling of connection/subscription/message-rate gauges with a
bounded history, streamed over WebSocket to the UI and queryable at
/monitor_current). The SPA itself is not bundled (the reference fetches a
prebuilt web app at build time, scripts/get-dashboard.sh); a minimal
status page is served at / so the endpoint is human-usable.
"""

from __future__ import annotations

import asyncio
import hashlib
import hmac
import os
import time
from typing import Dict, List, Optional

from emqx_tpu.broker.auth import JwtAuth


class DashboardAdmin:
    """Admin credential store + JWT mint/verify (emqx_dashboard_admin)."""

    def __init__(self, admins: Dict[str, str], ttl: float = 3600.0,
                 secret: Optional[bytes] = None):
        self.ttl = ttl
        self.secret = secret or os.urandom(32)
        self._users: Dict[str, tuple] = {}
        for user, password in admins.items():
            self.add_admin(user, password)

    def add_admin(self, user: str, password: str) -> None:
        salt = os.urandom(16)
        self._users[user] = (
            salt,
            hashlib.pbkdf2_hmac("sha256", password.encode(), salt, 10000),
        )

    def login(self, user: str, password: str) -> Optional[str]:
        ent = self._users.get(user)
        if ent is None:
            return None
        salt, phash = ent
        cand = hashlib.pbkdf2_hmac("sha256", password.encode(), salt, 10000)
        if not hmac.compare_digest(cand, phash):
            return None
        return JwtAuth.sign(
            self.secret,
            {"sub": user, "exp": time.time() + self.ttl, "iss": "emqx_tpu"},
        )

    def verify(self, token: str) -> Optional[str]:
        """-> username or None."""
        auth = JwtAuth(self.secret)
        ci: Dict = {}
        result, _rc = auth.authenticate(ci, {"password": token.encode()})
        if result != "ok":
            return None
        claims = ci.get("jwt_claims", {})
        return claims.get("sub")

    def has_admins(self) -> bool:
        return bool(self._users)


class Monitor:
    """Bounded ring of periodic samples (emqx_dashboard_monitor)."""

    def __init__(self, app, interval: float = 5.0, history: int = 360):
        self.app = app
        self.interval = interval
        self.history = history
        self.samples: List[Dict] = []
        self._task: Optional[asyncio.Task] = None
        self._subscribers: List[asyncio.Queue] = []
        self._last_counters: Dict[str, float] = {}

    def sample(self, update_baseline: bool = False) -> Dict:
        """One sample. Only the periodic loop passes update_baseline=True —
        ad-hoc REST/WS reads must not reset the rate window (two fast
        polls would otherwise produce garbage per-interval rates)."""
        m = self.app.broker.metrics.snapshot()
        now = time.time()
        recv = m.get("messages.received", 0)
        sent = m.get("messages.delivered", 0)
        last = self._last_counters
        dt = max(now - last.get("at", now), 1e-9) if last else None
        s = {
            "at": int(now * 1000),
            "connections": self.app.cm.channel_count(),
            "subscriptions": self.app.broker.subscription_count(),
            "topics": len(self.app.broker.router),
            "retained": len(self.app.retainer),
            "received": recv,
            "sent": sent,
            "received_rate": round((recv - last.get("recv", recv)) / dt, 2)
            if dt
            else 0.0,
            "sent_rate": round((sent - last.get("sent", sent)) / dt, 2)
            if dt
            else 0.0,
        }
        if update_baseline:
            self._last_counters = {"at": now, "recv": recv, "sent": sent}
        return s

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.get_event_loop().create_task(self._loop())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def _loop(self) -> None:
        try:
            while True:
                s = self.sample(update_baseline=True)
                self.samples.append(s)
                if len(self.samples) > self.history:
                    del self.samples[: -self.history]
                for q in list(self._subscribers):
                    if q.qsize() < 16:
                        q.put_nowait(s)
                await asyncio.sleep(self.interval)
        except asyncio.CancelledError:
            pass

    def subscribe(self) -> asyncio.Queue:
        q: asyncio.Queue = asyncio.Queue()
        self._subscribers.append(q)
        return q

    def unsubscribe(self, q: asyncio.Queue) -> None:
        if q in self._subscribers:
            self._subscribers.remove(q)


STATUS_PAGE = """<!doctype html>
<html><head><title>emqx_tpu dashboard</title>
<style>body{font-family:system-ui;margin:2rem;max-width:46rem}
table{border-collapse:collapse}td,th{padding:.3rem .8rem;border:1px solid #ccc}
code{background:#f4f4f4;padding:0 .3rem}</style></head>
<body><h1>emqx_tpu</h1>
<p>TPU-native MQTT broker. Management API at <code>/api/v5</code>,
OpenAPI at <code>/api-docs</code>, live samples at
<code>/api/v5/monitor_current</code>, stream at
<code>WS /api/v5/monitor</code>.</p>
<table id="t"><tr><th>metric</th><th>value</th></tr></table>
<script>
async function tick(){
  const r = await fetch('/api/v5/monitor_current');
  if(!r.ok) return;
  const d = await r.json();
  const t = document.getElementById('t');
  while(t.rows.length>1) t.deleteRow(1);
  for(const [k,v] of Object.entries(d)){
    const row = t.insertRow(); row.insertCell().textContent = k;
    row.insertCell().textContent = v;
  }
}
tick(); setInterval(tick, 5000);
</script></body></html>
"""
