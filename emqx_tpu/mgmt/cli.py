"""Admin CLI (reference: bin/emqx_ctl -> emqx_ctl command registry ->
emqx_mgmt_cli.erl). Talks to the running broker's REST API.

Usage: python -m emqx_tpu.mgmt.cli [--url http://127.0.0.1:18083] [--key K] CMD
Commands: status | metrics | stats | clients | client <id> | kick <id> |
subscriptions | routes | publish <topic> <payload> [--qos N] [--retain] |
banned | ban <kind> <value> | unban <kind> <value> | retained | configs |
set_config <path> <json> | gateways | gateway_load <type> <opts-json> |
gateway_unload <name> | bridges | bridge_create <id> <opts-json> |
bridge_restart <id> | bridge_delete <id> | plugins |
plugin_install <path> | plugin_start <ref> | plugin_stop <ref> |
plugin_uninstall <ref> | monitor | telemetry | rules | alarms | trace |
node_dump
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.error
import urllib.request


def _call(url: str, key: str, method: str = "GET", body=None):
    req = urllib.request.Request(url, method=method)
    if key:
        req.add_header("Authorization", f"Bearer {key}")
    data = None
    if body is not None:
        req.add_header("Content-Type", "application/json")
        data = json.dumps(body).encode()
    try:
        with urllib.request.urlopen(req, data=data, timeout=10) as resp:
            text = resp.read().decode() or "{}"
            return resp.status, json.loads(text)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode() or "{}")
    except urllib.error.URLError as e:
        return 599, {"code": "UNREACHABLE", "message": str(e.reason)}


# command -> minimum positional args after the command word
_MIN_ARGS = {
    "client": 1,
    "kick": 1,
    "publish": 1,
    "ban": 2,
    "unban": 2,
    "set_config": 2,
    "gateway_load": 1,
    "gateway_unload": 1,
    "bridge_create": 2,
    "bridge_restart": 1,
    "bridge_delete": 1,
    "plugin_install": 1,
    "plugin_start": 1,
    "plugin_stop": 1,
    "plugin_uninstall": 1,
}


def _json_arg(s: str):
    """Strict parse for arguments documented as <json>: a typo must fail
    loudly client-side, not travel as a quoted string."""
    try:
        return json.loads(s)
    except ValueError as e:
        print(f"invalid JSON argument {s!r}: {e}", file=sys.stderr)
        raise SystemExit(2)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="emqx_tpu_ctl", description=__doc__)
    ap.add_argument("--url", default="http://127.0.0.1:18083")
    ap.add_argument("--key", default="")
    ap.add_argument("cmd", nargs=argparse.REMAINDER)
    a = ap.parse_args(argv)
    if not a.cmd:
        ap.print_usage(sys.stderr)
        return 2
    base = a.url.rstrip("/") + "/api/v5"
    cmd, *rest = a.cmd
    # split flags (--retain, --qos N) from positional arguments
    flags: dict = {}
    positional: list = []
    i = 0
    while i < len(rest):
        tok = rest[i]
        if tok == "--qos":
            if i + 1 >= len(rest):
                print("--qos needs a value", file=sys.stderr)
                return 2
            try:
                flags["qos"] = int(rest[i + 1])
            except ValueError:
                print(f"--qos: bad value {rest[i + 1]!r}", file=sys.stderr)
                return 2
            i += 2
        elif tok == "--retain":
            flags["retain"] = True
            i += 1
        elif tok.startswith("--"):
            print(f"unknown flag {tok}", file=sys.stderr)
            return 2
        else:
            positional.append(tok)
            i += 1
    rest = positional
    if len(positional) < _MIN_ARGS.get(cmd, 0):
        print(
            f"{cmd}: expected at least {_MIN_ARGS[cmd]} argument(s)",
            file=sys.stderr,
        )
        return 2

    if cmd in ("status", "metrics", "stats", "subscriptions", "routes",
               "configs", "cluster"):
        code, out = _call(f"{base}/{cmd}", a.key)
    elif cmd == "drain":
        # `emqx_tpu_ctl drain [peer_node]` — rolling-upgrade drain
        body = {"peer": rest[0]} if rest else {}
        code, out = _call(f"{base}/nodes/drain", a.key, "POST", body)
    elif cmd == "clients":
        code, out = _call(f"{base}/clients", a.key)
    elif cmd == "client":
        code, out = _call(f"{base}/clients/{rest[0]}", a.key)
    elif cmd == "kick":
        code, out = _call(f"{base}/clients/{rest[0]}", a.key, "DELETE")
    elif cmd == "publish":
        body = {"topic": rest[0], "payload": rest[1] if len(rest) > 1 else ""}
        body.update(flags)
        code, out = _call(f"{base}/publish", a.key, "POST", body)
    elif cmd == "banned":
        code, out = _call(f"{base}/banned", a.key)
    elif cmd == "ban":
        code, out = _call(
            f"{base}/banned", a.key, "POST", {"as": rest[0], "who": rest[1]}
        )
    elif cmd == "unban":
        code, out = _call(f"{base}/banned/{rest[0]}/{rest[1]}", a.key, "DELETE")
    elif cmd == "retained":
        code, out = _call(f"{base}/retainer/messages", a.key)
    elif cmd == "set_config":
        code, out = _call(
            f"{base}/configs/{rest[0].replace('.', '/')}",
            a.key,
            "PUT",
            _json_arg(rest[1]),
        )
    elif cmd == "gateways":
        code, out = _call(f"{base}/gateways", a.key)
    elif cmd == "gateway_load":
        body = {"type": rest[0]}
        if len(rest) > 1:
            body["opts"] = _json_arg(rest[1])
        code, out = _call(f"{base}/gateways", a.key, "POST", body)
    elif cmd == "gateway_unload":
        code, out = _call(f"{base}/gateways/{rest[0]}", a.key, "DELETE")
    elif cmd == "bridges":
        code, out = _call(f"{base}/bridges", a.key)
    elif cmd == "bridge_create":
        code, out = _call(
            f"{base}/bridges", a.key, "POST",
            {"id": rest[0], "opts": _json_arg(rest[1])},
        )
    elif cmd == "bridge_restart":
        code, out = _call(f"{base}/bridges/{rest[0]}/restart", a.key, "POST")
    elif cmd == "bridge_delete":
        code, out = _call(f"{base}/bridges/{rest[0]}", a.key, "DELETE")
    elif cmd == "plugins":
        code, out = _call(f"{base}/plugins", a.key)
    elif cmd == "plugin_install":
        code, out = _call(
            f"{base}/plugins/install", a.key, "POST", {"path": rest[0]}
        )
    elif cmd == "plugin_start":
        code, out = _call(f"{base}/plugins/{rest[0]}/start", a.key, "PUT")
    elif cmd == "plugin_stop":
        code, out = _call(f"{base}/plugins/{rest[0]}/stop", a.key, "PUT")
    elif cmd == "plugin_uninstall":
        code, out = _call(f"{base}/plugins/{rest[0]}", a.key, "DELETE")
    elif cmd == "monitor":
        code, out = _call(f"{base}/monitor_current", a.key)
    elif cmd == "telemetry":
        code, out = _call(f"{base}/telemetry/data", a.key)
    elif cmd == "rules":
        code, out = _call(f"{base}/rules", a.key)
    elif cmd == "alarms":
        code, out = _call(f"{base}/alarms", a.key)
    elif cmd == "trace":
        code, out = _call(f"{base}/trace", a.key)
    elif cmd == "node_dump":
        code, out = _call(f"{base}/node_dump", a.key)
    else:
        print(f"unknown command: {cmd}", file=sys.stderr)
        return 2
    print(json.dumps(out, indent=2, default=str))
    return 0 if code < 400 else 1


if __name__ == "__main__":
    sys.exit(main())
