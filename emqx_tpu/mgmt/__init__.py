"""Management plane: REST API + CLI (reference: apps/emqx_management,
apps/emqx_dashboard backend, emqx_ctl)."""
