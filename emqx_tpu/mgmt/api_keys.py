"""Machine API keys for the management REST surface.

Parity: apps/emqx_management/src/emqx_mgmt_auth.erl — named API keys
(api_key + api_secret pairs) with enable flag, expiry, and description.
The secret is generated server-side, returned exactly once at creation,
and stored only as a salted SHA-256 hash (the reference stores a
pbkdf2-style hash in mnesia).

Used from the REST auth middleware via HTTP Basic ``api_key:api_secret``.
"""

from __future__ import annotations

import hashlib
import hmac
import secrets
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional


class DuplicateKey(ValueError):
    """Key name already taken (distinct from validation errors so the
    REST layer can map 409 vs 400)."""


@dataclass
class ApiKey:
    name: str
    api_key: str
    secret_hash: bytes
    salt: bytes
    description: str = ""
    enable: bool = True
    expired_at: Optional[float] = None  # epoch seconds, None = never
    created_at: float = field(default_factory=time.time)

    def expired(self, now: Optional[float] = None) -> bool:
        return (
            self.expired_at is not None
            and (now or time.time()) >= self.expired_at
        )

    def as_dict(self) -> Dict:
        return {
            "name": self.name,
            "api_key": self.api_key,
            "description": self.description,
            "enable": self.enable,
            "expired_at": self.expired_at,
            "created_at": self.created_at,
            "expired": self.expired(),
        }


def _hash(secret: str, salt: bytes) -> bytes:
    return hashlib.sha256(salt + secret.encode()).digest()


class ApiKeyStore:
    def __init__(self):
        self._keys: Dict[str, ApiKey] = {}  # name -> key
        self._by_key: Dict[str, str] = {}  # api_key -> name

    @staticmethod
    def _coerce_expiry(expired_at) -> Optional[float]:
        """Accept epoch seconds or an RFC3339/ISO string (the EMQX wire
        format); raise ValueError otherwise."""
        if expired_at is None or isinstance(expired_at, (int, float)):
            return expired_at
        if isinstance(expired_at, str):
            from datetime import datetime

            return datetime.fromisoformat(expired_at).timestamp()
        raise ValueError("expired_at must be epoch seconds or ISO-8601")

    def has_keys(self) -> bool:
        return bool(self._keys)

    def create(
        self,
        name: str,
        description: str = "",
        enable: bool = True,
        expired_at: Optional[float] = None,
    ) -> Dict:
        """-> the api_key/api_secret pair; the secret is never shown again
        (emqx_mgmt_auth create semantics)."""
        expired_at = self._coerce_expiry(expired_at)  # before any mutation
        if name in self._keys:
            raise DuplicateKey(f"api key exists: {name}")
        api_key = secrets.token_urlsafe(12)
        api_secret = secrets.token_urlsafe(24)
        salt = secrets.token_bytes(16)
        rec = ApiKey(
            name=name,
            api_key=api_key,
            secret_hash=_hash(api_secret, salt),
            salt=salt,
            description=description,
            enable=enable,
            expired_at=expired_at,
        )
        self._keys[name] = rec
        self._by_key[api_key] = name
        out = rec.as_dict()
        out["api_secret"] = api_secret
        return out

    def verify(self, api_key: str, api_secret: str) -> bool:
        name = self._by_key.get(api_key)
        rec = self._keys.get(name) if name else None
        if rec is None or not rec.enable or rec.expired():
            return False
        return hmac.compare_digest(
            _hash(api_secret, rec.salt), rec.secret_hash
        )

    def update(
        self,
        name: str,
        description: Optional[str] = None,
        enable: Optional[bool] = None,
        expired_at: object = "unset",
    ) -> Optional[Dict]:
        rec = self._keys.get(name)
        if rec is None:
            return None
        if expired_at != "unset":  # validate BEFORE any mutation
            expired_at = self._coerce_expiry(expired_at)
        if description is not None:
            rec.description = description
        if enable is not None:
            rec.enable = enable
        if expired_at != "unset":
            rec.expired_at = expired_at
        return rec.as_dict()

    def delete(self, name: str) -> bool:
        rec = self._keys.pop(name, None)
        if rec is not None:
            self._by_key.pop(rec.api_key, None)
        return rec is not None

    def get(self, name: str) -> Optional[Dict]:
        rec = self._keys.get(name)
        return rec.as_dict() if rec else None

    def list(self) -> List[Dict]:
        return [k.as_dict() for k in self._keys.values()]
