"""Device index for retained-message replay storms.

BASELINE config 5 is a retained-replay storm: a wildcard SUBSCRIBE against
millions of retained messages. The reference walks its retained-topic
table per subscribe (emqx_retainer_mnesia.erl:146-152 match_messages) —
O(store) per subscriber.

TPU-native inversion of the routing kernel: the stored retained TOPICS
are the batch, and the incoming subscribe FILTER becomes a one-entry
shape-index table. One `shape_route_step` launch per chunk of stored
topics answers "which retained topics match this filter" as a dense
match matrix — the same kernel that routes publishes, pointed the other
way. Topics are pre-tokenized into pinned device chunks at insert time,
so a replay query is pure kernel launches + one small readback per chunk.

Matches are re-verified on host (`T.match`) before use — kernel caps and
hash collisions can only cost a false candidate, never a wrong replay.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from emqx_tpu.ops import topics as T

CHUNK = 1 << 18  # 262144 topics per device launch


class DeviceRetainedIndex:
    def __init__(self, max_bytes: int = 64, max_levels: int = 8):
        self.max_bytes = max_bytes
        self.max_levels = max_levels
        self._rows: Dict[str, int] = {}  # topic -> global row
        self._by_row: List[Optional[str]] = []
        self._free: List[int] = []
        # host chunks; device mirrors uploaded lazily per query
        self._host_b: List[np.ndarray] = []  # [CHUNK, max_bytes] uint8
        self._host_l: List[np.ndarray] = []  # [CHUNK] int32
        self._dev: List[Optional[tuple]] = []  # (bytes, lens) or None=dirty

    def __len__(self) -> int:
        return len(self._rows)

    # -- mutation ----------------------------------------------------------
    def add(self, topic: str) -> bool:
        """False when the topic doesn't fit the device budget (too long /
        too deep) — the caller's CPU path remains authoritative for it."""
        if topic in self._rows:
            return True
        enc = topic.encode()
        if len(enc) > self.max_bytes or len(T.words(topic)) > self.max_levels:
            return False
        if self._free:
            row = self._free.pop()
            self._by_row[row] = topic
        else:
            row = len(self._by_row)
            self._by_row.append(topic)
            if row >= len(self._host_b) * CHUNK:
                self._host_b.append(
                    np.zeros((CHUNK, self.max_bytes), np.uint8)
                )
                self._host_l.append(np.zeros(CHUNK, np.int32))
                self._dev.append(None)
        self._rows[topic] = row
        c, i = divmod(row, CHUNK)
        self._host_b[c][i, : len(enc)] = np.frombuffer(enc, np.uint8)
        self._host_b[c][i, len(enc):] = 0
        self._host_l[c][i] = len(enc)
        self._dev[c] = None  # dirty
        return True

    def bulk_add(self, topics: List[str]) -> int:
        """Vectorized initial load (restore / bench); returns count added.
        Topics must fit the device budget (raises otherwise — callers
        pre-filter, the same contract `add` enforces per topic)."""
        from emqx_tpu.ops.tokenizer import encode_topics

        fresh = [t for t in topics if t not in self._rows]
        for t in fresh:
            if len(T.words(t)) > self.max_levels:
                raise ValueError(f"bulk_add: topic too deep: {t!r}")
        pos = 0
        while pos < len(fresh):
            # fill the tail of the current chunk
            row0 = len(self._by_row)
            c, i0 = divmod(row0, CHUNK)
            if c >= len(self._host_b):
                self._host_b.append(np.zeros((CHUNK, self.max_bytes), np.uint8))
                self._host_l.append(np.zeros(CHUNK, np.int32))
                self._dev.append(None)
            take = min(CHUNK - i0, len(fresh) - pos)
            batch = fresh[pos : pos + take]
            mat, lens, too_long = encode_topics(batch, self.max_bytes)
            if too_long.any():
                raise ValueError("bulk_add: topic exceeds max_bytes")
            self._host_b[c][i0 : i0 + take] = mat
            self._host_l[c][i0 : i0 + take] = lens
            self._dev[c] = None
            for k, t in enumerate(batch):
                self._rows[t] = row0 + k
            self._by_row.extend(batch)
            pos += take
        return len(fresh)

    def remove(self, topic: str) -> None:
        row = self._rows.pop(topic, None)
        if row is None:
            return
        self._by_row[row] = None
        self._free.append(row)
        c, i = divmod(row, CHUNK)
        self._host_l[c][i] = 0  # len-0 rows tokenize to zero words
        self._host_b[c][i, :] = 0
        self._dev[c] = None

    # -- query ------------------------------------------------------------
    def match(self, filter_: str) -> Optional[List[str]]:
        """Retained topics matching `filter_`, or None when the filter
        itself exceeds the device budget (caller falls back to CPU)."""
        import jax
        import jax.numpy as jnp

        from emqx_tpu.models.router_model import shape_route_step
        from emqx_tpu.ops.nfa import _next_pow2
        from emqx_tpu.ops.route_index import RouteIndex

        if len(T.words(filter_)) > self.max_levels:
            return None
        idx = RouteIndex()
        idx.add(filter_)
        shape_tables = {
            k: jax.device_put(v.copy())
            for k, v in idx.shapes.device_snapshot().items()
        }
        with_nfa = idx.residual_count > 0
        nfa_tables = (
            {
                k: jax.device_put(v.copy())
                for k, v in idx.nfa.device_snapshot().items()
            }
            if with_nfa
            else None
        )
        m_active = idx.shapes.m_active()
        out: List[str] = []
        outs = []
        for c in range(len(self._host_b)):
            if self._dev[c] is None:
                self._dev[c] = (
                    jax.device_put(self._host_b[c]),
                    jax.device_put(self._host_l[c]),
                )
            bm, ln = self._dev[c]
            r = shape_route_step(
                shape_tables,
                nfa_tables,
                None,
                bm,
                ln,
                m_active=m_active,
                with_nfa=with_nfa,
                salt=idx.salt,
                max_levels=self.max_levels,
            )
            # dispatch all chunks before reading any back (pipelining)
            outs.append((c, r["mcount"]))
        nrows = len(self._by_row)
        for c, mcount in outs:
            hit_rows = np.nonzero(np.asarray(mcount))[0]
            base = c * CHUNK
            for i in hit_rows:
                row = base + int(i)
                # padding rows (len 0) can match plen-0 filters like '#'
                t = self._by_row[row] if row < nrows else None
                # host verification: false candidates cost a check, false
                # replay would cost correctness
                if t is not None and T.match(t, filter_):
                    out.append(t)
        return out

    def match_many(self, filters: List[str]) -> Dict[str, np.ndarray]:
        """Answer a replay STORM: many wildcard subscribes in one pass.

        All filters enter ONE shape table; each chunk launch matches every
        stored topic against every filter simultaneously, and the [B, M]
        result (one fid lane per filter shape — within a shape at most one
        filter matches a topic, so the lanes are exact) scatters rows to
        subscribers. Per-storm cost is the same handful of kernel launches
        a single filter pays — the storm amortizes to ~O(1) passes, vs the
        reference's O(store) walk PER subscriber.

        Returns {filter: row-index array}; materialize topics lazily with
        `topic_at`. Unlike `match`, hits are spot-checked (sampled), not
        exhaustively re-verified — the 2^-64 combined-hash collision class
        is accepted here, matching the module's differential test gate.
        """
        import jax
        import jax.numpy as jnp

        from emqx_tpu.models.router_model import shape_route_step
        from emqx_tpu.ops.nfa import _next_pow2
        from emqx_tpu.ops.route_index import RouteIndex

        idx = RouteIndex()
        fids: Dict[int, str] = {}
        for f in filters:
            if len(T.words(f)) > self.max_levels:
                raise ValueError(f"filter too deep for device budget: {f}")
            fids[idx.add(f)] = f
        shape_tables = {
            k: jax.device_put(v.copy())
            for k, v in idx.shapes.device_snapshot().items()
        }
        with_nfa = idx.residual_count > 0
        nfa_tables = (
            {
                k: jax.device_put(v.copy())
                for k, v in idx.nfa.device_snapshot().items()
            }
            if with_nfa
            else None
        )
        m_active = idx.shapes.m_active(floor=1)
        outs = []
        for c in range(len(self._host_b)):
            if self._dev[c] is None:
                self._dev[c] = (
                    jax.device_put(self._host_b[c]),
                    jax.device_put(self._host_l[c]),
                )
            bm, ln = self._dev[c]
            r = shape_route_step(
                shape_tables,
                nfa_tables,
                None,
                bm,
                ln,
                m_active=m_active,
                with_nfa=with_nfa,
                salt=idx.salt,
                max_levels=self.max_levels,
            )
            outs.append((c, r["matched"]))
        nrows = len(self._by_row)
        # vectorized liveness mask: tombstoned rows (removed topics) can
        # still match plen-0 filters like '#' via their zeroed length
        live = np.zeros(nrows, dtype=bool)
        for r, t in enumerate(self._by_row):
            live[r] = t is not None
        by_fid: Dict[int, List[np.ndarray]] = {}
        rng = np.random.default_rng(0)
        checked = 0
        for c, matched in outs:
            m = np.asarray(matched)  # [CHUNK, M(+K)]
            base = c * CHUNK
            for lane in range(m.shape[1]):
                col = m[:, lane]
                rows = np.nonzero(col >= 0)[0]
                if not len(rows):
                    continue
                rows_g = rows + base
                keep = rows_g < nrows
                rows, rows_g = rows[keep], rows_g[keep]
                keep = live[rows_g]
                rows, rows_g = rows[keep], rows_g[keep]
                for fid in np.unique(col[rows]):
                    sel = rows_g[col[rows] == fid]
                    by_fid.setdefault(int(fid), []).append(sel)
                    if checked < 64 and len(sel):  # sampled verification
                        row = int(rng.choice(sel))
                        t = self._by_row[row]
                        f = fids.get(int(fid))
                        assert f is None or T.match(t, f), (t, f)
                        checked += 1
        out: Dict[str, np.ndarray] = {f: np.empty(0, np.int64) for f in filters}
        for fid, chunks in by_fid.items():
            f = fids.get(fid)
            if f is not None:
                out[f] = np.concatenate(chunks)
        return out

    def topic_at(self, row: int) -> Optional[str]:
        return self._by_row[row] if 0 <= row < len(self._by_row) else None
