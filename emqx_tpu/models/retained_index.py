"""Device index for retained-message replay storms.

BASELINE config 5 is a retained-replay storm: a wildcard SUBSCRIBE against
millions of retained messages. The reference walks its retained-topic
table per subscribe (emqx_retainer_mnesia.erl:146-152 match_messages) —
O(store) per subscriber.

TPU-native inversion of the routing kernel: the stored retained TOPICS
are the batch, and the incoming subscribe FILTER becomes a one-entry
shape-index table. One `shape_route_step` launch per chunk of stored
topics answers "which retained topics match this filter" as a dense
match matrix — the same kernel that routes publishes, pointed the other
way. Topics are pre-tokenized into pinned device chunks at insert time,
so a replay query is pure kernel launches + one small readback per chunk.

Matches are re-verified on host (`T.match`) before use — kernel caps and
hash collisions can only cost a false candidate, never a wrong replay.
"""

from __future__ import annotations

from typing import Dict, List, NamedTuple, Optional

import numpy as np

from emqx_tpu.ops import topics as T


class StormJob(NamedTuple):
    """A prepared replay storm, ready to ride a serving-path launch.

    Built on the event-loop thread (`DeviceRetainedIndex.prepare_storm`)
    so the table build and chunk uploads never race host mutation; the
    tuple is immutable device state safe to hand to an executor thread
    (the same contract as `DeviceRouter.prepare`). `decode` turns the
    per-chunk match matrices (host numpy) back into {filter: row-index
    array} — device-free, so it runs wherever the readback landed.
    """

    index: "DeviceRetainedIndex"
    filters: List[str]
    fids: Dict[int, str]
    shape_tables: Dict
    nfa_tables: Optional[Dict]
    kwargs: Dict
    chunks: List[object]  # device chunk buffers (uploaded)
    nrows: int  # live-row high-water at prepare time

    def decode(self, matched_list) -> Dict[str, np.ndarray]:
        return self.index._decode_storm(
            self.fids, self.filters, matched_list, self.nrows
        )


def _retained_step(
    shape_tables, nfa_tables, bm, *, m_active, with_nfa, salt, max_levels,
    narrow,
):
    """Storm launch: lengths derived on-device (topics cannot contain
    NUL — emqx_topic validate rejects it — so length = count of nonzero
    bytes), which removes the lengths operand from every launch; the
    result is narrowed to int16 when fids fit. Every byte crossing the
    host<->device link per launch is paid per storm, so operands are
    kept minimal."""
    import jax.numpy as jnp

    from emqx_tpu.models.router_model import shape_route_step_impl

    ln = jnp.sum((bm != 0).astype(jnp.int32), axis=1)
    out = shape_route_step_impl(
        shape_tables,
        nfa_tables,
        None,
        bm,
        ln,
        m_active=m_active,
        with_nfa=with_nfa,
        salt=salt,
        max_levels=max_levels,
    )
    m = out["matched"]
    return m.astype(jnp.int16) if narrow else m


_retained_step_jit = None


def _get_retained_step():
    global _retained_step_jit
    if _retained_step_jit is None:
        import jax
        from functools import partial

        _retained_step_jit = partial(
            jax.jit,
            static_argnames=(
                "m_active", "with_nfa", "salt", "max_levels", "narrow"
            ),
        )(_retained_step)
    return _retained_step_jit


# Topics per device launch. Sized large: per-launch dispatch overhead
# (host->device descriptor round-trips; ~hundreds of ms through a dev
# tunnel) dominates the kernel's per-row cost, so fewer, bigger launches
# win. One chunk = 64MB of topic bytes + 4MB lengths in HBM.
CHUNK = 1 << 20


class DeviceRetainedIndex:
    # retained churn is row-granular (up to `bucket` logged bytes per
    # insert/delete), so the op-log cap sits higher than the index
    # sources' — a full chunk re-upload is 64MB on the link
    OPLOG_MAX = 1 << 18

    def __init__(self, max_bytes: int = 64, max_levels: int = 8,
                 mesh=None):
        """`mesh`: a ('dp','tp') jax Mesh — chunk mirrors then upload
        through the segment manager pre-sharded (rows over 'dp', the
        layout `dist_fused_step` scans), and storm filter tables place
        replicated like every other match table. None = single-device
        placement, unchanged."""
        self.max_bytes = max_bytes  # hard cap (device-budget gate)
        self.max_levels = max_levels
        self.mesh = mesh
        # actual storage width: a pow2 bucket grown to the longest stored
        # topic. Every storm moves chunk bytes across the host<->device
        # link at least once, so padding to the cap when topics are short
        # doubles or quadruples the transfer for nothing.
        self.bucket = min(16, max_bytes)
        self._rows: Dict[str, int] = {}  # topic -> global row
        self._by_row: List[Optional[str]] = []
        self._free: List[int] = []
        self._tombstones = 0  # live rows removed (match_many fast path)
        # host chunks, mirrored on device by the ONE segment-table
        # manager (ops/segments.py): retained add/remove reaches the
        # device as row scatters (delta-overlay protocol), a fresh chunk
        # re-uploads alone (resync marker), and only a bucket-width
        # change pays a full re-upload (epoch bump). The manager's lock +
        # torn-version guard covers storm uploads running on executor
        # threads while the loop thread inserts.
        # device_snapshot builds the chunk_N names dynamically, so the
        # OL checker discovers the backing store from this annotation:
        self._host_b: List[np.ndarray] = []  # mirrored-array
        from emqx_tpu.ops.segments import DeviceSegmentManager

        if mesh is not None:
            from emqx_tpu.parallel.mesh import (
                retained_placement,
                table_placement,
            )

            self._seg = DeviceSegmentManager(
                placement=retained_placement(mesh), name="retained"
            )
            self._table_place = table_placement(mesh)
        else:
            self._seg = DeviceSegmentManager(name="retained")
            self._table_place = None
        self.epoch = 0
        self.oplog: list = []
        self.version = 0

    # -- delta protocol -----------------------------------------------------
    def device_snapshot(self) -> Dict[str, np.ndarray]:
        return {f"chunk_{c}": b for c, b in enumerate(self._host_b)}

    def _bump_epoch(self) -> None:
        self.epoch += 1
        self.oplog.clear()
        self.version += 1

    def _log_resync(self, name: str) -> None:
        self.version += 1
        if len(self.oplog) >= self.OPLOG_MAX:
            self._bump_epoch()
            return
        from emqx_tpu.ops.segments import RESYNC

        self.oplog.append((RESYNC, name, 0))

    def _log_row(self, c: int, i: int) -> None:
        """Op-log one row's bytes (post-write): the delta scatter replays
        the whole `bucket`-wide row, trailing zeros included, so the
        on-device length derivation stays exact."""
        self.version += 1
        if len(self.oplog) >= self.OPLOG_MAX:
            self._bump_epoch()
            return
        row = self._host_b[c][i]
        base = i * self.bucket
        name = f"chunk_{c}"
        for b in range(self.bucket):
            self.oplog.append((name, base + b, int(row[b])))

    def _grow_bucket(self, need: int) -> None:
        from emqx_tpu.ops.nfa import _next_pow2

        nb = min(max(self.bucket, _next_pow2(need)), self.max_bytes)
        if nb == self.bucket:
            return
        for c in range(len(self._host_b)):
            new = np.zeros((CHUNK, nb), np.uint8)
            new[:, : self.bucket] = self._host_b[c]
            self._host_b[c] = new
        self.bucket = nb
        self._bump_epoch()  # every chunk changed geometry: full upload

    def __len__(self) -> int:
        return len(self._rows)

    # -- mutation ----------------------------------------------------------
    def add(self, topic: str) -> bool:
        """False when the topic doesn't fit the device budget (too long /
        too deep) — the caller's CPU path remains authoritative for it."""
        if topic in self._rows:
            return True
        enc = topic.encode()
        if len(enc) > self.max_bytes or len(T.words(topic)) > self.max_levels:
            return False
        if len(enc) > self.bucket:
            self._grow_bucket(len(enc))
        if self._free:
            row = self._free.pop()
            self._by_row[row] = topic
            self._tombstones -= 1
        else:
            row = len(self._by_row)
            self._by_row.append(topic)
            if row >= len(self._host_b) * CHUNK:
                self._host_b.append(
                    np.zeros((CHUNK, self.bucket), np.uint8)
                )
                # a fresh chunk re-uploads ALONE; existing chunks'
                # mirrors are untouched
                self._log_resync(f"chunk_{len(self._host_b) - 1}")
        self._rows[topic] = row
        c, i = divmod(row, CHUNK)
        self._host_b[c][i, : len(enc)] = np.frombuffer(enc, np.uint8)
        self._host_b[c][i, len(enc):] = 0
        self._log_row(c, i)
        return True

    def bulk_add(self, topics: List[str]) -> int:
        """Vectorized initial load (restore / bench); returns count added.
        Topics must fit the device budget (raises otherwise — callers
        pre-filter, the same contract `add` enforces per topic)."""
        from emqx_tpu.ops.tokenizer import encode_topics

        fresh = [t for t in topics if t not in self._rows]
        longest = 0
        for t in fresh:
            if len(T.words(t)) > self.max_levels:
                raise ValueError(f"bulk_add: topic too deep: {t!r}")
            longest = max(longest, len(t.encode()))
        if longest > self.bucket:
            self._grow_bucket(longest)
        pos = 0
        while pos < len(fresh):
            # fill the tail of the current chunk
            row0 = len(self._by_row)
            c, i0 = divmod(row0, CHUNK)
            if c >= len(self._host_b):
                self._host_b.append(np.zeros((CHUNK, self.bucket), np.uint8))
            take = min(CHUNK - i0, len(fresh) - pos)
            batch = fresh[pos : pos + take]
            mat, _lens, too_long = encode_topics(batch, self.bucket)
            if too_long.any():
                raise ValueError("bulk_add: topic exceeds max_bytes")
            self._host_b[c][i0 : i0 + take] = mat
            # slab write: re-upload the touched chunk wholesale instead
            # of logging CHUNK x bucket scalar deltas
            self._log_resync(f"chunk_{c}")
            for k, t in enumerate(batch):
                self._rows[t] = row0 + k
            self._by_row.extend(batch)
            pos += take
        return len(fresh)

    def remove(self, topic: str) -> None:
        row = self._rows.pop(topic, None)
        if row is None:
            return
        self._by_row[row] = None
        self._free.append(row)
        self._tombstones += 1
        c, i = divmod(row, CHUNK)
        self._host_b[c][i, :] = 0  # len derives 0 -> zero words
        self._log_row(c, i)

    # -- query ------------------------------------------------------------
    def _build_tables(self, filters: List[str], floor: int = 0):
        """-> (idx, fid->filter, launch kwargs) for a storm's filter set."""
        import jax

        from emqx_tpu.ops.route_index import RouteIndex

        idx = RouteIndex()
        fids: Dict[int, str] = {}
        for f in filters:
            if len(T.words(f)) > self.max_levels:
                raise ValueError(f"filter too deep for device budget: {f}")
            fids[idx.add(f)] = f
        # storm tables are one-shot (a fresh table per storm, never
        # delta-synced); in mesh mode they place through the canonical
        # replicated layout so the fused sharded program reads them
        # without a per-launch reshard
        put = self._table_place or (lambda _n, a: jax.device_put(a))
        shape_tables = {
            k: put(k, v.copy())
            for k, v in idx.shapes.device_snapshot().items()
        }
        with_nfa = idx.residual_count > 0
        nfa_tables = (
            {
                k: put(k, v.copy())
                for k, v in idx.nfa.device_snapshot().items()
            }
            if with_nfa
            else None
        )
        kwargs = dict(
            m_active=idx.shapes.m_active(floor=floor) if floor else
            idx.shapes.m_active(),
            with_nfa=with_nfa,
            salt=idx.salt,
            max_levels=self.max_levels,
            narrow=idx.num_filters_capacity < (1 << 15) - 1,
        )
        return idx, fids, shape_tables, nfa_tables, kwargs

    def _ensure_chunks(self) -> list:
        """Sync the chunk mirrors through the segment manager; returns
        the device buffer list in chunk order. Safe off the mutating
        thread: the manager serializes concurrent syncs and never caches
        a torn upload as clean (version guard) — a torn snapshot is
        still used for THIS storm (a superset of the pre-mutation rows;
        decode re-verifies against live state)."""
        segs = self._seg.sync(self)
        return [segs[f"chunk_{c}"] for c in range(len(self._host_b))]

    def _launch_all(self, shape_tables, nfa_tables, kwargs) -> list:
        """Dispatch one storm launch per chunk (lengths derived
        on-device; no lengths operand), all before any readback."""
        step = _get_retained_step()
        return [
            step(shape_tables, nfa_tables, d, **kwargs)
            for d in self._ensure_chunks()
        ]

    def prepare_storm(self, filters: List[str]) -> Optional[StormJob]:
        """Build one replay storm's filter tables + chunk buffers so the
        serving pipeline can fuse the match into its next route launch
        (`DeviceRouter.route_prepared(..., retained=job)`): the storm
        then costs ZERO extra launches and zero extra readbacks for
        single-chunk stores, instead of its own launch+readback train.

        Returns None when the index is empty or any filter exceeds the
        device budget (callers fall back to the authoritative CPU walk).
        Must run on the thread that mutates the index (the event loop) —
        the same contract as `DeviceRouter.prepare`.
        """
        if not self._host_b:
            return None
        if any(len(T.words(f)) > self.max_levels for f in filters):
            return None
        _idx, fids, shape_tables, nfa_tables, kwargs = self._build_tables(
            filters, floor=1
        )
        return StormJob(
            index=self,
            filters=list(filters),
            fids=fids,
            shape_tables=shape_tables,
            nfa_tables=nfa_tables,
            kwargs=kwargs,
            chunks=self._ensure_chunks(),
            nrows=len(self._by_row),
        )

    def match(self, filter_: str) -> Optional[List[str]]:  # readback-site
        """Retained topics matching `filter_`, or None when the filter
        itself exceeds the device budget (caller falls back to CPU)."""
        if len(T.words(filter_)) > self.max_levels:
            return None
        _idx, _fids, shape_tables, nfa_tables, kwargs = self._build_tables(
            [filter_]
        )
        outs = self._launch_all(shape_tables, nfa_tables, kwargs)
        nrows = len(self._by_row)
        out: List[str] = []
        for c, matched in enumerate(outs):
            hit_rows = np.nonzero((np.asarray(matched) >= 0).any(axis=1))[0]
            base = c * CHUNK
            for i in hit_rows:
                row = base + int(i)
                # padding rows (len 0) can match plen-0 filters like '#'
                t = self._by_row[row] if row < nrows else None
                # host verification: false candidates cost a check, false
                # replay would cost correctness
                if t is not None and T.match(t, filter_):
                    out.append(t)
        return out

    def warm(self, filters: List[str]) -> None:  # readback-site
        """Upload chunks + compile the storm program WITHOUT reading
        results back (`match_many` works unwarmed, it just pays the XLA
        compile inline; the program is keyed on the filter table's size
        bucket, so warm with a representative filter set)."""
        import jax

        _idx, _f, shape_tables, nfa_tables, kwargs = self._build_tables(
            filters, floor=1
        )
        jax.block_until_ready(
            self._launch_all(shape_tables, nfa_tables, kwargs)
        )

    def match_many(  # readback-site
        self, filters: List[str]
    ) -> Dict[str, np.ndarray]:
        """Answer a replay STORM: many wildcard subscribes in one pass.

        All filters enter ONE shape table; each chunk launch matches every
        stored topic against every filter simultaneously, and the [B, M]
        result (one fid lane per filter shape — within a shape at most one
        filter matches a topic, so the lanes are exact) scatters rows to
        subscribers. Per-storm cost is the same handful of kernel launches
        a single filter pays — the storm amortizes to ~O(1) passes, vs the
        reference's O(store) walk PER subscriber.

        Returns {filter: row-index array}; materialize topics lazily with
        `topic_at`. Unlike `match`, hits are spot-checked (sampled), not
        exhaustively re-verified — the 2^-64 combined-hash collision class
        is accepted here, matching the module's differential test gate.
        """
        if not self._host_b:  # empty index: nothing can match
            return {f: np.empty(0, np.int64) for f in filters}
        _idx, fids, shape_tables, nfa_tables, kwargs = self._build_tables(
            filters, floor=1
        )
        outs = self._launch_all(shape_tables, nfa_tables, kwargs)
        # all chunks dispatched before any readback (launches pipeline);
        # read back per chunk — moderate transfer sizes behave far better
        # on the dev tunnel than one giant buffer
        matched_list = [np.asarray(m) for m in outs]
        del outs
        return self._decode_storm(
            fids, filters, matched_list, len(self._by_row)
        )

    def _decode_storm(
        self, fids, filters: List[str], matched_list, nrows: int
    ) -> Dict[str, np.ndarray]:
        """Host-side storm decode: per-chunk match matrices (numpy) ->
        {filter: row-index array}. Device-free, so the fused serving path
        (`StormJob.decode`) runs it on whatever thread did the readback."""
        lanes = int(matched_list[0].shape[1])
        flat = np.concatenate([np.asarray(m).ravel() for m in matched_list])
        # flat index = (row_g * lanes + lane); group hit rows by fid with
        # one stable argsort instead of per-chunk unique passes. Dtypes
        # stay narrow: the sort is the host-side hot spot at 5M+ pairs.
        nhits = int(np.count_nonzero(flat >= 0))
        if nhits == flat.size and lanes == 1 and nrows == flat.size:
            # dense storm (every stored row matched): skip the index
            # materialization entirely
            hits = rows_g = np.arange(flat.size, dtype=np.int64)
        else:
            hits = np.nonzero(flat >= 0)[0]
            rows_g = hits if lanes == 1 else hits // lanes
            oob = rows_g >= nrows  # padding rows can match plen-0 filters
            if oob.any():
                keep = ~oob
                hits, rows_g = hits[keep], rows_g[keep]
        if self._tombstones:
            # tombstoned rows (removed topics) can still match plen-0
            # filters like '#' via their zeroed length. Slice to nrows:
            # on the fused path the store may have grown since prepare.
            live = np.zeros(nrows, dtype=bool)
            for r, t in enumerate(self._by_row[:nrows]):
                live[r] = t is not None
            keep = live[rows_g]
            hits, rows_g = hits[keep], rows_g[keep]
        hit_fids = flat[hits]
        order = np.argsort(hit_fids, kind="stable")
        rows_g = rows_g[order]
        hit_fids = hit_fids[order]
        bounds = np.nonzero(np.diff(hit_fids))[0] + 1
        starts = np.concatenate([[0], bounds])
        ends = np.concatenate([bounds, [len(hit_fids)]])
        out: Dict[str, np.ndarray] = {f: np.empty(0, np.int64) for f in filters}
        rng = np.random.default_rng(0)
        for s, e in zip(starts, ends):
            if e <= s:
                continue
            f = fids.get(int(hit_fids[s]))
            if f is None:
                continue
            sel = rows_g[s:e]
            out[f] = sel
            # sampled verification (see docstring)
            row = int(rng.choice(sel))
            t = self._by_row[row]
            assert t is None or T.match(t, f), (t, f)
        return out

    def topic_at(self, row: int) -> Optional[str]:
        return self._by_row[row] if 0 <= row < len(self._by_row) else None
