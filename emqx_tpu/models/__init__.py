"""Routing "models": end-to-end jittable pipelines over the NFA tables.

In this framework the analog of a model-family zoo is the family of routing
pipelines — match-only, match+fanout, shared-group pick — each a pure jittable
function over compiled tables.
"""
