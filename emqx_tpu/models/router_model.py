"""The flagship routing pipeline: topics -> matched filters -> subscriber bitmaps.

This fuses, in one jitted program, what the reference does per message across
three modules (SURVEY.md §3.3 hot path):

  emqx_router:match_routes  (emqx_router.erl:128-141)  -> NFA batch match
  emqx_broker:subscribers    (emqx_broker.erl:505-530) -> bitmap gather
  dispatch fan-out OR-union                            -> segment OR-reduce

Subscriber state is a dense bitmap matrix ``sub_bitmaps [Fcap, W]`` (uint32):
row = filter id, bit = local subscriber slot. The fanout output for a topic is
the OR over its matched filters' rows — one gather + reduce, MXU-adjacent
VPU work that scales with W, and the axis the multi-chip layout shards
("tensor parallelism" over subscriber lanes; see emqx_tpu.parallel).

Per-batch stats (routed topics, total matches, fanout bits) are computed
on-device so multi-chip deployments can psum them over the mesh instead of
funneling counters through a host (reference analog: emqx_metrics counter
arrays, emqx_metrics.erl:439).
"""

from __future__ import annotations

from functools import partial
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from emqx_tpu.ops.matcher import batch_match_bytes_impl
from emqx_tpu.ops.nfa import _next_pow2


def popcount32(x):
    """Vectorized popcount for uint32 (no TPU popcnt primitive needed)."""
    x = x - ((x >> 1) & jnp.uint32(0x55555555))
    x = (x & jnp.uint32(0x33333333)) + ((x >> 2) & jnp.uint32(0x33333333))
    x = (x + (x >> 4)) & jnp.uint32(0x0F0F0F0F)
    return (x * jnp.uint32(0x01010101)) >> 24


def fanout_bitmaps(sub_bitmaps, matched):
    """OR the bitmap rows of each topic's matched filters.

    sub_bitmaps: uint32 [Fcap, W]; matched: int32 [B, K]; -> uint32 [B, W].
    """
    safe = jnp.maximum(matched, 0)  # [B, K]
    rows = sub_bitmaps[safe]  # [B, K, W]
    valid = (matched >= 0)[:, :, None]
    rows = jnp.where(valid, rows, jnp.uint32(0))
    # OR-reduce over K (no lax.reduce_or over axis for uint32? use bitwise.reduce)
    return jax.lax.reduce(
        rows, jnp.uint32(0), jax.lax.bitwise_or, dimensions=(1,)
    )


def route_step_impl(
    tables: Dict,
    sub_bitmaps,
    bytes_mat,
    lengths,
    *,
    salt: int,
    max_levels: int = 16,
    frontier: int = 32,
    max_matches: int = 64,
    probes: int = 8,
):
    """Full forward step: tokenize + match + fanout + stats. Jittable.

    Returns dict with matched [B,K], mcount [B], flags [B], bitmaps [B,W],
    stats {routed, matches, fanout_bits}.
    """
    matched, mcount, flags = batch_match_bytes_impl(
        tables,
        bytes_mat,
        lengths,
        salt=salt,
        max_levels=max_levels,
        frontier=frontier,
        max_matches=max_matches,
        probes=probes,
    )
    bitmaps = fanout_bitmaps(sub_bitmaps, matched)
    stats = {
        "routed": jnp.sum((mcount > 0).astype(jnp.int32)),
        "matches": jnp.sum(mcount),
        "fanout_bits": jnp.sum(popcount32(bitmaps).astype(jnp.int32)),
    }
    return {
        "matched": matched,
        "mcount": mcount,
        "flags": flags,
        "bitmaps": bitmaps,
        "stats": stats,
    }


route_step = partial(jax.jit, static_argnames=(
    "salt", "max_levels", "frontier", "max_matches", "probes"
))(route_step_impl)


class SubscriberTable:
    """Host-side registry: (filter id, subscriber slot) -> bitmap matrix.

    The reference keeps subscribers in per-node ETS bag tables
    (emqx_broker.erl:98-110). Here each local subscriber gets a dense slot;
    the [Fcap, W] uint32 matrix is the PRIMARY storage, mutated in place
    with every write op-logged (flat index) so `DeviceDeltaSync` can replay
    churn as O(delta) scatters. Either axis auto-grows by doubling; growth
    bumps `epoch` (full re-upload + one route_step recompile).
    """

    def __init__(self, max_subscribers: int = 1024):
        self.width_words = max(2, _next_pow2((max_subscribers + 31) // 32))
        self._fcap = 64
        self.arr = np.zeros((self._fcap, self.width_words), dtype=np.uint32)
        self.epoch = 0
        self.oplog: list = []  # (name, flat_idx, value)
        self.version = 0
        self.OPLOG_MAX = 65536

    def _log(self, fid: int, w: int, val: int) -> None:
        self.version += 1
        if len(self.oplog) >= self.OPLOG_MAX:
            self.epoch += 1
            self.oplog.clear()
            return
        self.oplog.append(("sub_bitmaps", fid * self.width_words + w, val))

    def _ensure(self, fid: int, slot: int) -> None:
        need_w = _next_pow2(slot // 32 + 1)
        need_f = _next_pow2(fid + 1)
        if need_w > self.width_words or need_f > self._fcap:
            nw = max(self.width_words, need_w)
            nf = max(self._fcap, need_f)
            new = np.zeros((nf, nw), dtype=np.uint32)
            new[: self._fcap, : self.width_words] = self.arr
            self.arr = new
            self.width_words = nw
            self._fcap = nf
            self.epoch += 1
            self.oplog.clear()
            self.version += 1

    def add(self, filter_id: int, slot: int) -> None:
        self._ensure(filter_id, slot)
        w = slot // 32
        self.arr[filter_id, w] |= np.uint32(1 << (slot % 32))
        self._log(filter_id, w, int(self.arr[filter_id, w]))

    def remove(self, filter_id: int, slot: int) -> None:
        if filter_id >= self._fcap or slot // 32 >= self.width_words:
            return
        w = slot // 32
        self.arr[filter_id, w] &= np.uint32(~(1 << (slot % 32)) & 0xFFFFFFFF)
        self._log(filter_id, w, int(self.arr[filter_id, w]))

    def pack(self, filter_capacity: int) -> np.ndarray:
        """Grow to cover `filter_capacity` rows and return the live matrix
        (a view — valid until the next mutation)."""
        if filter_capacity > self._fcap:
            self._ensure(filter_capacity - 1, 0)
        return self.arr

    def device_snapshot(self):
        return {"sub_bitmaps": self.arr}


class DeviceRouter:
    """Serving-path engine: owns the device copies of the NFA tables and the
    subscriber bitmaps and runs `route_step` over host batches.

    This is what puts the flagship kernel on the broker's hot path (the
    reference analog is the emqx_router:match_routes + emqx_broker:subscribers
    pair every publish crosses, emqx_broker.erl:204-215). Table/bitmap uploads
    are cached by version so steady-state batches pay only the kernel launch
    plus the bitmap readback.
    """

    def __init__(self, builder, subtab: SubscriberTable, config=None):
        import dataclasses

        from emqx_tpu.ops.matcher import MatcherConfig
        from emqx_tpu.ops.nfa import MAX_PROBES, DeviceDeltaSync

        self.builder = builder
        self.subtab = subtab
        config = config or MatcherConfig()
        if config.probes < MAX_PROBES:
            config = dataclasses.replace(config, probes=MAX_PROBES)
        self.config = config
        self._nfa_sync = DeviceDeltaSync()
        self._bits_sync = DeviceDeltaSync()

    def _device_args(self):
        # grow the bitmap matrix to cover every live filter id BEFORE the
        # snapshot — a matched fid must always gather a real row
        self.subtab.pack(self.builder.num_filters_capacity)
        tables = self._nfa_sync.sync(self.builder)
        bits = self._bits_sync.sync(self.subtab)["sub_bitmaps"]
        return tables, bits, self.builder.salt

    def prepare(self):
        """Snapshot + upload current tables/bitmaps. MUST run on the thread
        that mutates the builder/subtab (the event loop): packing walks live
        Python structures. The returned pair is immutable device state safe
        to hand to `route_prepared` on a worker thread."""
        return self._device_args()

    def route(self, topics):
        """Batch route: returns host np arrays
        (matched [B,K], mcount [B], flags [B], bitmaps [B,W])."""
        return self.route_prepared(self._device_args(), topics)

    def route_prepared(self, args, topics):
        """Kernel launch + readback against a `prepare()` snapshot; touches
        no mutable host state, so it may run in an executor thread while
        the event loop keeps serving connections (the jit compile on a new
        batch/table shape can take tens of seconds on a real chip)."""
        from emqx_tpu.ops import tokenizer as tok

        cfg = self.config
        tables, bits, salt = args
        B = len(topics)
        Bp = max(64, _next_pow2(B))
        mat, lens, too_long = tok.encode_topics(list(topics), cfg.max_bytes)
        if Bp != B:
            mat = np.pad(mat, ((0, Bp - B), (0, 0)))
            lens = np.pad(lens, (0, Bp - B))
        out = route_step(
            tables,
            bits,
            mat,
            lens,
            salt=salt,
            max_levels=cfg.max_levels,
            frontier=cfg.frontier,
            max_matches=cfg.max_matches,
            probes=cfg.probes,
        )
        matched = np.asarray(out["matched"][:B])
        mcount = np.asarray(out["mcount"][:B])
        flags = np.asarray(out["flags"][:B]) | too_long
        # ascontiguousarray: some backends (axon TPU) hand back strided
        # buffers, and the dispatch path reinterprets rows as uint8
        bitmaps = np.ascontiguousarray(out["bitmaps"][:B])
        return matched, mcount, flags, bitmaps
