"""The flagship routing pipeline: topics -> matched filters -> subscriber bitmaps.

This fuses, in one jitted program, what the reference does per message across
three modules (SURVEY.md §3.3 hot path):

  emqx_router:match_routes  (emqx_router.erl:128-141)  -> NFA batch match
  emqx_broker:subscribers    (emqx_broker.erl:505-530) -> bitmap gather
  dispatch fan-out OR-union                            -> segment OR-reduce

Subscriber state is a dense bitmap matrix ``sub_bitmaps [Fcap, W]`` (uint32):
row = filter id, bit = local subscriber slot. The fanout output for a topic is
the OR over its matched filters' rows — one gather + reduce, MXU-adjacent
VPU work that scales with W, and the axis the multi-chip layout shards
("tensor parallelism" over subscriber lanes; see emqx_tpu.parallel).

Per-batch stats (routed topics, total matches, fanout bits) are computed
on-device so multi-chip deployments can psum them over the mesh instead of
funneling counters through a host (reference analog: emqx_metrics counter
arrays, emqx_metrics.erl:439).
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from emqx_tpu.observe import faults as _faults
from emqx_tpu.observe.profiler import record_kernel_launch
from emqx_tpu.ops.contract import device_contract
from emqx_tpu.ops.csr_table import CsrSegmentOwner, CsrTable, sparse_fanout_slots
from emqx_tpu.ops.matcher import batch_match_bytes_impl
from emqx_tpu.ops.nfa import _next_pow2
from emqx_tpu.ops.semantic_table import (
    SemanticSegmentOwner,
    semantic_match_step,
    union_semantic_slots,
)


def popcount32(x):
    """Vectorized popcount for uint32 (no TPU popcnt primitive needed)."""
    x = x - ((x >> 1) & jnp.uint32(0x55555555))
    x = (x & jnp.uint32(0x33333333)) + ((x >> 2) & jnp.uint32(0x33333333))
    x = (x + (x >> 4)) & jnp.uint32(0x0F0F0F0F)
    return (x * jnp.uint32(0x01010101)) >> 24


def fanout_bitmaps(sub_bitmaps, matched):
    """OR the bitmap rows of each topic's matched filters.

    sub_bitmaps: uint32 [Fcap, W]; matched: int32 [B, K]; -> uint32 [B, W].
    """
    safe = jnp.maximum(matched, 0)  # [B, K]
    rows = sub_bitmaps[safe]  # [B, K, W]
    valid = (matched >= 0)[:, :, None]
    rows = jnp.where(valid, rows, jnp.uint32(0))
    # OR-reduce over K (no lax.reduce_or over axis for uint32? use bitwise.reduce)
    return jax.lax.reduce(
        rows, jnp.uint32(0), jax.lax.bitwise_or, dimensions=(1,)
    )


@device_contract(
    "compact_fanout_slots",
    # the whole point of the stage: outputs scale with B*kslot, never
    # with the bitmap width W
    out_bounds={
        "slots": lambda cfg: cfg["B"] * cfg["kslot"] * 4,
        "count": lambda cfg: cfg["B"] * 4,
        "overflow": lambda cfg: cfg["B"],
    },
)
def compact_fanout_slots(bitmaps, kslot: int):
    """On-device sparse fan-out compaction: set bits -> slot-id lists.

    Makes the device->host readback O(matches) instead of O(B x W):
    instead of shipping the dense ``[B, W]`` uint32 bitmap matrix, ship
    ``slots [B, kslot]`` int32 (ascending slot ids, -1 padded),
    ``count [B]`` (UNCAPPED total set bits), and ``overflow [B]`` (count
    > kslot: the row's dense bitmap must be fetched instead, so
    correctness never depends on the cap).

    Two stages keep peak memory O(B * kslot * 32), not O(B * W * 32)
    (W grows with the connection table; expanding every word's 32 bits
    first would materialize the whole slot universe per row):

      1. left-pack the NONZERO words (index + value) with the same
         iota + prefix-sum + capped scatter as the matched-fid
         compaction (`ops.matcher._compact`). A nonzero word carries
         >= 1 set bit, so > kslot nonzero words implies count > kslot —
         word-stage drops only ever happen on rows already flagged
         overflow;
      2. expand only the packed words into their 32 candidate slots and
         left-pack those into the final [B, kslot] buffer.
    """
    from emqx_tpu.ops.matcher import _compact

    B, W = bitmaps.shape
    kw = min(kslot, W)  # a row cannot have more nonzero words than W
    nz = bitmaps != 0
    pos = jnp.cumsum(nz.astype(jnp.int32), axis=1) - 1
    idx = jnp.where(nz & (pos < kw), pos, kw)
    rows = jnp.arange(B, dtype=jnp.int32)[:, None]
    widx = jnp.broadcast_to(jnp.arange(W, dtype=jnp.int32), (B, W))
    pwidx = jnp.full((B, kw), -1, jnp.int32).at[rows, idx].set(
        widx, mode="drop"
    )
    pword = jnp.zeros((B, kw), jnp.uint32).at[rows, idx].set(
        bitmaps, mode="drop"
    )
    # unpacked holes have pword == 0, so every candidate they produce
    # is already -1 — no extra validity mask needed
    bit = (
        pword[:, :, None] >> jnp.arange(32, dtype=jnp.uint32)
    ) & jnp.uint32(1)
    cand = jnp.where(
        bit.astype(bool),
        pwidx[:, :, None] * 32 + jnp.arange(32, dtype=jnp.int32),
        jnp.int32(-1),
    ).reshape(B, kw * 32)
    slots, _ = _compact(cand, kslot)
    count = jnp.sum(popcount32(bitmaps).astype(jnp.int32), axis=1)
    return slots, count, count > kslot


def route_step_impl(
    tables: Dict,
    sub_bitmaps,
    bytes_mat,
    lengths,
    *,
    salt: int,
    max_levels: int = 16,
    frontier: int = 32,
    max_matches: int = 64,
    probes: int = 8,
    kslot: int = 0,
    kg: int = 0,
):
    """Full forward step: tokenize + match + fanout + stats. Jittable.

    Returns dict with matched [B,K], mcount [B], flags [B], bitmaps [B,W],
    stats {routed, matches, fanout_bits}. With ``kslot > 0`` the output
    additionally carries the sparse fan-out compaction
    (`compact_fanout_slots`): slots [B, kslot], slot_count [B],
    overflow [B].

    ``sub_bitmaps`` may instead be a CSR table dict (ops/csr_table.py
    array set): the fan-out half then runs `sparse_fanout_slots` —
    memory O(total subscriptions) instead of O(Fcap * W) — emitting the
    same compact contract directly (``kg`` bounds the gather window;
    0 = 2 * kslot). The dense trace is unchanged either way.
    """
    # cause breakdown is unused on this path (XLA dead-code-eliminates it);
    # the serving path folds all causes into one fallback flag per row
    matched, mcount, flags, _causes = batch_match_bytes_impl(
        tables,
        bytes_mat,
        lengths,
        salt=salt,
        max_levels=max_levels,
        frontier=frontier,
        max_matches=max_matches,
        probes=probes,
    )
    if isinstance(sub_bitmaps, dict):  # CSR representation
        slots, scount, sovf, live = sparse_fanout_slots(
            sub_bitmaps, matched, kslot=kslot, kg=kg
        )
        stats = {
            "routed": jnp.sum((mcount > 0).astype(jnp.int32)),
            "matches": jnp.sum(mcount),
            "fanout_bits": jnp.sum(live),
        }
        return {
            "matched": matched,
            "mcount": mcount,
            "flags": flags,
            "bitmaps": None,
            "stats": stats,
            "slots": slots,
            "slot_count": scount,
            "overflow": sovf,
        }
    bitmaps = fanout_bitmaps(sub_bitmaps, matched)
    stats = {
        "routed": jnp.sum((mcount > 0).astype(jnp.int32)),
        "matches": jnp.sum(mcount),
        "fanout_bits": jnp.sum(popcount32(bitmaps).astype(jnp.int32)),
    }
    out = {
        "matched": matched,
        "mcount": mcount,
        "flags": flags,
        "bitmaps": bitmaps,
        "stats": stats,
    }
    if kslot > 0:
        slots, scount, sovf = compact_fanout_slots(bitmaps, kslot)
        out["slots"] = slots
        out["slot_count"] = scount
        out["overflow"] = sovf
    return out


route_step = device_contract(
    "route_step",
    # single-device program: no collectives may appear, and the compact
    # outputs stay O(B*kslot) regardless of bitmap width
    collectives=(),
    out_bounds={
        "slots": lambda cfg: cfg["B"] * cfg["kslot"] * 4,
        "slot_count": lambda cfg: cfg["B"] * 4,
    },
)(partial(jax.jit, static_argnames=(
    "salt", "max_levels", "frontier", "max_matches", "probes", "kslot",
    "kg",
))(route_step_impl))


def shape_route_step_impl(
    shape_tables,
    nfa_tables,
    sub_bitmaps,
    bytes_mat,
    lengths,
    group_tables=None,
    client_hash=None,
    topic_hash=None,
    rand=None,
    sem_tables=None,
    q_vecs=None,
    rule_feats=None,
    rule_valid=None,
    *,
    m_active: int,
    with_nfa: bool,
    salt: int,
    max_levels: int = 16,
    frontier: int = 32,
    max_matches: int = 64,
    probes: int = 8,
    shape_probes: Optional[int] = None,
    with_groups: bool = False,
    share_strategy: int = 0,
    dp_axis: Optional[str] = None,
    kslot: int = 0,
    kg: int = 0,
    sem_topk: int = 0,
    rule_progs: tuple = (),
):
    """The serving-path kernel: shape index + (residual NFA) + fanout.

    Tokenizes once, matches via the O(#shapes) hash path
    (ops/shape_index.shape_match_device), runs the general NFA walk only
    when residual filters exist (`with_nfa`), ORs subscriber bitmaps over
    every matched fid. `matched` is SPARSE ([B, M(+K)] with -1 holes), not
    prefix-compacted.

    ``kslot > 0`` adds the sparse fan-out compaction stage
    (`compact_fanout_slots`): the output dict grows slots [B, kslot] /
    slot_count [B] / overflow [B], so the host can read back O(matches)
    compact slot lists and fetch dense bitmap rows only for the
    (rare, overflow-flagged) rows whose fan-out exceeds the cap.

    ``sub_bitmaps`` may instead be a CSR table dict (ops/csr_table.py):
    the fan-out stage then runs `sparse_fanout_slots` over the
    O(subscriptions) slot lists and emits the same compact contract
    directly (no dense bitmaps exist; overflow rows rebuild on host).
    ``kg`` is the CSR gather-window bound (0 = 2 * kslot).

    ``sem_tables`` set (ops/semantic_table.py array dict) engages the
    SEMANTIC routing plane: `semantic_match_step` runs one batched
    similarity matmul over ``q_vecs`` [B, D] in the SAME program, and
    its top-``sem_topk`` winner slots union into the compact slot rows
    before readback (`union_semantic_slots` — the topic part stays
    byte-identical, so slot_count/overflow keep topic-only semantics).
    Requires the compact stage (kslot > 0). The qualifying count rides
    the readback as ``sem_count`` [B].

    ``rule_progs`` (a static tuple of compiled WHERE programs,
    rules/compile.py) evaluates every compiled rule over the
    ``rule_feats``/``rule_valid`` [B, F] feature batch inside this
    launch; the bool masks ride readback as ``rule_masks`` [R, B].
    Defaults leave the trace bit-identical (golden jaxprs unchanged).
    """
    import jax.numpy as jnp

    from emqx_tpu.ops import tokenizer as tok
    from emqx_tpu.ops.matcher import batch_match_syms
    from emqx_tpu.ops.shape_index import SHAPE_PROBES, shape_match_device

    if shape_probes is None:
        # must cover the host placement bound (ShapeIndex._place probes
        # SHAPE_PROBES slots) or cluster-tail entries become invisible
        shape_probes = SHAPE_PROBES
    h1, h2, nwords, dollar = tok.tokenize_device(
        bytes_mat, lengths, salt, max_levels
    )
    matched = shape_match_device(
        shape_tables, m_active, h1, h2, nwords, dollar, probes=shape_probes
    )
    flags = nwords > max_levels
    if with_nfa:
        syms = tok.vocab_lookup_device(nfa_tables, h1, h2, probes)
        m2, _c2, f2, _causes2 = batch_match_syms(
            nfa_tables,
            syms,
            nwords,
            dollar,
            frontier=frontier,
            max_matches=max_matches,
            probes=probes,
        )
        matched = jnp.concatenate([matched, m2], axis=1)
        flags = flags | f2
    mcount = jnp.sum((matched >= 0).astype(jnp.int32), axis=1)
    sparse_out = None
    if isinstance(sub_bitmaps, dict):  # CSR representation
        bitmaps = None
        s_slots, s_count, s_ovf, s_live = sparse_fanout_slots(
            sub_bitmaps, matched, kslot=kslot, kg=kg
        )
        sparse_out = (s_slots, s_count, s_ovf)
        fanout_bits = jnp.sum(s_live)
    elif sub_bitmaps is not None:
        bitmaps = fanout_bitmaps(sub_bitmaps, matched)
        fanout_bits = jnp.sum(popcount32(bitmaps).astype(jnp.int32))
    else:  # match-only callers (Router.match_batch) skip the fan-out half
        bitmaps = None
        fanout_bits = jnp.int32(0)
    if with_groups and group_tables is not None:
        pick_gid, pick_idx = share_pick_device(
            group_tables,
            matched,
            client_hash,
            topic_hash,
            rand,
            strategy=share_strategy,
            dp_axis=dp_axis,
        )
    else:
        pick_gid = pick_idx = None
    stats = {
        "routed": jnp.sum((mcount > 0).astype(jnp.int32)),
        "matches": jnp.sum(mcount),
        "fanout_bits": fanout_bits,
    }
    out = {
        "matched": matched,
        "mcount": mcount,
        "flags": flags,
        "bitmaps": bitmaps,
        "pick_gid": pick_gid,
        "pick_idx": pick_idx,
        "stats": stats,
    }
    if sparse_out is not None:
        out["slots"], out["slot_count"], out["overflow"] = sparse_out
    elif kslot > 0 and bitmaps is not None:
        slots, scount, sovf = compact_fanout_slots(bitmaps, kslot)
        out["slots"] = slots
        out["slot_count"] = scount
        out["overflow"] = sovf
    if sem_tables is not None:
        if "slots" not in out:
            raise ValueError(
                "semantic routing requires the compact fan-out stage "
                "(kslot > 0 and a subscriber table)"
            )
        sem_slots, sem_count = semantic_match_step(
            sem_tables, q_vecs, matched, sem_topk
        )
        out["slots"] = union_semantic_slots(out["slots"], sem_slots)
        out["sem_count"] = sem_count
    if rule_progs:
        from emqx_tpu.rules.compile import eval_rule_masks

        out["rule_masks"] = eval_rule_masks(
            rule_progs, rule_feats, rule_valid
        )
    return out


shape_route_step = device_contract(
    "shape_route_step",
    collectives=(),
    out_bounds={
        "slots": lambda cfg: cfg["B"] * cfg["kslot"] * 4,
        "slot_count": lambda cfg: cfg["B"] * 4,
    },
)(partial(
    jax.jit,
    static_argnames=(
        "m_active",
        "with_nfa",
        "salt",
        "max_levels",
        "frontier",
        "max_matches",
        "probes",
        "shape_probes",
        "with_groups",
        "share_strategy",
        "dp_axis",
        "kslot",
        "kg",
        "sem_topk",
        "rule_progs",
    ),
)(shape_route_step_impl))

# Serving-path entry with input-buffer donation: the per-batch lengths
# buffer is donated so XLA reuses it for a matching output (mcount /
# slot_count are the same int32 [B] shape) instead of allocating fresh —
# steady-state batches recycle their upload buffers. The token-bytes
# matrix is NOT donated: uint8 [B, max_bytes] aliases no output aval,
# so XLA would ignore the donation and warn on every compile. Same
# trace as `shape_route_step` (donation is a compile option, not a
# program change), so no second device contract. Only PER-BATCH
# operands may donate — tables/bitmaps persist across batches.
shape_route_step_donated = partial(
    jax.jit,
    static_argnames=(
        "m_active",
        "with_nfa",
        "salt",
        "max_levels",
        "frontier",
        "max_matches",
        "probes",
        "shape_probes",
        "with_groups",
        "share_strategy",
        "dp_axis",
        "kslot",
        "kg",
        "sem_topk",
        "rule_progs",
    ),
    donate_argnames=("lengths",),
)(shape_route_step_impl)

# Second registry entry for the SAME serving jit traced with a CSR
# subscriber table instead of the dense bitmap matrix: the sparse mode
# compiles a different program (gather-union fan-out, no [B, W]
# bitmaps), so it gets its own golden jaxpr + byte bounds. The audit
# harness (tools/analysis/device_contract.py) builds the CSR workload.
sparse_shape_route_step = device_contract(
    "sparse_shape_route_step",
    collectives=(),
    out_bounds={
        "slots": lambda cfg: cfg["B"] * cfg["kslot"] * 4,
        "slot_count": lambda cfg: cfg["B"] * 4,
    },
)(shape_route_step)


def session_route_step_impl(
    shape_tables,
    nfa_tables,
    sub_bitmaps,
    bytes_mat,
    lengths,
    sess_tables,
    sess_idxs,
    sess_vals,
    sess_clock,
    group_tables=None,
    client_hash=None,
    topic_hash=None,
    rand=None,
    sem_tables=None,
    q_vecs=None,
    rule_feats=None,
    rule_valid=None,
    *,
    m_active: int,
    with_nfa: bool,
    salt: int,
    max_levels: int = 16,
    frontier: int = 32,
    max_matches: int = 64,
    probes: int = 8,
    shape_probes: Optional[int] = None,
    with_groups: bool = False,
    share_strategy: int = 0,
    kslot: int = 0,
    kg: int = 0,
    sem_topk: int = 0,
    rule_progs: tuple = (),
    sweep_k: int = 0,
):
    """Publish routing + the session-ack stage as ONE device program.

    The composition of two audited kernels (`shape_route_step` +
    `session_ack_step`, docs/sessions.md): a batch's pending inflight
    writes — delivery inserts, PUBACK/PUBREC/PUBCOMP/PUBREL clears —
    scatter onto the device session table inside the SAME launch the
    batch pays for routing, and (``sweep_k > 0``) the QoS retransmit /
    session-expiry sweep's compact row lists ride the same coalesced
    readback. The updated session arrays stay on device (the store
    adopts them as the new mirror); only the O(sweep_k) sweep outputs
    ever cross the link — no extra launch, no extra transfer.
    """
    from emqx_tpu.ops.session_table import session_ack_impl

    out = shape_route_step_impl(
        shape_tables,
        nfa_tables,
        sub_bitmaps,
        bytes_mat,
        lengths,
        group_tables,
        client_hash,
        topic_hash,
        rand,
        sem_tables,
        q_vecs,
        rule_feats,
        rule_valid,
        m_active=m_active,
        with_nfa=with_nfa,
        salt=salt,
        max_levels=max_levels,
        frontier=frontier,
        max_matches=max_matches,
        probes=probes,
        shape_probes=shape_probes,
        with_groups=with_groups,
        share_strategy=share_strategy,
        kslot=kslot,
        kg=kg,
        sem_topk=sem_topk,
        rule_progs=rule_progs,
    )
    out["session"] = session_ack_impl(
        sess_tables, sess_idxs, sess_vals, sess_clock, sweep_k=sweep_k
    )
    return out


# jit entry for the session-fused program. Not a separate device
# contract: it composes two registered kernels (`shape_route_step` +
# `session_ack_step`), each audited with its own golden jaxpr — the
# same rationale as shape_route_step_donated's shared contract.
session_route_step = partial(
    jax.jit,
    static_argnames=(
        "m_active",
        "with_nfa",
        "salt",
        "max_levels",
        "frontier",
        "max_matches",
        "probes",
        "shape_probes",
        "with_groups",
        "share_strategy",
        "kslot",
        "kg",
        "sem_topk",
        "rule_progs",
        "sweep_k",
    ),
)(session_route_step_impl)


def fused_route_retained_step_impl(
    shape_tables,
    nfa_tables,
    sub_bitmaps,
    bytes_mat,
    lengths,
    ret_shape_tables,
    ret_nfa_tables,
    ret_bytes,
    group_tables=None,
    client_hash=None,
    topic_hash=None,
    rand=None,
    sem_tables=None,
    q_vecs=None,
    rule_feats=None,
    rule_valid=None,
    *,
    m_active: int,
    with_nfa: bool,
    salt: int,
    ret_m_active: int,
    ret_with_nfa: bool,
    ret_salt: int,
    ret_max_levels: int,
    ret_narrow: bool,
    max_levels: int = 16,
    frontier: int = 32,
    max_matches: int = 64,
    probes: int = 8,
    shape_probes: Optional[int] = None,
    with_groups: bool = False,
    share_strategy: int = 0,
    kslot: int = 0,
    kg: int = 0,
    sem_topk: int = 0,
    rule_progs: tuple = (),
):
    """Publish routing + retained-replay match as ONE device program.

    A batch that carries wildcard SUBSCRIBEs used to pay two launch+
    readback trains: the route step for the publish rows, then one
    `_retained_step` launch per retained chunk for the replay storm
    (models/retained_index.py). This kernel runs both halves in the same
    jitted program — the storm's filter tables (a small one-off shape
    index) and one retained-topic chunk ride the route launch, and the
    [chunk, lanes] match matrix rides the same coalesced readback. The
    retained half is bit-identical to `_retained_step`: lengths derive
    on-device (retained topics cannot contain NUL), result narrows to
    int16 when the storm's fid space fits.
    """
    out = shape_route_step_impl(
        shape_tables,
        nfa_tables,
        sub_bitmaps,
        bytes_mat,
        lengths,
        group_tables,
        client_hash,
        topic_hash,
        rand,
        sem_tables,
        q_vecs,
        rule_feats,
        rule_valid,
        m_active=m_active,
        with_nfa=with_nfa,
        salt=salt,
        max_levels=max_levels,
        frontier=frontier,
        max_matches=max_matches,
        probes=probes,
        shape_probes=shape_probes,
        with_groups=with_groups,
        share_strategy=share_strategy,
        kslot=kslot,
        kg=kg,
        sem_topk=sem_topk,
        rule_progs=rule_progs,
    )
    rl = jnp.sum((ret_bytes != 0).astype(jnp.int32), axis=1)
    rout = shape_route_step_impl(
        ret_shape_tables,
        ret_nfa_tables,
        None,
        ret_bytes,
        rl,
        m_active=ret_m_active,
        with_nfa=ret_with_nfa,
        salt=ret_salt,
        max_levels=ret_max_levels,
    )
    rm = rout["matched"]
    out["retained"] = rm.astype(jnp.int16) if ret_narrow else rm
    return out


fused_route_retained_step = device_contract(
    "fused_route_retained_step",
    # single-device fusion: still no collectives, and the route half's
    # compact outputs keep their O(B*Kslot) bound
    collectives=(),
    out_bounds={
        "slots": lambda cfg: cfg["B"] * cfg["kslot"] * 4,
        "slot_count": lambda cfg: cfg["B"] * 4,
    },
)(partial(
    jax.jit,
    static_argnames=(
        "m_active",
        "with_nfa",
        "salt",
        "max_levels",
        "frontier",
        "max_matches",
        "probes",
        "shape_probes",
        "with_groups",
        "share_strategy",
        "kslot",
        "kg",
        "sem_topk",
        "rule_progs",
        "ret_m_active",
        "ret_with_nfa",
        "ret_salt",
        "ret_max_levels",
        "ret_narrow",
    ),
    donate_argnames=("lengths",),
)(fused_route_retained_step_impl))


STRATEGY_IDS = {
    "random": 0,
    "round_robin": 1,
    "sticky": 2,
    "hash_clientid": 3,
    "hash_topic": 4,
}


class GroupTable:
    """$share groups as device lane segments (SURVEY hard part (d)).

    Host registry mapping (real filter, group name) -> gid, mirrored on
    device as:
      ``filter_groups [Fcap, GPF]`` int32 — group ids per filter (-1 pad)
      ``group_len     [Gcap]``      int32 — member count per group
      ``group_rr      [Gcap]``      int32 — round-robin base (synced once
                                            per batch, not per message)
      ``group_sticky  [Gcap]``      int32 — sticky member index (-1 unset)

    The kernel picks a member INDEX per (topic, group); the host resolves
    index -> member and keeps only ack/retry failover
    (emqx_shared_sub.erl:234-285 pick semantics on-device).
    Implements the epoch/oplog/device_snapshot contract DeviceDeltaSync
    expects, same as SubscriberTable.
    """

    def __init__(self, gpf: int = 4):
        self.gpf = gpf
        self._fcap = 64
        self._gcap = 64
        self.filter_groups = np.full((self._fcap, self.gpf), -1, np.int32)
        self.group_len = np.zeros(self._gcap, np.int32)
        self.group_rr = np.zeros(self._gcap, np.int32)
        self.group_sticky = np.full(self._gcap, -1, np.int32)
        self._gids: Dict = {}  # (real, gname) -> gid
        self._info: Dict[int, tuple] = {}  # gid -> (real, gname)
        self._free: List[int] = []
        self._next_gid = 0
        self.epoch = 0
        self.oplog: list = []
        self.version = 0
        self.OPLOG_MAX = 65536

    def _bump(self) -> None:
        self.epoch += 1
        self.oplog.clear()
        self.version += 1

    def _log(self, name: str, flat_idx: int, val: int) -> None:
        self.version += 1
        if len(self.oplog) >= self.OPLOG_MAX:
            self._bump()
            return
        self.oplog.append((name, flat_idx, val))

    def _grow_fcap(self, need: int) -> None:
        nf = max(self._fcap, _next_pow2(need))
        if nf != self._fcap:
            new = np.full((nf, self.gpf), -1, np.int32)
            new[: self._fcap] = self.filter_groups
            self.filter_groups = new
            self._fcap = nf
            self._bump()

    def _grow_gpf(self) -> None:
        new = np.full((self._fcap, self.gpf * 2), -1, np.int32)
        new[:, : self.gpf] = self.filter_groups
        self.filter_groups = new
        self.gpf *= 2
        self._bump()

    def _grow_gcap(self) -> None:
        ng = self._gcap * 2
        for name in ("group_len", "group_rr", "group_sticky"):
            arr = getattr(self, name)
            fill = -1 if name == "group_sticky" else 0
            new = np.full(ng, fill, arr.dtype)
            new[: self._gcap] = arr
            setattr(self, name, new)
        self._gcap = ng
        self._bump()

    # -- membership ---------------------------------------------------------
    def ensure_group(self, fid: int, real: str, gname: str) -> int:
        key = (real, gname)
        gid = self._gids.get(key)
        if gid is not None:
            return gid
        if self._free:
            gid = self._free.pop()
        else:
            gid = self._next_gid
            self._next_gid += 1
        while gid >= self._gcap:
            self._grow_gcap()
        self._gids[key] = gid
        self._info[gid] = key
        # reset through the log so a recycled gid's device row resets too
        for name, val in (
            ("group_len", 0),
            ("group_rr", 0),
            ("group_sticky", -1),
        ):
            getattr(self, name)[gid] = val
            self._log(name, gid, val)
        self._grow_fcap(fid + 1)
        row = self.filter_groups[fid]
        slot = int(np.argmax(row < 0)) if (row < 0).any() else -1
        if slot < 0 or row[slot] >= 0:
            self._grow_gpf()
            row = self.filter_groups[fid]
            slot = int(np.argmax(row < 0))
        self.filter_groups[fid, slot] = gid
        self._log("filter_groups", fid * self.gpf + slot, gid)
        return gid

    def set_len(self, gid: int, n: int) -> None:
        if self.group_len[gid] != n:
            self.group_len[gid] = n
            self._log("group_len", gid, n)

    def set_rr(self, gid: int, v: int) -> None:
        v &= 0x7FFFFFFF
        if self.group_rr[gid] != v:
            self.group_rr[gid] = v
            self._log("group_rr", gid, v)

    def set_sticky(self, gid: int, idx: int) -> None:
        if self.group_sticky[gid] != idx:
            self.group_sticky[gid] = idx
            self._log("group_sticky", gid, idx)

    def repin(self, gid: int, member_sids, sticky_sid) -> None:
        """Recompute the device sticky index from the pinned sid (the
        ONE place the sid->index mapping convention lives; membership
        changes shift indices, so a raw index cannot be kept)."""
        sids = list(member_sids)
        if sticky_sid in sids:
            self.set_sticky(gid, sids.index(sticky_sid))
        else:
            self.set_sticky(gid, -1)

    def drop_group(self, fid: int, real: str, gname: str) -> None:
        gid = self._gids.pop((real, gname), None)
        if gid is None:
            return
        self._info.pop(gid, None)
        self._free.append(gid)
        self.group_len[gid] = 0
        self._log("group_len", gid, 0)
        if fid < self._fcap:
            row = self.filter_groups[fid]
            for slot in np.nonzero(row == gid)[0]:
                self.filter_groups[fid, slot] = -1
                self._log("filter_groups", fid * self.gpf + int(slot), -1)

    def gid_of(self, real: str, gname: str):
        return self._gids.get((real, gname))

    def info(self, gid: int):
        return self._info.get(gid)

    def pack_fcap(self, filter_capacity: int) -> None:
        if filter_capacity > self._fcap:
            self._grow_fcap(filter_capacity)

    def device_snapshot(self):
        return {
            "filter_groups": self.filter_groups,
            "group_len": self.group_len,
            "group_rr": self.group_rr,
            "group_sticky": self.group_sticky,
        }

    def __len__(self) -> int:
        return len(self._gids)


def _occurrence_index(flat_gids):
    """occ[i] = #{j < i : g[j] == g[i]} in flat (batch-major) order — the
    per-batch round-robin offset. Stable argsort groups equal gids while
    preserving arrival order; run positions come from a cummax of run
    starts; scatter restores original order."""
    n = flat_gids.shape[0]
    order = jnp.argsort(flat_gids, stable=True)
    sg = flat_gids[order]
    idx = jnp.arange(n, dtype=jnp.int32)
    new_seg = jnp.concatenate(
        [jnp.ones((1,), bool), sg[1:] != sg[:-1]]
    )
    seg_start = jax.lax.associative_scan(
        jnp.maximum, jnp.where(new_seg, idx, 0)
    )
    run_pos = idx - seg_start
    return jnp.zeros(n, jnp.int32).at[order].set(run_pos)


def share_pick_device(
    group_tables,
    matched,
    client_hash,
    topic_hash,
    rand,
    *,
    strategy: int,
    dp_axis: Optional[str] = None,
):
    """Resolve $share picks on-device: matched fids -> group lanes ->
    member index per strategy (emqx_shared_sub.erl:234-285 on the MXU-
    adjacent path). Returns (pick_gid [B,P], pick_idx [B,P]), -1 holes.

    strategy: STRATEGY_IDS value (static — each strategy is its own
    compiled program; brokers run one strategy at a time).

    `dp_axis`: when running INSIDE shard_map with the batch sharded over
    a mesh axis, round_robin's per-batch occurrence index must count
    occurrences across ALL shards, not just the local rows — otherwise
    every shard re-picks from the same synced base. The exact global
    offset comes from a per-group histogram all_gather over the axis:
    shard s adds sum of counts from shards < s (a segmented exclusive
    scan over ICI; one [dp, Gcap] all_gather per batch).
    """
    fg = group_tables["filter_groups"]
    glen = group_tables["group_len"]
    B, K = matched.shape
    gpf = fg.shape[1]
    safe = jnp.maximum(matched, 0)
    gids = fg[safe]  # [B, K, GPF]
    valid = (matched >= 0)[:, :, None] & (gids >= 0)
    gids = jnp.where(valid, gids, -1).reshape(B, K * gpf)
    gsafe = jnp.maximum(gids, 0)
    lens = glen[gsafe]
    denom = jnp.maximum(lens, 1)
    if strategy == 1:  # round_robin: per-batch occurrence + synced base
        occ = _occurrence_index(gids.reshape(-1)).reshape(B, -1)
        if dp_axis is not None:
            Gcap = glen.shape[0]
            ones = (gids >= 0).astype(jnp.int32).reshape(-1)
            counts = jnp.zeros(Gcap, jnp.int32).at[
                gsafe.reshape(-1)
            ].add(ones, mode="drop")
            all_c = jax.lax.all_gather(counts, dp_axis)  # [dp, Gcap]
            rank = jax.lax.axis_index(dp_axis)
            ndp = all_c.shape[0]
            prev = jnp.sum(
                jnp.where(
                    (jnp.arange(ndp) < rank)[:, None], all_c, 0
                ),
                axis=0,
            )  # [Gcap] occurrences in earlier shards
            occ = occ + prev[gsafe]
        idx = (group_tables["group_rr"][gsafe] + occ) % denom
    elif strategy == 2:  # sticky: stored index, random fallback
        st = group_tables["group_sticky"][gsafe]
        fallback = (
            (rand[:, None].astype(jnp.uint32) ^ gsafe.astype(jnp.uint32))
            % denom.astype(jnp.uint32)
        ).astype(jnp.int32)
        idx = jnp.where((st >= 0) & (st < lens), st, fallback)
    elif strategy == 3:  # hash_clientid
        idx = (
            client_hash[:, None].astype(jnp.uint32)
            % denom.astype(jnp.uint32)
        ).astype(jnp.int32)
    elif strategy == 4:  # hash_topic
        idx = (
            topic_hash[:, None].astype(jnp.uint32)
            % denom.astype(jnp.uint32)
        ).astype(jnp.int32)
    else:  # random: per-message entropy decorrelated across groups
        mixed = rand[:, None].astype(jnp.uint32) * jnp.uint32(
            2654435761
        ) ^ gsafe.astype(jnp.uint32)
        idx = (mixed % denom.astype(jnp.uint32)).astype(jnp.int32)
    ok = (gids >= 0) & (lens > 0)
    return jnp.where(ok, gids, -1), jnp.where(ok, idx, -1)


def _popcount_u32(arr: np.ndarray) -> int:
    """Total set bits of a uint32 array (chunked: no 8x byte blowup)."""
    bc = getattr(np, "bitwise_count", None)
    if bc is not None:
        return int(bc(arr).sum())
    total = 0
    flat = arr.reshape(-1).view(np.uint8)
    step = 1 << 22
    for lo in range(0, len(flat), step):
        total += int(np.unpackbits(flat[lo : lo + step]).sum())
    return total


class SubscriberTable:
    """Host-side registry: (filter id, subscriber slot) -> fan-out state,
    in one of TWO device representations behind one mutation interface:

    - **dense** (the original): a ``sub_bitmaps [Fcap, W]`` uint32
      matrix — O(Fcap * W) memory, one gather+OR per batch row. Right
      for small tables and shared-heavy/high-occupancy workloads;
    - **sparse** (ops/csr_table.py): per-fid CSR slot lists —
      O(total subscriptions) memory, the representation that makes a
      million DISTINCT single-subscriber topics (and the 100M-sub mesh
      run) physically possible.

    ``mode`` is the `router.sub_table` policy: ``dense`` pins the
    matrix (the degrade fallback), ``sparse`` converts immediately, and
    ``auto`` starts dense and flips ONCE (grow-only, checked at growth
    events so the per-subscribe cost is zero) when the matrix passes
    `AUTO_MIN_DENSE_BYTES` and exceeds `AUTO_RATIO` x the estimated CSR
    footprint — i.e. when occupancy x width says the bitmap is mostly
    zeros. A flip is an ordinary epoch bump on the SAME object: every
    holder (Broker, DeviceRouter, segment manager) just sees a full
    resync with the other representation's arrays.

    The reference keeps subscribers in per-node ETS bag tables
    (emqx_broker.erl:98-110); both representations op-log their scalar
    writes (flat index) so `DeviceSegmentManager` replays churn as
    O(delta) scatters, and growth/flips bump `epoch` (full re-upload).
    """

    AUTO_MIN_DENSE_BYTES = 8 << 20  # don't bother below 8MB dense
    AUTO_RATIO = 2.0  # flip when dense > ratio x estimated CSR bytes

    def __init__(self, max_subscribers: int = 1024, mode: str = "dense",
                 shards: int = 1):
        self.width_words = max(2, _next_pow2((max_subscribers + 31) // 32))
        self._fcap = 64
        self.arr = np.zeros((self._fcap, self.width_words), dtype=np.uint32)
        self.epoch = 0
        self.oplog: list = []  # (name, flat_idx, value)
        self.version = 0
        self.OPLOG_MAX = 65536
        self.mode = "dense"
        self.shards = max(1, int(shards))
        self._sp: Optional[CsrTable] = None  # the sparse rep when active
        self.live = 0  # live subscriptions (both reps; drives the policy)
        self.flips = 0
        if mode != "dense":
            self.set_mode(mode)

    # -- op-log plumbing (shared by both representations) ------------------
    def _bump_epoch(self) -> None:
        self.epoch += 1
        self.oplog.clear()
        self.version += 1

    def _log_any(self, name: str, flat_idx: int, val: int) -> None:
        self.version += 1
        if len(self.oplog) >= self.OPLOG_MAX:
            self._bump_epoch()
            return
        self.oplog.append((name, int(flat_idx), int(val)))

    def _log_resync(self, name: str) -> None:
        self.version += 1
        if len(self.oplog) >= self.OPLOG_MAX:
            self._bump_epoch()
            return
        from emqx_tpu.ops.segments import RESYNC

        self.oplog.append((RESYNC, name, 0))

    def _log(self, fid: int, w: int, val: int) -> None:
        self._log_any("sub_bitmaps", fid * self.width_words + w, val)

    # -- representation policy ---------------------------------------------
    @property
    def sparse(self) -> bool:
        return self._sp is not None

    @property
    def csr(self) -> Optional[CsrTable]:
        return self._sp

    def set_mode(self, mode: str) -> None:
        """Pin the representation policy; converts immediately when the
        pinned representation differs from the live one."""
        if mode not in ("auto", "dense", "sparse"):
            raise ValueError(f"sub_table mode {mode!r}")
        self.mode = mode
        if mode == "sparse" and self._sp is None:
            self._flip_sparse()
        elif mode == "dense" and self._sp is not None:
            self._flip_dense()

    def set_shards(self, shards: int) -> None:
        """Partition count for the mesh placement ('tp' slices of the
        slot column). Re-shards a live sparse table (epoch bump)."""
        shards = max(1, int(shards))
        if shards == self.shards:
            return
        self.shards = shards
        if self._sp is not None:
            self._sp.reshard(shards)

    def _csr_estimate(self) -> int:
        """Estimated CSR footprint: 4B slot column + 2 x 4B region lanes
        per fid + the hot segment floor."""
        return 16 * max(self.live, 1) + 8 * self._fcap + 8192

    def _maybe_flip(self) -> None:
        """Auto policy, checked only at dense growth events (the only
        times the answer can change): flip when occupancy x width says
        the matrix is mostly zeros AND it is big enough to matter."""
        if self.mode != "auto" or self._sp is not None:
            return
        dense_bytes = self.arr.nbytes
        if dense_bytes < self.AUTO_MIN_DENSE_BYTES:
            return
        if dense_bytes > self.AUTO_RATIO * self._csr_estimate():
            self._flip_sparse()

    def _mk_csr(self) -> CsrTable:
        return CsrTable(
            shards=self.shards,
            log=self._log_any,
            log_resync=self._log_resync,
            bump=self._bump_epoch,
        )

    def _flip_sparse(self) -> None:
        """dense -> CSR: expand the live bits (vectorized), build the
        exact-size CSR + registry, drop the matrix. One epoch bump."""
        rows, words = np.nonzero(self.arr)
        if len(rows):
            vals = self.arr[rows, words]
            bits = (
                (vals[:, None] >> np.arange(32, dtype=np.uint32)) & 1
            ).astype(bool)
            e_idx, e_bit = np.nonzero(bits)
            fids = rows[e_idx].astype(np.int64)
            slots = words[e_idx].astype(np.int64) * 32 + e_bit
        else:
            fids = slots = np.empty(0, np.int64)
        sp = self._mk_csr()
        built = CsrTable._build(fids, slots, sp.shards, self._fcap)
        sp._install(built)
        sp.max_slot = max(
            sp.max_slot, self.width_words * 32 - 1 if len(rows) else -1
        )
        self._sp = sp
        self.arr = None  # the matrix is gone — that is the point
        self.live = built["n"]
        self.flips += 1
        self._bump_epoch()

    def _flip_dense(self) -> None:
        """CSR -> dense (the degrade fallback / explicit pin)."""
        sp = self._sp
        fids, slots = sp.live_pairs()
        self._sp = None
        nf = max(64, _next_pow2(int(fids.max()) + 1 if len(fids) else 1))
        nw = max(
            self.width_words,
            _next_pow2((int(slots.max()) // 32 + 1) if len(slots) else 2),
        )
        self._fcap, self.width_words = nf, nw
        self.arr = np.zeros((nf, nw), np.uint32)
        if len(fids):
            w = slots // 32
            bits = (np.uint32(1) << (slots % 32).astype(np.uint32)).astype(
                np.uint32
            )
            np.bitwise_or.at(self.arr, (fids, w), bits)
        self.live = len(fids)
        self.flips += 1
        self._bump_epoch()

    # -- mutation (mode-dispatched) ----------------------------------------
    def _ensure(self, fid: int, slot: int) -> None:
        need_w = _next_pow2(slot // 32 + 1)
        need_f = _next_pow2(fid + 1)
        if need_w > self.width_words or need_f > self._fcap:
            nw = max(self.width_words, need_w)
            nf = max(self._fcap, need_f)
            new = np.zeros((nf, nw), dtype=np.uint32)
            new[: self._fcap, : self.width_words] = self.arr
            self.arr = new
            self.width_words = nw
            self._fcap = nf
            self._bump_epoch()
            self._maybe_flip()

    def _track_width(self, slot: int) -> None:
        # external readers size dense fallback rows from width_words;
        # keep it covering the slot universe in sparse mode too
        need_w = _next_pow2(slot // 32 + 1)
        if need_w > self.width_words:
            self.width_words = need_w

    def add(self, filter_id: int, slot: int) -> None:
        if self._sp is not None:
            if self._sp.add(filter_id, slot):
                self.live += 1
            self._fcap = max(self._fcap, self._sp._fcap)
            self._track_width(slot)
            return
        self._ensure(filter_id, slot)
        if self._sp is not None:  # _ensure's growth flipped the rep
            return self.add(filter_id, slot)
        w = slot // 32
        bit = np.uint32(1 << (slot % 32))
        if not self.arr[filter_id, w] & bit:
            self.live += 1
        self.arr[filter_id, w] |= bit
        self._log(filter_id, w, int(self.arr[filter_id, w]))

    def bulk_add(self, fids, slots) -> None:
        """Vectorized (fid, slot) load for cold starts; one epoch bump."""
        fids = np.asarray(fids, dtype=np.int64)
        slots = np.asarray(slots, dtype=np.int64)
        if not len(fids):
            return
        if self._sp is not None:
            self._sp.bulk_add(fids, slots)
            self.live = self._sp.live
            self._fcap = max(self._fcap, self._sp._fcap)
            self._track_width(int(slots.max()))
            return
        self._ensure(int(fids.max()), int(slots.max()))
        if self._sp is not None:
            return self.bulk_add(fids, slots)
        w = slots // 32
        bits = (np.uint32(1) << (slots % 32).astype(np.uint32)).astype(
            np.uint32
        )
        np.bitwise_or.at(self.arr, (fids, w), bits)
        self.live = _popcount_u32(self.arr)
        self._bump_epoch()
        self._maybe_flip()

    def remove(self, filter_id: int, slot: int) -> None:
        if self._sp is not None:
            if self._sp.remove(filter_id, slot):
                self.live -= 1
            return
        if filter_id >= self._fcap or slot // 32 >= self.width_words:
            return
        w = slot // 32
        bit = np.uint32(1 << (slot % 32))
        if self.arr[filter_id, w] & bit:
            self.live -= 1
        self.arr[filter_id, w] &= np.uint32(~bit & 0xFFFFFFFF)
        self._log(filter_id, w, int(self.arr[filter_id, w]))

    def pack(self, filter_capacity: int):
        """Grow to cover `filter_capacity` filter rows. Dense mode
        returns the live matrix (a view — valid until the next
        mutation); sparse mode returns None (there is no matrix)."""
        if self._sp is not None:
            # serve-time hot bound: a storm of adds with no background
            # compactor must not hand the kernel a giant hot scan
            self._sp.maybe_absorb()
            self._sp.pack(filter_capacity)
            self._fcap = max(self._fcap, self._sp._fcap)
            return None
        if filter_capacity > self._fcap:
            self._ensure(filter_capacity - 1, 0)
            if self._sp is not None:
                self._sp.pack(filter_capacity)
                return None
        return self.arr

    def device_snapshot(self):
        if self._sp is not None:
            return self._sp.device_snapshot()
        return {"sub_bitmaps": self.arr}

    # -- introspection (REST / gauges / benches) ---------------------------
    def fill_row_bits(self, fid: int, row: np.ndarray) -> None:
        """OR one fid's subscriber bits into a uint32 bitmap row — the
        host-built dense fallback for sparse overflow rows. Runs against
        the LIVE table (loop thread; the per-delivery filter re-verify
        is the staleness net, as everywhere on the dispatch path)."""
        if self._sp is not None:
            slots = self._sp.slots_of(fid)
            slots = slots[slots < len(row) * 32]
            if len(slots):
                np.bitwise_or.at(
                    row,
                    slots // 32,
                    (np.uint32(1) << (slots % 32).astype(np.uint32)).astype(
                        np.uint32
                    ),
                )
            return
        if fid < self._fcap:
            n = min(len(row), self.width_words)
            row[:n] |= self.arr[fid, :n]

    def table_bytes(self) -> int:
        """Device-table footprint of the ACTIVE representation — the
        `sub_table_bytes` number the memory-budget docs talk about."""
        if self._sp is not None:
            return self._sp.nbytes
        return int(self.arr.nbytes)

    def status(self) -> Dict:
        """Hotpath-REST / gauge block: mode, bytes, fill, tombstones."""
        out = {
            "mode": "sparse" if self._sp is not None else "dense",
            "policy": self.mode,
            "bytes": self.table_bytes(),
            "subscriptions": self.live,
            "width_words": self.width_words,
            "fcap": self._fcap,
            "flips": self.flips,
            "shards": self.shards,
        }
        if self._sp is not None:
            sp = self._sp
            out["csr_fill"] = sp.live
            out["csr_tombstones"] = sp.packed_tombs + sp.hot_tombs
            out["hot_fill"] = sp.hot_fill
            out["max_region"] = sp.max_region
        return out


class RouteResult(NamedTuple):
    """Host-side outputs of one routed batch (all numpy, device-free).

    Exactly ONE of the fan-out encodings is populated per row:

    - compact path (``slots is not None`` and not ``overflow[i]``):
      ``slots[i]`` holds the row's subscriber slot ids (-1 holes allowed
      anywhere — mesh serving concatenates per-shard segments);
    - dense path: ``bitmaps[i]`` (compaction off) or
      ``dense_rows[dense_index[i]]`` (compaction on, row overflowed the
      Kslot cap — the masked second transfer of the fallback contract).

    ``readback_bytes`` is the device->host transfer this batch actually
    paid (the `dispatch.readback.bytes` series).
    """

    matched: np.ndarray  # [B, K] sparse fids, -1 holes
    mcount: np.ndarray  # [B]
    flags: np.ndarray  # [B] host-must-fallback rows
    bitmaps: Optional[np.ndarray]  # [B, W] dense (None on compact path)
    picks: Optional[tuple]  # (pick_gid [B,P], pick_idx [B,P]) | None
    slots: Optional[np.ndarray] = None  # [B, Kslot] int32, -1 pad
    slot_count: Optional[np.ndarray] = None  # [B] total set bits (uncapped)
    overflow: Optional[np.ndarray] = None  # [B] bool: fanout > Kslot
    dense_rows: Optional[np.ndarray] = None  # [n_overflow, W] uint32
    dense_index: Optional[Dict[int, int]] = None  # batch row -> dense_rows row
    readback_bytes: int = 0
    # fused retained-replay storm that rode this batch's launch
    # (fused_route_retained_step): {filter: matched row-index array}
    retained: Optional[Dict[str, np.ndarray]] = None
    # fused session-ack stage outputs (session_route_step): a
    # `broker.session_store.SessionStepOut` — updated device mirror
    # (stays on device) + the O(sweep_k) sweep lists
    session: Optional[tuple] = None
    # semantic routing plane (docs/semantic_routing.md): qualifying
    # embedding-filter hits per row (UNCAPPED; winners are already
    # unioned into `slots`, so dispatch needs no extra decode)
    sem_count: Optional[np.ndarray] = None
    # compiled rule-predicate masks [R, B] bool, in DeviceRuleFilter
    # order (rules/compile.py) — consumed by the settle-time rule fire
    rule_masks: Optional[np.ndarray] = None
    # @device_contract registry names of every kernel that rode this
    # launch's program (observe/profiler.py per-kernel attribution:
    # `device.kernel.<name>.seconds/.bytes`); () on paths nobody times
    kernels: Tuple[str, ...] = ()


class _LazyDenseRows:
    """Dense fallback rows for SPARSE overflow rows, built on demand.

    The CSR path has no device bitmap matrix to gather overflow rows
    from, so the fallback unions the row's matched fids' slot lists
    from the HOST table instead. Construction here stores only the fid
    lists (cheap, runs on the dispatch executor); the actual union runs
    at `__getitem__` time — which is `Broker._dispatch_device_results`,
    on the event loop, the thread that owns the table — so no cross-
    thread reads of live arrays ever happen. Duck-types the
    `dense_rows[j]` indexing of the device-gathered overflow contract;
    nothing crossed the link for these rows (readback_bytes excludes
    them honestly).
    """

    __slots__ = ("subtab", "fid_lists")

    def __init__(self, subtab, fid_lists):
        self.subtab = subtab
        self.fid_lists = fid_lists

    def __len__(self) -> int:
        return len(self.fid_lists)

    def __getitem__(self, j: int) -> np.ndarray:
        row = np.zeros(self.subtab.width_words, np.uint32)
        for fid in self.fid_lists[j]:
            self.subtab.fill_row_bits(int(fid), row)
        return row


# prepared-args tuple layout (DeviceRouter._device_args_dirty): the
# clean-path Kslot recheck swaps one element in place, so the position
# is a named constant instead of a fragile negative index
_ARGS_KSLOT = 7

# floor for the auto-sized compact-slot cap: below this the slot list is
# cheaper than the program bookkeeping either way, and a tiny cap would
# overflow constantly while the fanout histogram warms up
KSLOT_MIN = 64


class DeviceRouter:
    """Serving-path engine: owns the device mirrors of the shape index, the
    residual NFA tables, and the subscriber bitmaps; runs
    `shape_route_step` over host batches.

    This is what puts the flagship kernel on the broker's hot path (the
    reference analog is the emqx_router:match_routes + emqx_broker:subscribers
    pair every publish crosses, emqx_broker.erl:204-215). All three table
    sets sync via the delta-overlay protocol, so steady-state batches pay
    only the kernel launch plus the readback.
    """

    def __init__(
        self,
        index,
        subtab: Optional[SubscriberTable],
        config=None,
        grouptab: Optional[GroupTable] = None,
        share_strategy: str = "round_robin",
        mesh=None,
        metrics=None,
        semtab=None,
    ):
        """`mesh`: a jax.sharding.Mesh with ("dp", "tp") axes — when set,
        batches execute the SPMD dist_shape_route_step (tables replicated,
        topic batch sharded over dp, subscriber lanes over tp, stats
        psum'd over ICI; parallel/mesh.py). $share picks resolve on-device
        in mesh mode too: group tables ride replicated like the match
        tables, per-topic pick entropy shards with the batch, and
        round_robin's occurrence index is cross-shard exact (an
        all_gather histogram over 'dp'; share_pick_device dp_axis)."""
        import dataclasses

        from emqx_tpu.ops.matcher import MatcherConfig
        from emqx_tpu.ops.nfa import MAX_PROBES
        from emqx_tpu.ops.segments import DeviceSegmentManager

        self.index = index
        self.subtab = subtab  # None => match-only (no fan-out bitmaps)
        self.grouptab = grouptab  # None => host-side $share pick
        # SemanticTable (ops/semantic_table.py): embedding-filter
        # subscriptions riding the same launch; None / empty = the
        # semantic stage never traces (docs/semantic_routing.md)
        self.semtab = semtab
        self.mesh = mesh
        # hot-path flight recorder (router.* series); None = don't record
        self.metrics = metrics
        self.share_strategy = STRATEGY_IDS.get(share_strategy, 1)
        config = config or MatcherConfig()
        if config.probes < MAX_PROBES:
            config = dataclasses.replace(config, probes=MAX_PROBES)
        self.config = config
        if mesh is not None:
            # sharded-from-upload mirrors: the canonical mesh layout is
            # applied at the DeviceDeltaSync level, so subscribe/
            # unsubscribe churn stays O(delta) scatters on the mesh too
            # (jit propagates the placed sharding through the scatter)
            from emqx_tpu.parallel.mesh import (
                bitmap_placement,
                table_placement,
            )

            tplace = table_placement(mesh)
            self._table_placement = tplace
            self._bitmap_placement = bitmap_placement(mesh)
            self._shape_sync = DeviceSegmentManager(
                placement=tplace, free_retired=True, metrics=self.metrics, name="shapes"
            )
            self._nfa_sync = DeviceSegmentManager(
                placement=tplace, free_retired=True, metrics=self.metrics, name="nfa"
            )
            # group tables are replicated on the mesh like match tables
            self._group_sync = DeviceSegmentManager(
                placement=tplace, free_retired=True, metrics=self.metrics, name="groups"
            )
        else:
            self._table_placement = None
            self._bitmap_placement = None
            self._shape_sync = DeviceSegmentManager(
                free_retired=True, metrics=self.metrics, name="shapes"
            )
            self._nfa_sync = DeviceSegmentManager(
                free_retired=True, metrics=self.metrics, name="nfa"
            )
            self._group_sync = DeviceSegmentManager(
                free_retired=True, metrics=self.metrics, name="groups"
            )
        # the subscriber-table mirror follows the table's ACTIVE
        # representation: dense lanes shard over 'tp', a CSR table's
        # arrays shard their leading (slot-owner) axis over 'tp'. A
        # representation flip (router.sub_table=auto) swaps the manager
        # — an ordinary full resync under the new placement.
        self._bits_sparse = (
            subtab is not None and getattr(subtab, "sparse", False)
        )
        self._bits_sync = self._mk_bits_sync(self._bits_sparse)
        # semantic-table mirror: entries shard their leading slot-owner
        # axis over 'tp' (slot % shards — the CSR regime, so per-shard
        # semantic hits are global slot ids; parallel/mesh.py)
        sem_place = None
        if mesh is not None and semtab is not None:
            from emqx_tpu.parallel.mesh import semantic_placement

            sem_place = semantic_placement(mesh)
        self._sem_sync = DeviceSegmentManager(
            placement=sem_place, free_retired=True, metrics=self.metrics, name="semantic"
        )
        # per-batch entropy seed; itertools.count's next() is atomic
        # under the GIL, keeping route_prepared free of shared mutable
        # state (it runs on executor threads)
        import itertools

        self._rand_seq = itertools.count(0xEC0)
        # auto-sized compact-slot cap (grow-only so the jit program is
        # stable; only _device_args — loop thread — mutates it)
        self._kslot = 0  # single-writer: loop
        # O(dirty) prepare: cached (version key, args) of the last
        # snapshot. While every source table's generation counter is
        # unchanged, prepare() returns this tuple without touching
        # pack/delta-sync at all — a clean-table batch costs a few dict
        # reads, not a re-walk of live structures. Only the loop thread
        # (prepare/_device_args callers) mutates it; `tpu-dispatch`
        # workers only ever see the immutable args tuple passed to
        # route_prepared (the publication pattern the CX checker's
        # single-writer declaration encodes — a pool-rooted writer
        # appearing later is a CX002)
        self._prep_key = None  # single-writer: loop
        self._prep_args = None  # single-writer: loop
        self._clean_streak = 0  # single-writer: loop

    def _mk_bits_sync(self, sparse: bool):
        from emqx_tpu.ops.segments import DeviceSegmentManager

        placement = None
        if self.mesh is not None:
            if sparse:
                from emqx_tpu.parallel.mesh import csr_placement

                placement = csr_placement(self.mesh)
            else:
                placement = self._bitmap_placement
        return DeviceSegmentManager(
            placement=placement, free_retired=True, metrics=self.metrics, name="bitmaps"
        )

    # clean-table prepares re-check the auto-sized Kslot only every this
    # many batches: the fanout histogram drifts slowly and the p99 scan
    # would otherwise be the only per-batch work left on the clean path
    KSLOT_RECHECK = 64

    def _fanout_kslot(self, width_words: int, sparse: bool = False,
                      semantic: bool = False) -> int:
        """Static Kslot for the next batch; 0 = compaction off.

        An explicit ``config.fanout_slots`` pins the cap (pow2-padded to
        avoid one recompile per odd value). Auto mode (0) sizes from the
        `dispatch.fanout` histogram p99 with 2x headroom, pow2-padded and
        GROW-ONLY — shrinking on a quiet period would recompile the
        serving program twice for zero readback win — and turns
        compaction off entirely while the slot universe (W*32) is no
        wider than the compact output would be.

        ``sparse``: a CSR table HAS no dense readback to fall back to —
        compaction is mandatory there, so the cap never returns 0 (and
        the fanout_compact knob / width win-condition don't apply).
        ``semantic``: the semantic union rides the compact slot rows
        (docs/semantic_routing.md), so an active semantic table makes
        the cap mandatory the same way.
        """
        cfg = self.config
        if self.subtab is None or (
            not sparse and not semantic and not cfg.fanout_compact
        ):
            return 0
        if cfg.fanout_slots > 0:
            return _next_pow2(cfg.fanout_slots)
        want = KSLOT_MIN
        if self.metrics is not None:
            h = self.metrics.histogram("dispatch.fanout")
            # 256 observations before trusting p99: the first batches
            # after boot are not a fan-out distribution yet
            if h is not None and h.count >= 256:
                want = max(want, 2 * max(1, int(h.p99)))
        k = max(self._kslot, _next_pow2(want))
        self._kslot = k
        if sparse or semantic:
            return k
        if self.mesh is not None:
            # per-shard compaction: each tp shard emits its own kslot-wide
            # list, so the win condition is against the LOCAL lane width
            width_words = max(1, width_words // self.mesh.shape["tp"])
        if k >= width_words * 32:
            return 0  # dense rows are already the smaller readback
        return k

    def _version_key(self):
        """Generation counters of every host table the snapshot is built
        from — equal keys mean the device mirrors are already current."""
        return (
            self.index.version,
            self.subtab.version if self.subtab is not None else -1,
            self.grouptab.version if self.grouptab is not None else -1,
            self.semtab.version if self.semtab is not None else -1,
        )

    def _device_args(self):
        # loop-side growth packs BEFORE the version key: the dirty sync
        # itself grows the bitmap/group tables to cover every live
        # filter id, and that (legitimate, same-thread) version bump
        # must not trip the torn-snapshot check below — filter-only
        # growth (e.g. a bulk route load) would fail its first prepare
        # spuriously. No-ops when capacities already cover the index.
        if self.subtab is not None:
            if (
                self.mesh is not None
                and self.subtab.sparse
                and self.subtab.shards != self.mesh.shape["tp"]
            ):
                # mesh attached after the representation flip (or the
                # app wiring was skipped): re-partition the slot column
                # over 'tp' BEFORE the version key, like any growth
                self.subtab.set_shards(self.mesh.shape["tp"])
            self.subtab.pack(self.index.num_filters_capacity)
        if self.grouptab is not None and len(self.grouptab):
            self.grouptab.pack_fcap(self.index.num_filters_capacity)
        key = self._version_key()
        if self._prep_key == key:
            # clean tables: skip pack/delta-sync entirely. The auto-sized
            # Kslot still gets a periodic re-check (traffic can grow the
            # fanout p99 without any table churn); growth only swaps the
            # cached tuple's kslot element — everything else is current.
            self._clean_streak += 1
            sem_on = self.semtab is not None and len(self.semtab) > 0
            if (
                self._clean_streak % self.KSLOT_RECHECK == 0
                and self.subtab is not None
                and (
                    self.config.fanout_compact
                    or self.subtab.sparse
                    or sem_on
                )
            ):
                kslot = self._fanout_kslot(
                    self.subtab.width_words,
                    sparse=self.subtab.sparse,
                    semantic=sem_on,
                )
                if kslot != self._prep_args[_ARGS_KSLOT]:
                    self._prep_args = (
                        self._prep_args[:_ARGS_KSLOT]
                        + (kslot,)
                        + self._prep_args[_ARGS_KSLOT + 1 :]
                    )
            if self.metrics is not None:
                self.metrics.inc("router.sync.skipped")
            return self._prep_args
        self._clean_streak = 0
        # Epoch discipline around the dirty sync (docs/robustness.md): a
        # pack/upload that raises — or tears (fault mode "corrupt": the
        # snapshot interleaves epochs) — must NEVER become the serving
        # snapshot. Roll back to the last good epoch (the generation
        # counters from the O(dirty) cache make "good" checkable) and
        # leave _prep_key stale so the next prepare retries the sync;
        # serving a slightly-stale-but-consistent table beats serving a
        # torn one, and beats taking the whole batch path down.
        try:
            action = _faults.hit("router.delta_sync")
            args = self._device_args_dirty()
            if action == "corrupt" or self._version_key() != key:
                raise RuntimeError(
                    "torn delta-sync: table generations moved during the "
                    "snapshot"
                )
        except Exception:
            if self._prep_args is None:
                raise  # no good epoch yet: the caller degrades to CPU
            if self.metrics is not None:
                self.metrics.inc("router.sync.rollback")
            return self._prep_args
        self._prep_key = key
        self._prep_args = args
        if self.metrics is not None:
            self.metrics.inc("router.prepare.dirty")
        self._trim_jit_cache()
        return args

    def _trim_jit_cache(self) -> None:
        """Bound the serving jits' compiled-program caches: every table
        growth / config transition compiles a fresh program keyed on the
        new shapes, and a long-lived process (bench sweeps every config
        in ONE process now) must not accumulate every program it ever
        served. Runs only on dirty prepares — the clean path never
        recompiles."""
        lim = getattr(self.config, "jit_cache_max", 0)
        if lim <= 0:
            return
        for fn in (
            shape_route_step,
            shape_route_step_donated,
            fused_route_retained_step,
            route_step,
        ):
            try:
                size = fn._cache_size()
            except Exception:  # noqa: BLE001 — introspection best-effort
                continue
            if size > lim:
                fn.clear_cache()

    def _device_args_dirty(self):
        idx = self.index
        kg = 0
        sem_on = self.semtab is not None and len(self.semtab) > 0
        if self.subtab is not None:
            sparse = self.subtab.sparse
            if sparse != self._bits_sparse:
                # representation flip (router.sub_table policy): swap
                # the mirror manager so the full resync lands under the
                # new placement; the retired mirror frees with it
                self._bits_sync = self._mk_bits_sync(sparse)
                self._bits_sparse = sparse
                if self.metrics is not None:
                    self.metrics.inc("router.sparse.flips")
            # grow the fan-out table to cover every live filter id
            # BEFORE the snapshot — a matched fid must always gather a
            # real bitmap row / CSR region
            self.subtab.pack(idx.num_filters_capacity)
            if self.mesh is not None and not sparse:
                tp = self.mesh.shape["tp"]
                if self.subtab.width_words % tp:
                    # fail HERE with the config fix, before the sharded
                    # upload inside the delta sync raises an opaque
                    # NamedSharding divisibility error
                    raise ValueError(
                        f"subscriber bitmap width "
                        f"{self.subtab.width_words} not divisible by "
                        f"mesh tp={tp}; use a power-of-two tp"
                    )
            snap = self._bits_sync.sync(self.subtab)
            bits = snap if sparse else snap["sub_bitmaps"]
            kslot = self._fanout_kslot(
                self.subtab.width_words, sparse=sparse, semantic=sem_on
            )
            if sparse:
                kg = getattr(self.config, "sparse_gather", 0)
        else:
            bits = None
            kslot = 0
        shape_tables = self._shape_sync.sync(idx.shapes)
        with_nfa = idx.residual_count > 0
        nfa_tables = self._nfa_sync.sync(idx.nfa) if with_nfa else None
        m_active = idx.shapes.m_active()
        if self.grouptab is not None and len(self.grouptab):
            self.grouptab.pack_fcap(idx.num_filters_capacity)
            group_tables = self._group_sync.sync(self.grouptab)
        else:
            group_tables = None
        if sem_on:
            # the semantic mirror rides the same sync machinery: full
            # upload on epoch bumps, op-logged scatter deltas otherwise
            sem_tables = self._sem_sync.sync(self.semtab)
            sem_topk = self.semtab.topk
        else:
            sem_tables = None
            sem_topk = 0
        return (
            shape_tables,
            nfa_tables,
            bits,
            idx.salt,
            m_active,
            with_nfa,
            group_tables,
            kslot,
            kg,
            sem_tables,
            sem_topk,
        )

    # -- segment maintenance (ops/segments.SegmentCompactor) --------------
    def segment_status(self) -> Dict:
        """Hot-segment occupancy + tombstone load of the serving tables —
        feeds the `router.segment.*` gauges and the compaction trigger."""
        sh = self.index.shapes
        return {
            "hot_fill": sh.hot_live,
            "hot_capacity": sh.hot_capacity,
            "tombstones": sh.packed_tombstones,
            "packed_capacity": sh._Tcap,
            "full_resyncs": self._shape_sync.full_resyncs,
            "delta_launches": self._shape_sync.delta_launches,
            "array_resyncs": self._shape_sync.array_resyncs,
        }

    def compaction_owners(self, hot_entries: int = 1024,
                          tombstone_frac: float = 0.25) -> list:
        """Adapters the background `SegmentCompactor` drives: merge the
        shape hot segment into the packed table, and proactively grow
        the subscriber bitmap matrix — both built + pre-uploaded on the
        compaction executor, applied on the loop, so the subscribe path
        never pays an O(table) rebuild or a full upload."""
        from emqx_tpu.ops.segments import (
            BitmapGrowthOwner,
            ShapeSegmentOwner,
        )

        owners = [
            ShapeSegmentOwner(
                self.index.shapes,
                self._shape_sync,
                placement=self._table_placement,
                hot_entries=hot_entries,
                tombstone_frac=tombstone_frac,
            )
        ]
        if self.subtab is not None and self.subtab.sparse:
            # CSR representation: merge the hot segment into the packed
            # slot column + purge tombstones (the ShapeIndex cycle);
            # built + pre-uploaded off the subscribe path
            placement = None
            if self.mesh is not None:
                from emqx_tpu.parallel.mesh import csr_placement

                placement = csr_placement(self.mesh)
            owners.append(
                CsrSegmentOwner(
                    self.subtab,
                    self._bits_sync,
                    placement=placement,
                    hot_entries=hot_entries,
                    tombstone_frac=tombstone_frac,
                )
            )
        elif self.subtab is not None:
            owners.append(
                BitmapGrowthOwner(
                    self.subtab,
                    self.index,
                    self._bits_sync,
                    placement=self._bitmap_placement,
                )
            )
        if self.semtab is not None:
            sem_place = None
            if self.mesh is not None:
                from emqx_tpu.parallel.mesh import semantic_placement

                sem_place = semantic_placement(self.mesh)
            owners.append(
                SemanticSegmentOwner(
                    self.semtab,
                    self._sem_sync,
                    placement=sem_place,
                    hot_entries=hot_entries,
                    tombstone_frac=tombstone_frac,
                )
            )
        return owners

    def prepare(self):
        """Snapshot + upload current tables/bitmaps. MUST run on the thread
        that mutates the index/subtab (the event loop): packing walks live
        Python structures. The returned tuple is immutable device state
        safe to hand to `route_prepared` on a worker thread."""
        import time

        t0 = time.perf_counter()
        args = self._device_args()
        if self.metrics is not None:
            self.metrics.observe(
                "router.sync.seconds", time.perf_counter() - t0
            )
        return args

    def route(self, topics, client_hashes=None, embeds=None, rules=None):
        """Batch route: returns a host-side `RouteResult` (all numpy)."""
        return self.route_prepared(
            self._device_args(), topics, client_hashes,
            embeds=embeds, rules=rules,
        )

    def route_prepared(self, args, topics, client_hashes=None,
                       retained=None, session=None, embeds=None,
                       rules=None):
        """Kernel launch + readback against a `prepare()` snapshot; touches
        no mutable host state, so it may run in an executor thread while
        the event loop keeps serving connections (the jit compile on a new
        batch/table shape can take tens of seconds on a real chip).

        `client_hashes` ([B] uint32, stable_hash of each publisher id)
        feeds the device $share pick; required only when a group table is
        loaded and the strategy is hash_clientid.

        `embeds` ([B, D] f32 per-message embeddings) feeds the fused
        semantic-match stage when the prepared args carry a semantic
        table (rows without an embedding ride a zero vector — matching
        nothing at any positive threshold). `rules` is an optional
        ``(progs, feats, valid)`` triple from rules/compile.
        DeviceRuleFilter: the compiled WHERE masks evaluate inside this
        same launch and land in `RouteResult.rule_masks`.

        `retained`: an optional prepared replay storm
        (DeviceRetainedIndex.prepare_storm) to fuse into this launch —
        chunk 0 rides the SAME program (fused_route_retained_step — or
        dist_fused_step on a `MeshServingRouter`) and the same readback;
        additional chunks (stores past 1M topics) launch alongside
        before any readback. Engines that cannot fuse advertise
        `supports_retained_fusion = False` and must not be handed a
        storm. The decoded {filter: rows} lands in
        `RouteResult.retained`. Returns a `RouteResult`.
        """
        import time

        t0 = time.perf_counter()
        out = self._route_prepared(
            args, topics, client_hashes, retained, session, embeds,
            rules, t_launch0=t0,
        )
        if self.metrics is not None:
            # Histogram.observe is lock-safe: this runs on executor threads
            wall = time.perf_counter() - t0
            self.metrics.observe("router.device.seconds", wall)
            self.metrics.observe("router.batch.size", len(topics))
            # per-kernel launch attribution (observe/profiler.py): the
            # whole launch's wall + readback into every contract kernel
            # that rode the program
            record_kernel_launch(
                self.metrics, out.kernels, wall, out.readback_bytes
            )
            # cumulative link-bandwidth accounting (device_watch.py)
            self.metrics.inc("device.transfer.bytes", out.readback_bytes)
            if out.bitmaps is not None or out.slots is not None:
                self.metrics.observe(
                    "dispatch.readback.bytes", out.readback_bytes
                )
            if out.slots is not None:
                n_ovf = int(np.count_nonzero(out.overflow))
                self.metrics.inc(
                    "dispatch.compact.rows", len(topics) - n_ovf
                )
                if n_ovf:
                    self.metrics.inc(
                        "dispatch.compact.overflow.rows", n_ovf
                    )
        return out

    def _route_prepared(self, args, topics, client_hashes=None,
                        retained=None, session=None, embeds=None,
                        rules=None, t_launch0=None):
        from emqx_tpu.broker.shared_sub import stable_hash
        from emqx_tpu.ops import tokenizer as tok

        # fault site: a failed tpu-dispatch launch (raise) or a slow one
        # (delay) — the broker's degradation ladder handles both
        _faults.hit("device.launch")
        cfg = self.config
        (
            shape_tables,
            nfa_tables,
            bits,
            salt,
            m_active,
            with_nfa,
            group_tables,
            kslot,
            kg,
            sem_tables,
            sem_topk,
        ) = args
        B = len(topics)
        Bp = max(64, _next_pow2(B))
        mat, lens, too_long = tok.encode_topics(list(topics), cfg.max_bytes)
        if Bp != B:
            mat = np.pad(mat, ((0, Bp - B), (0, 0)))
            lens = np.pad(lens, (0, Bp - B))
        with_groups = group_tables is not None
        if with_groups:
            # only the inputs this strategy reads are materialized — the
            # others are cheap zero vectors, not per-topic Python hashing
            ch = np.zeros(Bp, np.uint32)
            if client_hashes is not None:
                ch[:B] = np.asarray(client_hashes, np.uint32)
            if self.share_strategy == 4:  # hash_topic
                # TopicRef entries (zero-copy slab rows) decode here:
                # the pick hash is defined over the str form
                th = np.fromiter(
                    (
                        stable_hash(t if isinstance(t, str) else str(t))
                        for t in topics
                    ),
                    np.uint32,
                    count=B,
                )
                th = np.pad(th, (0, Bp - B))
            else:
                th = np.zeros(Bp, np.uint32)
            if self.share_strategy in (0, 2):  # random / sticky fallback
                rand = np.random.default_rng(
                    next(self._rand_seq)
                ).integers(0, 1 << 32, size=Bp, dtype=np.uint32)
            else:
                rand = np.zeros(Bp, np.uint32)
        else:
            ch = th = rand = None
        if sem_tables is not None:
            # per-message query embeddings, padded like the batch; rows
            # without one ride a zero vector (matches nothing at any
            # positive threshold)
            D = sem_tables["sem_vec"].shape[2]
            qv = np.zeros((Bp, D), np.float32)
            if embeds is not None:
                qv[:B] = np.asarray(embeds, np.float32)
        else:
            qv = None
        if rules is not None and rules[0]:
            rprogs, rf, rv = rules
            F = rf.shape[1]
            rfeats = np.zeros((Bp, F), np.float32)
            rfeats[:B] = rf
            rvalid = np.zeros((Bp, F), bool)
            rvalid[:B] = rv
        else:
            rprogs, rfeats, rvalid = (), None, None
        if self.mesh is not None and bits is not None:
            if session is not None:
                # engine contract: callers gate on
                # supports_session_fusion — the mesh engine's session
                # mirror updates ride the segment scatter path instead
                raise RuntimeError(
                    "session rider handed to a non-fusing mesh engine"
                )
            return self._route_mesh(
                shape_tables, nfa_tables, bits, salt, m_active, with_nfa,
                mat, lens, B, too_long, group_tables, ch, th, rand, kslot,
                retained=retained, kg=kg,
                sem_tables=sem_tables, sem_topk=sem_topk, qv=qv,
                rprogs=rprogs, rfeats=rfeats, rvalid=rvalid,
                t_launch0=t_launch0,
            )
        step_kw = dict(
            m_active=m_active,
            with_nfa=with_nfa,
            salt=salt,
            max_levels=cfg.max_levels,
            frontier=cfg.frontier,
            max_matches=cfg.max_matches,
            probes=cfg.probes,
            with_groups=with_groups,
            share_strategy=self.share_strategy,
            kslot=kslot,
            kg=kg,
            sem_topk=sem_topk,
            rule_progs=rprogs,
        )
        if session is not None:
            # the fused session-ack stage: the rider's inflight writes +
            # retry/expiry sweep ride THIS launch and THIS readback (the
            # broker never pairs a rider with a retained storm)
            out = session_route_step(
                shape_tables, nfa_tables, bits, mat, lens,
                session.arrays, session.idxs, session.vals,
                session.clock,
                group_tables, ch, th, rand,
                sem_tables, qv, rfeats, rvalid,
                sweep_k=session.sweep_k, **step_kw,
            )
            return self._readback(
                out, B, too_long, with_groups, kslot, session=session,
                kernels=("shape_route_step", "session_ack_step"),
                t_launch0=t_launch0,
            )
        if retained is not None and retained.chunks:
            # one launch, one readback: the storm's chunk-0 match rides
            # the route program; extra chunks launch before any readback
            out = fused_route_retained_step(
                shape_tables, nfa_tables, bits, mat, lens,
                retained.shape_tables, retained.nfa_tables,
                retained.chunks[0],
                group_tables, ch, th, rand,
                sem_tables, qv, rfeats, rvalid,
                ret_m_active=retained.kwargs["m_active"],
                ret_with_nfa=retained.kwargs["with_nfa"],
                ret_salt=retained.kwargs["salt"],
                ret_max_levels=retained.kwargs["max_levels"],
                ret_narrow=retained.kwargs["narrow"],
                **step_kw,
            )
            from emqx_tpu.models.retained_index import _get_retained_step

            rstep = _get_retained_step()
            extra = [
                rstep(
                    retained.shape_tables, retained.nfa_tables, c,
                    **retained.kwargs,
                )
                for c in retained.chunks[1:]
            ]
            return self._readback(
                out, B, too_long, with_groups, kslot,
                retained=retained, extra_retained=extra,
                kernels=("fused_route_retained_step",),
                t_launch0=t_launch0,
            )
        step = (
            shape_route_step_donated
            if getattr(cfg, "donate_buffers", False)
            else shape_route_step
        )
        out = step(
            shape_tables,
            nfa_tables,
            bits,
            mat,
            lens,
            group_tables,
            ch,
            th,
            rand,
            sem_tables,
            qv,
            rfeats,
            rvalid,
            **step_kw,
        )
        return self._readback(
            out, B, too_long, with_groups, kslot,
            kernels=("shape_route_step",), t_launch0=t_launch0,
        )

    def _readback(  # readback-site
        self, out, B, too_long, with_groups, kslot, mesh=False,
        retained=None, extra_retained=None, session=None,
        kernels=(), t_launch0=None,
    ):
        """Pull one batch's outputs to host -> `RouteResult`.

        This is THE bandwidth boundary the compaction stage exists for:
        with ``kslot`` on, only the O(matches) compact arrays cross the
        link, plus one masked second transfer of the dense bitmap rows
        for the (overflow-flagged) rows the cap could not hold. Dense
        ``bitmaps`` rows of the full batch transfer only when compaction
        is off (or for match-only callers, never).

        Everything the batch needs crosses in ONE `jax.device_get` of a
        trimmed dict (sliced to the live rows): each separate `asarray`
        pull used to pay its own sync + RTT — eight of them per batch on
        the group+compact path — where one coalesced transfer pays one.
        Only the overflow fetch remains a (rare, masked) second
        transfer, because which rows need it is decided by `slot_count`,
        which must be on host first.

        ``mesh``: single-device overflow is derived on host from
        ``slot_count > kslot`` (one fewer array on the link); the mesh
        kernel's overflow is per-shard (any tp shard over its local cap)
        and must be read back.
        """
        # fault site: a wedged/failed device->host transfer (the other
        # half of the launch's round trip; same recovery ladder)
        _faults.hit("device.readback")
        import time

        # waterfall stages (observe/profiler.py): `launch` = host encode
        # + kernel enqueue up to here; `device_execute` = program
        # completion wait; `readback` = the coalesced device_get + host
        # decode. Per-batch perf_counter reads, nothing per-message.
        m = self.metrics
        t_rb0 = time.perf_counter()
        if m is not None:
            if t_launch0 is not None:
                m.observe(
                    "profile.stage.launch.seconds", t_rb0 - t_launch0
                )
            # the program's outputs complete together: waiting on one
            # output IS the device-execute boundary
            jax.block_until_ready(out["matched"])
            t_dev = time.perf_counter()
            m.observe(
                "profile.stage.device_execute.seconds", t_dev - t_rb0
            )
        else:
            t_dev = t_rb0
        pulls = {
            "matched": out["matched"][:B],
            "mcount": out["mcount"][:B],
            "flags": out["flags"][:B],
        }
        if with_groups:
            pulls["pick_gid"] = out["pick_gid"][:B]
            pulls["pick_idx"] = out["pick_idx"][:B]
        # sparse (CSR) fan-out: compact outputs exist with NO dense
        # bitmap matrix behind them — overflow rows rebuild on host
        sparse_fan = out["bitmaps"] is None and out.get("slots") is not None
        if out["bitmaps"] is not None or sparse_fan:
            if kslot:
                pulls["slots"] = out["slots"][:B]
                pulls["slot_count"] = out["slot_count"][:B]
                if mesh:
                    pulls["overflow"] = out["overflow"][:B]
            else:
                pulls["bitmaps"] = out["bitmaps"][:B]
        if retained is not None:
            # the fused storm's chunk-0 match matrix rides the SAME
            # coalesced transfer as the route outputs; extra chunks
            # (launched alongside, no barrier) join the one device_get
            pulls["retained"] = out["retained"]
            for j, m in enumerate(extra_retained or ()):
                pulls[f"retained_{j + 1}"] = m
        if out.get("sem_count") is not None:
            # the semantic winners are already unioned into `slots`;
            # only the O(B) qualifying count crosses separately
            pulls["sem_count"] = out["sem_count"][:B]
        if out.get("rule_masks") is not None:
            pulls["rule_masks"] = out["rule_masks"][:, :B]
        if session is not None and session.sweep_k:
            # the session sweep's compact lists join the one device_get;
            # the updated table arrays themselves NEVER cross the link
            sess = out["session"]
            pulls["session_due"] = sess["due"]
            pulls["session_due_count"] = sess["due_count"]
            pulls["session_expired"] = sess["expired"]
            pulls["session_expired_count"] = sess["expired_count"]
        host = jax.device_get(pulls)
        if m is not None:
            m.observe(
                "profile.stage.readback.seconds",
                time.perf_counter() - t_dev,
            )
        matched = host["matched"]
        sem_count = host.get("sem_count")
        rule_masks = host.get("rule_masks")
        mcount = host["mcount"]
        flags = host["flags"] | too_long
        picks = (
            (host["pick_gid"], host["pick_idx"]) if with_groups else None
        )
        readback = 0
        for v in host.values():
            readback += v.nbytes
        # refine the launch's kernel-attribution names from what the
        # program actually carried: the CSR/semantic/compaction stages
        # are registered contracts of their own, and the base serving
        # program traces under a different registry name per table rep
        kern = list(kernels)
        if mesh:
            if "dist_shape_step" in kern:
                if sparse_fan:
                    kern[kern.index("dist_shape_step")] = (
                        "sparse_dist_shape_step"
                    )
                elif sem_count is not None:
                    kern[kern.index("dist_shape_step")] = (
                        "sem_dist_shape_step"
                    )
        else:
            if sparse_fan:
                if "shape_route_step" in kern:
                    kern[kern.index("shape_route_step")] = (
                        "sparse_shape_route_step"
                    )
                kern.append("sparse_fanout_slots")
            elif kslot and host.get("slots") is not None:
                kern.append("compact_fanout_slots")
            if sem_count is not None:
                kern.append("semantic_match_step")
        kernels = tuple(kern)
        retained_res = None
        if retained is not None:
            chunks_m = [host["retained"]] + [
                host[f"retained_{j + 1}"]
                for j in range(len(extra_retained or ()))
            ]
            retained_res = retained.decode(chunks_m)
        sess_res = None
        if session is not None:
            from emqx_tpu.broker.session_store import SessionStepOut

            sess = out["session"]
            if session.sweep_k:
                sess_res = SessionStepOut(
                    sess["tables"],
                    host["session_due"],
                    int(host["session_due_count"]),
                    host["session_expired"],
                    int(host["session_expired_count"]),
                )
            else:
                sess_res = SessionStepOut(sess["tables"], None, 0, None, 0)
        if out["bitmaps"] is None and not sparse_fan:
            return RouteResult(
                matched, mcount, flags, None, picks,
                readback_bytes=readback, retained=retained_res,
                session=sess_res, sem_count=sem_count,
                rule_masks=rule_masks, kernels=kernels,
            )
        if kslot:
            slots = host["slots"]
            slot_count = host["slot_count"]
            if mesh:
                overflow = host["overflow"]
            else:
                # holds on the sparse path too: the kernel forces
                # count past kslot for gather-window overflow rows
                overflow = slot_count > kslot
            dense_rows = dense_index = None
            ovf_idx = np.nonzero(overflow)[0]
            if ovf_idx.size:
                dense_index = {int(r): j for j, r in enumerate(ovf_idx)}
                if sparse_fan:
                    # no dense matrix exists: the fallback rows build
                    # lazily from the HOST table at dispatch time (on
                    # the loop thread — see _LazyDenseRows); nothing
                    # extra crosses the link
                    dense_rows = _LazyDenseRows(
                        self.subtab,
                        [
                            matched[r][matched[r] >= 0].tolist()
                            for r in ovf_idx
                        ],
                    )
                    if self.metrics is not None:
                        self.metrics.inc(
                            "router.sparse.overflow.rows",
                            int(ovf_idx.size),
                        )
                else:
                    # masked second transfer: ONLY the rows whose fan-
                    # out exceeded the cap come back dense
                    dense_rows = np.ascontiguousarray(
                        jax.device_get(out["bitmaps"][ovf_idx])
                    )
                    readback += dense_rows.nbytes
            return RouteResult(
                matched, mcount, flags, None, picks,
                slots=slots, slot_count=slot_count, overflow=overflow,
                dense_rows=dense_rows, dense_index=dense_index,
                readback_bytes=readback, retained=retained_res,
                session=sess_res, sem_count=sem_count,
                rule_masks=rule_masks, kernels=kernels,
            )
        # ascontiguousarray: some backends (axon TPU) hand back strided
        # buffers, and the dispatch path reinterprets rows as uint8
        bitmaps = np.ascontiguousarray(host["bitmaps"])
        return RouteResult(
            matched, mcount, flags, bitmaps, picks,
            readback_bytes=readback, retained=retained_res,
            session=sess_res, sem_count=sem_count,
            rule_masks=rule_masks, kernels=kernels,
        )

    # engine capability flag the broker gates storm fusion on: the
    # single-device engine fuses via fused_route_retained_step; a plain
    # DeviceRouter pointed at a mesh has no fused mesh program (that is
    # MeshServingRouter's job), so a storm must not be handed to it
    @property
    def supports_retained_fusion(self) -> bool:
        return self.mesh is None

    # session-ack fusion (session_route_step) is a single-device program;
    # the mesh engine's session mirrors update via the segment scatter
    # path on the 'dp'-sharded placement instead (docs/sessions.md)
    @property
    def supports_session_fusion(self) -> bool:
        return self.mesh is None

    def span_attrs(self) -> Dict:
        """Engine attributes stamped onto `router.device_step` spans."""
        return {}

    def _route_mesh(
        self, shape_tables, nfa_tables, bits, salt, m_active, with_nfa,
        mat, lens, B, too_long, group_tables=None, ch=None, th=None,
        rand=None, kslot=0, retained=None, kg=0, sem_tables=None,
        sem_topk=0, qv=None, rprogs=(), rfeats=None, rvalid=None,
        t_launch0=None,
    ):
        """SPMD serving: the batch rides dist_shape_route_step over the
        device mesh (SURVEY §2.4 TPU mapping; the multi-chip layout the
        dryrun gate compiles). Tables/bitmaps arrive ALREADY sharded —
        the sync mirrors upload straight into the canonical layout, so
        nothing is re-placed per batch; only the topic batch itself (and
        the per-topic $share pick entropy, which shards with it) is
        placed here."""
        from emqx_tpu.parallel.mesh import dist_shape_route_step, place_batch

        if retained is not None:
            # engine contract: callers gate on supports_retained_fusion.
            # Silently dropping the storm here would hang its waiters.
            raise RuntimeError(
                "retained storm handed to a non-fusing mesh engine; "
                "use MeshServingRouter for mesh serving"
            )
        cfg = self.config
        mat, lens, ch, th, rand, with_groups = self._mesh_pad(
            mat, lens, ch, th, rand, group_tables is not None
        )
        qv, rfeats, rvalid = self._mesh_pad_rows(mat, qv, rfeats, rvalid)
        st, nt, sb = shape_tables, nfa_tables, bits
        bm, ln = place_batch(self.mesh, mat, lens)
        out = dist_shape_route_step(
            self.mesh,
            st,
            nt,
            sb,
            bm,
            ln,
            group_tables,
            ch,
            th,
            rand,
            sem_tables,
            qv,
            rfeats,
            rvalid,
            m_active=m_active,
            salt=salt,
            max_levels=cfg.max_levels,
            frontier=cfg.frontier,
            max_matches=cfg.max_matches,
            probes=cfg.probes,
            share_strategy=self.share_strategy,
            kslot=kslot,
            kg=kg,
            sem_topk=sem_topk,
            rule_progs=rprogs,
            donate=getattr(cfg, "donate_buffers", False),
        )
        return self._readback(
            out, B, too_long, with_groups, kslot, mesh=True,
            kernels=("dist_shape_step",), t_launch0=t_launch0,
        )

    @staticmethod
    def _mesh_pad_rows(mat, qv, rfeats, rvalid):
        """Per-row semantic/rule operands pad to the dp-padded batch
        length the same way the $share entropy vectors do."""
        rows = mat.shape[0]
        if qv is not None and len(qv) != rows:
            qv = np.pad(qv, ((0, rows - len(qv)), (0, 0)))
        if rfeats is not None and len(rfeats) != rows:
            rfeats = np.pad(rfeats, ((0, rows - len(rfeats)), (0, 0)))
            rvalid = np.pad(rvalid, ((0, rows - len(rvalid)), (0, 0)))
        return qv, rfeats, rvalid

    def _mesh_pad(self, mat, lens, ch, th, rand, with_groups):
        """Round the batch up to a dp multiple (shard_map constraint) and
        keep the per-topic $share entropy vectors the same length.
        (Bitmap-width/tp divisibility is checked in _device_args, before
        the sharded upload; mat was already padded to a pow2 >= 64 — the
        extra rows here cover non-pow2 dp sizes.)"""
        dp = self.mesh.shape["dp"]
        rows = mat.shape[0]
        if rows % dp:
            extra = dp - rows % dp
            mat = np.pad(mat, ((0, extra), (0, 0)))
            lens = np.pad(lens, (0, extra))
        if with_groups and mat.shape[0] != (0 if ch is None else len(ch)):
            pad = mat.shape[0] - len(ch)
            ch = np.pad(ch, (0, pad))
            th = np.pad(th, (0, pad))
            rand = np.pad(rand, (0, pad))
        return mat, lens, ch, th, rand, with_groups

    def match_batch(
        self, topics: Sequence[str], fallback=None
    ) -> List[List[str]]:
        """Match topic strings -> matched filter names (no fan-out half).

        Flagged rows (too deep / NFA overflow) go to `fallback(topic)`.
        Each device hit is re-verified on host with a single-pair topic
        match before being returned: the shape path's 64-bit combined hash
        admits a ~2^-64 false positive, and a route decision (unlike local
        dispatch, which re-checks per delivery) would propagate it
        cluster-wide.
        """
        from emqx_tpu.ops import topics as T

        res = self.route(topics)
        matched, flags = res.matched, res.flags
        out: List[List[str]] = []
        for i, t in enumerate(topics):
            if flags[i]:
                if fallback is None:
                    # per-row error contract (ops/matcher.MatchError):
                    # one flagged row must not poison its batchmates
                    from emqx_tpu.ops.matcher import MatchError

                    out.append(MatchError(t))
                else:
                    out.append(fallback(t))
                continue
            row = matched[i]
            names = []
            for fid in row[row >= 0]:
                name = self.index.filter_name(int(fid))
                if name is not None and T.match(t, name):
                    names.append(name)
            out.append(names)
        return out


class MeshServingRouter(DeviceRouter):
    """The scale-out serving engine: `route_prepared` runs the SPMD dist
    step over a ('dp','tp') mesh as the broker's REAL dispatch engine —
    subscription table sharded over 'tp' (subscriber-lane slices), the
    ingest batch over 'dp', with retained-replay storms fused into the
    same sharded program (`dist_fused_step`). Everything the
    single-device engine earned is preserved by inheritance: the
    O(dirty) prepare cache, buffer donation, Kslot auto-sizing (against
    the per-shard lane width), the breaker/degrade ladder hooks, and the
    segment-manager upload path (all mirrors land pre-sharded via the
    placement hooks — nothing is re-placed per batch).

    `shard_label` names the mesh slice this process owns for span/
    metric attribution; a clustered node sets it to its advertised
    ('dp','tp') slice (cluster/route_sync.ShardOwnership), a standalone
    mesh broker keeps the default.
    """

    supports_retained_fusion = True

    def __init__(
        self,
        index,
        subtab: Optional[SubscriberTable],
        config=None,
        grouptab: Optional[GroupTable] = None,
        share_strategy: str = "round_robin",
        mesh=None,
        metrics=None,
        semtab=None,
    ):
        if mesh is None:
            raise ValueError("MeshServingRouter requires a ('dp','tp') mesh")
        super().__init__(
            index, subtab, config, grouptab=grouptab,
            share_strategy=share_strategy, mesh=mesh, metrics=metrics,
            semtab=semtab,
        )
        self.shard_label = "local"  # single-writer: loop

    def span_attrs(self) -> Dict:
        sh = self.mesh.shape
        return {
            "device.mesh_shape": f"{sh['dp']}x{sh['tp']}",
            "device.shard": self.shard_label,
        }

    def shard_status(self) -> Dict:
        """Per-tp-shard lane occupancy of the subscriber matrix — feeds
        the `mesh.shard.*` gauges. Nonzero WORDS (not bits): one pass of
        numpy counting, cheap enough for a housekeeping tick."""
        sh = dict(self.mesh.shape)
        out = {"dp": sh["dp"], "tp": sh["tp"], "shards": sh["dp"] * sh["tp"]}
        if self.subtab is not None and self.subtab.sparse:
            # CSR shards: per-'tp'-slice live-subscription counts (the
            # sparse lane-fill analog — exact, one pass over [S, F])
            sp = self.subtab.csr
            per = sp.csr_len.sum(axis=1)
            hot_live = (sp.hot_fid >= 0).sum(axis=1)
            fills = (per + hot_live).astype(np.float64)
            denom = max(1.0, float(fills.sum()))
            out["lane_fill_max"] = float(fills.max()) / denom
            out["lane_fill_min"] = float(fills.min()) / denom
            out["sub_table"] = "sparse"
            return out
        if self.subtab is not None:
            arr = self.subtab.arr
            tp = sh["tp"]
            w = arr.shape[1]
            per = w // tp if tp and w % tp == 0 else w
            fills = []
            for s in range(w // per if per else 0):
                sl = arr[:, s * per : (s + 1) * per]
                fills.append(
                    float(np.count_nonzero(sl)) / max(1, sl.size)
                )
            out["lane_fill_max"] = max(fills) if fills else 0.0
            out["lane_fill_min"] = min(fills) if fills else 0.0
        return out

    def _route_mesh(
        self, shape_tables, nfa_tables, bits, salt, m_active, with_nfa,
        mat, lens, B, too_long, group_tables=None, ch=None, th=None,
        rand=None, kslot=0, retained=None, kg=0, sem_tables=None,
        sem_topk=0, qv=None, rprogs=(), rfeats=None, rvalid=None,
        t_launch0=None,
    ):
        """SPMD serving with optional fused retained storm: chunk 0 of a
        prepared `StormJob` rides the SAME sharded program + readback
        (its rows scan sharded over 'dp'); extra chunks launch alongside
        before any readback — exactly the single-device fusion contract,
        spread over the mesh."""
        if retained is None or not retained.chunks:
            return super()._route_mesh(
                shape_tables, nfa_tables, bits, salt, m_active, with_nfa,
                mat, lens, B, too_long, group_tables, ch, th, rand, kslot,
                kg=kg, sem_tables=sem_tables, sem_topk=sem_topk, qv=qv,
                rprogs=rprogs, rfeats=rfeats, rvalid=rvalid,
                t_launch0=t_launch0,
            )
        from emqx_tpu.parallel.mesh import (
            dist_fused_route_step,
            place_batch,
        )

        cfg = self.config
        mat, lens, ch, th, rand, with_groups = self._mesh_pad(
            mat, lens, ch, th, rand, group_tables is not None
        )
        qv, rfeats, rvalid = self._mesh_pad_rows(mat, qv, rfeats, rvalid)
        bm, ln = place_batch(self.mesh, mat, lens)
        out = dist_fused_route_step(
            self.mesh,
            shape_tables,
            nfa_tables,
            bits,
            bm,
            ln,
            retained.shape_tables,
            retained.nfa_tables,
            retained.chunks[0],
            group_tables,
            ch,
            th,
            rand,
            sem_tables,
            qv,
            rfeats,
            rvalid,
            m_active=m_active,
            salt=salt,
            ret_m_active=retained.kwargs["m_active"],
            ret_with_nfa=retained.kwargs["with_nfa"],
            ret_salt=retained.kwargs["salt"],
            ret_max_levels=retained.kwargs["max_levels"],
            ret_narrow=retained.kwargs["narrow"],
            max_levels=cfg.max_levels,
            frontier=cfg.frontier,
            max_matches=cfg.max_matches,
            probes=cfg.probes,
            share_strategy=self.share_strategy,
            kslot=kslot,
            kg=kg,
            sem_topk=sem_topk,
            rule_progs=rprogs,
            donate=getattr(cfg, "donate_buffers", False),
        )
        from emqx_tpu.models.retained_index import _get_retained_step

        rstep = _get_retained_step()
        extra = [
            rstep(
                retained.shape_tables, retained.nfa_tables, c,
                **retained.kwargs,
            )
            for c in retained.chunks[1:]
        ]
        return self._readback(
            out, B, too_long, with_groups, kslot, mesh=True,
            retained=retained, extra_retained=extra,
            kernels=("dist_fused_step",), t_launch0=t_launch0,
        )
