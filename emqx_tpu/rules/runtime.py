"""Rule SQL evaluator: apply a parsed Query to an event context dict.

Reference analog: emqx_rule_runtime.erl — select/where evaluation per
event, with the reference's semantics:
- unknown fields evaluate to None ('undefined');
- `payload` is lazily JSON-decoded when a dotted path reaches into it
  (the reference decodes on demand the same way);
- comparisons against None are False except =/!= equality checks;
- FOREACH iterates an array expression, applying DO/INCASE per element;
- un-aliased dotted selects keep their nested shape in the output
  (`SELECT payload.x` -> {"payload": {"x": ...}}).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

from emqx_tpu.rules.funcs import CONTEXT_FUNCS, FUNCS
from emqx_tpu.rules.sql import (
    BinOp,
    Call,
    Case,
    InList,
    Lit,
    Query,
    SelectItem,
    UnOp,
    Var,
)


class RuleEvalError(Exception):
    pass


def _decode_payload(val):
    if isinstance(val, (bytes, str)):
        try:
            return json.loads(val)
        except (ValueError, TypeError):
            return None
    return val


def _walk(ctx: Dict, path: List[object]):
    cur: Any = ctx
    for i, seg in enumerate(path):
        if cur is None:
            return None
        if isinstance(seg, int):
            if isinstance(cur, (list, tuple)) and 1 <= seg <= len(cur):
                cur = cur[seg - 1]  # SQL arrays are 1-based
            else:
                return None
            continue
        if isinstance(cur, (bytes, str)) and i > 0:
            # dotted access into an undecoded JSON payload string/bytes
            cur = _decode_payload(cur)
        if not isinstance(cur, dict) or seg not in cur:
            return None
        cur = cur[seg]
    return cur


def _truthy(v) -> bool:
    return v is True or v == "true" or (isinstance(v, (int, float)) and not isinstance(v, bool) and v != 0)


def _cmp_values(a, b):
    """Normalize operands: numeric strings compare numerically."""
    if isinstance(a, (int, float)) and not isinstance(a, bool) and isinstance(b, str):
        try:
            return a, float(b)
        except ValueError:
            return a, b
    if isinstance(b, (int, float)) and not isinstance(b, bool) and isinstance(a, str):
        try:
            return float(a), b
        except ValueError:
            return a, b
    if isinstance(a, bytes):
        a = a.decode("utf-8", "replace")
    if isinstance(b, bytes):
        b = b.decode("utf-8", "replace")
    return a, b


def eval_expr(node, ctx: Dict):
    if isinstance(node, Lit):
        return node.value
    if isinstance(node, Var):
        return _walk(ctx, node.path)
    if isinstance(node, UnOp):
        v = eval_expr(node.operand, ctx)
        if node.op == "not":
            return not _truthy(v)
        if node.op == "neg":
            return -v if isinstance(v, (int, float)) else None
    if isinstance(node, InList):
        needle = eval_expr(node.needle, ctx)
        items = [eval_expr(i, ctx) for i in node.items]
        hit = any(_eq(needle, i) for i in items)
        return hit != node.negated
    if isinstance(node, Case):
        for cond, result in node.whens:
            if _truthy(eval_expr(cond, ctx)):
                return eval_expr(result, ctx)
        return eval_expr(node.default, ctx) if node.default is not None else None
    if isinstance(node, Call):
        # zero-arg message-context accessors (clientid(), topic(), ...)
        cf = CONTEXT_FUNCS.get(node.name)
        if cf is not None and not node.args:
            return cf(ctx)
        if node.name == "flag" and len(node.args) == 1:
            from emqx_tpu.rules.funcs import context_flag

            return context_flag(ctx, eval_expr(node.args[0], ctx))
        if node.name == "topic" and len(node.args) == 1:
            # topic(N): Nth level of the message topic, 1-based
            # (emqx_rule_funcs.erl topic/1 -> lists:nth over tokens)
            i = eval_expr(node.args[0], ctx)
            toks = str(ctx.get("topic") or "").split("/")
            if isinstance(i, (int, float)) and 1 <= int(i) <= len(toks):
                return toks[int(i) - 1]
            return None
        if node.name == "payload" and len(node.args) == 1:
            # payload(Path): nested get into the (decoded) payload map
            # (emqx_rule_funcs.erl payload/1 -> nested_get(map_path(...)))
            from emqx_tpu.rules.funcs import FUNCS as _F

            return _F["map_path"](
                eval_expr(node.args[0], ctx), ctx.get("payload")
            )
        fn = FUNCS.get(node.name)
        if fn is None:
            raise RuleEvalError(f"unknown function {node.name!r}")
        return fn(*[eval_expr(a, ctx) for a in node.args])
    if isinstance(node, BinOp):
        op = node.op
        if op == "and":
            return _truthy(eval_expr(node.left, ctx)) and _truthy(
                eval_expr(node.right, ctx)
            )
        if op == "or":
            return _truthy(eval_expr(node.left, ctx)) or _truthy(
                eval_expr(node.right, ctx)
            )
        a = eval_expr(node.left, ctx)
        b = eval_expr(node.right, ctx)
        if op == "=":
            return _eq(a, b)
        if op == "!=":
            return not _eq(a, b)
        if op in (">", "<", ">=", "<="):
            a, b = _cmp_values(a, b)
            try:
                if op == ">":
                    return a > b
                if op == "<":
                    return a < b
                if op == ">=":
                    return a >= b
                return a <= b
            except TypeError:
                return False
        # arithmetic
        if op == "+" and isinstance(a, str) and isinstance(b, str):
            return a + b
        if not isinstance(a, (int, float)) or isinstance(a, bool):
            return None
        if not isinstance(b, (int, float)) or isinstance(b, bool):
            return None
        if op == "+":
            return a + b
        if op == "-":
            return a - b
        if op == "*":
            return a * b
        if op == "/":
            return a / b if b != 0 else None
        if op == "div":
            return int(a) // int(b) if b != 0 else None
        if op == "mod":
            return int(a) % int(b) if b != 0 else None
    raise RuleEvalError(f"cannot evaluate {node!r}")


def _eq(a, b) -> bool:
    a, b = _cmp_values(a, b)
    if isinstance(a, bool) or isinstance(b, bool):
        return a is b
    return a == b


def _set_path(out: Dict, path: List[str], value) -> None:
    cur = out
    for seg in path[:-1]:
        nxt = cur.get(seg)
        if not isinstance(nxt, dict):
            nxt = cur[seg] = {}
        cur = nxt
    cur[path[-1]] = value


def _project(selects: Optional[List[SelectItem]], ctx: Dict) -> Dict:
    if selects is None:  # SELECT *
        return {k: v for k, v in ctx.items() if not k.startswith("__")}
    out: Dict = {}
    for item in selects:
        val = eval_expr(item.expr, ctx)
        if item.alias:
            _set_path(out, item.alias, val)
        elif isinstance(item.expr, Var):
            path = [str(p) for p in item.expr.path]
            if path[0] == "payload" and len(path) > 1:
                _set_path(out, path, val)
            else:
                _set_path(out, [path[-1]], val)
        else:
            # un-aliased computed column: reference names it by position
            _set_path(out, [f"${len(out)}"], val)
    return out


def eval_where_rows(q: Query, ctxs: List[Dict]):
    """Vectorized batch WHERE: one bool mask for a whole dispatch batch
    of event contexts, instead of per-message dict-row evaluation.

    Compilable predicates (rules/compile.py) evaluate ONCE over numpy
    feature columns — the host rung of the device/numpy/scalar degrade
    ladder; rows a hashed (inexact) program passes re-verify with the
    scalar evaluator, and uncompilable expressions fall back to the
    scalar loop wholesale. Differential-tested against `eval_expr` in
    tests/test_rule_compile.py.
    """
    import numpy as np

    if q.where is None:
        return np.ones(len(ctxs), bool)
    from emqx_tpu.rules.compile import (
        compile_where,
        eval_prog,
        extract_features,
    )

    lanes: Dict = {}
    res = compile_where(q.where, lanes)
    if res is None:
        return np.fromiter(
            (_truthy(eval_expr(q.where, c)) for c in ctxs),
            bool, count=len(ctxs),
        )
    prog, exact = res
    feats, valid, suspect = extract_features(ctxs, lanes)
    mask = np.asarray(eval_prog(prog, feats, valid, np)).copy()
    # suspect rows (string/bool-typed numeric lanes) and hashed-lane
    # programs make the vector mask a SUPERSET filter — re-verify only
    # the rows it passes (the rare case); well-typed exact rows stay
    # pure-vector
    mask |= suspect
    verify = mask & (suspect if exact else np.ones_like(mask))
    for i in np.nonzero(verify)[0]:
        mask[i] = _truthy(eval_expr(q.where, ctxs[i]))
    return mask


def apply_query(q: Query, ctx: Dict) -> Optional[List[Dict]]:
    """Run the query against one event context.

    Returns None if the event doesn't pass WHERE (rule no-match), else the
    list of output rows (1 row for SELECT; N for FOREACH).
    """
    if q.where is not None and not _truthy(eval_expr(q.where, ctx)):
        return None
    if q.foreach is None:
        return [_project(q.selects, ctx)]
    arr = eval_expr(q.foreach, ctx)
    if not isinstance(arr, (list, tuple)):
        return []
    rows = []
    alias = q.foreach_alias or "item"
    for elem in arr:
        row_ctx = dict(ctx)
        row_ctx[alias] = elem
        if q.foreach_alias is None:
            row_ctx["item"] = elem
        if q.incase is not None and not _truthy(eval_expr(q.incase, row_ctx)):
            continue
        if q.selects is None:
            rows.append(elem if isinstance(elem, dict) else {alias: elem})
        else:
            rows.append(_project(q.selects, row_ctx))
    return rows
