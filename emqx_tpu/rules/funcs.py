"""Built-in SQL functions for the rule engine.

Reference analog: emqx_rule_funcs.erl (~200 functions). This library covers
the families its test suite exercises: arithmetic, comparison helpers,
strings, maps/arrays, type conversion, JSON, hashing/encoding, time,
and id generation. Functions are total: bad input returns None (the
reference raises and fails the rule; we fail the row the same way by
letting real errors propagate only for arity mistakes).
"""

from __future__ import annotations

import base64
import hashlib
import json
import math
import re
import time
import uuid
from typing import Any, Callable, Dict, List, Optional

FUNCS: Dict[str, Callable] = {}


def func(*names):
    def deco(f):
        for n in names:
            FUNCS[n] = f
        return f

    return deco


def _num(x) -> Optional[float]:
    if isinstance(x, bool):
        return None
    if isinstance(x, (int, float)):
        return x
    try:
        f = float(x)
        return int(f) if f.is_integer() else f
    except (TypeError, ValueError):
        return None


def _s(x) -> str:
    if isinstance(x, bytes):
        return x.decode("utf-8", "replace")
    if isinstance(x, bool):
        return "true" if x else "false"
    if x is None:
        return ""
    if isinstance(x, float) and x.is_integer():
        return str(int(x))
    return str(x)


# -- arithmetic / math -------------------------------------------------------

@func("abs")
def _abs(x):
    n = _num(x)
    return None if n is None else abs(n)


@func("ceil")
def _ceil(x):
    n = _num(x)
    return None if n is None else math.ceil(n)


@func("floor")
def _floor(x):
    n = _num(x)
    return None if n is None else math.floor(n)


@func("round")
def _round(x):
    n = _num(x)
    return None if n is None else round(n)


@func("sqrt")
def _sqrt(x):
    n = _num(x)
    return None if n is None or n < 0 else math.sqrt(n)


@func("power", "pow")
def _pow(x, y):
    a, b = _num(x), _num(y)
    return None if a is None or b is None else a**b

@func("exp")
def _exp(x):
    n = _num(x)
    return None if n is None else math.exp(n)


@func("log")
def _log(x):
    n = _num(x)
    return None if n is None or n <= 0 else math.log(n)


@func("random")
def _random():
    import random

    return random.random()


@func("range")
def _range(a, b):
    x, y = _num(a), _num(b)
    if x is None or y is None:
        return None
    return list(range(int(x), int(y) + 1))


# -- strings -----------------------------------------------------------------

@func("lower")
def _lower(s):
    return _s(s).lower()


@func("upper")
def _upper(s):
    return _s(s).upper()


@func("trim")
def _trim(s):
    return _s(s).strip()


@func("ltrim")
def _ltrim(s):
    return _s(s).lstrip()


@func("rtrim")
def _rtrim(s):
    return _s(s).rstrip()


@func("reverse")
def _reverse(s):
    if isinstance(s, list):
        return s[::-1]
    return _s(s)[::-1]


@func("strlen")
def _strlen(s):
    return len(_s(s))


@func("substr")
def _substr(s, start, length=None):
    st = int(_num(start) or 0)
    text = _s(s)
    return text[st:] if length is None else text[st : st + int(_num(length) or 0)]


@func("split")
def _split(s, sep=" "):
    return [p for p in _s(s).split(_s(sep)) if p != ""]


@func("concat")
def _concat(*parts):
    if parts and all(isinstance(p, list) for p in parts):
        out: List = []
        for p in parts:
            out.extend(p)
        return out
    return "".join(_s(p) for p in parts)


@func("pad")
def _pad(s, width, side="trailing", char=" "):
    text, w, c = _s(s), int(_num(width) or 0), _s(char) or " "
    if side == "leading":
        return text.rjust(w, c[0])
    if side == "both":
        return text.center(w, c[0])
    return text.ljust(w, c[0])


@func("replace")
def _replace(s, old, new):
    return _s(s).replace(_s(old), _s(new))


@func("regex_match")
def _regex_match(s, pattern):
    try:
        return re.search(_s(pattern), _s(s)) is not None
    except re.error:
        return None


@func("regex_replace")
def _regex_replace(s, pattern, repl):
    try:
        return re.sub(_s(pattern), _s(repl), _s(s))
    except re.error:
        return None


@func("ascii")
def _ascii(s):
    text = _s(s)
    return ord(text[0]) if text else None


@func("find")
def _find(s, sub, direction="leading"):
    text, needle = _s(s), _s(sub)
    i = text.find(needle) if direction == "leading" else text.rfind(needle)
    return text[i:] if i >= 0 else ""


@func("tokens")
def _tokens(s, seps):
    parts = re.split("[" + re.escape(_s(seps)) + "]", _s(s))
    return [p for p in parts if p]


@func("sprintf")
def _sprintf(fmt, *args):
    # Erlang io_lib ~s/~p/~w -> python format
    out, i = [], 0
    fmt = _s(fmt)
    j = 0
    while j < len(fmt):
        if fmt[j] == "~" and j + 1 < len(fmt):
            c = fmt[j + 1]
            if c in "spw":
                out.append(_s(args[i]) if i < len(args) else "")
                i += 1
                j += 2
                continue
            if c == "n":
                out.append("\n")
                j += 2
                continue
        out.append(fmt[j])
        j += 1
    return "".join(out)


# -- maps / arrays -----------------------------------------------------------

@func("map_get", "mget")
def _map_get(key, m, default=None):
    if isinstance(m, dict):
        return m.get(_s(key), default)
    return default


@func("map_put", "mput")
def _map_put(key, value, m):
    if not isinstance(m, dict):
        m = {}
    out = dict(m)
    out[_s(key)] = value
    return out


@func("map_keys")
def _map_keys(m):
    return list(m.keys()) if isinstance(m, dict) else None


@func("map_values")
def _map_values(m):
    return list(m.values()) if isinstance(m, dict) else None


@func("nth")
def _nth(i, arr):
    n = _num(i)
    if n is None or not isinstance(arr, (list, tuple)):
        return None
    idx = int(n) - 1  # 1-based (reference Erlang lists:nth)
    return arr[idx] if 0 <= idx < len(arr) else None


@func("length")
def _length(x):
    if isinstance(x, (list, tuple, dict)):
        return len(x)
    return len(_s(x))


@func("sublist")
def _sublist(a, b, c=None):
    """sublist(Len, Array) or sublist(Start, Len, Array), 1-based
    (reference lists:sublist argument order)."""
    if c is None:
        length, arr = a, b
        if not isinstance(arr, (list, tuple)):
            return None
        return list(arr[: int(_num(length) or 0)])
    start, length, arr = a, b, c
    if not isinstance(arr, (list, tuple)):
        return None
    st = int(_num(start) or 1) - 1
    return list(arr[st : st + int(_num(length) or 0)])


@func("first")
def _first(arr):
    return arr[0] if isinstance(arr, (list, tuple)) and arr else None


@func("last")
def _last(arr):
    return arr[-1] if isinstance(arr, (list, tuple)) and arr else None


@func("contains")
def _contains(item, arr):
    return item in arr if isinstance(arr, (list, tuple)) else None


@func("zip")
def _zip(a, b):
    if isinstance(a, list) and isinstance(b, list):
        return [list(p) for p in zip(a, b)]
    return None


# -- type conversion / checks ------------------------------------------------

@func("str", "str_utf8")
def _str(x):
    if isinstance(x, (dict, list)):
        return json.dumps(x)
    return _s(x)


@func("int")
def _int(x):
    n = _num(x)
    return None if n is None else int(n)


@func("float")
def _float(x):
    n = _num(x)
    return None if n is None else float(n)


@func("bool")
def _bool(x):
    if isinstance(x, bool):
        return x
    if x in (0, 1):
        return bool(x)
    if _s(x).lower() in ("true", "false"):
        return _s(x).lower() == "true"
    return None


@func("is_null")
def _is_null(x):
    return x is None


@func("is_not_null")
def _is_not_null(x):
    return x is not None


@func("is_num")
def _is_num(x):
    return isinstance(x, (int, float)) and not isinstance(x, bool)


@func("is_int")
def _is_int(x):
    return isinstance(x, int) and not isinstance(x, bool)


@func("is_float")
def _is_float(x):
    return isinstance(x, float)


@func("is_str")
def _is_str(x):
    return isinstance(x, str)


@func("is_bool")
def _is_bool(x):
    return isinstance(x, bool)


@func("is_map")
def _is_map(x):
    return isinstance(x, dict)


@func("is_array")
def _is_array(x):
    return isinstance(x, list)


@func("coalesce")
def _coalesce(*args):
    for a in args:
        if a is not None:
            return a
    return None


@func("iif")
def _iif(cond, then, otherwise):
    return then if cond in (True, 1, "true") else otherwise


# -- JSON --------------------------------------------------------------------

@func("json_encode")
def _json_encode(x):
    try:
        return json.dumps(x)
    except (TypeError, ValueError):
        return None


@func("json_decode")
def _json_decode(x):
    try:
        return json.loads(_s(x))
    except (TypeError, ValueError):
        return None


# -- hashing / encoding ------------------------------------------------------

def _bytes(x) -> bytes:
    return x if isinstance(x, bytes) else _s(x).encode()


@func("md5")
def _md5(x):
    return hashlib.md5(_bytes(x)).hexdigest()


@func("sha")
def _sha(x):
    return hashlib.sha1(_bytes(x)).hexdigest()


@func("sha256")
def _sha256(x):
    return hashlib.sha256(_bytes(x)).hexdigest()


@func("crc32")
def _crc32(x):
    import zlib

    return zlib.crc32(_bytes(x))


@func("base64_encode")
def _b64e(x):
    return base64.b64encode(_bytes(x)).decode()


@func("base64_decode")
def _b64d(x):
    try:
        return base64.b64decode(_s(x)).decode("utf-8", "replace")
    except (ValueError, TypeError):
        return None


@func("hexstr")
def _hexstr(x):
    return _bytes(x).hex()


@func("bitand")
def _bitand(a, b):
    return int(_num(a) or 0) & int(_num(b) or 0)


@func("bitor")
def _bitor(a, b):
    return int(_num(a) or 0) | int(_num(b) or 0)


@func("bitxor")
def _bitxor(a, b):
    return int(_num(a) or 0) ^ int(_num(b) or 0)


@func("bitnot")
def _bitnot(a):
    return ~int(_num(a) or 0)


@func("bitsl")
def _bitsl(a, n):
    return int(_num(a) or 0) << int(_num(n) or 0)


@func("bitsr")
def _bitsr(a, n):
    return int(_num(a) or 0) >> int(_num(n) or 0)


# -- time / ids --------------------------------------------------------------

@func("now_timestamp")
def _now_timestamp(unit="second"):
    t = time.time()
    if unit == "millisecond":
        return int(t * 1000)
    if unit == "microsecond":
        return int(t * 1e6)
    return int(t)


@func("unix_ts_to_rfc3339")
def _ts_to_rfc3339(ts, unit="second"):
    import datetime

    n = _num(ts)
    if n is None:
        return None
    if unit == "millisecond":
        n = n / 1000.0
    return (
        datetime.datetime.fromtimestamp(n, datetime.timezone.utc)
        .isoformat()
        .replace("+00:00", "Z")
    )


@func("rfc3339_to_unix_ts")
def _rfc3339_to_ts(s):
    import datetime

    try:
        return int(
            datetime.datetime.fromisoformat(
                _s(s).replace("Z", "+00:00")
            ).timestamp()
        )
    except ValueError:
        return None


@func("uuid_v4", "uuid")
def _uuid():
    return str(uuid.uuid4())


@func("timezone_to_second")
def _tz_to_s(tz):
    s = _s(tz)
    if s in ("Z", "z", "+00:00"):
        return 0
    m = re.match(r"([+-])(\d\d):?(\d\d)", s)
    if not m:
        return None
    sign = 1 if m.group(1) == "+" else -1
    return sign * (int(m.group(2)) * 3600 + int(m.group(3)) * 60)


# -- trig / extra math (emqx_rule_funcs.erl math family) ---------------------

for _name in (
    "sin", "cos", "tan", "asin", "acos", "atan",
    "sinh", "cosh", "tanh", "asinh", "acosh", "atanh",
    "log2", "log10",
):
    def _mk(fname):
        mf = getattr(math, fname)

        def _f(x, _mf=mf):
            v = _num(x)
            try:
                return _mf(v) if v is not None else None
            except ValueError:
                return None

        return _f

    FUNCS[_name] = _mk(_name)
del _name, _mk


@func("mod")
def _mod(x, y):
    a, b = _num(x), _num(y)
    if a is None or b is None or int(b) == 0:
        return None
    return int(a) % int(b)


@func("fmod")
def _fmod(x, y):
    a, b = _num(x), _num(y)
    if a is None or b in (None, 0):
        return None
    return math.fmod(a, b)


@func("eq")
def _eq_fn(a, b):
    # same semantics as the SQL '=' operator (runtime._eq): bools only
    # equal themselves, numbers/strings compare through coercion
    if isinstance(a, bool) or isinstance(b, bool):
        return a is b
    na, nb = _num(a), _num(b)
    if na is not None and nb is not None:
        return na == nb
    return a == b


# -- binaries / encoding -----------------------------------------------------


@func("bin2hexstr")
def _bin2hexstr(b):
    if isinstance(b, str):
        b = b.encode()
    return b.hex() if isinstance(b, bytes) else None


@func("hexstr2bin")
def _hexstr2bin(s):
    try:
        return bytes.fromhex(_s(s))
    except ValueError:
        return None


@func("hash")
def _hash(alg, data):
    alg = _s(alg).lower()
    if isinstance(data, str):
        data = data.encode()
    if not isinstance(data, bytes):
        data = _s(data).encode()
    try:
        return hashlib.new(alg, data).hexdigest()
    except ValueError:
        return None


@func("bitsize")
def _bitsize(b):
    if isinstance(b, str):
        b = b.encode()
    return len(b) * 8 if isinstance(b, bytes) else None


@func("subbits", "get_subbits")
def _subbits(b, *args):
    """subbits(bytes, len) / subbits(bytes, start, len): big-endian
    unsigned integer slice (emqx_rule_funcs subbits default mode)."""
    if isinstance(b, str):
        b = b.encode()
    if not isinstance(b, bytes):
        return None
    nums = [_num(a) for a in args]
    if any(v is None for v in nums) or not nums:
        return None
    if len(nums) == 1:
        start, ln = 1, int(nums[0])
    else:
        start, ln = int(nums[0]), int(nums[1])
    bits = int.from_bytes(b, "big")
    total = len(b) * 8
    lo = total - (start - 1) - ln
    if lo < 0 or ln <= 0:
        return None
    return (bits >> lo) & ((1 << ln) - 1)


# -- topic helpers -----------------------------------------------------------


@func("contains_topic")
def _contains_topic(topics, topic):
    if not isinstance(topics, list):
        return False
    return any(_s(t) == _s(topic) for t in topics)


@func("contains_topic_match")
def _contains_topic_match(filters, topic):
    from emqx_tpu.ops import topics as _T

    if not isinstance(filters, list):
        return False
    return any(_T.match(_s(topic), _s(f)) for f in filters)


@func("find_topic_filter")
def _find_topic_filter(filters, topic):
    from emqx_tpu.ops import topics as _T

    if not isinstance(filters, list):
        return None
    for f in filters:
        if _T.match(_s(topic), _s(f)):
            return f
    return None


# -- strings / maps extras ---------------------------------------------------


@func("find_s")
def _find_s(s, sub):
    """Suffix of `s` from the first occurrence of `sub` ('' if absent)."""
    s, sub = _s(s), _s(sub)
    i = s.find(sub)
    return "" if i < 0 else s[i:]


@func("sprintf_s")
def _sprintf_s(fmt, *args):
    """Erlang io_lib-style ~s/~p/~w formatting subset."""
    out = []
    it = iter(args)
    i = 0
    fmt = _s(fmt)
    while i < len(fmt):
        c = fmt[i]
        if c == "~" and i + 1 < len(fmt):
            d = fmt[i + 1]
            if d in ("s", "p", "w"):
                try:
                    v = next(it)
                except StopIteration:
                    return None
                out.append(_s(v) if d == "s" else json.dumps(v, default=str))
                i += 2
                continue
            if d == "n":
                out.append("\n")
                i += 2
                continue
        out.append(c)
        i += 1
    return "".join(out)


@func("map_new")
def _map_new():
    return {}


@func("map_path", "mget_path")
def _map_path(path, m):
    """Dotted-path get (map_path("a.b.c", m))."""
    cur = m
    for seg in _s(path).split("."):
        if isinstance(cur, (str, bytes)):
            try:
                cur = json.loads(cur)
            except (ValueError, TypeError):
                return None
        if not isinstance(cur, dict) or seg not in cur:
            return None
        cur = cur[seg]
    return cur


@func("null")
def _null():
    return None


@func("now_rfc3339")
def _now_rfc3339(unit="second"):
    t = time.time()
    base = time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(t))
    u = _s(unit)
    if u == "millisecond":
        return f"{base}.{int(t * 1e3) % 1000:03d}Z"
    if u == "microsecond":
        return f"{base}.{int(t * 1e6) % 1000000:06d}Z"
    return base + "Z"


# -- rule-engine KV store / proc dict (emqx_rule_funcs kv_store_*,
#    proc_dict_* — cross-rule persistent scratch state) ----------------------

# NOTE scope divergence vs the reference: emqx scopes proc_dict_* to the
# rule's process while kv_store_* is node-global; this runtime evaluates
# all rules on one loop, so both are node-global (separate namespaces).
_KV_STORE: Dict[str, Any] = {}
_PROC_DICT: Dict[str, Any] = {}


def _store_put(store, k, v):
    store[_s(k)] = v
    return v


@func("kv_store_put")
def _kv_put(k, v):
    return _store_put(_KV_STORE, k, v)


@func("kv_store_get")
def _kv_get(k, default=None):
    return _KV_STORE.get(_s(k), default)


@func("kv_store_del")
def _kv_del(k):
    _KV_STORE.pop(_s(k), None)
    return None


@func("proc_dict_put")
def _pd_put(k, v):
    return _store_put(_PROC_DICT, k, v)


@func("proc_dict_get")
def _pd_get(k):
    return _PROC_DICT.get(_s(k))


@func("proc_dict_del")
def _pd_del(k):
    _PROC_DICT.pop(_s(k), None)
    return None


# -- message-context accessors (zero-arg funcs reading the rule ctx;
#    emqx_rule_funcs clientid/0, topic/0, payload/0 etc.) --------------------
# The runtime special-cases these: they receive the evaluation context.

CONTEXT_FUNCS: Dict[str, Callable[[Dict], Any]] = {
    "clientid": lambda ctx: ctx.get("clientid"),
    "username": lambda ctx: ctx.get("username"),
    "topic": lambda ctx: ctx.get("topic"),
    "payload": lambda ctx: ctx.get("payload"),
    "qos": lambda ctx: ctx.get("qos"),
    "msgid": lambda ctx: ctx.get("id"),
    "peerhost": lambda ctx: ctx.get("peerhost"),
    "clientip": lambda ctx: ctx.get("peerhost"),
    "flags": lambda ctx: ctx.get("flags") or {},
    "pub_props": lambda ctx: ctx.get("pub_props") or {},
}


def context_flag(ctx: Dict, name) -> Any:
    return (ctx.get("flags") or {}).get(_s(name))


# -- named operator forms + term codec + map conversion ----------------------
# (parity with emqx_rule_funcs.erl exports '+'/2 '-'/2 '*'/2 '/'/2 'div'/2,
# map/1, term_encode/1, term_decode/1. The SQL grammar reaches the
# arithmetic ones as infix operators; the named forms exist so the
# function surface matches the reference export list 1:1.)


@func("+")
def _op_add(x, y):
    # numeric add; if either side is a string, implicit-concat like the
    # reference ('+'(X, Y) when is_binary -> concat)
    if isinstance(x, (bytes, str)) or isinstance(y, (bytes, str)):
        return _concat(x, y)
    a, b = _num(x), _num(y)
    return None if a is None or b is None else a + b


@func("-")
def _op_sub(x, y):
    a, b = _num(x), _num(y)
    return None if a is None or b is None else a - b


@func("*")
def _op_mul(x, y):
    a, b = _num(x), _num(y)
    return None if a is None or b is None else a * b


@func("/")
def _op_div(x, y):
    a, b = _num(x), _num(y)
    if a is None or b is None or b == 0:
        return None
    return a / b


@func("div")
def _op_intdiv(x, y):
    a, b = _num(x), _num(y)
    if a is None or b is None or int(b) == 0:
        return None
    q = abs(int(a)) // abs(int(b))  # erlang div truncates toward zero
    return q if (int(a) < 0) == (int(b) < 0) else -q


@func("map")
def _to_map(x):
    """Coerce to a map (emqx_plugin_libs_rule:map/1): maps pass through,
    JSON strings decode, key-value pair lists fold."""
    if isinstance(x, dict):
        return x
    if isinstance(x, (bytes, str)):
        try:
            v = json.loads(_s(x))
            return v if isinstance(v, dict) else None
        except (ValueError, TypeError):
            return None
    if isinstance(x, list):
        try:
            return {str(k): v for k, v in x}
        except (ValueError, TypeError):
            return None
    return None


def _term_tag(x):
    if isinstance(x, bytes):
        return {"t": "b", "v": base64.b64encode(x).decode()}
    if isinstance(x, list):
        return {"t": "l", "v": [_term_tag(i) for i in x]}
    if isinstance(x, dict):
        return {"t": "m", "v": {str(k): _term_tag(v) for k, v in x.items()}}
    return {"t": "v", "v": x}


def _term_untag(d):
    t = d.get("t")
    if t == "b":
        return base64.b64decode(d["v"])
    if t == "l":
        return [_term_untag(i) for i in d["v"]]
    if t == "m":
        return {k: _term_untag(v) for k, v in d["v"].items()}
    return d.get("v")


@func("term_encode")
def _term_encode(x):
    """Self-describing binary term encoding (reference: term_to_binary —
    a BEAM-native format; here a tagged-JSON framework-native one, so
    encode/decode round-trips bytes/lists/maps losslessly)."""
    try:
        return b"\x01ET" + json.dumps(_term_tag(x)).encode()
    except (TypeError, ValueError):
        return None


@func("term_decode")
def _term_decode(x):
    if isinstance(x, str):
        x = x.encode("utf-8", "surrogatepass")
    if not isinstance(x, bytes) or not x.startswith(b"\x01ET"):
        return None
    try:
        return _term_untag(json.loads(x[3:].decode()))
    except (ValueError, TypeError):
        return None
