"""Hookpoint → rule-event bridging.

Reference analog: emqx_rule_events.erl:76-116 — each broker hookpoint maps
to an event topic; a rule's FROM clause decides which events feed it:
- a plain topic filter (`FROM "t/#"`) selects 'message.publish' events
  whose MESSAGE TOPIC matches the filter;
- `FROM "$events/<name>"` selects that lifecycle event.

Event context fields follow the reference's event schemas (clientid,
username, topic, qos, payload, timestamp, event, ...).
"""

from __future__ import annotations

import time
from typing import Dict

from emqx_tpu.broker.message import Message

# $events/<name> supported (emqx_rule_events event list)
EVENT_TOPICS = (
    "$events/message_delivered",
    "$events/message_acked",
    "$events/message_dropped",
    "$events/client_connected",
    "$events/client_disconnected",
    "$events/session_subscribed",
    "$events/session_unsubscribed",
)


def _base(event: str) -> Dict:
    return {
        "event": event,
        "timestamp": int(time.time() * 1000),
        "node": _node(),
    }


def _node() -> str:
    from emqx_tpu.utils.node import node_name

    return node_name()


def _msg_fields(msg: Message) -> Dict:
    out = {
        # provenance for the engine's self-loop guard (hidden from SELECT *)
        "__from_rule": msg.headers.get("from_rule"),
    }
    out.update(_msg_public_fields(msg))
    return out


def _msg_public_fields(msg: Message) -> Dict:
    return {
        "id": str(msg.mid),
        "clientid": msg.from_client,
        "username": msg.from_username,
        "topic": msg.topic,
        "qos": msg.qos,
        "flags": {"retain": msg.retain, "dup": msg.dup},
        "payload": msg.payload,
        "publish_received_at": int(msg.timestamp * 1000),
        "pub_props": dict(msg.properties),
    }


def message_publish(msg: Message) -> Dict:
    ctx = _base("message.publish")
    ctx.update(_msg_fields(msg))
    return ctx


def message_delivered(client_info: Dict, msg: Message) -> Dict:
    ctx = _base("message.delivered")
    ctx.update(_msg_fields(msg))
    ctx["from_clientid"] = msg.from_client
    ctx["from_username"] = msg.from_username
    ctx["clientid"] = client_info.get("client_id")
    ctx["username"] = client_info.get("username")
    return ctx


def message_acked(client_info: Dict, msg_or_pid) -> Dict:
    ctx = _base("message.acked")
    if isinstance(msg_or_pid, Message):
        ctx.update(_msg_fields(msg_or_pid))
    else:
        ctx["packet_id"] = msg_or_pid
    ctx["clientid"] = client_info.get("client_id")
    ctx["username"] = client_info.get("username")
    return ctx


def message_dropped(msg: Message, reason: str) -> Dict:
    ctx = _base("message.dropped")
    ctx.update(_msg_fields(msg))
    ctx["reason"] = reason
    return ctx


def client_connected(client_info: Dict) -> Dict:
    ctx = _base("client.connected")
    ctx.update(
        {
            "clientid": client_info.get("client_id"),
            "username": client_info.get("username"),
            "keepalive": client_info.get("keepalive"),
            "clean_start": client_info.get("clean_start"),
            "proto_ver": client_info.get("proto_ver"),
            "peerhost": str(client_info.get("peerhost", "")),
            "connected_at": int(time.time() * 1000),
        }
    )
    return ctx


def client_disconnected(client_info: Dict, reason: str) -> Dict:
    ctx = _base("client.disconnected")
    ctx.update(
        {
            "clientid": client_info.get("client_id"),
            "username": client_info.get("username"),
            "reason": reason,
            "disconnected_at": int(time.time() * 1000),
        }
    )
    return ctx


def session_subscribed(client_info: Dict, filter_: str, opts) -> Dict:
    ctx = _base("session.subscribed")
    ctx.update(
        {
            "clientid": client_info.get("client_id"),
            "username": client_info.get("username"),
            "topic": filter_,
            "qos": getattr(opts, "qos", 0),
        }
    )
    return ctx


def session_unsubscribed(client_info: Dict, filter_: str) -> Dict:
    ctx = _base("session.unsubscribed")
    ctx.update(
        {
            "clientid": client_info.get("client_id"),
            "username": client_info.get("username"),
            "topic": filter_,
        }
    )
    return ctx


# event name as it appears in FROM "$events/..." -> context 'event' field
def event_topic_to_name(topic: str) -> str:
    return topic[len("$events/") :].replace("_", ".", 1)
