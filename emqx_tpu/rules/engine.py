"""Rule registry + runtime wiring + outputs.

Reference analog: emqx_rule_engine.erl (registry/metrics),
emqx_rule_outputs.erl (republish/console/custom function),
emqx_plugin_libs' emqx_placeholder (${var} templating),
emqx_rule_sqltester (test_sql).
"""

from __future__ import annotations

import json
import logging
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from emqx_tpu.broker.hooks import Hooks
from emqx_tpu.broker.message import Message
from emqx_tpu.ops import topics as T
from emqx_tpu.rules import events as EV
from emqx_tpu.rules.runtime import apply_query, eval_expr
from emqx_tpu.rules.sql import Query, parse_sql

log = logging.getLogger("emqx_tpu.rules")

# shared ${a.b} placeholder substitution (emqx_placeholder parity) — one
# implementation for rules, bridges, authz (emqx_tpu/utils/placeholder.py)
from emqx_tpu.utils.placeholder import render as render_template  # noqa: E402


# -- outputs -----------------------------------------------------------------

class Output:
    name = "output"

    def run(self, engine: "RuleEngine", rule: "Rule", row: Dict, ctx: Dict):
        raise NotImplementedError


class Republish(Output):
    """Publish the rule result back into the broker
    (emqx_rule_outputs republish)."""

    name = "republish"

    def __init__(
        self,
        topic: str,
        payload: str = "${payload}",
        qos: int = 0,
        retain: bool = False,
    ):
        self.topic = topic
        self.payload = payload
        self.qos = qos
        self.retain = retain

    def run(self, engine, rule, row, ctx):
        env = dict(ctx)
        env.update(row)
        topic = render_template(self.topic, env)
        if self.payload == "${payload}" and "payload" not in env:
            payload = json.dumps(row).encode()
        else:
            payload = render_template(self.payload, env).encode()
        msg = Message(
            topic=topic,
            payload=payload,
            qos=self.qos,
            retain=self.retain,
            from_client=ctx.get("clientid") or "rule_engine",
        )
        # guard against a rule republishing into its own FROM clause forever
        msg.headers["from_rule"] = rule.id
        engine.broker.publish(msg)


class Console(Output):
    """Log the result (emqx_rule_outputs console)."""

    name = "console"

    def run(self, engine, rule, row, ctx):
        log.info("rule %s output: %s", rule.id, row)
        engine.console_log.append((rule.id, row))


class FunctionOutput(Output):
    """Custom callable — the seam data bridges plug into
    (reference: bridge outputs resolve to connector sends)."""

    name = "function"

    def __init__(self, fn: Callable[[Dict, Dict], None], name: str = "function"):
        self.fn = fn
        self.name = name

    def run(self, engine, rule, row, ctx):
        self.fn(row, ctx)


@dataclass
class RuleMetrics:
    matched: int = 0
    passed: int = 0
    failed: int = 0
    no_result: int = 0
    outputs_success: int = 0
    outputs_failed: int = 0

    def as_dict(self) -> Dict[str, int]:
        return dict(self.__dict__)


@dataclass
class Rule:
    id: str
    sql: str
    outputs: List[Output]
    description: str = ""
    enabled: bool = True
    query: Query = None  # type: ignore[assignment]
    metrics: RuleMetrics = field(default_factory=RuleMetrics)

    def __post_init__(self):
        if self.query is None:
            self.query = parse_sql(self.sql)


class RuleEngine:
    MAX_CHAIN_DEPTH = 5  # republish -> event -> republish chains

    def __init__(self, broker) -> None:
        self.broker = broker
        self._rules: Dict[str, Rule] = {}
        self._lock = threading.Lock()
        self.console_log: List = []
        self._depth = threading.local()
        # DeviceRuleFilter (rules/compile.py): compiled WHERE programs
        # evaluated inside serving launches (docs/semantic_routing.md).
        # None = every rule stays on the per-message hook path.
        self.device_filter = None

    # -- device-predicate plane (rules/compile.py) -------------------------
    def attach_device(self) -> None:
        """Enable device-compiled WHERE filtering: the broker batch
        paths defer compiled rules to settle time, where they fire from
        the in-launch masks (or the vectorized host ladder)."""
        from emqx_tpu.rules.compile import DeviceRuleFilter

        self.device_filter = DeviceRuleFilter()
        self.device_filter.refresh(self.rules())
        self.broker.rule_hook = self

    def refresh_device(self) -> None:
        """Recompile the device rule set (rule create/delete/enable).
        The progs tuple is the serving jit's static key, so this is
        also exactly when the launch program retraces."""
        if self.device_filter is not None:
            self.device_filter.refresh(self.rules())

    def device_active(self) -> bool:
        df = self.device_filter
        return df is not None and df.active

    def device_progs(self, msgs):
        """(progs, feats [B,F], valid) for a batch about to launch, or
        None — called by the broker right before the device dispatch."""
        df = self.device_filter
        if df is None or not df.active:
            return None
        feats, valid = df.features(msgs)
        return df.progs, feats, valid

    def fire_settled(self, msgs, masks=None) -> None:
        """Fire deferred (device-compiled) rules for the marked
        messages of a settled batch. `masks` [R, B] comes from the
        launch readback; None (or a rule-set shape mismatch — the set
        churned while the batch was in flight) drops to the vectorized
        numpy twin. Passing rows re-run `apply_query` — the scalar host
        stays the single authority for SELECT projection AND the final
        WHERE word (hashed string lanes make the device mask a
        superset filter; see rules/compile.py)."""
        df = self.device_filter
        marked = [
            i for i, m in enumerate(msgs)
            if m.headers.pop("_batch_rules", None) is not None
        ]
        if not marked or df is None or not df.compiled:
            for m in msgs:
                m.headers.pop("_rule_suspect", None)
            return
        mtr = self.broker.metrics
        if masks is None or len(masks) != len(df.compiled):
            masks = df.host_masks(msgs)
            mtr.inc("rules.host.batches")
        else:
            mtr.inc("rules.device.batches")
        if self._chain_depth() >= self.MAX_CHAIN_DEPTH:
            return
        self._depth.value = self._chain_depth() + 1
        try:
            memo: Dict = {}
            for r, cr in enumerate(df.compiled):
                rule = cr.rule
                if not rule.enabled or self._rules.get(rule.id) is not rule:
                    continue
                row = masks[r]
                for i in marked:
                    msg = msgs[i]
                    if msg.headers.get("from_rule") == rule.id:
                        continue
                    key = (rule.id, msg.topic)
                    sel = memo.get(key)
                    if sel is None:
                        sel = any(
                            T.match(msg.topic, t)
                            for t in rule.query.topics
                        )
                        memo[key] = sel
                    if not sel:
                        continue
                    rule.metrics.matched += 1
                    mtr.inc("rules.matched")
                    if not row[i] and not msg.headers.get(
                        "_rule_suspect"
                    ):
                        # the device-rate drop: WHERE said no, the host
                        # never builds a context for this row (suspect
                        # rows — string/bool-typed numeric lanes — fall
                        # through to the scalar re-verify below)
                        rule.metrics.no_result += 1
                        mtr.inc("rules.dropped")
                        continue
                    ctx = EV.message_publish(msg)
                    try:
                        rows = apply_query(rule.query, ctx)
                    except Exception:
                        rule.metrics.failed += 1
                        mtr.inc("rules.failed")
                        log.exception("rule %s SQL failed", rule.id)
                        continue
                    if not rows:
                        rule.metrics.no_result += 1
                        mtr.inc("rules.dropped")
                        continue
                    rule.metrics.passed += 1
                    mtr.inc("rules.passed")
                    for row_out in rows:
                        for out in rule.outputs:
                            try:
                                out.run(self, rule, row_out, ctx)
                                rule.metrics.outputs_success += 1
                            except Exception:
                                rule.metrics.outputs_failed += 1
                                log.exception(
                                    "rule %s output %s failed",
                                    rule.id, out.name,
                                )
        finally:
            self._depth.value = self._chain_depth() - 1
            for m in msgs:
                m.headers.pop("_rule_suspect", None)

    # -- registry ----------------------------------------------------------
    def create_rule(
        self,
        rule_id: str,
        sql: str,
        outputs: List[Output],
        description: str = "",
        replace: bool = False,
    ) -> Rule:
        rule = Rule(id=rule_id, sql=sql, outputs=outputs, description=description)
        with self._lock:
            if not replace and rule_id in self._rules:
                raise ValueError(f"rule {rule_id!r} already exists")
            self._rules[rule_id] = rule
        self.refresh_device()
        return rule

    def delete_rule(self, rule_id: str) -> bool:
        with self._lock:
            existed = self._rules.pop(rule_id, None) is not None
        if existed:
            self.refresh_device()
        return existed

    def get_rule(self, rule_id: str) -> Optional[Rule]:
        return self._rules.get(rule_id)

    def rules(self) -> List[Rule]:
        return list(self._rules.values())

    # -- hook wiring (emqx_rule_events parity) ----------------------------
    def _any_enabled(self) -> bool:
        """Fast gate for the per-message event hooks: building an event
        context (dict of ~10 fields) on every delivery/ack is pure
        overhead on a rule-less broker — the dominant per-delivery cost
        in the r4 serving profile. Same live-check semantics as
        _on_publish: no cached flag, so an externally toggled
        `rule.enabled = True` is honored immediately."""
        rules = self._rules
        return bool(rules) and any(r.enabled for r in rules.values())

    def attach(self, hooks: Hooks) -> None:
        hooks.add("message.publish", self._on_publish, priority=120)
        hooks.add(
            "message.delivered",
            lambda ci, msg: self._any_enabled()
            and self._fire(EV.message_delivered(ci, msg)),
        )
        hooks.add(
            "message.acked",
            lambda ci, m: self._any_enabled()
            and self._fire(EV.message_acked(ci, m)),
        )
        hooks.add(
            "message.dropped",
            lambda msg, reason: self._any_enabled()
            and self._fire(EV.message_dropped(msg, reason)),
        )
        hooks.add(
            "client.connected",
            lambda ci, _ch: self._any_enabled()
            and self._fire(EV.client_connected(ci)),
        )
        hooks.add(
            "client.disconnected",
            lambda ci, reason: self._any_enabled()
            and self._fire(EV.client_disconnected(ci, reason)),
        )
        hooks.add(
            "session.subscribed",
            lambda ci, f, opts, _ch=None: self._any_enabled()
            and self._fire(EV.session_subscribed(ci, f, opts)),
        )
        hooks.add(
            "session.unsubscribed",
            lambda ci, f: self._any_enabled()
            and self._fire(EV.session_unsubscribed(ci, f)),
        )

    def _on_publish(self, msg: Optional[Message]):
        """'message.publish' fold callback: fire rules, pass msg through.

        Fast path: with no enabled rules there is nothing to select —
        skip building the event context entirely (this hook runs on
        EVERY publish; the context dict was ~9us/msg of pure overhead
        on rule-less brokers)."""
        if msg is None:
            return None
        # O(1) for the common rule-less broker; with rules registered the
        # any() scan is noise next to _fire's own per-rule work, and a
        # cached flag would silently bypass rules if an external
        # `.enabled = True` forgot to refresh it
        if not self._rules or not any(
            r.enabled for r in self._rules.values()
        ):
            return None
        skip = None
        df = self.device_filter
        if df is not None and msg.headers.get("_batch_rules"):
            # the broker marked this message for settle-time firing:
            # device-compiled rules evaluate in the serving launch, the
            # hook path keeps only the uncompilable remainder
            skip = df._ids
        self._fire(
            EV.message_publish(msg),
            from_rule=msg.headers.get("from_rule"),
            skip_rules=skip,
        )
        return None

    def _chain_depth(self) -> int:
        return getattr(self._depth, "value", 0)

    # -- evaluation --------------------------------------------------------
    def _selects_event(self, q: Query, ctx: Dict) -> bool:
        event = ctx["event"]
        for t in q.topics:
            if t.startswith("$events/"):
                if EV.event_topic_to_name(t) == event:
                    return True
            elif event == "message.publish" and T.match(ctx["topic"], t):
                return True
        return False

    def _fire(self, ctx: Dict, from_rule: Optional[str] = None,
              skip_rules=None) -> None:
        # re-entrancy bound: outputs that publish re-enter _fire
        # synchronously (via broker hooks); cap the chain so a rule feeding
        # its own event class (e.g. $events/message_dropped -> republish to
        # a subscriber-less topic) cannot recurse unboundedly
        if self._chain_depth() >= self.MAX_CHAIN_DEPTH:
            log.warning("rule chain depth limit hit; dropping event %s", ctx.get("event"))
            return
        from_rule = from_rule or ctx.get("__from_rule")
        mtr = self.broker.metrics
        self._depth.value = self._chain_depth() + 1
        try:
            for rule in list(self._rules.values()):
                if not rule.enabled:
                    continue
                if skip_rules is not None and rule.id in skip_rules:
                    continue  # fires at settle from the device mask
                if from_rule is not None and rule.id == from_rule:
                    continue  # self-republish loop guard
                if not self._selects_event(rule.query, ctx):
                    continue
                rule.metrics.matched += 1
                mtr.inc("rules.matched")
                try:
                    rows = apply_query(rule.query, ctx)
                except Exception:
                    rule.metrics.failed += 1
                    mtr.inc("rules.failed")
                    log.exception("rule %s SQL failed", rule.id)
                    continue
                if rows is None or not rows:
                    rule.metrics.no_result += 1
                    mtr.inc("rules.dropped")
                    continue
                rule.metrics.passed += 1
                mtr.inc("rules.passed")
                for row in rows:
                    for out in rule.outputs:
                        try:
                            out.run(self, rule, row, ctx)
                            rule.metrics.outputs_success += 1
                        except Exception:
                            rule.metrics.outputs_failed += 1
                            log.exception(
                                "rule %s output %s failed", rule.id, out.name
                            )
        finally:
            self._depth.value = self._chain_depth() - 1


def test_sql(sql: str, ctx: Dict) -> Optional[List[Dict]]:
    """SQL test bench (emqx_rule_sqltester parity): run a statement against
    a hand-built event context, no broker required."""
    q = parse_sql(sql)
    full = dict(ctx)
    full.setdefault("event", "message.publish")
    return apply_query(q, full)


test_sql.__test__ = False  # not a pytest case despite the reference's name
