"""Rule registry + runtime wiring + outputs.

Reference analog: emqx_rule_engine.erl (registry/metrics),
emqx_rule_outputs.erl (republish/console/custom function),
emqx_plugin_libs' emqx_placeholder (${var} templating),
emqx_rule_sqltester (test_sql).
"""

from __future__ import annotations

import json
import logging
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from emqx_tpu.broker.hooks import Hooks
from emqx_tpu.broker.message import Message
from emqx_tpu.ops import topics as T
from emqx_tpu.rules import events as EV
from emqx_tpu.rules.runtime import apply_query, eval_expr
from emqx_tpu.rules.sql import Query, parse_sql

log = logging.getLogger("emqx_tpu.rules")

# shared ${a.b} placeholder substitution (emqx_placeholder parity) — one
# implementation for rules, bridges, authz (emqx_tpu/utils/placeholder.py)
from emqx_tpu.utils.placeholder import render as render_template  # noqa: E402


# -- outputs -----------------------------------------------------------------

class Output:
    name = "output"

    def run(self, engine: "RuleEngine", rule: "Rule", row: Dict, ctx: Dict):
        raise NotImplementedError


class Republish(Output):
    """Publish the rule result back into the broker
    (emqx_rule_outputs republish)."""

    name = "republish"

    def __init__(
        self,
        topic: str,
        payload: str = "${payload}",
        qos: int = 0,
        retain: bool = False,
    ):
        self.topic = topic
        self.payload = payload
        self.qos = qos
        self.retain = retain

    def run(self, engine, rule, row, ctx):
        env = dict(ctx)
        env.update(row)
        topic = render_template(self.topic, env)
        if self.payload == "${payload}" and "payload" not in env:
            payload = json.dumps(row).encode()
        else:
            payload = render_template(self.payload, env).encode()
        msg = Message(
            topic=topic,
            payload=payload,
            qos=self.qos,
            retain=self.retain,
            from_client=ctx.get("clientid") or "rule_engine",
        )
        # guard against a rule republishing into its own FROM clause forever
        msg.headers["from_rule"] = rule.id
        engine.broker.publish(msg)


class Console(Output):
    """Log the result (emqx_rule_outputs console)."""

    name = "console"

    def run(self, engine, rule, row, ctx):
        log.info("rule %s output: %s", rule.id, row)
        engine.console_log.append((rule.id, row))


class FunctionOutput(Output):
    """Custom callable — the seam data bridges plug into
    (reference: bridge outputs resolve to connector sends)."""

    name = "function"

    def __init__(self, fn: Callable[[Dict, Dict], None], name: str = "function"):
        self.fn = fn
        self.name = name

    def run(self, engine, rule, row, ctx):
        self.fn(row, ctx)


@dataclass
class RuleMetrics:
    matched: int = 0
    passed: int = 0
    failed: int = 0
    no_result: int = 0
    outputs_success: int = 0
    outputs_failed: int = 0

    def as_dict(self) -> Dict[str, int]:
        return dict(self.__dict__)


@dataclass
class Rule:
    id: str
    sql: str
    outputs: List[Output]
    description: str = ""
    enabled: bool = True
    query: Query = None  # type: ignore[assignment]
    metrics: RuleMetrics = field(default_factory=RuleMetrics)

    def __post_init__(self):
        if self.query is None:
            self.query = parse_sql(self.sql)


class RuleEngine:
    MAX_CHAIN_DEPTH = 5  # republish -> event -> republish chains

    def __init__(self, broker) -> None:
        self.broker = broker
        self._rules: Dict[str, Rule] = {}
        self._lock = threading.Lock()
        self.console_log: List = []
        self._depth = threading.local()

    # -- registry ----------------------------------------------------------
    def create_rule(
        self,
        rule_id: str,
        sql: str,
        outputs: List[Output],
        description: str = "",
        replace: bool = False,
    ) -> Rule:
        rule = Rule(id=rule_id, sql=sql, outputs=outputs, description=description)
        with self._lock:
            if not replace and rule_id in self._rules:
                raise ValueError(f"rule {rule_id!r} already exists")
            self._rules[rule_id] = rule
        return rule

    def delete_rule(self, rule_id: str) -> bool:
        with self._lock:
            return self._rules.pop(rule_id, None) is not None

    def get_rule(self, rule_id: str) -> Optional[Rule]:
        return self._rules.get(rule_id)

    def rules(self) -> List[Rule]:
        return list(self._rules.values())

    # -- hook wiring (emqx_rule_events parity) ----------------------------
    def _any_enabled(self) -> bool:
        """Fast gate for the per-message event hooks: building an event
        context (dict of ~10 fields) on every delivery/ack is pure
        overhead on a rule-less broker — the dominant per-delivery cost
        in the r4 serving profile. Same live-check semantics as
        _on_publish: no cached flag, so an externally toggled
        `rule.enabled = True` is honored immediately."""
        rules = self._rules
        return bool(rules) and any(r.enabled for r in rules.values())

    def attach(self, hooks: Hooks) -> None:
        hooks.add("message.publish", self._on_publish, priority=120)
        hooks.add(
            "message.delivered",
            lambda ci, msg: self._any_enabled()
            and self._fire(EV.message_delivered(ci, msg)),
        )
        hooks.add(
            "message.acked",
            lambda ci, m: self._any_enabled()
            and self._fire(EV.message_acked(ci, m)),
        )
        hooks.add(
            "message.dropped",
            lambda msg, reason: self._any_enabled()
            and self._fire(EV.message_dropped(msg, reason)),
        )
        hooks.add(
            "client.connected",
            lambda ci, _ch: self._any_enabled()
            and self._fire(EV.client_connected(ci)),
        )
        hooks.add(
            "client.disconnected",
            lambda ci, reason: self._any_enabled()
            and self._fire(EV.client_disconnected(ci, reason)),
        )
        hooks.add(
            "session.subscribed",
            lambda ci, f, opts, _ch=None: self._any_enabled()
            and self._fire(EV.session_subscribed(ci, f, opts)),
        )
        hooks.add(
            "session.unsubscribed",
            lambda ci, f: self._any_enabled()
            and self._fire(EV.session_unsubscribed(ci, f)),
        )

    def _on_publish(self, msg: Optional[Message]):
        """'message.publish' fold callback: fire rules, pass msg through.

        Fast path: with no enabled rules there is nothing to select —
        skip building the event context entirely (this hook runs on
        EVERY publish; the context dict was ~9us/msg of pure overhead
        on rule-less brokers)."""
        if msg is None:
            return None
        # O(1) for the common rule-less broker; with rules registered the
        # any() scan is noise next to _fire's own per-rule work, and a
        # cached flag would silently bypass rules if an external
        # `.enabled = True` forgot to refresh it
        if not self._rules or not any(
            r.enabled for r in self._rules.values()
        ):
            return None
        self._fire(EV.message_publish(msg), from_rule=msg.headers.get("from_rule"))
        return None

    def _chain_depth(self) -> int:
        return getattr(self._depth, "value", 0)

    # -- evaluation --------------------------------------------------------
    def _selects_event(self, q: Query, ctx: Dict) -> bool:
        event = ctx["event"]
        for t in q.topics:
            if t.startswith("$events/"):
                if EV.event_topic_to_name(t) == event:
                    return True
            elif event == "message.publish" and T.match(ctx["topic"], t):
                return True
        return False

    def _fire(self, ctx: Dict, from_rule: Optional[str] = None) -> None:
        # re-entrancy bound: outputs that publish re-enter _fire
        # synchronously (via broker hooks); cap the chain so a rule feeding
        # its own event class (e.g. $events/message_dropped -> republish to
        # a subscriber-less topic) cannot recurse unboundedly
        if self._chain_depth() >= self.MAX_CHAIN_DEPTH:
            log.warning("rule chain depth limit hit; dropping event %s", ctx.get("event"))
            return
        from_rule = from_rule or ctx.get("__from_rule")
        self._depth.value = self._chain_depth() + 1
        try:
            for rule in list(self._rules.values()):
                if not rule.enabled:
                    continue
                if from_rule is not None and rule.id == from_rule:
                    continue  # self-republish loop guard
                if not self._selects_event(rule.query, ctx):
                    continue
                rule.metrics.matched += 1
                try:
                    rows = apply_query(rule.query, ctx)
                except Exception:
                    rule.metrics.failed += 1
                    log.exception("rule %s SQL failed", rule.id)
                    continue
                if rows is None or not rows:
                    rule.metrics.no_result += 1
                    continue
                rule.metrics.passed += 1
                for row in rows:
                    for out in rule.outputs:
                        try:
                            out.run(self, rule, row, ctx)
                            rule.metrics.outputs_success += 1
                        except Exception:
                            rule.metrics.outputs_failed += 1
                            log.exception(
                                "rule %s output %s failed", rule.id, out.name
                            )
        finally:
            self._depth.value = self._chain_depth() - 1


def test_sql(sql: str, ctx: Dict) -> Optional[List[Dict]]:
    """SQL test bench (emqx_rule_sqltester parity): run a statement against
    a hand-built event context, no broker required."""
    q = parse_sql(sql)
    full = dict(ctx)
    full.setdefault("event", "message.publish")
    return apply_query(q, full)


test_sql.__test__ = False  # not a pytest case despite the reference's name
