"""Rule engine: SQL-over-events stream processing.

Reference analog: apps/emqx_rule_engine — rules are SQL statements over
broker events (`SELECT ... FROM "topic" WHERE ...`), parsed by the rulesql
grammar (emqx_rule_sqlparser.erl:52-55), fed by hookpoint→event bridging
(emqx_rule_events.erl:76-116), evaluated per event
(emqx_rule_runtime.erl), with a built-in SQL function library
(emqx_rule_funcs.erl) and outputs republish/console/bridge
(emqx_rule_outputs.erl). `test_sql` mirrors emqx_rule_sqltester.

This implementation is a fresh recursive-descent parser + evaluator over
plain dicts — events are host-side control flow, deliberately OFF the TPU
path (the TPU plane owns batch route matching; rules run per matched event
on the host exactly as the reference runs them per hook callback).
"""

from emqx_tpu.rules.engine import Rule, RuleEngine, test_sql
from emqx_tpu.rules.sql import SqlParseError, parse_sql

__all__ = ["Rule", "RuleEngine", "test_sql", "parse_sql", "SqlParseError"]
