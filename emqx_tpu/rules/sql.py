"""Rule SQL dialect: lexer + recursive-descent parser.

Grammar (the subset of the reference's rulesql grammar that its docs and
test suites exercise, emqx_rule_sqlparser.erl:52-55):

    query    := SELECT selects FROM topics [WHERE expr]
              | FOREACH expr [AS ident] [DO selects] [INCASE expr]
                FROM topics [WHERE expr]
    selects  := '*' | sel (',' sel)*
    sel      := expr [AS dotted_ident]
    topics   := string (',' string)*
    expr     := disjunction of conjunctions of comparisons over
                + - * / div mod, unary -, function calls, dotted/indexed
                access (payload.a.b, arr[1]), literals, CASE WHEN

Keywords are case-insensitive; identifiers are case-sensitive. String
literals take single or double quotes (the reference uses double quotes
for FROM topics, single for strings).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import List, Optional, Tuple


class SqlParseError(Exception):
    pass


# -- AST ---------------------------------------------------------------------

@dataclass
class Lit:
    value: object


@dataclass
class Var:
    path: List[object]  # mixed str keys / int indices; ["payload","x"]


@dataclass
class Call:
    name: str
    args: List[object]


@dataclass
class BinOp:
    op: str
    left: object
    right: object


@dataclass
class UnOp:
    op: str  # 'not' | 'neg'
    operand: object


@dataclass
class InList:
    needle: object
    items: List[object]
    negated: bool = False


@dataclass
class Case:
    whens: List[Tuple[object, object]]
    default: Optional[object] = None


@dataclass
class SelectItem:
    expr: object
    alias: Optional[List[str]] = None  # dotted alias path


@dataclass
class Query:
    selects: Optional[List[SelectItem]]  # None => SELECT *
    topics: List[str]
    where: Optional[object] = None
    # FOREACH parts
    foreach: Optional[object] = None
    foreach_alias: Optional[str] = None
    incase: Optional[object] = None


# -- lexer -------------------------------------------------------------------

_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+)
  | (?P<num>\d+\.\d+|\d+)
  | (?P<str>'(?:[^'\\]|\\.)*'|"(?:[^"\\]|\\.)*")
  | (?P<op><>|!=|>=|<=|=|>|<|\+|-|\*|/|\(|\)|\[|\]|,|\.)
  | (?P<ident>[A-Za-z_$][A-Za-z0-9_$]*)
    """,
    re.VERBOSE,
)

_KEYWORDS = {
    "select", "from", "where", "as", "and", "or", "not", "in", "div",
    "mod", "foreach", "do", "incase", "case", "when", "then", "else",
    "end", "true", "false", "null", "like",
}


def _lex(text: str) -> List[Tuple[str, object]]:
    out: List[Tuple[str, object]] = []
    pos = 0
    while pos < len(text):
        m = _TOKEN_RE.match(text, pos)
        if m is None:
            raise SqlParseError(f"bad character {text[pos]!r} at {pos}")
        pos = m.end()
        kind = m.lastgroup
        tok = m.group()
        if kind == "ws":
            continue
        if kind == "num":
            out.append(("num", float(tok) if "." in tok else int(tok)))
        elif kind == "str":
            body = tok[1:-1]
            body = re.sub(r"\\(.)", r"\1", body)
            out.append(("str", body))
        elif kind == "ident":
            low = tok.lower()
            if low in _KEYWORDS:
                out.append(("kw", low))
            else:
                out.append(("ident", tok))
        else:
            out.append(("op", tok))
    out.append(("eof", None))
    return out


# -- parser ------------------------------------------------------------------

class _Parser:
    def __init__(self, toks: List[Tuple[str, object]]):
        self.toks = toks
        self.i = 0

    def peek(self) -> Tuple[str, object]:
        return self.toks[self.i]

    def next(self) -> Tuple[str, object]:
        t = self.toks[self.i]
        self.i += 1
        return t

    def expect(self, kind: str, val=None):
        k, v = self.next()
        if k != kind or (val is not None and v != val):
            raise SqlParseError(f"expected {val or kind}, got {v!r}")
        return v

    def accept_kw(self, word: str) -> bool:
        k, v = self.peek()
        if k == "kw" and v == word:
            self.i += 1
            return True
        return False

    # query := SELECT ... | FOREACH ...
    def parse_query(self) -> Query:
        if self.accept_kw("select"):
            selects = self.parse_selects()
            q = Query(selects=selects, topics=[])
        elif self.accept_kw("foreach"):
            fe = self.parse_expr()
            alias = None
            if self.accept_kw("as"):
                alias = self.expect("ident")
            selects = None
            if self.accept_kw("do"):
                selects = self.parse_selects()
            incase = None
            if self.accept_kw("incase"):
                incase = self.parse_expr()
            q = Query(
                selects=selects,
                topics=[],
                foreach=fe,
                foreach_alias=alias,
                incase=incase,
            )
        else:
            raise SqlParseError("query must start with SELECT or FOREACH")
        self.expect("kw", "from")
        q.topics = [self.expect("str")]
        while self.peek() == ("op", ","):
            self.next()
            q.topics.append(self.expect("str"))
        if self.accept_kw("where"):
            q.where = self.parse_expr()
        if self.peek()[0] != "eof":
            raise SqlParseError(f"trailing input at token {self.peek()!r}")
        return q

    def parse_selects(self) -> Optional[List[SelectItem]]:
        if self.peek() == ("op", "*"):
            self.next()
            return None
        items = [self.parse_select_item()]
        while self.peek() == ("op", ","):
            self.next()
            items.append(self.parse_select_item())
        return items

    def parse_select_item(self) -> SelectItem:
        e = self.parse_expr()
        alias = None
        if self.accept_kw("as"):
            alias = [self.expect("ident")]
            while self.peek() == ("op", "."):
                self.next()
                alias.append(self.expect("ident"))
        return SelectItem(expr=e, alias=alias)

    # precedence climb: or > and > not > cmp > add > mul > unary > postfix
    def parse_expr(self):
        return self.parse_or()

    def parse_or(self):
        left = self.parse_and()
        while self.accept_kw("or"):
            left = BinOp("or", left, self.parse_and())
        return left

    def parse_and(self):
        left = self.parse_not()
        while self.accept_kw("and"):
            left = BinOp("and", left, self.parse_not())
        return left

    def parse_not(self):
        if self.accept_kw("not"):
            return UnOp("not", self.parse_not())
        return self.parse_cmp()

    def parse_cmp(self):
        left = self.parse_add()
        k, v = self.peek()
        if k == "op" and v in ("=", "!=", "<>", ">", "<", ">=", "<="):
            self.next()
            op = "!=" if v == "<>" else v
            return BinOp(op, left, self.parse_add())
        negated = False
        save = self.i
        if self.accept_kw("not"):
            if self.peek() == ("kw", "in"):
                negated = True
            else:
                self.i = save
                return left
        if self.accept_kw("in"):
            self.expect("op", "(")
            items = [self.parse_expr()]
            while self.peek() == ("op", ","):
                self.next()
                items.append(self.parse_expr())
            self.expect("op", ")")
            return InList(left, items, negated)
        if self.accept_kw("like"):
            pat = self.expect("str")
            # SQL LIKE: % = any run, _ = one char
            rx = re.escape(pat).replace("%", ".*").replace("_", ".")
            return Call("regex_match", [left, Lit(f"^{rx}$")])
        return left

    def parse_add(self):
        left = self.parse_mul()
        while True:
            k, v = self.peek()
            if k == "op" and v in ("+", "-"):
                self.next()
                left = BinOp(v, left, self.parse_mul())
            else:
                return left

    def parse_mul(self):
        left = self.parse_unary()
        while True:
            k, v = self.peek()
            if (k == "op" and v in ("*", "/")) or (
                k == "kw" and v in ("div", "mod")
            ):
                self.next()
                left = BinOp(v, left, self.parse_unary())
            else:
                return left

    def parse_unary(self):
        if self.peek() == ("op", "-"):
            self.next()
            return UnOp("neg", self.parse_unary())
        return self.parse_postfix()

    def parse_postfix(self):
        e = self.parse_primary()
        while True:
            k, v = self.peek()
            if (k, v) == ("op", "."):
                self.next()
                nk, nv = self.next()
                if nk not in ("ident", "kw"):
                    raise SqlParseError(f"bad attribute {nv!r}")
                if isinstance(e, Var):
                    e = Var(e.path + [str(nv)])
                else:
                    e = Call("map_get", [Lit(str(nv)), e])
            elif (k, v) == ("op", "["):
                self.next()
                idx = self.parse_expr()
                self.expect("op", "]")
                if isinstance(e, Var) and isinstance(idx, Lit):
                    e = Var(e.path + [idx.value])
                else:
                    e = Call("nth", [idx, e])
            else:
                return e

    def parse_primary(self):
        k, v = self.next()
        if k == "num" or k == "str":
            return Lit(v)
        if k == "kw":
            if v == "true":
                return Lit(True)
            if v == "false":
                return Lit(False)
            if v == "null":
                return Lit(None)
            if v == "case":
                return self.parse_case()
            raise SqlParseError(f"unexpected keyword {v!r}")
        if k == "ident":
            if self.peek() == ("op", "("):
                self.next()
                args = []
                if self.peek() != ("op", ")"):
                    args.append(self.parse_expr())
                    while self.peek() == ("op", ","):
                        self.next()
                        args.append(self.parse_expr())
                self.expect("op", ")")
                return Call(v.lower(), args)
            return Var([v])
        if (k, v) == ("op", "("):
            e = self.parse_expr()
            self.expect("op", ")")
            return e
        raise SqlParseError(f"unexpected token {v!r}")

    def parse_case(self) -> Case:
        whens = []
        while self.accept_kw("when"):
            cond = self.parse_expr()
            self.expect("kw", "then")
            whens.append((cond, self.parse_expr()))
        default = None
        if self.accept_kw("else"):
            default = self.parse_expr()
        self.expect("kw", "end")
        if not whens:
            raise SqlParseError("CASE needs at least one WHEN")
        return Case(whens, default)


def parse_sql(text: str) -> Query:
    return _Parser(_lex(text)).parse_query()
