"""Rule-predicate compiler: WHERE clauses -> device masks in the
serving launch.

The rule engine evaluates WHERE per message on the host (rules/
runtime.py) — post-dispatch Python rate. This module compiles the
supported AST subset (comparisons, AND/OR/NOT, IN-lists, numeric
arithmetic over a per-message feature schema) into a tiny stack
PROGRAM — a hashable tuple of RPN ops — that a trace-time interpreter
(`eval_prog`) unrolls into the serving jit: every enabled compiled
rule's WHERE evaluates over the whole batch INSIDE the same launch the
batch already pays for routing, and only the [R, B] boolean masks ride
the coalesced readback. Non-matching rows therefore drop at device
match rate; the host only ever touches rows that passed.

Degrade ladder (the robustness idiom):

  device mask  ->  vectorized numpy twin  ->  per-row scalar evaluator

The SAME program evaluates under numpy (`xp=np`) for CPU-degraded
batches — that is the vectorized host fallback `rules/runtime.
eval_where_rows` exposes — and anything the compiler cannot express
returns None and stays on the scalar `eval_expr` path unchanged.

Feature schema (host-extracted per batch into one f32 [B, F] matrix +
a validity mask): ``qos``, numeric ``payload.<key>`` lanes (the JSON
payload decodes ONCE per message, only when a payload lane exists),
and hashed string-identity lanes for ``topic(N)`` / ``payload.<key>``
string equality. String lanes hash to 24 bits (f32-exact): equal
strings always collide (no false negatives), unequal strings may — so
rules carrying a string lane are flagged ``exact=False`` and the
engine RE-VERIFIES device-passed rows with the scalar evaluator before
firing (passing rows are the rare case; non-matching rows still drop
at device rate, which is the whole win).

Null semantics mirror `rules/runtime.eval_expr` exactly (the fuzz
suite pins this): every numeric node carries a validity lane; invalid
(undefined/non-numeric) operands poison arithmetic, lose every
ordering comparison, and compare equal only to each other.
"""

from __future__ import annotations

import json
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from emqx_tpu.rules.sql import BinOp, Call, InList, Lit, Query, UnOp, Var

# f32 holds 24-bit integers exactly; string identity lanes live there
_HASH_BITS = 0xFFFFFF


def _shash(s) -> float:
    if isinstance(s, bytes):
        s = s.decode("utf-8", "replace")
    return float(zlib.crc32(str(s).encode("utf-8")) & _HASH_BITS)


class _Uncompilable(Exception):
    pass


class _Compiler:
    """AST -> RPN ops. Lane keys: ("num", "qos"), ("num",
    "payload.<k>"), ("str", "payload.<k>"), ("str", "topic.<n>")."""

    def __init__(self, lanes: Dict[Tuple[str, str], int]):
        self.lanes = lanes
        self.ops: List[tuple] = []
        self.exact = True

    def _lane(self, kind: str, name: str) -> int:
        key = (kind, name)
        if key not in self.lanes:
            self.lanes[key] = len(self.lanes)
        if kind == "str":
            self.exact = False
        return self.lanes[key]

    # numeric-producing nodes push ("feat"|"lit"|arith...) ops
    def num(self, node) -> None:
        if isinstance(node, Lit):
            v = node.value
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                raise _Uncompilable(f"non-numeric literal {v!r}")
            self.ops.append(("lit", float(v)))
            return
        if isinstance(node, Var):
            p = node.path
            if p == ["qos"]:
                self.ops.append(("feat", self._lane("num", "qos")))
                return
            if (
                len(p) == 2 and p[0] == "payload"
                and isinstance(p[1], str)
            ):
                self.ops.append(
                    ("feat", self._lane("num", f"payload.{p[1]}"))
                )
                return
            raise _Uncompilable(f"variable {p!r}")
        if isinstance(node, UnOp) and node.op == "neg":
            self.num(node.operand)
            self.ops.append(("neg",))
            return
        if isinstance(node, BinOp) and node.op in (
            "+", "-", "*", "/", "div", "mod"
        ):
            self.num(node.left)
            self.num(node.right)
            self.ops.append((
                {"+": "add", "-": "sub", "*": "mul", "/": "truediv",
                 "div": "idiv", "mod": "mod"}[node.op],
            ))
            return
        raise _Uncompilable(f"numeric node {type(node).__name__}")

    def _str_operand(self, node) -> None:
        """Push a string-identity feature (hashed lane)."""
        if isinstance(node, Var):
            p = node.path
            if (
                len(p) == 2 and p[0] == "payload"
                and isinstance(p[1], str)
            ):
                self.ops.append(
                    ("feat", self._lane("str", f"payload.{p[1]}"))
                )
                return
        if (
            isinstance(node, Call) and node.name == "topic"
            and len(node.args) == 1 and isinstance(node.args[0], Lit)
            and isinstance(node.args[0].value, int)
        ):
            n = node.args[0].value
            self.ops.append(("feat", self._lane("str", f"topic.{n}")))
            return
        raise _Uncompilable(f"string operand {type(node).__name__}")

    def _eq_pair(self, left, right, neq: bool) -> None:
        """Equality: numeric x numeric, or string-feature x string-lit
        (hashed identity)."""
        lit_str = isinstance(right, Lit) and isinstance(right.value, str)
        lit_str_l = isinstance(left, Lit) and isinstance(left.value, str)
        if lit_str or lit_str_l:
            feat, lit = (left, right) if lit_str else (right, left)
            self._str_operand(feat)
            self.ops.append(("lit", _shash(lit.value)))
        else:
            self.num(left)
            self.num(right)
        self.ops.append(("ne",) if neq else ("eq",))

    # boolean-producing nodes push mask ops
    def boolean(self, node) -> None:
        if isinstance(node, Lit) and isinstance(node.value, bool):
            self.ops.append(("blit", bool(node.value)))
            return
        if isinstance(node, BinOp):
            op = node.op
            if op in ("and", "or"):
                self.boolean(node.left)
                self.boolean(node.right)
                self.ops.append((op,))
                return
            if op in ("=", "!="):
                self._eq_pair(node.left, node.right, op == "!=")
                return
            if op in (">", "<", ">=", "<="):
                self.num(node.left)
                self.num(node.right)
                self.ops.append((
                    {">": "gt", "<": "lt", ">=": "ge", "<=": "le"}[op],
                ))
                return
            raise _Uncompilable(f"operator {op!r}")
        if isinstance(node, UnOp) and node.op == "not":
            self.boolean(node.operand)
            self.ops.append(("not",))
            return
        if isinstance(node, InList):
            # expand to OR of equalities (device has no set primitive);
            # items may be any compilable operand (-3 parses as a neg)
            for i, item in enumerate(node.items):
                self._eq_pair(node.needle, item, neq=False)
                if i:
                    self.ops.append(("or",))
            if node.negated:
                self.ops.append(("not",))
            return
        # numeric node in boolean position: truthiness (non-zero)
        self.num(node)
        self.ops.append(("truthy",))


def compile_where(expr, lanes: Dict[Tuple[str, str], int]):
    """Compile one WHERE AST against a SHARED lane table (lanes grow in
    place so every rule in a set extracts from one feature matrix).

    Returns ``(prog, exact)`` or None when the expression uses anything
    outside the compilable subset. ``prog`` is a hashable tuple of ops —
    the serving jit's static argument, so a rule-set change recompiles
    the program exactly once.
    """
    c = _Compiler(lanes)
    snapshot = dict(lanes)
    try:
        c.boolean(expr)
    except _Uncompilable:
        # roll back lanes this expression introduced before failing
        lanes.clear()
        lanes.update(snapshot)
        return None
    return tuple(c.ops), c.exact


# -- evaluation (ONE interpreter, two array modules) -------------------------


def eval_prog(prog: Sequence[tuple], feats, valid, xp):
    """Evaluate a compiled program over a feature batch.

    feats: f32 [B, F]; valid: bool [B, F]; xp: jax.numpy at trace time
    (the mask unrolls INTO the serving program) or numpy for the
    vectorized host fallback — same semantics by construction, which is
    what makes the numpy twin a trustworthy degrade target.

    Stack values are ("n", value, valid) numeric pairs or ("b", mask)
    booleans; null semantics follow rules/runtime.eval_expr (module
    docstring).
    """
    B = feats.shape[0]
    tt = xp.ones(B, bool)
    stack: list = []
    for op in prog:
        tag = op[0]
        if tag == "feat":
            lane = op[1]
            stack.append(("n", feats[:, lane], valid[:, lane]))
        elif tag == "lit":
            stack.append((
                "n", xp.full(B, op[1], np.float32), tt,
            ))
        elif tag == "blit":
            stack.append(("b", tt if op[1] else ~tt))
        elif tag in ("add", "sub", "mul", "truediv", "idiv", "mod"):
            _, b, vb = stack.pop()
            _, a, va = stack.pop()
            ok = va & vb
            if tag == "add":
                r = a + b
            elif tag == "sub":
                r = a - b
            elif tag == "mul":
                r = a * b
            else:
                ok = ok & (b != 0)
                safe = xp.where(b != 0, b, np.float32(1))
                if tag == "truediv":
                    r = a / safe
                elif tag == "idiv":
                    # host: int(a) // int(b) — trunc the operands, floor
                    # the quotient (python // semantics on the ints)
                    r = xp.floor_divide(xp.trunc(a), xp.trunc(safe))
                else:
                    r = xp.mod(xp.trunc(a), xp.trunc(safe))
            stack.append(("n", r, ok))
        elif tag == "neg":
            _, a, va = stack.pop()
            stack.append(("n", -a, va))
        elif tag in ("eq", "ne"):
            _, b, vb = stack.pop()
            _, a, va = stack.pop()
            # None = None is True; None = x is False (runtime._eq)
            eq = xp.where(
                va & vb, a == b, ~va & ~vb
            )
            stack.append(("b", eq if tag == "eq" else ~eq))
        elif tag in ("gt", "lt", "ge", "le"):
            _, b, vb = stack.pop()
            _, a, va = stack.pop()
            ok = va & vb
            if tag == "gt":
                r = a > b
            elif tag == "lt":
                r = a < b
            elif tag == "ge":
                r = a >= b
            else:
                r = a <= b
            stack.append(("b", ok & r))
        elif tag == "truthy":
            _, a, va = stack.pop()
            stack.append(("b", va & (a != 0)))
        elif tag == "not":
            _, m = stack.pop()
            stack.append(("b", ~m))
        elif tag == "and":
            _, m2 = stack.pop()
            _, m1 = stack.pop()
            stack.append(("b", m1 & m2))
        elif tag == "or":
            _, m2 = stack.pop()
            _, m1 = stack.pop()
            stack.append(("b", m1 | m2))
        else:  # pragma: no cover - compiler and interpreter co-evolve
            raise ValueError(f"unknown rule op {tag!r}")
    # the compiler leaves exactly one boolean on the stack
    tag, *rest = stack[-1] if stack else ("b", ~tt)
    if tag == "b":
        return rest[0]
    a, va = rest  # numeric top (bare `WHERE payload.x`): truthiness
    return va & (a != 0)


def eval_rule_masks(progs, feats, valid):
    """Trace-time entry the serving step calls: stack every compiled
    rule's mask into one bool [R, B] output (R = len(progs) >= 1)."""
    import jax.numpy as jnp

    return jnp.stack([eval_prog(p, feats, valid, jnp) for p in progs])


# -- feature extraction ------------------------------------------------------


def _mget(m, key, default=None):
    """Feature source accessor: a Message object (broker batches) or an
    event-context dict (rules/runtime.eval_where_rows) both work."""
    if isinstance(m, dict):
        return m.get(key, default)
    return getattr(m, key, default)


def extract_features(msgs, lanes: Dict[Tuple[str, str], int]):
    """One f32 [B, F] matrix + validity mask + per-row SUSPECT flags
    for a message batch (Message objects or event-context dicts).

    Host-side, loop thread; the payload JSON decodes at most once per
    message and only when some rule declared a payload lane. A numeric
    lane is valid only for REAL numbers; a string/bool/structure value
    marks the ROW suspect instead — the scalar evaluator's coercion
    rules there (numeric strings compare numerically but poison
    arithmetic, bools are identity-only) cannot be mirrored by one f32
    lane, so suspect rows force a PASS and the engine re-verifies them
    with the scalar authority. Well-typed rows (the overwhelming case)
    keep the pure device-rate drop. Message objects additionally carry
    the flag in ``headers["_rule_suspect"]`` so settle-time firing
    needs no re-extraction.
    """
    B, F = len(msgs), len(lanes)
    feats = np.zeros((B, F), np.float32)
    valid = np.zeros((B, F), bool)
    suspect = np.zeros(B, bool)
    keys = list(lanes.items())
    need_payload = any(
        name.startswith("payload.") for (_k, name), _i in keys
    )
    for i, m in enumerate(msgs):
        payload = None
        decoded = False
        for (kind, name), lane in keys:
            if name == "qos":
                q = _mget(m, "qos", 0)
                if isinstance(q, bool) or not isinstance(
                    q, (int, float)
                ):
                    continue
                feats[i, lane] = float(q)
                valid[i, lane] = True
                continue
            if name.startswith("topic."):
                n = int(name[6:])
                toks = str(_mget(m, "topic", "") or "").split("/")
                if 1 <= n <= len(toks):
                    feats[i, lane] = _shash(toks[n - 1])
                    valid[i, lane] = True
                continue
            # payload.<key>
            if need_payload and not decoded:
                decoded = True
                payload = _mget(m, "payload", None)
                if isinstance(payload, (bytes, str)):
                    try:
                        payload = json.loads(payload or b"null")
                    except (ValueError, TypeError):
                        payload = None
            if not isinstance(payload, dict):
                continue
            v = payload.get(name[8:])
            if kind == "str":
                if isinstance(v, (str, bytes)):
                    feats[i, lane] = _shash(v)
                    valid[i, lane] = True
                continue
            if v is None:
                continue  # missing: exact None semantics in-program
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                feats[i, lane] = np.float32(v)
                valid[i, lane] = True
            else:
                # string/bool/structure in a numeric lane: the scalar
                # evaluator's coercion rules decide — flag the row
                suspect[i] = True
        if suspect[i] and not isinstance(m, dict):
            m.headers["_rule_suspect"] = True
    return feats, valid, suspect


class CompiledRule:
    __slots__ = ("rule", "prog", "exact")

    def __init__(self, rule, prog, exact: bool):
        self.rule = rule
        self.prog = prog
        self.exact = exact


class DeviceRuleFilter:
    """The rule set's device-resident half: compiled WHERE programs +
    the shared feature-lane table, refreshed whenever the registry
    changes (rule create/delete/enable toggles).

    A rule compiles when: it is enabled, selects 'message.publish'
    events through plain topic filters (no $events, no FOREACH), and
    its WHERE fits the compilable subset. Everything else stays on the
    scalar hook path untouched.
    """

    def __init__(self):
        self.lanes: Dict[Tuple[str, str], int] = {}
        self.compiled: List[CompiledRule] = []
        self._ids: frozenset = frozenset()

    def refresh(self, rules) -> None:
        lanes: Dict[Tuple[str, str], int] = {}
        out: List[CompiledRule] = []
        for rule in rules:
            q: Query = rule.query
            if not rule.enabled or q.where is None:
                continue
            if q.foreach is not None:
                continue
            if any(t.startswith("$events/") for t in q.topics):
                continue
            res = compile_where(q.where, lanes)
            if res is None:
                continue
            prog, exact = res
            out.append(CompiledRule(rule, prog, exact))
        self.lanes = lanes
        self.compiled = out
        self._ids = frozenset(c.rule.id for c in out)

    @property
    def active(self) -> bool:
        return bool(self.compiled)

    @property
    def progs(self) -> tuple:
        """The serving jit's static argument (hashable; identity keys
        the compiled program, so rule-set churn retraces exactly once)."""
        return tuple(c.prog for c in self.compiled)

    def covers(self, rule_id: str) -> bool:
        return rule_id in self._ids

    def features(self, msgs):
        """(feats, valid) for the device launch; the per-row suspect
        flags land in the message headers (see extract_features)."""
        feats, valid, _suspect = extract_features(msgs, self.lanes)
        return feats, valid

    def host_masks(self, msgs) -> np.ndarray:
        """Vectorized numpy evaluation — the CPU-degraded batch path
        (and the differential reference for the device masks)."""
        if not self.compiled:
            return np.zeros((0, len(msgs)), bool)
        feats, valid, _suspect = extract_features(msgs, self.lanes)
        return np.stack([
            np.asarray(eval_prog(c.prog, feats, valid, np))
            for c in self.compiled
        ])
