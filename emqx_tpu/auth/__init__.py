"""Authentication/authorization backends.

The chain framework lives in emqx_tpu/broker/auth.py (provider protocol +
'client.authenticate' fold) and emqx_tpu/broker/authz.py (rule sources +
'client.authorize' fold). This package holds the external-backend
providers, mirroring the reference's apps:

- `http`  — HTTP authn provider + HTTP authz source
  (apps/emqx_authn/src/simple_authn/emqx_authn_http.erl,
   apps/emqx_authz/src/emqx_authz_http.erl)
- `jwks`  — RS256 JWT verification against a JWKS endpoint
  (emqx_authn_jwt.erl jwks mode), pure-python RSA verify
- `scram` — SCRAM-SHA-256 enhanced authentication over MQTT5 AUTH
  (apps/emqx_authn/src/enhanced_authn/emqx_enhanced_authn_scram_mnesia.erl)
- `psk`   — TLS-PSK identity store (apps/emqx_psk/src/emqx_psk.erl);
  handshake wiring is gated on Python's ssl PSK support
- `file_acl` — file-based authorization source (emqx_authz_file.erl)
"""
