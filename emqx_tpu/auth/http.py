"""HTTP authentication provider + HTTP authorization source.

Parity with the reference's HTTP backends:
- authn (apps/emqx_authn/src/simple_authn/emqx_authn_http.erl): request
  templated from client info; 200/204 with JSON body decides
  allow/deny/ignore (+ is_superuser), 4xx/5xx => ignore (fall through).
- authz (apps/emqx_authz/src/emqx_authz_http.erl): per (client, action,
  topic) query; 200 {"result": "allow"|"deny"|"ignore"}; transport errors
  => ignore (the chain's no_match policy applies).

Both are async (aiohttp) — the channel runs auth hooks via arun_fold, so
a slow auth service suspends only that client's task.
"""

from __future__ import annotations

import json
import logging
from typing import Dict, Optional

from emqx_tpu.broker.auth import DENY, IGNORE, OK, Provider
from emqx_tpu.mqtt import packet as pkt
from emqx_tpu.utils.placeholder import render

log = logging.getLogger("emqx_tpu.auth.http")


def _client_env(ci: Dict, credentials: Optional[Dict] = None) -> Dict:
    pw = (credentials or {}).get("password") or b""
    return {
        "clientid": ci.get("client_id", ""),
        "username": ci.get("username") or "",
        "password": pw.decode("utf-8", "replace") if isinstance(pw, bytes) else pw,
        "peerhost": str(ci.get("peerhost", "")),
        "mountpoint": ci.get("mountpoint") or "",
    }


class _HttpCaller:
    def __init__(
        self,
        url: str,
        method: str = "POST",
        headers: Optional[Dict[str, str]] = None,
        body: Optional[Dict[str, str]] = None,
        timeout: float = 5.0,
    ):
        self.url = url
        self.method = method.upper()
        self.headers = headers or {"content-type": "application/json"}
        self.body = body or {}
        self.timeout = timeout
        self._session = None

    async def _ensure(self):
        if self._session is None:
            import aiohttp

            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=self.timeout)
            )
        return self._session

    async def call(self, env: Dict):
        """-> (status, json_or_none) or None on transport error."""
        s = await self._ensure()
        url = render(self.url, env)
        rendered = {k: render(v, env) for k, v in self.body.items()}
        try:
            if self.method == "GET":
                async with s.get(url, params=rendered) as resp:
                    return resp.status, await self._json(resp)
            async with s.request(
                self.method, url, json=rendered, headers=self.headers
            ) as resp:
                return resp.status, await self._json(resp)
        except Exception as e:
            log.warning("http auth call failed: %s", e)
            return None

    @staticmethod
    async def _json(resp):
        try:
            return json.loads(await resp.text())
        except (ValueError, UnicodeDecodeError):
            return None

    async def close(self):
        if self._session is not None:
            await self._session.close()
            self._session = None


class HttpAuthProvider(Provider):
    """'client.authenticate' provider backed by an HTTP service."""

    def __init__(self, url: str, method: str = "POST",
                 headers: Optional[Dict[str, str]] = None,
                 body: Optional[Dict[str, str]] = None,
                 timeout: float = 5.0):
        self.caller = _HttpCaller(
            url,
            method,
            headers,
            body
            or {
                "clientid": "${clientid}",
                "username": "${username}",
                "password": "${password}",
            },
            timeout,
        )

    def authenticate(self, client_info, credentials):
        # sync path (tests/tools): no opinion — the async path decides
        return IGNORE, None

    async def authenticate_async(self, client_info, credentials):
        out = await self.caller.call(_client_env(client_info, credentials))
        if out is None:
            return IGNORE, None
        status, data = out
        if status == 204:
            return OK, None
        if status != 200 or not isinstance(data, dict):
            return IGNORE, None
        # missing/invalid `result` falls through the chain (emqx_authn_http
        # parity) — a 200 error payload must not become allow-all
        result = data.get("result", "ignore")
        if result == "allow":
            if data.get("is_superuser"):
                client_info["is_superuser"] = True
            return OK, None
        if result == "deny":
            return DENY, pkt.RC_NOT_AUTHORIZED
        return IGNORE, None

    async def close(self):
        await self.caller.close()


class HttpAuthzSource:
    """'client.authorize' source backed by an HTTP service."""

    def __init__(self, url: str, method: str = "POST",
                 headers: Optional[Dict[str, str]] = None,
                 body: Optional[Dict[str, str]] = None,
                 timeout: float = 5.0):
        self.caller = _HttpCaller(
            url,
            method,
            headers,
            body
            or {
                "clientid": "${clientid}",
                "username": "${username}",
                "topic": "${topic}",
                "action": "${action}",
            },
            timeout,
        )

    async def check(self, ci: Dict, action: str, topic: str) -> str:
        env = _client_env(ci)
        env["action"] = action
        env["topic"] = topic
        out = await self.caller.call(env)
        if out is None:
            return "ignore"
        status, data = out
        if status == 204:
            return "allow"
        if status != 200 or not isinstance(data, dict):
            return "ignore"
        r = data.get("result", "ignore")
        return r if r in ("allow", "deny") else "ignore"

    async def close(self):
        await self.caller.close()
