"""TLS-PSK identity store.

Parity: apps/emqx_psk/src/emqx_psk.erl — an identity->secret store fed
from config/file (``identity:hex-secret`` lines) and consulted by the TLS
handshake callback.

Python's ssl module grew PSK callbacks in 3.13
(`SSLContext.set_psk_server_callback`); on this image (3.12) the store,
file import, and management surface work, and `wire_into` reports whether
the running interpreter can terminate PSK handshakes — the listener skips
PSK wiring cleanly when it can't.
"""

from __future__ import annotations

import binascii
import logging
import ssl
from typing import Dict, List, Optional

log = logging.getLogger("emqx_tpu.auth.psk")

SUPPORTED = hasattr(ssl.SSLContext, "set_psk_server_callback")


class PskStore:
    def __init__(self):
        self._identities: Dict[str, bytes] = {}

    def insert(self, identity: str, secret_hex: str) -> None:
        self._identities[identity] = binascii.unhexlify(secret_hex)

    def delete(self, identity: str) -> bool:
        return self._identities.pop(identity, None) is not None

    def lookup(self, identity: str) -> Optional[bytes]:
        return self._identities.get(identity)

    def identities(self) -> List[str]:
        return list(self._identities)

    def import_file(self, path: str) -> int:
        """``identity:hexsecret`` per line (emqx_psk init file parity)."""
        n = 0
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                ident, _, secret = line.partition(":")
                if not secret:
                    log.warning("psk: skipping malformed line %r", line)
                    continue
                try:
                    self.insert(ident, secret)
                    n += 1
                except binascii.Error:
                    log.warning("psk: bad hex secret for %r", ident)
        return n

    def wire_into(self, ctx: ssl.SSLContext, hint: str = "emqx_tpu") -> bool:
        """Attach this store to a server-side TLS context. Returns False
        (and leaves the context untouched) when the interpreter's ssl
        module has no PSK support."""
        if not SUPPORTED:
            log.warning(
                "TLS-PSK requested but ssl.SSLContext has no PSK callbacks "
                "on this Python; listener continues without PSK"
            )
            return False

        def cb(conn, identity):
            secret = self._identities.get(identity or "")
            return secret or b""

        ctx.set_psk_server_callback(cb, identity_hint=hint)
        return True
