"""File-based authorization source.

Parity: apps/emqx_authz/src/emqx_authz_file.erl — the reference consults
an ``acl.conf`` of Erlang terms; this stack's native format is JSON lines
(one rule object per line, comments with #), same rule semantics
(permit/who/action/topics with placeholders, first match wins):

    {"permit": "allow", "who": {"username": "alice"}, "action": "publish",
     "topics": ["a/b", "c/${clientid}/#"]}
    {"permit": "deny", "who": "all", "action": "all", "topics": ["#"]}

`load` parses into the Authorizer's AclRule list; `watch`-style reload is
a `load` + `Authorizer.set_rules` (cache invalidation included).
"""

from __future__ import annotations

import json
import logging
from typing import List

from emqx_tpu.broker.authz import AclRule

log = logging.getLogger("emqx_tpu.auth.file")


def parse_rules(text: str) -> List[AclRule]:
    rules: List[AclRule] = []
    for i, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            obj = json.loads(line)
            rules.append(
                AclRule(
                    permit=obj["permit"],
                    who=obj.get("who", "all"),
                    action=obj.get("action", "all"),
                    topics=list(obj.get("topics", [])),
                )
            )
        except (ValueError, KeyError) as e:
            raise ValueError(f"acl file line {i}: {e}") from e
    return rules


def load(path: str) -> List[AclRule]:
    with open(path) as f:
        return parse_rules(f.read())
