"""SCRAM-SHA-256 server-side enhanced authentication (RFC 5802/7677).

Parity: apps/emqx_authn/src/enhanced_authn/emqx_enhanced_authn_scram_mnesia.erl
— MQTT5 enhanced auth with Authentication-Method "SCRAM-SHA-256": the
CONNECT carries the client-first message in Authentication-Data, the
server answers with an AUTH (0x18 continue) carrying server-first, the
client's AUTH carries client-final, and the CONNACK returns server-final
(the server signature), mutually authenticating both sides without the
password ever crossing the wire.

The user store keeps only (salt, iterations, StoredKey, ServerKey), so a
leaked store does not reveal passwords (RFC 5802 §9).
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import os
import secrets
from dataclasses import dataclass
from typing import Dict, Optional, Tuple


def _h(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


def _hmac(key: bytes, msg: bytes) -> bytes:
    return hmac.new(key, msg, hashlib.sha256).digest()


def _xor(a: bytes, b: bytes) -> bytes:
    return bytes(x ^ y for x, y in zip(a, b))


def derive_keys(password: str, salt: bytes, iterations: int) -> Tuple[bytes, bytes]:
    """-> (StoredKey, ServerKey)"""
    salted = hashlib.pbkdf2_hmac("sha256", password.encode(), salt, iterations)
    client_key = _hmac(salted, b"Client Key")
    server_key = _hmac(salted, b"Server Key")
    return _h(client_key), server_key


@dataclass
class ScramUser:
    salt: bytes
    iterations: int
    stored_key: bytes
    server_key: bytes
    is_superuser: bool = False


def _parse_attrs(msg: str) -> Dict[str, str]:
    out = {}
    for part in msg.split(","):
        if len(part) >= 2 and part[1] == "=":
            out[part[0]] = part[2:]
    return out


class ScramAuthenticator:
    """User store + per-connection exchange state machine."""

    METHOD = "SCRAM-SHA-256"

    def __init__(self, iterations: int = 4096):
        self.iterations = iterations
        self._users: Dict[str, ScramUser] = {}

    # -- user management ---------------------------------------------------
    def add_user(self, username: str, password: str, is_superuser: bool = False) -> None:
        salt = os.urandom(16)
        stored, server = derive_keys(password, salt, self.iterations)
        self._users[username] = ScramUser(
            salt, self.iterations, stored, server, is_superuser
        )

    def delete_user(self, username: str) -> bool:
        return self._users.pop(username, None) is not None

    def users(self):
        return list(self._users)

    # -- exchange ----------------------------------------------------------
    def start(self, client_first: bytes):
        """client-first-message -> ('continue', server_first, state) or
        ('deny', reason)."""
        try:
            text = client_first.decode()
            # gs2 header: 'n,,' (no channel binding)
            if not text.startswith(("n,,", "y,,")):
                return ("deny", "channel binding unsupported")
            bare = text[3:]
            attrs = _parse_attrs(bare)
            username = attrs.get("n")
            cnonce = attrs.get("r")
            if not username or not cnonce:
                return ("deny", "malformed client-first")
        except (UnicodeDecodeError, ValueError):
            return ("deny", "malformed client-first")
        user = self._users.get(username)
        if user is None:
            return ("deny", "unknown user")
        snonce = cnonce + secrets.token_urlsafe(18)
        server_first = (
            f"r={snonce},s={base64.b64encode(user.salt).decode()},"
            f"i={user.iterations}"
        )
        state = {
            "user": user,
            "username": username,
            "nonce": snonce,
            "client_first_bare": bare,
            "server_first": server_first,
        }
        return ("continue", server_first.encode(), state)

    def finish(self, state: Dict, client_final: bytes):
        """client-final-message -> ('ok', server_final, attrs) or
        ('deny', reason)."""
        try:
            text = client_final.decode()
            attrs = _parse_attrs(text)
            nonce = attrs.get("r")
            proof_b64 = attrs.get("p")
            if nonce != state["nonce"] or not proof_b64:
                return ("deny", "nonce mismatch")
            proof = base64.b64decode(proof_b64)
            without_proof = text[: text.rindex(",p=")]
        except (UnicodeDecodeError, ValueError):
            return ("deny", "malformed client-final")
        user: ScramUser = state["user"]
        auth_message = (
            f"{state['client_first_bare']},{state['server_first']},"
            f"{without_proof}"
        ).encode()
        client_signature = _hmac(user.stored_key, auth_message)
        client_key = _xor(proof, client_signature)
        if not hmac.compare_digest(_h(client_key), user.stored_key):
            return ("deny", "bad proof")
        server_signature = _hmac(user.server_key, auth_message)
        server_final = b"v=" + base64.b64encode(server_signature)
        return (
            "ok",
            server_final,
            {"username": state["username"], "is_superuser": user.is_superuser},
        )


class ScramClient:
    """Client half (tests / in-repo client use)."""

    def __init__(self, username: str, password: str):
        self.username = username
        self.password = password
        self.cnonce = secrets.token_urlsafe(18)
        self._bare = f"n={username},r={self.cnonce}"
        self._server_first: Optional[str] = None
        self._auth_message: Optional[bytes] = None
        self._salted: Optional[bytes] = None

    def client_first(self) -> bytes:
        return ("n,," + self._bare).encode()

    def client_final(self, server_first: bytes) -> bytes:
        sf = server_first.decode()
        attrs = _parse_attrs(sf)
        nonce = attrs["r"]
        if not nonce.startswith(self.cnonce):
            raise ValueError("server nonce does not extend client nonce")
        salt = base64.b64decode(attrs["s"])
        iterations = int(attrs["i"])
        without_proof = f"c=biws,r={nonce}"
        self._auth_message = f"{self._bare},{sf},{without_proof}".encode()
        self._salted = hashlib.pbkdf2_hmac(
            "sha256", self.password.encode(), salt, iterations
        )
        client_key = _hmac(self._salted, b"Client Key")
        stored = _h(client_key)
        proof = _xor(client_key, _hmac(stored, self._auth_message))
        return (
            f"{without_proof},p={base64.b64encode(proof).decode()}"
        ).encode()

    def verify_server(self, server_final: bytes) -> bool:
        attrs = _parse_attrs(server_final.decode())
        server_key = _hmac(self._salted, b"Server Key")
        expect = _hmac(server_key, self._auth_message)
        return hmac.compare_digest(
            base64.b64decode(attrs.get("v", "")), expect
        )
