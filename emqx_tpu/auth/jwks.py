"""RS256 JWT verification against a JWKS endpoint.

Parity: emqx_authn_jwt's jwks mode (apps/emqx_authn/src/simple_authn/
emqx_authn_jwt.erl with emqx_authn_jwks_connector) — tokens arrive in the
MQTT password field, keys come from a JWKS URL (kid-matched), refreshed
periodically.

RSA PKCS#1 v1.5 verification is implemented directly (modular
exponentiation + EMSA-PKCS1-v1_5 digest comparison) — no crypto
dependency in this image; verification-only, no key generation.
"""

from __future__ import annotations

import base64
import hashlib
import json
import logging
import time
from typing import Dict, List, Optional

from emqx_tpu.broker.auth import DENY, IGNORE, OK, Provider
from emqx_tpu.mqtt import packet as pkt

log = logging.getLogger("emqx_tpu.auth.jwks")

# DigestInfo prefix for SHA-256 (RFC 8017 §9.2 notes)
_SHA256_PREFIX = bytes.fromhex("3031300d060960864801650304020105000420")


def _b64d(s: str) -> bytes:
    return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))


def rsa_verify_pkcs1_sha256(n: int, e: int, message: bytes, sig: bytes) -> bool:
    k = (n.bit_length() + 7) // 8
    if len(sig) != k:
        return False
    m = pow(int.from_bytes(sig, "big"), e, n)
    em = m.to_bytes(k, "big")
    expect = _SHA256_PREFIX + hashlib.sha256(message).digest()
    # EM = 0x00 0x01 PS(0xff..., >=8) 0x00 T
    if em[0] != 0 or em[1] != 1:
        return False
    try:
        sep = em.index(b"\x00", 2)
    except ValueError:
        return False
    if sep < 10 or any(b != 0xFF for b in em[2:sep]):
        return False
    return em[sep + 1 :] == expect


class JwksAuthProvider(Provider):
    """'client.authenticate' provider: RS256 password-field JWTs."""

    def __init__(
        self,
        endpoint: str,
        refresh_interval: float = 300.0,
        verify_claims: Optional[Dict[str, str]] = None,
        timeout: float = 5.0,
    ):
        self.endpoint = endpoint
        self.refresh_interval = refresh_interval
        self.verify_claims = verify_claims or {}
        self.timeout = timeout
        self._keys: Dict[str, Dict] = {}  # kid -> {n: int, e: int}
        self._fetched_at = 0.0
        self._last_attempt = 0.0
        self.retry_interval = 5.0  # failure backoff: don't hammer a dead
        # endpoint once per connecting client
        self._session = None

    # -- key management ----------------------------------------------------
    def load_keys(self, jwks: Dict) -> None:
        """Install a JWKS document (also the test seam)."""
        keys = {}
        for k in jwks.get("keys", []):
            if k.get("kty") != "RSA" or k.get("use", "sig") != "sig":
                continue
            try:
                keys[k.get("kid", "")] = {
                    "n": int.from_bytes(_b64d(k["n"]), "big"),
                    "e": int.from_bytes(_b64d(k["e"]), "big"),
                }
            except (KeyError, ValueError):
                continue
        self._keys = keys
        self._fetched_at = time.time()

    async def _refresh(self) -> None:
        now = time.time()
        if self._keys and now - self._fetched_at < self.refresh_interval:
            return
        if now - self._last_attempt < self.retry_interval:
            return
        self._last_attempt = now
        try:
            import aiohttp

            if self._session is None:
                self._session = aiohttp.ClientSession(
                    timeout=aiohttp.ClientTimeout(total=self.timeout)
                )
            async with self._session.get(self.endpoint) as resp:
                if resp.status == 200:
                    self.load_keys(json.loads(await resp.text()))
        except Exception as e:
            log.warning("jwks refresh failed: %s", e)

    async def close(self):
        if self._session is not None:
            await self._session.close()
            self._session = None

    # -- provider ----------------------------------------------------------
    def authenticate(self, client_info, credentials):
        token = credentials.get("password")
        if not token:
            return IGNORE, None
        return self._verify(client_info, token)

    async def authenticate_async(self, client_info, credentials):
        token = credentials.get("password")
        if not token:
            return IGNORE, None
        await self._refresh()
        return self._verify(client_info, token)

    def _verify(self, client_info, token: bytes):
        try:
            parts = token.decode().split(".")
            if len(parts) != 3:
                return IGNORE, None
            header = json.loads(_b64d(parts[0]))
            if header.get("alg") != "RS256":
                return IGNORE, None
            key = self._keys.get(header.get("kid", ""))
            if key is None and len(self._keys) == 1:
                key = next(iter(self._keys.values()))
            if key is None:
                return DENY, pkt.RC_BAD_USERNAME_OR_PASSWORD
            signing = f"{parts[0]}.{parts[1]}".encode()
            if not rsa_verify_pkcs1_sha256(
                key["n"], key["e"], signing, _b64d(parts[2])
            ):
                return DENY, pkt.RC_BAD_USERNAME_OR_PASSWORD
            claims = json.loads(_b64d(parts[1]))
        except Exception:
            return DENY, pkt.RC_BAD_USERNAME_OR_PASSWORD
        if "exp" in claims and time.time() > claims["exp"]:
            return DENY, pkt.RC_BAD_USERNAME_OR_PASSWORD
        for claim, expect in self.verify_claims.items():
            expect = expect.replace(
                "${clientid}", client_info.get("client_id", "")
            ).replace("${username}", client_info.get("username") or "")
            if claims.get(claim) != expect:
                return DENY, pkt.RC_NOT_AUTHORIZED
        client_info["jwt_claims"] = claims
        return OK, None
