"""STOMP 1.0/1.1/1.2 gateway.

Parity with the reference's STOMP gateway (apps/emqx_gateway/src/stomp/:
emqx_stomp_frame.erl codec, emqx_stomp_channel.erl semantics):

- CONNECT/STOMP -> CONNECTED with version + heart-beat negotiation
- SEND -> broker publish (``destination`` header is the topic); optional
  transactions (BEGIN/COMMIT/ABORT buffer SENDs/ACKs atomically)
- SUBSCRIBE/UNSUBSCRIBE (``id`` + ``destination``) -> broker subscribe;
  deliveries come back as MESSAGE frames with ``subscription``/
  ``message-id`` headers
- RECEIPT for any client frame carrying ``receipt``; ERROR + close on
  protocol violations
- heart-beat: newline keepalives both ways, connection dropped after
  2x the negotiated incoming period

Framing: ``COMMAND\\n headers \\n\\n body NUL``; 1.2 header escaping
(\\c \\n \\r \\\\); ``content-length`` for binary bodies.
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from emqx_tpu.gateway.base import Gateway, GwClientInfo, GwFrame, GwSession
from emqx_tpu.mqtt import packet as pkt

log = logging.getLogger("emqx_tpu.gateway.stomp")

SERVER_VERSIONS = ("1.0", "1.1", "1.2")
MAX_HEADERS = 32
MAX_HEADER_LEN = 1024
MAX_BODY = 1 << 20


@dataclass
class StompFrame:
    command: str
    headers: Dict[str, str] = field(default_factory=dict)
    body: bytes = b""


_ESC = {"\\n": "\n", "\\r": "\r", "\\c": ":", "\\\\": "\\"}


def _unescape(s: str, version: str) -> str:
    if version == "1.0":
        return s
    out = []
    i = 0
    while i < len(s):
        if s[i] == "\\" and i + 1 < len(s):
            pair = s[i : i + 2]
            if pair not in _ESC:
                raise ValueError(f"bad escape {pair!r}")
            out.append(_ESC[pair])
            i += 2
        else:
            out.append(s[i])
            i += 1
    return "".join(out)


def _escape(s: str, version: str) -> str:
    if version == "1.0":
        return s
    return (
        s.replace("\\", "\\\\")
        .replace("\r", "\\r")
        .replace("\n", "\\n")
        .replace(":", "\\c")
    )


class StompCodec(GwFrame):
    """Incremental STOMP parser (emqx_gateway_frame behaviour)."""

    def __init__(self, version: str = "1.2"):
        self.version = version
        self._buf = b""

    def parse(self, data: bytes) -> List[StompFrame]:
        self._buf += data
        frames: List[StompFrame] = []
        while True:
            f, rest = self._parse_one(self._buf)
            if f is None:
                break
            self._buf = rest
            if f != "heartbeat":
                frames.append(f)
        return frames

    def _parse_one(self, buf: bytes):
        # leading EOLs between frames are heart-beats
        if buf[:2] == b"\r\n":
            return "heartbeat", buf[2:]
        if buf[:1] == b"\n":
            return "heartbeat", buf[1:]
        hdr_end = buf.find(b"\n\n")
        hdr_end_crlf = buf.find(b"\r\n\r\n")
        if hdr_end_crlf != -1 and (hdr_end == -1 or hdr_end_crlf < hdr_end):
            head, rest = buf[:hdr_end_crlf], buf[hdr_end_crlf + 4 :]
        elif hdr_end != -1:
            head, rest = buf[:hdr_end], buf[hdr_end + 2 :]
        else:
            if len(buf) > MAX_HEADERS * MAX_HEADER_LEN:
                raise ValueError("headers too large")
            return None, buf
        lines = head.replace(b"\r\n", b"\n").split(b"\n")
        command = lines[0].decode("utf-8")
        headers: Dict[str, str] = {}
        for line in lines[1:]:
            if not line:
                continue
            k, sep, v = line.decode("utf-8").partition(":")
            if not sep:
                raise ValueError("header without ':'")
            k = _unescape(k, self.version)
            # repeated header: first occurrence wins (STOMP 1.2 spec)
            if k not in headers and len(headers) < MAX_HEADERS:
                headers[k] = _unescape(v, self.version)
        clen = headers.get("content-length")
        if clen is not None:
            n = int(clen)
            if n > MAX_BODY:
                raise ValueError("body too large")
            if len(rest) < n + 1:
                return None, buf
            if rest[n : n + 1] != b"\x00":
                raise ValueError("missing frame NUL")
            return StompFrame(command, headers, rest[:n]), rest[n + 1 :]
        z = rest.find(b"\x00")
        if z == -1:
            if len(rest) > MAX_BODY:
                raise ValueError("body too large")
            return None, buf
        return StompFrame(command, headers, rest[:z]), rest[z + 1 :]

    def serialize(self, f: StompFrame) -> bytes:
        out = [f.command.encode()]
        for k, v in f.headers.items():
            out.append(
                f"{_escape(k, self.version)}:{_escape(str(v), self.version)}".encode()
            )
        if f.body and "content-length" not in f.headers:
            out.append(f"content-length:{len(f.body)}".encode())
        return b"\n".join(out) + b"\n\n" + f.body + b"\x00\n"


class StompChannel:
    """One STOMP connection's protocol state machine
    (emqx_stomp_channel.erl)."""

    def __init__(self, gw: "StompGateway", writer: asyncio.StreamWriter, peer):
        self.gw = gw
        self.writer = writer
        self.peer = peer
        self.codec = StompCodec()
        self.session: Optional[GwSession] = None
        self.connected = False
        self.version = "1.2"
        # subscription id -> (destination, ack_mode). Several ids may share
        # one destination (legal in STOMP); the broker-side subscription is
        # refcounted per destination and each matching id gets its own
        # MESSAGE frame on delivery.
        self.subs: Dict[str, Tuple[str, str]] = {}
        self._dest_refs: Dict[str, int] = {}
        self.txns: Dict[str, List[StompFrame]] = {}
        self._msg_seq = 0
        self._hb_out = 0.0  # negotiated outgoing period (s), 0 = none
        self._hb_in = 0.0
        self._last_recv = time.monotonic()
        self._hb_task: Optional[asyncio.Task] = None
        self.closing = False

    # -- outgoing ----------------------------------------------------------
    def send(self, f: StompFrame) -> None:
        if not self.writer.is_closing():
            self.writer.write(self.codec.serialize(f))

    def send_error(self, msg: str, detail: str = "") -> None:
        self.send(
            StompFrame(
                "ERROR",
                {"message": msg, "content-type": "text/plain"},
                detail.encode(),
            )
        )

    def _maybe_receipt(self, f: StompFrame) -> None:
        rid = f.headers.get("receipt")
        if rid is not None:
            self.send(StompFrame("RECEIPT", {"receipt-id": rid}))

    # -- incoming ----------------------------------------------------------
    async def handle_data(self, data: bytes) -> None:
        self._last_recv = time.monotonic()
        try:
            frames = self.codec.parse(data)
        except ValueError as e:
            self.send_error("protocol error", str(e))
            await self.shutdown("frame_error")
            return
        for f in frames:
            await self.handle_frame(f)

    async def handle_frame(self, f: StompFrame) -> None:
        if not self.connected and f.command not in ("CONNECT", "STOMP"):
            self.send_error("not connected")
            await self.shutdown("not_connected")
            return
        handler = getattr(self, f"_on_{f.command.lower()}", None)
        if handler is None:
            self.send_error(f"unsupported command {f.command}")
            return
        try:
            await handler(f)
        except (ValueError, KeyError) as e:
            # malformed headers (bad qos, missing fields): ERROR the frame,
            # keep the connection — never let it kill the reader task
            self.send_error("malformed frame", str(e))

    async def _on_connect(self, f: StompFrame) -> None:
        if self.connected:
            self.send_error("already connected")
            await self.shutdown("duplicate_connect")
            return
        accept = f.headers.get("accept-version", "1.0").split(",")
        vers = [v for v in SERVER_VERSIONS if v in accept]
        if not vers:
            self.send_error("unsupported version")
            await self.shutdown("bad_version")
            return
        self.version = max(vers)
        self.codec.version = self.version
        login = f.headers.get("login")
        clientid = f.headers.get("client-id") or f"stomp-{id(self):x}"
        info = GwClientInfo(
            clientid=clientid,
            username=login,
            peername=self.peer,
            protocol="stomp",
            mountpoint=self.gw.config.get("mountpoint"),
        )
        ok = await self.gw.authenticate(info, f.headers.get("passcode"))
        if not ok:
            self.send_error("authentication failed")
            await self.shutdown("auth_failure")
            return
        # heart-beat negotiation: cx,cy vs server 10s,10s
        cx, _, cy = f.headers.get("heart-beat", "0,0").partition(",")
        try:
            cx_ms, cy_ms = int(cx), int(cy or 0)
        except ValueError:
            cx_ms = cy_ms = 0
        sx_ms = sy_ms = self.gw.config.get("heartbeat_ms", 10_000)
        self._hb_out = max(sx_ms, cy_ms) / 1e3 if sx_ms and cy_ms else 0.0
        self._hb_in = max(sy_ms, cx_ms) / 1e3 if sy_ms and cx_ms else 0.0
        old = self.gw.cm.open(clientid, self)
        if old is not None:
            await old.shutdown("discarded")
        self.session = GwSession(
            self.gw.name, self.gw.broker, self.gw.hooks, info, self._deliver
        )
        self.session.open()
        self.connected = True
        self.send(
            StompFrame(
                "CONNECTED",
                {
                    "version": self.version,
                    "heart-beat": f"{sx_ms},{sy_ms}",
                    "server": "emqx-tpu-stomp",
                    "session": self.session.sid,
                },
            )
        )
        if self._hb_in or self._hb_out:
            self._hb_task = asyncio.get_running_loop().create_task(
                self._heartbeat_loop()
            )

    _on_stomp = _on_connect

    async def _on_send(self, f: StompFrame) -> None:
        txn = f.headers.get("transaction")
        if txn is not None:
            buf = self.txns.get(txn)
            if buf is None:
                self.send_error(f"unknown transaction {txn}")
                return
            buf.append(f)
            self._maybe_receipt(f)
            return
        await self._do_send(f)
        self._maybe_receipt(f)

    async def _do_send(self, f: StompFrame) -> None:
        dest = f.headers.get("destination")
        if not dest:
            self.send_error("SEND requires destination")
            return
        props = {}
        if "content-type" in f.headers:
            props["Content-Type"] = f.headers["content-type"]
        try:
            qos = min(max(int(f.headers.get("qos", 0)), 0), 2)
        except ValueError:
            self.send_error("bad qos header")
            return
        r = self.session.publish(dest, f.body, qos=qos, properties=props)
        res = await r
        if asyncio.isfuture(res):
            await res

    async def _on_subscribe(self, f: StompFrame) -> None:
        sub_id = f.headers.get("id")
        dest = f.headers.get("destination")
        if self.version != "1.0" and sub_id is None:
            self.send_error("SUBSCRIBE requires id")
            return
        sub_id = sub_id or dest
        if not dest:
            self.send_error("SUBSCRIBE requires destination")
            return
        if sub_id in self.subs:
            self.send_error(f"subscription id {sub_id} in use")
            return
        ack = f.headers.get("ack", "auto")
        self.subs[sub_id] = (dest, ack)
        n = self._dest_refs.get(dest, 0)
        self._dest_refs[dest] = n + 1
        if n == 0:  # first id on this destination opens the broker route
            qos = 1 if ack in ("client", "client-individual") else 0
            self.session.subscribe(dest, pkt.SubOpts(qos=qos))
        self._maybe_receipt(f)

    async def _on_unsubscribe(self, f: StompFrame) -> None:
        sub_id = f.headers.get("id") or f.headers.get("destination")
        ent = self.subs.pop(sub_id, None)
        if ent is not None:
            dest, _ = ent
            n = self._dest_refs.get(dest, 1) - 1
            if n <= 0:  # last id on this destination closes the route
                self._dest_refs.pop(dest, None)
                self.session.unsubscribe(dest)
            else:
                self._dest_refs[dest] = n
        self._maybe_receipt(f)

    async def _on_ack(self, f: StompFrame) -> None:
        txn = f.headers.get("transaction")
        if txn is not None and txn in self.txns:
            self.txns[txn].append(f)
        self._maybe_receipt(f)

    async def _on_nack(self, f: StompFrame) -> None:
        self._maybe_receipt(f)

    async def _on_begin(self, f: StompFrame) -> None:
        txn = f.headers.get("transaction")
        if txn is None or txn in self.txns:
            self.send_error("bad transaction")
            return
        self.txns[txn] = []
        self._maybe_receipt(f)

    async def _on_commit(self, f: StompFrame) -> None:
        txn = f.headers.get("transaction")
        buf = self.txns.pop(txn, None)
        if buf is None:
            self.send_error(f"unknown transaction {txn}")
            return
        for queued in buf:
            if queued.command == "SEND":
                await self._do_send(queued)
        self._maybe_receipt(f)

    async def _on_abort(self, f: StompFrame) -> None:
        txn = f.headers.get("transaction")
        if self.txns.pop(txn, None) is None:
            self.send_error(f"unknown transaction {txn}")
            return
        self._maybe_receipt(f)

    async def _on_disconnect(self, f: StompFrame) -> None:
        self._maybe_receipt(f)
        await self.shutdown("normal")

    # -- delivery ----------------------------------------------------------
    def _deliver(self, msg, opts: pkt.SubOpts) -> None:
        from emqx_tpu.ops import topics as T

        # every subscription id whose destination matches gets its own
        # MESSAGE frame (ids are independent subscriptions in STOMP)
        matched = [
            (sid, ack)
            for sid, (dest, ack) in self.subs.items()
            if dest == msg.topic or T.match(msg.topic, dest)
        ] or [("", "auto")]
        ct = msg.properties.get("Content-Type")
        for sub_id, ack_mode in matched:
            self._msg_seq += 1
            headers = {
                "subscription": sub_id,
                "message-id": f"{self.session.sid}-{self._msg_seq}",
                "destination": msg.topic,
            }
            if ack_mode in ("client", "client-individual"):
                headers["ack"] = headers["message-id"]
            if ct:
                headers["content-type"] = ct
            self.send(StompFrame("MESSAGE", headers, msg.payload))

    # -- heart-beat / shutdown ---------------------------------------------
    async def _heartbeat_loop(self) -> None:
        try:
            while not self.closing:
                period = min(
                    p for p in (self._hb_out, self._hb_in) if p > 0
                )
                await asyncio.sleep(period)
                now = time.monotonic()
                if self._hb_in and now - self._last_recv > 2 * self._hb_in:
                    await self.shutdown("heartbeat_timeout")
                    return
                if self._hb_out and not self.writer.is_closing():
                    self.writer.write(b"\n")
        except asyncio.CancelledError:
            pass

    async def shutdown(self, reason: str) -> None:
        if self.closing:
            return
        self.closing = True
        if self._hb_task is not None:
            self._hb_task.cancel()
        if self.session is not None:
            self.gw.cm.close(self.session.info.clientid, self)
            self.session.close(reason)
        try:
            self.writer.close()
        except Exception:
            pass


class StompGateway(Gateway):
    """STOMP listener + per-connection channels (emqx_gateway_impl)."""

    def __init__(self, name: str, config: Dict):
        super().__init__(name, config)
        self._server: Optional[asyncio.AbstractServer] = None
        self._chans: set = set()

    async def start(self) -> None:
        host = self.config.get("bind", "127.0.0.1")
        port = self.config.get("port", 61613)

        async def on_conn(reader, writer):
            peer = writer.get_extra_info("peername") or ("", 0)
            chan = StompChannel(self, writer, peer)
            self._chans.add(chan)
            try:
                while True:
                    data = await reader.read(4096)
                    if not data:
                        break
                    await chan.handle_data(data)
                    if chan.closing:
                        break
            except (ConnectionError, asyncio.IncompleteReadError):
                pass
            finally:
                await chan.shutdown("sock_closed")
                self._chans.discard(chan)

        self._server = await asyncio.start_server(on_conn, host, port)
        self.port = self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        for chan in list(self._chans):
            await chan.shutdown("gateway_stopped")
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
