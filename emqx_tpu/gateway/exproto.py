"""exproto gateway: bring-your-own-protocol over gRPC.

The user implements a `ConnectionHandler` gRPC service (their protocol
logic); this gateway owns raw TCP/UDP listeners and, per connection:

- streams socket lifecycle + received bytes to the handler
  (OnSocketCreated/OnReceivedBytes/OnSocketClosed, client-streaming RPCs)
- exposes a `ConnectionAdapter` gRPC service the handler calls back into:
  Send / Close / Authenticate / StartTimer / Publish / Subscribe /
  Unsubscribe, keyed by the conn id we handed it
- delivers broker messages to the handler via OnReceivedMessages

Wire-compatible with the reference's exproto
(apps/emqx_gateway/src/exproto/protos/exproto.proto:23,46): same package
`emqx.exproto.v1`, services, and message layout — a handler binary built
against the reference attaches unchanged. Like exhook, the stubs are
assembled from grpc-core primitives (no grpc_tools in this toolchain).
"""

from __future__ import annotations

import asyncio
import logging
import time
import uuid
from typing import Dict, Optional

import grpc
import grpc.aio

from emqx_tpu.gateway import exproto_pb2 as pb
from emqx_tpu.gateway.base import Gateway, GwClientInfo, GwSession
from emqx_tpu.mqtt import packet as pkt

log = logging.getLogger("emqx_tpu.gateway.exproto")

ADAPTER_SERVICE = "emqx.exproto.v1.ConnectionAdapter"
HANDLER_SERVICE = "emqx.exproto.v1.ConnectionHandler"

ADAPTER_METHODS = {
    "Send": (pb.SendBytesRequest, pb.CodeResponse),
    "Close": (pb.CloseSocketRequest, pb.CodeResponse),
    "Authenticate": (pb.AuthenticateRequest, pb.CodeResponse),
    "StartTimer": (pb.TimerRequest, pb.CodeResponse),
    "Publish": (pb.PublishRequest, pb.CodeResponse),
    "Subscribe": (pb.SubscribeRequest, pb.CodeResponse),
    "Unsubscribe": (pb.UnsubscribeRequest, pb.CodeResponse),
}

HANDLER_METHODS = {
    "OnSocketCreated": pb.SocketCreatedRequest,
    "OnSocketClosed": pb.SocketClosedRequest,
    "OnReceivedBytes": pb.ReceivedBytesRequest,
    "OnTimerTimeout": pb.TimerTimeoutRequest,
    "OnReceivedMessages": pb.ReceivedMessagesRequest,
}


class _HandlerClient:
    """Client-streaming pushes to the user's ConnectionHandler service.

    One long-lived stream per RPC (the reference holds streams open the
    same way); events are queued and forwarded by a pump task per stream.
    A stream that errors (handler restart) is torn down so the NEXT push
    re-opens it — events queued while the handler is down are bounded by
    QUEUE_MAX and the oldest are dropped, not hoarded.
    """

    QUEUE_MAX = 10_000

    def __init__(self, target: str):
        self._channel = grpc.aio.insecure_channel(target)
        self._queues: Dict[str, asyncio.Queue] = {}
        self._tasks: Dict[str, asyncio.Task] = {}

    def _stream(self, rpc: str):
        q = self._queues.get(rpc)
        if q is None:
            q = asyncio.Queue()
            self._queues[rpc] = q
            req_cls = HANDLER_METHODS[rpc]
            method = self._channel.stream_unary(
                f"/{HANDLER_SERVICE}/{rpc}",
                request_serializer=req_cls.SerializeToString,
                response_deserializer=pb.EmptySuccess.FromString,
            )

            async def gen():
                while True:
                    item = await q.get()
                    if item is None:
                        return
                    yield item

            async def pump():
                try:
                    await method(gen())
                except grpc.aio.AioRpcError as e:
                    log.warning("exproto handler stream %s: %s", rpc, e.code())
                finally:
                    # drop the dead stream so the next push re-opens it
                    if self._queues.get(rpc) is q:
                        del self._queues[rpc]
                        self._tasks.pop(rpc, None)

            self._tasks[rpc] = asyncio.get_running_loop().create_task(pump())
        return q

    def push(self, rpc: str, msg) -> None:
        q = self._stream(rpc)
        while q.qsize() >= self.QUEUE_MAX:
            q.get_nowait()  # shed oldest under backpressure
        q.put_nowait(msg)

    async def close(self) -> None:
        # snapshot: pump teardown mutates these dicts as streams finish
        for q in list(self._queues.values()):
            q.put_nowait(None)
        for t in list(self._tasks.values()):
            try:
                await asyncio.wait_for(t, timeout=1.0)
            except (asyncio.TimeoutError, Exception):
                t.cancel()
        await self._channel.close()


class _ExprotoConn:
    """One raw socket under exproto management."""

    def __init__(self, gw: "ExprotoGateway", writer, peer, sock):
        self.gw = gw
        self.conn_id = uuid.uuid4().hex
        self.writer = writer
        self.peer = peer
        self.sock = sock
        self.session: Optional[GwSession] = None
        self.clientid: Optional[str] = None
        self.keepalive_task: Optional[asyncio.Task] = None
        self.keepalive_interval: int = 0
        self.keepalive_deadline: Optional[float] = None
        self.closed = False

    def touch(self) -> None:
        """Inbound traffic extends the keepalive deadline."""
        if self.keepalive_interval:
            self.keepalive_deadline = (
                time.monotonic() + 2 * self.keepalive_interval
            )

    def conninfo(self) -> pb.ConnInfo:
        return pb.ConnInfo(
            socktype=pb.TCP,
            peername=pb.Address(host=self.peer[0], port=self.peer[1]),
            sockname=pb.Address(host=self.sock[0], port=self.sock[1]),
        )

    async def close(self, reason: str = "normal") -> None:
        if self.closed:
            return
        self.closed = True
        if self.keepalive_task is not None:
            self.keepalive_task.cancel()
        if self.session is not None:
            self.gw.cm.close(self.clientid, self)
            self.session.close(reason)
        self.gw.handler.push(
            "OnSocketClosed",
            pb.SocketClosedRequest(conn=self.conn_id, reason=reason),
        )
        self.gw.conns.pop(self.conn_id, None)
        try:
            self.writer.close()
        except Exception:
            pass

    def deliver(self, msg, opts: pkt.SubOpts) -> None:
        self.gw.handler.push(
            "OnReceivedMessages",
            pb.ReceivedMessagesRequest(
                conn=self.conn_id,
                messages=[
                    pb.Message(
                        node=self.gw.config.get("node", "emqx_tpu@local"),
                        id=str(msg.mid),
                        qos=min(msg.qos, opts.qos),
                        topic=msg.topic,
                        payload=msg.payload,
                        timestamp=int(msg.timestamp * 1000),
                        **{"from": msg.from_client},
                    )
                ],
            ),
        )


class ExprotoGateway(Gateway):
    """TCP listener + ConnectionAdapter service + handler streams."""

    def __init__(self, name: str, config: Dict):
        super().__init__(name, config)
        self.conns: Dict[str, _ExprotoConn] = {}
        self.handler: Optional[_HandlerClient] = None
        self._server: Optional[asyncio.AbstractServer] = None
        self._grpc_server: Optional[grpc.aio.Server] = None

    # -- ConnectionAdapter service ----------------------------------------
    def _adapter_handlers(self):
        def ok():
            return pb.CodeResponse(code=pb.SUCCESS)

        def fail(code, msg=""):
            return pb.CodeResponse(code=code, message=msg)

        def need_conn(fn):
            async def wrapped(req, ctx):
                conn = self.conns.get(req.conn)
                if conn is None or conn.closed:
                    return fail(pb.CONN_PROCESS_NOT_ALIVE, "no such conn")
                return await fn(req, conn)

            return wrapped

        @need_conn
        async def send(req, conn):
            conn.writer.write(req.bytes)
            return ok()

        @need_conn
        async def close(req, conn):
            await conn.close("adapter_close")
            return ok()

        @need_conn
        async def authenticate(req, conn):
            ci = req.clientinfo
            if not ci.clientid:
                return fail(pb.REQUIRED_PARAMS_MISSED, "clientid required")
            info = GwClientInfo(
                clientid=ci.clientid,
                username=ci.username or None,
                peername=conn.peer,
                protocol=ci.proto_name or "exproto",
                mountpoint=ci.mountpoint or self.config.get("mountpoint"),
            )
            res = await self.hooks.arun_fold(
                "client.authenticate",
                (info.as_dict(),),
                {"ok": True, "password": req.password},
            )
            if res is not None and res.get("ok") is False:
                return fail(pb.PERMISSION_DENY, "authentication failed")
            old = self.cm.open(ci.clientid, conn)
            if old is not None and old is not conn:
                await old.close("discarded")
            conn.clientid = ci.clientid
            conn.session = GwSession(
                self.name, self.broker, self.hooks, info, conn.deliver
            )
            conn.session.open()
            return ok()

        @need_conn
        async def start_timer(req, conn):
            if req.type != pb.KEEPALIVE or req.interval == 0:
                return fail(pb.PARAMS_TYPE_ERROR, "bad timer")
            conn.keepalive_interval = req.interval
            conn.touch()
            if conn.keepalive_task is None:
                conn.keepalive_task = asyncio.get_running_loop().create_task(
                    self._keepalive_loop(conn, req.interval)
                )
            return ok()

        @need_conn
        async def publish(req, conn):
            if conn.session is None:
                return fail(pb.PERMISSION_DENY, "not authenticated")
            r = conn.session.publish(req.topic, req.payload, qos=req.qos)
            res = await r
            if asyncio.isfuture(res):
                await res
            return ok()

        @need_conn
        async def subscribe(req, conn):
            if conn.session is None:
                return fail(pb.PERMISSION_DENY, "not authenticated")
            conn.session.subscribe(req.topic, pkt.SubOpts(qos=min(req.qos, 2)))
            return ok()

        @need_conn
        async def unsubscribe(req, conn):
            if conn.session is None:
                return fail(pb.PERMISSION_DENY, "not authenticated")
            conn.session.unsubscribe(req.topic)
            return ok()

        impls = {
            "Send": send,
            "Close": close,
            "Authenticate": authenticate,
            "StartTimer": start_timer,
            "Publish": publish,
            "Subscribe": subscribe,
            "Unsubscribe": unsubscribe,
        }
        handlers = {}
        for rpc, (req_cls, resp_cls) in ADAPTER_METHODS.items():
            handlers[rpc] = grpc.unary_unary_rpc_method_handler(
                impls[rpc],
                request_deserializer=req_cls.FromString,
                response_serializer=resp_cls.SerializeToString,
            )
        return grpc.method_handlers_generic_handler(ADAPTER_SERVICE, handlers)

    async def _keepalive_loop(self, conn: _ExprotoConn, interval: int) -> None:
        try:
            while not conn.closed:
                await asyncio.sleep(interval)
                if (
                    conn.keepalive_deadline is not None
                    and time.monotonic() > conn.keepalive_deadline
                ):
                    self.handler.push(
                        "OnTimerTimeout",
                        pb.TimerTimeoutRequest(
                            conn=conn.conn_id, type=pb.KEEPALIVE
                        ),
                    )
                    await conn.close("keepalive_timeout")
                    return
        except asyncio.CancelledError:
            pass

    # -- lifecycle ----------------------------------------------------------
    async def start(self) -> None:
        target = self.config["handler"]  # e.g. "127.0.0.1:9100"
        self.handler = _HandlerClient(target)

        self._grpc_server = grpc.aio.server()
        self._grpc_server.add_generic_rpc_handlers((self._adapter_handlers(),))
        adapter_bind = self.config.get("adapter_bind", "127.0.0.1:0")
        self.adapter_port = self._grpc_server.add_insecure_port(adapter_bind)
        await self._grpc_server.start()

        async def on_conn(reader, writer):
            peer = writer.get_extra_info("peername") or ("", 0)
            sock = writer.get_extra_info("sockname") or ("", 0)
            conn = _ExprotoConn(self, writer, peer, sock)
            self.conns[conn.conn_id] = conn
            self.handler.push(
                "OnSocketCreated",
                pb.SocketCreatedRequest(
                    conn=conn.conn_id, conninfo=conn.conninfo()
                ),
            )
            try:
                while True:
                    data = await reader.read(4096)
                    if not data:
                        break
                    conn.touch()
                    self.handler.push(
                        "OnReceivedBytes",
                        pb.ReceivedBytesRequest(conn=conn.conn_id, bytes=data),
                    )
            except ConnectionError:
                pass
            finally:
                await conn.close("sock_closed")

        host = self.config.get("bind", "127.0.0.1")
        port = self.config.get("port", 7993)
        self._server = await asyncio.start_server(on_conn, host, port)
        self.port = self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        for conn in list(self.conns.values()):
            await conn.close("gateway_stopped")
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        if self._grpc_server is not None:
            await self._grpc_server.stop(grace=0.5)
        if self.handler is not None:
            await self.handler.close()
