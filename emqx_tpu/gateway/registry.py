"""Gateway registry + gateway-local client manager.

Parity: emqx_gateway.erl:22-61 (registry: registered gateway types,
load/unload/start/stop instances, status) and emqx_gateway_cm.erl (each
gateway keeps its OWN clientid->channel table, separate from the MQTT
CM — a STOMP client and an MQTT client may share a clientid without
kicking each other).
"""

from __future__ import annotations

import logging
from typing import Callable, Dict, List, Optional

log = logging.getLogger("emqx_tpu.gateway")


class GatewayCM:
    """Per-gateway client manager: clientid -> channel, with the same
    discard-on-duplicate semantics the core CM applies
    (emqx_gateway_cm.erl open_session clean_start path)."""

    def __init__(self, gw_name: str):
        self.gw = gw_name
        self._chans: Dict[str, object] = {}

    def open(self, clientid: str, chan: object) -> Optional[object]:
        """Register; returns the displaced old channel (caller kicks it)."""
        old = self._chans.pop(clientid, None)
        self._chans[clientid] = chan
        return old

    def close(self, clientid: str, chan: object) -> None:
        if self._chans.get(clientid) is chan:
            del self._chans[clientid]

    def get(self, clientid: str) -> Optional[object]:
        return self._chans.get(clientid)

    def count(self) -> int:
        return len(self._chans)

    def clients(self) -> List[str]:
        return list(self._chans)


class GatewayRegistry:
    """Registered gateway types + running instances
    (emqx_gateway.erl registry + per-gateway supervision tree)."""

    def __init__(self, broker, hooks, retainer=None, psk=None):
        self.broker = broker
        self.hooks = hooks
        self.retainer = retainer
        self.psk = psk  # broker-wide PSK store (dtls listeners)
        self._types: Dict[str, Callable] = {}  # type name -> Gateway class
        self._running: Dict[str, object] = {}  # instance name -> Gateway

    def register_type(self, type_name: str, factory: Callable) -> None:
        self._types[type_name] = factory

    def types(self) -> List[str]:
        return list(self._types)

    async def load(self, type_name: str, config: Dict, name: Optional[str] = None):
        """Create + start a gateway instance (emqx_gateway:load/2)."""
        if type_name not in self._types:
            raise ValueError(f"unknown gateway type: {type_name}")
        name = name or type_name
        if name in self._running:
            raise ValueError(f"gateway already loaded: {name}")
        gw = self._types[type_name](name, config)
        gw.cm = GatewayCM(name)
        gw.broker = self.broker
        gw.hooks = self.hooks
        gw.retainer = self.retainer
        gw.psk_store = self.psk
        await gw.start()
        self._running[name] = gw
        log.info("gateway %s (%s) started", name, type_name)
        return gw

    async def unload(self, name: str) -> bool:
        gw = self._running.pop(name, None)
        if gw is None:
            return False
        await gw.stop()
        log.info("gateway %s stopped", name)
        return True

    async def unload_all(self) -> None:
        for name in list(self._running):
            await self.unload(name)

    def get(self, name: str):
        return self._running.get(name)

    def list(self) -> List[Dict]:
        return [gw.status() for gw in self._running.values()]
