"""Multi-protocol gateway framework.

TPU-stack analog of the reference's largest app, `apps/emqx_gateway`
(19.7k LoC): a registry of pluggable protocol gateways, generic behaviours
(frame codec / channel / connection), a gateway-local client manager, and
protocol implementations (STOMP, MQTT-SN, exproto gRPC bring-your-own-
protocol). Gateway clients bridge into the core broker through
`GwSession` — subscribe/publish with per-gateway mountpoints, exactly how
the reference's gateways attach via hooks + emqx_broker
(apps/emqx_gateway/src/emqx_gateway.erl:22-61, src/bhvrs/).
"""

from emqx_tpu.gateway.base import Gateway, GwClientInfo, GwSession
from emqx_tpu.gateway.registry import GatewayCM, GatewayRegistry

__all__ = [
    "Gateway",
    "GwClientInfo",
    "GwSession",
    "GatewayCM",
    "GatewayRegistry",
]
