"""MQTT-SN v1.2 gateway (UDP).

Parity with the reference's MQTT-SN gateway (apps/emqx_gateway/src/mqttsn/:
emqx_sn_frame.erl codec, channel + registry semantics):

- CONNECT/CONNACK over datagrams; one channel per peer address
- topic registry: REGISTER/REGACK map topic names <-> 16-bit topic ids,
  per-client (emqx_sn_registry.erl); predefined topic ids from config;
  2-char short topic names inline
- PUBLISH QoS 0/1/2 (+ QoS -1 "publish without connect" to predefined
  topics), PUBACK/PUBREC/PUBREL/PUBCOMP
- SUBSCRIBE/UNSUBSCRIBE by name, id, or short name; SUBACK assigns ids;
  wildcard subscriptions get topic ids lazily via server REGISTER on
  first delivery
- PINGREQ/PINGRESP keepalive; DISCONNECT with duration = sleeping client
  (messages buffered, flushed on the wake-up PINGREQ)
- ADVERTISE/SEARCHGW/GWINFO discovery responses
"""

from __future__ import annotations

import asyncio
import logging
import struct
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from emqx_tpu.gateway.base import Gateway, GwClientInfo, GwSession
from emqx_tpu.transport.dtls import DtlsUdpGatewayMixin
from emqx_tpu.mqtt import packet as pkt
from emqx_tpu.ops import topics as T

log = logging.getLogger("emqx_tpu.gateway.mqttsn")

# message types (MQTT-SN v1.2 §5.2.2)
ADVERTISE = 0x00
SEARCHGW = 0x01
GWINFO = 0x02
CONNECT = 0x04
CONNACK = 0x05
WILLTOPICREQ = 0x06
WILLTOPIC = 0x07
WILLMSGREQ = 0x08
WILLMSG = 0x09
REGISTER = 0x0A
REGACK = 0x0B
PUBLISH = 0x0C
PUBACK = 0x0D
PUBCOMP = 0x0E
PUBREC = 0x0F
PUBREL = 0x10
SUBSCRIBE = 0x12
SUBACK = 0x13
UNSUBSCRIBE = 0x14
UNSUBACK = 0x15
PINGREQ = 0x16
PINGRESP = 0x17
DISCONNECT = 0x18

# flags
FLAG_DUP = 0x80
FLAG_QOS_MASK = 0x60
FLAG_RETAIN = 0x10
FLAG_WILL = 0x08
FLAG_CLEAN = 0x04
FLAG_TOPIC_MASK = 0x03
TOPIC_NORMAL = 0x00
TOPIC_PREDEF = 0x01
TOPIC_SHORT = 0x02

RC_ACCEPTED = 0x00
RC_CONGESTION = 0x01
RC_INVALID_TOPIC_ID = 0x02
RC_NOT_SUPPORTED = 0x03

QOS_NEG1 = 3  # flag value 0b11: QoS -1


def qos_from_flags(flags: int) -> int:
    return (flags & FLAG_QOS_MASK) >> 5


def flags_from(qos: int = 0, retain: bool = False, dup: bool = False,
               topic_type: int = TOPIC_NORMAL, clean: bool = False,
               will: bool = False) -> int:
    return (
        (FLAG_DUP if dup else 0)
        | ((qos & 3) << 5)
        | (FLAG_RETAIN if retain else 0)
        | (FLAG_WILL if will else 0)
        | (FLAG_CLEAN if clean else 0)
        | (topic_type & FLAG_TOPIC_MASK)
    )


@dataclass
class SnFrame:
    type: int
    # decoded fields, per type
    fields: Dict = field(default_factory=dict)


def encode(type_: int, body: bytes) -> bytes:
    n = len(body) + 2
    if n + 2 > 255:
        return struct.pack("!BHB", 0x01, n + 2, type_) + body
    return struct.pack("!BB", n, type_) + body


def decode(data: bytes) -> Optional[SnFrame]:
    if len(data) < 2:
        return None
    if data[0] == 0x01:
        if len(data) < 4:
            return None
        length = struct.unpack("!H", data[1:3])[0]
        type_ = data[3]
        body = data[4:length]
    else:
        length = data[0]
        type_ = data[1]
        body = data[2:length]
    f = SnFrame(type_)
    d = f.fields
    try:
        if type_ == CONNECT:
            d["flags"], d["protocol_id"] = body[0], body[1]
            d["duration"] = struct.unpack("!H", body[2:4])[0]
            d["client_id"] = body[4:].decode("utf-8")
        elif type_ == CONNACK:
            d["rc"] = body[0]
        elif type_ == SEARCHGW:
            d["radius"] = body[0]
        elif type_ in (REGISTER,):
            d["topic_id"] = struct.unpack("!H", body[0:2])[0]
            d["msg_id"] = struct.unpack("!H", body[2:4])[0]
            d["topic"] = body[4:].decode("utf-8")
        elif type_ in (REGACK, PUBACK):
            d["topic_id"] = struct.unpack("!H", body[0:2])[0]
            d["msg_id"] = struct.unpack("!H", body[2:4])[0]
            d["rc"] = body[4]
        elif type_ == PUBLISH:
            d["flags"] = body[0]
            d["topic_id"] = struct.unpack("!H", body[1:3])[0]
            d["topic_raw"] = body[1:3]
            d["msg_id"] = struct.unpack("!H", body[3:5])[0]
            d["payload"] = body[5:]
        elif type_ in (PUBREC, PUBREL, PUBCOMP):
            d["msg_id"] = struct.unpack("!H", body[0:2])[0]
        elif type_ in (SUBSCRIBE, UNSUBSCRIBE):
            d["flags"] = body[0]
            d["msg_id"] = struct.unpack("!H", body[1:3])[0]
            tt = body[0] & FLAG_TOPIC_MASK
            if tt in (TOPIC_PREDEF,):
                d["topic_id"] = struct.unpack("!H", body[3:5])[0]
            elif tt == TOPIC_SHORT:
                d["topic"] = body[3:5].decode("utf-8")
            else:
                d["topic"] = body[3:].decode("utf-8")
        elif type_ == SUBACK:
            d["flags"] = body[0]
            d["topic_id"] = struct.unpack("!H", body[1:3])[0]
            d["msg_id"] = struct.unpack("!H", body[3:5])[0]
            d["rc"] = body[5]
        elif type_ == UNSUBACK:
            d["msg_id"] = struct.unpack("!H", body[0:2])[0]
        elif type_ == PINGREQ:
            d["client_id"] = body.decode("utf-8") if body else ""
        elif type_ == DISCONNECT:
            d["duration"] = (
                struct.unpack("!H", body[0:2])[0] if len(body) >= 2 else None
            )
        elif type_ == WILLTOPIC:
            if body:
                d["flags"] = body[0]
                d["topic"] = body[1:].decode("utf-8")
        elif type_ == WILLMSG:
            d["payload"] = body
    except (IndexError, struct.error, UnicodeDecodeError):
        return None
    return f


class SnTopicRegistry:
    """Per-client topic-name <-> topic-id map (emqx_sn_registry.erl)."""

    def __init__(self, predefined: Dict[int, str]):
        self.predefined = dict(predefined)
        self._pre_rev = {v: k for k, v in predefined.items()}
        self._by_id: Dict[int, str] = {}
        self._by_name: Dict[str, int] = {}
        self._next = 0x0100  # ids below are reserved for predefined

    def register(self, topic: str) -> int:
        tid = self._by_name.get(topic) or self._pre_rev.get(topic)
        if tid is not None:
            return tid
        tid = self._next
        self._next += 1
        self._by_id[tid] = topic
        self._by_name[topic] = tid
        return tid

    def lookup_id(self, tid: int) -> Optional[str]:
        return self._by_id.get(tid) or self.predefined.get(tid)

    def lookup_name(self, topic: str) -> Optional[int]:
        return self._by_name.get(topic) or self._pre_rev.get(topic)


class SnChannel:
    """One MQTT-SN client (keyed by UDP peer address)."""

    AWAKE_FLUSH_MAX = 100

    def __init__(self, gw: "SnGateway", peer: Tuple[str, int]):
        self.gw = gw
        self.peer = peer
        self.session: Optional[GwSession] = None
        self.reg = SnTopicRegistry(gw.predefined)
        self.connected = False
        self.client_id = ""
        self.keepalive = 0
        self.last_seen = time.monotonic()
        self._msg_seq = 0
        # sleeping-client buffer (DISCONNECT with duration)
        self.asleep = False
        self.sleep_until = 0.0
        self.sleep_duration = 0
        self._sleep_buf: List = []
        # QoS1 pending: msg_id -> (topic_id, payload) for retransmit-free ack
        self._in_qos2: Dict[int, object] = {}
        self.will_topic: Optional[str] = None
        self.will_msg: bytes = b""
        self._pending_connack = False
        # frames of one channel are handled strictly in order by a single
        # worker (a client pipelines CONNECT then SUBSCRIBE in back-to-back
        # datagrams; concurrent handling would race the handshake). The
        # worker task reference lives here — the loop only keeps weak refs.
        self._inbox: asyncio.Queue = asyncio.Queue()
        self._worker: Optional[asyncio.Task] = None

    def enqueue(self, f: SnFrame) -> None:
        self._inbox.put_nowait(f)
        if self._worker is None:
            self._worker = asyncio.get_running_loop().create_task(self._run())

    async def _run(self) -> None:
        try:
            while True:
                try:
                    f = await asyncio.wait_for(self._inbox.get(), timeout=30.0)
                except asyncio.TimeoutError:
                    if self.gw._chans.get(self.peer) is not self:
                        return  # orphaned (dropped/reaped): stop idling
                    continue
                try:
                    await self.handle(f)
                except Exception:
                    log.exception("mqttsn frame handling failed")
                # anonymous peers (QoS -1 publishers, stray frames) must
                # not accumulate channel state on an open UDP port
                if (
                    not self.connected
                    and not self._pending_connack
                    and self.session is None
                    and self._inbox.empty()
                ):
                    self.gw.forget(self.peer)
                    return
        except asyncio.CancelledError:
            pass

    def _send(self, type_: int, body: bytes) -> None:
        self.gw.sendto(encode(type_, body), self.peer)

    def _next_msg_id(self) -> int:
        self._msg_seq = self._msg_seq % 0xFFFF + 1
        return self._msg_seq

    # -- incoming ----------------------------------------------------------
    async def handle(self, f: SnFrame) -> None:
        self.last_seen = time.monotonic()
        d = f.fields
        if f.type == CONNECT:
            await self._on_connect(d)
        elif f.type == WILLTOPIC:
            self.will_topic = d.get("topic")
            self._send(WILLMSGREQ, b"")
        elif f.type == WILLMSG:
            self.will_msg = d.get("payload", b"")
            if self._pending_connack:
                self._finish_connect()
        elif f.type == REGISTER:
            tid = self.reg.register(d["topic"])
            self._send(
                REGACK,
                struct.pack("!HHB", tid, d["msg_id"], RC_ACCEPTED),
            )
        elif f.type == REGACK:
            pass  # client confirmed our server-side REGISTER
        elif f.type == PUBLISH:
            await self._on_publish(d)
        elif f.type == PUBACK:
            pass  # QoS1 delivery confirmed (no retransmit queue yet)
        elif f.type == PUBREC:
            self._send(PUBREL, struct.pack("!H", d["msg_id"]))
        elif f.type == PUBREL:
            msg = self._in_qos2.pop(d["msg_id"], None)
            if msg is not None:
                r = self.session.publish(*msg)
                res = await r
                if asyncio.isfuture(res):
                    await res
            self._send(PUBCOMP, struct.pack("!H", d["msg_id"]))
        elif f.type == PUBCOMP:
            pass
        elif f.type == SUBSCRIBE:
            await self._on_subscribe(d)
        elif f.type == UNSUBSCRIBE:
            await self._on_unsubscribe(d)
        elif f.type == PINGREQ:
            if self.asleep:
                self._flush_sleep_buffer()
                # back to sleep for another cycle (spec: awake ends with
                # PINGRESP; client re-sleeps for its negotiated duration)
                self.sleep_until = time.monotonic() + 2 * self.sleep_duration
            self._send(PINGRESP, b"")
        elif f.type == DISCONNECT:
            await self._on_disconnect(d)

    async def _on_connect(self, d: Dict) -> None:
        self.client_id = d["client_id"] or f"sn-{self.peer[0]}-{self.peer[1]}"
        self.keepalive = d["duration"]
        clean = bool(d["flags"] & FLAG_CLEAN)
        info = GwClientInfo(
            clientid=self.client_id,
            peername=self.peer,
            protocol="mqtt-sn",
            mountpoint=self.gw.config.get("mountpoint"),
            keepalive=self.keepalive,
            clean_start=clean,
        )
        ok = await self.gw.authenticate(info)
        if not ok:
            self._send(CONNACK, bytes([RC_NOT_SUPPORTED]))
            return
        old = self.gw.cm.open(self.client_id, self)
        if old is not None and old is not self:
            old.drop("discarded")
        if self.session is not None:
            self.session.close("reconnect")
        self.session = GwSession(
            self.gw.name, self.gw.broker, self.gw.hooks, info, self._deliver
        )
        self.asleep = False
        if d["flags"] & FLAG_WILL:
            self._pending_connack = True
            self._send(WILLTOPICREQ, b"")
            return
        self._finish_connect()

    def _finish_connect(self) -> None:
        self._pending_connack = False
        self.session.open()
        self.connected = True
        self._send(CONNACK, bytes([RC_ACCEPTED]))

    def _resolve_topic(self, d: Dict) -> Optional[str]:
        tt = d["flags"] & FLAG_TOPIC_MASK
        if tt == TOPIC_SHORT:
            return d["topic_raw"].decode("utf-8", "replace")
        if tt == TOPIC_PREDEF:
            return self.gw.predefined.get(d["topic_id"])
        return self.reg.lookup_id(d["topic_id"])

    async def _on_publish(self, d: Dict) -> None:
        qos = qos_from_flags(d["flags"])
        retain = bool(d["flags"] & FLAG_RETAIN)
        if qos == QOS_NEG1:
            # QoS -1: publish without a session, predefined/short topics only
            topic = None
            tt = d["flags"] & FLAG_TOPIC_MASK
            if tt == TOPIC_PREDEF:
                topic = self.gw.predefined.get(d["topic_id"])
            elif tt == TOPIC_SHORT:
                topic = d["topic_raw"].decode("utf-8", "replace")
            if topic:
                from emqx_tpu.broker.message import Message

                await_r = self.gw.broker.apublish_enqueue(
                    Message(topic=topic, payload=d["payload"], qos=0,
                            retain=retain, from_client=self.client_id or "sn-anon")
                )
                res = await await_r
                if asyncio.isfuture(res):
                    await res
            return
        if not self.connected:
            return
        topic = self._resolve_topic(d)
        if topic is None:
            self._send(
                PUBACK,
                struct.pack("!HHB", d["topic_id"], d["msg_id"], RC_INVALID_TOPIC_ID),
            )
            return
        if qos == 2:
            self._in_qos2[d["msg_id"]] = (topic, d["payload"], 2, retain)
            self._send(PUBREC, struct.pack("!H", d["msg_id"]))
            return
        r = self.session.publish(topic, d["payload"], qos=qos, retain=retain)
        res = await r
        if asyncio.isfuture(res):
            await res
        if qos == 1:
            self._send(
                PUBACK,
                struct.pack("!HHB", d["topic_id"], d["msg_id"], RC_ACCEPTED),
            )

    async def _on_subscribe(self, d: Dict) -> None:
        if not self.connected:
            return
        qos = min(qos_from_flags(d["flags"]), 1)
        tt = d["flags"] & FLAG_TOPIC_MASK
        if tt == TOPIC_PREDEF:
            topic = self.gw.predefined.get(d.get("topic_id", 0))
            tid = d.get("topic_id", 0)
        else:
            topic = d.get("topic")
            tid = 0
            if topic and not T.wildcard(topic) and tt == TOPIC_NORMAL:
                tid = self.reg.register(topic)
        if not topic:
            self._send(
                SUBACK,
                struct.pack(
                    "!BHHB", flags_from(qos=qos), 0, d["msg_id"],
                    RC_INVALID_TOPIC_ID,
                ),
            )
            return
        self.session.subscribe(topic, pkt.SubOpts(qos=qos))
        self._send(
            SUBACK,
            struct.pack("!BHHB", flags_from(qos=qos), tid, d["msg_id"], RC_ACCEPTED),
        )

    async def _on_unsubscribe(self, d: Dict) -> None:
        tt = d["flags"] & FLAG_TOPIC_MASK
        if tt == TOPIC_PREDEF:
            topic = self.gw.predefined.get(d.get("topic_id", 0))
        else:
            topic = d.get("topic")
        if topic and self.session:
            self.session.unsubscribe(topic)
        self._send(UNSUBACK, struct.pack("!H", d["msg_id"]))

    async def _on_disconnect(self, d: Dict) -> None:
        duration = d.get("duration")
        if duration:
            # sleeping client: keep session + subscriptions, buffer deliveries
            self.asleep = True
            self.sleep_duration = duration
            self.sleep_until = time.monotonic() + 2 * duration
            self._send(DISCONNECT, b"")
            return
        self._send(DISCONNECT, b"")
        self.drop("normal")

    def drop(self, reason: str) -> None:
        w = self._worker
        if w is not None and w is not asyncio.current_task():
            w.cancel()  # reaper/shutdown path; self-drop exits via forget
        if self.session is not None:
            if reason not in ("normal", "discarded") and self.will_topic:
                self.session.publish_sync(self.will_topic, self.will_msg)
            self.gw.cm.close(self.client_id, self)
            self.session.close(reason)
            self.session = None
        self.connected = False
        self.gw.forget(self.peer)

    # -- delivery ----------------------------------------------------------
    def _deliver(self, msg, opts: pkt.SubOpts) -> None:
        if self.asleep:
            if len(self._sleep_buf) < self.AWAKE_FLUSH_MAX:
                self._sleep_buf.append((msg, opts))
            return
        self._deliver_now(msg, opts)

    def _flush_sleep_buffer(self) -> None:
        buf, self._sleep_buf = self._sleep_buf, []
        for msg, opts in buf:
            self._deliver_now(msg, opts)

    def _deliver_now(self, msg, opts: pkt.SubOpts) -> None:
        qos = min(msg.qos, opts.qos, 1)
        if len(msg.topic) == 2:
            tt, tid_bytes = TOPIC_SHORT, msg.topic.encode()
        else:
            tid = self.reg.lookup_name(msg.topic)
            if tid is None:
                # server-side REGISTER before first delivery on this topic
                tid = self.reg.register(msg.topic)
                self._send(
                    REGISTER,
                    struct.pack("!HH", tid, self._next_msg_id())
                    + msg.topic.encode(),
                )
            tt = (
                TOPIC_PREDEF
                if tid in self.gw.predefined
                else TOPIC_NORMAL
            )
            tid_bytes = struct.pack("!H", tid)
        body = (
            bytes([flags_from(qos=qos, retain=msg.retain, topic_type=tt)])
            + tid_bytes
            + struct.pack("!H", self._next_msg_id() if qos else 0)
            + msg.payload
        )
        self._send(PUBLISH, body)


class SnGateway(DtlsUdpGatewayMixin, Gateway):
    """UDP endpoint + per-peer channels + discovery."""

    def __init__(self, name: str, config: Dict):
        super().__init__(name, config)
        self.predefined: Dict[int, str] = {
            int(k): v for k, v in config.get("predefined", {}).items()
        }
        self.gw_id = config.get("gateway_id", 1)
        self._transport = None
        self._dtls = None  # DtlsEndpoint when transport == "dtls"
        self._chans: Dict[Tuple[str, int], SnChannel] = {}
        self._reaper: Optional[asyncio.Task] = None

    def _plain_datagram(self, data: bytes, addr) -> None:
        f = decode(data)
        if f is None:
            return
        if f.type == SEARCHGW:
            self.sendto(encode(GWINFO, bytes([self.gw_id])), addr)
            return
        chan = self._chans.get(addr)
        if chan is None:
            chan = SnChannel(self, addr)
            self._chans[addr] = chan
        chan.enqueue(f)

    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        # transport: udp | dtls (emqx_gateway_schema.erl:361-371 parity)
        self._init_dtls()
        host = self.config.get("bind", "127.0.0.1")
        port = self.config.get("port", 1884)
        self._endpoint = await loop.create_datagram_endpoint(
            self._make_proto(), local_addr=(host, port)
        )
        self.port = self._endpoint[0].get_extra_info("sockname")[1]
        self._reaper = loop.create_task(self._reap_loop())

    async def _reap_loop(self, period: float = 5.0) -> None:
        """Expire channels whose peer vanished (UDP has no FIN): connected
        clients past 2x their negotiated keepalive get their will published
        and session torn down (emqx_sn keepalive semantics). Sleeping
        clients are exempt until their sleep duration elapses twice."""
        try:
            while True:
                await asyncio.sleep(period)
                now = time.monotonic()
                for chan in list(self._chans.values()):
                    if chan.asleep:
                        if now > chan.sleep_until:
                            chan.drop("sleep_expired")
                        continue
                    ka = chan.keepalive
                    if ka <= 0:
                        continue
                    if now - chan.last_seen > 2 * ka:
                        chan.drop("keepalive_timeout")
        except asyncio.CancelledError:
            pass

    async def stop(self) -> None:
        if self._reaper is not None:
            self._reaper.cancel()
        for chan in list(self._chans.values()):
            chan.drop("gateway_stopped")
        if self._transport is not None:
            self._transport.close()
            self._transport = None
