"""LwM2M gateway: registration lifecycle + device management over CoAP.

Parity with the reference LwM2M gateway
(apps/emqx_gateway/src/lwm2m/: emqx_lwm2m_impl.erl listener/registry,
emqx_lwm2m_channel.erl + emqx_lwm2m_session.erl register/update/
deregister lifecycle and downlink queue, emqx_lwm2m_cmd.erl MQTT-JSON
<-> CoAP command translation; behavior contract in lwm2m README):

- UDP CoAP endpoint (reuses the RFC 7252 codec from gateway/coap.py)
- ``POST /rd?ep=&lt=&lwm2m=`` registers: opens a broker session for the
  endpoint under mountpoint ``lwm2m/{ep}/``, subscribes the downlink
  command topic ``dn/#``, publishes a ``register`` uplink, answers
  2.01 Created with ``Location-Path: rd/<loc>``
- ``POST /rd/<loc>`` updates lifetime/objects (``update`` uplink, 2.04);
  ``DELETE /rd/<loc>`` deregisters (2.02)
- downlink commands are JSON messages on ``dn/#``:
  ``{"reqID": n, "msgType": "read|write|execute|discover|observe|
  cancel-observe|write-attr|create|delete", "data": {...}}`` — each is
  translated to a CoAP request to the device (emqx_lwm2m_cmd.erl
  mqtt_to_coap), retransmitted per RFC 7252, and the device's response
  is published as JSON on ``up/resp`` (coap_to_mqtt)
- observe notifications (Observe seq > 0) are published on
  ``up/notify`` with ``seqNum``
- lifetime expiry reaps the registration (session close + will-style
  disconnect hooks)
"""

from __future__ import annotations

import asyncio
import json
import logging
import secrets
import struct
import time
from typing import Dict, Optional, Tuple

from emqx_tpu.broker.message import Message
from emqx_tpu.gateway import coap as C
from emqx_tpu.gateway import lwm2m_codec as LC
from emqx_tpu.gateway.base import Gateway, GwClientInfo, GwSession
from emqx_tpu.transport.dtls import DtlsUdpGatewayMixin
from emqx_tpu.mqtt import packet as pkt

log = logging.getLogger("emqx_tpu.gateway.lwm2m")

# translator topics (gateway.lwm2m.translators config defaults in the
# reference's emqx_gateway_schema: command dn/#, response/register/update
# up/resp, notify up/notify)
TOPIC_COMMAND = "dn/#"
TOPIC_RESPONSE = "up/resp"
TOPIC_NOTIFY = "up/notify"

CODE_MSG = {
    C.CREATED: "created",
    C.DELETED: "deleted",
    C.VALID: "valid",
    C.CHANGED: "changed",
    C.CONTENT: "content",
    C.CONTINUE: "continue",
    C.BAD_REQUEST: "bad_request",
    C.UNAUTHORIZED: "unauthorized",
    C.FORBIDDEN: "forbidden",
    C.NOT_FOUND: "not_found",
    C.NOT_ALLOWED: "method_not_allowed",
    C.INTERNAL_ERROR: "internal_server_error",
}


class Lwm2mChannel:
    """One LwM2M endpoint (emqx_lwm2m_channel.erl + session)."""

    def __init__(self, gw: "Lwm2mGateway", peer: Tuple[str, int]):
        self.gw = gw
        self.peer = peer
        self.endpoint: Optional[str] = None
        self.location: Optional[str] = None
        self.lifetime = 86400.0
        self.reg_info: Dict = {}
        self.session: Optional[GwSession] = None
        self.last_seen = time.monotonic()
        self._next_mid = secrets.randbelow(0x10000)
        self._next_tok = 1
        # token -> (cmd_json, coap_path) awaiting a device response
        self._pending: Dict[bytes, Dict] = {}
        # observe-token -> path (kept after the first response, for notifies)
        self._observing: Dict[bytes, Dict] = {}
        self._retransmits: Dict[int, asyncio.Task] = {}
        self._con_tokens: Dict[int, bytes] = {}  # mid -> token (in-flight)
        self._dedup: Dict[int, Tuple[float, Optional[bytes]]] = {}

    # -- plumbing ------------------------------------------------------------
    def next_mid(self) -> int:
        self._next_mid = (self._next_mid + 1) & 0xFFFF
        return self._next_mid

    def next_token(self) -> bytes:
        t = self._next_tok
        self._next_tok = (self._next_tok + 1) & 0xFFFFFFFF
        return t.to_bytes(4, "big")

    def send(self, m: C.CoapMessage) -> None:
        self.gw.sendto(C.encode_message(m), self.peer)

    def send_con(self, m: C.CoapMessage) -> None:
        self.send(m)
        task = asyncio.get_running_loop().create_task(self._retransmit(m))
        self._retransmits[m.msg_id] = task
        # RSTs carry only the msg id; remember the token for cleanup
        self._con_tokens[m.msg_id] = m.token

    async def _retransmit(self, m: C.CoapMessage) -> None:
        try:
            timeout = C.ACK_TIMEOUT * C.ACK_RANDOM_FACTOR
            for _ in range(C.MAX_RETRANSMIT):
                await asyncio.sleep(timeout)
                self.send(m)
                timeout *= 2
            await asyncio.sleep(timeout)
            # device unreachable: fail the pending command upward
            ref = self._pending.pop(m.token, None)
            if ref is not None:
                self._uplink_response(
                    ref, code="timeout", content=None, msg_type_override=(
                        f"{ref.get('msgType', 'cmd')}_timeout"
                    )
                )
        except asyncio.CancelledError:
            pass

    def _ack(self, mid: int) -> Optional[bytes]:
        task = self._retransmits.pop(mid, None)
        if task is not None:
            task.cancel()
        return self._con_tokens.pop(mid, None)

    # -- inbound from the device --------------------------------------------
    def handle(self, m: C.CoapMessage) -> None:
        self.last_seen = time.monotonic()
        if m.type in (C.ACK, C.RST):
            con_token = self._ack(m.msg_id)
            if m.type == C.RST:
                # device rejected a downlink: resolve the (empty-token)
                # RST back to the CON it answers, fail the command
                # upward and drop any observe bookkeeping
                token = m.token or con_token
                if token:
                    self._observing.pop(token, None)
                    ref = self._pending.pop(token, None)
                    if ref is not None:
                        self._uplink_response(
                            ref, code="reset", content=None
                        )
                return
            if m.code != C.EMPTY:
                self._handle_response(m)
            return
        # separate response / notification from the device (CON or NON)
        if m.code == C.EMPTY:
            return
        if (m.code >> 5) >= 2:  # response class
            if m.type == C.CON:
                self.send(
                    C.CoapMessage(type=C.ACK, code=C.EMPTY, msg_id=m.msg_id)
                )
            self._handle_response(m)
            return
        # request from the device: registration interface
        now = time.monotonic()
        hit = self._dedup.get(m.msg_id)
        if hit is not None and now - hit[0] < C.DEDUP_WINDOW:
            if hit[1] is not None:
                self.gw.sendto(hit[1], self.peer)
            return
        resp = self._handle_request(m)
        raw = C.encode_message(resp) if resp is not None else None
        self._dedup[m.msg_id] = (now, raw)
        if raw is not None:
            self.gw.sendto(raw, self.peer)

    def _reply(self, req: C.CoapMessage, code: int, **kw) -> C.CoapMessage:
        return C.CoapMessage(
            type=C.ACK if req.type == C.CON else C.NON,
            code=code,
            msg_id=req.msg_id if req.type == C.CON else self.next_mid(),
            token=req.token,
            **kw,
        )

    # -- registration interface (emqx_lwm2m_session.erl init/update) ---------
    def _handle_request(self, m: C.CoapMessage) -> Optional[C.CoapMessage]:
        path = m.uri_path
        if not path or path[0] != "rd":
            return self._reply(m, C.NOT_FOUND)
        if m.code == C.POST and len(path) == 1:
            return self._register(m)
        if m.code == C.POST and len(path) == 2:
            return self._update(m, path[1])
        if m.code == C.DELETE and len(path) == 2:
            return self._deregister(m, path[1])
        return self._reply(m, C.NOT_ALLOWED)

    def _register(self, m: C.CoapMessage) -> C.CoapMessage:
        q = m.queries
        ep = q.get("ep")
        if not ep:
            return self._reply(m, C.BAD_REQUEST)
        try:
            self.lifetime = float(q.get("lt", self.gw.default_lifetime))
        except ValueError:
            return self._reply(m, C.BAD_REQUEST)
        if not (
            self.gw.lifetime_min <= self.lifetime <= self.gw.lifetime_max
        ):
            return self._reply(m, C.BAD_REQUEST)
        links = m.payload.decode("utf-8", "replace") if m.payload else ""
        object_list = [
            s.strip().strip("<>") for s in links.split(",") if s.strip()
        ]
        self.reg_info = {
            "ep": ep,
            "lt": int(self.lifetime),
            "lwm2m": q.get("lwm2m", "1.0"),
            "sms": q.get("sms"),
            "b": q.get("b", "U"),
            "alternatePath": "/",
            "objectList": object_list,
        }
        info = GwClientInfo(
            clientid=ep,
            username=q.get("imei") or None,
            peername=self.peer,
            protocol="lwm2m",
            mountpoint=self.gw.mountpoint_for(ep),
            keepalive=int(self.lifetime),
        )
        if not self.gw.authenticate_sync(info):
            return self._reply(m, C.UNAUTHORIZED)
        if self.session is not None:
            self.session.close("re_register")
        self.endpoint = ep
        self.location = secrets.token_hex(4)
        self.session = GwSession(
            self.gw.name, self.gw.broker, self.gw.hooks, info, self._downlink
        )
        old = self.gw.cm.open(ep, self)
        if old is not None and old is not self:
            old.drop("kicked")
        self.session.open()
        self.session.subscribe(TOPIC_COMMAND, pkt.SubOpts(qos=self.gw.qos))
        self._uplink("register", dict(self.reg_info))
        r = self._reply(m, C.CREATED)
        r.options = [(8, b"rd"), (8, self.location.encode())]  # Location-Path
        return r

    def _update(self, m: C.CoapMessage, loc: str) -> C.CoapMessage:
        if loc != self.location or self.session is None:
            return self._reply(m, C.NOT_FOUND)
        q = m.queries
        if "lt" in q:
            try:
                self.lifetime = float(q["lt"])
            except ValueError:
                return self._reply(m, C.BAD_REQUEST)
            self.reg_info["lt"] = int(self.lifetime)
        if m.payload:
            links = m.payload.decode("utf-8", "replace")
            self.reg_info["objectList"] = [
                s.strip().strip("<>") for s in links.split(",") if s.strip()
            ]
        self._uplink("update", dict(self.reg_info))
        return self._reply(m, C.CHANGED)

    def _deregister(self, m: C.CoapMessage, loc: str) -> C.CoapMessage:
        if loc != self.location:
            return self._reply(m, C.NOT_FOUND)
        self.drop("deregister")
        return self._reply(m, C.DELETED)

    # -- downlink: MQTT command JSON -> CoAP request (emqx_lwm2m_cmd) --------
    def _downlink(self, msg: Message, opts: pkt.SubOpts) -> None:
        try:
            cmd = json.loads(msg.payload)
        except (ValueError, UnicodeDecodeError):
            log.warning("lwm2m %s: bad downlink payload", self.endpoint)
            return
        msg_type = cmd.get("msgType")
        data = cmd.get("data") or {}
        if not isinstance(data, dict):
            log.warning("lwm2m %s: bad downlink data", self.endpoint)
            return
        path = data.get("path") or data.get("basePath") or "/"
        token = self.next_token()
        req = C.CoapMessage(type=C.CON, msg_id=self.next_mid(), token=token)
        try:
            for seg in LC.parse_path(path):
                req.options.append((C.OPT_URI_PATH, str(seg).encode()))
            self._build_downlink(req, msg_type, data, path)
        except (ValueError, TypeError, IndexError, KeyError) as e:
            # bad command from the MQTT side: answer on up/resp instead
            # of letting the error escape the broker's delivery fan-out
            log.warning("lwm2m %s: bad downlink %r: %s",
                        self.endpoint, msg_type, e)
            self._uplink_response(
                {**cmd, "_path": path}, code="bad_request", content=None
            )
            return
        if req.code == C.EMPTY:
            log.warning("lwm2m %s: unknown msgType %r", self.endpoint, msg_type)
            return
        self._pending[token] = {**cmd, "_path": path}
        self.send_con(req)

    def _build_downlink(
        self, req: C.CoapMessage, msg_type: str, data: Dict, path: str
    ) -> None:
        if msg_type == "read":
            req.code = C.GET
        elif msg_type == "write":
            req.code = C.PUT
            if "basePath" in data and "content" in data:
                req.payload = LC.json_to_tlv(data["basePath"], data["content"])
            else:
                req.payload = LC.json_to_tlv(
                    path, [{"path": path, "value": data.get("value")}]
                )
            req.set_uint(C.OPT_CONTENT_FORMAT, LC.FMT_TLV)
        elif msg_type == "create":
            req.code = C.POST
            req.payload = LC.json_to_tlv(
                data.get("basePath", path), data.get("content", [])
            )
            req.set_uint(C.OPT_CONTENT_FORMAT, LC.FMT_TLV)
        elif msg_type == "delete":
            req.code = C.DELETE
        elif msg_type == "execute":
            req.code = C.POST
            args = data.get("args")
            if args:
                req.payload = str(args).encode()
        elif msg_type == "discover":
            req.code = C.GET
            req.set_uint(17, LC.FMT_LINK)  # Accept: link-format
        elif msg_type == "observe":
            req.code = C.GET
            req.set_uint(C.OPT_OBSERVE, 0)
        elif msg_type == "cancel-observe":
            req.code = C.GET
            req.set_uint(C.OPT_OBSERVE, 1)
        elif msg_type == "write-attr":
            req.code = C.PUT
            for k in ("pmin", "pmax", "gt", "lt", "st"):
                if k in data and data[k] is not None:
                    req.options.append(
                        (C.OPT_URI_QUERY, f"{k}={data[k]}".encode())
                    )
        # unknown msg_type: req.code stays EMPTY, caller drops it

    # -- device response -> uplink JSON (emqx_lwm2m_cmd coap_to_mqtt) --------
    def _handle_response(self, m: C.CoapMessage) -> None:
        ref = self._pending.pop(m.token, None)
        obs_seq = m.observe
        if ref is None:
            ref = self._observing.get(m.token)
            if ref is None:
                return
            # continuing notification stream
            self._notify(ref, m, obs_seq or 0)
            return
        msg_type = ref.get("msgType")
        if msg_type == "observe" and (m.code >> 5) == 2:
            self._observing[m.token] = ref
        if msg_type == "cancel-observe":
            # drop any observe entry sharing this path
            for tok, oref in list(self._observing.items()):
                if oref.get("_path") == ref.get("_path"):
                    del self._observing[tok]
        if msg_type == "observe" and obs_seq not in (None, 0):
            self._notify(ref, m, obs_seq)
            return
        content = self._decode_content(m, ref)
        self._uplink_response(ref, code=C.code_str(m.code), content=content)

    def _decode_content(self, m: C.CoapMessage, ref: Dict):
        if (m.code >> 5) != 2 or m.code in (C.CHANGED, C.CREATED, C.DELETED):
            return None
        path = ref.get("_path", "/")
        fmt = m.opt_uint(C.OPT_CONTENT_FORMAT)
        try:
            if fmt == LC.FMT_TLV:
                return LC.tlv_to_json(path, m.payload)
            if fmt == LC.FMT_LINK:
                return m.payload.decode("utf-8", "replace").split(",")
            if fmt == LC.FMT_OPAQUE:
                return LC.opaque_to_json(path, m.payload)
            return LC.text_to_json(path, m.payload)
        except (IndexError, ValueError, KeyError, struct.error) as e:
            # malformed device payload: report it upward rather than
            # dropping the exchange (emqx_lwm2m_cmd bad_payload_format)
            log.warning(
                "lwm2m %s: bad payload for %s: %s", self.endpoint, path, e
            )
            return LC.opaque_to_json(path, m.payload)

    def _uplink_response(
        self, ref: Dict, code, content, msg_type_override: Optional[str] = None
    ) -> None:
        data = {
            "code": code,
            "codeMsg": CODE_MSG.get(code, code) if isinstance(code, int)
            else code,
            "reqPath": ref.get("_path"),
        }
        if isinstance(code, str) and "." in code:
            try:
                num = (int(code.split(".")[0]) << 5) | int(code.split(".")[1])
                data["codeMsg"] = CODE_MSG.get(num, code)
            except ValueError:
                pass
        if content is not None:
            data["content"] = content
        self._publish_up(
            TOPIC_RESPONSE,
            {
                "reqID": ref.get("reqID"),
                "msgType": msg_type_override or ref.get("msgType"),
                "data": data,
            },
        )

    def _notify(self, ref: Dict, m: C.CoapMessage, seq: int) -> None:
        content = self._decode_content(m, ref)
        self._publish_up(
            TOPIC_NOTIFY,
            {
                "reqID": ref.get("reqID"),
                "msgType": "notify",
                "seqNum": seq,
                "data": {
                    "code": C.code_str(m.code),
                    "codeMsg": CODE_MSG.get(m.code, ""),
                    "reqPath": ref.get("_path"),
                    "content": content,
                },
            },
        )

    def _uplink(self, msg_type: str, data: Dict) -> None:
        self._publish_up(
            TOPIC_RESPONSE, {"msgType": msg_type, "data": data}
        )

    def _publish_up(self, topic: str, obj: Dict) -> None:
        if self.session is None:
            return
        self.session.publish_sync(
            topic, json.dumps(obj).encode(), qos=self.gw.qos
        )

    # -- teardown ------------------------------------------------------------
    def drop(self, reason: str) -> None:
        for task in self._retransmits.values():
            task.cancel()
        self._retransmits.clear()
        self._con_tokens.clear()
        self._pending.clear()
        self._observing.clear()
        if self.session is not None:
            self.session.close(reason)
            self.session = None
        if self.endpoint is not None:
            self.gw.cm.close(self.endpoint, self)
        self.location = None
        self.gw.forget(self.peer)


class Lwm2mGateway(DtlsUdpGatewayMixin, Gateway):
    """UDP endpoint + per-endpoint channels (emqx_lwm2m_impl.erl)."""

    def __init__(self, name: str, config: Dict):
        super().__init__(name, config)
        self.qos = config.get("qos", 0)
        self.default_lifetime = config.get("lifetime", 86400)
        self.lifetime_min = config.get("lifetime_min", 1)
        self.lifetime_max = config.get("lifetime_max", 86400 * 7)
        self.mountpoint = config.get("mountpoint", "lwm2m/{ep}/")
        self._transport = None
        self._dtls = None  # DtlsEndpoint when transport == "dtls"
        self._chans: Dict[Tuple[str, int], Lwm2mChannel] = {}
        self._reaper: Optional[asyncio.Task] = None

    def mountpoint_for(self, ep: str) -> str:
        return self.mountpoint.replace("{ep}", ep).replace(
            "${endpoint_name}", ep
        )

    def find_channel(self, endpoint: str) -> Optional[Lwm2mChannel]:
        return self.cm.get(endpoint)

    def _plain_datagram(self, data: bytes, addr) -> None:
        m = C.decode_message(data)
        if m is None:
            return
        chan = self._chans.get(addr)
        if chan is None:
            chan = Lwm2mChannel(self, addr)
            self._chans[addr] = chan
        chan.handle(m)

    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        # transport: udp | dtls — LwM2M in the field is DTLS-first
        # (emqx_gateway_schema.erl:361-371,399 parity)
        self._init_dtls()
        host = self.config.get("bind", "127.0.0.1")
        port = self.config.get("port", 5783)
        self._endpoint = await loop.create_datagram_endpoint(
            self._make_proto(), local_addr=(host, port)
        )
        self.port = self._endpoint[0].get_extra_info("sockname")[1]
        self._reaper = loop.create_task(self._reap_loop())

    async def _reap_loop(self, period: float = 5.0) -> None:
        """Registration lifetime expiry (emqx_lwm2m_session lifetime)."""
        try:
            while True:
                await asyncio.sleep(period)
                now = time.monotonic()
                for chan in list(self._chans.values()):
                    if (
                        chan.session is not None
                        and now - chan.last_seen > chan.lifetime * 1.5
                    ):
                        chan.drop("lifetime_expired")
                        continue
                    chan._dedup = {
                        mid: v
                        for mid, v in chan._dedup.items()
                        if now - v[0] < C.DEDUP_WINDOW
                    }
        except asyncio.CancelledError:
            pass

    async def stop(self) -> None:
        if self._reaper is not None:
            self._reaper.cancel()
        for chan in list(self._chans.values()):
            chan.drop("gateway_stopped")
        if self._transport is not None:
            self._transport.close()
            self._transport = None
