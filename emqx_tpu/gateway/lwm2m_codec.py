"""LwM2M data formats: OMA-TLV codec + core object registry.

Parity with the reference's LwM2M codec stack
(apps/emqx_gateway/src/lwm2m/emqx_lwm2m_tlv.erl — TLV parse/encode;
emqx_lwm2m_message.erl — TLV/text/opaque <-> JSON translation;
emqx_lwm2m_xml_object.erl + emqx_lwm2m_xml_object_db.erl — object
definitions; here the core OMA objects are hardcoded instead of loaded
from the lwm2m_xml/ files, same ids/resources/types).

TLV wire format (OMA-TS-LightweightM2M §6.4.3):
  type byte: bits 7-6 = identifier kind (00 object instance, 01 resource
  instance, 10 multiple resource, 11 resource with value), bit 5 =
  16-bit identifier, bits 4-3 = length-field width (0 = in bits 2-0),
  bits 2-0 = inline length; then identifier, length, value.
"""

from __future__ import annotations

import base64
import struct
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple, Union

# identifier kinds
OBJ_INSTANCE = 0b00
RES_INSTANCE = 0b01
MULT_RESOURCE = 0b10
RESOURCE = 0b11


@dataclass
class Tlv:
    kind: int
    ident: int
    value: Union[bytes, List["Tlv"]] = b""

    @property
    def children(self) -> List["Tlv"]:
        return self.value if isinstance(self.value, list) else []


def encode_tlv(items: List[Tlv]) -> bytes:
    out = bytearray()
    for t in items:
        body = (
            encode_tlv(t.value) if isinstance(t.value, list) else bytes(t.value)
        )
        hdr = t.kind << 6
        if t.ident > 0xFF:
            hdr |= 0x20
        n = len(body)
        if n < 8:
            hdr |= n
            lenb = b""
        elif n < 0x100:
            hdr |= 0x08
            lenb = bytes([n])
        elif n < 0x10000:
            hdr |= 0x10
            lenb = struct.pack("!H", n)
        else:
            hdr |= 0x18
            lenb = n.to_bytes(3, "big")
        out.append(hdr)
        if t.ident > 0xFF:
            out += struct.pack("!H", t.ident)
        else:
            out.append(t.ident)
        out += lenb + body
    return bytes(out)


def decode_tlv(data: bytes) -> List[Tlv]:
    out: List[Tlv] = []
    pos = 0
    while pos < len(data):
        hdr = data[pos]
        pos += 1
        kind = hdr >> 6
        if hdr & 0x20:
            ident = struct.unpack_from("!H", data, pos)[0]
            pos += 2
        else:
            ident = data[pos]
            pos += 1
        lw = (hdr >> 3) & 0x03
        if lw == 0:
            n = hdr & 0x07
        else:
            n = int.from_bytes(data[pos : pos + lw], "big")
            pos += lw
        body = data[pos : pos + n]
        pos += n
        if kind in (OBJ_INSTANCE, MULT_RESOURCE):
            out.append(Tlv(kind, ident, decode_tlv(body)))
        else:
            out.append(Tlv(kind, ident, body))
    return out


# -- typed value packing (emqx_lwm2m_tlv value encode/decode rules) ----------


def pack_value(type_: str, value) -> bytes:
    t = type_.lower()
    if t == "integer":
        v = int(value)
        for size in (1, 2, 4, 8):
            try:
                return v.to_bytes(size, "big", signed=True)
            except OverflowError:
                continue
        raise ValueError("integer out of range")
    if t == "float":
        return struct.pack("!d", float(value))
    if t == "boolean":
        return b"\x01" if value in (True, 1, "1", "true") else b"\x00"
    if t == "opaque":
        if isinstance(value, (bytes, bytearray)):
            return bytes(value)
        return base64.b64decode(value)
    if t == "time":
        return int(value).to_bytes(8, "big", signed=True)
    # string / default
    return str(value).encode("utf-8")


def unpack_value(type_: str, data: bytes):
    t = type_.lower()
    if t == "integer" or t == "time":
        return int.from_bytes(data, "big", signed=True)
    if t == "float":
        if len(data) == 4:
            return struct.unpack("!f", data)[0]
        return struct.unpack("!d", data)[0]
    if t == "boolean":
        return bool(data and data[0])
    if t == "opaque":
        return base64.b64encode(data).decode()
    return data.decode("utf-8", "replace")


# -- core OMA object registry ------------------------------------------------


@dataclass
class ResourceDef:
    rid: int
    name: str
    operations: str  # "R", "W", "RW", "E"
    type: str  # Integer | String | Float | Boolean | Opaque | Time | Execute
    multiple: bool = False


@dataclass
class ObjectDef:
    oid: int
    name: str
    resources: Dict[int, ResourceDef] = field(default_factory=dict)

    def res_type(self, rid: int) -> str:
        r = self.resources.get(rid)
        return r.type if r is not None else "String"


def _obj(oid: int, name: str, rows: List[Tuple[int, str, str, str]]) -> ObjectDef:
    return ObjectDef(
        oid,
        name,
        {rid: ResourceDef(rid, n, ops, t) for rid, n, ops, t in rows},
    )


# ids/names/types match the OMA registry files the reference ships under
# apps/emqx_gateway/src/lwm2m/lwm2m_xml/ (spot-check: 3/0/0 Manufacturer
# String R, 1/0/1 Lifetime Integer RW).
CORE_OBJECTS: Dict[int, ObjectDef] = {
    o.oid: o
    for o in [
        _obj(0, "LWM2M Security", [
            (0, "LWM2M Server URI", "W", "String"),
            (1, "Bootstrap-Server", "W", "Boolean"),
            (2, "Security Mode", "W", "Integer"),
            (3, "Public Key or Identity", "W", "Opaque"),
            (4, "Server Public Key", "W", "Opaque"),
            (5, "Secret Key", "W", "Opaque"),
            (10, "Short Server ID", "W", "Integer"),
        ]),
        _obj(1, "LWM2M Server", [
            (0, "Short Server ID", "R", "Integer"),
            (1, "Lifetime", "RW", "Integer"),
            (2, "Default Minimum Period", "RW", "Integer"),
            (3, "Default Maximum Period", "RW", "Integer"),
            (4, "Disable", "E", "Execute"),
            (5, "Disable Timeout", "RW", "Integer"),
            (6, "Notification Storing", "RW", "Boolean"),
            (7, "Binding", "RW", "String"),
            (8, "Registration Update Trigger", "E", "Execute"),
        ]),
        _obj(2, "LWM2M Access Control", [
            (0, "Object ID", "R", "Integer"),
            (1, "Object Instance ID", "R", "Integer"),
            (2, "ACL", "RW", "Integer"),
            (3, "Access Control Owner", "RW", "Integer"),
        ]),
        _obj(3, "Device", [
            (0, "Manufacturer", "R", "String"),
            (1, "Model Number", "R", "String"),
            (2, "Serial Number", "R", "String"),
            (3, "Firmware Version", "R", "String"),
            (4, "Reboot", "E", "Execute"),
            (5, "Factory Reset", "E", "Execute"),
            (6, "Available Power Sources", "R", "Integer"),
            (7, "Power Source Voltage", "R", "Integer"),
            (8, "Power Source Current", "R", "Integer"),
            (9, "Battery Level", "R", "Integer"),
            (10, "Memory Free", "R", "Integer"),
            (11, "Error Code", "R", "Integer"),
            (12, "Reset Error Code", "E", "Execute"),
            (13, "Current Time", "RW", "Time"),
            (14, "UTC Offset", "RW", "String"),
            (15, "Timezone", "RW", "String"),
            (16, "Supported Binding and Modes", "R", "String"),
        ]),
        _obj(4, "Connectivity Monitoring", [
            (0, "Network Bearer", "R", "Integer"),
            (1, "Available Network Bearer", "R", "Integer"),
            (2, "Radio Signal Strength", "R", "Integer"),
            (3, "Link Quality", "R", "Integer"),
            (4, "IP Addresses", "R", "String"),
            (5, "Router IP Addresses", "R", "String"),
            (6, "Link Utilization", "R", "Integer"),
            (7, "APN", "R", "String"),
            (8, "Cell ID", "R", "Integer"),
            (9, "SMNC", "R", "Integer"),
            (10, "SMCC", "R", "Integer"),
        ]),
        _obj(5, "Firmware Update", [
            (0, "Package", "W", "Opaque"),
            (1, "Package URI", "RW", "String"),
            (2, "Update", "E", "Execute"),
            (3, "State", "R", "Integer"),
            (5, "Update Result", "R", "Integer"),
            (6, "PkgName", "R", "String"),
            (7, "PkgVersion", "R", "String"),
        ]),
        _obj(6, "Location", [
            (0, "Latitude", "R", "Float"),
            (1, "Longitude", "R", "Float"),
            (2, "Altitude", "R", "Float"),
            (3, "Radius", "R", "Float"),
            (4, "Velocity", "R", "Opaque"),
            (5, "Timestamp", "R", "Time"),
            (6, "Speed", "R", "Float"),
        ]),
        _obj(7, "Connectivity Statistics", [
            (0, "SMS Tx Counter", "R", "Integer"),
            (1, "SMS Rx Counter", "R", "Integer"),
            (2, "Tx Data", "R", "Integer"),
            (3, "Rx Data", "R", "Integer"),
            (6, "Start", "E", "Execute"),
            (7, "Stop", "E", "Execute"),
        ]),
    ]
}


def parse_path(path: str) -> List[int]:
    """'/3/0/1' -> [3, 0, 1]"""
    return [int(p) for p in path.strip("/").split("/") if p != ""]


def path_type(path: str) -> str:
    """Resource data type from the object registry, 'String' if unknown."""
    ids = parse_path(path)
    if len(ids) >= 3 and ids[0] in CORE_OBJECTS:
        return CORE_OBJECTS[ids[0]].res_type(ids[2])
    return "String"


# -- content <-> JSON translation (emqx_lwm2m_message.erl) -------------------

FMT_TEXT = 0  # text/plain
FMT_LINK = 40  # application/link-format
FMT_OPAQUE = 42  # application/octet-stream
FMT_TLV = 11542  # application/vnd.oma.lwm2m+tlv
FMT_JSON = 11543  # application/vnd.oma.lwm2m+json


def tlv_to_json(base_path: str, payload: bytes) -> List[Dict]:
    """Decode a TLV payload into [{"path", "value"}, ...] rows, resource
    types resolved via the object registry (tlv_level1/tlv_level2 walk of
    emqx_lwm2m_message.erl)."""
    ids = parse_path(base_path)
    oid = ids[0] if ids else 0
    items = decode_tlv(payload)
    rows: List[Dict] = []

    def emit(path_ids: List[int], t: Tlv) -> None:
        if t.kind == OBJ_INSTANCE:
            for c in t.children:
                emit(path_ids + [t.ident], c)
        elif t.kind == MULT_RESOURCE:
            for c in t.children:
                emit(path_ids + [t.ident], c)
        else:  # RESOURCE | RES_INSTANCE
            rid = (
                path_ids[-1] if t.kind == RES_INSTANCE and len(path_ids) >= 3
                else t.ident
            )
            type_ = (
                CORE_OBJECTS[oid].res_type(rid)
                if oid in CORE_OBJECTS
                else "String"
            )
            full = path_ids + [t.ident]
            rows.append(
                {
                    "path": "/" + "/".join(str(i) for i in full),
                    "value": unpack_value(type_, t.value),
                }
            )

    for t in items:
        emit(ids[:1] if t.kind == OBJ_INSTANCE else ids[:2], t)
    return rows


def text_to_json(path: str, payload: bytes) -> List[Dict]:
    """text/plain carries the *textual* representation (emqx_lwm2m_message
    text_to_json), so numbers parse from the string, not binary."""
    t = path_type(path)
    text = payload.decode("utf-8", "replace")
    value: object = text
    try:
        if t in ("Integer", "Time"):
            value = int(text)
        elif t == "Float":
            value = float(text)
        elif t == "Boolean":
            value = text.strip() in ("1", "true", "True")
        elif t == "Opaque":
            value = base64.b64encode(payload).decode()
    except ValueError:
        value = text
    return [{"path": path, "value": value}]


def opaque_to_json(path: str, payload: bytes) -> List[Dict]:
    return [{"path": path, "value": base64.b64encode(payload).decode()}]


def json_to_text(path: str, value) -> bytes:
    """Encode a single-resource write as text/plain (write_to_coap's
    simple-value branch)."""
    t = path_type(path)
    if t == "Boolean":
        return b"1" if value in (True, 1, "1", "true") else b"0"
    if t == "Opaque":
        return base64.b64decode(value) if isinstance(value, str) else bytes(value)
    return str(value).encode()


def json_to_tlv(path: str, rows: List[Dict]) -> bytes:
    """Encode batch-write rows into a TLV payload (emqx_lwm2m_message
    json_to_tlv)."""
    items = []
    for row in rows:
        ids = parse_path(row["path"])
        rid = ids[-1]
        items.append(
            Tlv(RESOURCE, rid, pack_value(path_type(row["path"]), row["value"]))
        )
    return encode_tlv(items)
