"""CoAP gateway (RFC 7252) with EMQX's PubSub + MQTT-connection handlers.

Parity with the reference CoAP gateway
(apps/emqx_gateway/src/coap/: emqx_coap_frame.erl codec,
emqx_coap_channel.erl + emqx_coap_session.erl lifecycle,
emqx_coap_tm.erl / emqx_coap_transport.erl message layer,
handler/emqx_coap_pubsub_handler.erl + emqx_coap_mqtt_handler.erl,
behavior contract in src/coap/README.md):

- RFC 7252 message layer: CON/NON/ACK/RST, message-id dedup window,
  CON retransmission with exponential backoff, token-matched exchanges
- Observe (RFC 7641): GET + Observe:0 subscribes (per-token observe
  entry, monotonically increasing sequence numbers on notifications),
  GET + Observe:1 unsubscribes
- Block-wise transfer (RFC 7959): Block1 request-payload assembly and
  Block2 response slicing (the reference's emqx_coap_frame block options)
- PubSub handler: POST/PUT ``ps/{topic}`` publishes (2.04 Changed), GET
  reads the retained message (2.05 Content / 4.04), subscribe/
  unsubscribe per the draft-ietf-core-coap-pubsub mapping
- MQTT handler: POST/PUT/DELETE ``mqtt/connection`` = connect /
  heartbeat / close; connection mode hands out a token and every
  subsequent request must carry matching ``clientid`` + ``token`` query
  parameters or the request is RST/4.01, exactly as the README specifies
- connectionless mode: requests carry ``clientid`` in the query string

The gateway bridges into the core Broker through GwSession, so retained
messages, shared subs, the rule engine and hooks all behave as for MQTT.
"""

from __future__ import annotations

import asyncio
import logging
import secrets
import struct
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from emqx_tpu.broker import mountpoint as MP
from emqx_tpu.broker.message import Message
from emqx_tpu.gateway.base import Gateway, GwClientInfo, GwSession
from emqx_tpu.transport.dtls import DtlsUdpGatewayMixin
from emqx_tpu.mqtt import packet as pkt
from emqx_tpu.ops import topics as T

log = logging.getLogger("emqx_tpu.gateway.coap")

# -- RFC 7252 constants ------------------------------------------------------

VER = 1
CON, NON, ACK, RST = 0, 1, 2, 3

# method / response codes, c.dd encoded as (c << 5) | dd
EMPTY = 0x00
GET, POST, PUT, DELETE = 0x01, 0x02, 0x03, 0x04
CREATED = 0x41  # 2.01
DELETED = 0x42  # 2.02
VALID = 0x43  # 2.03
CHANGED = 0x44  # 2.04
CONTENT = 0x45  # 2.05
NO_CONTENT = 0x47  # 2.07 (pubsub draft; reference uses it for unsubscribe)
CONTINUE = 0x5F  # 2.31 (block1)
BAD_REQUEST = 0x80  # 4.00
UNAUTHORIZED = 0x81  # 4.01
FORBIDDEN = 0x83  # 4.03
NOT_FOUND = 0x84  # 4.04
NOT_ALLOWED = 0x85  # 4.05
REQ_INCOMPLETE = 0x88  # 4.08
TOO_LARGE = 0x8D  # 4.13
INTERNAL_ERROR = 0xA0  # 5.00

# option numbers
OPT_OBSERVE = 6
OPT_URI_PATH = 11
OPT_CONTENT_FORMAT = 12
OPT_MAX_AGE = 14
OPT_URI_QUERY = 15
OPT_BLOCK2 = 23
OPT_BLOCK1 = 27
OPT_SIZE2 = 28
OPT_SIZE1 = 60

# transmission parameters (RFC 7252 §4.8)
ACK_TIMEOUT = 2.0
ACK_RANDOM_FACTOR = 1.5
MAX_RETRANSMIT = 4
EXCHANGE_LIFETIME = 247.0
DEDUP_WINDOW = 60.0  # practical dedup retention for the test/server regime

DEFAULT_BLOCK_SIZE = 1024  # szx 6


def code_str(code: int) -> str:
    return f"{code >> 5}.{code & 0x1F:02d}"


@dataclass
class CoapMessage:
    type: int = CON
    code: int = EMPTY
    msg_id: int = 0
    token: bytes = b""
    options: List[Tuple[int, bytes]] = field(default_factory=list)
    payload: bytes = b""

    # -- option helpers ------------------------------------------------------
    def opt_all(self, num: int) -> List[bytes]:
        return [v for n, v in self.options if n == num]

    def opt(self, num: int) -> Optional[bytes]:
        vals = self.opt_all(num)
        return vals[0] if vals else None

    def opt_uint(self, num: int) -> Optional[int]:
        v = self.opt(num)
        if v is None:
            return None
        return int.from_bytes(v, "big")

    def set_uint(self, num: int, val: int) -> None:
        b = b"" if val == 0 else val.to_bytes((val.bit_length() + 7) // 8, "big")
        self.options.append((num, b))

    @property
    def uri_path(self) -> List[str]:
        return [v.decode("utf-8", "replace") for v in self.opt_all(OPT_URI_PATH)]

    @property
    def queries(self) -> Dict[str, str]:
        out = {}
        for v in self.opt_all(OPT_URI_QUERY):
            s = v.decode("utf-8", "replace")
            k, _, val = s.partition("=")
            out[k] = val
        return out

    @property
    def observe(self) -> Optional[int]:
        return self.opt_uint(OPT_OBSERVE)

    def block(self, num: int) -> Optional[Tuple[int, bool, int]]:
        """-> (block_num, more, size) for OPT_BLOCK1/OPT_BLOCK2."""
        v = self.opt_uint(num)
        if v is None:
            return None
        return v >> 4, bool(v & 0x08), 1 << ((v & 0x07) + 4)

    def set_block(self, opt_num: int, block_num: int, more: bool, size: int) -> None:
        szx = max(0, size.bit_length() - 5)  # size == 2 ** (szx + 4)
        self.set_uint(opt_num, (block_num << 4) | (0x08 if more else 0) | szx)


def encode_message(m: CoapMessage) -> bytes:
    out = bytearray()
    out.append((VER << 6) | (m.type << 4) | len(m.token))
    out.append(m.code)
    out += struct.pack("!H", m.msg_id)
    out += m.token
    prev = 0
    for num, val in sorted(m.options, key=lambda o: o[0]):
        delta = num - prev
        prev = num
        dn, dext = _opt_nibble(delta)
        ln, lext = _opt_nibble(len(val))
        out.append((dn << 4) | ln)
        out += dext + lext + val
    if m.payload:
        out.append(0xFF)
        out += m.payload
    return bytes(out)


def _opt_nibble(v: int) -> Tuple[int, bytes]:
    if v < 13:
        return v, b""
    if v < 269:
        return 13, bytes([v - 13])
    return 14, struct.pack("!H", v - 269)


def _opt_ext(nibble: int, data: bytes, pos: int) -> Tuple[int, int]:
    if nibble < 13:
        return nibble, pos
    if nibble == 13:
        return data[pos] + 13, pos + 1
    if nibble == 14:
        return struct.unpack_from("!H", data, pos)[0] + 269, pos + 2
    raise ValueError("reserved option nibble 15")


def decode_message(data: bytes) -> Optional[CoapMessage]:
    if len(data) < 4 or (data[0] >> 6) != VER:
        return None
    tkl = data[0] & 0x0F
    if tkl > 8:
        return None
    m = CoapMessage(
        type=(data[0] >> 4) & 0x03,
        code=data[1],
        msg_id=struct.unpack_from("!H", data, 2)[0],
        token=data[4 : 4 + tkl],
    )
    pos = 4 + tkl
    prev = 0
    try:
        while pos < len(data):
            b = data[pos]
            pos += 1
            if b == 0xFF:
                m.payload = data[pos:]
                break
            delta, pos = _opt_ext(b >> 4, data, pos)
            length, pos = _opt_ext(b & 0x0F, data, pos)
            prev += delta
            m.options.append((prev, data[pos : pos + length]))
            pos += length
    except (IndexError, ValueError, struct.error):
        return None
    return m


# -- per-peer channel --------------------------------------------------------


@dataclass
class ObserveEntry:
    token: bytes
    topic: str
    seq: int = 1


@dataclass
class Block1Buf:
    next_num: int = 0
    data: bytearray = field(default_factory=bytearray)
    at: float = field(default_factory=time.monotonic)


class CoapChannel:
    """One CoAP peer: message layer + request handlers
    (emqx_coap_channel.erl + emqx_coap_tm.erl roles)."""

    def __init__(self, gw: "CoapGateway", peer: Tuple[str, int]):
        self.gw = gw
        self.peer = peer
        self.session: Optional[GwSession] = None
        self.conn_token: Optional[str] = None  # connection-mode auth token
        self.clientid: Optional[str] = None
        self.last_seen = time.monotonic()
        self.heartbeat = gw.heartbeat
        self._next_mid = secrets.randbelow(0x10000)
        self._observes: Dict[str, ObserveEntry] = {}  # topic -> entry
        self._dedup: Dict[int, Tuple[float, Optional[bytes]]] = {}
        self._pending_con: Dict[int, asyncio.Task] = {}  # mid -> retransmit
        self._con_tokens: Dict[int, bytes] = {}  # mid -> token (in-flight CON)
        self._block1: Dict[bytes, Block1Buf] = {}  # token -> partial upload
        self._block2: Dict[bytes, bytes] = {}  # token -> full response body

    # -- plumbing ------------------------------------------------------------
    def next_mid(self) -> int:
        self._next_mid = (self._next_mid + 1) & 0xFFFF
        return self._next_mid

    def send(self, m: CoapMessage) -> None:
        self.gw.sendto(encode_message(m), self.peer)

    def send_con(self, m: CoapMessage) -> None:
        """Send a CON message with RFC 7252 retransmission."""
        self.send(m)
        task = asyncio.get_running_loop().create_task(self._retransmit(m))
        self._pending_con[m.msg_id] = task
        # an RST carries only the msg id (no token), so remember which
        # token each in-flight CON belongs to for observe cancellation
        self._con_tokens[m.msg_id] = m.token

    async def _retransmit(self, m: CoapMessage) -> None:
        try:
            timeout = ACK_TIMEOUT * ACK_RANDOM_FACTOR
            for _ in range(MAX_RETRANSMIT):
                await asyncio.sleep(timeout)
                self.send(m)
                timeout *= 2
            await asyncio.sleep(timeout)
            # give up: peer is gone (emqx_coap_transport timeout semantics)
            self.drop("con_timeout")
        except asyncio.CancelledError:
            pass

    def _ack_received(self, mid: int) -> Optional[bytes]:
        task = self._pending_con.pop(mid, None)
        if task is not None:
            task.cancel()
        return self._con_tokens.pop(mid, None)

    def reply(
        self,
        req: CoapMessage,
        code: int,
        payload: bytes = b"",
        options: Optional[List[Tuple[int, bytes]]] = None,
    ) -> CoapMessage:
        """Build a response: piggybacked ACK for CON, NON for NON."""
        m = CoapMessage(
            type=ACK if req.type == CON else NON,
            code=code,
            msg_id=req.msg_id if req.type == CON else self.next_mid(),
            token=req.token,
            options=list(options or []),
            payload=payload,
        )
        return m

    def rst(self, req: CoapMessage) -> None:
        self.send(CoapMessage(type=RST, code=EMPTY, msg_id=req.msg_id))

    # -- inbound -------------------------------------------------------------
    def handle(self, m: CoapMessage) -> None:
        self.last_seen = time.monotonic()
        if m.type in (ACK, RST):
            con_token = self._ack_received(m.msg_id)
            if m.type == RST:
                # peer rejected a notification: cancel its observe. RFC
                # 7252 RSTs carry no token, so resolve it from the
                # in-flight CON's msg id.
                self._cancel_observes_by_token(
                    m.token or con_token or b""
                )
            return
        if m.code == EMPTY:
            if m.type == CON:  # CoAP ping
                self.send(CoapMessage(type=RST, code=EMPTY, msg_id=m.msg_id))
            return
        # message-id dedup (emqx_coap_tm duplicate detection)
        now = time.monotonic()
        hit = self._dedup.get(m.msg_id)
        if hit is not None and now - hit[0] < DEDUP_WINDOW:
            if hit[1] is not None:
                self.gw.sendto(hit[1], self.peer)  # replay cached response
            return
        resp = self._handle_request(m)
        raw = encode_message(resp) if resp is not None else None
        self._dedup[m.msg_id] = (now, raw)
        if raw is not None:
            self.gw.sendto(raw, self.peer)

    # -- request routing -----------------------------------------------------
    def _handle_request(self, m: CoapMessage) -> Optional[CoapMessage]:
        path = m.uri_path
        if not path:
            return self.reply(m, NOT_FOUND)
        if path[0] == "ps" and len(path) >= 2:
            return self._handle_pubsub(m, "/".join(path[1:]))
        if path[0] == "mqtt" and path[1:] == ["connection"]:
            return self._handle_connection(m)
        return self.reply(m, NOT_FOUND)

    # -- auth / identity (emqx_coap_channel check_token + enter_connected) ---
    def _check_identity(self, m: CoapMessage) -> Optional[CoapMessage]:
        """Connection-mode guard: clientid+token must match. Returns an
        error response to send, or None when the request may proceed."""
        q = m.queries
        if self.conn_token is not None:
            if (
                q.get("clientid") != self.clientid
                or q.get("token") != self.conn_token
            ):
                return self.reply(m, UNAUTHORIZED)
            return None
        if q.get("token"):
            # token given but no connection: unauthorized per README
            return self.reply(m, UNAUTHORIZED)
        return None

    def _ensure_session(self, m: CoapMessage) -> Optional[GwSession]:
        """Connectionless mode: lazily open a session named by the
        clientid query param (or the peer address)."""
        if self.session is not None:
            return self.session
        q = m.queries
        clientid = q.get("clientid") or f"coap-{self.peer[0]}-{self.peer[1]}"
        info = GwClientInfo(
            clientid=clientid,
            username=q.get("username"),
            peername=self.peer,
            protocol="coap",
            mountpoint=self.gw.config.get("mountpoint"),
        )
        self.clientid = clientid
        self.session = GwSession(
            self.gw.name, self.gw.broker, self.gw.hooks, info, self._notify
        )
        old = self.gw.cm.open(clientid, self)
        if old is not None and old is not self:
            old.drop("kicked")
        self.session.open()
        return self.session

    # -- pubsub handler (handler/emqx_coap_pubsub_handler.erl) ---------------
    def _handle_pubsub(self, m: CoapMessage, topic: str) -> Optional[CoapMessage]:
        err = self._check_identity(m)
        if err is not None:
            return err
        try:
            T.validate(topic, kind="filter" if m.code == GET else "name")
        except T.TopicValidationError:
            return self.reply(m, BAD_REQUEST)
        if m.code in (POST, PUT):
            return self._do_publish(m, topic)
        if m.code == GET:
            obs = m.observe
            if obs == 0:
                return self._do_subscribe(m, topic)
            if obs == 1:
                return self._do_unsubscribe(m, topic)
            return self._do_read(m, topic)
        return self.reply(m, NOT_ALLOWED)

    def _do_publish(self, m: CoapMessage, topic: str) -> Optional[CoapMessage]:
        sess = self._ensure_session(m)
        if sess is None:
            return self.reply(m, UNAUTHORIZED)
        # Block1: assemble multi-block uploads before publishing
        b1 = m.block(OPT_BLOCK1)
        payload = m.payload
        if b1 is not None:
            num, more, size = b1
            buf = self._block1.get(m.token)
            if num == 0:
                buf = Block1Buf()
                self._block1[m.token] = buf
            if buf is None or num != buf.next_num:
                self._block1.pop(m.token, None)
                return self.reply(m, REQ_INCOMPLETE)
            buf.data += m.payload
            buf.next_num += 1
            buf.at = time.monotonic()  # live upload: not abandoned
            if more:
                r = self.reply(m, CONTINUE)
                r.set_block(OPT_BLOCK1, num, True, size)
                return r
            payload = bytes(self._block1.pop(m.token).data)
        q = m.queries
        qos = _parse_qos(q.get("qos"), default=0 if m.type == NON else 1)
        retain = q.get("retain", "").lower() in ("true", "1")
        sess.publish_sync(topic, payload, qos=qos, retain=retain)
        r = self.reply(m, CHANGED)
        if b1 is not None:
            r.set_block(OPT_BLOCK1, b1[0], False, b1[2])
        return r

    def _do_read(self, m: CoapMessage, topic: str) -> CoapMessage:
        """Plain GET: return the retained message (pubsub-draft read)."""
        retainer = self.gw.config.get("retainer") or getattr(
            self.gw, "retainer", None
        )
        sess = self._ensure_session(m)
        if sess is None:
            return self.reply(m, UNAUTHORIZED)
        # match under the mountpoint publishes were stored with
        mounted = MP.mount(sess.mountpoint, topic)
        msgs = retainer.match(mounted) if retainer is not None else []
        if not msgs:
            return self.reply(m, NOT_FOUND)
        return self._content_reply(m, msgs[0].payload)

    def _do_subscribe(self, m: CoapMessage, topic: str) -> CoapMessage:
        sess = self._ensure_session(m)
        if sess is None:
            return self.reply(m, UNAUTHORIZED)
        qos = _parse_qos(m.queries.get("qos"), default=0)
        ent = self._observes.get(topic)
        if ent is None:
            ent = ObserveEntry(token=m.token, topic=topic)
            self._observes[topic] = ent
            sess.subscribe(topic, pkt.SubOpts(qos=qos))
        else:
            ent.token = m.token  # re-register refreshes the token
        r = self.reply(m, CONTENT)
        r.set_uint(OPT_OBSERVE, ent.seq)
        return r

    def _do_unsubscribe(self, m: CoapMessage, topic: str) -> CoapMessage:
        ent = self._observes.pop(topic, None)
        if ent is not None and self.session is not None:
            self.session.unsubscribe(topic)
        return self.reply(m, NO_CONTENT)

    def _cancel_observes_by_token(self, token: bytes) -> None:
        if not token:
            return
        for topic, ent in list(self._observes.items()):
            if ent.token == token:
                self._observes.pop(topic, None)
                if self.session is not None:
                    self.session.unsubscribe(topic)

    # -- delivery → observe notification (emqx_coap_observe_res.erl) ---------
    def _notify(self, msg: Message, opts: pkt.SubOpts) -> None:
        ent = None
        for topic, e in self._observes.items():
            if T.match(msg.topic, topic):
                ent = e
                break
        if ent is None:
            return
        ent.seq = (ent.seq + 1) & 0xFFFFFF
        notify_type = self.gw.notify_type
        if notify_type == "qos":
            mtype = CON if msg.qos > 0 else NON
        else:
            mtype = CON if notify_type == "con" else NON
        m = CoapMessage(
            type=mtype,
            code=CONTENT,
            msg_id=self.next_mid(),
            token=ent.token,
            payload=msg.payload,
        )
        m.set_uint(OPT_OBSERVE, ent.seq)
        if len(m.payload) > self.gw.max_block_size:
            # Block2 slicing: cache body, send first block
            self._block2[ent.token] = m.payload
            m.payload = m.payload[: self.gw.max_block_size]
            m.set_block(OPT_BLOCK2, 0, True, self.gw.max_block_size)
        if mtype == CON:
            self.send_con(m)
        else:
            self.send(m)

    def _content_reply(self, m: CoapMessage, body: bytes) -> CoapMessage:
        """2.05 response with Block2 slicing for large bodies."""
        b2 = m.block(OPT_BLOCK2)
        size = b2[2] if b2 is not None else self.gw.max_block_size
        num = b2[0] if b2 is not None else 0
        if len(body) <= size and num == 0:
            return self.reply(m, CONTENT, payload=body)
        if num == 0:
            self._block2[m.token] = body
        else:
            body = self._block2.get(m.token, body)
        lo = num * size
        if lo >= len(body):
            return self.reply(m, BAD_REQUEST)
        chunk = body[lo : lo + size]
        more = lo + size < len(body)
        if not more:
            self._block2.pop(m.token, None)
        r = self.reply(m, CONTENT, payload=chunk)
        r.set_block(OPT_BLOCK2, num, more, size)
        return r

    # -- mqtt/connection handler (handler/emqx_coap_mqtt_handler.erl) --------
    def _handle_connection(self, m: CoapMessage) -> Optional[CoapMessage]:
        q = m.queries
        if m.code == POST:  # connect
            clientid = q.get("clientid")
            if not clientid:
                return self.reply(m, BAD_REQUEST)
            info = GwClientInfo(
                clientid=clientid,
                username=q.get("username"),
                peername=self.peer,
                protocol="coap",
                mountpoint=self.gw.config.get("mountpoint"),
                clean_start=True,
            )
            ok = self.gw.authenticate_sync(info, q.get("password"))
            if not ok:
                return self.reply(m, UNAUTHORIZED)
            if self.session is not None:
                self.session.close("reconnect")
            self.clientid = clientid
            self.conn_token = secrets.token_hex(8)
            self.session = GwSession(
                self.gw.name, self.gw.broker, self.gw.hooks, info, self._notify
            )
            old = self.gw.cm.open(clientid, self)
            if old is not None and old is not self:
                old.drop("kicked")
            self.session.open()
            return self.reply(m, CREATED, payload=self.conn_token.encode())
        if m.code == PUT:  # heartbeat
            if self.conn_token is not None and (
                q.get("clientid") != self.clientid
                or q.get("token") != self.conn_token
            ):
                return self.reply(m, UNAUTHORIZED)
            return self.reply(m, CHANGED)
        if m.code == DELETE:  # close
            if self.conn_token is None or (
                q.get("clientid") != self.clientid
                or q.get("token") != self.conn_token
            ):
                return self.reply(m, UNAUTHORIZED)
            self.drop("client_disconnect")
            return self.reply(m, DELETED)
        return self.reply(m, NOT_ALLOWED)

    # -- teardown ------------------------------------------------------------
    def drop(self, reason: str) -> None:
        for task in self._pending_con.values():
            task.cancel()
        self._pending_con.clear()
        self._con_tokens.clear()
        self._observes.clear()
        if self.session is not None:
            self.session.close(reason)
            self.session = None
        if self.clientid is not None:
            self.gw.cm.close(self.clientid, self)
        self.conn_token = None
        self.gw.forget(self.peer)


def _parse_qos(s: Optional[str], default: int) -> int:
    try:
        q = int(s) if s is not None else default
    except ValueError:
        return default
    return min(max(q, 0), 2)


class CoapGateway(DtlsUdpGatewayMixin, Gateway):
    """UDP endpoint + per-peer CoAP channels (emqx_coap_impl.erl)."""

    def __init__(self, name: str, config: Dict):
        super().__init__(name, config)
        self.heartbeat = config.get("heartbeat", 30.0)
        self.notify_type = config.get("notify_type", "qos")  # qos|con|non
        self.max_block_size = config.get("max_block_size", DEFAULT_BLOCK_SIZE)
        self._transport = None
        self._dtls = None  # DtlsEndpoint when transport == "dtls"
        self._chans: Dict[Tuple[str, int], CoapChannel] = {}
        self._reaper: Optional[asyncio.Task] = None

    def _plain_datagram(self, data: bytes, addr) -> None:
        m = decode_message(data)
        if m is None:
            return
        chan = self._chans.get(addr)
        if chan is None:
            chan = CoapChannel(self, addr)
            self._chans[addr] = chan
        chan.handle(m)

    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        # transport: udp | dtls (emqx_gateway_schema.erl:361-371 parity;
        # dtls = PSK-only DTLS 1.2, transport/dtls.py)
        self._init_dtls()
        host = self.config.get("bind", "127.0.0.1")
        port = self.config.get("port", 5683)
        self._endpoint = await loop.create_datagram_endpoint(
            self._make_proto(), local_addr=(host, port)
        )
        self.port = self._endpoint[0].get_extra_info("sockname")[1]
        self._reaper = loop.create_task(self._reap_loop())

    async def _reap_loop(self, period: float = 5.0) -> None:
        """Expire peers silent past 2x heartbeat (channel keepalive,
        emqx_coap_channel.erl heartbeat timer); sweep stale dedup cache
        entries and abandoned Block1 uploads."""
        try:
            while True:
                await asyncio.sleep(period)
                now = time.monotonic()
                for chan in list(self._chans.values()):
                    if now - chan.last_seen > 2 * self.heartbeat:
                        chan.drop("heartbeat_timeout")
                        continue
                    chan._dedup = {
                        mid: v
                        for mid, v in chan._dedup.items()
                        if now - v[0] < DEDUP_WINDOW
                    }
                    for tok, buf in list(chan._block1.items()):
                        if now - buf.at > EXCHANGE_LIFETIME:
                            del chan._block1[tok]
        except asyncio.CancelledError:
            pass

    async def stop(self) -> None:
        if self._reaper is not None:
            self._reaper.cancel()
        for chan in list(self._chans.values()):
            chan.drop("gateway_stopped")
        if self._transport is not None:
            self._transport.close()
            self._transport = None
