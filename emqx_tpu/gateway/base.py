"""Gateway behaviours: the generic half every protocol gateway shares.

Parity with the reference's behaviour modules
(apps/emqx_gateway/src/bhvrs/emqx_gateway_{impl,channel,conn,frame}.erl):

- `Gateway`      — the impl behaviour: load/unload lifecycle, listeners
                   (emqx_gateway_impl.erl on_gateway_load/unload)
- `GwFrame`      — incremental codec behaviour (emqx_gateway_frame.erl)
- `GwSession`    — bridges one gateway client into the core broker:
                   subscribe/publish with mountpoint, hook runs, delivery
                   callback (the role emqx_gateway_channel fills via
                   emqx_broker + hooks)
- `GwClientInfo` — client identity passed to hooks/authn

Gateways do NOT reimplement broker semantics: retained delivery, shared
subs, rule-engine events etc. all come for free because GwSession calls the
same Broker/Hooks the MQTT channel does.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from emqx_tpu.broker import mountpoint as MP
from emqx_tpu.broker.message import Message
from emqx_tpu.mqtt import packet as pkt


@dataclass
class GwClientInfo:
    clientid: str
    username: Optional[str] = None
    peername: Tuple[str, int] = ("", 0)
    protocol: str = ""
    mountpoint: Optional[str] = None
    keepalive: int = 0
    clean_start: bool = True
    connected_at: float = field(default_factory=time.time)

    def as_dict(self) -> Dict:
        return {
            "client_id": self.clientid,
            "clientid": self.clientid,
            "username": self.username,
            "peername": self.peername,
            "protocol": self.protocol,
            "mountpoint": self.mountpoint,
            "keepalive": self.keepalive,
            "clean_start": self.clean_start,
            "connected_at": self.connected_at,
        }


class GwSession:
    """One gateway client's bridge into the core broker.

    Delivery: the broker calls the session's deliver callback with
    (Message, SubOpts); the protocol channel serializes it out. Topics are
    mounted on the way in and unmounted on delivery
    (emqx_mountpoint.erl semantics, same helper the MQTT channel uses).
    """

    def __init__(
        self,
        gw_name: str,
        broker,
        hooks,
        info: GwClientInfo,
        deliver: Callable[[Message, pkt.SubOpts], None],
    ):
        self.gw = gw_name
        self.broker = broker
        self.hooks = hooks
        self.info = info
        self.mountpoint = MP.replvar(info.mountpoint, info.as_dict())
        self._deliver = deliver
        self.subs: Dict[str, pkt.SubOpts] = {}  # client-visible filters
        self.sid = f"gw:{gw_name}:{info.clientid}"
        self.connected = False

    # -- lifecycle ---------------------------------------------------------
    def open(self) -> None:
        self.connected = True
        self.hooks.run("client.connected", self.info.as_dict(), self)

    def close(self, reason: str = "normal") -> None:
        if not self.connected:
            return
        self.connected = False
        for f in list(self.subs):
            self.unsubscribe(f)
        self.hooks.run("client.disconnected", self.info.as_dict(), reason)

    # -- pub/sub -----------------------------------------------------------
    def subscribe(self, filter_: str, opts: Optional[pkt.SubOpts] = None) -> None:
        opts = opts or pkt.SubOpts()
        mf = MP.mount(self.mountpoint, filter_)
        self.broker.subscribe(
            self.sid, self.info.clientid, mf, opts, self._on_deliver
        )
        self.subs[filter_] = opts
        self.hooks.run(
            "session.subscribed", self.info.as_dict(), mf, opts
        )

    def unsubscribe(self, filter_: str) -> bool:
        mf = MP.mount(self.mountpoint, filter_)
        ok = self.broker.unsubscribe(self.sid, mf)
        self.subs.pop(filter_, None)
        if ok:
            self.hooks.run(
                "session.unsubscribed", self.info.as_dict(), mf
            )
        return ok

    def publish(
        self,
        topic: str,
        payload: bytes,
        qos: int = 0,
        retain: bool = False,
        properties: Optional[Dict] = None,
    ) -> "asyncio.Future":
        """Fold + route one message (async enqueue onto the device batch
        window when the broker has one); returns an awaitable/int."""
        msg = Message(
            topic=MP.mount(self.mountpoint, topic),
            payload=payload,
            qos=qos,
            retain=retain,
            from_client=self.info.clientid,
            from_username=self.info.username,
            properties=properties or {},
        )
        return self.broker.apublish_enqueue(msg)

    def publish_sync(
        self, topic: str, payload: bytes, qos: int = 0, retain: bool = False
    ) -> int:
        msg = Message(
            topic=MP.mount(self.mountpoint, topic),
            payload=payload,
            qos=qos,
            retain=retain,
            from_client=self.info.clientid,
            from_username=self.info.username,
        )
        return self.broker.publish(msg)

    # -- delivery ----------------------------------------------------------
    def _on_deliver(self, msg: Message, opts: pkt.SubOpts) -> None:
        if self.mountpoint and msg.topic.startswith(self.mountpoint):
            import copy

            msg = copy.copy(msg)
            msg.topic = MP.unmount(self.mountpoint, msg.topic)
        self.hooks.run("message.delivered", self.info.as_dict(), msg)
        self._deliver(msg, opts)


class GwFrame:
    """Incremental frame codec behaviour (emqx_gateway_frame.erl).

    Subclasses keep partial-input state; `parse` returns complete frames
    and buffers the remainder — the same contract as the MQTT codec
    (emqx_tpu.mqtt.frame)."""

    def parse(self, data: bytes) -> List[object]:
        raise NotImplementedError

    def serialize(self, frame: object) -> bytes:
        raise NotImplementedError


class Gateway:
    """Impl behaviour: one registered protocol gateway
    (emqx_gateway_impl.erl on_gateway_load/on_gateway_unload).

    Subclasses own their listeners/transports and create GwSessions
    through the GatewayCM handed to them at load."""

    name: str = "?"

    def __init__(self, name: str, config: Dict):
        self.name = name
        self.config = config
        self.cm = None  # set by registry at load
        self.broker = None
        self.hooks = None

    async def start(self) -> None:
        raise NotImplementedError

    async def stop(self) -> None:
        raise NotImplementedError

    # -- auth (shared by all protocol gateways): run the same
    # 'client.authenticate' fold the MQTT channel uses
    # (emqx_gateway_channel authenticate -> emqx_access_control) --------
    async def authenticate(self, info: GwClientInfo, password=None) -> bool:
        res = await self.hooks.arun_fold(
            "client.authenticate",
            (info.as_dict(),),
            {"ok": True, "password": password},
        )
        return bool(res is None or res.get("ok", True))

    def authenticate_sync(self, info: GwClientInfo, password=None) -> bool:
        res = self.hooks.run_fold(
            "client.authenticate",
            (info.as_dict(),),
            {"ok": True, "password": password},
        )
        return bool(res is None or res.get("ok", True))

    def status(self) -> Dict:
        return {
            "name": self.name,
            "running": True,
            "clients": self.cm.count() if self.cm else 0,
        }
