"""Config schema: one typed dataclass tree, one loader, env overrides.

The reference's config plane is HOCON text checked against typerefl schemas
and stored in persistent_term with env overrides under `EMQX_`
(apps/emqx/src/emqx_config.erl:199-218, emqx_schema.erl,
bin/emqx:31 HOCON_ENV_OVERRIDE_PREFIX). Here the single source of truth is
this dataclass tree: it gives defaults, types, validation, JSON round-trip,
and (in emqx_tpu.mgmt.api) the /configs REST payload — one schema feeding
validation and the API, as emqx_dashboard_swagger does from HOCON.

Files are JSON (optionally with #-comments). Env overrides use
EMQX_TPU__SECTION__FIELD=value paths, e.g.
EMQX_TPU__MQTT__MAX_PACKET_SIZE=2097152.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
from dataclasses import dataclass, field, fields, is_dataclass
from typing import Any, Dict, List, Optional, get_args, get_origin

from emqx_tpu.broker.session import SessionConfig
from emqx_tpu.broker.channel import MqttCaps

ENV_PREFIX = "EMQX_TPU__"


@dataclass
class NodeConfig:
    name: str = ""
    cookie: str = "emqxtpusecret"


@dataclass
class ClusterSeed:
    node: str = ""  # peer node name, e.g. "n2@127.0.0.1"
    host: str = "127.0.0.1"
    port: int = 0  # the peer's cluster bus port


@dataclass
class ClusterConfig:
    """Config-driven clustering (ekka/mria autocluster analog): the app
    starts a TcpBus + ClusterNode around its broker, dials the seeds,
    and joins the first reachable one. Routes replicate and publishes
    forward over the bus (cluster/node.py)."""

    enable: bool = False
    bind: str = "127.0.0.1"
    listen_port: int = 0  # 0 = ephemeral (printed at boot)
    seeds: List[ClusterSeed] = field(default_factory=list)
    # cluster send robustness (tcp_transport.py): each send retries up
    # to send_retries times with bounded exponential backoff before the
    # dead-letter counter takes it; send_deadline_s bounds the WHOLE
    # attempt train (0 = timeout * (retries + 1))
    send_retries: int = 2
    send_backoff_ms: float = 50.0
    send_deadline_s: float = 0.0
    # scale-out sharded serving (docs/scale_out.md): this node's slice
    # of the global subscriber-lane space, [index, total]. With
    # router.mesh_shape set, the node advertises the slice on join
    # (ShardOwnership) and publishes reroute to the rendezvous
    # successor when an owner dies. [0, 1] = the whole space (default).
    shard_slice: List[int] = field(default_factory=lambda: [0, 1])


@dataclass
class ListenerSpec:
    name: str = "default"
    type: str = "tcp"  # tcp | ssl | ws | wss
    bind: str = "0.0.0.0"
    port: int = 1883
    max_connections: int = 1_024_000
    ssl_certfile: Optional[str] = None
    ssl_keyfile: Optional[str] = None
    ssl_cacertfile: Optional[str] = None
    ssl_verify: bool = False
    # topic namespace prefix for clients of this listener; supports
    # ${clientid}/${username} placeholders (emqx_mountpoint.erl parity)
    mountpoint: Optional[str] = None
    # >0: serve this (tcp-only) listener from N connection-worker
    # PROCESSES on a shared SO_REUSEPORT socket, speaking the batched
    # fabric protocol to the router process (transport/workers.py) —
    # the host-data-plane analog of the reference's process-per-
    # connection parallelism (emqx_connection.erl:173-176)
    workers: int = 0


@dataclass
class RouterConfig:
    enable_tpu: bool = True
    min_tpu_batch: int = 64
    max_levels: int = 16
    frontier: int = 32
    max_matches: int = 64
    max_bytes: int = 256
    # sparse fan-out compaction (docs/observability.md "readback
    # budget"): read back O(matches) compact slot lists per batch
    # instead of dense [B, W] subscriber bitmaps; rows whose fan-out
    # exceeds the cap fall back to a masked dense transfer
    fanout_compact: bool = True
    # per-row compact-slot cap Kslot: 0 = auto-size from the
    # dispatch.fanout histogram p99 (grow-only, pow2); > 0 pins it
    fanout_slots: int = 0
    # subscriber-table representation (docs/serving_pipeline.md
    # "subscriber-table memory budget"): dense = the [Fcap, W] bitmap
    # matrix (O(filters x slots) memory; the degrade fallback),
    # sparse = CSR slot lists (O(total subscriptions) — what makes 1M
    # distinct single-subscriber topics possible), auto = start dense,
    # flip once when occupancy x width says the matrix is mostly zeros
    sub_table: str = "auto"
    # sparse-mode gather-window bound per routed row (0 = 2 x Kslot);
    # rows past it rebuild their fan-out on host like Kslot overflow
    sparse_gather: int = 0
    # ingest-side adaptive batch window (broker/ingest.py): collect
    # concurrent publishes into one device route_step
    ingest_enable: bool = True
    ingest_window_us: int = 1000
    ingest_max_batch: int = 4096
    # device dispatches in flight at once (batch N+1's upload/launch
    # overlaps batch N's readback); settlement stays FIFO for ordering
    ingest_pipeline: int = 2
    # donate per-batch input buffers (token bytes/lengths) to the
    # serving jit: steady-state batches reuse them for outputs instead
    # of allocating fresh device buffers every launch
    donate_buffers: bool = True
    # bound on cached compiled programs per serving jit entry (table
    # growth compiles fresh programs; a long-lived process must not
    # accumulate every shape it ever served). 0 = unbounded.
    jit_cache_max: int = 64
    # SPMD serving over a device mesh: [dp, tp] axis sizes. [0, 0] (the
    # default) = single-device serving; set e.g. [4, 2] on an 8-chip
    # host to run dist_shape_route_step on the live dispatch path.
    mesh_shape: List[int] = field(default_factory=lambda: [0, 0])
    # segmented update path (docs/update_path.md): background compaction
    # merges the shape-index hot segment into the packed table once it
    # holds this many live entries (housekeeping-driven, built + pre-
    # uploaded on the segment-compact executor)
    compact_hot_entries: int = 1024
    # minimum seconds between background compaction cycles per table
    compact_interval_s: float = 5.0
    # also compact when this fraction of the packed table is tombstoned
    # (mass unsubscribe reclaim)
    compact_tombstone_frac: float = 0.25


@dataclass
class SemanticConfig:
    """Semantic routing plane (docs/semantic_routing.md): embedding-
    filter subscriptions answered by a similarity matmul fused into the
    serving launch, plus device-compiled rule WHERE predicates. The
    whole plane is one opt-in; `rule_predicates` can switch the rule
    half off independently."""

    enable: bool = False
    # embedding dimensionality; every filter and message embedding
    # must match it exactly
    dim: int = 64
    # per-message semantic fan-out bound: route to the topk most
    # similar qualifying subscribers (per 'tp' shard on a mesh)
    topk: int = 16
    # default cosine-similarity threshold for filters that don't pin
    # their own via the semantic-threshold user property
    threshold: float = 0.75
    # device storage dtype for the embedding matrix: float32, or
    # bfloat16 to halve HBM + double MXU throughput (quantized at
    # upload; host keeps f32)
    dtype: str = "float32"
    # compile eligible rule-engine WHERE clauses to in-launch masks
    # (rules/compile.py); off = rules stay on the host hook path
    rule_predicates: bool = True


@dataclass
class RetainerConfig:
    enable: bool = True
    max_retained_messages: int = 1_000_000
    max_payload_size: int = 1024 * 1024
    msg_clear_interval: float = 60.0
    # device replay index for wildcard storms over big stores; engages at
    # device_threshold topics when the TPU path is enabled
    device_threshold: int = 10_000
    # batch wildcard-subscribe replays through the serving pipeline:
    # pending storms fuse into the next publish launch
    # (fused_route_retained_step) or flush standalone after storm_window
    storm_ride: bool = True
    storm_window_us: int = 2000


@dataclass
class DelayedConfig:
    enable: bool = True
    max_delayed_messages: int = 0  # 0 = unlimited


@dataclass
class RewriteRuleSpec:
    action: str = "all"
    source_topic: str = ""
    re: str = ""
    dest_topic: str = ""


@dataclass
class AuthUser:
    user_id: str = ""
    password: str = ""
    is_superuser: bool = False


@dataclass
class AuthnConfig:
    enable: bool = False
    allow_anonymous: bool = True
    user_id_type: str = "username"
    password_hash: str = "pbkdf2"
    users: List[AuthUser] = field(default_factory=list)
    jwt_secret: str = ""
    jwt_verify_claims: Dict[str, str] = field(default_factory=dict)
    # HTTP authn provider (emqx_authn_http analog)
    http_url: str = ""
    http_method: str = "POST"
    http_timeout: float = 5.0
    # JWKS RS256 provider (emqx_authn_jwt jwks mode)
    jwks_endpoint: str = ""
    jwks_refresh_interval: float = 300.0
    jwks_verify_claims: Dict[str, str] = field(default_factory=dict)
    # SCRAM-SHA-256 enhanced auth (emqx enhanced_authn scram)
    scram_enable: bool = False
    scram_iterations: int = 4096
    scram_users: List[AuthUser] = field(default_factory=list)


@dataclass
class PskConfig:
    """TLS-PSK identity store (emqx_psk analog); wired into ssl/wss
    listeners when the interpreter's ssl module supports PSK."""

    enable: bool = False
    identities: Dict[str, str] = field(default_factory=dict)  # id -> hex
    file: str = ""  # identity:hexsecret lines


@dataclass
class AclRuleSpec:
    permit: str = "allow"
    who: str = "all"  # all | clientid:<x> | username:<x> | ipaddr:<prefix>
    action: str = "all"
    topics: List[str] = field(default_factory=list)


@dataclass
class AuthzConfig:
    no_match: str = "allow"
    deny_action: str = "ignore"  # 'ignore' | 'disconnect' (reference knob)
    rules: List[AclRuleSpec] = field(default_factory=list)
    # file source: JSON-lines ACL rules (emqx_authz_file analog)
    acl_file: str = ""
    # HTTP source (emqx_authz_http analog)
    http_url: str = ""
    http_method: str = "POST"
    http_timeout: float = 5.0


@dataclass
class FlappingConfig:
    enable: bool = True
    max_count: int = 15
    window_time: float = 60.0
    ban_time: float = 300.0


@dataclass
class SharedSubConfig:
    strategy: str = "round_robin"


@dataclass
class SysConfig:
    sys_msg_interval: float = 60.0  # $SYS heartbeat
    sys_heartbeat_interval: float = 30.0


@dataclass
class DashboardConfig:
    enable: bool = True
    bind: str = "127.0.0.1"
    port: int = 18083
    api_key: str = ""  # empty => no auth (dev mode)
    # admin users for JWT login (emqx_dashboard_admin analog); password
    # accepted in plain here, hashed at app assembly
    admins: Dict[str, str] = field(default_factory=dict)  # user -> password
    jwt_ttl: float = 3600.0
    # live monitor sampling (emqx_dashboard_monitor analog)
    monitor_interval: float = 5.0
    monitor_history: int = 360  # samples kept for monitor_current charts


@dataclass
class ExhookServerSpec:
    name: str = ""
    url: str = ""  # e.g. 127.0.0.1:9000
    timeout: float = 0.5
    failed_action: str = "deny"  # deny | ignore


@dataclass
class DurabilityConfig:
    """Persistent sessions + durable broker state (retained/delayed/banned).
    Reference: emqx_persistent_session backends + mnesia disc tables."""

    enable: bool = False
    data_dir: str = "data"
    flush_interval: float = 5.0
    fsync: bool = False
    # checkpoint the device-table host state (route index + hot
    # segments + subscriber bitmaps) as a sidecar pickle so a rolling
    # upgrade restores million-entry tables instead of replaying every
    # subscribe (ops/segments.SegmentStateSnapshot)
    segment_snapshot: bool = False


@dataclass
class OlpConfig:
    enable: bool = False
    lag_watermark_ms: float = 500.0
    cooldown: float = 5.0


@dataclass
class SloConfig:
    """SLO-driven adaptive batching (broker/slo.py): the ingest window
    as a controlled variable holding a p99 target, priority lanes, and
    the graded backpressure ladder (widen -> defer -> shed) replacing
    the binary shed cliff. docs/robustness.md "SLO controller"."""

    enable: bool = True
    target_p99_ms: float = 5.0
    # window bounds the controller adapts inside; the initial value is
    # router.ingest_window_us (continuity with the fixed-window era)
    min_window_us: int = 0
    max_window_us: int = 20000
    eval_interval_ms: float = 50.0  # one look per flush-cycle stretch
    min_samples: int = 32  # settles needed to judge a tail
    gain: float = 0.25  # multiplicative widen/narrow step
    hysteresis: float = 0.7  # hold inside [hysteresis*target, target]
    ladder_patience: int = 3  # consecutive readings to move a rung
    defer_max_ms: float = 250.0  # low-lane defer age bound (starvation)
    starvation_ms: float = 50.0  # lane-fairness reserve trigger
    shed_hard_mult: float = 4.0  # absolute backlog valve (x shed bound)
    qos0_low_lane: bool = True  # QoS0 publishes ride the low lane
    # sustained-miss alarm (observe/alarm.py SloViolationWatch)
    alarm_enable: bool = True
    alarm_threshold: float = 0.5  # violating fraction of eval windows
    alarm_window: float = 10.0
    alarm_min_windows: int = 4


# Every injectable fault site (observe/faults.py). These literals MUST
# stay in lockstep with faults.SITES — the FT checker in tools/analysis
# statically cross-checks the two, so a site added to the injector
# without config awareness fails the lint, not a midnight soak.
FAULT_SITES = frozenset({
    "ingest.enqueue",
    "device.launch",
    "device.readback",
    "router.delta_sync",
    "retained.storm",
    "cluster.forward",
    "exhook.call",
})

FAULT_MODES = ("raise", "delay", "drop", "corrupt")


@dataclass
class FaultRuleSpec:
    """One armed fault behavior (observe/faults.py FaultRule). Default
    off at the root (`faults.enable`); rules also arm at runtime via
    GET/POST /api/v5/faults for soak testing."""

    site: str = ""
    mode: str = "raise"  # raise | delay | drop | corrupt
    probability: float = 1.0
    nth: int = 0  # fire on every nth eligible call (0 = every)
    max_fires: int = 0  # stop after this many fires (0 = unlimited, 1 = one-shot)
    delay_ms: float = 0.0


@dataclass
class FaultsConfig:
    enable: bool = False
    rules: List[FaultRuleSpec] = field(default_factory=list)


@dataclass
class DegradeConfig:
    """Graceful-degradation ladder knobs (broker/degrade.py): bounded
    retry/backoff before a batch degrades, breaker trip threshold, open
    dwell before the half-open probe, and the ingest shed bound."""

    enable: bool = True
    max_retries: int = 2
    backoff_base_ms: float = 20.0
    backoff_max_ms: float = 2000.0
    failure_threshold: int = 1  # exhausted-retry batches to trip open
    open_secs: float = 5.0  # open dwell before a half-open probe
    probe_successes: int = 1  # probes needed to close from half-open
    # ingest sheds enqueues past shed_queue_batches * ingest_max_batch
    # pending messages while overloaded or the device breaker is open
    shed_queue_batches: int = 8


@dataclass
class ForceGcConfig:
    enable: bool = True
    count: int = 16000
    bytes: int = 16 * 1024 * 1024


@dataclass
class SlowSubsConfig:
    enable: bool = True
    threshold_ms: float = 500.0
    top_k_num: int = 10
    expire_interval: float = 300.0


@dataclass
class StatsdConfig:
    enable: bool = False
    server_host: str = "127.0.0.1"
    server_port: int = 8125
    flush_interval: float = 30.0


@dataclass
class EventMessageConfig:
    client_connected: bool = True
    client_disconnected: bool = True
    session_subscribed: bool = True
    session_unsubscribed: bool = True
    message_delivered: bool = False
    message_acked: bool = False
    message_dropped: bool = False


@dataclass
class TelemetryConfig:
    """Opt-in anonymized usage reporting (emqx_telemetry analog)."""

    enable: bool = False
    url: str = ""
    interval: float = 604800.0  # weekly


@dataclass
class PluginsConfig:
    """Runtime-installable plugins (emqx_plugins analog)."""

    install_dir: str = "plugins"
    start: List[str] = field(default_factory=list)  # name-version refs


@dataclass
class ObserveConfig:
    slow_subs: SlowSubsConfig = field(default_factory=SlowSubsConfig)
    statsd: StatsdConfig = field(default_factory=StatsdConfig)
    event_message: EventMessageConfig = field(
        default_factory=EventMessageConfig
    )
    trace_dir: str = "trace"
    telemetry: TelemetryConfig = field(default_factory=TelemetryConfig)
    alarm_size_limit: int = 1000
    alarm_validity_period: float = 24 * 3600.0
    os_mon_enable: bool = True
    vm_mon_enable: bool = True
    sys_mon_enable: bool = True
    # hot-path flight recorder: alarm when the TPU route path's
    # fallback-row rate (device-flagged rows routed by the CPU trie)
    # exceeds the threshold over a sliding window — sustained fallback
    # means the fast path has degraded to per-message CPU matching
    # (observe/alarm.py FallbackRateWatch)
    tpu_fallback_alarm_enable: bool = True
    tpu_fallback_alarm_threshold: float = 0.2
    tpu_fallback_alarm_window: float = 10.0
    tpu_fallback_alarm_min_rows: int = 64
    # causal span tracing (observe/spans.py): head-based sampling at the
    # publish entry; one flow samples deterministically (seeded hash of
    # client+topic), so repeated runs trace the same clients. Clients
    # matched by an active TraceSpec always sample at 100%.
    trace_spans_enable: bool = True
    trace_sample_rate: float = 0.01  # base fraction of publish flows
    # per-client / per-topic-filter rate overrides (most specific wins)
    trace_sample_clients: Dict[str, float] = field(default_factory=dict)
    trace_sample_topics: Dict[str, float] = field(default_factory=dict)
    trace_sample_seed: int = 0
    trace_span_ring: int = 2048  # recent spans kept for /trace/spans
    trace_span_file: str = ""  # OTLP-shaped JSON lines sink ("" = off)
    # on-demand device profiling (observe/profiler.py): REST-armed
    # jax.profiler trace captures, bounded by wall clock AND by on-disk
    # bytes — an armed capture can never fill the data disk
    profile_trace_dir: str = "profile_traces"
    profile_max_seconds: float = 30.0
    profile_max_bytes: int = 64 << 20
    # device runtime telemetry (observe/device_watch.py): alarm when the
    # jit compile rate stays nonzero after warmup (retrace storm)
    retrace_alarm_enable: bool = True
    retrace_alarm_threshold: int = 1  # compiles per window that count
    retrace_alarm_window: float = 10.0
    retrace_alarm_warmup: float = 60.0  # boot compiles never alarm
    retrace_alarm_sustain: int = 2  # consecutive hot windows to trip


@dataclass
class AutoSubscribeSpec:
    topic: str = ""
    qos: int = 0


@dataclass
class RuleOutputSpec:
    function: str = "console"  # console | republish | bridge
    args: Dict[str, Any] = field(default_factory=dict)


@dataclass
class BridgeSpec:
    """One data bridge (emqx_bridge config analog). id = `type:name`
    (http:alarm, mqtt:site_a); connector options in `opts` (url/method/
    body for http; host/port/remote_topic/ingress_filter for mqtt;
    local_topic binds an automatic egress)."""

    id: str = ""
    enable: bool = True
    opts: Dict[str, Any] = field(default_factory=dict)


@dataclass
class RuleSpec:
    id: str = ""
    sql: str = ""
    enable: bool = True
    description: str = ""
    outputs: List[RuleOutputSpec] = field(default_factory=list)


@dataclass
class LicenseConfig:
    """Enterprise license (lib-ee/emqx_license analog). `key` is the
    signed license string; `pubkey_n`/`pubkey_e` override the verifier
    key (hex n). Empty key => community/unlimited."""

    key: str = ""
    pubkey_n: str = ""
    pubkey_e: int = 65537


@dataclass
class LogConfig:
    """Structured logging (``log`` config root; emqx_logger_jsonfmt /
    textfmt analog). formatter switches at runtime via /configs/log."""

    level: str = "info"  # debug|info|warning|error
    formatter: str = "text"  # text | json
    to_file: str = ""  # empty = stderr


@dataclass
class GatewaySpec:
    """One protocol gateway instance (emqx_gateway config analog).
    type: stomp | mqttsn | exproto | coap | lwm2m; options go in `opts`
    (bind/port/mountpoint/predefined/handler/notify_type/lifetime...)."""

    type: str = "stomp"
    name: Optional[str] = None  # defaults to type
    enable: bool = True
    opts: Dict[str, Any] = field(default_factory=dict)


# Every key a gateway may read from `GatewaySpec.opts` (the free-form
# dict above). The gateways read these with `self.config.get("key")`;
# tools/analysis (CK002) statically rejects reads of undeclared keys, so
# a typo'd opt surfaces at lint time instead of silently hitting the
# default. Add new keys HERE when a gateway grows a knob.
GATEWAY_OPT_KEYS = frozenset({
    # shared listener plumbing
    "bind", "port", "mountpoint", "transport", "psk",
    # mqtt-sn
    "predefined", "gateway_id",
    # lwm2m
    "qos", "lifetime", "lifetime_min", "lifetime_max",
    # stomp
    "heartbeat_ms",
    # coap
    "heartbeat", "notify_type", "max_block_size", "retainer",
    # exproto
    "node", "adapter_bind",
})


@dataclass
class AppConfig:
    node: NodeConfig = field(default_factory=NodeConfig)
    cluster: ClusterConfig = field(default_factory=ClusterConfig)
    listeners: List[ListenerSpec] = field(default_factory=lambda: [ListenerSpec()])
    mqtt: MqttCaps = field(default_factory=MqttCaps)
    session: SessionConfig = field(default_factory=SessionConfig)
    router: RouterConfig = field(default_factory=RouterConfig)
    semantic: SemanticConfig = field(default_factory=SemanticConfig)
    retainer: RetainerConfig = field(default_factory=RetainerConfig)
    delayed: DelayedConfig = field(default_factory=DelayedConfig)
    rewrite: List[RewriteRuleSpec] = field(default_factory=list)
    authn: AuthnConfig = field(default_factory=AuthnConfig)
    authz: AuthzConfig = field(default_factory=AuthzConfig)
    flapping: FlappingConfig = field(default_factory=FlappingConfig)
    shared_subscription: SharedSubConfig = field(default_factory=SharedSubConfig)
    sys: SysConfig = field(default_factory=SysConfig)
    observe: ObserveConfig = field(default_factory=ObserveConfig)
    # {type: {rate, burst, client: {rate, burst}}}; types: bytes_in,
    # message_in, connection, message_routing (emqx_limiter schema analog)
    limiter: Dict[str, Any] = field(default_factory=dict)
    olp: OlpConfig = field(default_factory=OlpConfig)
    slo: SloConfig = field(default_factory=SloConfig)
    faults: FaultsConfig = field(default_factory=FaultsConfig)
    degrade: DegradeConfig = field(default_factory=DegradeConfig)
    force_gc: ForceGcConfig = field(default_factory=ForceGcConfig)
    durability: DurabilityConfig = field(default_factory=DurabilityConfig)
    exhook: List[ExhookServerSpec] = field(default_factory=list)
    dashboard: DashboardConfig = field(default_factory=DashboardConfig)
    auto_subscribe: List[AutoSubscribeSpec] = field(default_factory=list)
    rules: List[RuleSpec] = field(default_factory=list)
    gateways: List[GatewaySpec] = field(default_factory=list)
    bridges: List[BridgeSpec] = field(default_factory=list)
    psk: PskConfig = field(default_factory=PskConfig)
    plugins: PluginsConfig = field(default_factory=PluginsConfig)
    license: LicenseConfig = field(default_factory=LicenseConfig)
    log: LogConfig = field(default_factory=LogConfig)


class ConfigError(ValueError):
    pass


def _coerce(tp, value, path):
    origin = get_origin(tp)
    if is_dataclass(tp):
        if not isinstance(value, dict):
            raise ConfigError(f"{path}: expected object, got {value!r}")
        return _from_dict(tp, value, path)
    if origin is list:
        (item_t,) = get_args(tp)
        if not isinstance(value, list):
            raise ConfigError(f"{path}: expected list")
        return [_coerce(item_t, v, f"{path}[{i}]") for i, v in enumerate(value)]
    if origin is dict:
        return dict(value)
    if tp is Optional[str] or tp == Optional[str]:
        return None if value is None else str(value)
    if origin is not None:  # other Optionals / unions: pass through
        return value
    if tp is bool:
        if isinstance(value, bool):
            return value
        if isinstance(value, str):
            return value.lower() in ("1", "true", "yes", "on")
        return bool(value)
    if tp is int:
        try:
            return int(value)
        except (TypeError, ValueError):
            raise ConfigError(f"{path}: expected integer, got {value!r}")
    if tp is float:
        try:
            return float(value)
        except (TypeError, ValueError):
            raise ConfigError(f"{path}: expected number, got {value!r}")
    if tp is str:
        return str(value)
    return value


def _from_dict(cls, data: Dict, path: str = ""):
    import typing

    known = {f.name for f in fields(cls)}
    for k in data:
        if k not in known:
            raise ConfigError(f"{path or cls.__name__}: unknown key {k!r}")
    # field types are strings under `from __future__ import annotations`
    hints = typing.get_type_hints(cls)
    kwargs = {
        name: _coerce(hints[name], data[name], f"{path}.{name}")
        for name in known
        if name in data
    }
    return cls(**kwargs)


def to_dict(cfg) -> Dict:
    return dataclasses.asdict(cfg)


_COMMENT_RE = re.compile(r"^\s*#.*$", re.M)


def load_config(data: Dict) -> AppConfig:
    cfg = _from_dict(AppConfig, data)
    _apply_env_overrides(cfg)
    _validate(cfg)
    return cfg


def load_file(path: Optional[str]) -> AppConfig:
    if path is None:
        return load_config({})
    with open(path) as f:
        text = _COMMENT_RE.sub("", f.read())
    return load_config(json.loads(text) if text.strip() else {})


def _apply_env_overrides(cfg: AppConfig) -> None:
    """EMQX_TPU__MQTT__MAX_QOS_ALLOWED=1 style deep overrides."""
    import typing

    for key, raw in os.environ.items():
        if not key.startswith(ENV_PREFIX):
            continue
        parts = [p.lower() for p in key[len(ENV_PREFIX) :].split("__")]
        obj = cfg
        ok = True
        for p in parts[:-1]:
            if not hasattr(obj, p):
                ok = False
                break
            obj = getattr(obj, p)
        leaf = parts[-1]
        if not ok or not hasattr(obj, leaf):
            raise ConfigError(f"unknown config env override: {key}")
        hints = typing.get_type_hints(type(obj))
        setattr(obj, leaf, _coerce(hints[leaf], raw, key))


def _validate(cfg: AppConfig) -> None:
    if not cfg.listeners:
        raise ConfigError("at least one listener is required")
    seen = set()
    for l in cfg.listeners:
        key = (l.type, l.name)
        if key in seen:
            raise ConfigError(f"duplicate listener {key}")
        seen.add(key)
        if l.type not in ("tcp", "ssl", "ws", "wss"):
            raise ConfigError(f"unsupported listener type {l.type!r}")
        if l.type in ("ssl", "wss") and not (l.ssl_certfile and l.ssl_keyfile):
            raise ConfigError(f"{l.type} listener requires certfile and keyfile")
    if cfg.shared_subscription.strategy not in (
        "random", "round_robin", "sticky", "hash_clientid", "hash_topic",
    ):
        raise ConfigError(
            f"unknown shared sub strategy {cfg.shared_subscription.strategy!r}"
        )
    if cfg.authz.no_match not in ("allow", "deny"):
        raise ConfigError("authz.no_match must be allow|deny")
    if cfg.log.formatter not in ("text", "json"):
        raise ConfigError("log.formatter must be text|json")
    if cfg.log.level.upper() not in ("DEBUG", "INFO", "WARNING", "ERROR"):
        raise ConfigError("log.level must be debug|info|warning|error")
    ms = cfg.router.mesh_shape
    if len(ms) != 2 or any(not isinstance(x, int) or x < 0 for x in ms):
        raise ConfigError("router.mesh_shape must be [dp, tp] with ints >= 0")
    dp, tp = ms
    if (dp == 0) != (tp == 0):
        raise ConfigError(
            "router.mesh_shape: dp and tp must both be 0 (off) or both >= 1"
        )
    if tp and (tp & (tp - 1)):
        raise ConfigError(
            "router.mesh_shape: tp must be a power of two (subscriber "
            "bitmap lanes are power-of-two words)"
        )
    if cfg.router.fanout_slots < 0:
        raise ConfigError(
            "router.fanout_slots must be >= 0 (0 = auto-size)"
        )
    if cfg.router.sub_table not in ("auto", "dense", "sparse"):
        raise ConfigError(
            "router.sub_table must be one of auto|dense|sparse"
        )
    if cfg.router.sub_table == "sparse" and not cfg.router.fanout_compact:
        raise ConfigError(
            "router.sub_table=sparse requires router.fanout_compact "
            "(the CSR table serves through the compact readback)"
        )
    if cfg.router.sparse_gather < 0:
        raise ConfigError(
            "router.sparse_gather must be >= 0 (0 = 2 x Kslot)"
        )
    if cfg.router.jit_cache_max < 0:
        raise ConfigError(
            "router.jit_cache_max must be >= 0 (0 = unbounded)"
        )
    if cfg.router.compact_hot_entries < 1:
        raise ConfigError("router.compact_hot_entries must be >= 1")
    if cfg.router.compact_interval_s < 0:
        raise ConfigError("router.compact_interval_s must be >= 0")
    if not (0.0 < cfg.router.compact_tombstone_frac <= 1.0):
        raise ConfigError(
            "router.compact_tombstone_frac must be in (0, 1]"
        )
    if cfg.retainer.storm_window_us < 0:
        raise ConfigError("retainer.storm_window_us must be >= 0")
    if not 1 <= cfg.semantic.dim <= 4096:
        raise ConfigError("semantic.dim must be in 1..4096")
    if not 1 <= cfg.semantic.topk <= 1024:
        raise ConfigError("semantic.topk must be in 1..1024")
    if not -1.0 <= cfg.semantic.threshold <= 1.0:
        raise ConfigError(
            "semantic.threshold must be in [-1, 1] (cosine similarity)"
        )
    if cfg.semantic.dtype not in ("float32", "bfloat16"):
        raise ConfigError("semantic.dtype must be float32|bfloat16")
    if cfg.semantic.enable and not cfg.router.fanout_compact:
        raise ConfigError(
            "semantic.enable requires router.fanout_compact (semantic "
            "winners union into the compact slot readback)"
        )
    if cfg.session.store_capacity < 64:
        raise ConfigError("session.store_capacity must be >= 64")
    if cfg.session.store_sweep_slots < 16:
        raise ConfigError("session.store_sweep_slots must be >= 16")
    if cfg.session.store_sweep_interval <= 0:
        raise ConfigError("session.store_sweep_interval must be > 0")
    for i, fr in enumerate(cfg.faults.rules):
        if fr.site not in FAULT_SITES:
            raise ConfigError(
                f"faults.rules[{i}].site {fr.site!r} is not a registered "
                f"fault site (one of {sorted(FAULT_SITES)})"
            )
        if fr.mode not in FAULT_MODES:
            raise ConfigError(
                f"faults.rules[{i}].mode {fr.mode!r} must be one of "
                f"{FAULT_MODES}"
            )
        if not 0.0 <= fr.probability <= 1.0:
            raise ConfigError(
                f"faults.rules[{i}].probability must be in [0, 1]"
            )
    if cfg.degrade.max_retries < 0:
        raise ConfigError("degrade.max_retries must be >= 0")
    if cfg.degrade.failure_threshold < 1:
        raise ConfigError("degrade.failure_threshold must be >= 1")
    if cfg.degrade.open_secs < 0:
        raise ConfigError("degrade.open_secs must be >= 0")
    if cfg.degrade.shed_queue_batches < 1:
        raise ConfigError("degrade.shed_queue_batches must be >= 1")
    if cfg.slo.target_p99_ms <= 0:
        raise ConfigError("slo.target_p99_ms must be > 0")
    if cfg.slo.min_window_us < 0:
        raise ConfigError("slo.min_window_us must be >= 0")
    if cfg.slo.max_window_us < cfg.slo.min_window_us:
        raise ConfigError(
            "slo.max_window_us must be >= slo.min_window_us"
        )
    if not 0.0 < cfg.slo.gain < 1.0:
        raise ConfigError("slo.gain must be in (0, 1)")
    if not 0.0 <= cfg.slo.hysteresis <= 1.0:
        raise ConfigError("slo.hysteresis must be in [0, 1]")
    if cfg.slo.ladder_patience < 1:
        raise ConfigError("slo.ladder_patience must be >= 1")
    if cfg.slo.shed_hard_mult < 1.0:
        raise ConfigError("slo.shed_hard_mult must be >= 1.0")
    if cfg.slo.eval_interval_ms <= 0:
        raise ConfigError("slo.eval_interval_ms must be > 0")
    if not 0.0 < cfg.slo.alarm_threshold <= 1.0:
        raise ConfigError("slo.alarm_threshold must be in (0, 1]")
    if cfg.cluster.send_retries < 0:
        raise ConfigError("cluster.send_retries must be >= 0")
    ss = cfg.cluster.shard_slice
    if (
        len(ss) != 2
        or not all(isinstance(v, int) for v in ss)
        or ss[1] < 1
        or not 0 <= ss[0] < ss[1]
    ):
        raise ConfigError(
            "cluster.shard_slice must be [index, total] with "
            "0 <= index < total"
        )
    from emqx_tpu.broker.limiter import TYPES as _LIMITER_TYPES

    for lt in cfg.limiter:
        if lt not in _LIMITER_TYPES:
            raise ConfigError(
                f"unknown limiter type {lt!r} (one of {_LIMITER_TYPES})"
            )
    if cfg.authz.deny_action not in ("ignore", "disconnect"):
        raise ConfigError("authz.deny_action must be ignore|disconnect")
    if not 0.0 < cfg.observe.tpu_fallback_alarm_threshold <= 1.0:
        raise ConfigError(
            "observe.tpu_fallback_alarm_threshold must be in (0, 1]"
        )
    for name, rate in [
        ("observe.trace_sample_rate", cfg.observe.trace_sample_rate),
        *(
            (f"observe.trace_sample_clients[{k!r}]", v)
            for k, v in cfg.observe.trace_sample_clients.items()
        ),
        *(
            (f"observe.trace_sample_topics[{k!r}]", v)
            for k, v in cfg.observe.trace_sample_topics.items()
        ),
    ]:
        if not 0.0 <= float(rate) <= 1.0:
            raise ConfigError(f"{name} must be in [0, 1]")
    if cfg.observe.retrace_alarm_threshold < 1:
        raise ConfigError("observe.retrace_alarm_threshold must be >= 1")
    if not 0 <= cfg.mqtt.max_qos_allowed <= 2:
        raise ConfigError("mqtt.max_qos_allowed must be 0..2")
    for r in cfg.rules:
        if not r.id or not r.sql:
            raise ConfigError("each rule needs an id and sql")
        from emqx_tpu.rules.sql import SqlParseError, parse_sql

        try:
            parse_sql(r.sql)
        except SqlParseError as e:
            raise ConfigError(f"rule {r.id}: bad sql: {e}") from e
        for o in r.outputs:
            if o.function not in ("console", "republish"):
                raise ConfigError(
                    f"rule {r.id}: unknown output {o.function!r}"
                )
