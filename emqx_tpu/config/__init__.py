"""Typed configuration system."""

from emqx_tpu.config.schema import (  # noqa: F401
    AppConfig,
    load_config,
    load_file,
)
