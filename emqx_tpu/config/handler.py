"""Runtime config-update pipeline.

Parity: apps/emqx/src/emqx_config_handler.erl + emqx_conf's cluster-wide
update flow — an update targets a dotted subtree path, is validated by
re-coercing the FULL config through the typed schema (a bad value rejects
the update before any side effect), then the most-specific registered
subtree handler applies the side effects (rebuild limiter buckets, swap
ACL rules, patch live caps). A handler raising rolls the stored config
back.

Cluster-wide propagation rides the replicated config txn log
(cluster/cluster_rpc.py, the emqx_cluster_rpc analog): `update` appends a
``config_update`` op when a log is attached; every node's handler applies
the same entry through `apply_entry`.
"""

from __future__ import annotations

import copy
import logging
from typing import Callable, Dict, List, Optional, Tuple

from emqx_tpu.config.schema import AppConfig, ConfigError, load_config, to_dict

log = logging.getLogger("emqx_tpu.config")

# handler: (new_config: AppConfig) -> None; raising rolls back
Handler = Callable[[AppConfig], None]

OP_CONFIG_UPDATE = "config_update"


class ConfigHandler:
    def __init__(self, get_config, set_config, conf_log=None):
        """get_config/set_config: accessors for the owning app's AppConfig.
        conf_log: optional ClusterRpcLog for cluster-wide updates."""
        self._get = get_config
        self._set = set_config
        self._handlers: List[Tuple[str, Handler]] = []
        self.conf_log = conf_log
        if conf_log is not None:
            conf_log.register_handler(
                OP_CONFIG_UPDATE, lambda path, value: self.apply_local(path, value)
            )

    def register(self, path: str, handler: Handler) -> None:
        """Register a side-effect handler for a config subtree
        (emqx_config_handler:add_handler)."""
        self._handlers.append((path, handler))
        # most specific prefix wins
        self._handlers.sort(key=lambda e: -len(e[0]))

    # -- update pipeline ---------------------------------------------------
    def update(self, path: str, value) -> Dict:
        """Validate + apply + (if clustered) replicate one subtree update.
        Returns the new subtree as a plain dict."""
        if self.conf_log is not None:
            # validate BEFORE the entry enters the replicated log — an
            # invalid update must never be committed cluster-wide
            self._merged_config(path, value)
            entry = self.conf_log.append(OP_CONFIG_UPDATE, (path, value))
            self.conf_log.apply_pending()
            if entry[0] in self.conf_log._skipped:
                raise RuntimeError(
                    f"config update {path} failed to apply on this node"
                )
        else:
            self.apply_local(path, value)
        return self.get_subtree(path)

    def _merged_config(self, path: str, value) -> AppConfig:
        """Merge `value` at `path` over the current config and run it
        through full schema validation; raises ConfigError on any problem."""
        data = to_dict(self._get())
        node = data
        segs = path.split(".") if path else []
        if not segs:
            raise ConfigError("empty config path")
        for s in segs[:-1]:
            if not isinstance(node.get(s), dict):
                raise ConfigError(f"no such config subtree: {path}")
            node = node[s]
        leaf = segs[-1]
        if leaf not in node:
            raise ConfigError(f"no such config key: {path}")
        if isinstance(node[leaf], dict) and isinstance(value, dict):
            node[leaf] = _deep_merge(node[leaf], value)
        else:
            node[leaf] = value
        return load_config(data)

    def apply_local(self, path: str, value) -> None:
        """The per-node half: validate, store, run side-effect handlers,
        roll back on failure."""
        old_cfg = self._get()
        new_cfg = self._merged_config(path, value)  # full-schema validation
        self._set(new_cfg)
        try:
            for prefix, handler in self._handlers:
                if path == prefix or path.startswith(prefix + "."):
                    handler(new_cfg)
                    break
        except Exception:
            self._set(old_cfg)
            raise

    def get_subtree(self, path: str) -> Dict:
        data = to_dict(self._get())
        for s in path.split("."):
            data = data[s]
        return data


def _deep_merge(base: Dict, over: Dict) -> Dict:
    out = copy.deepcopy(base)
    for k, v in over.items():
        if isinstance(out.get(k), dict) and isinstance(v, dict):
            out[k] = _deep_merge(out[k], v)
        else:
            out[k] = v
    return out
