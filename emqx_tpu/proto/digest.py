"""Structural digest canon for externalized formats.

One canonical, human-readable string per format structure — NOT a hash:
when a digest drifts, the diff in the golden pin file
(tests/fixtures/analysis/wire/digests.json) reads as the actual field
change, so review is "tlen moved from offset 0 to 2", never "sha256
changed".

This module is deliberately dependency-free (stdlib `struct` only): the
tier-A checkers (tools/analysis WF/SS/BP, pure AST, no broker imports)
recompute digests from AST-extracted literals with these exact
functions, and the runtime registry computes them from the same literal
declarations — one canonicalization, two call sites, zero drift between
the static and runtime views. dtype itemsize/offsets are derived here
from the type codes (packed layout, numpy `np.dtype([...])` default);
the tier-B audit cross-checks the derivation against the LIVE
`np.dtype` objects, so the shortcut cannot rot silently.
"""

from __future__ import annotations

import struct
from typing import Dict, Iterable, Mapping, Sequence, Tuple


def type_code_size(code: str) -> int:
    """Byte size of a numpy-style scalar type code ('<u2', 'u1', '<f8').

    Packed-layout helper for `dtype_digest`: endianness prefix optional,
    kind letter(s), then the byte count. Raises on anything the wire
    formats don't use (no sub-arrays, no strings, no objects).
    """
    c = code
    if c and c[0] in "<>=|":
        c = c[1:]
    kind = ""
    while c and c[0].isalpha():
        kind += c[0]
        c = c[1:]
    if not kind or not c or not c.isdigit():
        raise ValueError(f"unsupported dtype code {code!r}")
    return int(c)


def dtype_digest(fields: Sequence[Tuple[str, str]]) -> str:
    """Canonical digest of a packed structured dtype.

    `fields` is the literal `np.dtype([...])` field list:
    (name, type_code) pairs in declaration order. Offsets are the packed
    cumulative sizes — the layout `np.dtype(list)` produces.
    """
    parts = []
    off = 0
    for name, code in fields:
        parts.append(f"{name}:{code}@{off}")
        off += type_code_size(code)
    return "dtype{" + ",".join(parts) + "}#" + str(off)


def struct_digest(fmt: str) -> str:
    """Canonical digest of a `struct.Struct` format string."""
    return f"struct[{fmt}]#{struct.calcsize(fmt)}"


def tag_digest(tags: Mapping[str, object]) -> str:
    """Canonical digest of a tag table (frame types, message kinds).

    Values may be ints (frame type bytes) or the tag string itself
    (string-discriminated bus messages). Sorted by name so declaration
    order never matters.
    """
    parts = [f"{k}={tags[k]}" for k in sorted(tags)]
    return "tags{" + ",".join(parts) + "}"


def schema_digest(groups: Iterable[Iterable[str]]) -> str:
    """Canonical digest of a snapshot/capture schema: one key group per
    dict shape the root emits, each a sorted key set. Groups are sorted
    by their canonical form, so neither declaration order nor the
    checker's AST walk order matters."""
    canon = sorted("{" + ",".join(sorted(g)) + "}" for g in groups)
    return "keys{" + ";".join(canon) + "}"


def class_state_digest(
    fields: Iterable[str], drops: Iterable[str] = ()
) -> str:
    """Canonical digest of a pickled class's `__getstate__`-visible
    surface: the instance fields, minus the declared drops (fields the
    `__getstate__` must null/remove — live device handles, meshes)."""
    f = ",".join(sorted(fields))
    d = ",".join(sorted(drops))
    return f"state{{fields{{{f}}};drops{{{d}}}}}"


def proto_digest(table: Mapping[str, Mapping[int, Iterable[str]]]) -> str:
    """Canonical digest of a BPAPI proto table: api -> version ->
    method names. Frozen-per-version is the whole point, so versions
    render separately."""
    apis = []
    for api in sorted(table):
        vers = []
        for v in sorted(table[api]):
            methods = ",".join(sorted(table[api][v]))
            vers.append(f"v{v}{{{methods}}}")
        apis.append(f"{api}:" + ",".join(vers))
    return "bpapi{" + ";".join(apis) + "}"


def digest_for(kind: str, structure) -> str:
    """Dispatch: digest a structure literal by registry kind."""
    if kind == "dtype":
        return dtype_digest(structure)
    if kind == "struct":
        return struct_digest(structure)
    if kind == "tags":
        return tag_digest(structure)
    if kind == "schema":
        return schema_digest(structure)
    if kind == "class_state":
        fields, drops = structure
        return class_state_digest(fields, drops)
    if kind == "proto":
        return proto_digest(structure)
    raise ValueError(f"unknown format kind {kind!r}")


def parse_pin(doc: Dict) -> Dict[str, Tuple[int, str]]:
    """Golden pin file -> {name: (version, digest)}."""
    out: Dict[str, Tuple[int, str]] = {}
    for name, ent in doc.get("formats", {}).items():
        out[name] = (int(ent["version"]), str(ent["digest"]))
    return out
