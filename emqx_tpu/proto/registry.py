"""The versioned wire-format registry (BPAPI discipline for bytes).

Every externalized format — anything that leaves this process as bytes
and is decoded by a DIFFERENT process, version, or machine — declares
here: a stable name, a version, and the structure the digest canon
(emqx_tpu/proto/digest.py) renders into a pinned digest string.
Reference analog: the frozen `*_proto_vN` BPAPI modules under
apps/emqx/src/bpapi/ — a layout change without a version bump is a
contract violation, caught before it ships, not at a rolling upgrade.

Three consumers anchor on these declarations:

- the WF/SS/BP checkers (tools/analysis, tier A) AST-extract the
  `register(...)` calls below, recompute digests from the structure
  literals, and cross-check them against BOTH the defining code (the
  actual `np.dtype`/`struct.Struct`/tag/dict literals at the `source`
  pointers) and the golden pins in
  tests/fixtures/analysis/wire/digests.json;
- the tier-B wire-compat audit (`python -m tools.analysis --wirecompat`)
  verifies the same digests against the LIVE objects and replays the
  committed byte corpus (tests/fixtures/wire_corpus/) through the
  current decoders;
- humans: the `source` field is a clickable pointer to the layout.

Rules (enforced by WF + the audit):
- structure literals here must mirror the defining module EXACTLY;
- changing a structure requires bumping the version AND regenerating
  the pins + corpus (`--wirecompat --update-corpus`);
- every registered format keeps >= 1 committed corpus file.

This module imports nothing from the broker (the digest canon is
stdlib-only), so the registry is loadable anywhere — including the
analyzer's test fixtures and a bare management shell.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from emqx_tpu.proto.digest import digest_for

# -- structure literals (mirrors of the defining modules) -------------------

# transport/fabric.py PUB_HDR_DT / DLV_HDR_DT — the slab header tables
# (ROADMAP item 2 turns these into the acceptor->owner IPC layout)
FABRIC_PUB_HDR_FIELDS = (
    ("tlen", "<u2"), ("plen", "<u4"), ("clen", "<u2"),
    ("pblen", "<u4"), ("flags", "u1"),
)
FABRIC_DLV_HDR_FIELDS = (
    ("tlen", "<u2"), ("plen", "<u4"), ("clen", "<u2"),
    ("pblen", "<u4"), ("flags", "u1"), ("nh", "<u4"),
)

# transport/fabric.py T_* — the frame-type byte after the length prefix
FABRIC_FRAME_TYPES = {
    "T_HELLO": 0, "T_SUB": 1, "T_UNSUB": 2, "T_PUBB": 3, "T_DLV": 4,
    "T_PUBB_ACK": 5, "T_SUB_ACK": 6, "T_SESS": 7, "T_RAW": 8,
    "T_PUBB_S": 9, "T_DLV_S": 10,
}

# cluster/tcp_transport.py frame kinds + cluster/node.py payload dispatch
CLUSTER_BUS_KINDS = {
    "hello": "hello", "call": "call", "cast": "cast", "reply": "reply",
}
CLUSTER_PAYLOAD_KINDS = {"membership": "membership", "rpc": "rpc"}
MEMBERSHIP_TAGS = {
    "join": "join", "heartbeat": "heartbeat",
    "heartbeat_ack": "heartbeat_ack", "leave": "leave",
}
CLUSTER_RPC_KINDS = {"announce": "announce", "call": "call"}

# cluster/node.py _register_protos — the frozen BPAPI tables. The BP
# checker asserts the in-code register() calls spell EXACTLY this.
BPAPI = {
    "broker": {1: ("forward", "forward_batch")},
    "route": {1: ("add_route", "delete_route", "dump")},
    "cm": {1: ("insert_channel", "delete_channel", "lookup_channel",
               "discard")},
    "conf": {1: ("append", "receive_apply", "entries_after")},
    "shared": {1: ("join", "leave", "dump")},
    "shard": {1: ("advertise", "dump")},
    "retain": {1: ("store", "dump"),
               2: ("store", "dump", "dump_page")},
    "sess": {1: ("insert_parked", "delete_parked", "resume_begin",
                 "resume_end", "dump_parked"),
             2: ("insert_parked", "delete_parked", "resume_begin",
                 "resume_end", "dump_parked", "park_remote",
                 "park_append")},
}

# BPAPI methods registered for REMOTE callers with no local send site:
# the BP sender-symmetry check exempts exactly these, each justified.
BPAPI_SERVE_ONLY = {
    # registered so peers (and the management API) can resolve a
    # client's home node; local lookups call the method directly
    ("cm", "lookup_channel"),
}

# broker/persistent_session.py NS_* — FileKv namespace names (the
# durable snapshot "table names"; a rename orphans committed state)
DURABLE_NAMESPACES = {
    "NS_SESSIONS": "persistent_sessions", "NS_RETAINED": "retained",
    "NS_DELAYED": "delayed", "NS_BANNED": "banned",
    "NS_DEGRADE": "degrade", "NS_SEGMENTS": "segments",
}

# storage/codec.py JSON shapes (durable snapshots + cluster handoff)
MSG_JSON_KEYS = (
    ("topic", "payload", "qos", "retain", "dup", "from_client",
     "from_username", "mid", "headers", "properties", "timestamp"),
)
SUBOPTS_JSON_KEYS = (
    ("qos", "no_local", "retain_as_published", "retain_handling"),
)
SESSION_JSON_KEYS = (
    # the session snapshot itself ...
    ("client_id", "created_at", "expiry_interval", "next_pid",
     "subscriptions", "mqueue", "inflight", "awaiting_rel"),
    # ... and each inflight entry (ages, not raw monotonic stamps —
    # the PR 11 clock-rebase contract)
    ("pid", "phase", "age", "msg"),
)

# broker/persistent_session.py flush payloads
SESSIONS_NS_KEYS = (("at", "sessions"),)
DURABLE_STATE_KEYS = (
    ("paths",),                                   # degrade
    ("messages",),                                # retained
    ("at", "messages"),                           # delayed envelope
    ("remaining_s", "msg"),                       # delayed entry
    ("entries",),                                 # banned envelope
    ("kind", "value", "reason", "until", "by"),   # banned entry
)

# ops/segments.py SegmentStateSnapshot.save sidecar meta
SEGMENT_META_KEYS = (("path", "at", "keys"),)

# broker/session_store.py SessionStore.capture — the pickled
# segment-snapshot state for the device-resident session plane
SESSION_STORE_CAPTURE_KEYS = (
    ("table", "slab", "free_mids", "slots", "slot_cid", "free_slots",
     "t0_age_ds"),
)

# cluster/node.py park_session — the parked-session record shipped by
# sess.park_remote during drain handoff
SESS_PARK_KEYS = (("session", "deadline", "pending", "marker"),)

# pickled classes (cluster forward / segment snapshots). fields = the
# __getstate__-visible instance surface; drops = fields __getstate__
# MUST null (live device handles — the PR 10 unpicklable-mesh class)
MESSAGE_STATE = (
    ("topic", "payload", "qos", "retain", "dup", "from_client",
     "from_username", "mid", "headers", "properties", "timestamp"),
    (),
)
ROUTER_STATE = (
    ("_exact", "_trie", "_index", "_matcher", "_matcher_config",
     "min_tpu_batch", "enable_tpu", "mesh"),
    ("_matcher", "mesh"),
)


@dataclass(frozen=True)
class WireFormat:
    """One registered externalized format."""

    name: str
    version: int
    kind: str        # dtype | struct | tags | schema | class_state | proto
    digest: str      # canonical structural digest (digest.py)
    source: str      # "path/to/defining_module.py:SYMBOL" pointer
    doc: str = ""
    structure: object = field(default=None, compare=False, repr=False)


_FORMATS: Dict[str, WireFormat] = {}


class FormatError(Exception):
    pass


def register(
    name: str,
    version: int,
    kind: str,
    structure,
    source: str,
    doc: str = "",
) -> WireFormat:
    """Declare a format. Re-registering a name is a programming error —
    evolution means a version bump in place, never a second entry."""
    if name in _FORMATS:
        raise FormatError(f"wire format {name!r} already registered")
    fmt = WireFormat(
        name=name, version=version, kind=kind,
        digest=digest_for(kind, structure), source=source, doc=doc,
        structure=structure,
    )
    _FORMATS[name] = fmt
    return fmt


def formats() -> List[WireFormat]:
    return [_FORMATS[k] for k in sorted(_FORMATS)]


def get(name: str) -> Optional[WireFormat]:
    return _FORMATS.get(name)


def digest_of(name: str) -> str:
    fmt = _FORMATS.get(name)
    if fmt is None:
        raise FormatError(f"unknown wire format {name!r}")
    return fmt.digest


def pin_doc() -> Dict:
    """The golden-pin document shape for digests.json (repo formats
    only — fixture pins are maintained by hand next to the fixtures)."""
    return {
        "formats": {
            f.name: {"version": f.version, "digest": f.digest}
            for f in formats()
        }
    }


# -- registrations ----------------------------------------------------------

register(
    "fabric.slab.pub_hdr", 1, "dtype", FABRIC_PUB_HDR_FIELDS,
    "emqx_tpu/transport/fabric.py:PUB_HDR_DT",
    "slab PUBB_S per-record header table row (13B packed)",
)
register(
    "fabric.slab.dlv_hdr", 1, "dtype", FABRIC_DLV_HDR_FIELDS,
    "emqx_tpu/transport/fabric.py:DLV_HDR_DT",
    "slab DLV_S per-record header table row (17B packed, u32 nh)",
)
register(
    "fabric.frame_hdr", 1, "struct", "<IB",
    "emqx_tpu/transport/fabric.py:_HDR",
    "fabric frame prelude: u32 LE body length + u8 frame type",
)
register(
    "fabric.u16", 1, "struct", "<H",
    "emqx_tpu/transport/fabric.py:_U16",
    "legacy per-record wire: u16 LE length fields",
)
register(
    "fabric.u32", 1, "struct", "<I",
    "emqx_tpu/transport/fabric.py:_U32",
    "legacy per-record wire: u32 LE length/seq/count fields",
)
register(
    "fabric.frame_types", 1, "tags", FABRIC_FRAME_TYPES,
    "emqx_tpu/transport/fabric.py:T_*",
    "frame-type byte values (slab + legacy + control frames)",
)
register(
    "cluster.bus.len_prefix", 1, "struct", ">I",
    "emqx_tpu/cluster/tcp_transport.py:_LEN",
    "cluster bus frame prelude: u32 BE pickled-payload length",
)
register(
    "cluster.bus.kinds", 1, "tags", CLUSTER_BUS_KINDS,
    # "#pos0": the BP checker enforces sender/handler symmetry for
    # tuple[0] discriminators, with handlers in the fragment-less path
    "emqx_tpu/cluster/tcp_transport.py#pos0",
    "bus frame discriminators: (kind, req_id, payload) tuples",
)
register(
    "cluster.payload.kinds", 1, "tags", CLUSTER_PAYLOAD_KINDS,
    "emqx_tpu/cluster/node.py#pos0",
    "node-level payload dispatch: payload[0] families",
)
register(
    "membership.tags", 1, "tags", MEMBERSHIP_TAGS,
    # "#key=K": tuple[1] discriminators, gated on tuple[0] == K
    "emqx_tpu/cluster/membership.py#key=membership",
    "membership gossip ops: (\"membership\", tag, ...) tuples",
)
register(
    "cluster.rpc.kinds", 1, "tags", CLUSTER_RPC_KINDS,
    "emqx_tpu/cluster/rpc.py#key=rpc",
    "rpc envelope ops: (\"rpc\", kind, ...) tuples",
)
register(
    "cluster.bpapi", 1, "proto", BPAPI,
    "emqx_tpu/cluster/node.py:_register_protos",
    "frozen BPAPI proto tables: api -> version -> methods",
)
register(
    "durable.kv.namespaces", 1, "tags", DURABLE_NAMESPACES,
    "emqx_tpu/broker/persistent_session.py:NS_*",
    "FileKv namespace names for the durable snapshot plane",
)
register(
    "codec.msg_json", 1, "schema", MSG_JSON_KEYS,
    "emqx_tpu/storage/codec.py:msg_to_json",
    "Message JSON shape (durable stores + cluster handoff)",
)
register(
    "codec.subopts_json", 1, "schema", SUBOPTS_JSON_KEYS,
    "emqx_tpu/storage/codec.py:subopts_to_json",
    "SubOpts JSON shape inside session snapshots",
)
register(
    "codec.session_json", 1, "schema", SESSION_JSON_KEYS,
    "emqx_tpu/storage/codec.py:session_to_json",
    "session snapshot JSON: metadata + inflight AGE entries (PR 11)",
)
register(
    "durable.sessions_ns", 1, "schema", SESSIONS_NS_KEYS,
    "emqx_tpu/broker/persistent_session.py:SessionPersistence.flush",
    "NS_SESSIONS payload envelope; per-session snaps add "
    "expiry_remaining_s (legacy: wall-clock deadline, PR 15)",
)
register(
    "durable.state", 1, "schema", DURABLE_STATE_KEYS,
    "emqx_tpu/broker/persistent_session.py:DurableState.flush",
    "retained/delayed/banned/degrade kv payload shapes",
)
register(
    "snapshot.segment_meta", 1, "schema", SEGMENT_META_KEYS,
    "emqx_tpu/ops/segments.py:SegmentStateSnapshot.save",
    "segment-snapshot kv pointer meta (sidecar path + generation)",
)
register(
    "snapshot.session_store", 1, "schema", SESSION_STORE_CAPTURE_KEYS,
    "emqx_tpu/broker/session_store.py:SessionStore.capture",
    "device-resident session plane capture (pickled sidecar state)",
)
register(
    "cluster.sess.park", 1, "schema", SESS_PARK_KEYS,
    "emqx_tpu/cluster/node.py:ClusterNode.park_session",
    "parked-session record shipped by sess v2 park_remote",
)
register(
    "message.pickle", 1, "class_state", MESSAGE_STATE,
    "emqx_tpu/broker/message.py:Message",
    "pickled Message surface (cluster forward; slab msgs materialize)",
)
register(
    "router.pickle", 1, "class_state", ROUTER_STATE,
    "emqx_tpu/broker/router.py:Router",
    "pickled Router surface; __getstate__ MUST null the device-handle "
    "fields (the PR 10 unpicklable-mesh bug class)",
)
register(
    "mqtt.slab_serializer.u16be", 1, "struct", ">H",
    "emqx_tpu/mqtt/slab_serializer.py:_U16BE",
    "MQTT remaining-length-adjacent u16 BE fields in the slab "
    "serializer fast path",
)
register(
    "transport.dtls.record_hdr", 1, "struct", "!BHHHIH",
    "emqx_tpu/transport/dtls.py:_REC",
    "DTLS 1.2 record header (type, version, epoch, 48-bit seq, len)",
)
