"""Wire-contract plane: the versioned format registry + digest canon.

Every byte layout that crosses a process boundary (slab frames, cluster
bus pickles, durable snapshots) is declared once in
`emqx_tpu.proto.registry` with a name, a version, and a structural
digest. The static checkers (tools/analysis: WF/SS/BP) and the tier-B
wire-compat audit (`python -m tools.analysis --wirecompat`) both anchor
on these declarations — see docs/static_analysis.md.
"""
