"""Runtime-installable plugins.

Parity: apps/emqx_plugins/src/emqx_plugins.erl:72-91 — a plugin ships as
a ``.tar.gz`` package (name-version.tar.gz) containing:

    release.json      {"name", "version", "description", "entry"}
    <module>.py       (+ any support files)

Install extracts into the install dir, `start` imports the entry module
and calls its ``plugin_start(app)`` (symmetric ``plugin_stop(app)``), and
configured start ordering is applied at boot. Plugins attach to the same
hookpoints as built-in extensions — the in-process analog of exhook's
out-of-process extension model.
"""

from __future__ import annotations

import importlib.util
import json
import logging
import shutil
import sys
import tarfile
from pathlib import Path
from typing import Dict, List, Optional

log = logging.getLogger("emqx_tpu.plugins")


class PluginError(Exception):
    pass


class _Plugin:
    def __init__(self, name: str, version: str, dir_: Path, meta: Dict):
        self.name = name
        self.version = version
        self.dir = dir_
        self.meta = meta
        self.module = None
        self.running = False

    @property
    def ref(self) -> str:
        return f"{self.name}-{self.version}"


class PluginManager:
    def __init__(self, app, install_dir: str):
        self.app = app
        self.install_dir = Path(install_dir)
        self.install_dir.mkdir(parents=True, exist_ok=True)
        self._plugins: Dict[str, _Plugin] = {}  # "name-version" -> plugin
        self.scan()

    # -- discovery ---------------------------------------------------------
    def scan(self) -> None:
        """Pick up already-extracted plugin dirs (restart survival)."""
        for d in self.install_dir.iterdir() if self.install_dir.exists() else []:
            manifest = d / "release.json"
            if d.is_dir() and manifest.exists():
                try:
                    meta = json.loads(manifest.read_text())
                    p = _Plugin(meta["name"], meta["version"], d, meta)
                    self._plugins.setdefault(p.ref, p)
                except (ValueError, KeyError) as e:
                    log.warning("skipping bad plugin dir %s: %s", d, e)

    # -- lifecycle ---------------------------------------------------------
    def install(self, package_path: str) -> _Plugin:
        """Extract a plugin package (emqx_plugins:ensure_installed)."""
        with tarfile.open(package_path, "r:gz") as tf:
            names = tf.getnames()
            if "release.json" not in names:
                raise PluginError("package missing release.json")
            for n in names:
                if n.startswith(("/", "..")) or ".." in Path(n).parts:
                    raise PluginError(f"unsafe path in package: {n}")
            meta = json.loads(tf.extractfile("release.json").read())
            for key in ("name", "version", "entry"):
                if key not in meta:
                    raise PluginError(f"release.json missing {key!r}")
            ref = f"{meta['name']}-{meta['version']}"
            if ref in self._plugins:
                raise PluginError(f"plugin already installed: {ref}")
            dest = self.install_dir / ref
            dest.mkdir(parents=True, exist_ok=True)
            # filter="data" also rejects symlink/hardlink members that the
            # name check above cannot see (arbitrary-write hardening)
            tf.extractall(dest, filter="data")
        p = _Plugin(meta["name"], meta["version"], dest, meta)
        self._plugins[p.ref] = p
        log.info("plugin %s installed", p.ref)
        return p

    def start(self, ref: str) -> None:
        p = self._require(ref)
        if p.running:
            return
        if p.module is None:
            entry = p.meta["entry"]
            path = p.dir / f"{entry}.py"
            if not path.exists():
                raise PluginError(f"entry module not found: {path}")
            spec = importlib.util.spec_from_file_location(
                f"emqx_tpu_plugin_{p.name}", path
            )
            mod = importlib.util.module_from_spec(spec)
            sys.modules[spec.name] = mod
            spec.loader.exec_module(mod)
            p.module = mod
        starter = getattr(p.module, "plugin_start", None)
        if starter is None:
            raise PluginError(f"{ref}: no plugin_start(app) in entry module")
        starter(self.app)
        p.running = True
        log.info("plugin %s started", ref)

    def stop(self, ref: str) -> None:
        p = self._require(ref)
        if not p.running:
            return
        stopper = getattr(p.module, "plugin_stop", None)
        if stopper is not None:
            try:
                stopper(self.app)
            except Exception:
                log.exception("plugin %s stop failed", ref)
        p.running = False
        log.info("plugin %s stopped", ref)

    def uninstall(self, ref: str) -> None:
        p = self._require(ref)
        if p.running:
            self.stop(ref)
        shutil.rmtree(p.dir, ignore_errors=True)
        del self._plugins[ref]
        log.info("plugin %s uninstalled", ref)

    def stop_all(self) -> None:
        for ref, p in self._plugins.items():
            if p.running:
                self.stop(ref)

    def _require(self, ref: str) -> _Plugin:
        p = self._plugins.get(ref)
        if p is None:
            raise PluginError(f"no such plugin: {ref}")
        return p

    # -- introspection -----------------------------------------------------
    def list(self) -> List[Dict]:
        return [
            {
                "name": p.name,
                "version": p.version,
                "description": p.meta.get("description", ""),
                "running": p.running,
            }
            for p in self._plugins.values()
        ]
