"""Loader for the native MQTT codec (_codec.c).

Builds the C extension on first import when a compiler is available and
no prebuilt .so exists (cc -O2 -shared, ~1s; cached next to the source),
then exposes `split_frames` / `parse_publish` / `serialize_publish`.
`available` is False when the build fails or the platform lacks a
toolchain — callers (mqtt/frame.py) fall back to the pure-Python
reference codec, which stays the semantic source of truth and
differentially tests this module (tests/test_codec_native.py).
"""

from __future__ import annotations

import importlib.util
import logging
import os
import subprocess
import sysconfig

log = logging.getLogger("emqx_tpu.codec")

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "_codec.c")
_SO = os.path.join(
    _DIR, "_codec" + (sysconfig.get_config_var("EXT_SUFFIX") or ".so")
)

available = False
split_frames = None
parse_publish = None
serialize_publish = None
# worker-fabric record codec (transport/fabric.py hot path)
pack_dlv_frames = None
unpack_dlv_batch = None
pack_pub_batch = None
unpack_pub_batch = None


def _build() -> bool:
    cc = os.environ.get("CC", "cc")
    inc = sysconfig.get_path("include")
    # compile to a per-pid temp then rename: N worker processes may race
    # the first build, and a sibling must never dlopen a half-written .so
    tmp = f"{_SO}.{os.getpid()}.tmp"
    cmd = [
        cc, "-O2", "-fPIC", "-shared", "-o", tmp, _SRC, f"-I{inc}",
    ]
    try:
        r = subprocess.run(
            cmd, capture_output=True, text=True, timeout=120
        )
    except (OSError, subprocess.TimeoutExpired) as e:
        log.info("native codec build unavailable: %s", e)
        return False
    if r.returncode != 0:
        log.info("native codec build failed: %s", r.stderr[-500:])
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False
    os.replace(tmp, _SO)  # atomic on POSIX
    return True


def _load() -> None:
    global available, split_frames, parse_publish, serialize_publish
    if not os.path.exists(_SO) or (
        os.path.exists(_SRC)
        and os.path.getmtime(_SRC) > os.path.getmtime(_SO)
    ):
        if not _build():
            return
    try:
        spec = importlib.util.spec_from_file_location(
            "emqx_tpu.mqtt._codec", _SO
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
    except Exception as e:  # corrupt/ABI-mismatched .so: rebuild once
        log.info("native codec load failed (%s); rebuilding", e)
        try:
            os.unlink(_SO)
        except OSError:
            pass
        if not _build():
            return
        spec = importlib.util.spec_from_file_location(
            "emqx_tpu.mqtt._codec", _SO
        )
        mod = importlib.util.module_from_spec(spec)
        try:
            spec.loader.exec_module(mod)
        except Exception:
            return
    global pack_dlv_frames, unpack_dlv_batch, pack_pub_batch
    global unpack_pub_batch
    split_frames = mod.split_frames
    parse_publish = mod.parse_publish
    serialize_publish = mod.serialize_publish
    pack_dlv_frames = getattr(mod, "pack_dlv_frames", None)
    unpack_dlv_batch = getattr(mod, "unpack_dlv_batch", None)
    pack_pub_batch = getattr(mod, "pack_pub_batch", None)
    unpack_pub_batch = getattr(mod, "unpack_pub_batch", None)
    available = True


if os.environ.get("EMQX_TPU_NO_NATIVE_CODEC") != "1":
    _load()
