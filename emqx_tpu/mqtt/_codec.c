/* Native MQTT wire codec: the host data plane's hot path in C.
 *
 * The reference's codec is BEAM-native binary pattern matching
 * (apps/emqx/src/emqx_frame.erl:115-170 parse, :559-580 serialize);
 * a Python host pays ~10-20us per packet in pure-Python parsing. This
 * extension does the three per-message operations in C:
 *
 *   split_frames(buf, max_size)    -> ([(header, body_bytes)...], consumed)
 *   parse_publish(flags, body, v5) -> (topic, pid|None, props|None, payload)
 *   serialize_publish(topic_utf8, payload, qos, retain, dup, pid, props)
 *                                  -> complete wire frame, one allocation
 *
 * Anything outside the hot path (CONNECT, SUBSCRIBE, v5 property maps)
 * stays in the Python reference codec (emqx_tpu/mqtt/frame.py), which
 * differentially tests this module.  Built with the CPython C API —
 * no third-party binding dependency.
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <string.h>

/* -- varint ---------------------------------------------------------- */

static int
read_varint(const unsigned char *p, Py_ssize_t len, Py_ssize_t off,
            unsigned int *val, Py_ssize_t *end)
{
    unsigned int mult = 1, v = 0;
    for (int i = 0; i < 4; i++) {
        if (off + i >= len)
            return 1; /* need more */
        unsigned char b = p[off + i];
        v += (unsigned int)(b & 0x7F) * mult;
        if (!(b & 0x80)) {
            *val = v;
            *end = off + i + 1;
            return 0;
        }
        mult *= 128;
    }
    return -1; /* malformed */
}

static Py_ssize_t
write_varint(unsigned char *out, unsigned int n)
{
    Py_ssize_t i = 0;
    do {
        unsigned char b = n % 128;
        n /= 128;
        out[i++] = n ? (b | 0x80) : b;
    } while (n);
    return i;
}

/* -- split_frames ----------------------------------------------------- */

static PyObject *
split_frames(PyObject *self, PyObject *args)
{
    Py_buffer view;
    unsigned long max_size;
    if (!PyArg_ParseTuple(args, "y*k", &view, &max_size))
        return NULL;
    const unsigned char *p = (const unsigned char *)view.buf;
    Py_ssize_t len = view.len, off = 0;
    PyObject *frames = PyList_New(0);
    if (!frames) {
        PyBuffer_Release(&view);
        return NULL;
    }
    while (len - off >= 2) {
        unsigned int rem;
        Py_ssize_t body_off;
        int rc = read_varint(p, len, off + 1, &rem, &body_off);
        if (rc == 1)
            break; /* partial varint */
        if (rc < 0) {
            Py_DECREF(frames);
            PyBuffer_Release(&view);
            PyErr_SetString(PyExc_ValueError, "malformed_varint");
            return NULL;
        }
        if (rem > max_size) {
            Py_DECREF(frames);
            PyBuffer_Release(&view);
            PyErr_SetString(PyExc_ValueError, "frame_too_large");
            return NULL;
        }
        if (body_off + (Py_ssize_t)rem > len)
            break; /* partial body */
        PyObject *body = PyBytes_FromStringAndSize(
            (const char *)p + body_off, (Py_ssize_t)rem);
        if (!body)
            goto fail;
        PyObject *tup = Py_BuildValue("(iN)", (int)p[off], body);
        if (!tup)
            goto fail;
        if (PyList_Append(frames, tup) < 0) {
            Py_DECREF(tup);
            goto fail;
        }
        Py_DECREF(tup);
        off = body_off + (Py_ssize_t)rem;
    }
    PyBuffer_Release(&view);
    return Py_BuildValue("(Nn)", frames, off);
fail:
    Py_DECREF(frames);
    PyBuffer_Release(&view);
    return NULL;
}

/* -- parse_publish ----------------------------------------------------- */

static PyObject *
parse_publish(PyObject *self, PyObject *args)
{
    int flags, v5;
    Py_buffer view;
    if (!PyArg_ParseTuple(args, "iy*i", &flags, &view, &v5))
        return NULL;
    const unsigned char *p = (const unsigned char *)view.buf;
    Py_ssize_t len = view.len, off = 0;
    int qos = (flags >> 1) & 3;
    if (qos == 3) {
        PyBuffer_Release(&view);
        PyErr_SetString(PyExc_ValueError, "bad_qos");
        return NULL;
    }
    if (len < 2)
        goto truncated;
    Py_ssize_t tlen = ((Py_ssize_t)p[0] << 8) | p[1];
    off = 2;
    if (off + tlen > len)
        goto truncated;
    PyObject *topic = PyUnicode_DecodeUTF8(
        (const char *)p + off, tlen, "strict");
    if (!topic) {
        PyBuffer_Release(&view);
        return NULL;
    }
    off += tlen;
    PyObject *pid = Py_None;
    Py_INCREF(Py_None);
    if (qos > 0) {
        if (off + 2 > len) {
            Py_DECREF(topic);
            Py_DECREF(pid);
            goto truncated;
        }
        Py_DECREF(pid);
        pid = PyLong_FromLong(((long)p[off] << 8) | p[off + 1]);
        off += 2;
    }
    PyObject *props = Py_None;
    Py_INCREF(Py_None);
    if (v5) {
        unsigned int plen;
        Py_ssize_t pend;
        int rc = read_varint(p, len, off, &plen, &pend);
        if (rc != 0 || pend + (Py_ssize_t)plen > len) {
            Py_DECREF(topic);
            Py_DECREF(pid);
            Py_DECREF(props);
            goto truncated;
        }
        if (plen > 0) {
            Py_DECREF(props);
            props = PyBytes_FromStringAndSize(
                (const char *)p + pend, (Py_ssize_t)plen);
            if (!props) {
                Py_DECREF(topic);
                Py_DECREF(pid);
                PyBuffer_Release(&view);
                return NULL;
            }
        }
        off = pend + (Py_ssize_t)plen;
    }
    PyObject *payload = PyBytes_FromStringAndSize(
        (const char *)p + off, len - off);
    PyBuffer_Release(&view);
    if (!payload) {
        Py_DECREF(topic);
        Py_DECREF(pid);
        Py_DECREF(props);
        return NULL;
    }
    return Py_BuildValue("(NNNN)", topic, pid, props, payload);
truncated:
    PyBuffer_Release(&view);
    PyErr_SetString(PyExc_ValueError, "frame_truncated");
    return NULL;
}

/* -- serialize_publish -------------------------------------------------- */

static PyObject *
serialize_publish(PyObject *self, PyObject *args)
{
    Py_buffer topic, payload, props;
    int qos, retain, dup, pid, v5;
    if (!PyArg_ParseTuple(args, "y*y*iiiiy*i", &topic, &payload, &qos,
                          &retain, &dup, &pid, &props, &v5))
        return NULL;
    if (topic.len > 0xFFFF) {
        PyBuffer_Release(&topic);
        PyBuffer_Release(&payload);
        PyBuffer_Release(&props);
        PyErr_SetString(PyExc_ValueError, "utf8_string_too_long");
        return NULL;
    }
    /* body = topic_len(2) + topic + [pid(2)] + [props] + payload */
    Py_ssize_t body = 2 + topic.len + (qos > 0 ? 2 : 0)
                      + (v5 ? props.len : 0) + payload.len;
    if (body > 0xFFFFFFF) { /* varint ceiling, matching encode_varint */
        PyBuffer_Release(&topic);
        PyBuffer_Release(&payload);
        PyBuffer_Release(&props);
        PyErr_SetString(PyExc_ValueError, "varint_out_of_range");
        return NULL;
    }
    unsigned char hdr[6];
    hdr[0] = (unsigned char)((3 << 4) | ((dup ? 1 : 0) << 3)
                             | ((qos & 3) << 1) | (retain ? 1 : 0));
    Py_ssize_t vlen = write_varint(hdr + 1, (unsigned int)body);
    PyObject *out = PyBytes_FromStringAndSize(NULL, 1 + vlen + body);
    if (!out) {
        PyBuffer_Release(&topic);
        PyBuffer_Release(&payload);
        PyBuffer_Release(&props);
        return NULL;
    }
    unsigned char *w = (unsigned char *)PyBytes_AS_STRING(out);
    memcpy(w, hdr, 1 + vlen);
    w += 1 + vlen;
    *w++ = (unsigned char)(topic.len >> 8);
    *w++ = (unsigned char)(topic.len & 0xFF);
    memcpy(w, topic.buf, topic.len);
    w += topic.len;
    if (qos > 0) {
        *w++ = (unsigned char)((pid >> 8) & 0xFF);
        *w++ = (unsigned char)(pid & 0xFF);
    }
    if (v5) {
        memcpy(w, props.buf, props.len);
        w += props.len;
    }
    memcpy(w, payload.buf, payload.len);
    PyBuffer_Release(&topic);
    PyBuffer_Release(&payload);
    PyBuffer_Release(&props);
    return out;
}

/* -- worker-fabric record codec ---------------------------------------
 *
 * The router<->worker fabric (transport/fabric.py) moves every message
 * of the multi-process host data plane; packing its records in Python
 * was the single largest router-process cost in the serving profile.
 * Wire format mirrors fabric.py exactly (it differentially tests this):
 *
 *   pub_record: u16 tlen, topic, u32 plen, payload,
 *               u8 flags (qos | retain<<2 | dup<<3 | has_props<<4),
 *               u16 clen, client, [u32 pblen, props] (iff has_props)
 *   dlv_record: pub_record head (flags bit3 = retained)
 *               + u16 ntargets + ntargets * u32 handle
 *   frame:      u32 len (excl. 5-byte header), u8 type, body
 *
 * The PACK functions here never set has_props (the Python wrapper
 * routes props-carrying batches to the reference packer); the UNPACK
 * functions handle both forms, returning the raw props block for the
 * wrapper to decode.
 */

#define FAB_T_PUBB 3
#define FAB_T_DLV 4

typedef struct {
    const char *topic; Py_ssize_t tlen;
    const char *payload; Py_ssize_t plen;
    const char *client; Py_ssize_t clen;
    unsigned char flags;
    PyObject *handles; /* borrowed; NULL for pub records */
    Py_ssize_t nh;     /* len(handles) */
} fab_rec;

/* Read one message's wire fields.  Returns 0 ok, -1 error.
 * `retained_hdr`: for DLV records bit3 comes from headers["retained"];
 * for PUBB records it is the dup flag. */
static int
fab_read_msg(PyObject *msg, fab_rec *r, int is_dlv)
{
    PyObject *topic = PyObject_GetAttrString(msg, "topic");
    if (!topic) return -1;
    r->topic = PyUnicode_AsUTF8AndSize(topic, &r->tlen);
    Py_DECREF(topic); /* interned in the Message; borrow survives */
    if (!r->topic) return -1;
    if (r->tlen > 0xFFFF) {
        PyErr_SetString(PyExc_ValueError, "fabric topic too long");
        return -1;
    }
    PyObject *payload = PyObject_GetAttrString(msg, "payload");
    if (!payload) return -1;
    if (payload == Py_None) { r->payload = ""; r->plen = 0; }
    else if (PyBytes_Check(payload)) {
        r->payload = PyBytes_AS_STRING(payload);
        r->plen = PyBytes_GET_SIZE(payload);
    } else {
        Py_DECREF(payload);
        PyErr_SetString(PyExc_TypeError, "payload must be bytes");
        return -1;
    }
    Py_DECREF(payload); /* Message holds a ref; borrow survives */
    PyObject *client = PyObject_GetAttrString(msg, "from_client");
    if (!client) return -1;
    if (client == Py_None) { r->client = ""; r->clen = 0; }
    else {
        r->client = PyUnicode_AsUTF8AndSize(client, &r->clen);
        if (!r->client) { Py_DECREF(client); return -1; }
    }
    Py_DECREF(client);
    if (r->clen > 0xFFFF) {
        PyErr_SetString(PyExc_ValueError, "fabric client id too long");
        return -1;
    }
    PyObject *qos = PyObject_GetAttrString(msg, "qos");
    if (!qos) return -1;
    long q = PyLong_AsLong(qos);
    Py_DECREF(qos);
    if (q == -1 && PyErr_Occurred()) return -1;
    PyObject *retain = PyObject_GetAttrString(msg, "retain");
    if (!retain) return -1;
    int ret = PyObject_IsTrue(retain);
    Py_DECREF(retain);
    if (ret < 0) return -1;
    int bit3 = 0;
    if (is_dlv) {
        PyObject *headers = PyObject_GetAttrString(msg, "headers");
        if (!headers) return -1;
        if (PyDict_Check(headers)) {
            PyObject *rv = PyDict_GetItemString(headers, "retained");
            bit3 = rv ? PyObject_IsTrue(rv) : 0;
        }
        Py_DECREF(headers);
        if (bit3 < 0) return -1;
    } else {
        PyObject *dup = PyObject_GetAttrString(msg, "dup");
        if (dup) { bit3 = PyObject_IsTrue(dup); Py_DECREF(dup); }
        else { PyErr_Clear(); bit3 = 0; }
        if (bit3 < 0) return -1;
    }
    r->flags = (unsigned char)((q & 3) | (ret ? 4 : 0) | (bit3 ? 8 : 0));
    return 0;
}

static void
fab_write_head(unsigned char **wp, const fab_rec *r)
{
    unsigned char *w = *wp;
    *w++ = (unsigned char)(r->tlen & 0xFF);
    *w++ = (unsigned char)(r->tlen >> 8);
    memcpy(w, r->topic, r->tlen); w += r->tlen;
    *w++ = (unsigned char)(r->plen & 0xFF);
    *w++ = (unsigned char)((r->plen >> 8) & 0xFF);
    *w++ = (unsigned char)((r->plen >> 16) & 0xFF);
    *w++ = (unsigned char)((r->plen >> 24) & 0xFF);
    memcpy(w, r->payload, r->plen); w += r->plen;
    *w++ = r->flags;
    *w++ = (unsigned char)(r->clen & 0xFF);
    *w++ = (unsigned char)(r->clen >> 8);
    memcpy(w, r->client, r->clen); w += r->clen;
    *wp = w;
}

/* pack_dlv_frames(records, max_body) -> [frame_bytes, ...]
 * records: [(msg, [handle, ...])]; splits >0xFFFF handle fan-outs and
 * bounds each frame body by ~max_body (always >= 1 record per frame). */
static PyObject *
pack_dlv_frames(PyObject *self, PyObject *args)
{
    PyObject *records;
    Py_ssize_t max_body;
    if (!PyArg_ParseTuple(args, "On", &records, &max_body))
        return NULL;
    PyObject *seq = PySequence_Fast(records, "records must be a sequence");
    if (!seq) return NULL;
    Py_ssize_t n_in = PySequence_Fast_GET_SIZE(seq);
    fab_rec *recs = PyMem_Malloc(
        (n_in ? n_in : 1) * sizeof(fab_rec));
    if (!recs) { Py_DECREF(seq); return PyErr_NoMemory(); }
    PyObject *frames = PyList_New(0);
    if (!frames) { PyMem_Free(recs); Py_DECREF(seq); return NULL; }

    Py_ssize_t n_recs = 0;
    for (Py_ssize_t i = 0; i < n_in; i++) {
        PyObject *item = PySequence_Fast_GET_ITEM(seq, i);
        PyObject *msg, *handles;
        if (!PyArg_ParseTuple(item, "OO", &msg, &handles))
            goto fail;
        Py_ssize_t nh = PyList_Check(handles)
            ? PyList_GET_SIZE(handles) : PySequence_Size(handles);
        if (nh < 0) goto fail;
        if (nh == 0)
            continue; /* no targets: the Python codec emits nothing */
        if (fab_read_msg(msg, &recs[n_recs], 1) < 0)
            goto fail;
        recs[n_recs].handles = handles;
        recs[n_recs].nh = nh;
        n_recs++;
    }
    n_in = n_recs;

    /* emit frames: walk records, splitting handle lists at 0xFFFF and
     * frames at max_body */
    Py_ssize_t i = 0, hoff = 0;
    while (i < n_in) {
        /* measure this frame */
        Py_ssize_t body = 4, n_rec = 0;
        Py_ssize_t j = i, jh = hoff;
        while (j < n_in) {
            Py_ssize_t total_h = recs[j].nh;
            Py_ssize_t chunk = total_h - jh;
            if (chunk > 0xFFFF) chunk = 0xFFFF;
            Py_ssize_t rec_len = 9 + recs[j].tlen + recs[j].plen
                                 + recs[j].clen + 2 + 4 * chunk;
            /* boundary matches fabric.pack_dlv_batches exactly (it
             * counts the 5-byte frame header too) so the two codecs
             * produce byte-identical frame splits */
            if (n_rec && 5 + body + rec_len > max_body)
                break;
            body += rec_len;
            n_rec++;
            jh += chunk;
            if (jh >= total_h) { j++; jh = 0; }
        }
        PyObject *frame = PyBytes_FromStringAndSize(NULL, 5 + body);
        if (!frame) goto fail;
        unsigned char *w = (unsigned char *)PyBytes_AS_STRING(frame);
        *w++ = (unsigned char)(body & 0xFF);
        *w++ = (unsigned char)((body >> 8) & 0xFF);
        *w++ = (unsigned char)((body >> 16) & 0xFF);
        *w++ = (unsigned char)((body >> 24) & 0xFF);
        *w++ = FAB_T_DLV;
        *w++ = (unsigned char)(n_rec & 0xFF);
        *w++ = (unsigned char)((n_rec >> 8) & 0xFF);
        *w++ = (unsigned char)((n_rec >> 16) & 0xFF);
        *w++ = (unsigned char)((n_rec >> 24) & 0xFF);
        /* fill */
        Py_ssize_t emitted = 0;
        while (emitted < n_rec) {
            PyObject *hl = recs[i].handles;
            Py_ssize_t total_h = recs[i].nh;
            Py_ssize_t chunk = total_h - hoff;
            if (chunk > 0xFFFF) chunk = 0xFFFF;
            fab_write_head(&w, &recs[i]);
            *w++ = (unsigned char)(chunk & 0xFF);
            *w++ = (unsigned char)(chunk >> 8);
            for (Py_ssize_t k = 0; k < chunk; k++) {
                PyObject *h = PyList_Check(hl)
                    ? PyList_GET_ITEM(hl, hoff + k)
                    : NULL;
                unsigned long hv;
                if (h) hv = PyLong_AsUnsignedLong(h);
                else {
                    PyObject *hi = PySequence_GetItem(hl, hoff + k);
                    if (!hi) { Py_DECREF(frame); goto fail; }
                    hv = PyLong_AsUnsignedLong(hi);
                    Py_DECREF(hi);
                }
                if (hv == (unsigned long)-1 && PyErr_Occurred()) {
                    Py_DECREF(frame); goto fail;
                }
                *w++ = (unsigned char)(hv & 0xFF);
                *w++ = (unsigned char)((hv >> 8) & 0xFF);
                *w++ = (unsigned char)((hv >> 16) & 0xFF);
                *w++ = (unsigned char)((hv >> 24) & 0xFF);
            }
            emitted++;
            hoff += chunk;
            if (hoff >= total_h) { i++; hoff = 0; }
        }
        if (PyList_Append(frames, frame) < 0) {
            Py_DECREF(frame); goto fail;
        }
        Py_DECREF(frame);
    }
    PyMem_Free(recs);
    Py_DECREF(seq);
    return frames;
fail:
    PyMem_Free(recs);
    Py_DECREF(seq);
    Py_DECREF(frames);
    return NULL;
}

/* unpack_dlv_batch(body) ->
 *   [(topic, payload, qos, retain, retained, client, [handles])] */
static PyObject *
unpack_dlv_batch(PyObject *self, PyObject *args)
{
    Py_buffer view;
    if (!PyArg_ParseTuple(args, "y*", &view))
        return NULL;
    const unsigned char *p = (const unsigned char *)view.buf;
    Py_ssize_t len = view.len, off = 4;
    if (len < 4) goto truncated;
    unsigned long n = (unsigned long)p[0] | ((unsigned long)p[1] << 8)
        | ((unsigned long)p[2] << 16) | ((unsigned long)p[3] << 24);
    PyObject *out = PyList_New(0);
    if (!out) { PyBuffer_Release(&view); return NULL; }
    for (unsigned long i = 0; i < n; i++) {
        if (off + 2 > len) goto trunc_out;
        Py_ssize_t tlen = (Py_ssize_t)p[off] | ((Py_ssize_t)p[off+1] << 8);
        off += 2;
        if (off + tlen + 4 > len) goto trunc_out;
        PyObject *topic = PyUnicode_DecodeUTF8(
            (const char *)p + off, tlen, "strict");
        if (!topic) goto err_out;
        off += tlen;
        Py_ssize_t plen = (Py_ssize_t)p[off] | ((Py_ssize_t)p[off+1] << 8)
            | ((Py_ssize_t)p[off+2] << 16) | ((Py_ssize_t)p[off+3] << 24);
        off += 4;
        if (off + plen + 3 > len) { Py_DECREF(topic); goto trunc_out; }
        PyObject *payload = PyBytes_FromStringAndSize(
            (const char *)p + off, plen);
        if (!payload) { Py_DECREF(topic); goto err_out; }
        off += plen;
        unsigned char flags = p[off++];
        Py_ssize_t clen = (Py_ssize_t)p[off] | ((Py_ssize_t)p[off+1] << 8);
        off += 2;
        if (off + clen + 2 > len) {
            Py_DECREF(topic); Py_DECREF(payload); goto trunc_out;
        }
        PyObject *client = PyUnicode_DecodeUTF8(
            (const char *)p + off, clen, "strict");
        if (!client) { Py_DECREF(topic); Py_DECREF(payload); goto err_out; }
        off += clen;
        /* flags bit 4: optional MQTT5 property block (raw bytes here;
         * the Python wrapper decodes) */
        PyObject *props = Py_None;
        Py_INCREF(Py_None);
        if (flags & 0x10) {
            if (off + 4 > len) {
                Py_DECREF(topic); Py_DECREF(payload); Py_DECREF(client);
                Py_DECREF(props); goto trunc_out;
            }
            Py_ssize_t pbl = (Py_ssize_t)p[off]
                | ((Py_ssize_t)p[off+1] << 8)
                | ((Py_ssize_t)p[off+2] << 16)
                | ((Py_ssize_t)p[off+3] << 24);
            off += 4;
            if (off + pbl + 2 > len) {
                Py_DECREF(topic); Py_DECREF(payload); Py_DECREF(client);
                Py_DECREF(props); goto trunc_out;
            }
            Py_DECREF(props);
            props = PyBytes_FromStringAndSize((const char *)p + off, pbl);
            if (!props) {
                Py_DECREF(topic); Py_DECREF(payload); Py_DECREF(client);
                goto err_out;
            }
            off += pbl;
        }
        Py_ssize_t nh = (Py_ssize_t)p[off] | ((Py_ssize_t)p[off+1] << 8);
        off += 2;
        if (off + 4 * nh > len) {
            Py_DECREF(topic); Py_DECREF(payload); Py_DECREF(client);
            Py_DECREF(props); goto trunc_out;
        }
        PyObject *handles = PyList_New(nh);
        if (!handles) {
            Py_DECREF(topic); Py_DECREF(payload); Py_DECREF(client);
            goto err_out;
        }
        for (Py_ssize_t k = 0; k < nh; k++) {
            unsigned long hv = (unsigned long)p[off]
                | ((unsigned long)p[off+1] << 8)
                | ((unsigned long)p[off+2] << 16)
                | ((unsigned long)p[off+3] << 24);
            off += 4;
            PyObject *h = PyLong_FromUnsignedLong(hv);
            if (!h) {
                Py_DECREF(topic); Py_DECREF(payload); Py_DECREF(client);
                Py_DECREF(handles); goto err_out;
            }
            PyList_SET_ITEM(handles, k, h);
        }
        PyObject *tup = Py_BuildValue(
            "(NNiOONNN)", topic, payload, (int)(flags & 3),
            (flags & 4) ? Py_True : Py_False,
            (flags & 8) ? Py_True : Py_False,
            client, props, handles);
        if (!tup) goto err_out;
        if (PyList_Append(out, tup) < 0) { Py_DECREF(tup); goto err_out; }
        Py_DECREF(tup);
    }
    PyBuffer_Release(&view);
    return out;
trunc_out:
    Py_DECREF(out);
    goto truncated;
err_out:
    Py_DECREF(out);
    PyBuffer_Release(&view);
    return NULL;
truncated:
    PyBuffer_Release(&view);
    PyErr_SetString(PyExc_ValueError, "dlv_batch_truncated");
    return NULL;
}

/* pack_pub_batch(msgs, seq) -> one PUBB frame (worker -> router) */
static PyObject *
pack_pub_batch_c(PyObject *self, PyObject *args)
{
    PyObject *msgs;
    unsigned long seqno;
    if (!PyArg_ParseTuple(args, "Ok", &msgs, &seqno))
        return NULL;
    PyObject *seq = PySequence_Fast(msgs, "msgs must be a sequence");
    if (!seq) return NULL;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    fab_rec *recs = PyMem_Malloc((n ? n : 1) * sizeof(fab_rec));
    if (!recs) { Py_DECREF(seq); return PyErr_NoMemory(); }
    Py_ssize_t body = 8;
    for (Py_ssize_t i = 0; i < n; i++) {
        if (fab_read_msg(PySequence_Fast_GET_ITEM(seq, i),
                         &recs[i], 0) < 0) {
            PyMem_Free(recs); Py_DECREF(seq); return NULL;
        }
        body += 9 + recs[i].tlen + recs[i].plen + recs[i].clen;
    }
    PyObject *frame = PyBytes_FromStringAndSize(NULL, 5 + body);
    if (!frame) { PyMem_Free(recs); Py_DECREF(seq); return NULL; }
    unsigned char *w = (unsigned char *)PyBytes_AS_STRING(frame);
    *w++ = (unsigned char)(body & 0xFF);
    *w++ = (unsigned char)((body >> 8) & 0xFF);
    *w++ = (unsigned char)((body >> 16) & 0xFF);
    *w++ = (unsigned char)((body >> 24) & 0xFF);
    *w++ = FAB_T_PUBB;
    *w++ = (unsigned char)(seqno & 0xFF);
    *w++ = (unsigned char)((seqno >> 8) & 0xFF);
    *w++ = (unsigned char)((seqno >> 16) & 0xFF);
    *w++ = (unsigned char)((seqno >> 24) & 0xFF);
    *w++ = (unsigned char)(n & 0xFF);
    *w++ = (unsigned char)((n >> 8) & 0xFF);
    *w++ = (unsigned char)((n >> 16) & 0xFF);
    *w++ = (unsigned char)((n >> 24) & 0xFF);
    for (Py_ssize_t i = 0; i < n; i++)
        fab_write_head(&w, &recs[i]);
    PyMem_Free(recs);
    Py_DECREF(seq);
    return frame;
}

/* unpack_pub_batch(body) ->
 *   (seq, [(topic, payload, qos, retain, dup, client)]) */
static PyObject *
unpack_pub_batch_c(PyObject *self, PyObject *args)
{
    Py_buffer view;
    if (!PyArg_ParseTuple(args, "y*", &view))
        return NULL;
    const unsigned char *p = (const unsigned char *)view.buf;
    Py_ssize_t len = view.len, off = 8;
    if (len < 8) goto truncated;
    unsigned long seqno = (unsigned long)p[0] | ((unsigned long)p[1] << 8)
        | ((unsigned long)p[2] << 16) | ((unsigned long)p[3] << 24);
    unsigned long n = (unsigned long)p[4] | ((unsigned long)p[5] << 8)
        | ((unsigned long)p[6] << 16) | ((unsigned long)p[7] << 24);
    PyObject *out = PyList_New(0);
    if (!out) { PyBuffer_Release(&view); return NULL; }
    for (unsigned long i = 0; i < n; i++) {
        if (off + 2 > len) goto trunc_out;
        Py_ssize_t tlen = (Py_ssize_t)p[off] | ((Py_ssize_t)p[off+1] << 8);
        off += 2;
        if (off + tlen + 4 > len) goto trunc_out;
        PyObject *topic = PyUnicode_DecodeUTF8(
            (const char *)p + off, tlen, "strict");
        if (!topic) goto err_out;
        off += tlen;
        Py_ssize_t plen = (Py_ssize_t)p[off] | ((Py_ssize_t)p[off+1] << 8)
            | ((Py_ssize_t)p[off+2] << 16) | ((Py_ssize_t)p[off+3] << 24);
        off += 4;
        if (off + plen + 3 > len) { Py_DECREF(topic); goto trunc_out; }
        PyObject *payload = PyBytes_FromStringAndSize(
            (const char *)p + off, plen);
        if (!payload) { Py_DECREF(topic); goto err_out; }
        off += plen;
        unsigned char flags = p[off++];
        Py_ssize_t clen = (Py_ssize_t)p[off] | ((Py_ssize_t)p[off+1] << 8);
        off += 2;
        if (off + clen > len) {
            Py_DECREF(topic); Py_DECREF(payload); goto trunc_out;
        }
        PyObject *client = PyUnicode_DecodeUTF8(
            (const char *)p + off, clen, "strict");
        if (!client) { Py_DECREF(topic); Py_DECREF(payload); goto err_out; }
        off += clen;
        /* flags bit 4: optional MQTT5 property block (raw bytes; the
         * Python wrapper decodes) */
        PyObject *pprops = Py_None;
        Py_INCREF(Py_None);
        if (flags & 0x10) {
            if (off + 4 > len) {
                Py_DECREF(topic); Py_DECREF(payload); Py_DECREF(client);
                Py_DECREF(pprops); goto trunc_out;
            }
            Py_ssize_t pbl = (Py_ssize_t)p[off]
                | ((Py_ssize_t)p[off+1] << 8)
                | ((Py_ssize_t)p[off+2] << 16)
                | ((Py_ssize_t)p[off+3] << 24);
            off += 4;
            if (off + pbl > len) {
                Py_DECREF(topic); Py_DECREF(payload); Py_DECREF(client);
                Py_DECREF(pprops); goto trunc_out;
            }
            Py_DECREF(pprops);
            pprops = PyBytes_FromStringAndSize((const char *)p + off, pbl);
            if (!pprops) {
                Py_DECREF(topic); Py_DECREF(payload); Py_DECREF(client);
                goto err_out;
            }
            off += pbl;
        }
        PyObject *tup = Py_BuildValue(
            "(NNiOONN)", topic, payload, (int)(flags & 3),
            (flags & 4) ? Py_True : Py_False,
            (flags & 8) ? Py_True : Py_False,
            client, pprops);
        if (!tup) goto err_out;
        if (PyList_Append(out, tup) < 0) { Py_DECREF(tup); goto err_out; }
        Py_DECREF(tup);
    }
    PyBuffer_Release(&view);
    return Py_BuildValue("(kN)", seqno, out);
trunc_out:
    Py_DECREF(out);
    goto truncated;
err_out:
    Py_DECREF(out);
    PyBuffer_Release(&view);
    return NULL;
truncated:
    PyBuffer_Release(&view);
    PyErr_SetString(PyExc_ValueError, "pub_batch_truncated");
    return NULL;
}

/* -- module ----------------------------------------------------------- */

static PyMethodDef methods[] = {
    {"split_frames", split_frames, METH_VARARGS,
     "split_frames(buf, max_size) -> ([(header, body)...], consumed)"},
    {"parse_publish", parse_publish, METH_VARARGS,
     "parse_publish(flags, body, v5) -> (topic, pid, props_raw, payload)"},
    {"serialize_publish", serialize_publish, METH_VARARGS,
     "serialize_publish(topic, payload, qos, retain, dup, pid, props, v5)"},
    {"pack_dlv_frames", pack_dlv_frames, METH_VARARGS,
     "pack_dlv_frames(records, max_body) -> [frame, ...]"},
    {"unpack_dlv_batch", unpack_dlv_batch, METH_VARARGS,
     "unpack_dlv_batch(body) -> [(topic, payload, qos, retain, retained,"
     " client, [handles])]"},
    {"pack_pub_batch", pack_pub_batch_c, METH_VARARGS,
     "pack_pub_batch(msgs, seq) -> PUBB frame"},
    {"unpack_pub_batch", unpack_pub_batch_c, METH_VARARGS,
     "unpack_pub_batch(body) -> (seq, [(topic, payload, qos, retain, dup,"
     " client)])"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_codec", "native MQTT wire codec", -1, methods,
};

PyMODINIT_FUNC
PyInit__codec(void)
{
    return PyModule_Create(&moduledef);
}
