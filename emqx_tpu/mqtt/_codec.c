/* Native MQTT wire codec: the host data plane's hot path in C.
 *
 * The reference's codec is BEAM-native binary pattern matching
 * (apps/emqx/src/emqx_frame.erl:115-170 parse, :559-580 serialize);
 * a Python host pays ~10-20us per packet in pure-Python parsing. This
 * extension does the three per-message operations in C:
 *
 *   split_frames(buf, max_size)    -> ([(header, body_bytes)...], consumed)
 *   parse_publish(flags, body, v5) -> (topic, pid|None, props|None, payload)
 *   serialize_publish(topic_utf8, payload, qos, retain, dup, pid, props)
 *                                  -> complete wire frame, one allocation
 *
 * Anything outside the hot path (CONNECT, SUBSCRIBE, v5 property maps)
 * stays in the Python reference codec (emqx_tpu/mqtt/frame.py), which
 * differentially tests this module.  Built with the CPython C API —
 * no third-party binding dependency.
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <string.h>

/* -- varint ---------------------------------------------------------- */

static int
read_varint(const unsigned char *p, Py_ssize_t len, Py_ssize_t off,
            unsigned int *val, Py_ssize_t *end)
{
    unsigned int mult = 1, v = 0;
    for (int i = 0; i < 4; i++) {
        if (off + i >= len)
            return 1; /* need more */
        unsigned char b = p[off + i];
        v += (unsigned int)(b & 0x7F) * mult;
        if (!(b & 0x80)) {
            *val = v;
            *end = off + i + 1;
            return 0;
        }
        mult *= 128;
    }
    return -1; /* malformed */
}

static Py_ssize_t
write_varint(unsigned char *out, unsigned int n)
{
    Py_ssize_t i = 0;
    do {
        unsigned char b = n % 128;
        n /= 128;
        out[i++] = n ? (b | 0x80) : b;
    } while (n);
    return i;
}

/* -- split_frames ----------------------------------------------------- */

static PyObject *
split_frames(PyObject *self, PyObject *args)
{
    Py_buffer view;
    unsigned long max_size;
    if (!PyArg_ParseTuple(args, "y*k", &view, &max_size))
        return NULL;
    const unsigned char *p = (const unsigned char *)view.buf;
    Py_ssize_t len = view.len, off = 0;
    PyObject *frames = PyList_New(0);
    if (!frames) {
        PyBuffer_Release(&view);
        return NULL;
    }
    while (len - off >= 2) {
        unsigned int rem;
        Py_ssize_t body_off;
        int rc = read_varint(p, len, off + 1, &rem, &body_off);
        if (rc == 1)
            break; /* partial varint */
        if (rc < 0) {
            Py_DECREF(frames);
            PyBuffer_Release(&view);
            PyErr_SetString(PyExc_ValueError, "malformed_varint");
            return NULL;
        }
        if (rem > max_size) {
            Py_DECREF(frames);
            PyBuffer_Release(&view);
            PyErr_SetString(PyExc_ValueError, "frame_too_large");
            return NULL;
        }
        if (body_off + (Py_ssize_t)rem > len)
            break; /* partial body */
        PyObject *body = PyBytes_FromStringAndSize(
            (const char *)p + body_off, (Py_ssize_t)rem);
        if (!body)
            goto fail;
        PyObject *tup = Py_BuildValue("(iN)", (int)p[off], body);
        if (!tup)
            goto fail;
        if (PyList_Append(frames, tup) < 0) {
            Py_DECREF(tup);
            goto fail;
        }
        Py_DECREF(tup);
        off = body_off + (Py_ssize_t)rem;
    }
    PyBuffer_Release(&view);
    return Py_BuildValue("(Nn)", frames, off);
fail:
    Py_DECREF(frames);
    PyBuffer_Release(&view);
    return NULL;
}

/* -- parse_publish ----------------------------------------------------- */

static PyObject *
parse_publish(PyObject *self, PyObject *args)
{
    int flags, v5;
    Py_buffer view;
    if (!PyArg_ParseTuple(args, "iy*i", &flags, &view, &v5))
        return NULL;
    const unsigned char *p = (const unsigned char *)view.buf;
    Py_ssize_t len = view.len, off = 0;
    int qos = (flags >> 1) & 3;
    if (qos == 3) {
        PyBuffer_Release(&view);
        PyErr_SetString(PyExc_ValueError, "bad_qos");
        return NULL;
    }
    if (len < 2)
        goto truncated;
    Py_ssize_t tlen = ((Py_ssize_t)p[0] << 8) | p[1];
    off = 2;
    if (off + tlen > len)
        goto truncated;
    PyObject *topic = PyUnicode_DecodeUTF8(
        (const char *)p + off, tlen, "strict");
    if (!topic) {
        PyBuffer_Release(&view);
        return NULL;
    }
    off += tlen;
    PyObject *pid = Py_None;
    Py_INCREF(Py_None);
    if (qos > 0) {
        if (off + 2 > len) {
            Py_DECREF(topic);
            Py_DECREF(pid);
            goto truncated;
        }
        Py_DECREF(pid);
        pid = PyLong_FromLong(((long)p[off] << 8) | p[off + 1]);
        off += 2;
    }
    PyObject *props = Py_None;
    Py_INCREF(Py_None);
    if (v5) {
        unsigned int plen;
        Py_ssize_t pend;
        int rc = read_varint(p, len, off, &plen, &pend);
        if (rc != 0 || pend + (Py_ssize_t)plen > len) {
            Py_DECREF(topic);
            Py_DECREF(pid);
            Py_DECREF(props);
            goto truncated;
        }
        if (plen > 0) {
            Py_DECREF(props);
            props = PyBytes_FromStringAndSize(
                (const char *)p + pend, (Py_ssize_t)plen);
            if (!props) {
                Py_DECREF(topic);
                Py_DECREF(pid);
                PyBuffer_Release(&view);
                return NULL;
            }
        }
        off = pend + (Py_ssize_t)plen;
    }
    PyObject *payload = PyBytes_FromStringAndSize(
        (const char *)p + off, len - off);
    PyBuffer_Release(&view);
    if (!payload) {
        Py_DECREF(topic);
        Py_DECREF(pid);
        Py_DECREF(props);
        return NULL;
    }
    return Py_BuildValue("(NNNN)", topic, pid, props, payload);
truncated:
    PyBuffer_Release(&view);
    PyErr_SetString(PyExc_ValueError, "frame_truncated");
    return NULL;
}

/* -- serialize_publish -------------------------------------------------- */

static PyObject *
serialize_publish(PyObject *self, PyObject *args)
{
    Py_buffer topic, payload, props;
    int qos, retain, dup, pid, v5;
    if (!PyArg_ParseTuple(args, "y*y*iiiiy*i", &topic, &payload, &qos,
                          &retain, &dup, &pid, &props, &v5))
        return NULL;
    if (topic.len > 0xFFFF) {
        PyBuffer_Release(&topic);
        PyBuffer_Release(&payload);
        PyBuffer_Release(&props);
        PyErr_SetString(PyExc_ValueError, "utf8_string_too_long");
        return NULL;
    }
    /* body = topic_len(2) + topic + [pid(2)] + [props] + payload */
    Py_ssize_t body = 2 + topic.len + (qos > 0 ? 2 : 0)
                      + (v5 ? props.len : 0) + payload.len;
    if (body > 0xFFFFFFF) { /* varint ceiling, matching encode_varint */
        PyBuffer_Release(&topic);
        PyBuffer_Release(&payload);
        PyBuffer_Release(&props);
        PyErr_SetString(PyExc_ValueError, "varint_out_of_range");
        return NULL;
    }
    unsigned char hdr[6];
    hdr[0] = (unsigned char)((3 << 4) | ((dup ? 1 : 0) << 3)
                             | ((qos & 3) << 1) | (retain ? 1 : 0));
    Py_ssize_t vlen = write_varint(hdr + 1, (unsigned int)body);
    PyObject *out = PyBytes_FromStringAndSize(NULL, 1 + vlen + body);
    if (!out) {
        PyBuffer_Release(&topic);
        PyBuffer_Release(&payload);
        PyBuffer_Release(&props);
        return NULL;
    }
    unsigned char *w = (unsigned char *)PyBytes_AS_STRING(out);
    memcpy(w, hdr, 1 + vlen);
    w += 1 + vlen;
    *w++ = (unsigned char)(topic.len >> 8);
    *w++ = (unsigned char)(topic.len & 0xFF);
    memcpy(w, topic.buf, topic.len);
    w += topic.len;
    if (qos > 0) {
        *w++ = (unsigned char)((pid >> 8) & 0xFF);
        *w++ = (unsigned char)(pid & 0xFF);
    }
    if (v5) {
        memcpy(w, props.buf, props.len);
        w += props.len;
    }
    memcpy(w, payload.buf, payload.len);
    PyBuffer_Release(&topic);
    PyBuffer_Release(&payload);
    PyBuffer_Release(&props);
    return out;
}

/* -- module ----------------------------------------------------------- */

static PyMethodDef methods[] = {
    {"split_frames", split_frames, METH_VARARGS,
     "split_frames(buf, max_size) -> ([(header, body)...], consumed)"},
    {"parse_publish", parse_publish, METH_VARARGS,
     "parse_publish(flags, body, v5) -> (topic, pid, props_raw, payload)"},
    {"serialize_publish", serialize_publish, METH_VARARGS,
     "serialize_publish(topic, payload, qos, retain, dup, pid, props, v5)"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_codec", "native MQTT wire codec", -1, methods,
};

PyMODINIT_FUNC
PyInit__codec(void)
{
    return PyModule_Create(&moduledef);
}
