"""Batched PUBLISH serialization: one preallocated slab, vectorized
fixed-header/varint build, per-target patches as small scatter writes.

The per-delivery cost the protocol plane used to pay was a full Python
`frame.serialize` per outbound PUBLISH — packet-object construction,
per-field `struct.pack`, bytearray growth. Two batched shapes replace it
(docs/protocol_plane.md):

- `serialize_pub_slab`: N (possibly distinct) PUBLISH frames built into
  ONE bytearray. All fixed headers, remaining-length varints, topic
  lengths and packet ids are written with vectorized numpy scatter
  stores; only the topic/payload byte copies run per record (each a
  single slice-assign memcpy — topic bytes come straight from a fabric
  slab view when available). Frame i is `memoryview(slab)[offs[i]:
  offs[i+1]]` — callers hand the views to `writelines`-style sinks
  without ever joining. This is the session-store redelivery flood's
  serializer (`SessionStore._redeliver` -> `Channel._store_resend_batch`)
  and the bench's codec-path microbench subject.

- `split_publish`: ONE message fanned to many targets whose frames
  differ only in the 2-byte packet id: returns (head, tail) so each
  target costs `writelines([head, pid_be, tail])` — zero copies of the
  payload per target (the channel's QoS1/2 fan-out fast path; the QoS0
  path already shares one cached frame).

Byte-exactness vs `frame.serialize` is the contract (differential test
in tests/test_fabric_slab.py); v5 frames carry the encoded property
block. Frames above the varint-1 size classes are supported up to the
MQTT maximum (268435455 bytes).
"""

from __future__ import annotations

import struct
from typing import List, Optional, Sequence, Tuple

import numpy as np

from emqx_tpu.mqtt import packet as pkt
from emqx_tpu.mqtt.frame import encode_properties

_U16BE = struct.Struct(">H")

# remaining-length varint size-class thresholds
_V1 = 128
_V2 = 16384
_V3 = 2097152


def _varint_len(rem: np.ndarray) -> np.ndarray:
    return (
        1 + (rem >= _V1).astype(np.int64) + (rem >= _V2) + (rem >= _V3)
    )


def serialize_pub_slab(
    items: Sequence[Tuple],
    version: int = pkt.MQTT_V4,
) -> Tuple[bytearray, np.ndarray]:
    """items: [(topic_bytes, payload, qos, retain, dup, packet_id,
    props_bytes | None)] -> (slab, offs int64 [n+1]).

    `topic_bytes`/`payload` are bytes-like (memoryview slices of a
    fabric slab are fine — nothing here forces a copy beyond the one
    memcpy into the output slab). `props_bytes` is a pre-encoded MQTT5
    property block INCLUDING its own length varint (frame.
    encode_properties output); ignored unless version is v5, where None
    means the empty block. Frame i is slab[offs[i]:offs[i+1]], byte-
    identical to frame.serialize of the equivalent Publish packet.
    """
    n = len(items)
    v5 = version == pkt.MQTT_V5
    if n == 0:
        return bytearray(), np.zeros(1, np.int64)
    # C-level extraction: zip(*) transposes the batch and map(len, ...)
    # measures each field without a Python-bytecode loop — at flood
    # scale (1M frames) the per-row interpreted loop was the dominant
    # serializer cost
    ts, ps, qs, rets, dups, pids, pbs = zip(*items)
    tl_l = list(map(len, ts))
    pl_l = [len(p) if p is not None else 0 for p in ps]
    tl = np.array(tl_l, np.int64)
    pl = np.array(pl_l, np.int64)
    qos = np.fromiter(qs, np.int64, n)
    pid = np.fromiter((p or 0 for p in pids), np.int64, n)
    hdrb = (
        0x30
        | (np.fromiter(dups, bool, n) << 3)
        | (qos << 1)
        | np.fromiter(rets, bool, n)
    )
    if v5:
        props_l = [b"\x00" if pb is None else pb for pb in pbs]
        prl_l = list(map(len, props_l))
        prl = np.array(prl_l, np.int64)
    else:
        props_l = []
        prl_l = []
        prl = np.zeros(n, np.int64)
    pidl = np.where(qos > 0, 2, 0)
    rem = 2 + tl + pidl + prl + pl
    vl = _varint_len(rem)
    flen = 1 + vl + rem
    offs = np.empty(n + 1, np.int64)
    offs[0] = 0
    np.cumsum(flen, out=offs[1:])
    slab = bytearray(int(offs[-1]))
    u8 = np.frombuffer(slab, np.uint8)
    o = offs[:-1]
    # fixed header byte + remaining-length varint, one scatter per size
    # class (almost every frame lands in class 1 or 2)
    u8[o] = hdrb
    r = rem.copy()
    for k in range(4):
        sel = vl > k
        if not sel.any():
            break
        byte = (r[sel] & 0x7F) | np.where(vl[sel] > k + 1, 0x80, 0)
        u8[o[sel] + 1 + k] = byte
        r >>= 7
    # topic length (u16 BE)
    to = o + 1 + vl
    u8[to] = tl >> 8
    u8[to + 1] = tl & 0xFF
    # packet id (u16 BE) for qos>0 rows
    po = to + 2 + tl
    has_pid = qos > 0
    if has_pid.any():
        u8[po[has_pid]] = pid[has_pid] >> 8
        u8[po[has_pid] + 1] = pid[has_pid] & 0xFF
    # variable byte regions: one slice-assign memcpy per field
    body_o = (po + pidl).tolist()
    to_list = (to + 2).tolist()
    i = 0
    for t, p in zip(ts, ps):
        to_i = to_list[i]
        slab[to_i : to_i + tl_l[i]] = t
        bo = body_o[i]
        if v5:
            pbb = props_l[i]
            slab[bo : bo + prl_l[i]] = pbb
            bo += prl_l[i]
        if pl_l[i]:
            slab[bo : bo + pl_l[i]] = p
        i += 1
    return slab, offs


def frames_of(slab: bytearray, offs: np.ndarray) -> List[memoryview]:
    """Per-frame memoryviews into the slab (writelines-ready)."""
    mv = memoryview(slab)
    ol = offs.tolist()
    return [mv[ol[i] : ol[i + 1]] for i in range(len(ol) - 1)]


def split_publish(
    topic_b,
    payload,
    qos: int,
    retain: bool,
    dup: bool,
    version: int = pkt.MQTT_V4,
    props: Optional[dict] = None,
) -> Tuple[bytes, bytes]:
    """One QoS>0 PUBLISH split around its packet-id slot: -> (head,
    tail). `writelines([head, _U16BE.pack(pid), tail])` emits the frame
    byte-identical to frame.serialize — serialize once per message,
    patch 2 bytes per target."""
    assert qos > 0, "split frames exist for per-target packet ids"
    pb = b""
    if version == pkt.MQTT_V5:
        pb = encode_properties(props)
    p = payload or b""
    rem = 2 + len(topic_b) + 2 + len(pb) + len(p)
    head = bytearray()
    head.append(
        0x30 | (0x8 if dup else 0) | (qos << 1) | (0x1 if retain else 0)
    )
    while True:
        b = rem % 128
        rem //= 128
        head.append(b | 0x80 if rem else b)
        if not rem:
            break
    head += _U16BE.pack(len(topic_b))
    head += topic_b
    return bytes(head), pb + bytes(p)


def pid_bytes(pid: int) -> bytes:
    """The 2-byte packet-id patch between a split frame's head/tail."""
    return _U16BE.pack(pid)


# tiny fixed frames for the rel phase: PUBREL with rc=SUCCESS and no
# props serializes identically for v4/v5 — cache one prefix
_PUBREL_PREFIX = b"\x62\x02"


def pubrel_frame(pid: int) -> bytes:
    return _PUBREL_PREFIX + _U16BE.pack(pid)
