"""Asyncio MQTT client (the reference tests drive the broker with the real
`emqtt` client — apps/emqx/rebar.config:36; this is that role here: a small,
spec-honest client for conformance tests, benchmarks and tooling).

Supports v3.1.1/v5: connect/subscribe/unsubscribe/publish QoS0-2 (full
QoS2 handshake both directions), ping, will, incoming-message queue.
"""

from __future__ import annotations

import asyncio
from typing import Dict, List, Optional, Tuple

from emqx_tpu.mqtt import packet as pkt
from emqx_tpu.mqtt.frame import Parser, serialize


class MqttError(Exception):
    pass


def _insecure_client_ctx():
    """No-verify TLS context (test/tooling default, like `emqtt`'s
    verify_none); pass an explicit `ssl=` context for real deployments."""
    import ssl as ssl_mod

    ctx = ssl_mod.SSLContext(ssl_mod.PROTOCOL_TLS_CLIENT)
    ctx.check_hostname = False
    ctx.verify_mode = ssl_mod.CERT_NONE
    return ctx


class Client:
    def __init__(
        self,
        client_id: str = "",
        version: int = pkt.MQTT_V4,
        clean_start: bool = True,
        keepalive: int = 60,
        username: Optional[str] = None,
        password: Optional[bytes] = None,
        will: Optional[pkt.Will] = None,
        properties: Optional[dict] = None,
    ):
        self.client_id = client_id
        self.version = version
        self.clean_start = clean_start
        self.keepalive = keepalive
        self.username = username
        self.password = password
        self.will = will
        self.conn_properties = properties or {}
        self.messages: asyncio.Queue = asyncio.Queue()
        self.connack: Optional[pkt.Connack] = None
        self.disconnect_packet: Optional[pkt.Disconnect] = None
        self._reader = None
        self._writer = None
        self._parser = Parser(version=version)
        self._pid = 0
        self._pending: Dict[Tuple[int, int], asyncio.Future] = {}
        self._await_rel: set = set()
        self._reader_task: Optional[asyncio.Task] = None
        self.closed = asyncio.Event()

    def _next_pid(self) -> int:
        self._pid = self._pid % 65535 + 1
        return self._pid

    async def connect(
        self,
        host: str = "127.0.0.1",
        port: int = 1883,
        timeout: float = 5.0,
        transport: str = "tcp",
        path: str = "/mqtt",
        ssl: object = None,
    ):
        if transport in ("ws", "wss"):
            # MQTT-over-WebSocket (binary frames, "mqtt" subprotocol).
            # require_ws_support gives the actionable no-package error
            # instead of a bare ModuleNotFoundError mid-connect
            from emqx_tpu.transport.ws import _WsStream, require_ws_support

            require_ws_support()
            from websockets.asyncio.client import connect as ws_connect

            scheme = "wss" if transport == "wss" else "ws"
            if transport == "wss" and ssl is None:
                ssl = _insecure_client_ctx()
            ws = await ws_connect(
                f"{scheme}://{host}:{port}{path}",
                subprotocols=["mqtt"],
                max_size=None,
                ssl=ssl,
            )
            self._reader = self._writer = _WsStream(ws)
        elif transport in ("tcp", "ssl"):
            if transport == "ssl" and ssl is None:
                ssl = _insecure_client_ctx()
            self._reader, self._writer = await asyncio.open_connection(
                host, port, ssl=ssl
            )
        else:
            raise ValueError(
                f"unsupported transport {transport!r} (tcp|ssl|ws|wss)"
            )
        self._send(
            pkt.Connect(
                proto_ver=self.version,
                clean_start=self.clean_start,
                keepalive=self.keepalive,
                client_id=self.client_id,
                username=self.username,
                password=self.password,
                will=self.will,
                properties=self.conn_properties,
            )
        )
        fut = asyncio.get_event_loop().create_future()
        self._pending[(pkt.CONNACK, 0)] = fut
        self._reader_task = asyncio.ensure_future(self._read_loop())
        self.connack = await asyncio.wait_for(fut, timeout)
        ok = (
            self.connack.reason_code == 0
        )
        if not ok:
            raise MqttError(f"connack error: {self.connack.reason_code:#x}")
        return self.connack

    def _send(self, p) -> None:
        self._writer.write(serialize(p, self.version))

    async def _read_loop(self) -> None:
        try:
            while True:
                data = await self._reader.read(65536)
                if not data:
                    break
                for p in self._parser.feed(data):
                    self._handle(p)
        except (ConnectionError, asyncio.CancelledError):
            pass
        finally:
            self.closed.set()
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(MqttError("connection closed"))
            self._pending.clear()

    def _handle(self, p) -> None:
        # sync on purpose: the inbox queue is unbounded (put never
        # blocks), and an await per inbound packet dominated receiver
        # CPU under delivery floods
        t = p.type
        if t == pkt.CONNACK:
            self._resolve((pkt.CONNACK, 0), p)
        elif t == pkt.PUBLISH:
            if p.qos == 0:
                self.messages.put_nowait(p)
            elif p.qos == 1:
                self.messages.put_nowait(p)
                self._send(pkt.PubAck(packet_id=p.packet_id))
            else:
                if p.packet_id not in self._await_rel:
                    self._await_rel.add(p.packet_id)
                    self.messages.put_nowait(p)
                rec = pkt.PubAck(packet_id=p.packet_id)
                rec.type = pkt.PUBREC
                self._send(rec)
        elif t == pkt.PUBREL:
            self._await_rel.discard(p.packet_id)
            comp = pkt.PubAck(packet_id=p.packet_id)
            comp.type = pkt.PUBCOMP
            self._send(comp)
        elif t in (pkt.PUBACK, pkt.PUBCOMP):
            self._resolve((t, p.packet_id), p)
        elif t == pkt.PUBREC:
            rel = pkt.PubAck(packet_id=p.packet_id)
            rel.type = pkt.PUBREL
            self._send(rel)
        elif t in (pkt.SUBACK, pkt.UNSUBACK):
            self._resolve((t, p.packet_id), p)
        elif t == pkt.PINGRESP:
            self._resolve((pkt.PINGRESP, 0), p)
        elif t == pkt.DISCONNECT:
            self.disconnect_packet = p

    def _resolve(self, key, p) -> None:
        fut = self._pending.pop(key, None)
        if fut is not None and not fut.done():
            fut.set_result(p)

    async def _request(self, key, send_pkt, timeout: float = 5.0):
        fut = asyncio.get_event_loop().create_future()
        self._pending[key] = fut
        self._send(send_pkt)
        return await asyncio.wait_for(fut, timeout)

    async def subscribe(
        self, filters, qos: int = 0, timeout: float = 5.0
    ) -> pkt.Suback:
        if isinstance(filters, str):
            filters = [(filters, pkt.SubOpts(qos=qos))]
        elif filters and isinstance(filters[0], str):
            filters = [(f, pkt.SubOpts(qos=qos)) for f in filters]
        pid = self._next_pid()
        return await self._request(
            (pkt.SUBACK, pid),
            pkt.Subscribe(packet_id=pid, filters=list(filters)),
            timeout,
        )

    async def unsubscribe(self, filters, timeout: float = 5.0) -> pkt.Unsuback:
        if isinstance(filters, str):
            filters = [filters]
        pid = self._next_pid()
        return await self._request(
            (pkt.UNSUBACK, pid),
            pkt.Unsubscribe(packet_id=pid, filters=list(filters)),
            timeout,
        )

    async def publish(
        self,
        topic: str,
        payload: bytes = b"",
        qos: int = 0,
        retain: bool = False,
        properties: Optional[dict] = None,
        timeout: float = 5.0,
    ):
        p = pkt.Publish(
            topic=topic,
            payload=payload,
            qos=qos,
            retain=retain,
            properties=properties or {},
        )
        if qos == 0:
            self._send(p)
            # drain only past a buffer high-water mark: an await
            # round-trip per QoS0 publish dominated flood-side CPU
            # (the WS stream adapter has no transport: always drain)
            tr = getattr(self._writer, "transport", None)
            if tr is None or tr.get_write_buffer_size() > 65536:
                await self._writer.drain()
            return None
        p.packet_id = self._next_pid()
        ack_t = pkt.PUBACK if qos == 1 else pkt.PUBCOMP
        return await self._request((ack_t, p.packet_id), p, timeout)

    async def ping(self, timeout: float = 5.0):
        return await self._request((pkt.PINGRESP, 0), pkt.PingReq(), timeout)

    async def recv(self, timeout: float = 5.0) -> pkt.Publish:
        # fast path: a queued message skips the wait_for timeout
        # machinery entirely (it dominated receiver-side CPU in floods)
        try:
            return self.messages.get_nowait()
        except asyncio.QueueEmpty:
            return await asyncio.wait_for(self.messages.get(), timeout)

    async def disconnect(self, reason_code: int = 0) -> None:
        try:
            self._send(pkt.Disconnect(reason_code=reason_code))
            await self._writer.drain()
        except Exception:
            pass
        await self.close()

    async def close(self) -> None:
        if self._reader_task:
            self._reader_task.cancel()
        if self._writer:
            self._writer.close()
            try:
                await self._writer.wait_closed()
            except Exception:
                pass
