"""MQTT control packet model (v3.1 / v3.1.1 / v5.0).

Parity with the reference's packet records (apps/emqx/include/emqx_mqtt.hrl,
apps/emqx/src/emqx_packet.erl): typed packet classes, MQTT5 properties with
their wire types, reason codes, and QoS/flag helpers. The wire codec lives in
`emqx_tpu.mqtt.frame`.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

# Packet types (MQTT spec table 2.1)
CONNECT = 1
CONNACK = 2
PUBLISH = 3
PUBACK = 4
PUBREC = 5
PUBREL = 6
PUBCOMP = 7
SUBSCRIBE = 8
SUBACK = 9
UNSUBSCRIBE = 10
UNSUBACK = 11
PINGREQ = 12
PINGRESP = 13
DISCONNECT = 14
AUTH = 15

TYPE_NAMES = {
    CONNECT: "CONNECT", CONNACK: "CONNACK", PUBLISH: "PUBLISH",
    PUBACK: "PUBACK", PUBREC: "PUBREC", PUBREL: "PUBREL",
    PUBCOMP: "PUBCOMP", SUBSCRIBE: "SUBSCRIBE", SUBACK: "SUBACK",
    UNSUBSCRIBE: "UNSUBSCRIBE", UNSUBACK: "UNSUBACK", PINGREQ: "PINGREQ",
    PINGRESP: "PINGRESP", DISCONNECT: "DISCONNECT", AUTH: "AUTH",
}

# Protocol versions (CONNECT variable header "protocol level")
MQTT_V3 = 3
MQTT_V4 = 4  # a.k.a. 3.1.1
MQTT_V5 = 5

QOS0, QOS1, QOS2 = 0, 1, 2

# MQTT5 reason codes (subset used broker-wide; emqx_reason_codes.erl parity)
RC_SUCCESS = 0x00
RC_GRANTED_QOS1 = 0x01
RC_GRANTED_QOS2 = 0x02
RC_DISCONNECT_WITH_WILL = 0x04
RC_NO_MATCHING_SUBSCRIBERS = 0x10
RC_NO_SUBSCRIPTION_EXISTED = 0x11
RC_CONTINUE_AUTHENTICATION = 0x18
RC_REAUTHENTICATE = 0x19
RC_UNSPECIFIED_ERROR = 0x80
RC_MALFORMED_PACKET = 0x81
RC_PROTOCOL_ERROR = 0x82
RC_IMPLEMENTATION_SPECIFIC = 0x83
RC_UNSUPPORTED_PROTOCOL_VERSION = 0x84
RC_CLIENT_IDENTIFIER_NOT_VALID = 0x85
RC_BAD_USERNAME_OR_PASSWORD = 0x86
RC_NOT_AUTHORIZED = 0x87
RC_SERVER_UNAVAILABLE = 0x88
RC_SERVER_BUSY = 0x89
RC_BANNED = 0x8A
RC_BAD_AUTHENTICATION_METHOD = 0x8C
RC_KEEP_ALIVE_TIMEOUT = 0x8D
RC_SESSION_TAKEN_OVER = 0x8E
RC_TOPIC_FILTER_INVALID = 0x8F
RC_TOPIC_NAME_INVALID = 0x90
RC_PACKET_IDENTIFIER_IN_USE = 0x91
RC_PACKET_IDENTIFIER_NOT_FOUND = 0x92
RC_RECEIVE_MAXIMUM_EXCEEDED = 0x93
RC_TOPIC_ALIAS_INVALID = 0x94
RC_PACKET_TOO_LARGE = 0x95
RC_MESSAGE_RATE_TOO_HIGH = 0x96
RC_QUOTA_EXCEEDED = 0x97
RC_ADMINISTRATIVE_ACTION = 0x98
RC_PAYLOAD_FORMAT_INVALID = 0x99
RC_RETAIN_NOT_SUPPORTED = 0x9A
RC_QOS_NOT_SUPPORTED = 0x9B
RC_USE_ANOTHER_SERVER = 0x9C
RC_SERVER_MOVED = 0x9D
RC_SHARED_SUBSCRIPTIONS_NOT_SUPPORTED = 0x9E
RC_CONNECTION_RATE_EXCEEDED = 0x9F
RC_MAXIMUM_CONNECT_TIME = 0xA0
RC_SUBSCRIPTION_IDENTIFIERS_NOT_SUPPORTED = 0xA1
RC_WILDCARD_SUBSCRIPTIONS_NOT_SUPPORTED = 0xA2

# CONNACK return codes for MQTT < 5 (emqx_reason_codes:compat/2 parity)
CONNACK_ACCEPT = 0
CONNACK_PROTO_VER = 1
CONNACK_INVALID_ID = 2
CONNACK_SERVER = 3
CONNACK_CREDENTIALS = 4
CONNACK_AUTH = 5

def connack_compat(rc: int) -> int:
    """Map an MQTT5 reason code onto a v3 CONNACK return code —
    delegates to the ONE compat table (mqtt/reason_codes.py,
    emqx_reason_codes:compat/1 parity)."""
    from emqx_tpu.mqtt.reason_codes import compat_connack

    code = compat_connack(rc)
    return CONNACK_SERVER if code is None else code


# -- MQTT5 properties --------------------------------------------------------
# id -> (name, wire_type); wire types: byte | two | four | varint | binary |
# utf8 | utf8_pair  (spec section 2.2.2.2)
PROPERTY_TABLE: Dict[int, Tuple[str, str]] = {
    0x01: ("Payload-Format-Indicator", "byte"),
    0x02: ("Message-Expiry-Interval", "four"),
    0x03: ("Content-Type", "utf8"),
    0x08: ("Response-Topic", "utf8"),
    0x09: ("Correlation-Data", "binary"),
    0x0B: ("Subscription-Identifier", "varint"),
    0x11: ("Session-Expiry-Interval", "four"),
    0x12: ("Assigned-Client-Identifier", "utf8"),
    0x13: ("Server-Keep-Alive", "two"),
    0x15: ("Authentication-Method", "utf8"),
    0x16: ("Authentication-Data", "binary"),
    0x17: ("Request-Problem-Information", "byte"),
    0x18: ("Will-Delay-Interval", "four"),
    0x19: ("Request-Response-Information", "byte"),
    0x1A: ("Response-Information", "utf8"),
    0x1C: ("Server-Reference", "utf8"),
    0x1F: ("Reason-String", "utf8"),
    0x21: ("Receive-Maximum", "two"),
    0x22: ("Topic-Alias-Maximum", "two"),
    0x23: ("Topic-Alias", "two"),
    0x24: ("Maximum-QoS", "byte"),
    0x25: ("Retain-Available", "byte"),
    0x26: ("User-Property", "utf8_pair"),
    0x27: ("Maximum-Packet-Size", "four"),
    0x28: ("Wildcard-Subscription-Available", "byte"),
    0x29: ("Subscription-Identifier-Available", "byte"),
    0x2A: ("Shared-Subscription-Available", "byte"),
}
PROPERTY_IDS = {name: pid for pid, (name, _) in PROPERTY_TABLE.items()}

# Properties = {name: value}; User-Property accumulates a list of (k, v)
Properties = Dict[str, object]


@dataclass
class Will:
    topic: str
    payload: bytes = b""
    qos: int = QOS0
    retain: bool = False
    properties: Properties = field(default_factory=dict)


@dataclass
class Connect:
    proto_ver: int = MQTT_V4
    proto_name: str = "MQTT"
    clean_start: bool = True
    keepalive: int = 60
    client_id: str = ""
    will: Optional[Will] = None
    username: Optional[str] = None
    password: Optional[bytes] = None
    properties: Properties = field(default_factory=dict)
    type: int = CONNECT


@dataclass
class Connack:
    session_present: bool = False
    reason_code: int = RC_SUCCESS
    properties: Properties = field(default_factory=dict)
    type: int = CONNACK


@dataclass
class Publish:
    topic: str
    payload: bytes = b""
    qos: int = QOS0
    retain: bool = False
    dup: bool = False
    packet_id: Optional[int] = None  # required for qos > 0
    properties: Properties = field(default_factory=dict)
    type: int = PUBLISH


@dataclass
class PubAck:
    packet_id: int
    reason_code: int = RC_SUCCESS
    properties: Properties = field(default_factory=dict)
    type: int = PUBACK  # also used for PUBREC/PUBREL/PUBCOMP via `type`


@dataclass
class SubOpts:
    qos: int = QOS0
    no_local: bool = False
    retain_as_published: bool = False
    retain_handling: int = 0


@dataclass
class Subscribe:
    packet_id: int
    filters: List[Tuple[str, SubOpts]] = field(default_factory=list)
    properties: Properties = field(default_factory=dict)
    type: int = SUBSCRIBE


@dataclass
class Suback:
    packet_id: int
    reason_codes: List[int] = field(default_factory=list)
    properties: Properties = field(default_factory=dict)
    type: int = SUBACK


@dataclass
class Unsubscribe:
    packet_id: int
    filters: List[str] = field(default_factory=list)
    properties: Properties = field(default_factory=dict)
    type: int = UNSUBSCRIBE


@dataclass
class Unsuback:
    packet_id: int
    reason_codes: List[int] = field(default_factory=list)
    properties: Properties = field(default_factory=dict)
    type: int = UNSUBACK


@dataclass
class PingReq:
    type: int = PINGREQ


@dataclass
class PingResp:
    type: int = PINGRESP


@dataclass
class Disconnect:
    reason_code: int = RC_SUCCESS
    properties: Properties = field(default_factory=dict)
    type: int = DISCONNECT


@dataclass
class Auth:
    reason_code: int = RC_SUCCESS
    properties: Properties = field(default_factory=dict)
    type: int = AUTH


Packet = object  # union of the dataclasses above
