"""MQTT protocol layer: packet model, wire codec, client.

Capability parity with the reference's protocol core:
- packet model + helpers   (apps/emqx/src/emqx_packet.erl, emqx_message.erl)
- incremental frame codec  (apps/emqx/src/emqx_frame.erl)
Supports MQTT 3.1 (protocol level 3), 3.1.1 (4) and 5.0 (5).
"""
