"""MQTT reason-code tables: code -> name/text, v5 <-> v3 compatibility.

Parity: apps/emqx/src/emqx_reason_codes.erl — human-readable names and
texts for every MQTT 5.0 reason code, plus the v5 -> v3.1.1 CONNACK
compatibility mapping (compat/1) used when rejecting v3 clients.
"""

from __future__ import annotations

from typing import Optional

# code -> (name, text); names follow the MQTT 5.0 spec table 2.4/3.x
V5 = {
    0x00: ("success", "Success"),
    0x01: ("granted_qos1", "Granted QoS 1"),
    0x02: ("granted_qos2", "Granted QoS 2"),
    0x04: ("disconnect_with_will_message", "Disconnect with Will Message"),
    0x10: ("no_matching_subscribers", "No matching subscribers"),
    0x11: ("no_subscription_existed", "No subscription existed"),
    0x18: ("continue_authentication", "Continue authentication"),
    0x19: ("re_authenticate", "Re-authenticate"),
    0x80: ("unspecified_error", "Unspecified error"),
    0x81: ("malformed_packet", "Malformed Packet"),
    0x82: ("protocol_error", "Protocol Error"),
    0x83: ("implementation_specific_error", "Implementation specific error"),
    0x84: ("unsupported_protocol_version", "Unsupported Protocol Version"),
    0x85: ("client_identifier_not_valid", "Client Identifier not valid"),
    0x86: ("bad_username_or_password", "Bad User Name or Password"),
    0x87: ("not_authorized", "Not authorized"),
    0x88: ("server_unavailable", "Server unavailable"),
    0x89: ("server_busy", "Server busy"),
    0x8A: ("banned", "Banned"),
    0x8B: ("server_shutting_down", "Server shutting down"),
    0x8C: ("bad_authentication_method", "Bad authentication method"),
    0x8D: ("keepalive_timeout", "Keep Alive timeout"),
    0x8E: ("session_taken_over", "Session taken over"),
    0x8F: ("topic_filter_invalid", "Topic Filter invalid"),
    0x90: ("topic_name_invalid", "Topic Name invalid"),
    0x91: ("packet_identifier_inuse", "Packet Identifier in use"),
    0x92: ("packet_identifier_not_found", "Packet Identifier not found"),
    0x93: ("receive_maximum_exceeded", "Receive Maximum exceeded"),
    0x94: ("topic_alias_invalid", "Topic Alias invalid"),
    0x95: ("packet_too_large", "Packet too large"),
    0x96: ("message_rate_too_high", "Message rate too high"),
    0x97: ("quota_exceeded", "Quota exceeded"),
    0x98: ("administrative_action", "Administrative action"),
    0x99: ("payload_format_invalid", "Payload format invalid"),
    0x9A: ("retain_not_supported", "Retain not supported"),
    0x9B: ("qos_not_supported", "QoS not supported"),
    0x9C: ("use_another_server", "Use another server"),
    0x9D: ("server_moved", "Server moved"),
    0x9E: ("shared_subscriptions_not_supported",
           "Shared Subscriptions not supported"),
    0x9F: ("connection_rate_exceeded", "Connection rate exceeded"),
    0xA0: ("maximum_connect_time", "Maximum connect time"),
    0xA1: ("subscription_identifiers_not_supported",
           "Subscription Identifiers not supported"),
    0xA2: ("wildcard_subscriptions_not_supported",
           "Wildcard Subscriptions not supported"),
}

# MQTT 3.1.1 CONNACK return codes (emqx_reason_codes.erl name/1 for v3)
V3_CONNACK = {
    0: ("connection_accepted", "Connection accepted"),
    1: ("unacceptable_protocol_version",
        "Connection Refused: unacceptable protocol version"),
    2: ("client_identifier_not_valid",
        "Connection Refused: client identifier rejected"),
    3: ("server_unavailable", "Connection Refused: server unavailable"),
    4: ("malformed_username_or_password",
        "Connection Refused: bad user name or password"),
    5: ("unauthorized_client", "Connection Refused: not authorized"),
}

# v5 CONNACK code -> v3.1.1 CONNACK return code (compat/1)
_COMPAT_CONNACK = {
    0x80: 3, 0x81: 3, 0x82: 3, 0x83: 3,
    0x84: 1,
    0x85: 2,
    0x86: 4,
    0x87: 5, 0x8A: 5, 0x8C: 5,
    0x88: 3, 0x89: 3, 0x8B: 3, 0x97: 3, 0x9C: 3, 0x9D: 3, 0x9F: 3,
}


def name(code: int, version: int = 5) -> str:
    if version < 5:
        entry = V3_CONNACK.get(code)
    else:
        entry = V5.get(code)
    return entry[0] if entry else f"unknown_0x{code:02x}"


def text(code: int, version: int = 5) -> str:
    if version < 5:
        entry = V3_CONNACK.get(code)
    else:
        entry = V5.get(code)
    return entry[1] if entry else f"Unknown reason code 0x{code:02x}"


def compat_connack(v5_code: int) -> Optional[int]:
    """v5 CONNACK reason -> v3.1.1 return code; None when the v5 code
    has no listed v3 analog (emqx_reason_codes:compat(connack, _)) —
    the caller picks its own fallback (the channel uses server
    unavailable)."""
    if v5_code == 0:
        return 0
    return _COMPAT_CONNACK.get(v5_code)
