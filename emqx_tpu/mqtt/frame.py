"""MQTT wire codec: incremental parser + serializer.

Parity with the reference codec (apps/emqx/src/emqx_frame.erl:56-66 parse
state continuation, :115-170 fixed/variable header parse, :559-580
serialize): handles partial frames across TCP reads, enforces max packet
size and varint bounds, parses/serializes v3.1, v3.1.1 and v5 packets
including MQTT5 properties, and auto-switches the session's protocol version
when CONNECT is seen.

This module is the semantic SOURCE OF TRUTH; the C extension
(`emqx_tpu/mqtt/_codec.c`, loaded via `codec_native`) accelerates the
hot path — frame splitting and PUBLISH parse/serialize — and is
differentially tested against it (tests/test_codec_native.py). Anything
the native path cannot express exactly (strict-mode errors, v5 property
maps, exotic inputs) falls back here.
"""

from __future__ import annotations

import struct
from typing import List, Optional, Tuple

from emqx_tpu.mqtt import codec_native as _nc
from emqx_tpu.mqtt import packet as pkt


class FrameError(Exception):
    def __init__(self, reason: str, **ctx):
        super().__init__(reason)
        self.reason = reason
        self.ctx = ctx


MAX_PACKET_SIZE = 0xFFFFFFF  # varint ceiling (268435455)


# -- primitive encoders ------------------------------------------------------

def encode_varint(n: int) -> bytes:
    if n < 0 or n > MAX_PACKET_SIZE:
        raise FrameError("varint_out_of_range", value=n)
    out = bytearray()
    while True:
        b = n % 128
        n //= 128
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def encode_utf8(s: str) -> bytes:
    b = s.encode("utf-8")
    if len(b) > 0xFFFF:
        raise FrameError("utf8_string_too_long")
    return struct.pack(">H", len(b)) + b


def encode_binary(b: bytes) -> bytes:
    if len(b) > 0xFFFF:
        raise FrameError("binary_too_long")
    return struct.pack(">H", len(b)) + b


def encode_properties(props: Optional[pkt.Properties]) -> bytes:
    if not props:
        return b"\x00"
    out = bytearray()
    for name, value in props.items():
        pid = pkt.PROPERTY_IDS.get(name)
        if pid is None:
            raise FrameError("unknown_property", name=name)
        _, wt = pkt.PROPERTY_TABLE[pid]
        if wt == "utf8_pair":
            for k, v in value:  # list of pairs
                out.append(pid)
                out += encode_utf8(k) + encode_utf8(v)
            continue
        if wt == "varint" and isinstance(value, list):
            # Subscription-Identifier may appear multiple times
            for v in value:
                out.append(pid)
                out += encode_varint(v)
            continue
        out.append(pid)
        if wt == "byte":
            out.append(int(value) & 0xFF)
        elif wt == "two":
            out += struct.pack(">H", value)
        elif wt == "four":
            out += struct.pack(">I", value)
        elif wt == "varint":
            out += encode_varint(value)
        elif wt == "binary":
            out += encode_binary(value)
        elif wt == "utf8":
            out += encode_utf8(value)
    return encode_varint(len(out)) + bytes(out)


# -- primitive decoders (operate on memoryview + offset) ---------------------

def decode_varint(buf, off: int) -> Tuple[int, int]:
    mult, val = 1, 0
    for i in range(4):
        if off + i >= len(buf):
            raise _NeedMore()
        b = buf[off + i]
        val += (b & 0x7F) * mult
        if not (b & 0x80):
            return val, off + i + 1
        mult *= 128
    raise FrameError("malformed_varint")


def _take(buf, off: int, n: int):
    if off + n > len(buf):
        raise FrameError("frame_truncated")
    return bytes(buf[off : off + n]), off + n


def decode_utf8(buf, off: int) -> Tuple[str, int]:
    raw, off = _take(buf, off, 2)
    (n,) = struct.unpack(">H", raw)
    raw, off = _take(buf, off, n)
    try:
        return raw.decode("utf-8"), off
    except UnicodeDecodeError:
        raise FrameError("invalid_utf8_string")


def decode_binary(buf, off: int) -> Tuple[bytes, int]:
    raw, off = _take(buf, off, 2)
    (n,) = struct.unpack(">H", raw)
    return _take(buf, off, n)


def decode_properties(buf, off: int) -> Tuple[pkt.Properties, int]:
    plen, off = decode_varint(buf, off)
    end = off + plen
    if end > len(buf):
        raise FrameError("frame_truncated")
    props: pkt.Properties = {}
    while off < end:
        pid = buf[off]
        off += 1
        ent = pkt.PROPERTY_TABLE.get(pid)
        if ent is None:
            raise FrameError("unknown_property_id", pid=pid)
        name, wt = ent
        if wt == "byte":
            value, off = buf[off], off + 1
        elif wt == "two":
            raw, off = _take(buf, off, 2)
            (value,) = struct.unpack(">H", raw)
        elif wt == "four":
            raw, off = _take(buf, off, 4)
            (value,) = struct.unpack(">I", raw)
        elif wt == "varint":
            value, off = decode_varint(buf, off)
        elif wt == "binary":
            value, off = decode_binary(buf, off)
        elif wt == "utf8":
            value, off = decode_utf8(buf, off)
        else:  # utf8_pair
            k, off = decode_utf8(buf, off)
            v, off = decode_utf8(buf, off)
            props.setdefault(name, []).append((k, v))
            continue
        if name == "Subscription-Identifier" and name in props:
            prev = props[name]
            props[name] = (prev if isinstance(prev, list) else [prev]) + [value]
        else:
            props[name] = value
    if off != end:
        raise FrameError("malformed_properties")
    return props, off


class _NeedMore(Exception):
    """Internal: fixed header incomplete; wait for more bytes."""


# -- parser ------------------------------------------------------------------

class Parser:
    """Incremental MQTT parser: feed() bytes, collect whole packets.

    Version-sensitive fields follow `self.version`, which starts at the
    configured default and switches when a CONNECT packet is parsed
    (emqx_frame.erl keeps the same in its parse-state options).
    """

    def __init__(
        self,
        version: int = pkt.MQTT_V4,
        max_size: int = MAX_PACKET_SIZE,
        strict: bool = True,
    ):
        self.version = version
        self.max_size = max_size
        self.strict = strict
        self._buf = bytearray()
        # bytes needed to complete the frame at the buffer head (None =
        # unknown): lets feed() skip re-copy/re-scan of a growing buffer
        # while a large fragmented frame accumulates
        self._need: Optional[int] = None

    def feed(self, data: bytes) -> List[pkt.Packet]:
        self._buf += data
        out: List[pkt.Packet] = []
        if _nc.available:
            # native frame split + PUBLISH fast path (one C call per
            # read instead of per-byte python varint walking). While a
            # large frame is known-incomplete, skip the copy + rescan
            # entirely (a fragmented 100MB PUBLISH would otherwise
            # re-copy the growing buffer on every TCP segment).
            if self._need is not None and len(self._buf) < self._need:
                return out
            self._need = None
            try:
                frames, consumed = _nc.split_frames(
                    bytes(self._buf), self.max_size
                )
            except ValueError as e:
                raise FrameError(str(e))
            del self._buf[:consumed]
            if len(self._buf) >= 2:
                try:
                    rem, body_off = decode_varint(self._buf, 1)
                    if rem > self.max_size:
                        raise FrameError("frame_too_large", size=rem)
                    self._need = body_off + rem
                except _NeedMore:
                    self._need = None  # header itself incomplete
            for header, body in frames:
                ptype, flags = header >> 4, header & 0x0F
                if ptype == pkt.PUBLISH:
                    out.append(self._p_publish_native(flags, body))
                else:
                    out.append(
                        self._parse_packet(ptype, flags, memoryview(body))
                    )
            return out
        while True:
            p = self._try_parse_one()
            if p is None:
                return out
            out.append(p)

    def _p_publish_native(self, flags: int, body: bytes) -> pkt.Publish:
        """PUBLISH via the C parser; strict-mode checks and v5 property
        decoding stay in Python. Any native rejection re-runs the python
        parser so error reasons match the reference codec exactly."""
        v5 = self.version == pkt.MQTT_V5
        try:
            topic, packet_id, props_raw, payload = _nc.parse_publish(
                flags, body, 1 if v5 else 0
            )
        except (ValueError, UnicodeDecodeError):
            return self._p_publish(flags, memoryview(body))
        if self.strict and ("#" in topic or "+" in topic):
            raise FrameError("topic_name_with_wildcard", topic=topic)
        if self.strict and packet_id == 0 and ((flags >> 1) & 3) > 0:
            raise FrameError("zero_packet_id")
        props: pkt.Properties = {}
        if props_raw is not None:
            props, _ = decode_properties(
                memoryview(encode_varint(len(props_raw)) + props_raw), 0
            )
        return pkt.Publish(
            topic=topic,
            payload=payload,
            qos=(flags >> 1) & 3,
            retain=bool(flags & 1),
            dup=bool(flags & 8),
            packet_id=packet_id,
            properties=props,
        )

    def _try_parse_one(self) -> Optional[pkt.Packet]:
        buf = self._buf
        if len(buf) < 2:
            return None
        try:
            rem_len, body_off = decode_varint(buf, 1)
        except _NeedMore:
            return None
        if rem_len > self.max_size:
            raise FrameError("frame_too_large", size=rem_len)
        if len(buf) < body_off + rem_len:
            return None
        header = buf[0]
        body = memoryview(bytes(buf[body_off : body_off + rem_len]))
        del self._buf[: body_off + rem_len]
        return self._parse_packet(header >> 4, header & 0x0F, body)

    # each _p_* consumes the full body and returns a packet
    def _parse_packet(self, ptype: int, flags: int, body) -> pkt.Packet:
        try:
            return self._parse_packet_inner(ptype, flags, body)
        except _NeedMore:
            # the frame body is complete by construction; a varint running
            # off its end is malformed, not a partial read
            raise FrameError("frame_truncated")

    def _parse_packet_inner(self, ptype: int, flags: int, body) -> pkt.Packet:
        if ptype == pkt.CONNECT:
            return self._p_connect(body)
        if ptype == pkt.CONNACK:
            return self._p_connack(body)
        if ptype == pkt.PUBLISH:
            return self._p_publish(flags, body)
        if ptype in (pkt.PUBACK, pkt.PUBREC, pkt.PUBREL, pkt.PUBCOMP):
            if ptype == pkt.PUBREL and flags != 0x2:
                raise FrameError("malformed_flags", type=ptype)
            return self._p_puback(ptype, body)
        if ptype == pkt.SUBSCRIBE:
            if flags != 0x2:
                raise FrameError("malformed_flags", type=ptype)
            return self._p_subscribe(body)
        if ptype == pkt.SUBACK:
            return self._p_suback(body)
        if ptype == pkt.UNSUBSCRIBE:
            if flags != 0x2:
                raise FrameError("malformed_flags", type=ptype)
            return self._p_unsubscribe(body)
        if ptype == pkt.UNSUBACK:
            return self._p_unsuback(body)
        if ptype == pkt.PINGREQ:
            return pkt.PingReq()
        if ptype == pkt.PINGRESP:
            return pkt.PingResp()
        if ptype == pkt.DISCONNECT:
            return self._p_disconnect(body)
        if ptype == pkt.AUTH:
            return self._p_auth(body)
        raise FrameError("unknown_packet_type", type=ptype)

    def _p_connect(self, body) -> pkt.Connect:
        off = 0
        proto_name, off = decode_utf8(body, off)
        if proto_name not in ("MQTT", "MQIsdp"):
            raise FrameError("invalid_proto_name", name=proto_name)
        ver = body[off]
        off += 1
        if ver not in (pkt.MQTT_V3, pkt.MQTT_V4, pkt.MQTT_V5):
            raise FrameError("unsupported_protocol_version", version=ver)
        cflags = body[off]
        off += 1
        if self.strict and (cflags & 0x01):
            raise FrameError("reserved_connect_flag")
        clean_start = bool(cflags & 0x02)
        will_flag = bool(cflags & 0x04)
        will_qos = (cflags >> 3) & 0x3
        will_retain = bool(cflags & 0x20)
        has_password = bool(cflags & 0x40)
        has_username = bool(cflags & 0x80)
        raw, off = _take(body, off, 2)
        (keepalive,) = struct.unpack(">H", raw)
        props: pkt.Properties = {}
        if ver == pkt.MQTT_V5:
            props, off = decode_properties(body, off)
        client_id, off = decode_utf8(body, off)
        will = None
        if will_flag:
            wprops: pkt.Properties = {}
            if ver == pkt.MQTT_V5:
                wprops, off = decode_properties(body, off)
            wtopic, off = decode_utf8(body, off)
            wpayload, off = decode_binary(body, off)
            will = pkt.Will(
                topic=wtopic, payload=wpayload, qos=will_qos,
                retain=will_retain, properties=wprops,
            )
        elif self.strict and (will_qos or will_retain):
            raise FrameError("invalid_will_flags")
        username = password = None
        if has_username:
            username, off = decode_utf8(body, off)
        if has_password:
            password, off = decode_binary(body, off)
        if off != len(body):
            raise FrameError("trailing_bytes")
        self.version = ver
        return pkt.Connect(
            proto_ver=ver, proto_name=proto_name, clean_start=clean_start,
            keepalive=keepalive, client_id=client_id, will=will,
            username=username, password=password, properties=props,
        )

    def _p_connack(self, body) -> pkt.Connack:
        off = 0
        ackflags = body[off]
        off += 1
        rc = body[off]
        off += 1
        props: pkt.Properties = {}
        if self.version == pkt.MQTT_V5:
            props, off = decode_properties(body, off)
        return pkt.Connack(
            session_present=bool(ackflags & 0x1), reason_code=rc,
            properties=props,
        )

    def _p_publish(self, flags: int, body) -> pkt.Publish:
        dup = bool(flags & 0x8)
        qos = (flags >> 1) & 0x3
        retain = bool(flags & 0x1)
        if qos == 3:
            raise FrameError("bad_qos")
        off = 0
        topic, off = decode_utf8(body, off)
        if self.strict and ("#" in topic or "+" in topic):
            raise FrameError("topic_name_with_wildcard", topic=topic)
        packet_id = None
        if qos > 0:
            raw, off = _take(body, off, 2)
            (packet_id,) = struct.unpack(">H", raw)
            if self.strict and packet_id == 0:
                raise FrameError("zero_packet_id")
        props: pkt.Properties = {}
        if self.version == pkt.MQTT_V5:
            props, off = decode_properties(body, off)
        payload = bytes(body[off:])
        return pkt.Publish(
            topic=topic, payload=payload, qos=qos, retain=retain, dup=dup,
            packet_id=packet_id, properties=props,
        )

    def _p_puback(self, ptype: int, body) -> pkt.PubAck:
        raw, off = _take(body, 0, 2)
        (packet_id,) = struct.unpack(">H", raw)
        rc = pkt.RC_SUCCESS
        props: pkt.Properties = {}
        if self.version == pkt.MQTT_V5 and len(body) > 2:
            rc = body[off]
            off += 1
            if len(body) > off:
                props, off = decode_properties(body, off)
        p = pkt.PubAck(packet_id=packet_id, reason_code=rc, properties=props)
        p.type = ptype
        return p

    def _p_subscribe(self, body) -> pkt.Subscribe:
        raw, off = _take(body, 0, 2)
        (packet_id,) = struct.unpack(">H", raw)
        props: pkt.Properties = {}
        if self.version == pkt.MQTT_V5:
            props, off = decode_properties(body, off)
        filters: List[Tuple[str, pkt.SubOpts]] = []
        while off < len(body):
            f, off = decode_utf8(body, off)
            o = body[off]
            off += 1
            if self.strict and o & 0xC0:
                raise FrameError("reserved_subopts_bits")
            opts = pkt.SubOpts(
                qos=o & 0x3,
                no_local=bool(o & 0x4),
                retain_as_published=bool(o & 0x8),
                retain_handling=(o >> 4) & 0x3,
            )
            if opts.qos == 3:
                raise FrameError("bad_qos")
            filters.append((f, opts))
        if self.strict and not filters:
            raise FrameError("empty_topic_filters")
        return pkt.Subscribe(packet_id=packet_id, filters=filters, properties=props)

    def _p_suback(self, body) -> pkt.Suback:
        raw, off = _take(body, 0, 2)
        (packet_id,) = struct.unpack(">H", raw)
        props: pkt.Properties = {}
        if self.version == pkt.MQTT_V5:
            props, off = decode_properties(body, off)
        return pkt.Suback(
            packet_id=packet_id, reason_codes=list(body[off:]), properties=props
        )

    def _p_unsubscribe(self, body) -> pkt.Unsubscribe:
        raw, off = _take(body, 0, 2)
        (packet_id,) = struct.unpack(">H", raw)
        props: pkt.Properties = {}
        if self.version == pkt.MQTT_V5:
            props, off = decode_properties(body, off)
        filters: List[str] = []
        while off < len(body):
            f, off = decode_utf8(body, off)
            filters.append(f)
        if self.strict and not filters:
            raise FrameError("empty_topic_filters")
        return pkt.Unsubscribe(packet_id=packet_id, filters=filters, properties=props)

    def _p_unsuback(self, body) -> pkt.Unsuback:
        raw, off = _take(body, 0, 2)
        (packet_id,) = struct.unpack(">H", raw)
        props: pkt.Properties = {}
        rcs: List[int] = []
        if self.version == pkt.MQTT_V5:
            props, off = decode_properties(body, off)
            rcs = list(body[off:])
        return pkt.Unsuback(packet_id=packet_id, reason_codes=rcs, properties=props)

    def _p_disconnect(self, body) -> pkt.Disconnect:
        rc = pkt.RC_SUCCESS
        props: pkt.Properties = {}
        if self.version == pkt.MQTT_V5 and len(body) >= 1:
            rc = body[0]
            if len(body) > 1:
                props, _ = decode_properties(body, 1)
        return pkt.Disconnect(reason_code=rc, properties=props)

    def _p_auth(self, body) -> pkt.Auth:
        rc = pkt.RC_SUCCESS
        props: pkt.Properties = {}
        if len(body) >= 1:
            rc = body[0]
            if len(body) > 1:
                props, _ = decode_properties(body, 1)
        return pkt.Auth(reason_code=rc, properties=props)


# -- serializer --------------------------------------------------------------

def _frame(ptype: int, flags: int, body: bytes) -> bytes:
    return bytes([ptype << 4 | flags]) + encode_varint(len(body)) + body


def serialize(p, version: int = pkt.MQTT_V4) -> bytes:
    """Serialize a packet for the given protocol version."""
    v5 = version == pkt.MQTT_V5
    t = p.type
    if t == pkt.CONNECT:
        v5c = p.proto_ver == pkt.MQTT_V5
        cflags = (
            (0x02 if p.clean_start else 0)
            | (0x04 if p.will else 0)
            | ((p.will.qos << 3) if p.will else 0)
            | (0x20 if p.will and p.will.retain else 0)
            | (0x40 if p.password is not None else 0)
            | (0x80 if p.username is not None else 0)
        )
        body = bytearray()
        body += encode_utf8("MQIsdp" if p.proto_ver == pkt.MQTT_V3 else "MQTT")
        body.append(p.proto_ver)
        body.append(cflags)
        body += struct.pack(">H", p.keepalive)
        if v5c:
            body += encode_properties(p.properties)
        body += encode_utf8(p.client_id)
        if p.will:
            if v5c:
                body += encode_properties(p.will.properties)
            body += encode_utf8(p.will.topic)
            body += encode_binary(p.will.payload)
        if p.username is not None:
            body += encode_utf8(p.username)
        if p.password is not None:
            body += encode_binary(p.password)
        return _frame(t, 0, bytes(body))
    if t == pkt.CONNACK:
        body = bytearray([1 if p.session_present else 0, p.reason_code])
        if v5:
            body += encode_properties(p.properties)
        return _frame(t, 0, bytes(body))
    if t == pkt.PUBLISH:
        flags = (
            (0x8 if p.dup else 0) | (p.qos << 1) | (0x1 if p.retain else 0)
        )
        if p.qos > 0 and not p.packet_id:
            raise FrameError("missing_packet_id")
        if _nc.available:
            try:
                return _nc.serialize_publish(
                    p.topic.encode("utf-8"),
                    p.payload or b"",
                    p.qos,
                    1 if p.retain else 0,
                    1 if p.dup else 0,
                    p.packet_id or 0,
                    encode_properties(p.properties) if v5 else b"",
                    1 if v5 else 0,
                )
            except ValueError as e:
                raise FrameError(str(e))
        body = bytearray(encode_utf8(p.topic))
        if p.qos > 0:
            body += struct.pack(">H", p.packet_id)
        if v5:
            body += encode_properties(p.properties)
        body += p.payload
        return _frame(t, flags, bytes(body))
    if t in (pkt.PUBACK, pkt.PUBREC, pkt.PUBREL, pkt.PUBCOMP):
        flags = 0x2 if t == pkt.PUBREL else 0
        body = bytearray(struct.pack(">H", p.packet_id))
        if v5 and (p.reason_code != pkt.RC_SUCCESS or p.properties):
            body.append(p.reason_code)
            if p.properties:
                body += encode_properties(p.properties)
        return _frame(t, flags, bytes(body))
    if t == pkt.SUBSCRIBE:
        body = bytearray(struct.pack(">H", p.packet_id))
        if v5:
            body += encode_properties(p.properties)
        for f, o in p.filters:
            body += encode_utf8(f)
            body.append(
                o.qos
                | (0x4 if o.no_local else 0)
                | (0x8 if o.retain_as_published else 0)
                | (o.retain_handling << 4)
            )
        return _frame(t, 0x2, bytes(body))
    if t == pkt.SUBACK:
        body = bytearray(struct.pack(">H", p.packet_id))
        if v5:
            body += encode_properties(p.properties)
        body += bytes(p.reason_codes)
        return _frame(t, 0, bytes(body))
    if t == pkt.UNSUBSCRIBE:
        body = bytearray(struct.pack(">H", p.packet_id))
        if v5:
            body += encode_properties(p.properties)
        for f in p.filters:
            body += encode_utf8(f)
        return _frame(t, 0x2, bytes(body))
    if t == pkt.UNSUBACK:
        body = bytearray(struct.pack(">H", p.packet_id))
        if v5:
            body += encode_properties(p.properties)
            body += bytes(p.reason_codes)
        return _frame(t, 0, bytes(body))
    if t == pkt.PINGREQ:
        return _frame(t, 0, b"")
    if t == pkt.PINGRESP:
        return _frame(t, 0, b"")
    if t == pkt.DISCONNECT:
        if not v5 or (p.reason_code == pkt.RC_SUCCESS and not p.properties):
            return _frame(t, 0, b"" if not v5 else bytes([p.reason_code]))
        return _frame(
            t, 0, bytes([p.reason_code]) + encode_properties(p.properties)
        )
    if t == pkt.AUTH:
        if p.reason_code == pkt.RC_SUCCESS and not p.properties:
            return _frame(t, 0, b"")
        return _frame(
            t, 0, bytes([p.reason_code]) + encode_properties(p.properties)
        )
    raise FrameError("unknown_packet", packet=p)
