"""License parsing/verification + expiry alarms.

Parity: lib-ee/emqx_license — the enterprise overlay's license checker:
a signed license file carries customer/edition/limits/expiry, the broker
verifies the signature against the configured issuer public key
(license.pubkey_n/pubkey_e), raises alarms as expiry approaches, and
gates the connection count.

Wire format here: ``base64url(payload-json).base64url(rsa-signature)``
with RS256 over the payload (the same dependency-free RSA primitive the
JWKS provider uses). Payload fields: customer, edition, max_connections,
expiry_at (epoch seconds). With no license configured the broker runs as
"community" with no imposed limit — matching the reference's
opensource/default behavior.
"""

from __future__ import annotations

import base64
import hashlib
import json
import time
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

from emqx_tpu.auth.jwks import rsa_verify_pkcs1_sha256

# warn this long before expiry (reference alarms in the last 30 days)
WARN_BEFORE = 30 * 24 * 3600.0


class LicenseError(Exception):
    pass


@dataclass
class License:
    customer: str = "community"
    edition: str = "opensource"
    max_connections: Optional[int] = None  # None = unlimited
    expiry_at: Optional[float] = None  # None = never

    def expired(self, now: Optional[float] = None) -> bool:
        return (
            self.expiry_at is not None
            and (now or time.time()) > self.expiry_at
        )

    def expiring_soon(self, now: Optional[float] = None) -> bool:
        return (
            self.expiry_at is not None
            and not self.expired(now)
            and (now or time.time()) > self.expiry_at - WARN_BEFORE
        )

    def info(self) -> Dict:
        return {
            "customer": self.customer,
            "edition": self.edition,
            "max_connections": self.max_connections,
            "expiry_at": self.expiry_at,
            "expired": self.expired(),
        }


def _b64d(s: str) -> bytes:
    return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))


def parse(key: str, pubkey: Tuple[int, int]) -> License:
    """Verify + decode a license string; raises LicenseError."""
    try:
        payload_b64, sig_b64 = key.strip().split(".")
        payload = _b64d(payload_b64)
        sig = _b64d(sig_b64)
    except ValueError as e:
        raise LicenseError(f"malformed license: {e}") from e
    n, e = pubkey
    if not rsa_verify_pkcs1_sha256(n, e, payload, sig):
        raise LicenseError("license signature invalid")
    try:
        data = json.loads(payload)
        return License(
            customer=str(data.get("customer", "?")),
            edition=str(data.get("edition", "enterprise")),
            max_connections=data.get("max_connections"),
            expiry_at=data.get("expiry_at"),
        )
    except (ValueError, TypeError) as e:
        raise LicenseError(f"bad license payload: {e}") from e


def sign(privkey: Tuple[int, int], payload: Dict) -> str:
    """Mint a license (issuer tooling / tests): privkey = (n, d)."""
    n, d = privkey
    body = json.dumps(payload, separators=(",", ":")).encode()
    prefix = bytes.fromhex("3031300d060960864801650304020105000420")
    t = prefix + hashlib.sha256(body).digest()
    k = (n.bit_length() + 7) // 8
    em = b"\x00\x01" + b"\xff" * (k - len(t) - 3) + b"\x00" + t
    sig = pow(int.from_bytes(em, "big"), d, n).to_bytes(k, "big")

    def b64(b: bytes) -> str:
        return base64.urlsafe_b64encode(b).rstrip(b"=").decode()

    return f"{b64(body)}.{b64(sig)}"


class LicenseChecker:
    """Holds the active license, raises expiry alarms, gates connects
    (lib-ee/emqx_license checker + connection-limit hook)."""

    def __init__(self, license_: Optional[License] = None, alarms=None):
        self.license = license_ or License()
        self.alarms = alarms
        self._alarmed = False

    def check_connection(self, current_connections: int) -> bool:
        """False => reject the new connection (limit reached/expired)."""
        lic = self.license
        if lic.expired():
            return False
        if (
            lic.max_connections is not None
            and current_connections >= lic.max_connections
        ):
            return False
        return True

    def tick(self, now: Optional[float] = None) -> None:
        """Periodic expiry check; activates/deactivates the alarm."""
        if self.alarms is None:
            return
        lic = self.license
        if lic.expired(now) or lic.expiring_soon(now):
            if not self._alarmed:
                self.alarms.activate(
                    "license_expiry",
                    {
                        "customer": lic.customer,
                        "expiry_at": lic.expiry_at,
                        "expired": lic.expired(now),
                    },
                )
                self._alarmed = True
        elif self._alarmed:
            self.alarms.deactivate("license_expiry")
            self._alarmed = False

    def attach(self, hooks, cm) -> None:
        def gate(ci, _p, acc=None):
            # a same-clientid reconnect REPLACES its old channel (takeover/
            # discard), so it must not count against the limit. The check
            # is best-effort under concurrency (the authenticate fold has
            # await windows before registration) — same as the reference's
            # listener-level max_connections accounting.
            cid = ci.get("client_id")
            count = cm.channel_count()
            if cid and cm.get_channel(cid) is not None:
                count -= 1
            if not self.check_connection(count):
                from emqx_tpu.mqtt import packet as pkt

                return (
                    "stop",
                    {"result": "deny", "reason_code": pkt.RC_SERVER_BUSY},
                )
            return None

        # above the auth chain, below the ban gate
        hooks.add("client.authenticate", gate, priority=900)
