"""MongoDB connector: from-scratch BSON + OP_MSG client + authn/authz.

Parity: apps/emqx_connector/src/emqx_connector_mongo.erl (mongodb-erlang
client) plus emqx_authn_mongodb.erl / emqx_authz_mongodb.erl.

No MongoDB client library exists in this image, so both layers are
implemented directly (the same approach as integration/redis.py and
integration/mysql.py / pgsql.py):

- a minimal BSON codec (the types the auth/sink documents use: double,
  string, embedded document, array, binary, ObjectId, bool, datetime,
  null, int32, int64)
- the modern wire protocol: OP_MSG (opcode 2013) kind-0 sections over
  the standard 16-byte message header; ``hello``/``ping``/``find``/
  ``insert`` as commands
- SCRAM-SHA-256 authentication via saslStart/saslContinue (RFC 7677 —
  the client-proof math is shared with the PostgreSQL client)

``find(collection, filter)`` returns plain dicts, which the authn
provider (password_hash/salt/is_superuser fields) and authz source
(permission/action/topics documents) consume.
"""

from __future__ import annotations

import asyncio
import base64
import hmac
import logging
import secrets
import struct
import time
from typing import Dict, List, Optional, Tuple

from emqx_tpu.broker.auth import DENY, IGNORE, OK, Provider, _hash_password
from emqx_tpu.integration.resource import Resource
from emqx_tpu.mqtt import packet as pkt
from emqx_tpu.ops import topics as T
from emqx_tpu.utils.placeholder import render

log = logging.getLogger("emqx_tpu.integration.mongodb")

OP_MSG = 2013


class MongoError(Exception):
    """Transport/protocol failure (connection must be reset)."""


class MongoServerError(MongoError):
    """Command returned ok: 0 — stream still aligned."""

    def __init__(self, doc: Dict):
        self.doc = doc
        super().__init__(doc.get("errmsg", "server error"))


# -- BSON (subset) -----------------------------------------------------------


class ObjectId(bytes):
    """12-byte BSON ObjectId."""

    def __new__(cls, raw: Optional[bytes] = None):
        if raw is None:
            raw = (
                int(time.time()).to_bytes(4, "big")
                + secrets.token_bytes(5)
                + secrets.token_bytes(3)
            )
        if len(raw) != 12:
            raise ValueError("ObjectId must be 12 bytes")
        return super().__new__(cls, raw)


def bson_encode(doc: Dict) -> bytes:
    out = bytearray()
    for k, v in doc.items():
        key = k.encode() + b"\x00"
        if isinstance(v, bool):  # before int (bool is int subclass)
            out += b"\x08" + key + (b"\x01" if v else b"\x00")
        elif isinstance(v, float):
            out += b"\x01" + key + struct.pack("<d", v)
        elif isinstance(v, ObjectId):
            out += b"\x07" + key + v
        elif isinstance(v, int):
            if -(1 << 31) <= v < (1 << 31):
                out += b"\x10" + key + struct.pack("<i", v)
            else:
                out += b"\x12" + key + struct.pack("<q", v)
        elif isinstance(v, str):
            enc = v.encode() + b"\x00"
            out += b"\x02" + key + struct.pack("<i", len(enc)) + enc
        elif isinstance(v, (bytes, bytearray)):
            out += b"\x05" + key + struct.pack("<i", len(v)) + b"\x00" + bytes(v)
        elif v is None:
            out += b"\x0a" + key
        elif isinstance(v, dict):
            out += b"\x03" + key + bson_encode(v)
        elif isinstance(v, (list, tuple)):
            arr = {str(i): x for i, x in enumerate(v)}
            out += b"\x04" + key + bson_encode(arr)
        else:
            raise TypeError(f"BSON: unsupported type {type(v)} for {k!r}")
    return struct.pack("<i", len(out) + 5) + bytes(out) + b"\x00"


def bson_decode(data: bytes, pos: int = 0) -> Tuple[Dict, int]:
    (total,) = struct.unpack_from("<i", data, pos)
    end = pos + total - 1  # trailing NUL
    pos += 4
    out: Dict = {}
    while pos < end:
        t = data[pos]
        pos += 1
        z = data.index(b"\x00", pos)
        key = data[pos:z].decode("utf-8", "replace")
        pos = z + 1
        if t == 0x01:
            (out[key],) = struct.unpack_from("<d", data, pos)
            pos += 8
        elif t == 0x02:
            (n,) = struct.unpack_from("<i", data, pos)
            out[key] = data[pos + 4 : pos + 4 + n - 1].decode(
                "utf-8", "replace"
            )
            pos += 4 + n
        elif t in (0x03, 0x04):
            sub, pos = bson_decode(data, pos)
            out[key] = (
                [sub[str(i)] for i in range(len(sub))] if t == 0x04 else sub
            )
        elif t == 0x05:
            (n,) = struct.unpack_from("<i", data, pos)
            out[key] = bytes(data[pos + 5 : pos + 5 + n])
            pos += 5 + n
        elif t == 0x07:
            out[key] = ObjectId(bytes(data[pos : pos + 12]))
            pos += 12
        elif t == 0x08:
            out[key] = data[pos] == 1
            pos += 1
        elif t == 0x09:  # UTC datetime (ms since epoch)
            (ms,) = struct.unpack_from("<q", data, pos)
            out[key] = ms
            pos += 8
        elif t == 0x0A:
            out[key] = None
        elif t == 0x10:
            (out[key],) = struct.unpack_from("<i", data, pos)
            pos += 4
        elif t == 0x12:
            (out[key],) = struct.unpack_from("<q", data, pos)
            pos += 8
        else:
            raise MongoError(f"BSON: unsupported type 0x{t:02x} for {key!r}")
    return out, end + 1


# -- wire client -------------------------------------------------------------


class MongoConnector(Resource):
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 27017,
        username: str = "",
        password: str = "",
        database: str = "mqtt",
        auth_source: str = "admin",
        timeout: float = 5.0,
    ):
        self.host = host
        self.port = port
        self.username = username
        self.password = password
        self.database = database
        self.auth_source = auth_source
        self.timeout = timeout
        self._r: Optional[asyncio.StreamReader] = None
        self._w: Optional[asyncio.StreamWriter] = None
        self._lock = asyncio.Lock()
        self._req_id = 0

    # -- framing -------------------------------------------------------------
    async def _roundtrip(self, doc: Dict) -> Dict:
        self._req_id += 1
        body = struct.pack("<I", 0) + b"\x00" + bson_encode(doc)
        header = struct.pack(
            "<iiii", 16 + len(body), self._req_id, 0, OP_MSG
        )
        self._w.write(header + body)
        hdr = await self._r.readexactly(16)
        length, _rid, _resp_to, opcode = struct.unpack("<iiii", hdr)
        payload = await self._r.readexactly(length - 16)
        if opcode != OP_MSG:
            raise MongoError(f"unexpected opcode {opcode}")
        # flagBits (4) + kind byte; kind-0 single document follows
        if payload[4] != 0:
            raise MongoError(f"unexpected section kind {payload[4]}")
        reply, _ = bson_decode(payload, 5)
        if reply.get("ok") != 1 and reply.get("ok") != 1.0:
            raise MongoServerError(reply)
        return reply

    async def command(self, doc: Dict, db: Optional[str] = None) -> Dict:
        if self._w is None:
            raise MongoError("not connected")
        doc = dict(doc)
        doc["$db"] = db or self.database
        async with self._lock:
            try:
                return await asyncio.wait_for(
                    self._roundtrip(doc), self.timeout
                )
            except MongoServerError:
                raise
            except (
                asyncio.TimeoutError,
                asyncio.IncompleteReadError,
                OSError,
                MongoError,
            ) as e:
                try:
                    self._w.close()
                except Exception:
                    pass
                self._r = self._w = None
                raise MongoError(f"connection reset: {e}") from e

    # -- lifecycle -----------------------------------------------------------
    async def start(self) -> None:
        self._r, self._w = await asyncio.wait_for(
            asyncio.open_connection(self.host, self.port), self.timeout
        )
        hello = await self.command(
            {"hello": 1, "client": {"driver": {
                "name": "emqx_tpu", "version": "0"}}},
            db="admin",
        )
        if self.username:
            await asyncio.wait_for(self._scram_auth(), self.timeout)
        self.server_hello = hello

    async def _scram_auth(self) -> None:
        from emqx_tpu.integration.pgsql import _scram_client_proof

        cnonce = base64.b64encode(secrets.token_bytes(18)).decode()
        user = self.username.replace("=", "=3D").replace(",", "=2C")
        first_bare = f"n={user},r={cnonce}".encode()
        r = await self.command(
            {
                "saslStart": 1,
                "mechanism": "SCRAM-SHA-256",
                "payload": b"n,," + first_bare,
                "options": {"skipEmptyExchange": True},
            },
            db=self.auth_source,
        )
        server_first = bytes(r["payload"])
        attrs = dict(
            kv.split(b"=", 1) for kv in server_first.split(b",") if b"=" in kv
        )
        rnonce = attrs[b"r"].decode()
        if not rnonce.startswith(cnonce):
            raise MongoError("server nonce does not extend client nonce")
        salt = base64.b64decode(attrs[b"s"])
        iterations = int(attrs[b"i"])
        final_bare = f"c=biws,r={rnonce}".encode()
        auth_message = first_bare + b"," + server_first + b"," + final_bare
        proof, server_sig = _scram_client_proof(
            self.password.encode(), salt, iterations, auth_message
        )
        final = final_bare + b",p=" + base64.b64encode(proof)
        r = await self.command(
            {
                "saslContinue": 1,
                "conversationId": r.get("conversationId", 1),
                "payload": final,
            },
            db=self.auth_source,
        )
        got = dict(
            kv.split(b"=", 1)
            for kv in bytes(r["payload"]).split(b",")
            if b"=" in kv
        )
        if base64.b64decode(got.get(b"v", b"")) != server_sig:
            raise MongoError("bad server signature (server not authenticated)")
        if not r.get("done", False):
            r = await self.command(
                {
                    "saslContinue": 1,
                    "conversationId": r.get("conversationId", 1),
                    "payload": b"",
                },
                db=self.auth_source,
            )
            if not r.get("done", False):
                raise MongoError("SASL conversation did not complete")

    async def stop(self) -> None:
        if self._w is not None:
            try:
                self._w.close()
                await self._w.wait_closed()
            except Exception:
                pass
            self._r = self._w = None

    async def health_check(self) -> bool:
        try:
            await self.command({"ping": 1})
            return True
        except Exception:
            return False

    # -- commands ------------------------------------------------------------
    async def find(
        self, collection: str, filter_: Dict, limit: int = 0
    ) -> List[Dict]:
        doc = {"find": collection, "filter": filter_}
        if limit:
            doc["limit"] = limit
        r = await self.command(doc)
        return list(r.get("cursor", {}).get("firstBatch", []))

    async def insert(self, collection: str, docs: List[Dict]) -> int:
        r = await self.command({"insert": collection, "documents": docs})
        return int(r.get("n", 0))

    async def query(self, env: Dict):
        """Bridge-sink interface: insert one rendered document."""
        doc = {
            k: render(str(v), env) if isinstance(v, str) else v
            for k, v in (self.sink_template or {}).items()
        }
        return await self.insert(self.sink_collection, [doc])

    sink_template: Optional[Dict] = None
    sink_collection: str = "mqtt_messages"


# -- authn / authz backends --------------------------------------------------


class MongoAuthProvider(Provider):
    """find-one credential lookup (emqx_authn_mongodb.erl parity):
    default collection ``mqtt_user``, filter ``{username: ${username}}``,
    fields password_hash / salt / is_superuser."""

    def __init__(
        self,
        conn: MongoConnector,
        collection: str = "mqtt_user",
        filter_template: Optional[Dict] = None,
        algo: str = "sha256",
    ):
        self.conn = conn
        self.collection = collection
        self.filter_template = filter_template or {"username": "${username}"}
        self.algo = algo

    def authenticate(self, client_info, credentials):
        return IGNORE, None

    async def authenticate_async(self, client_info, credentials):
        if credentials.get("enhanced_auth"):
            return IGNORE, None
        env = {
            "username": client_info.get("username") or "",
            "clientid": client_info.get("client_id", ""),
        }
        filt = {
            k: render(str(v), env) for k, v in self.filter_template.items()
        }
        try:
            rows = await self.conn.find(self.collection, filt, limit=1)
        except Exception as e:
            log.warning("mongodb authn lookup failed: %s", e)
            return IGNORE, None
        if not rows:
            return IGNORE, None
        row = rows[0]
        phash = row.get("password_hash")
        if phash is None:
            return IGNORE, None
        salt = (row.get("salt") or "").encode()
        password = credentials.get("password") or b""
        cand = _hash_password(password, self.algo, salt)
        if hmac.compare_digest(cand.hex(), str(phash)) or hmac.compare_digest(
            cand, str(phash).encode()
        ):
            if row.get("is_superuser") in (True, 1, "true", "1"):
                client_info["is_superuser"] = True
            return OK, None
        return DENY, pkt.RC_BAD_USERNAME_OR_PASSWORD


class MongoAuthzSource:
    """ACL documents (emqx_authz_mongodb.erl parity): default collection
    ``mqtt_acl``, filter ``{username: ${username}}``; each document
    carries permission (allow|deny), action (publish|subscribe|all) and
    ``topics`` (list of filters, ``eq `` prefix pins literals)."""

    def __init__(
        self,
        conn: MongoConnector,
        collection: str = "mqtt_acl",
        filter_template: Optional[Dict] = None,
    ):
        self.conn = conn
        self.collection = collection
        self.filter_template = filter_template or {"username": "${username}"}

    async def check(self, ci: Dict, action: str, topic: str) -> str:
        env = {
            "username": ci.get("username") or "",
            "clientid": ci.get("client_id", ""),
        }
        filt = {
            k: render(str(v), env) for k, v in self.filter_template.items()
        }
        try:
            docs = await self.conn.find(self.collection, filt)
        except Exception as e:
            log.warning("mongodb authz lookup failed: %s", e)
            return "ignore"
        for doc in docs:
            act = str(doc.get("action", "all")).lower()
            if act not in (action, "all"):
                continue
            topics = doc.get("topics") or []
            if isinstance(topics, str):
                topics = [topics]
            for filt_s in topics:
                filt_s = str(filt_s)
                if filt_s.startswith("eq "):
                    matched = topic == filt_s[3:]
                else:
                    matched = T.match(topic, render(filt_s, env))
                if matched:
                    permission = str(doc.get("permission", "allow")).lower()
                    return "allow" if permission == "allow" else "deny"
        return "ignore"
