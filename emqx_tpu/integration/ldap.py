"""LDAP connector: from-scratch BER/LDAPv3 client + authn provider.

Parity: apps/emqx_connector/src/emqx_connector_ldap.erl (eldap client)
and the LDAP authentication it backs.

No LDAP library exists in this image, so LDAPv3 (RFC 4511) is spoken
directly over a minimal BER codec: BindRequest/BindResponse (simple
auth), SearchRequest with equality/AND filters, SearchResultEntry/Done,
UnbindRequest. That subset is exactly what directory-backed MQTT auth
uses.

Two authn modes (both present in directory deployments and in the
reference's eldap usage):

- ``bind``: build the user's DN from a template and simple-bind with
  the client's password — the directory itself verifies the credential
- ``search``: bind as a service account, search for the user entry, and
  compare a password-hash attribute locally
"""

from __future__ import annotations

import asyncio
import hmac
import logging
from typing import Dict, List, Optional, Tuple

from emqx_tpu.broker.auth import DENY, IGNORE, OK, Provider, _hash_password
from emqx_tpu.integration.resource import Resource
from emqx_tpu.mqtt import packet as pkt
from emqx_tpu.utils.placeholder import render

log = logging.getLogger("emqx_tpu.integration.ldap")


class LdapError(Exception):
    """Transport/protocol failure (connection must be reset)."""


class LdapResultError(LdapError):
    """Non-zero LDAP resultCode; stream still aligned."""

    def __init__(self, code: int, message: str = ""):
        super().__init__(f"ldap result {code}: {message}")
        self.code = code


# -- BER (definite lengths only) ---------------------------------------------


def ber(tag: int, content: bytes) -> bytes:
    n = len(content)
    if n < 0x80:
        return bytes([tag, n]) + content
    nb = n.to_bytes((n.bit_length() + 7) // 8, "big")
    return bytes([tag, 0x80 | len(nb)]) + nb + content


def ber_int(v: int, tag: int = 0x02) -> bytes:
    if v == 0:
        return ber(tag, b"\x00")
    out = v.to_bytes((v.bit_length() + 8) // 8, "big", signed=True)
    return ber(tag, out.lstrip(b"\x00") or b"\x00") if v > 0 else ber(tag, out)


def ber_str(s, tag: int = 0x04) -> bytes:
    return ber(tag, s.encode() if isinstance(s, str) else bytes(s))


def ber_read(data: bytes, pos: int) -> Tuple[int, bytes, int]:
    """-> (tag, content, next_pos)"""
    tag = data[pos]
    n = data[pos + 1]
    pos += 2
    if n & 0x80:
        k = n & 0x7F
        n = int.from_bytes(data[pos : pos + k], "big")
        pos += k
    return tag, data[pos : pos + n], pos + n


def ber_read_int(content: bytes) -> int:
    return int.from_bytes(content, "big", signed=True)


# filter builders (RFC 4511 §4.5.1)
def eq_filter(attr: str, value: str) -> bytes:
    return ber(0xA3, ber_str(attr) + ber_str(value))


def and_filter(*filters: bytes) -> bytes:
    return ber(0xA0, b"".join(filters))


SCOPE_BASE, SCOPE_ONE, SCOPE_SUB = 0, 1, 2


class LdapConnector(Resource):
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 389,
        bind_dn: str = "",
        bind_password: str = "",
        base_dn: str = "",
        timeout: float = 5.0,
    ):
        self.host = host
        self.port = port
        self.bind_dn = bind_dn
        self.bind_password = bind_password
        self.base_dn = base_dn
        self.timeout = timeout
        self._r: Optional[asyncio.StreamReader] = None
        self._w: Optional[asyncio.StreamWriter] = None
        self._lock = asyncio.Lock()
        self._mid = 0

    # -- framing -------------------------------------------------------------
    async def _read_message(self) -> Tuple[int, int, bytes]:
        """-> (message id, protocol-op tag, op content)"""
        hdr = await self._r.readexactly(2)
        n = hdr[1]
        if n & 0x80:
            k = n & 0x7F
            ext = await self._r.readexactly(k)
            n = int.from_bytes(ext, "big")
            body = await self._r.readexactly(n)
        else:
            body = await self._r.readexactly(n)
        _tag, mid_content, pos = ber_read(body, 0)
        mid = ber_read_int(mid_content)
        op_tag, op_content, _ = ber_read(body, pos)
        return mid, op_tag, op_content

    async def _send_op(self, op: bytes) -> int:
        self._mid += 1
        self._w.write(ber(0x30, ber_int(self._mid) + op))
        return self._mid

    @staticmethod
    def _parse_result(content: bytes) -> Tuple[int, str]:
        _t, code_c, pos = ber_read(content, 0)
        _t, _matched, pos = ber_read(content, pos)
        _t, diag, _pos = ber_read(content, pos)
        return ber_read_int(code_c), diag.decode("utf-8", "replace")

    # -- lifecycle -----------------------------------------------------------
    async def start(self) -> None:
        self._r, self._w = await asyncio.wait_for(
            asyncio.open_connection(self.host, self.port), self.timeout
        )
        if self.bind_dn:
            await self.bind(self.bind_dn, self.bind_password)

    async def stop(self) -> None:
        if self._w is not None:
            try:
                self._mid += 1
                # UnbindRequest [APPLICATION 2] NULL
                self._w.write(ber(0x30, ber_int(self._mid) + b"\x42\x00"))
                self._w.close()
                await self._w.wait_closed()
            except Exception:
                pass
            self._r = self._w = None

    async def health_check(self) -> bool:
        try:
            # base-scope search of the root DSE is the standard liveness op
            await self.search("", SCOPE_BASE, None, ["objectClass"])
            return True
        except LdapResultError:
            return True  # server answered; stream healthy
        except Exception:
            return False

    # -- operations ----------------------------------------------------------
    async def bind(self, dn: str, password: str) -> None:
        """Simple bind; raises LdapResultError on invalid credentials."""
        async with self._lock:
            try:
                op = ber(
                    0x60,  # BindRequest [APPLICATION 0]
                    ber_int(3) + ber_str(dn) + ber_str(password, tag=0x80),
                )
                mid = await self._send_op(op)
                rmid, op_tag, content = await asyncio.wait_for(
                    self._read_message(), self.timeout
                )
                if rmid != mid or op_tag != 0x61:
                    raise LdapError(f"unexpected bind reply {op_tag:#x}")
                code, diag = self._parse_result(content)
                if code != 0:
                    raise LdapResultError(code, diag)
            except LdapResultError:
                raise
            except (asyncio.TimeoutError, asyncio.IncompleteReadError,
                    OSError, LdapError) as e:
                self._drop()
                raise LdapError(f"connection reset: {e}") from e

    async def search(
        self,
        base_dn: str,
        scope: int,
        filt: Optional[bytes],
        attributes: List[str],
    ) -> List[Tuple[str, Dict[str, List[bytes]]]]:
        """-> [(dn, {attr: [values]})]; `filt` from eq_filter/and_filter
        (None = present(objectClass), the match-everything filter)."""
        if filt is None:
            filt = ber(0x87, b"objectClass")  # present filter
        async with self._lock:
            try:
                op = ber(
                    0x63,  # SearchRequest [APPLICATION 3]
                    ber_str(base_dn)
                    + ber(0x0A, bytes([scope]))
                    + ber(0x0A, b"\x00")  # neverDerefAliases
                    + ber_int(0)  # sizeLimit
                    + ber_int(0)  # timeLimit
                    + ber(0x01, b"\x00")  # typesOnly FALSE
                    + filt
                    + ber(0x30, b"".join(ber_str(a) for a in attributes)),
                )
                mid = await self._send_op(op)
                out = []
                while True:
                    rmid, op_tag, content = await asyncio.wait_for(
                        self._read_message(), self.timeout
                    )
                    if rmid != mid:
                        continue
                    if op_tag == 0x64:  # SearchResultEntry
                        _t, dn, pos = ber_read(content, 0)
                        _t, attrs_seq, _ = ber_read(content, pos)
                        attrs: Dict[str, List[bytes]] = {}
                        p = 0
                        while p < len(attrs_seq):
                            _t, pa, p = ber_read(attrs_seq, p)
                            _t, name, q = ber_read(pa, 0)
                            _t, vals_set, _ = ber_read(pa, q)
                            vals = []
                            v = 0
                            while v < len(vals_set):
                                _t, val, v = ber_read(vals_set, v)
                                vals.append(val)
                            attrs[name.decode()] = vals
                        out.append((dn.decode("utf-8", "replace"), attrs))
                    elif op_tag == 0x65:  # SearchResultDone
                        code, diag = self._parse_result(content)
                        if code != 0:
                            raise LdapResultError(code, diag)
                        return out
                    else:
                        raise LdapError(f"unexpected search reply {op_tag:#x}")
            except LdapResultError:
                raise
            except (asyncio.TimeoutError, asyncio.IncompleteReadError,
                    OSError, LdapError) as e:
                self._drop()
                raise LdapError(f"connection reset: {e}") from e

    def _drop(self) -> None:
        try:
            if self._w is not None:
                self._w.close()
        except Exception:
            pass
        self._r = self._w = None


class LdapAuthProvider(Provider):
    """Directory-backed authentication.

    mode="bind": render the user DN template (e.g.
    ``cn=${username},ou=mqtt,dc=example,dc=com``) and simple-bind with
    the client's password on a DEDICATED connection — the directory is
    the credential authority.
    mode="search": search under base_dn for the user entry via the
    service connection and compare the password-hash attribute locally.
    """

    def __init__(
        self,
        conn: LdapConnector,
        mode: str = "bind",
        dn_template: str = "cn=${username},${base_dn}",
        filter_attr: str = "uid",
        hash_attr: str = "userPassword",
        algo: str = "plain",
    ):
        self.conn = conn
        self.mode = mode
        self.dn_template = dn_template
        self.filter_attr = filter_attr
        self.hash_attr = hash_attr
        self.algo = algo

    def authenticate(self, client_info, credentials):
        return IGNORE, None

    async def authenticate_async(self, client_info, credentials):
        if credentials.get("enhanced_auth"):
            return IGNORE, None
        username = client_info.get("username") or ""
        if not username:
            return IGNORE, None
        password = credentials.get("password") or b""
        env = {
            "username": username,
            "clientid": client_info.get("client_id", ""),
            "base_dn": self.conn.base_dn,
        }
        try:
            if self.mode == "bind":
                return await self._auth_bind(env, password)
            return await self._auth_search(env, password)
        except LdapResultError as e:
            if e.code == 49:  # invalidCredentials
                return DENY, pkt.RC_BAD_USERNAME_OR_PASSWORD
            log.warning("ldap authn result %s", e)
            return IGNORE, None
        except Exception as e:
            log.warning("ldap authn failed: %s", e)
            return IGNORE, None

    async def _auth_bind(self, env, password):
        dn = render(self.dn_template, env)
        probe = LdapConnector(
            host=self.conn.host,
            port=self.conn.port,
            timeout=self.conn.timeout,
        )
        await probe.start()
        try:
            await probe.bind(dn, password.decode("utf-8", "replace"))
            return OK, None
        finally:
            await probe.stop()

    async def _auth_search(self, env, password):
        rows = await self.conn.search(
            self.conn.base_dn,
            SCOPE_SUB,
            eq_filter(self.filter_attr, env["username"]),
            [self.hash_attr, "isSuperuser", "salt"],
        )
        if not rows:
            return IGNORE, None
        _dn, attrs = rows[0]
        stored = (attrs.get(self.hash_attr) or [b""])[0]
        salt = (attrs.get("salt") or [b""])[0]
        cand = _hash_password(password, self.algo, salt)
        if hmac.compare_digest(cand, stored) or hmac.compare_digest(
            cand.hex().encode(), stored
        ):
            return OK, None
        return DENY, pkt.RC_BAD_USERNAME_OR_PASSWORD
