"""Shared SQL-database authn/authz logic for the MySQL/PostgreSQL clients.

Parity: the query/row handling common to
apps/emqx_authn/src/simple_authn/emqx_authn_mysql.erl + _pgsql.erl
(SELECT password_hash/salt/is_superuser, hash check) and
apps/emqx_authz/src/emqx_authz_mysql.erl + _pgsql.erl
(SELECT permission/action/topic rows mapped to allow|deny).

Both wire clients expose ``await query(sql) -> (columns, rows)`` with
text-protocol values as bytes/str; everything here is protocol-agnostic.

The reference binds parameters with prepared statements; here templated
``${var}`` placeholders are rendered as SQL string literals with quote
escaping (render_sql), which is equivalent for the quoted-literal cases
these queries use.
"""

from __future__ import annotations

import hmac
import logging
import re
from typing import Dict, List, Optional, Sequence, Tuple

from emqx_tpu.broker.auth import DENY, IGNORE, OK, Provider, _hash_password
from emqx_tpu.mqtt import packet as pkt
from emqx_tpu.ops import topics as T
from emqx_tpu.utils.placeholder import render

log = logging.getLogger("emqx_tpu.integration.sql")

_VAR = re.compile(r"\$\{([a-zA-Z0-9_.]+)\}")


def sql_quote(value) -> str:
    """Render one value as a SQL literal (single quotes doubled,
    backslashes escaped — safe for both MySQL and PostgreSQL with
    standard_conforming_strings handled by doubling only quotes for pg;
    backslash doubling is harmless in string context)."""
    if value is None:
        return "NULL"
    if isinstance(value, (bytes, bytearray)):
        s = value.decode("utf-8", "replace")
    else:
        s = str(value)
    return "'" + s.replace("\\", "\\\\").replace("'", "''") + "'"


def render_sql(template: str, env: Dict) -> str:
    """``${var}`` -> quoted SQL literal from env ('' when missing)."""
    return _VAR.sub(lambda m: sql_quote(env.get(m.group(1), "")), template)


def _to_str(v) -> Optional[str]:
    if v is None:
        return None
    if isinstance(v, (bytes, bytearray)):
        return v.decode("utf-8", "replace")
    return str(v)


def _client_env(client_info: Dict) -> Dict:
    return {
        "username": client_info.get("username") or "",
        "clientid": client_info.get("client_id")
        or client_info.get("clientid", ""),
        "peerhost": (client_info.get("peername") or ("", 0))[0],
    }


DEFAULT_AUTHN_QUERY = (
    "SELECT password_hash, salt, is_superuser FROM mqtt_user "
    "where username = ${username} LIMIT 1"
)
DEFAULT_AUTHZ_QUERY = (
    "SELECT permission, action, topic FROM mqtt_acl "
    "where username = ${username}"
)


class SqlSink:
    """Bridge/rule-sink adapter: renders an INSERT template per row env
    and executes it on the wrapped connector (the role of
    emqx_bridge_mysql/pgsql's ``sql`` template)."""

    def __init__(self, conn, sql_template: str):
        self.conn = conn
        self.sql_template = sql_template

    async def start(self) -> None:
        await self.conn.start()

    async def stop(self) -> None:
        await self.conn.stop()

    async def health_check(self) -> bool:
        return await self.conn.health_check()

    async def query(self, env: Dict):
        return await self.conn.query(render_sql(self.sql_template, env))


class SqlAuthProvider(Provider):
    """Credential lookup via a templated SELECT (emqx_authn_mysql/_pgsql
    parity). The query must yield password_hash [, salt [, is_superuser]]
    — matched positionally when columns are unnamed, by name when the
    connector reports column names."""

    def __init__(
        self,
        conn,
        query: str = DEFAULT_AUTHN_QUERY,
        algo: str = "sha256",
    ):
        self.conn = conn
        self.query_template = query
        self.algo = algo

    def authenticate(self, client_info, credentials):
        return IGNORE, None  # decided on the async path

    async def authenticate_async(self, client_info, credentials):
        if credentials.get("enhanced_auth"):
            return IGNORE, None
        sql = render_sql(self.query_template, _client_env(client_info))
        try:
            cols, rows = await self.conn.query(sql)
        except Exception as e:
            log.warning("sql authn lookup failed: %s", e)
            return IGNORE, None
        if not rows:
            return IGNORE, None
        row = rows[0]
        names = [c.lower() for c in cols] if cols else []
        def col(name: str, idx: int):
            if name in names:
                return row[names.index(name)]
            return row[idx] if idx < len(row) else None

        phash = _to_str(col("password_hash", 0))
        salt = _to_str(col("salt", 1)) or ""
        is_super = _to_str(col("is_superuser", 2))
        if phash is None:
            return IGNORE, None
        password = credentials.get("password") or b""
        cand = _hash_password(password, self.algo, salt.encode())
        # stored value may be hex (hashed algos) or raw (algo=plain)
        if hmac.compare_digest(cand.hex(), phash) or hmac.compare_digest(
            cand, phash.encode()
        ):
            if is_super in ("1", "true", "t", "True"):
                client_info["is_superuser"] = True
            return OK, None
        return DENY, pkt.RC_BAD_USERNAME_OR_PASSWORD


class SqlAuthzSource:
    """permission/action/topic rule rows (emqx_authz_mysql/_pgsql parity):
    first row whose action+topic match decides allow|deny; no match falls
    through the chain."""

    def __init__(self, conn, query: str = DEFAULT_AUTHZ_QUERY):
        self.conn = conn
        self.query_template = query

    async def check(self, ci: Dict, action: str, topic: str) -> str:
        env = _client_env(ci)
        sql = render_sql(self.query_template, env)
        try:
            cols, rows = await self.conn.query(sql)
        except Exception as e:
            log.warning("sql authz lookup failed: %s", e)
            return "ignore"
        names = [c.lower() for c in cols] if cols else []

        def col(row: Sequence, name: str, idx: int):
            if name in names:
                return row[names.index(name)]
            return row[idx] if idx < len(row) else None

        for row in rows:
            permission = (_to_str(col(row, "permission", 0)) or "").lower()
            act = (_to_str(col(row, "action", 1)) or "").lower()
            filt = _to_str(col(row, "topic", 2)) or ""
            if act not in (action, "all"):
                continue
            # ``eq `` prefix pins a literal topic (reference authz rule DSL)
            if filt.startswith("eq "):
                matched = topic == filt[3:]
            else:
                matched = T.match(topic, render(filt, env))
            if matched:
                return "allow" if permission == "allow" else "deny"
        return "ignore"
