"""PostgreSQL connector: from-scratch client protocol + authn/authz.

Parity: apps/emqx_connector/src/emqx_connector_pgsql.erl (epgsql client)
plus emqx_authn_pgsql.erl / emqx_authz_pgsql.erl.

No libpq/psycopg in this image, so the v3 frontend/backend protocol is
implemented directly:

- StartupMessage (protocol 3.0) with user/database parameters
- authentication: trust (AuthenticationOk), cleartext password, MD5
  (``md5`` + md5(md5(password+user)+salt)), and SCRAM-SHA-256 SASL
  (RFC 5802/7677 client: client-first/server-first/client-final with
  server-signature verification)
- simple query protocol: Q -> RowDescription/DataRow/CommandComplete/
  ReadyForQuery, ErrorResponse handling

``query(sql) -> (column_names, rows)`` with values as bytes|None, the
interface sql_common.py consumes.
"""

from __future__ import annotations

import asyncio
import base64
import hashlib
import hmac
import logging
import secrets
import struct
from typing import List, Optional, Tuple

from emqx_tpu.integration.resource import Resource
from emqx_tpu.integration.sql_common import (
    DEFAULT_AUTHN_QUERY,
    DEFAULT_AUTHZ_QUERY,
    SqlAuthProvider,
    SqlAuthzSource,
)

log = logging.getLogger("emqx_tpu.integration.pgsql")


class PgError(Exception):
    """Transport / protocol failure (connection must be reset)."""


class PgServerError(PgError):
    """An ErrorResponse: server refused, stream still aligned (the
    backend always follows with ReadyForQuery in the simple protocol)."""

    def __init__(self, fields: dict):
        self.fields = fields
        super().__init__(fields.get("M", "server error"))


def _scram_client_proof(
    password: bytes, salt: bytes, iterations: int, auth_message: bytes
) -> Tuple[bytes, bytes]:
    """-> (client_proof, expected_server_signature) per RFC 5802."""
    salted = hashlib.pbkdf2_hmac("sha256", password, salt, iterations)
    client_key = hmac.new(salted, b"Client Key", hashlib.sha256).digest()
    stored_key = hashlib.sha256(client_key).digest()
    client_sig = hmac.new(stored_key, auth_message, hashlib.sha256).digest()
    proof = bytes(a ^ b for a, b in zip(client_key, client_sig))
    server_key = hmac.new(salted, b"Server Key", hashlib.sha256).digest()
    server_sig = hmac.new(server_key, auth_message, hashlib.sha256).digest()
    return proof, server_sig


class PgsqlConnector(Resource):
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 5432,
        user: str = "postgres",
        password: str = "",
        database: str = "postgres",
        timeout: float = 5.0,
    ):
        self.host = host
        self.port = port
        self.user = user
        self.password = password
        self.database = database
        self.timeout = timeout
        self._r: Optional[asyncio.StreamReader] = None
        self._w: Optional[asyncio.StreamWriter] = None
        self._lock = asyncio.Lock()
        self.parameters: dict = {}

    # -- framing -------------------------------------------------------------
    async def _read_msg(self) -> Tuple[bytes, bytes]:
        hdr = await self._r.readexactly(5)
        tag = hdr[:1]
        n = struct.unpack("!I", hdr[1:])[0]
        body = await self._r.readexactly(n - 4)
        return tag, body

    def _send_msg(self, tag: bytes, body: bytes) -> None:
        self._w.write(tag + struct.pack("!I", len(body) + 4) + body)

    # -- lifecycle -----------------------------------------------------------
    async def start(self) -> None:
        self._r, self._w = await asyncio.wait_for(
            asyncio.open_connection(self.host, self.port), self.timeout
        )
        await asyncio.wait_for(self._startup(), self.timeout)

    async def _startup(self) -> None:
        params = (
            b"user\x00" + self.user.encode() + b"\x00"
            b"database\x00" + self.database.encode() + b"\x00\x00"
        )
        body = struct.pack("!I", 196608) + params  # protocol 3.0
        self._w.write(struct.pack("!I", len(body) + 4) + body)
        while True:
            tag, data = await self._read_msg()
            if tag == b"E":
                raise PgServerError(self._parse_error(data))
            if tag == b"R":
                await self._authenticate(data)
                continue
            if tag == b"S":  # ParameterStatus
                k, _, v = data.rstrip(b"\x00").partition(b"\x00")
                self.parameters[k.decode()] = v.decode()
                continue
            if tag == b"K":  # BackendKeyData
                continue
            if tag == b"Z":  # ReadyForQuery
                return
            raise PgError(f"unexpected startup message {tag!r}")

    async def _authenticate(self, data: bytes) -> None:
        code = struct.unpack_from("!I", data)[0]
        if code == 0:  # AuthenticationOk
            return
        if code == 3:  # cleartext
            self._send_msg(b"p", self.password.encode() + b"\x00")
            return
        if code == 5:  # md5
            salt = data[4:8]
            inner = hashlib.md5(
                self.password.encode() + self.user.encode()
            ).hexdigest()
            digest = hashlib.md5(inner.encode() + salt).hexdigest()
            self._send_msg(b"p", b"md5" + digest.encode() + b"\x00")
            return
        if code == 10:  # SASL: mechanism list
            mechs = [m for m in data[4:].split(b"\x00") if m]
            if b"SCRAM-SHA-256" not in mechs:
                raise PgError(f"no supported SASL mechanism in {mechs}")
            await self._scram()
            return
        raise PgError(f"unsupported authentication request {code}")

    async def _scram(self) -> None:
        cnonce = base64.b64encode(secrets.token_bytes(18)).decode()
        first_bare = f"n=,r={cnonce}".encode()
        initial = b"n,," + first_bare
        body = (
            b"SCRAM-SHA-256\x00" + struct.pack("!I", len(initial)) + initial
        )
        self._send_msg(b"p", body)
        tag, data = await self._read_msg()
        if tag == b"E":
            raise PgServerError(self._parse_error(data))
        if tag != b"R" or struct.unpack_from("!I", data)[0] != 11:
            raise PgError("expected SASLContinue")
        server_first = data[4:]
        attrs = dict(
            kv.split(b"=", 1) for kv in server_first.split(b",") if b"=" in kv
        )
        rnonce = attrs[b"r"].decode()
        if not rnonce.startswith(cnonce):
            raise PgError("server nonce does not extend client nonce")
        salt = base64.b64decode(attrs[b"s"])
        iterations = int(attrs[b"i"])
        final_bare = f"c=biws,r={rnonce}".encode()
        auth_message = first_bare + b"," + server_first + b"," + final_bare
        proof, server_sig = _scram_client_proof(
            self.password.encode(), salt, iterations, auth_message
        )
        final = final_bare + b",p=" + base64.b64encode(proof)
        self._send_msg(b"p", final)
        tag, data = await self._read_msg()
        if tag == b"E":
            raise PgServerError(self._parse_error(data))
        if tag != b"R" or struct.unpack_from("!I", data)[0] != 12:
            raise PgError("expected SASLFinal")
        sf = data[4:]
        got = dict(kv.split(b"=", 1) for kv in sf.split(b",") if b"=" in kv)
        if base64.b64decode(got.get(b"v", b"")) != server_sig:
            raise PgError("bad server signature (server not authenticated)")
        tag, data = await self._read_msg()
        if tag == b"E":
            raise PgServerError(self._parse_error(data))
        if tag != b"R" or struct.unpack_from("!I", data)[0] != 0:
            raise PgError("expected AuthenticationOk after SASL")

    async def stop(self) -> None:
        if self._w is not None:
            try:
                self._send_msg(b"X", b"")  # Terminate
                self._w.close()
                await self._w.wait_closed()
            except Exception:
                pass
            self._r = self._w = None

    async def health_check(self) -> bool:
        try:
            cols, rows = await self.query("SELECT 1")
            return bool(rows and rows[0][0] in (b"1", "1", 1))
        except Exception:
            return False

    # -- simple query protocol ------------------------------------------------
    def _parse_error(self, data: bytes) -> dict:
        out = {}
        pos = 0
        while pos < len(data) and data[pos] != 0:
            t = chr(data[pos])
            end = data.index(b"\x00", pos + 1)
            out[t] = data[pos + 1 : end].decode("utf-8", "replace")
            pos = end + 1
        return out

    async def query(
        self, sql: str
    ) -> Tuple[List[str], List[List[Optional[bytes]]]]:
        async with self._lock:
            try:
                return await asyncio.wait_for(
                    self._do_query(sql), self.timeout
                )
            except PgServerError:
                raise
            except (
                asyncio.TimeoutError,
                asyncio.IncompleteReadError,
                OSError,
                PgError,
            ) as e:
                try:
                    self._w.close()
                except Exception:
                    pass
                self._r = self._w = None
                raise PgError(f"connection reset: {e}") from e

    async def _do_query(self, sql: str):
        if self._w is None:
            raise PgError("not connected")
        self._send_msg(b"Q", sql.encode() + b"\x00")
        cols: List[str] = []
        rows: List[List[Optional[bytes]]] = []
        error: Optional[PgServerError] = None
        while True:
            tag, data = await self._read_msg()
            if tag == b"T":  # RowDescription
                (n,) = struct.unpack_from("!H", data)
                pos = 2
                cols = []
                for _ in range(n):
                    end = data.index(b"\x00", pos)
                    cols.append(data[pos:end].decode("utf-8", "replace"))
                    pos = end + 1 + 18  # oid/attnum/typoid/typlen/mod/fmt
            elif tag == b"D":  # DataRow
                (n,) = struct.unpack_from("!H", data)
                pos = 2
                row: List[Optional[bytes]] = []
                for _ in range(n):
                    (ln,) = struct.unpack_from("!i", data, pos)
                    pos += 4
                    if ln == -1:
                        row.append(None)
                    else:
                        row.append(data[pos : pos + ln])
                        pos += ln
                rows.append(row)
            elif tag == b"C" or tag == b"I":  # CommandComplete / EmptyQuery
                continue
            elif tag == b"E":
                error = PgServerError(self._parse_error(data))
            elif tag == b"N":  # NoticeResponse
                continue
            elif tag == b"Z":  # ReadyForQuery: transaction done
                if error is not None:
                    raise error
                return cols, rows
            else:
                raise PgError(f"unexpected message {tag!r}")

    async def execute(self, sql: str) -> None:
        await self.query(sql)


class PgsqlAuthProvider(SqlAuthProvider):
    """emqx_authn_pgsql.erl parity over the from-scratch client."""

    def __init__(self, conn: PgsqlConnector, query: str = DEFAULT_AUTHN_QUERY,
                 algo: str = "sha256"):
        super().__init__(conn, query, algo)


class PgsqlAuthzSource(SqlAuthzSource):
    """emqx_authz_pgsql.erl parity over the from-scratch client."""

    def __init__(self, conn: PgsqlConnector, query: str = DEFAULT_AUTHZ_QUERY):
        super().__init__(conn, query)
