"""MQTT connector: ingress/egress bridge to a remote MQTT broker.

Parity with emqx_connector's MQTT bridge (apps/emqx_connector/src/mqtt/ —
emqtt-based ingress/egress workers):

- **egress**: local messages handed to `query()` (from a rule output or a
  local-topic hook) are published to the remote broker under a templated
  remote topic.
- **ingress**: the connector subscribes on the remote broker; arriving
  messages are re-published into the LOCAL broker under a templated local
  topic (loop-guarded via a bridge header).

The remote session is the in-repo MQTT client; health = liveness of that
connection (reconnect is the ResourceManager's restart cycle).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Dict, Optional

from emqx_tpu.broker.message import Message
from emqx_tpu.integration.resource import Resource
from emqx_tpu.mqtt import packet as pkt
from emqx_tpu.utils.placeholder import render

log = logging.getLogger("emqx_tpu.integration.mqtt")


class MqttConnector(Resource):
    def __init__(
        self,
        broker,
        host: str,
        port: int,
        clientid: str = "emqx-tpu-bridge",
        username: Optional[str] = None,
        password: Optional[str] = None,
        # egress: query(env) -> publish remote_topic template
        remote_topic: str = "${topic}",
        remote_qos: int = 0,
        payload: str = "${payload}",
        # ingress: remote filter -> local topic template
        ingress_filter: Optional[str] = None,
        local_topic: str = "${topic}",
        local_qos: int = 0,
        keepalive: int = 30,
    ):
        self.broker = broker
        self.host = host
        self.port = port
        self.clientid = clientid
        self.username = username
        self.password = password
        self.remote_topic = remote_topic
        self.remote_qos = remote_qos
        self.payload = payload
        self.ingress_filter = ingress_filter
        self.local_topic = local_topic
        self.local_qos = local_qos
        self.keepalive = keepalive
        self._client = None
        self._ingress_task: Optional[asyncio.Task] = None

    async def start(self) -> None:
        from emqx_tpu.mqtt.client import Client

        pw = self.password
        c = Client(
            client_id=self.clientid,
            username=self.username,
            password=pw.encode() if isinstance(pw, str) else pw,
            keepalive=self.keepalive,
        )
        await c.connect(self.host, self.port)
        self._client = c
        if self.ingress_filter:
            await c.subscribe(
                [(self.ingress_filter, pkt.SubOpts(qos=self.local_qos))]
            )
            self._ingress_task = asyncio.get_running_loop().create_task(
                self._ingress_loop()
            )

    async def stop(self) -> None:
        if self._ingress_task is not None:
            self._ingress_task.cancel()
            self._ingress_task = None
        if self._client is not None:
            try:
                await self._client.disconnect()
            except Exception:
                pass
            self._client = None

    async def health_check(self) -> bool:
        c = self._client
        return bool(c is not None and not c.closed.is_set())

    # -- egress ------------------------------------------------------------
    async def query(self, env: Dict) -> None:
        """Publish one local message/row to the remote broker."""
        if self._client is None or self._client.closed.is_set():
            raise RuntimeError("mqtt bridge not connected")
        topic = render(self.remote_topic, env)
        payload = render(self.payload, env).encode()
        await self._client.publish(
            topic, payload, qos=self.remote_qos, timeout=30
        )

    # -- ingress -----------------------------------------------------------
    async def _ingress_loop(self) -> None:
        try:
            while True:
                p = await self._client.messages.get()
                env = {
                    "topic": p.topic,
                    "payload": p.payload,
                    "qos": p.qos,
                }
                msg = Message(
                    topic=render(self.local_topic, env),
                    payload=p.payload,
                    qos=self.local_qos,
                    from_client=self.clientid,
                )
                # loop guard: a bridged-in message must not be bridged out
                # again by an egress rule on the same broker
                msg.headers["bridged"] = True
                r = await self.broker.apublish_enqueue(msg)
                if asyncio.isfuture(r):
                    await r
        except asyncio.CancelledError:
            pass
        except Exception:
            log.exception("mqtt bridge ingress failed")
