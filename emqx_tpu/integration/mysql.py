"""MySQL connector: from-scratch client protocol + authn/authz backends.

Parity: apps/emqx_connector/src/emqx_connector_mysql.erl (mysql-otp
client) plus emqx_authn_mysql.erl / emqx_authz_mysql.erl.

No MySQL client library exists in this image, so the wire protocol is
implemented directly (the same approach as the RESP2 client in
integration/redis.py):

- packet framing: 3-byte little-endian length + sequence id
- handshake v10 parse + HandshakeResponse41 with
  ``mysql_native_password`` scramble (SHA1(p) XOR SHA1(nonce·SHA1²(p))),
  AuthSwitchRequest handling
- text protocol COM_QUERY result sets (column definitions skipped,
  length-encoded row values), OK/ERR/EOF packets, COM_PING health checks

``query(sql) -> (column_names, rows)`` with row values as bytes|None,
which is what the shared SQL authn/authz layer (sql_common.py) consumes.
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
import struct
from typing import List, Optional, Tuple

from emqx_tpu.integration.resource import Resource
from emqx_tpu.integration.sql_common import (
    DEFAULT_AUTHN_QUERY,
    DEFAULT_AUTHZ_QUERY,
    SqlAuthProvider,
    SqlAuthzSource,
)

log = logging.getLogger("emqx_tpu.integration.mysql")

# capability flags (include/mysql_com.h names)
CLIENT_LONG_PASSWORD = 0x1
CLIENT_CONNECT_WITH_DB = 0x8
CLIENT_PROTOCOL_41 = 0x200
CLIENT_TRANSACTIONS = 0x2000
CLIENT_SECURE_CONNECTION = 0x8000
CLIENT_PLUGIN_AUTH = 0x80000

COM_QUERY = 0x03
COM_PING = 0x0E
COM_QUIT = 0x01

UTF8_CHARSET = 33


class MysqlError(Exception):
    """Transport / protocol failure (connection must be reset)."""


class MysqlServerError(MysqlError):
    """An ERR packet: server refused, stream still aligned."""

    def __init__(self, code: int, message: str):
        super().__init__(f"[{code}] {message}")
        self.code = code


def native_password_scramble(password: bytes, nonce: bytes) -> bytes:
    """mysql_native_password: SHA1(p) XOR SHA1(nonce + SHA1(SHA1(p)))."""
    if not password:
        return b""
    h1 = hashlib.sha1(password).digest()
    h2 = hashlib.sha1(h1).digest()
    h3 = hashlib.sha1(nonce + h2).digest()
    return bytes(a ^ b for a, b in zip(h1, h3))


def _lenenc_int(data: bytes, pos: int) -> Tuple[Optional[int], int]:
    first = data[pos]
    if first < 0xFB:
        return first, pos + 1
    if first == 0xFB:  # NULL in row context
        return None, pos + 1
    if first == 0xFC:
        return struct.unpack_from("<H", data, pos + 1)[0], pos + 3
    if first == 0xFD:
        return int.from_bytes(data[pos + 1 : pos + 4], "little"), pos + 4
    if first == 0xFE:
        return struct.unpack_from("<Q", data, pos + 1)[0], pos + 9
    raise MysqlError(f"bad length-encoded integer 0x{first:02x}")


def _lenenc_str(data: bytes, pos: int) -> Tuple[Optional[bytes], int]:
    n, pos = _lenenc_int(data, pos)
    if n is None:
        return None, pos
    return data[pos : pos + n], pos + n


class MysqlConnector(Resource):
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 3306,
        user: str = "root",
        password: str = "",
        database: str = "",
        timeout: float = 5.0,
    ):
        self.host = host
        self.port = port
        self.user = user
        self.password = password
        self.database = database
        self.timeout = timeout
        self._r: Optional[asyncio.StreamReader] = None
        self._w: Optional[asyncio.StreamWriter] = None
        self._lock = asyncio.Lock()
        self._seq = 0
        self.server_version = ""

    # -- framing -------------------------------------------------------------
    async def _read_packet(self) -> bytes:
        hdr = await self._r.readexactly(4)
        n = int.from_bytes(hdr[:3], "little")
        self._seq = (hdr[3] + 1) & 0xFF
        return await self._r.readexactly(n)

    def _send_packet(self, payload: bytes) -> None:
        self._w.write(
            len(payload).to_bytes(3, "little")
            + bytes([self._seq])
            + payload
        )
        self._seq = (self._seq + 1) & 0xFF

    # -- lifecycle -----------------------------------------------------------
    async def start(self) -> None:
        self._r, self._w = await asyncio.wait_for(
            asyncio.open_connection(self.host, self.port), self.timeout
        )
        self._seq = 0
        await asyncio.wait_for(self._handshake(), self.timeout)

    async def _handshake(self) -> None:
        greeting = await self._read_packet()
        if greeting[0] == 0xFF:
            raise self._err(greeting)
        if greeting[0] != 10:
            raise MysqlError(f"unsupported protocol version {greeting[0]}")
        pos = 1
        end = greeting.index(b"\x00", pos)
        self.server_version = greeting[pos:end].decode()
        pos = end + 1 + 4  # connection id
        auth1 = greeting[pos : pos + 8]
        pos += 8 + 1  # filler
        cap = struct.unpack_from("<H", greeting, pos)[0]
        pos += 2
        auth2 = b""
        plugin = b"mysql_native_password"
        if len(greeting) > pos:
            pos += 1 + 2  # charset + status
            cap |= struct.unpack_from("<H", greeting, pos)[0] << 16
            pos += 2
            auth_len = greeting[pos]
            pos += 1 + 10  # reserved
            if cap & CLIENT_SECURE_CONNECTION:
                n2 = max(13, auth_len - 8)
                auth2 = greeting[pos : pos + n2].rstrip(b"\x00")
                pos += n2
            if cap & CLIENT_PLUGIN_AUTH:
                end = greeting.index(b"\x00", pos)
                plugin = greeting[pos:end]
        nonce = (auth1 + auth2)[:20]

        flags = (
            CLIENT_LONG_PASSWORD
            | CLIENT_PROTOCOL_41
            | CLIENT_TRANSACTIONS
            | CLIENT_SECURE_CONNECTION
            | CLIENT_PLUGIN_AUTH
        )
        if self.database:
            flags |= CLIENT_CONNECT_WITH_DB
        auth_resp = native_password_scramble(self.password.encode(), nonce)
        body = struct.pack("<IIB23x", flags, 1 << 24, UTF8_CHARSET)
        body += self.user.encode() + b"\x00"
        body += bytes([len(auth_resp)]) + auth_resp
        if self.database:
            body += self.database.encode() + b"\x00"
        body += b"mysql_native_password\x00"
        self._send_packet(body)

        resp = await self._read_packet()
        if resp[0] == 0xFE:  # AuthSwitchRequest
            end = resp.index(b"\x00", 1)
            switch_plugin = resp[1:end]
            new_nonce = resp[end + 1 :].rstrip(b"\x00")[:20]
            if switch_plugin != b"mysql_native_password":
                raise MysqlError(
                    f"unsupported auth plugin {switch_plugin!r}"
                )
            self._send_packet(
                native_password_scramble(self.password.encode(), new_nonce)
            )
            resp = await self._read_packet()
        if resp[0] == 0xFF:
            raise self._err(resp)
        if resp[0] != 0x00:
            raise MysqlError(f"unexpected handshake reply 0x{resp[0]:02x}")

    async def stop(self) -> None:
        if self._w is not None:
            try:
                self._seq = 0
                self._send_packet(bytes([COM_QUIT]))
                self._w.close()
                await self._w.wait_closed()
            except Exception:
                pass
            self._r = self._w = None

    async def health_check(self) -> bool:
        try:
            await self._command(bytes([COM_PING]))
            return True
        except Exception:
            return False

    # -- text protocol -------------------------------------------------------
    def _err(self, pkt: bytes) -> MysqlServerError:
        code = struct.unpack_from("<H", pkt, 1)[0]
        msg = pkt[3:]
        if msg[:1] == b"#":  # sql state marker
            msg = msg[6:]
        return MysqlServerError(code, msg.decode("utf-8", "replace"))

    async def _command(self, payload: bytes) -> bytes:
        if self._w is None:
            raise MysqlError("not connected")
        self._seq = 0
        self._send_packet(payload)
        pkt = await self._read_packet()
        if pkt[0] == 0xFF:
            raise self._err(pkt)
        return pkt

    async def query(
        self, sql: str
    ) -> Tuple[List[str], List[List[Optional[bytes]]]]:
        """COM_QUERY -> (column_names, rows); DML returns ([], [])."""
        async with self._lock:
            try:
                return await asyncio.wait_for(
                    self._do_query(sql), self.timeout
                )
            except MysqlServerError:
                raise
            except (
                asyncio.TimeoutError,
                asyncio.IncompleteReadError,
                OSError,
                MysqlError,
            ) as e:
                # desynced stream: drop the connection, resource layer
                # reconnects (same policy as the RESP2 client)
                try:
                    self._w.close()
                except Exception:
                    pass
                self._r = self._w = None
                raise MysqlError(f"connection reset: {e}") from e

    async def _do_query(self, sql: str):
        first = await self._command(bytes([COM_QUERY]) + sql.encode())
        if first[0] == 0x00:  # OK packet: no result set
            return [], []
        ncols, _ = _lenenc_int(first, 0)
        cols: List[str] = []
        for _ in range(ncols):
            coldef = await self._read_packet()
            # catalog, schema, table, org_table, name, org_name
            pos = 0
            vals = []
            for _f in range(6):
                v, pos = _lenenc_str(coldef, pos)
                vals.append(v)
            cols.append((vals[4] or b"").decode("utf-8", "replace"))
        pkt = await self._read_packet()
        if pkt[0] == 0xFE and len(pkt) < 9:  # EOF after col defs
            pkt = await self._read_packet()
        rows: List[List[Optional[bytes]]] = []
        while True:
            if pkt[0] == 0xFE and len(pkt) < 9:  # EOF: result done
                break
            if pkt[0] == 0xFF:
                raise self._err(pkt)
            pos = 0
            row: List[Optional[bytes]] = []
            for _ in range(ncols):
                v, pos = _lenenc_str(pkt, pos)
                row.append(v)
            rows.append(row)
            pkt = await self._read_packet()
        return cols, rows

    async def execute(self, sql: str) -> None:
        await self.query(sql)


class MysqlAuthProvider(SqlAuthProvider):
    """emqx_authn_mysql.erl parity over the from-scratch client."""

    def __init__(self, conn: MysqlConnector, query: str = DEFAULT_AUTHN_QUERY,
                 algo: str = "sha256"):
        super().__init__(conn, query, algo)


class MysqlAuthzSource(SqlAuthzSource):
    """emqx_authz_mysql.erl parity over the from-scratch client."""

    def __init__(self, conn: MysqlConnector, query: str = DEFAULT_AUTHZ_QUERY):
        super().__init__(conn, query)
