"""Bridges: named connector instances bound to the broker + rule engine.

Parity with emqx_bridge (apps/emqx_bridge/src/): a bridge is a configured
connector running under the ResourceManager, reachable three ways —

- as a **rule-engine output** (`outputs: [{type: bridge, id: ...}]`),
  the reference's `{bridge, BridgeId}` output resolution;
- via a **local_topic** binding: messages published to that filter are
  forwarded automatically (egress without a rule), matching the
  reference's bridge `local_topic` shortcut;
- **ingress** bridges re-publish remote messages locally (driven inside
  the MQTT connector itself).

Bridge ids follow the reference's `type:name` convention (http:alarm,
mqtt:site_a).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Dict, List, Optional

from emqx_tpu.integration.resource import ResourceManager
from emqx_tpu.ops import topics as T

log = logging.getLogger("emqx_tpu.integration.bridge")


def _msg_env(msg) -> Dict:
    return {
        "topic": msg.topic,
        "payload": msg.payload,
        "qos": msg.qos,
        "retain": msg.retain,
        "clientid": msg.from_client,
        "username": msg.from_username,
        "id": str(msg.mid),
        "timestamp": int(msg.timestamp * 1000),
    }


class BridgeManager:
    def __init__(self, broker, hooks, resources: Optional[ResourceManager] = None):
        self.broker = broker
        self.hooks = hooks
        self.resources = resources or ResourceManager()
        # bridge id -> config dict (incl. local_topic binding)
        self._bridges: Dict[str, Dict] = {}
        self._hooked = False

    # -- config ------------------------------------------------------------
    async def create(self, bridge_id: str, config: Dict):
        """config: {type: http|mqtt, enable, local_topic?, ...connector opts}"""
        if bridge_id in self._bridges:
            raise ValueError(f"bridge already exists: {bridge_id}")
        btype, _, _name = bridge_id.partition(":")
        cfg = dict(config)
        resource = self._make_resource(btype, cfg)
        await self.resources.create(
            bridge_id, resource, enabled=cfg.get("enable", True)
        )
        self._bridges[bridge_id] = cfg
        if cfg.get("local_topic") and not self._hooked:
            self.hooks.add(
                "message.publish", self._on_publish, tag="bridge"
            )
            self._hooked = True
        return self.resources.get(bridge_id)

    def _make_resource(self, btype: str, cfg: Dict):
        if btype == "http":
            from emqx_tpu.integration.http import HttpConnector

            return HttpConnector(
                base_url=cfg["url"],
                method=cfg.get("method", "POST"),
                path=cfg.get("path", ""),
                headers=cfg.get("headers"),
                body=cfg.get("body", "${payload}"),
                request_timeout=cfg.get("request_timeout", 5.0),
                pool_size=cfg.get("pool_size", 8),
                health_path=cfg.get("health_path", ""),
            )
        if btype == "mqtt":
            from emqx_tpu.integration.mqtt_bridge import MqttConnector

            return MqttConnector(
                self.broker,
                host=cfg["host"],
                port=cfg.get("port", 1883),
                clientid=cfg.get("clientid", "emqx-tpu-bridge"),
                username=cfg.get("username"),
                password=cfg.get("password"),
                remote_topic=cfg.get("remote_topic", "${topic}"),
                remote_qos=cfg.get("remote_qos", 0),
                payload=cfg.get("payload", "${payload}"),
                ingress_filter=cfg.get("ingress_filter"),
                local_topic=cfg.get("ingress_local_topic", "${topic}"),
                local_qos=cfg.get("ingress_local_qos", 0),
            )
        if btype in ("mysql", "pgsql"):
            from emqx_tpu.integration.sql_common import SqlSink

            if btype == "mysql":
                from emqx_tpu.integration.mysql import MysqlConnector as Conn
            else:
                from emqx_tpu.integration.pgsql import PgsqlConnector as Conn
            conn = Conn(
                host=cfg.get("host", "127.0.0.1"),
                port=cfg.get("port", 3306 if btype == "mysql" else 5432),
                user=cfg.get("user") or cfg.get("username", ""),
                password=cfg.get("password", ""),
                database=cfg.get("database", ""),
                timeout=cfg.get("request_timeout", 5.0),
            )
            return SqlSink(conn, cfg.get("sql", ""))
        if btype == "mongodb":
            from emqx_tpu.integration.mongodb import MongoConnector

            conn = MongoConnector(
                host=cfg.get("host", "127.0.0.1"),
                port=cfg.get("port", 27017),
                username=cfg.get("username", ""),
                password=cfg.get("password", ""),
                database=cfg.get("database", "mqtt"),
                auth_source=cfg.get("auth_source", "admin"),
                timeout=cfg.get("request_timeout", 5.0),
            )
            conn.sink_collection = cfg.get("collection", "mqtt_messages")
            conn.sink_template = cfg.get(
                "payload_template",
                {"topic": "${topic}", "payload": "${payload}"},
            )
            return conn
        if btype == "redis":
            from emqx_tpu.integration.redis import RedisConnector
            from emqx_tpu.utils.placeholder import render

            conn = RedisConnector(
                host=cfg.get("host", "127.0.0.1"),
                port=cfg.get("port", 6379),
                db=cfg.get("db", 0),
                password=cfg.get("password"),
                timeout=cfg.get("request_timeout", 5.0),
            )
            cmd_template = cfg.get("command", ["LPUSH", "emqx:${topic}", "${payload}"])

            class RedisSink:
                async def start(self):
                    await conn.start()

                async def stop(self):
                    await conn.stop()

                async def health_check(self):
                    return await conn.health_check()

                async def query(self, env):
                    args = [render(str(a), env) for a in cmd_template]
                    return await conn.command(*args)

            return RedisSink()
        raise ValueError(f"unknown bridge type: {btype}")

    async def remove(self, bridge_id: str) -> bool:
        self._bridges.pop(bridge_id, None)
        return await self.resources.remove(bridge_id)

    async def close(self) -> None:
        await self.resources.close()
        self._bridges.clear()

    # -- egress paths -------------------------------------------------------
    def _on_publish(self, msg):
        """local_topic binding ('message.publish' fold member, acc = the
        message): forward a matching publish to every bound bridge,
        without consuming it."""
        if msg is None or msg.headers.get("bridged"):
            return msg
        for bid, cfg in self._bridges.items():
            lt = cfg.get("local_topic")
            if lt and T.match(msg.topic, lt):
                asyncio.get_event_loop().create_task(
                    self._send_safe(bid, _msg_env(msg))
                )
        return msg

    async def _send_safe(self, bridge_id: str, env: Dict) -> None:
        try:
            await self.resources.query(bridge_id, env)
        except Exception as e:
            log.warning("bridge %s send failed: %s", bridge_id, e)

    def send_row(self, bridge_id: str, row: Dict, ctx: Dict) -> None:
        """Fire-and-forget one rule row / message env to a bridge."""
        env = dict(ctx)
        env.update(row)
        asyncio.get_event_loop().create_task(self._send_safe(bridge_id, env))

    def rule_output(self, bridge_id: str):
        """A rule-engine Output forwarding matched rows to this bridge
        (emqx_rule_outputs' {bridge, Id} resolution)."""
        from emqx_tpu.rules.engine import FunctionOutput

        return FunctionOutput(
            lambda row, ctx: self.send_row(bridge_id, row, ctx),
            name=f"bridge:{bridge_id}",
        )

    # -- introspection ------------------------------------------------------
    def list(self) -> List[Dict]:
        out = []
        for info in self.resources.list():
            cfg = self._bridges.get(info["id"], {})
            info = dict(info)
            info["local_topic"] = cfg.get("local_topic")
            info["type"] = info["id"].partition(":")[0]
            out.append(info)
        return out
