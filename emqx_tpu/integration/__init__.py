"""Data integration: resources, connectors, and bridges.

TPU-stack analog of the reference's integration triad:
- `emqx_resource` (apps/emqx_resource/src/emqx_resource_instance.erl) —
  resource instance lifecycle with health checks and auto-restart
  -> `integration/resource.py`
- `emqx_connector` (apps/emqx_connector/src/) — typed clients for
  external systems (HTTP, MQTT ingress/egress)
  -> `integration/http.py`, `integration/mqtt_bridge.py`
- `emqx_bridge` (apps/emqx_bridge/src/) — the config layer binding
  connectors to the broker and the rule engine
  -> `integration/bridge.py`
"""

from emqx_tpu.integration.bridge import BridgeManager
from emqx_tpu.integration.resource import Resource, ResourceManager, ResourceStatus

__all__ = ["BridgeManager", "Resource", "ResourceManager", "ResourceStatus"]
