"""Resource instance lifecycle: create / health-check / restart.

Parity with emqx_resource (apps/emqx_resource/src/emqx_resource_instance.erl
create/start/stop/restart/remove + emqx_resource_health_check.erl): each
resource is an async client owned by the manager, which drives a periodic
health check and restarts unhealthy instances with exponential backoff.

Statuses mirror the reference: ``connecting | connected | disconnected |
stopped``. Query errors mark the instance disconnected immediately, which
fast-tracks the next health cycle's restart.
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

log = logging.getLogger("emqx_tpu.integration")


class ResourceStatus:
    CONNECTING = "connecting"
    CONNECTED = "connected"
    DISCONNECTED = "disconnected"
    STOPPED = "stopped"


class Resource:
    """Behaviour every connector implements (emqx_resource callback
    module: on_start/on_stop/on_query/on_health_check)."""

    async def start(self) -> None:
        raise NotImplementedError

    async def stop(self) -> None:
        raise NotImplementedError

    async def health_check(self) -> bool:
        return True

    async def query(self, request) -> object:
        raise NotImplementedError


@dataclass
class _Instance:
    id: str
    resource: Resource
    status: str = ResourceStatus.CONNECTING
    enabled: bool = True
    restarts: int = 0
    last_error: Optional[str] = None
    started_at: float = field(default_factory=time.time)
    metrics: Dict[str, int] = field(
        default_factory=lambda: {"success": 0, "failed": 0, "matched": 0}
    )
    _backoff: float = 1.0
    _next_try: float = 0.0
    # serializes start/stop/health transitions: a health tick must never
    # interleave with an in-flight create/restart (both await)
    _lock: asyncio.Lock = field(default_factory=asyncio.Lock)


class ResourceManager:
    """Owns every resource instance + the health-check loop."""

    def __init__(self, health_interval: float = 5.0, backoff_max: float = 60.0):
        self.health_interval = health_interval
        self.backoff_max = backoff_max
        self._instances: Dict[str, _Instance] = {}
        self._task: Optional[asyncio.Task] = None

    # -- lifecycle ---------------------------------------------------------
    async def create(self, rid: str, resource: Resource, enabled: bool = True):
        """Create + start (emqx_resource_instance create_local)."""
        if rid in self._instances:
            raise ValueError(f"resource already exists: {rid}")
        inst = _Instance(id=rid, resource=resource, enabled=enabled)
        if enabled:
            await self._start_inst(inst)
        else:
            inst.status = ResourceStatus.STOPPED
        # register only once the initial start settled — the health loop
        # must not see (and "restart") an instance mid-create
        if rid in self._instances:
            raise ValueError(f"resource already exists: {rid}")
        self._instances[rid] = inst
        if self._task is None:
            self._task = asyncio.get_running_loop().create_task(
                self._health_loop()
            )
        return inst

    async def _start_inst(self, inst: _Instance) -> None:
        inst.status = ResourceStatus.CONNECTING
        try:
            await inst.resource.start()
        except Exception as e:
            inst.status = ResourceStatus.DISCONNECTED
            inst.last_error = str(e)
            log.warning("resource %s start failed: %s", inst.id, e)
            return
        healthy = False
        try:
            healthy = await inst.resource.health_check()
        except Exception as e:
            inst.last_error = str(e)
        inst.status = (
            ResourceStatus.CONNECTED if healthy else ResourceStatus.DISCONNECTED
        )
        if healthy:
            inst._backoff = 1.0
            inst.last_error = None

    async def stop(self, rid: str) -> bool:
        inst = self._instances.get(rid)
        if inst is None:
            return False
        inst.enabled = False
        try:
            await inst.resource.stop()
        except Exception:
            pass
        inst.status = ResourceStatus.STOPPED
        return True

    async def restart(self, rid: str) -> bool:
        inst = self._instances.get(rid)
        if inst is None:
            return False
        async with inst._lock:
            try:
                await inst.resource.stop()
            except Exception:
                pass
            inst.enabled = True
            inst.restarts += 1
            await self._start_inst(inst)
        return True

    async def remove(self, rid: str) -> bool:
        inst = self._instances.pop(rid, None)
        if inst is None:
            return False
        try:
            await inst.resource.stop()
        except Exception:
            pass
        return True

    async def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
        for rid in list(self._instances):
            await self.remove(rid)

    # -- query path --------------------------------------------------------
    async def query(self, rid: str, request) -> object:
        """Route one request to the resource; failures mark it
        disconnected so the health loop restarts it."""
        inst = self._instances.get(rid)
        if inst is None:
            raise KeyError(f"no such resource: {rid}")
        inst.metrics["matched"] += 1
        if inst.status == ResourceStatus.STOPPED:
            inst.metrics["failed"] += 1
            raise RuntimeError(f"resource {rid} is stopped")
        try:
            out = await inst.resource.query(request)
        except Exception as e:
            inst.metrics["failed"] += 1
            inst.status = ResourceStatus.DISCONNECTED
            inst.last_error = str(e)
            raise
        inst.metrics["success"] += 1
        return out

    # -- health ------------------------------------------------------------
    async def _health_loop(self) -> None:
        try:
            while True:
                await asyncio.sleep(self.health_interval)
                for inst in list(self._instances.values()):
                    if not inst.enabled:
                        continue
                    await self._check_one(inst)
        except asyncio.CancelledError:
            pass

    async def _check_one(self, inst: _Instance) -> None:
        if inst._lock.locked():
            return  # create/restart in flight; don't interleave
        async with inst._lock:
            if inst.status == ResourceStatus.CONNECTED:
                try:
                    ok = await inst.resource.health_check()
                except Exception as e:
                    ok = False
                    inst.last_error = str(e)
                if not ok:
                    inst.status = ResourceStatus.DISCONNECTED
                    log.warning("resource %s unhealthy", inst.id)
            if inst.status in (
                ResourceStatus.DISCONNECTED,
                ResourceStatus.CONNECTING,
            ):
                # exponential backoff between restart attempts
                now = time.monotonic()
                if now < inst._next_try:
                    return
                inst._backoff = min(inst._backoff * 2, self.backoff_max)
                inst._next_try = now + inst._backoff
                inst.restarts += 1
                log.info(
                    "resource %s: restart attempt %d", inst.id, inst.restarts
                )
                try:
                    await inst.resource.stop()
                except Exception:
                    pass
                await self._start_inst(inst)

    async def check_now(self, rid: str) -> Optional[str]:
        """Force one health cycle (tests / REST health endpoint)."""
        inst = self._instances.get(rid)
        if inst is None:
            return None
        inst._next_try = 0.0
        await self._check_one(inst)
        return inst.status

    # -- introspection -----------------------------------------------------
    def get(self, rid: str) -> Optional[_Instance]:
        return self._instances.get(rid)

    def status(self, rid: str) -> Optional[str]:
        inst = self._instances.get(rid)
        return inst.status if inst else None

    def list(self) -> List[Dict]:
        return [
            {
                "id": i.id,
                "status": i.status,
                "enabled": i.enabled,
                "restarts": i.restarts,
                "last_error": i.last_error,
                "metrics": dict(i.metrics),
            }
            for i in self._instances.values()
        ]
