"""Redis connector: minimal RESP2 client + authn/authz backends.

Parity: apps/emqx_connector/src/emqx_connector_redis.erl (eredis client)
plus the Redis authn/authz backends
(apps/emqx_authn/src/simple_authn/emqx_authn_redis.erl,
apps/emqx_authz/src/emqx_authz_redis.erl).

No redis-py in this image, so the RESP2 wire protocol is implemented
directly (it is an intentionally trivial protocol: `*N\\r\\n$len\\r\\n...`
arrays of bulk strings out, typed replies back). Single connection with
an asyncio lock (commands are cheap; the reference pools via ecpool —
pool_size here multiplexes over N connections).

- `RedisConnector` — Resource-lifecycle client (PING health checks)
- `RedisAuthProvider` — HMGET from a templated key: password_hash/salt/
  is_superuser fields, same hash algebra as the builtin DB
- `RedisAuthzSource` — HGETALL of a templated key: topic-filter ->
  publish|subscribe|all, mapped onto the rule DSL
"""

from __future__ import annotations

import asyncio
import hmac
import logging
from typing import Dict, List, Optional, Tuple

from emqx_tpu.broker.auth import DENY, IGNORE, OK, Provider, _hash_password
from emqx_tpu.integration.resource import Resource
from emqx_tpu.mqtt import packet as pkt
from emqx_tpu.ops import topics as T
from emqx_tpu.utils.placeholder import render

log = logging.getLogger("emqx_tpu.integration.redis")


class RespError(Exception):
    """Transport/protocol-level failure (stream possibly desynced)."""


class RedisServerError(RespError):
    """A `-ERR ...` reply: the server refused the command but the reply
    stream is still in sync — no reset needed."""


def _encode_command(args: List) -> bytes:
    out = [f"*{len(args)}\r\n".encode()]
    for a in args:
        b = a if isinstance(a, bytes) else str(a).encode()
        out.append(f"${len(b)}\r\n".encode())
        out.append(b + b"\r\n")
    return b"".join(out)


class RedisConnector(Resource):
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 6379,
        db: int = 0,
        password: Optional[str] = None,
        timeout: float = 5.0,
    ):
        self.host = host
        self.port = port
        self.db = db
        self.password = password
        self.timeout = timeout
        self._r: Optional[asyncio.StreamReader] = None
        self._w: Optional[asyncio.StreamWriter] = None
        self._lock = asyncio.Lock()

    # -- lifecycle ---------------------------------------------------------
    async def start(self) -> None:
        self._r, self._w = await asyncio.wait_for(
            asyncio.open_connection(self.host, self.port), self.timeout
        )
        if self.password:
            await self.command("AUTH", self.password)
        if self.db:
            await self.command("SELECT", self.db)

    async def stop(self) -> None:
        if self._w is not None:
            try:
                self._w.close()
                await self._w.wait_closed()
            except Exception:
                pass
            self._r = self._w = None

    async def health_check(self) -> bool:
        try:
            return (await self.command("PING")) in ("PONG", b"PONG")
        except Exception:
            return False

    async def query(self, request: List):
        return await self.command(*request)

    # -- RESP2 -------------------------------------------------------------
    async def command(self, *args):
        if self._w is None:
            raise RespError("not connected")
        async with self._lock:
            try:
                self._w.write(_encode_command(list(args)))
                return await asyncio.wait_for(
                    self._read_reply(), self.timeout
                )
            except RedisServerError:
                raise  # reply stream still aligned
            except (
                asyncio.TimeoutError,
                asyncio.IncompleteReadError,
                OSError,
                RespError,
            ) as e:
                # a timed-out/torn reply leaves the stream desynchronized —
                # the NEXT command would read THIS command's late reply.
                # Drop the connection; the health/restart cycle reconnects.
                try:
                    self._w.close()
                except Exception:
                    pass
                self._r = self._w = None
                raise RespError(f"connection reset: {e}") from e

    async def _read_reply(self):
        line = await self._r.readline()
        if not line.endswith(b"\r\n"):
            raise RespError("connection closed mid-reply")
        kind, rest = line[:1], line[1:-2]
        if kind == b"+":
            return rest.decode()
        if kind == b"-":
            raise RedisServerError(rest.decode())
        if kind == b":":
            return int(rest)
        if kind == b"$":
            n = int(rest)
            if n == -1:
                return None
            data = await self._r.readexactly(n + 2)
            return data[:-2]
        if kind == b"*":
            n = int(rest)
            if n == -1:
                return None
            return [await self._read_reply() for _ in range(n)]
        raise RespError(f"unknown RESP type {kind!r}")


class RedisAuthProvider(Provider):
    """HMGET-based credential lookup (emqx_authn_redis parity): the key
    template (default ``mqtt_user:${username}``) holds fields
    password_hash / salt / is_superuser; algorithm as the builtin DB
    (plain | sha256 | pbkdf2)."""

    def __init__(
        self,
        conn: RedisConnector,
        key_template: str = "mqtt_user:${username}",
        algo: str = "sha256",
    ):
        self.conn = conn
        self.key_template = key_template
        self.algo = algo

    def authenticate(self, client_info, credentials):
        return IGNORE, None  # sync path has no opinion; async decides

    async def authenticate_async(self, client_info, credentials):
        if credentials.get("enhanced_auth"):
            return IGNORE, None
        env = {
            "username": client_info.get("username") or "",
            "clientid": client_info.get("client_id", ""),
        }
        key = render(self.key_template, env)
        try:
            row = await self.conn.command(
                "HMGET", key, "password_hash", "salt", "is_superuser"
            )
        except Exception as e:
            log.warning("redis authn lookup failed: %s", e)
            return IGNORE, None
        if not row or row[0] is None:
            return IGNORE, None
        phash, salt, is_super = row[0], row[1] or b"", row[2]
        password = credentials.get("password") or b""
        cand = _hash_password(password, self.algo, salt)
        if hmac.compare_digest(cand.hex().encode(), phash) or hmac.compare_digest(
            cand, phash
        ):
            if is_super in (b"1", b"true", 1):
                client_info["is_superuser"] = True
            return OK, None
        return DENY, pkt.RC_BAD_USERNAME_OR_PASSWORD


class RedisAuthzSource:
    """HGETALL rule source (emqx_authz_redis parity): the key template
    (default ``mqtt_acl:${username}``) maps topic filters to
    publish|subscribe|all; a matching field allows, absence falls
    through the source chain."""

    def __init__(
        self, conn: RedisConnector, key_template: str = "mqtt_acl:${username}"
    ):
        self.conn = conn
        self.key_template = key_template

    async def check(self, ci: Dict, action: str, topic: str) -> str:
        env = {
            "username": ci.get("username") or "",
            "clientid": ci.get("client_id", ""),
        }
        try:
            flat = await self.conn.command(
                "HGETALL", render(self.key_template, env)
            )
        except Exception as e:
            log.warning("redis authz lookup failed: %s", e)
            return "ignore"
        if not flat:
            return "ignore"
        for i in range(0, len(flat) - 1, 2):
            filt = flat[i].decode()
            allowed = flat[i + 1].decode()
            if allowed not in (action, "all"):
                continue
            if T.match(topic, render(filt, env)):
                return "allow"
        return "ignore"
