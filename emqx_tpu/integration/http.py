"""HTTP connector: templated requests to an external web service.

Parity with emqx_connector's HTTP client (apps/emqx_connector/src/
emqx_connector_http.erl over ehttpc pools): a pooled async HTTP client
whose method/path/headers/body are ``${var}`` templates rendered per
message, with a connectivity health check against the base URL.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Dict, Optional

from emqx_tpu.integration.resource import Resource
from emqx_tpu.utils.placeholder import render

log = logging.getLogger("emqx_tpu.integration.http")


class HttpConnector(Resource):
    def __init__(
        self,
        base_url: str,
        method: str = "POST",
        path: str = "",
        headers: Optional[Dict[str, str]] = None,
        body: str = "${payload}",
        request_timeout: float = 5.0,
        pool_size: int = 8,
        health_path: str = "",
    ):
        self.base_url = base_url.rstrip("/")
        self.method = method.upper()
        self.path = path
        self.headers = headers or {"content-type": "application/json"}
        self.body = body
        self.timeout = request_timeout
        self.pool_size = pool_size
        self.health_path = health_path
        self._session = None

    async def start(self) -> None:
        import aiohttp

        self._session = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=self.timeout),
            connector=aiohttp.TCPConnector(limit=self.pool_size),
        )

    async def stop(self) -> None:
        if self._session is not None:
            await self._session.close()
            self._session = None

    async def health_check(self) -> bool:
        if self._session is None:
            return False
        try:
            async with self._session.get(
                self.base_url + self.health_path
            ) as resp:
                return resp.status < 500
        except Exception:
            return False

    async def query(self, env: Dict) -> int:
        """Render + fire one request; env is the rule row / message dict.
        Returns the response status; >= 400 raises (marks disconnected
        only on transport errors, not app-level 4xx)."""
        if self._session is None:
            raise RuntimeError("http connector not started")
        path = render(self.path, env)
        body = render(self.body, env).encode()
        headers = {k: render(v, env) for k, v in self.headers.items()}
        async with self._session.request(
            self.method, self.base_url + path, data=body, headers=headers
        ) as resp:
            await resp.read()
            if resp.status >= 500:
                raise RuntimeError(f"http {resp.status}")
            return resp.status
