"""Topic rewrite rules (reference: apps/emqx_modules/src/emqx_rewrite.erl).

Each rule: (action pub|sub|all, source topic filter, regex, dest template).
A topic matching BOTH the filter and the regex is rewritten to the template
with \\1..\\9 regex groups and ${clientid}/${username} placeholders.
Publish rewrites run on 'message.publish'; subscribe rewrites fold over the
filter list on 'client.subscribe'/'client.unsubscribe'.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import List, Optional

from emqx_tpu.broker.hooks import Hooks
from emqx_tpu.ops import topics as T


@dataclass
class RewriteRule:
    action: str  # 'pub' | 'sub' | 'all'
    source: str  # topic filter
    regex: str
    dest: str

    def __post_init__(self):
        self._re = re.compile(self.regex)


class TopicRewrite:
    def __init__(self, rules: Optional[List[RewriteRule]] = None):
        self.rules = rules or []

    def _apply(self, topic: str, action: str, client_info=None) -> str:
        for r in self.rules:
            if r.action not in (action, "all"):
                continue
            if not T.match(topic, r.source):
                continue
            m = r._re.match(topic)
            if not m:
                continue
            dest = r.dest
            for i, g in enumerate(m.groups(), start=1):
                dest = dest.replace(f"${i}", g or "")
            if client_info:
                dest = dest.replace("${clientid}", client_info.get("client_id", ""))
                dest = dest.replace("${username}", client_info.get("username") or "")
            return dest
        return topic

    def rewrite_publish(self, msg):
        if msg is None:
            return None
        new = self._apply(msg.topic, "pub")
        if new != msg.topic:
            import copy

            m = copy.copy(msg)
            m.topic = new
            return ("ok", m)
        return None

    def rewrite_subscribe(self, client_info, filters):
        """Fold callback for 'client.subscribe': filters is the acc."""
        out = []
        changed = False
        for item in filters:
            f, opts = item if isinstance(item, tuple) else (item, None)
            nf = self._apply(f, "sub", client_info)
            changed = changed or nf != f
            out.append((nf, opts) if opts is not None else nf)
        return ("ok", out) if changed else None

    def attach(self, hooks: Hooks) -> None:
        hooks.add("message.publish", self.rewrite_publish, priority=150)
        hooks.add(
            "client.subscribe",
            lambda ci, acc: self.rewrite_subscribe(ci, acc),
            priority=150,
        )
        hooks.add(
            "client.unsubscribe",
            lambda ci, acc: self.rewrite_subscribe(ci, acc),
            priority=150,
        )
