"""Channel manager: clientid -> channel registry, session open/takeover/discard.

Parity with the reference (apps/emqx/src/emqx_cm.erl:245-273 open_session
with clean-start discard, :346-366 takeover_session; registry tables
:104-113). The reference serializes per-clientid races with a cluster-wide
locker; here a single asyncio loop owns the registry, so the lock is the
loop itself (no await points inside open_session).

Detached sessions (clients gone, expiry_interval > 0) are parked for resume,
the emqx_cm session-expiry analog; `sweep_expired` is the GC.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

from emqx_tpu.broker.broker import Broker
from emqx_tpu.broker.session import Session, SessionConfig
from emqx_tpu.utils.tracepoints import tp


class ChannelManager:
    def __init__(self, broker: Broker, session_store=None):
        self.broker = broker
        # SessionStore (broker/session_store.py): when set, sessions are
        # created store-backed — inflight windows write through to the
        # device-resident table, sweeps retransmit via channel bindings
        self.session_store = session_store
        self._channels: Dict[str, object] = {}  # client_id -> Channel
        self._detached: Dict[str, Tuple[Session, float]] = {}
        # worker fabrics (transport/workers.WorkerFabric) register here:
        # a client LIVE on a connection worker reconnecting via an
        # in-process listener must still take its session over
        # (node-wide emqx_cm semantics; emqx_cm.erl:346-366)
        self.fabrics: List[object] = []

    def get_channel(self, client_id: str):
        return self._channels.get(client_id)

    def channel_count(self) -> int:
        return len(self._channels)

    def detached_count(self) -> int:
        return len(self._detached)

    def client_ids(self) -> List[str]:
        return list(self._channels)

    # -- session lifecycle -------------------------------------------------
    def open_session(self, channel):
        """-> (session, session_present), or an AWAITABLE of the same
        when the session is live on a connection worker (the channel
        handles both). The in-process path stays synchronous — the
        asyncio loop is the per-clientid lock."""
        cid = channel.client_id
        for fab in self.fabrics:
            if fab.owns(cid):
                return self._open_via_fabric(channel, fab)
        return self._open_local(channel)

    async def _open_via_fabric(self, channel, fab) -> Tuple[Session, bool]:
        """Take the live worker session over (or discard it) first, then
        run the normal local open with the taken state."""
        sj = await fab.take_session(
            channel.client_id, channel.clean_start
        )
        remote = None
        if sj is not None and not channel.clean_start:
            from emqx_tpu.storage.codec import session_from_json

            try:
                remote = session_from_json(
                    sj, channel.config.session, store=self.session_store
                )
            except Exception:
                remote = None
        return self._open_local(channel, remote=remote)

    def _open_local(
        self, channel, remote: Optional[Session] = None
    ) -> Tuple[Session, bool]:
        cid = channel.client_id
        old = self._channels.pop(cid, None)
        session: Optional[Session] = None
        present = False
        if channel.clean_start:
            if old is not None:
                self._discard_channel(old)
                tp("cm.discarded", cid=cid)
            self._drop_detached(cid)
        else:
            if old is not None:
                session = old.kick("takenover")
                self.broker.hooks.run("session.takenover", cid)
                present = session is not None
                tp("cm.takenover", cid=cid)
            elif cid in self._detached:
                session, _ = self._detached.pop(cid)
                self.broker.hooks.run("session.resumed", cid)
                present = True
                tp("cm.resumed", cid=cid)
            elif remote is not None:
                # taken over from a connection worker (fabric bridge)
                session = remote
                self.broker.hooks.run("session.takenover", cid)
                present = True
                tp("cm.takenover", cid=cid)
        if session is None:
            session = Session(
                cid, channel.config.session, store=self.session_store
            )
            self.broker.hooks.run("session.created", cid)
            tp("cm.created", cid=cid)
        else:
            # rebind broker deliverers from the old channel to the new one
            for f, opts in session.subscriptions.items():
                self.broker.subscribe(
                    cid, cid, f, opts, channel._make_deliverer(opts)
                )
        if self.session_store is not None and session.store_slot is not None:
            # live again: the sweep retransmits through THIS channel,
            # and the expiry lane disarms until the next detach
            self.session_store.bind(
                session.store_slot, channel._store_resend
            )
            self.session_store.set_expiry(cid, 0)
        self._channels[cid] = channel
        self.broker.metrics.gauge_set("connections.count", len(self._channels))
        return session, present

    def _discard_channel(self, old) -> None:
        sess = old.kick("discarded")
        if sess is not None:
            self.broker.drop_session_subs(
                sess.client_id, list(sess.subscriptions)
            )
        if self.session_store is not None:
            self.session_store.drop_session(old.client_id)
        self.broker.hooks.run("session.discarded", old.client_id)

    def _drop_detached(self, cid: str) -> None:
        ent = self._detached.pop(cid, None)
        if ent is not None:
            sess, _ = ent
            self.broker.drop_session_subs(cid, list(sess.subscriptions))
            if self.session_store is not None:
                self.session_store.drop_session(cid)
            self.broker.hooks.run("session.discarded", cid)

    def on_channel_closed(self, channel, reason: str) -> None:
        cid = channel.client_id
        if self._channels.get(cid) is not channel:
            return  # already replaced by takeover/discard
        del self._channels[cid]
        self.broker.metrics.gauge_set("connections.count", len(self._channels))
        sess = channel.session
        if sess is None:
            return
        store = self.session_store
        if store is not None and sess.store_slot is not None:
            store.unbind(sess.store_slot)
        expiry = sess.config.expiry_interval
        if expiry > 0:
            # monotonic deadline: a forward wall-clock step (NTP slew,
            # suspend/resume) must not mass-expire every detached
            # session — the inflight-window bug class PR 11 fixed.
            # Persistence converts to a remaining-interval at snapshot
            # time (persistent_session.py) so restarts still honor it.
            self._detached[cid] = (sess, time.monotonic() + expiry)
            if store is not None and sess.store_slot is not None:
                # arm the device expiry lane; the table rows stay put —
                # resume is a rebind, never a rebuild
                store.set_expiry(cid, expiry)
            # persistence swaps in its durable banker on this hookpoint
            self.broker.hooks.run("session.detached", cid)
        else:
            self.broker.drop_session_subs(cid, list(sess.subscriptions))
            if store is not None:
                store.drop_session(cid)
            self.broker.hooks.run("session.terminated", cid, reason)

    def kick_client(self, client_id: str) -> bool:
        """Administrative kick (mgmt API / CLI)."""
        ch = self._channels.pop(client_id, None)
        if ch is None:
            return False
        sess = ch.kick("kicked")
        if sess is not None:
            self.broker.drop_session_subs(client_id, list(sess.subscriptions))
        if self.session_store is not None:
            self.session_store.drop_session(client_id)
        return True

    def sweep_expired(self, now: Optional[float] = None) -> int:
        """GC detached sessions past their expiry deadline. `now` is a
        `time.monotonic()` value (tests patch it); wall time would make
        every deadline hostage to clock steps."""
        now = time.monotonic() if now is None else now
        gone = [cid for cid, (_, dl) in self._detached.items() if dl <= now]
        for cid in gone:
            self._drop_detached(cid)
        return len(gone)
