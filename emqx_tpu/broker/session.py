"""Per-client session state (reference: apps/emqx/src/emqx_session.erl).

Holds subscriptions, the inflight window, the bounded mqueue, QoS2
awaiting_rel set, and the packet-id counter. Survives connection churn:
on takeover the whole object moves to the new channel
(emqx_session:takeover/resume/replay, emqx_session.erl:85-90).

Pure state machine — no I/O. `deliver` returns the Publish packets to send;
acks mutate the window and release queued messages.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from emqx_tpu.broker.inflight import Inflight
from emqx_tpu.broker.message import Message
from emqx_tpu.broker.mqueue import MQueue
from emqx_tpu.mqtt import packet as pkt


@dataclass
class SessionConfig:
    max_inflight: int = 32
    max_mqueue: int = 1000
    retry_interval: float = 30.0
    await_rel_timeout: float = 300.0
    max_awaiting_rel: int = 100
    # default persistence for v3.1.1 clean_session=0 clients (the reference
    # defaults to 2h); v5 clients override via Session-Expiry-Interval, and
    # clean-start v4 sessions are forced to 0 by the channel manager
    expiry_interval: float = 7200.0
    # device-resident session store (broker/session_store.py): inflight
    # windows + QoS state land on segment tables, ack clears fuse into
    # serving launches, retry scans become device sweeps. Off = the
    # host-dict path alone (also the degrade-ladder fallback when on)
    device_store: bool = False
    # initial (slot, packet-id) row capacity; grows by doubling
    store_capacity: int = 4096
    # compact width of the device retry/expiry sweep (pow2-rounded);
    # uncapped counts tell the store when a flood needs a second sweep
    store_sweep_slots: int = 1024
    # how often housekeeping arms a sweep / runs the host fallback scan
    store_sweep_interval: float = 5.0


class Session:
    def __init__(
        self,
        client_id: str,
        config: SessionConfig = SessionConfig(),
        store=None,
    ):
        """`store`: an optional `broker.session_store.SessionStore` —
        when given, inflight/awaiting-rel state writes through to the
        device-resident session table (the dict view stays authoritative
        for this live session; the table carries the aggregate state for
        fused ack clears, device sweeps, and mass resume)."""
        import dataclasses

        self.client_id = client_id
        self.config = dataclasses.replace(config)  # per-session copy
        self.created_at = time.time()
        self.subscriptions: Dict[str, pkt.SubOpts] = {}
        self.store = store
        if store is not None:
            self.store_slot = store.attach(client_id)
            self.inflight = store.make_inflight(
                self.store_slot, config.max_inflight
            )
        else:
            self.store_slot = None
            self.inflight = Inflight(config.max_inflight)
        self.mqueue = MQueue(config.max_mqueue)
        self.awaiting_rel: Dict[int, float] = {}  # incoming QoS2 packet ids
        self._next_pid = 1

    # -- packet ids -------------------------------------------------------
    def alloc_packet_id(self) -> int:
        while True:
            pid = self._next_pid
            self._next_pid = pid % 65535 + 1
            if not self.inflight.contains(pid):
                return pid

    # -- outgoing (broker -> client) --------------------------------------
    def deliver(
        self, msg: Message, opts: Optional[pkt.SubOpts] = None
    ) -> List[pkt.Publish]:
        """Accept one routed message; return PUBLISH packets ready to send."""
        qos = min(msg.qos, opts.qos) if opts else msg.qos
        # MQTT spec: forwarded messages carry retain=0 unless the subscription
        # set retain-as-published; retained-store replays keep retain=1
        retain = (
            msg.retain
            if (opts and opts.retain_as_published)
            else bool(msg.headers.get("retained"))
        )
        msg = self._adjust(msg, qos, retain)
        if qos == 0:
            return [self._publish_packet(msg, 0, None)]
        if self.inflight.is_full():
            self.mqueue.in_(msg)
            return []
        pid = self.alloc_packet_id()
        self.inflight.insert(pid, msg)
        return [self._publish_packet(msg, qos, pid)]

    def _adjust(self, msg: Message, qos: int, retain: bool) -> Message:
        if msg.qos == qos and msg.retain == retain:
            return msg
        import copy

        m = copy.copy(msg)
        m.qos = qos
        m.retain = retain
        return m

    def _publish_packet(
        self, msg: Message, qos: int, pid: Optional[int], dup: bool = False
    ) -> pkt.Publish:
        return pkt.Publish(
            topic=msg.topic,
            payload=msg.payload,
            qos=qos,
            retain=msg.retain,
            dup=dup,
            packet_id=pid,
            properties=dict(msg.properties),
        )

    def puback(
        self, packet_id: int
    ) -> Tuple[Optional[Message], List[pkt.Publish]]:
        """QoS1 ack; returns (acked msg | None, replacement publishes)."""
        e = self.inflight.delete(packet_id)
        return (e.msg if e is not None else None), self._drain()

    def pubrec(self, packet_id: int) -> bool:
        """QoS2 phase 1 ack'd by receiver -> move to rel phase."""
        e = self.inflight.get(packet_id)
        if e is None or e.phase != "publish":
            return False
        self.inflight.update(packet_id, "pubrel")
        return True

    def pubcomp(
        self, packet_id: int
    ) -> Tuple[Optional[Message], List[pkt.Publish]]:
        e = self.inflight.delete(packet_id)
        ok = e is not None and e.phase == "pubrel"
        return (e.msg if ok else None), self._drain()

    def _drain(self) -> List[pkt.Publish]:
        out: List[pkt.Publish] = []
        while not self.inflight.is_full():
            msg = self.mqueue.out()
            if msg is None:
                break
            pid = self.alloc_packet_id()
            self.inflight.insert(pid, msg)
            out.append(self._publish_packet(msg, msg.qos, pid))
        return out

    # -- incoming QoS2 (client -> broker) ---------------------------------
    def await_rel(self, packet_id: int) -> bool:
        """Track an incoming QoS2 publish until PUBREL; False if duplicate.
        Stamps are monotonic (expiry is an elapsed-time question)."""
        if packet_id in self.awaiting_rel:
            return False
        if len(self.awaiting_rel) >= self.config.max_awaiting_rel:
            raise OverflowError("max_awaiting_rel")
        self.awaiting_rel[packet_id] = time.monotonic()
        if self.store is not None:
            self.store.await_rel(self.store_slot, packet_id)
        return True

    def release_rel(self, packet_id: int) -> bool:
        ok = self.awaiting_rel.pop(packet_id, None) is not None
        if ok and self.store is not None:
            self.store.release_rel(self.store_slot, packet_id)
        return ok

    # -- retry ------------------------------------------------------------
    def retry(self) -> List[pkt.Packet]:
        """Retransmit inflight entries older than retry_interval."""
        out: List[pkt.Packet] = []
        for pid, e in self.inflight.retry_due(self.config.retry_interval):
            if e.phase == "publish" and e.msg is not None:
                out.append(self._publish_packet(e.msg, e.msg.qos, pid, dup=True))
            else:
                rel = pkt.PubAck(packet_id=pid)
                rel.type = pkt.PUBREL
                out.append(rel)
            e.ts = time.monotonic()
            if self.store is not None:
                self.store.touch_inflight(self.store_slot, pid)
        return out

    # -- takeover ---------------------------------------------------------
    def replay(self) -> List[pkt.Packet]:
        """All inflight packets re-sent after takeover/resume (dup=True)."""
        out: List[pkt.Packet] = []
        for pid, e in self.inflight.items():
            if e.phase == "publish" and e.msg is not None:
                out.append(self._publish_packet(e.msg, e.msg.qos, pid, dup=True))
            else:
                rel = pkt.PubAck(packet_id=pid)
                rel.type = pkt.PUBREL
                out.append(rel)
        return out + self._drain()
