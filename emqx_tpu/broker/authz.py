"""Authorization rules (reference: apps/emqx_authz rule DSL,
emqx_authz_rule.erl + the file-ACL source; result cache as in
apps/emqx/src/emqx_authz_cache.erl).

Rule = (permit|deny, who, action, topics):
- who: 'all' | {'clientid': x} | {'username': x} | {'ipaddr': cidr-ish}
- action: 'publish' | 'subscribe' | 'all'
- topics: filters with ${clientid}/${username} placeholders; an 'eq ' prefix
  compares literally instead of wildcard-matching (reference eq semantics).

Folds over 'client.authorize'; first matching rule wins; default from
`no_match` (allow, as the reference ships). Per-client result cache keyed
(action, topic), invalidated by rule updates.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple, Union

from emqx_tpu.broker.hooks import Hooks
from emqx_tpu.ops import topics as T

Who = Union[str, Dict[str, str]]


@dataclass
class AclRule:
    permit: str  # 'allow' | 'deny'
    who: Who = "all"
    action: str = "all"  # 'publish' | 'subscribe' | 'all'
    topics: List[str] = field(default_factory=list)


class Authorizer:
    def __init__(
        self,
        rules: Optional[List[AclRule]] = None,
        no_match: str = "allow",
        deny_action: str = "ignore",
        cache_size: int = 1024,
    ):
        self.rules = rules or []
        self.no_match = no_match
        self.deny_action = deny_action
        self._cache: Dict[tuple, str] = {}
        self._cache_size = cache_size
        self._epoch = 0

    def set_rules(self, rules: List[AclRule]) -> None:
        self.rules = rules
        self._cache.clear()
        self._epoch += 1

    def _who_matches(self, who: Who, ci: Dict) -> bool:
        if who == "all":
            return True
        if isinstance(who, dict):
            if "clientid" in who:
                return ci.get("client_id") == who["clientid"]
            if "username" in who:
                return ci.get("username") == who["username"]
            if "ipaddr" in who:
                return str(ci.get("peerhost", "")).startswith(
                    who["ipaddr"].rstrip("*")
                )
        return False

    def _topic_matches(self, topic: str, pattern: str, ci: Dict) -> bool:
        pattern = pattern.replace("${clientid}", ci.get("client_id", ""))
        pattern = pattern.replace("${username}", ci.get("username") or "")
        if pattern.startswith("eq "):
            return topic == pattern[3:]
        return T.match(topic, pattern)

    def check(self, ci: Dict, action: str, topic: str) -> str:
        if ci.get("is_superuser"):
            return "allow"
        # key must capture the full client identity: rules and placeholders
        # depend on username/peerhost too, and client_ids can be reused by
        # different principals across connections
        key = (
            ci.get("client_id", ""),
            ci.get("username"),
            str(ci.get("peerhost", "")),
            action,
            topic,
        )
        hit = self._cache.get(key)
        if hit is not None:
            return hit
        result = self.no_match
        for r in self.rules:
            if r.action not in (action, "all"):
                continue
            if not self._who_matches(r.who, ci):
                continue
            if any(self._topic_matches(topic, p, ci) for p in r.topics):
                result = r.permit
                break
        if len(self._cache) >= self._cache_size:
            self._cache.clear()
        self._cache[key] = result
        return result

    def authorize(self, ci, action, topic, acc="allow"):
        """'client.authorize' fold callback.

        On deny, the fold result carries the configured deny_action: the
        channel drops the packet for 'ignore' and closes the connection for
        'disconnect' (reference authz.deny_action knob).
        """
        result = self.check(ci, action, topic)
        if result != "deny":
            return None
        return (
            "stop",
            "disconnect" if self.deny_action == "disconnect" else "deny",
        )

    def attach(self, hooks: Hooks) -> None:
        hooks.add("client.authorize", self.authorize, priority=100)
