"""Authorization rules (reference: apps/emqx_authz rule DSL,
emqx_authz_rule.erl + the file-ACL source; result cache as in
apps/emqx/src/emqx_authz_cache.erl).

Rule = (permit|deny, who, action, topics):
- who: 'all' | {'clientid': x} | {'username': x} | {'ipaddr': cidr-ish}
- action: 'publish' | 'subscribe' | 'all'
- topics: filters with ${clientid}/${username} placeholders; an 'eq ' prefix
  compares literally instead of wildcard-matching (reference eq semantics).

Folds over 'client.authorize'; first matching rule wins; default from
`no_match` (allow, as the reference ships). Per-client result cache keyed
(action, topic), invalidated by rule updates.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple, Union

from emqx_tpu.broker.hooks import Hooks
from emqx_tpu.ops import topics as T

Who = Union[str, Dict[str, str]]


@dataclass
class AclRule:
    permit: str  # 'allow' | 'deny'
    who: Who = "all"
    action: str = "all"  # 'publish' | 'subscribe' | 'all'
    topics: List[str] = field(default_factory=list)


class Authorizer:
    def __init__(
        self,
        rules: Optional[List[AclRule]] = None,
        no_match: str = "allow",
        deny_action: str = "ignore",
        cache_size: int = 1024,
        sources: Optional[List] = None,
        cache_ttl: float = 60.0,
    ):
        self.rules = rules or []
        self.no_match = no_match
        self.deny_action = deny_action
        # external sources consulted BEFORE the built-in rules, in order
        # (reference authz source chain: each answers allow/deny/ignore;
        # sources expose `async def check(ci, action, topic) -> str`)
        self.sources = sources or []
        self._cache: Dict[tuple, Tuple[str, float]] = {}
        self._cache_size = cache_size
        self._cache_ttl = cache_ttl
        self._epoch = 0

    def set_rules(self, rules: List[AclRule]) -> None:
        self.rules = rules
        self._cache.clear()
        self._epoch += 1

    def add_source(self, source) -> None:
        self.sources.append(source)
        self._cache.clear()

    def _who_matches(self, who: Who, ci: Dict) -> bool:
        if who == "all":
            return True
        if isinstance(who, dict):
            if "clientid" in who:
                return ci.get("client_id") == who["clientid"]
            if "username" in who:
                return ci.get("username") == who["username"]
            if "ipaddr" in who:
                return str(ci.get("peerhost", "")).startswith(
                    who["ipaddr"].rstrip("*")
                )
        return False

    def _topic_matches(self, topic: str, pattern: str, ci: Dict) -> bool:
        pattern = pattern.replace("${clientid}", ci.get("client_id", ""))
        pattern = pattern.replace("${username}", ci.get("username") or "")
        if pattern.startswith("eq "):
            return topic == pattern[3:]
        return T.match(topic, pattern)

    def _rules_check(self, ci: Dict, action: str, topic: str) -> str:
        """Built-in rule list -> allow | deny | ignore (no rule matched)."""
        for r in self.rules:
            if r.action not in (action, "all"):
                continue
            if not self._who_matches(r.who, ci):
                continue
            if any(self._topic_matches(topic, p, ci) for p in r.topics):
                return r.permit
        return "ignore"

    def _cache_key(self, ci: Dict, action: str, topic: str) -> tuple:
        # key must capture the full client identity: rules and placeholders
        # depend on username/peerhost too, and client_ids can be reused by
        # different principals across connections
        return (
            ci.get("client_id", ""),
            ci.get("username"),
            str(ci.get("peerhost", "")),
            action,
            topic,
        )

    def _cache_get(self, key) -> Optional[str]:
        hit = self._cache.get(key)
        if hit is None:
            return None
        result, expires = hit
        if time.monotonic() > expires:
            del self._cache[key]
            return None
        return result

    def _cache_put(self, key, result: str) -> None:
        if len(self._cache) >= self._cache_size:
            self._cache.clear()
        self._cache[key] = (result, time.monotonic() + self._cache_ttl)

    def check(self, ci: Dict, action: str, topic: str) -> str:
        """Sync path: built-in rules only (external sources are async)."""
        if ci.get("is_superuser"):
            return "allow"
        key = self._cache_key(ci, action, topic)
        hit = self._cache_get(key)
        if hit is not None:
            return hit
        result = self._rules_check(ci, action, topic)
        if result == "ignore":
            result = self.no_match
        self._cache_put(key, result)
        return result

    async def acheck(self, ci: Dict, action: str, topic: str) -> str:
        """Full path: external sources in order, then built-in rules, then
        no_match (reference source-chain semantics; result cached with
        TTL as in emqx_authz_cache)."""
        if ci.get("is_superuser"):
            return "allow"
        key = self._cache_key(ci, action, topic)
        hit = self._cache_get(key)
        if hit is not None:
            return hit
        result = "ignore"
        for src in self.sources:
            result = await src.check(ci, action, topic)
            if result in ("allow", "deny"):
                break
        if result == "ignore":
            result = self._rules_check(ci, action, topic)
        if result == "ignore":
            result = self.no_match
        self._cache_put(key, result)
        return result

    async def authorize(self, ci, action, topic, acc="allow"):
        """'client.authorize' fold callback (async: the channel folds via
        arun_fold, so a slow HTTP source suspends only that client).

        On deny, the fold result carries the configured deny_action: the
        channel drops the packet for 'ignore' and closes the connection for
        'disconnect' (reference authz.deny_action knob).
        """
        result = await self.acheck(ci, action, topic)
        if result != "deny":
            return None
        return (
            "stop",
            "disconnect" if self.deny_action == "disconnect" else "deny",
        )

    def attach(self, hooks: Hooks) -> None:
        hooks.add("client.authorize", self.authorize, priority=100)
