"""Broker metrics: counters + gauges.

Parity with the reference's counter families (apps/emqx/src/emqx_metrics.erl:
89-104: bytes/packets/messages/deliveries; emqx_stats.erl gauges). Names use
the reference's dotted style so the management API and Prometheus exporter
surface familiar series."""

from __future__ import annotations

import threading
import time
from collections import defaultdict
from typing import Dict


class Metrics:
    def __init__(self) -> None:
        self._counters: Dict[str, int] = defaultdict(int)
        self._gauges: Dict[str, float] = {}
        self._lock = threading.Lock()
        self.started_at = time.time()

    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] += n

    def get(self, name: str) -> int:
        return self._counters.get(name, 0)

    def gauge_set(self, name: str, value: float) -> None:
        self._gauges[name] = value

    def gauge(self, name: str) -> float:
        return self._gauges.get(name, 0.0)

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            out = dict(self._counters)
        out.update(self._gauges)
        out["uptime_seconds"] = time.time() - self.started_at
        return out


default_metrics = Metrics()
